"""Calibration-driven simulator refit + live drift detection.

Closes the observability loop (ROADMAP item 4): the Unity search is only
as trustworthy as the cost simulator, whose machine-model coefficients
(`ChipSpec` flop rates, `ici_link_gbps`, the latency constants) are
hand-set — yet the obs layer already records everything needed to FIT
them: per-op predicted-vs-profiled costs (`obs.calibrate`), the searched
plan's `predicted_step_us`, and live `StepStats`. Three pieces:

 - `FittedCoefficients` / `fit_coefficients`: a robust least-squares fit
   of the machine-model coefficients from calibration rows — per-dtype
   effective-flop-rate scale and dispatch latency from an L1-trimmed
   linear fit of measured-vs-predicted op costs, a link-bandwidth scale
   from the step-level communication residual, and a whole-step
   `step_scale` for systematic bias no per-op/per-link term can carry
   (XLA fusion wins, host dispatch, bwd-factor error). `step_scale` is
   uniform across candidate plans, so it can never flip a search ranking.
 - `FittedProfile`: the versioned persisted form — JSON keyed by a
   machine-spec hash (chip name + backend + format version). Loading a
   profile fitted for a different chip/backend, a future format version,
   or a tampered file raises a TYPED error instead of silently
   mis-pricing. `make_machine_model` applies a loaded profile as an
   overlay (`config.fitted_profile_file`), so every subsequent search
   prices with measured reality.
 - `DriftDetector`: watches live step wall times during training (an EMA
   of measured/predicted), publishes the `ff_calibration_drift` gauge and
   `ff_drift_breaches_total` counter, and — past a configurable threshold
   for `patience` consecutive steps, within a re-plan budget — tells the
   ElasticCoordinator to run a refit + budgeted re-search through its
   existing re-plan path (`refit.replan` span, `ff_replan_total`).

`refit(model, ...)` iterates fit rounds: apply the current coefficients
as an overlay, re-simulate the plan's predicted step cost and per-op
predictions, update the coefficients from the residuals, stop when
predicted-vs-measured converges within `tol`. Exposed as
`python -m flexflow_tpu profile --refit` (obs/cli.py); drill-proven by
the CI `refit` job (a deliberately mis-calibrated spec must converge).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import sys
from typing import Any, Dict, List, Optional, Tuple

from .registry import REGISTRY

PROFILE_FORMAT_VERSION = 1

# clamp band for every multiplicative coefficient: a fit outside this is a
# measurement pathology (e.g. a 0-time op row), not a machine property.
# The band must comfortably hold the LEGITIMATE cross-backend gap — a
# TPU-spec'd prediction measured on the CPU emulation is ~1e3-1e4 off
# before any refit, and the drill pins convergence there.
_SCALE_MIN, _SCALE_MAX = 1.0 / 65536.0, 65536.0


class FittedProfileError(ValueError):
    """A fitted-profile file could not be used (corrupt, future format)."""


class FittedProfileMismatch(FittedProfileError):
    """The profile was fitted for a different machine spec (chip/backend)
    than the one it is being loaded for."""


def _clamp(v: float, lo: float = _SCALE_MIN, hi: float = _SCALE_MAX) -> float:
    return min(hi, max(lo, float(v)))


@dataclasses.dataclass
class FittedCoefficients:
    """The machine-model coefficients a refit adjusts. All neutral at 1.0
    (latencies at the historical 1.0us constants), so an empty fit is an
    exact no-op overlay."""

    # effective-flop-rate multipliers per dtype class (bf16 = MXU path,
    # f32 = full-precision path); multiply the ChipSpec peak rates
    compute_scale: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"bf16": 1.0, "f32": 1.0})
    hbm_scale: float = 1.0
    # per-link bandwidth multiplier (ici_link_gbps / NetworkedMachineModel
    # link_gbps)
    link_bw_scale: float = 1.0
    # per-TIER bandwidth multipliers for hierarchical machine specs,
    # keyed by tier name ("ici", "dcn", ... — docs/machine.md). A tier
    # named here overrides link_bw_scale for that tier; unnamed tiers
    # (and every flat machine model) keep the single-scale path, so old
    # profiles — which lack this field — still load and apply.
    tier_link_scales: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # per-op dispatch/launch latency and per-collective base latency (us)
    dispatch_latency_us: float = 1.0
    collective_latency_us: float = 1.0
    # whole-step systematic-bias multiplier (see module docstring)
    step_scale: float = 1.0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FittedCoefficients":
        out = cls()
        for f in dataclasses.fields(cls):
            if f.name in d:
                setattr(out, f.name, d[f.name])
        out.compute_scale = {k: float(v)
                             for k, v in dict(out.compute_scale).items()}
        out.tier_link_scales = {str(k): float(v)
                                for k, v in dict(out.tier_link_scales
                                                 ).items()}
        return out


def spec_hash(chip_name: str, backend: str,
              version: int = PROFILE_FORMAT_VERSION) -> str:
    """Stable identity of the machine spec a profile was fitted for. Keyed
    by chip + backend + format version, NOT num_chips: the coefficients
    are per-chip / per-link properties, valid across mesh sizes — which is
    what lets an elastic re-plan on a shrunken mesh keep the overlay."""
    payload = json.dumps({"chip": chip_name, "backend": backend,
                          "format": version}, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _current_backend() -> Optional[str]:
    """The live jax backend, WITHOUT forcing backend initialization: when
    jax is not imported yet (e.g. the analyze CLI building a machine model
    pre-backend), the check is skipped rather than paid for."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.default_backend()
    except Exception:
        return None


@dataclasses.dataclass
class FittedProfile:
    """Versioned, spec-keyed persisted form of a coefficient fit."""

    chip: str
    backend: str
    coefficients: FittedCoefficients
    spec_hash: str = ""
    version: int = PROFILE_FORMAT_VERSION
    # provenance (informational; not part of the identity hash)
    fitted_steps: int = 0
    fitted_ops: int = 0
    rounds: int = 0
    step_ratio: float = float("nan")
    num_chips: int = 0
    # per-kernel-family calibration residuals (median measured/predicted
    # at fit time, obs/calibration.op_family_residuals): the evidence the
    # KernelRegistry auto-selects fused Pallas kernels from
    # (kernels/registry.py, docs/kernels.md). Informational for the
    # machine model itself — apply_to never touches it.
    op_family_residuals: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # per-family FITTED selection thresholds (fit_kernel_thresholds):
    # derived from real before/after kernel measurements — a family's
    # threshold is the residual the FUSED impl itself achieves at the
    # profiled shapes (x a small safety margin), so reference evidence
    # past it means switching genuinely pays. A family present here
    # overrides the hand-set RESIDUAL_CANDIDATE_THRESHOLD /
    # --kernel-residual-threshold default in the registry; absent
    # families keep the knob. Informational for the machine model.
    kernel_residual_thresholds: Dict[str, float] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        if not self.spec_hash:
            self.spec_hash = spec_hash(self.chip, self.backend, self.version)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["coefficients"] = self.coefficients.to_dict()
        return d

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
            f.write("\n")
        return path

    def apply_to(self, machine) -> None:
        """Overlay this profile's coefficients onto a MachineModel."""
        machine.apply_overlay(self.coefficients)

    @classmethod
    def load(cls, path: str, expect_chip: Optional[str] = None,
             expect_backend: Optional[str] = None) -> "FittedProfile":
        """Load + verify. Raises FittedProfileError on unreadable/corrupt
        files or a future format version, FittedProfileMismatch when the
        stored spec hash does not match the machine it is loaded for
        (wrong chip, wrong backend, or a tampered/stale hash)."""
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise FittedProfileError(
                f"fitted profile {path!r} unreadable: {e}") from e
        try:
            version = int(d["version"])
            chip = str(d["chip"])
            backend = str(d["backend"])
            coeffs = FittedCoefficients.from_dict(d["coefficients"])
            stored_hash = str(d["spec_hash"])
        except (KeyError, TypeError, ValueError) as e:
            raise FittedProfileError(
                f"fitted profile {path!r} malformed: {e}") from e
        if version > PROFILE_FORMAT_VERSION:
            raise FittedProfileError(
                f"fitted profile {path!r} is format v{version}; this "
                f"runtime reads up to v{PROFILE_FORMAT_VERSION}")
        expected_hash = spec_hash(chip, backend, version)
        if stored_hash != expected_hash:
            raise FittedProfileMismatch(
                f"fitted profile {path!r}: stored spec hash "
                f"{stored_hash!r} does not match its own spec "
                f"(chip={chip!r}, backend={backend!r} -> "
                f"{expected_hash!r}) — stale or tampered file")
        if expect_chip is not None and chip != expect_chip:
            raise FittedProfileMismatch(
                f"fitted profile {path!r} was fitted for chip {chip!r}, "
                f"but the machine model is {expect_chip!r}")
        check_backend = (expect_backend if expect_backend is not None
                         else _current_backend())
        if check_backend is not None and backend != check_backend:
            raise FittedProfileMismatch(
                f"fitted profile {path!r} was fitted on the {backend!r} "
                f"backend, but this process runs {check_backend!r} — "
                "refit on this backend instead of reusing it")
        return cls(chip=chip, backend=backend, coefficients=coeffs,
                   spec_hash=stored_hash, version=version,
                   fitted_steps=int(d.get("fitted_steps", 0)),
                   fitted_ops=int(d.get("fitted_ops", 0)),
                   rounds=int(d.get("rounds", 0)),
                   step_ratio=float(d.get("step_ratio", float("nan"))),
                   num_chips=int(d.get("num_chips", 0)),
                   op_family_residuals={
                       str(k): float(v) for k, v in dict(
                           d.get("op_family_residuals", {})).items()},
                   kernel_residual_thresholds={
                       str(k): float(v) for k, v in dict(
                           d.get("kernel_residual_thresholds",
                                 {})).items()})


# -- the coefficient fit ---------------------------------------------------

def _trimmed_linear_fit(xs: List[float], ys: List[float]
                        ) -> Tuple[float, float]:
    """Least-squares y ~= a*x + b, robustified: fit once, drop the 20%
    largest absolute residuals, fit again (L1-style trimming — one bad op
    measurement must not poison the machine coefficients). Falls back to a
    through-origin ratio-of-medians when the data cannot support an
    intercept (fewer than 3 points or degenerate x)."""
    import numpy as np

    x = np.asarray(xs, dtype=float)
    y = np.asarray(ys, dtype=float)

    def ratio_fallback() -> Tuple[float, float]:
        r = np.median(y / x)
        return float(max(r, 1e-12)), 0.0

    if len(x) == 2 and x[1] != x[0]:
        # two distinct points: an exact line still beats through-origin —
        # on dispatch-dominated backends the intercept IS the signal
        a = (y[1] - y[0]) / (x[1] - x[0])
        if a > 0 and math.isfinite(a):
            return float(a), float(max(0.0, y[0] - a * x[0]))
    if len(x) < 3 or float(np.ptp(x)) <= 0:
        return ratio_fallback()

    def lstsq(xv, yv):
        A = np.stack([xv, np.ones_like(xv)], axis=1)
        sol, *_ = np.linalg.lstsq(A, yv, rcond=None)
        return float(sol[0]), float(sol[1])

    a, b = lstsq(x, y)
    resid = np.abs(y - (a * x + b))
    keep = resid <= np.quantile(resid, 0.8)
    if keep.sum() >= 3:
        a, b = lstsq(x[keep], y[keep])
    if not (a > 0) or not math.isfinite(a) or not math.isfinite(b):
        return ratio_fallback()
    return a, b


def usable_rows(rows) -> List:
    """Calibration rows the fit can learn from: a positive finite
    prediction AND a positive finite measurement. Zero/negative measured
    times (clock resolution on trivially small ops) and failed
    measurements are excluded — the degenerate inputs the hardened
    calibration layer records as uncalibrated."""
    out = []
    for r in rows:
        pred = getattr(r, "predicted_us", None)
        meas = getattr(r, "measured_us", None)
        if (pred is not None and meas is not None
                and math.isfinite(pred) and math.isfinite(meas)
                and pred > 0 and meas > 0):
            out.append(r)
    return out


def fit_compute_coefficients(rows, prior: FittedCoefficients,
                             machine) -> FittedCoefficients:
    """One round of the per-op compute fit. `rows` carry predictions made
    UNDER `prior` (via the overlaid `machine`); the fit solves, per dtype
    class, measured ~= a * roofline + b where roofline = predicted minus
    the machine's current dispatch overhead — slope `a` divides the
    effective flop rate, intercept `b` (averaged across dtype groups,
    clamped >= 0) becomes the new dispatch latency."""
    rows = usable_rows(rows)
    out = dataclasses.replace(
        prior, compute_scale=dict(prior.compute_scale))
    by_dtype: Dict[str, List] = {}
    for r in rows:
        by_dtype.setdefault(getattr(r, "dtype", "") or "f32", []).append(r)
    overhead = float(getattr(machine, "dispatch_overhead_us", 1.0))
    intercepts = []
    for dtype, group in by_dtype.items():
        if dtype not in out.compute_scale:
            continue
        xs = [max(r.predicted_us - overhead, 1e-9) for r in group]
        ys = [r.measured_us for r in group]
        a, b = _trimmed_linear_fit(xs, ys)
        # measured = a * predicted_roofline: the effective rate is 1/a of
        # what the prior believed
        out.compute_scale[dtype] = _clamp(out.compute_scale[dtype] / a)
        intercepts.append(b)
    if intercepts:
        out.dispatch_latency_us = _clamp(
            sum(intercepts) / len(intercepts), 0.0, 1e4)
    return out


def _simulate_step_us(model, coeffs: FittedCoefficients,
                      comm_free: bool = False,
                      free_tier: Optional[str] = None) -> float:
    """The plan's predicted step cost under a coefficient overlay —
    `comm_free=True` re-prices with (near-)infinite link bandwidth and
    zero collective latency, isolating the communication share of the
    prediction for the bandwidth fit. `free_tier` frees ONE tier of a
    hierarchical machine instead (its comm share = total - this), which
    is how the per-tier bandwidth fit attributes the step-level residual
    to the tiers that actually carry traffic."""
    from ..search.machine_model import make_machine_model
    from ..search.simulator import Simulator

    cfg = model.config
    n_dev = max(1, cfg.total_devices)
    machine = make_machine_model(
        dataclasses.replace(cfg, fitted_profile_file=None), n_dev)
    applied = coeffs
    if comm_free:
        tier_free = {name: scale * 1e9
                     for name, scale in _effective_tier_scales(
                         machine, coeffs).items()}
        applied = dataclasses.replace(
            coeffs, compute_scale=dict(coeffs.compute_scale),
            link_bw_scale=coeffs.link_bw_scale * 1e9,
            tier_link_scales=tier_free,
            collective_latency_us=0.0)
        # a tier's EXPLICIT latency_us bypasses the fitted
        # collective_latency_us (machine_model.tier_latency); zero those
        # too, or latency-dominated DCN syncs would be misread as compute
        tiers = getattr(machine, "tiers", None)
        if tiers:
            machine.tiers = [dataclasses.replace(t, latency_us=0.0)
                             for t in tiers]
    elif free_tier is not None:
        scales = _effective_tier_scales(machine, coeffs)
        scales[free_tier] = scales.get(free_tier,
                                       coeffs.link_bw_scale) * 1e9
        applied = dataclasses.replace(
            coeffs, compute_scale=dict(coeffs.compute_scale),
            tier_link_scales=scales)
        # zero the freed tier's EXPLICIT latency too (mirroring the
        # comm_free branch): a latency-dominated DCN tier must still
        # show its comm share when freed, or it is never attributed
        machine.tiers = [dataclasses.replace(t, latency_us=0.0)
                         if t.name == free_tier else t
                         for t in machine.tiers]
    machine.apply_overlay(applied)
    sim = Simulator(machine, cfg)
    return float(sim.simulate(model.graph, model._op_strategies or {}))


def _effective_tier_scales(machine, coeffs: FittedCoefficients
                           ) -> Dict[str, float]:
    """The per-tier scales an overlay of `coeffs` would apply to
    `machine` — named tiers from tier_link_scales, the rest falling back
    to the global link_bw_scale. {} for flat machines."""
    tiers = getattr(machine, "tiers", None)
    if not tiers:
        return {}
    return {t.name: float(coeffs.tier_link_scales.get(
        t.name, coeffs.link_bw_scale)) for t in tiers}


def _predict_op_rows(model, coeffs: FittedCoefficients, rows) -> List:
    """Re-predict each measured op's forward cost under a coefficient
    overlay, keeping the measured side — the input of the next fit round."""
    from ..ffconst import OpType
    from ..search.machine_model import make_machine_model
    from ..search.simulator import CostModel, OpStrategy

    cfg = model.config
    n_dev = max(1, cfg.total_devices)
    machine = make_machine_model(
        dataclasses.replace(cfg, fitted_profile_file=None), n_dev)
    machine.apply_overlay(coeffs)
    cost = CostModel(machine, cfg)
    strategies = model._op_strategies or {}
    default = OpStrategy(dp=1, tp=1)
    by_name = {op.name: op for op in model.graph.ops.values()
               if op.op_type not in (OpType.INPUT, OpType.WEIGHT,
                                     OpType.NOOP)}
    out = []
    for r in rows:
        op = by_name.get(r.op)
        if op is None:
            continue
        s = strategies.get(op.guid, default)
        out.append(dataclasses.replace(
            r, predicted_us=float(cost.forward_time_us(op, s))))
    return out


@dataclasses.dataclass
class RefitRound:
    """One refit round's verdict, for the CLI/drill convergence report."""

    round: int
    predicted_step_us: float
    measured_step_us: float

    @property
    def ratio(self) -> float:
        if not (self.predicted_step_us > 0 and self.measured_step_us > 0):
            return float("nan")
        return self.measured_step_us / self.predicted_step_us

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["ratio"] = self.ratio
        return d


def fit_kernel_thresholds(pallas_rows, margin: float = 1.02
                          ) -> Dict[str, float]:
    """Per-family kernel-selection thresholds from real BEFORE/AFTER
    measurements, replacing the hand-set
    `RESIDUAL_CANDIDATE_THRESHOLD = 1.10` guess (kernels/registry.py).

    `pallas_rows` are calibration rows measured with the fused Pallas
    impls FORCED (the "after" side; the ordinary profile run is the
    "before" side whose residuals ride in `op_family_residuals`). The
    registry selects pallas when the reference residual
    (measured_ref/predicted) exceeds the threshold; switching genuinely
    pays exactly when the reference runs slower than the fused kernel —
    i.e. when the reference residual exceeds the residual the FUSED impl
    itself achieves. So the fitted threshold per family is the fused
    impl's own median measured/predicted at the profiled shapes, times a
    small `margin` (switching for a sub-2% win is churn), floored at 1.0
    (a fused impl beating the roofline still should not be selected on
    noise-level reference evidence). Families without usable pallas rows
    are omitted — they keep the knob/default."""
    from .calibration import op_family_residuals

    out: Dict[str, float] = {}
    for fam, resid in op_family_residuals(usable_rows(pallas_rows)).items():
        if math.isfinite(resid) and resid > 0:
            out[fam] = max(1.0, float(resid)) * float(margin)
    return out


def refit(model, measured_step_us: float, op_rows,
          prior: Optional[FittedCoefficients] = None,
          rounds: int = 3, tol: float = 0.15, pallas_rows=None,
          ) -> Tuple[FittedProfile, List[RefitRound]]:
    """Fit machine-model coefficients for `model`'s compiled plan until
    the re-simulated predicted step cost lands within `tol` of
    `measured_step_us` (or `rounds` is exhausted). Returns the persistable
    profile and the per-round convergence history.

    Round structure (all inside a `refit.fit` span):
      1. per-op robust linear fit -> per-dtype compute scale + dispatch
         latency (fit_compute_coefficients);
      2. step-level communication residual -> link bandwidth scale, but
         only when the prediction has a meaningful comm share to attribute
         it to (>= 2%);
      3. remaining whole-step residual -> step_scale;
      4. re-simulate; converged when |measured/predicted - 1| <= tol.
    """
    from .tracing import get_tracer

    assert model.graph is not None, "compile() the model first"
    if not (measured_step_us and measured_step_us > 0
            and math.isfinite(measured_step_us)):
        raise FittedProfileError(
            f"cannot refit against measured_step_us={measured_step_us!r}; "
            "run enough steps to measure first")
    coeffs = prior if prior is not None else FittedCoefficients()
    coeffs = dataclasses.replace(
        coeffs, compute_scale=dict(coeffs.compute_scale))
    rows = usable_rows(op_rows)
    history: List[RefitRound] = []
    # tier names are invariant across rounds: resolve them once instead
    # of rebuilding the machine model (a spec-file read) per round
    from ..search.machine_model import make_machine_model

    tier_names = [t.name for t in getattr(
        make_machine_model(
            dataclasses.replace(model.config, fitted_profile_file=None),
            max(1, model.config.total_devices)), "tiers", [])]
    with get_tracer().span("refit.fit", rounds=rounds) as sp:
        converged = False
        for rnd in range(1, max(1, rounds) + 1):
            predicted = _simulate_step_us(model, coeffs)
            history.append(RefitRound(rnd, predicted, measured_step_us))
            ratio = history[-1].ratio
            if math.isfinite(ratio) and abs(ratio - 1.0) <= tol:
                converged = True
                break
            # 1. compute terms from the op rows (re-predicted under the
            # current coefficients so each round fits fresh residuals)
            if rows:
                machine = make_machine_model(
                    dataclasses.replace(model.config,
                                        fitted_profile_file=None),
                    max(1, model.config.total_devices))
                machine.apply_overlay(coeffs)
                coeffs = fit_compute_coefficients(rows, coeffs, machine)
                rows = _predict_op_rows(model, coeffs, rows)
            # 2. comm residual -> bandwidth, when there is comm to blame
            total = _simulate_step_us(model, coeffs)
            comp_only = _simulate_step_us(model, coeffs, comm_free=True)
            comm_share = max(0.0, total - comp_only) / max(total, 1e-9)
            if comm_share > 0.02 and measured_step_us > comp_only:
                k = (measured_step_us - comp_only) / max(
                    total - comp_only, 1e-9)
                if tier_names:
                    # hierarchical machine: fit PER-TIER scales, keyed by
                    # tier name — the correction lands only on tiers that
                    # carry an attributable share of the step's comm
                    # (freeing a tier the plan never crosses changes
                    # nothing, so its share is 0 and its prior survives)
                    scales = dict(coeffs.tier_link_scales)
                    for name in tier_names:
                        t_free = _simulate_step_us(model, coeffs,
                                                   free_tier=name)
                        share_t = max(0.0, total - t_free) / max(total,
                                                                 1e-9)
                        if share_t > 0.02:
                            prior_t = scales.get(name,
                                                 coeffs.link_bw_scale)
                            scales[name] = _clamp(prior_t / k)
                    coeffs.tier_link_scales = scales
                else:
                    # flat machine spec: the single-scale path, unchanged
                    coeffs.link_bw_scale = _clamp(coeffs.link_bw_scale / k)
            # 3. whatever residual remains is whole-step systematic bias
            predicted = _simulate_step_us(model, coeffs)
            if predicted > 0:
                coeffs.step_scale = _clamp(
                    coeffs.step_scale * measured_step_us / predicted)
        if not converged:
            # the last round updated coefficients after its history entry:
            # record where they actually landed
            final = _simulate_step_us(model, coeffs)
            history.append(RefitRound(len(history) + 1, final,
                                      measured_step_us))
        sp.set(rounds_run=len(history), final_ratio=history[-1].ratio)

    machine = make_machine_model(
        dataclasses.replace(model.config, fitted_profile_file=None),
        max(1, model.config.total_devices))
    import jax

    from .calibration import op_family_residuals

    profile = FittedProfile(
        chip=machine.chip.name, backend=jax.default_backend(),
        coefficients=coeffs, fitted_steps=1, fitted_ops=len(rows),
        rounds=len(history), step_ratio=history[-1].ratio,
        num_chips=max(1, model.config.total_devices),
        # residuals from the ORIGINAL rows (usable_rows(op_rows)), not
        # the re-predicted ones: the registry wants the gap the backend
        # showed against the un-refit roofline, which is what nominates
        # a fused kernel
        op_family_residuals=op_family_residuals(usable_rows(op_rows)),
        # before/after threshold fit: rows measured with the fused impls
        # forced turn the hand-set selection threshold into a measured
        # per-family one (fit_kernel_thresholds); without them the
        # profile carries none and the knob/default stays in charge
        kernel_residual_thresholds=(
            fit_kernel_thresholds(pallas_rows) if pallas_rows else {}))
    REGISTRY.gauge(
        "ff_refit_step_ratio",
        "Measured/predicted step cost after the last refit "
        "(1.0 = converged)").set(history[-1].ratio)
    return profile, history


def fit_collective_coefficients(rows, machine,
                                prior: Optional[FittedCoefficients] = None
                                ) -> FittedCoefficients:
    """Fit per-tier link-bandwidth scales from MEASURED collectives
    (obs.calibration.CollectiveCalibration rows from the
    collective-bench sweep), rather than from the step-level residual
    attribution `refit()` uses when only op rows exist.

    The evidence is the per-tier ring phases (op="psum",
    strategy="tier_ring"): one tier's grouped psum in isolation is
    linear in bytes, `measured ~= slope/scale * bytes + latency`, so the
    robust linear fit of measured-vs-bytes against predicted-vs-bytes
    gives that tier's scale directly — `scale = slope_pred/slope_meas`.
    Whole-strategy rows (op="allreduce") mix tiers, and resharding
    transfer rows (`ReshardResult.calibration_rows`) mix a round's
    gather/transfer/slice components into one prediction — both are
    report/trace artifacts, not fit evidence, and are ignored here. On
    flat machines the single "mesh" tier fits
    `link_bw_scale`. The mean positive intercept across tiers becomes
    the fitted collective latency. Tiers with fewer than 2 usable rows
    keep their prior."""
    coeffs = prior if prior is not None else FittedCoefficients()
    coeffs = dataclasses.replace(
        coeffs, compute_scale=dict(coeffs.compute_scale),
        tier_link_scales=dict(coeffs.tier_link_scales))
    by_tier: Dict[str, List] = {}
    for r in usable_rows(rows):
        if getattr(r, "op", None) == "psum" \
                and getattr(r, "strategy", None) == "tier_ring":
            by_tier.setdefault(str(r.tier), []).append(r)
    tier_names = {t.name for t in getattr(machine, "tiers", [])}
    intercepts: List[float] = []
    for tier, group in by_tier.items():
        if len(group) < 2:
            continue
        xs = [float(r.bytes) for r in group]
        if max(xs) <= min(xs):
            continue  # one byte size cannot separate slope from latency
        a_meas, b_meas = _trimmed_linear_fit(xs,
                                             [r.measured_us for r in group])
        a_pred, _ = _trimmed_linear_fit(xs,
                                        [r.predicted_us for r in group])
        if not (a_meas > 0 and a_pred > 0):
            continue
        scale = _clamp(a_pred / a_meas)
        if tier in tier_names:
            prior_t = coeffs.tier_link_scales.get(tier,
                                                  coeffs.link_bw_scale)
            coeffs.tier_link_scales[tier] = _clamp(prior_t * scale)
        else:
            # flat machine ("mesh" tier): the single-scale path
            coeffs.link_bw_scale = _clamp(coeffs.link_bw_scale * scale)
        intercepts.append(max(0.0, b_meas))
    if intercepts:
        coeffs.collective_latency_us = _clamp(
            sum(intercepts) / len(intercepts), 0.0, 1e4)
    return coeffs


# -- live drift detection --------------------------------------------------

class DriftDetector:
    """EMA watch of measured-vs-predicted step time during training.

    `observe(measured_step_us)` is called once per committed optimizer
    step (FFModel.fit and the ElasticCoordinator loop both feed it). It
    maintains an EMA of the measured step time, publishes
    `ff_calibration_drift` (|ema/predicted - 1|, 0 = perfectly
    calibrated), and returns True when the drift has exceeded `threshold`
    for `patience` consecutive post-warmup steps AND the re-plan budget
    (`max_replans`) is not exhausted — the caller (ElasticCoordinator)
    then runs the budgeted refit + re-search. Plain `FFModel.fit` cannot
    re-plan; there a breach only marks the gauge/counter and an
    `obs.drift` trace instant (same contract as the watchdog's
    no-rollback guard mode).

    `rearm(new_predicted_step_us)` resets the EMA after a re-plan so the
    detector measures drift against the NEW plan's prediction."""

    def __init__(self, predicted_step_us: float, threshold: float = 0.5,
                 alpha: float = 0.25, warmup_steps: int = 3,
                 patience: int = 2, max_replans: int = 1,
                 registry=None):
        if not (predicted_step_us and predicted_step_us > 0):
            raise ValueError(
                f"DriftDetector needs a positive predicted_step_us, got "
                f"{predicted_step_us!r}")
        self.predicted_step_us = float(predicted_step_us)
        self.threshold = float(threshold)
        self.alpha = float(alpha)
        self.warmup_steps = int(warmup_steps)
        self.patience = max(1, int(patience))
        self.max_replans = int(max_replans)
        self.replans = 0
        reg = registry if registry is not None else REGISTRY
        self._g_drift = reg.gauge(
            "ff_calibration_drift",
            "|EMA(measured step)/predicted - 1|; 0 = calibrated")
        self._c_breach = reg.counter(
            "ff_drift_breaches_total",
            "Post-warmup steps whose drift exceeded the threshold")
        self._ema: Optional[float] = None
        self._seen = 0
        self._breach_run = 0

    @property
    def measured_step_us(self) -> Optional[float]:
        """The current EMA of measured step time (None pre-warmup)."""
        return self._ema

    @property
    def drift(self) -> float:
        if self._ema is None:
            return 0.0
        return abs(self._ema / self.predicted_step_us - 1.0)

    def observe(self, measured_step_us: float) -> bool:
        """Feed one committed step's measured wall time (us). Returns True
        when a budgeted re-plan should fire NOW. Observing never consumes
        the budget — only the caller that actually PERFORMS the re-plan
        does (`note_replan()`, then `rearm()`); plain FFModel.fit, which
        can only mark the breach, leaves the budget intact for a
        coordinator to spend later."""
        v = float(measured_step_us)
        if not (v > 0 and math.isfinite(v)):
            return False  # clock-resolution zero steps teach nothing
        self._seen += 1
        if self._seen <= self.warmup_steps:
            # warmup absorbs the jit-compile first steps; they would
            # permanently poison the EMA
            return False
        self._ema = (v if self._ema is None
                     else self.alpha * v + (1 - self.alpha) * self._ema)
        d = self.drift
        self._g_drift.set(d)
        if d <= self.threshold:
            self._breach_run = 0
            return False
        self._breach_run += 1
        self._c_breach.inc()
        if self._breach_run < self.patience:
            return False
        self._breach_run = 0  # a fresh patience window either way
        if self.replans >= self.max_replans:
            return False  # budget spent: keep gauging, stop firing
        return True

    def note_replan(self) -> None:
        """Record that a re-plan was actually performed (consumes one unit
        of `max_replans`). Called by the ElasticCoordinator, never by
        observers that cannot re-plan."""
        self.replans += 1

    def rearm(self, predicted_step_us: float) -> None:
        """Re-anchor after a re-plan: drift is now measured against the
        re-searched plan's prediction, with a fresh warmup/EMA."""
        if predicted_step_us and predicted_step_us > 0:
            self.predicted_step_us = float(predicted_step_us)
        self._ema = None
        self._seen = 0
        self._breach_run = 0
        self._g_drift.set(0.0)
