"""Span tracer: nestable wall-clock spans with Chrome-trace-event export.

The runtime is instrumented with `with tracer.span("name"):` blocks at
every phase boundary (search enumerate/prune/simulate, compile, executor
step dispatch, checkpoint save/restore, the elastic recovery pipeline,
serving request handling). The contract that keeps this free to leave in
hot loops:

 - DISABLED (the default): `span()` is one attribute check returning a
   shared no-op context manager — no allocation, no clock read, no lock.
   `tests/test_obs.py` bounds the overhead.
 - ENABLED: each span costs two monotonic clock reads plus one dict
   append under a lock; the buffer is a ring (`max_events`) so a long
   training run cannot grow memory without bound.

Export is the Chrome trace-event JSON format (complete "X" events with
`name`/`ph`/`ts`/`dur`/`pid`/`tid`), loadable in Perfetto / chrome://
tracing. `ts` is microseconds from tracer start; spans on one thread nest
by construction, so parent events always contain their children.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


class _NullSpan:
    """The disabled-path context manager: a shared, stateless no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):  # matches _Span.set; still a no-op
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.args = args

    def set(self, **args) -> "_Span":
        """Attach/override args mid-span (e.g. a result count discovered
        while the span is open)."""
        self.args.update(args)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self._tracer._emit(self.name, self._t0, t1, self.args)
        return False


class Tracer:
    """A span buffer. One process-wide instance (`get_tracer()`) backs the
    whole runtime; independent Tracers exist for tests."""

    def __init__(self, enabled: bool = False, max_events: int = 200_000):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max_events)
        self._epoch_ns = time.perf_counter_ns()
        self._tids: Dict[int, int] = {}

    # -- recording --------------------------------------------------------
    def span(self, name: str, **args):
        """Context manager timing a block. Near-zero cost when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (Chrome "i" event) — e.g. the moment a
        topology loss is detected, before recovery spans open."""
        if not self.enabled:
            return
        now = time.perf_counter_ns()
        self._append({
            "name": name, "ph": "i", "s": "t",
            "ts": (now - self._epoch_ns) / 1e3,
            "pid": os.getpid(), "tid": self._tid(),
            "args": args,
        })

    def _tid(self) -> int:
        # Chrome trace tids render best small and stable per thread
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids) + 1)
        return tid

    def _emit(self, name: str, t0_ns: int, t1_ns: int,
              args: Dict[str, Any]) -> None:
        self._append({
            "name": name, "ph": "X",
            "ts": (t0_ns - self._epoch_ns) / 1e3,
            "dur": (t1_ns - t0_ns) / 1e3,
            "pid": os.getpid(), "tid": self._tid(),
            "args": {k: _jsonable(v) for k, v in args.items()},
        })

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            self._events.append(ev)

    # -- control ----------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # -- export -----------------------------------------------------------
    def events(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self._events)
        if name is not None:
            evs = [e for e in evs if e["name"] == name]
        return evs

    def span_names(self) -> List[str]:
        return sorted({e["name"] for e in self.events()})

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The Chrome trace-event container Perfetto loads."""
        meta = [{
            "name": "process_name", "ph": "M", "pid": os.getpid(), "tid": 0,
            "args": {"name": "flexflow_tpu"},
        }]
        return {"traceEvents": meta + self.events(),
                "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


# -- the process-wide tracer ----------------------------------------------
_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def enable_tracing() -> Tracer:
    _TRACER.enable()
    return _TRACER


def disable_tracing() -> None:
    _TRACER.disable()


def span(name: str, **args):
    """Module-level convenience over the process tracer. Hot loops should
    hoist `tr = get_tracer()` and call `tr.span` directly."""
    return _TRACER.span(name, **args)


def traced_dispatch(fn, name: str):
    """Wrap a jitted step function so each host-side dispatch becomes a
    span. The wall time is the DISPATCH (host call until the result's
    futures are returned), not device completion — jax dispatch is async;
    the per-step wall clock lives in StepStats. Disabled tracing is one
    attribute check per call."""
    tr = _TRACER

    def wrapper(*a, **k):
        if not tr.enabled:
            return fn(*a, **k)
        with tr.span(name):
            return fn(*a, **k)

    wrapper.__wrapped__ = fn
    wrapper.__name__ = getattr(fn, "__name__", name)
    return wrapper
