"""Span tracer: nestable wall-clock spans with Chrome-trace-event export,
plus request-scoped distributed tracing (docs/observability.md "Request
tracing & post-mortem timelines").

The runtime is instrumented with `with tracer.span("name"):` blocks at
every phase boundary (search enumerate/prune/simulate, compile, executor
step dispatch, checkpoint save/restore, the elastic recovery pipeline,
serving request handling). The contract that keeps this free to leave in
hot loops:

 - DISABLED (the default): `span()` is one attribute check returning a
   shared no-op context manager — no allocation, no clock read, no lock.
   `tests/test_obs.py` bounds the overhead.
 - ENABLED: each span costs two monotonic clock reads plus one dict
   append under a lock; the buffer is a ring (`max_events`) so a long
   training run cannot grow memory without bound. Ring overflow is
   COUNTED (`dropped_events`, mirrored onto
   `ff_trace_events_dropped_total` and stamped into the exported trace
   metadata) so a truncated timeline is never mistaken for a complete
   one.

Request-scoped tracing: a `TraceContext` (trace_id / span_id /
parent_id) rides a contextvar. While a context is current, every span
becomes a CHILD of it — the span allocates its own span_id, records
trace_id/span_id/parent_id in its args, and re-parents the contextvar
for its duration, so nested spans chain correctly even across library
layers that know nothing about requests. Thread crossings are EXPLICIT:
the sending side captures `tracer.handoff(name)` (which emits a Chrome
flow-start "s" event so Perfetto draws the arrow) and the receiving
thread runs its work under `with tracer.resume(handoff):` (flow-finish
"f" on first resume, context restored on every resume). Both return
no-ops when tracing is disabled or no context is current, so the
serving hot path pays nothing by default.

Export is the Chrome trace-event JSON format (complete "X" events with
`name`/`ph`/`ts`/`dur`/`pid`/`tid`, flow "s"/"f" events for handoffs),
loadable in Perfetto / chrome://tracing. `ts` is microseconds from
tracer start; the wall-clock epoch captured at the same instant is
exported as trace metadata so other streams (EventLog, metric
snapshots) can be aligned onto the same axis by the `timeline` CLI.
Spans on one thread nest by construction, so parent events always
contain their children.
"""
from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional


class _NullSpan:
    """The disabled-path context manager: a shared, stateless no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):  # matches _Span.set; still a no-op
        return self


_NULL_SPAN = _NullSpan()


# -- request context -------------------------------------------------------
class TraceContext:
    """One request's position in its trace: which trace it belongs to
    (`trace_id`), the id of the span currently open for it (`span_id`),
    and that span's parent (`parent_id`, None at the root). Immutable —
    spans and handoffs derive CHILD contexts instead of mutating."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _new_span_id(), self.span_id)

    def __repr__(self):
        return (f"TraceContext(trace_id={self.trace_id!r},"
                f" span_id={self.span_id!r}, parent_id={self.parent_id!r})")


_CTX: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("ff_trace_context", default=None)


def new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def current_context() -> Optional[TraceContext]:
    """The TraceContext current on this thread/task, or None."""
    return _CTX.get()


def current_trace_id() -> Optional[str]:
    ctx = _CTX.get()
    return ctx.trace_id if ctx is not None else None


class _CtxScope:
    """`with use_context(ctx):` — install a TraceContext on the current
    thread, restore the previous one on exit."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._token = _CTX.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _CTX.reset(self._token)
        return False


def use_context(ctx: Optional[TraceContext]) -> _CtxScope:
    """Run a block under `ctx` (None clears the context — e.g. scheduler
    work not attributable to any request)."""
    return _CtxScope(ctx)


def root_context(trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None) -> TraceContext:
    """A fresh root context: new trace unless `trace_id` is given (the
    server passes the id from an incoming `traceparent` header, with the
    caller's span as `parent_id`)."""
    return TraceContext(trace_id or new_trace_id(), _new_span_id(),
                        parent_id)


class Handoff:
    """An explicit thread-crossing token: the captured TraceContext plus
    the Chrome flow id binding the sending span to the receiving one.
    Created by `Tracer.handoff()`, consumed by `Tracer.resume()` —
    resumable any number of times (the flow-finish event is emitted once)."""

    __slots__ = ("ctx", "flow_id", "name", "_consumed")

    def __init__(self, ctx: TraceContext, flow_id: int, name: str):
        self.ctx = ctx
        self.flow_id = flow_id
        self.name = name
        self._consumed = False

    @property
    def trace_id(self) -> str:
        return self.ctx.trace_id


class _Resume:
    """`with tracer.resume(handoff):` — restore the handed-off context on
    the receiving thread; first resume emits the flow-finish event."""

    __slots__ = ("_tracer", "_handoff", "_token")

    def __init__(self, tracer: "Tracer", handoff: Handoff):
        self._tracer = tracer
        self._handoff = handoff

    def __enter__(self):
        h = self._handoff
        self._token = _CTX.set(h.ctx)
        if not h._consumed:
            h._consumed = True
            self._tracer._emit_flow("f", h)
        return h.ctx

    def __exit__(self, *exc):
        _CTX.reset(self._token)
        return False


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0", "_ctx", "_token")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any],
                 parent: Optional[TraceContext]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._ctx = parent.child() if parent is not None else None

    def set(self, **args) -> "_Span":
        """Attach/override args mid-span (e.g. a result count discovered
        while the span is open)."""
        self.args.update(args)
        return self

    def __enter__(self):
        self._token = _CTX.set(self._ctx) if self._ctx is not None else None
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        if self._token is not None:
            _CTX.reset(self._token)
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        ctx = self._ctx
        if ctx is not None:
            self.args["trace_id"] = ctx.trace_id
            self.args["span_id"] = ctx.span_id
            if ctx.parent_id is not None:
                self.args["parent_id"] = ctx.parent_id
        self._tracer._emit(self.name, self._t0, t1, self.args)
        return False


class Tracer:
    """A span buffer. One process-wide instance (`get_tracer()`) backs the
    whole runtime; independent Tracers exist for tests."""

    def __init__(self, enabled: bool = False, max_events: int = 200_000):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max_events)
        # wall <-> perf_counter epoch pair, captured back-to-back: `ts`
        # microseconds are relative to _epoch_ns, and _epoch_wall_s is
        # the SAME instant on the wall clock — the alignment anchor the
        # timeline CLI uses to merge wall-clocked streams (EventLog,
        # metric snapshots) onto the trace axis
        self._epoch_wall_s = time.time()
        self._epoch_ns = time.perf_counter_ns()
        # per-thread-LIFETIME track ids. Keyed through threading.local —
        # NOT threading.get_ident(), which the interpreter recycles the
        # moment a thread dies: a respawned replica's scheduler would
        # inherit the dead one's ident, fold both incarnations onto one
        # track, and rename the victim's spans after the fact.
        self._tid_local = threading.local()
        self._next_tid = itertools.count(1)
        self._thread_names: Dict[int, str] = {}
        self._dropped = 0
        self._flow_ids = itertools.count(1)

    # -- recording --------------------------------------------------------
    def span(self, name: str, **args):
        """Context manager timing a block. Near-zero cost when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args, _CTX.get())

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (Chrome "i" event) — e.g. the moment a
        topology loss is detected, before recovery spans open."""
        if not self.enabled:
            return
        ctx = _CTX.get()
        if ctx is not None:
            args.setdefault("trace_id", ctx.trace_id)
        now = time.perf_counter_ns()
        self._append({
            "name": name, "ph": "i", "s": "t",
            "ts": (now - self._epoch_ns) / 1e3,
            "pid": os.getpid(), "tid": self._tid(),
            "args": {k: _jsonable(v) for k, v in args.items()},
        })

    # -- request context / thread handoff ---------------------------------
    def handoff(self, name: str = "handoff") -> Optional[Handoff]:
        """Capture the current TraceContext for an explicit thread
        crossing, emitting the Chrome flow-start ("s") event so Perfetto
        draws the arrow from here to the receiving thread's resume().
        Returns None (a no-op token) when disabled or there is no
        current context."""
        if not self.enabled:
            return None
        ctx = _CTX.get()
        if ctx is None:
            return None
        h = Handoff(ctx, next(self._flow_ids), name)
        self._emit_flow("s", h)
        return h

    def resume(self, handoff: Optional[Handoff]):
        """Run a block on the receiving thread under the handed-off
        context (no-op for a None token)."""
        if handoff is None or not self.enabled:
            return _NULL_SPAN
        return _Resume(self, handoff)

    def _emit_flow(self, ph: str, h: Handoff) -> None:
        now = time.perf_counter_ns()
        ev = {
            "name": h.name, "ph": ph, "cat": "handoff",
            "id": h.flow_id,
            "ts": (now - self._epoch_ns) / 1e3,
            "pid": os.getpid(), "tid": self._tid(),
            "args": {"trace_id": h.ctx.trace_id},
        }
        if ph == "f":
            ev["bp"] = "e"  # bind the arrow to the enclosing slice
        self._append(ev)

    def set_thread_name(self, name: str) -> None:
        """Label the CURRENT thread's track in the exported trace (Chrome
        `thread_name` metadata) — e.g. a replica's scheduler thread, so
        the merged timeline shows one track per replica. Cheap and valid
        before `enable()`."""
        self._thread_names[self._tid()] = str(name)

    def _tid(self) -> int:
        # Chrome trace tids render best small and stable per thread;
        # threading.local dies with its thread, so a tid is never reused
        tid = getattr(self._tid_local, "tid", None)
        if tid is None:
            tid = self._tid_local.tid = next(self._next_tid)
        return tid

    def _emit(self, name: str, t0_ns: int, t1_ns: int,
              args: Dict[str, Any]) -> None:
        self._append({
            "name": name, "ph": "X",
            "ts": (t0_ns - self._epoch_ns) / 1e3,
            "dur": (t1_ns - t0_ns) / 1e3,
            "pid": os.getpid(), "tid": self._tid(),
            "args": {k: _jsonable(v) for k, v in args.items()},
        })

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)

    # -- control ----------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    # -- export -----------------------------------------------------------
    @property
    def dropped_events(self) -> int:
        """Ring-buffer overflow count since the last clear() — also
        mirrored onto `ff_trace_events_dropped_total` at export."""
        with self._lock:
            return self._dropped

    def events(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self._events)
        if name is not None:
            evs = [e for e in evs if e["name"] == name]
        return evs

    def span_names(self) -> List[str]:
        return sorted({e["name"] for e in self.events()})

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The Chrome trace-event container Perfetto loads. Prepends
        process/thread names plus a `trace_metadata` record carrying the
        wall<->perf_counter epoch pair and the ring-drop count."""
        dropped = self.dropped_events
        self._sync_dropped_metric(dropped)
        pid = os.getpid()
        meta = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "flexflow_tpu"},
        }, {
            "name": "trace_metadata", "ph": "M", "pid": pid, "tid": 0,
            "args": {"epoch_wall_s": self._epoch_wall_s,
                     "dropped_events": dropped},
        }]
        for tid, tname in sorted(self._thread_names.items()):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tname}})
        return {"traceEvents": meta + self.events(),
                "displayTimeUnit": "ms"}

    def _sync_dropped_metric(self, dropped: int) -> None:
        if dropped <= 0:
            return
        from .registry import REGISTRY

        REGISTRY.counter(
            "ff_trace_events_dropped_total",
            "Trace events dropped by the tracer's ring buffer"
            " (a nonzero value means exported timelines are truncated)"
        ).set_total(dropped)

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


# -- the process-wide tracer ----------------------------------------------
_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def enable_tracing() -> Tracer:
    _TRACER.enable()
    return _TRACER


def disable_tracing() -> None:
    _TRACER.disable()


def span(name: str, **args):
    """Module-level convenience over the process tracer. Hot loops should
    hoist `tr = get_tracer()` and call `tr.span` directly."""
    return _TRACER.span(name, **args)


def traced_dispatch(fn, name: str):
    """Wrap a jitted step function so each host-side dispatch becomes a
    span. The wall time is the DISPATCH (host call until the result's
    futures are returned), not device completion — jax dispatch is async;
    the per-step wall clock lives in StepStats. Disabled tracing is one
    attribute check per call."""
    tr = _TRACER

    def wrapper(*a, **k):
        if not tr.enabled:
            return fn(*a, **k)
        with tr.span(name):
            return fn(*a, **k)

    wrapper.__wrapped__ = fn
    wrapper.__name__ = getattr(fn, "__name__", name)
    return wrapper
