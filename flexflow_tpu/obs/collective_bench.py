"""`python -m flexflow_tpu collective-bench`: measure the explicit
collective lowering on the current mesh.

Sweeps {reduction strategy} x {bytes} over all visible devices (one
'data' mesh axis, exactly the surface runtime/collectives.py lowers the
grad sync onto) and, on a hierarchical machine spec, each tier's ring
phase in isolation. Every timing lands as an obs.calibrate row
(`CollectiveCalibration`: op, strategy, tier, bytes, measured_us next to
the machine model's prediction) in
``<out>/collective_calibration.json`` — the data source
`refit.fit_collective_coefficients` fits the per-tier link constants
from, closing the loop between the tier pricing the Unity search ranks
plans with and collectives that actually ran (docs/observability.md).

``--fit-profile`` runs that fit and persists the resulting
FittedProfile as ``<out>/fitted_profile.json`` (loadable into any later
search via ``--fitted-profile``). A ``BENCH {...}`` stdout line reports
the largest-size measurement per strategy; the last stdout line is a
JSON summary and the exit code is nonzero unless every sweep point
measured a positive wall time.

All FFConfig flags pass through — ``--machine-spec`` selects the
hierarchy whose tiers are swept; without one the flat machine yields a
single "mesh" tier. The predicted side states the spec's TPU-class
constants, so on the CPU emulation the ratios are large and only the
RELATIVE per-tier slopes are meaningful — which is exactly what the fit
consumes.
"""
from __future__ import annotations

import json
import math
import os
import statistics
import sys
import time
from typing import Any, Dict, List, Optional

DEFAULT_SIZES_MB = (0.25, 1.0, 4.0)
DEFAULT_STRATEGIES = ("flat", "rs_ar_ag", "hier_ring")


def _median_wall_us(fn, args, warmup: int, repeats: int) -> float:
    import jax

    for _ in range(max(0, warmup)):
        jax.block_until_ready(fn(*args))
    samples = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append((time.perf_counter() - t0) * 1e6)
    return float(statistics.median(samples))


def sweep_collectives(config, sizes_bytes: List[int],
                      strategies: List[str], warmup: int = 1,
                      repeats: int = 3) -> Dict[str, Any]:
    """Run the sweep on the live devices; returns {"rows": [...],
    "n_devices", "tiers", "machine"} with rows as CollectiveCalibration
    objects."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..kernels import get_shard_map
    from ..runtime.collectives import lower_allreduce, tier_axis_groups
    from ..search.machine_model import make_machine_model
    from .calibration import CollectiveCalibration

    n = max(1, config.total_devices)
    devices = jax.devices()[:n]
    if len(devices) < n:
        raise SystemExit(
            f"collective-bench: {n} devices requested but only"
            f" {len(devices)} visible")
    mesh = Mesh(np.array(devices), ("data",))
    machine = make_machine_model(config, n)
    tier_path = (machine.tier_path(n)
                 if hasattr(machine, "tier_path") else [])
    if tier_path and math.prod(ni for _, ni in tier_path) != n:
        print(f"collective-bench: machine spec tiers do not factor the"
              f" {n}-device mesh; sweeping flat", file=sys.stderr)
        tier_path = []
    group_sizes = [ni for _, ni in tier_path] or [n]
    tier_names = [t.name for t, _ in tier_path] or ["mesh"]
    groups = tier_axis_groups(n, group_sizes)
    outer_tier = tier_names[-1]
    sm = get_shard_map(check_vma=False)
    rows: List[CollectiveCalibration] = []

    def timed(body, elems) -> float:
        x = jax.device_put(
            jnp.ones((n, elems), jnp.float32),
            NamedSharding(mesh, P("data")))
        fn = jax.jit(sm(body, mesh=mesh, in_specs=P("data"),
                        out_specs=P("data")))
        return _median_wall_us(fn, (x,), warmup, repeats)

    for strategy in strategies:
        if n <= 1:
            break
        if strategy != "flat" and len(group_sizes) <= 1:
            continue  # nothing to decompose on a flat machine
        for size in sizes_bytes:
            elems = max(1, int(size) // 4)

            def body(x, strategy=strategy):
                return lower_allreduce(x[0], "data", strategy,
                                       group_sizes, groups)[None]

            measured = timed(body, elems)
            bytes_ = elems * 4.0
            if hasattr(machine, "tier_path"):
                predicted = machine.allreduce_time_us(bytes_, n,
                                                      strategy=strategy)
            else:
                predicted = machine.allreduce_time_us(bytes_, n)
            rows.append(CollectiveCalibration(
                op="allreduce", strategy=strategy, tier=outer_tier,
                bytes=bytes_, participants=n, predicted_us=predicted,
                measured_us=measured))
    # each tier's ring phase in isolation: the per-tier fit's evidence
    for level_idx, (tname, nj) in enumerate(
            zip(tier_names, group_sizes)):
        if nj <= 1 or n <= 1:
            continue
        level_groups = groups[level_idx]
        for size in sizes_bytes:
            elems = max(1, int(size) // 4)

            def body(x, level_groups=level_groups):
                import jax.lax as lax

                return lax.psum(x[0], "data",
                                axis_index_groups=level_groups)[None]

            measured = timed(body, elems)
            bytes_ = elems * 4.0
            if tier_path:
                tier = next(t for t, _ in tier_path if t.name == tname)
                predicted = (2.0 * (nj - 1) / nj * bytes_
                             / machine.tier_bw(tier) * 1e6
                             + machine.tier_latency(tier))
            else:
                predicted = machine.allreduce_time_us(bytes_, n)
            rows.append(CollectiveCalibration(
                op="psum", strategy="tier_ring", tier=tname,
                bytes=bytes_, participants=nj, predicted_us=predicted,
                measured_us=measured))
    return {"rows": rows, "n_devices": n, "tiers": tier_names,
            "group_sizes": group_sizes,
            "machine": type(machine).__name__, "chip": machine.chip.name}


def run_collective_bench(argv: Optional[List[str]] = None) -> int:
    from .cli import _take

    argv = list(argv or [])
    out_dir = _take(argv, "--out", "collective_bench_out")
    warmup = _take(argv, "--warmup", 1, cast=int)
    repeats = _take(argv, "--repeats", 3, cast=int)
    sizes_spec = _take(argv, "--sizes-mb",
                       ",".join(str(s) for s in DEFAULT_SIZES_MB))
    strategies_spec = _take(argv, "--strategies",
                            ",".join(DEFAULT_STRATEGIES))
    fit_profile = "--fit-profile" in argv
    if fit_profile:
        argv.remove("--fit-profile")

    from ..runtime.platform import honor_env_platform

    honor_env_platform()

    import flexflow_tpu as ff

    config = ff.FFConfig()
    rest = config.parse_args(argv)
    if rest:
        print(f"warning: unrecognized flags {rest}", file=sys.stderr)
    try:
        sizes = [max(4, int(float(s) * 1e6))
                 for s in sizes_spec.split(",") if s.strip()]
    except ValueError:
        raise SystemExit(f"--sizes-mb: cannot parse {sizes_spec!r}") \
            from None
    strategies = [s.strip() for s in strategies_spec.split(",")
                  if s.strip()]
    bad = set(strategies) - set(DEFAULT_STRATEGIES)
    if bad:
        raise SystemExit(f"--strategies: unknown {sorted(bad)}; choices:"
                         f" {DEFAULT_STRATEGIES}")

    os.makedirs(out_dir, exist_ok=True)
    result = sweep_collectives(config, sizes, strategies,
                               warmup=warmup, repeats=repeats)
    rows = result["rows"]
    payload = {k: v for k, v in result.items() if k != "rows"}
    payload["rows"] = [r.to_dict() for r in rows]
    cal_path = os.path.join(out_dir, "collective_calibration.json")
    with open(cal_path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")

    problems: List[str] = []
    if not rows:
        problems.append("no collectives measurable (single device?)")
    for r in rows:
        if not (r.measured_us > 0 and math.isfinite(r.measured_us)):
            problems.append(
                f"{r.op}/{r.strategy}/{r.tier}@{int(r.bytes)}B measured"
                f" {r.measured_us!r}")

    profile_path = None
    if fit_profile and rows:
        import jax

        from ..search.machine_model import make_machine_model
        from .refit import FittedProfile, fit_collective_coefficients

        machine = make_machine_model(config, max(1, config.total_devices))
        coeffs = fit_collective_coefficients(rows, machine)
        profile_path = FittedProfile(
            chip=machine.chip.name, backend=jax.default_backend(),
            coefficients=coeffs, fitted_ops=len(rows),
            num_chips=max(1, config.total_devices),
        ).save(os.path.join(out_dir, "fitted_profile.json"))

    largest: Dict[str, Any] = {}
    for r in rows:
        if r.op != "allreduce":
            continue
        cur = largest.get(r.strategy)
        if cur is None or r.bytes > cur["bytes"]:
            largest[r.strategy] = {"bytes": r.bytes,
                                   "measured_us": r.measured_us,
                                   "predicted_us": r.predicted_us}
    bench = {
        "metric": "collective_allreduce_us",
        "n_devices": result["n_devices"],
        "tiers": result["tiers"],
        "per_strategy": largest,
        "rows": len(rows),
        "calibration": cal_path,
        "fitted_profile": profile_path,
    }
    print("BENCH " + json.dumps(bench))
    summary = {"ok": not problems, "out": out_dir, "rows": len(rows),
               "tiers": result["tiers"], "fitted_profile": profile_path,
               "problems": problems}
    print(json.dumps(summary))
    return 0 if not problems else 1
