"""StepStats: per-step training statistics in a bounded ring buffer.

Replaces `runtime/profiling.IterationTimer`'s internals: `FFModel.fit`
records every committed optimizer step (or K-step dispatch chunk) here —
wall ms, samples/s, achieved TFLOP/s, and MFU against the machine spec's
peak — and summarizes at fit end. The ring (`capacity`) bounds memory on
long runs; the newest records also feed the registry metrics
`ff_train_steps_total`, `ff_step_wall_ms` (histogram),
`ff_step_samples_per_s` and `ff_step_mfu` (gauges).

FLOPs accounting: `op.flops()` is the per-batch FORWARD estimate; a
training step is priced at 3x forward (backward ~2x forward — the
standard accounting, e.g. PaLM appendix B). MFU = achieved TFLOP/s over
`n_devices * chip peak` from the search's machine spec, so the number is
comparable with the cost simulator's roofline.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

from .registry import REGISTRY, MetricsRegistry

TRAIN_FLOPS_FACTOR = 3.0  # fwd + bwd(≈2x fwd)

_WALL_MS_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                    250.0, 500.0, 1000.0, 5000.0)


def model_train_flops_per_step(model) -> float:
    """Whole-graph per-step training FLOPs for a compiled FFModel."""
    if model.graph is None:
        return 0.0
    return TRAIN_FLOPS_FACTOR * sum(
        op.flops() for op in model.graph.ops.values())


def model_peak_tflops(model) -> float:
    """Aggregate peak TFLOP/s of the device set, from the same machine
    spec the cost simulator prices against."""
    from ..search.machine_model import make_machine_model

    n_dev = max(1, model.config.total_devices)
    chip = make_machine_model(model.config, n_dev).chip
    per_chip = (chip.peak_bf16_tflops if model.config.allow_mixed_precision
                else chip.peak_f32_tflops)
    return per_chip * n_dev


class StepStats:
    """Ring buffer of per-step records with derived throughput/MFU.

    Usage: `start()` arms the clock; `record_step(samples, loss,
    steps=K)` closes one dispatch (K optimizer steps) and opens the next
    interval. Zero-duration intervals (fast no-op steps on CPU CI) record
    wall_ms=0 with rates of 0 rather than dividing by zero."""

    def __init__(self, flops_per_step: float = 0.0,
                 peak_tflops: float = 0.0, capacity: int = 2048,
                 registry: Optional[MetricsRegistry] = None,
                 print_freq: int = 0, sink=print):
        self.flops_per_step = float(flops_per_step)
        self.peak_tflops = float(peak_tflops)
        self._records: deque = deque(maxlen=max(1, capacity))
        self._mark: Optional[float] = None
        self._total_steps = 0
        self._total_samples = 0
        # optional periodic print (the IterationTimer role)
        self.print_freq = int(print_freq)
        self.sink = sink
        reg = registry if registry is not None else REGISTRY
        self._m_steps = reg.counter(
            "ff_train_steps_total", "Committed optimizer steps")
        self._m_wall = reg.histogram(
            "ff_step_wall_ms", "Per-optimizer-step wall time (ms)",
            buckets=_WALL_MS_BUCKETS)
        self._m_rate = reg.gauge(
            "ff_step_samples_per_s", "Most recent step throughput")
        self._m_mfu = reg.gauge(
            "ff_step_mfu", "Most recent step model FLOPs utilization")

    # -- recording --------------------------------------------------------
    def start(self) -> None:
        self._mark = time.perf_counter()

    def record_step(self, samples: int, loss: Optional[float] = None,
                    steps: int = 1) -> Dict[str, float]:
        """Close the current interval as `steps` optimizer steps that
        consumed `samples` samples total."""
        now = time.perf_counter()
        if self._mark is None:
            self._mark = now
        wall_s = max(0.0, now - self._mark)
        self._mark = now
        steps = max(1, int(steps))
        per_step_s = wall_s / steps
        rate = samples / wall_s if wall_s > 0 else 0.0
        tflops = (self.flops_per_step / per_step_s / 1e12
                  if per_step_s > 0 and self.flops_per_step > 0 else 0.0)
        mfu = tflops / self.peak_tflops if self.peak_tflops > 0 else 0.0
        rec = {
            "wall_ms": wall_s * 1e3,
            "step_ms": per_step_s * 1e3,
            "steps": float(steps),
            "samples": float(samples),
            "samples_per_s": rate,
            "tflops": tflops,
            "mfu": mfu,
        }
        if loss is not None:
            rec["loss"] = float(loss)
        self._records.append(rec)
        self._total_steps += steps
        self._total_samples += samples
        self._m_steps.inc(steps)
        self._m_wall.observe(per_step_s * 1e3)
        self._m_rate.set(rate)
        self._m_mfu.set(mfu)
        if self.print_freq > 0 and self.sink is not None \
                and self._total_steps % self.print_freq == 0:
            self.sink(
                f"iter {self._total_steps}: {rate:.1f} samples/s "
                f"({per_step_s * 1e3:.1f} ms/iter"
                + (f", mfu={mfu:.3f}" if self.peak_tflops > 0 else "")
                + ")")
        return rec

    # -- reading ----------------------------------------------------------
    def __len__(self) -> int:
        return len(self._records)

    @property
    def total_steps(self) -> int:
        return self._total_steps

    def records(self) -> List[Dict[str, float]]:
        return list(self._records)

    def last(self) -> Optional[Dict[str, float]]:
        return self._records[-1] if self._records else None

    def mean_step_ms(self) -> float:
        recs = self.records()
        if not recs:
            return 0.0
        return sum(r["step_ms"] for r in recs) / len(recs)

    def summary(self) -> Dict[str, Any]:
        recs = self.records()
        if not recs:
            return {"steps": self._total_steps, "recorded": 0}
        step_ms = sorted(r["step_ms"] for r in recs)

        def pct(p: float) -> float:
            return step_ms[min(len(step_ms) - 1,
                               int(p / 100.0 * len(step_ms)))]

        rated = [r for r in recs if r["samples_per_s"] > 0]
        return {
            "steps": self._total_steps,
            "recorded": len(recs),
            "samples": self._total_samples,
            "mean_step_ms": sum(step_ms) / len(step_ms),
            "p50_step_ms": pct(50),
            "p95_step_ms": pct(95),
            "mean_samples_per_s": (
                sum(r["samples_per_s"] for r in rated) / len(rated)
                if rated else 0.0),
            "mean_tflops": (sum(r["tflops"] for r in recs) / len(recs)),
            "mean_mfu": (sum(r["mfu"] for r in recs) / len(recs)),
            "last_loss": recs[-1].get("loss"),
        }

    def format_summary(self) -> str:
        s = self.summary()
        if not s.get("recorded"):
            return "step stats: no recorded steps"
        return (f"step stats: {s['steps']} step(s), "
                f"mean {s['mean_step_ms']:.2f} ms/step "
                f"(p95 {s['p95_step_ms']:.2f}), "
                f"{s['mean_samples_per_s']:.1f} samples/s, "
                f"{s['mean_tflops']:.2f} TFLOP/s, "
                f"mfu={s['mean_mfu']:.4f}")
