"""MetricsRegistry: typed Counter/Gauge/Histogram primitives with labels.

One registry replaces the four hand-rolled counter dicts that grew up
around the runtime (`analysis/diagnostics.py` plan-diagnostic counters,
`runtime/durability.py` checkpoint counters, `elastic/watchdog.py`
watchdog counters, serving `ModelMetrics`) and is the SINGLE Prometheus
exposition renderer in the tree — every `/metrics` byte comes out of
`MetricsRegistry.render()`.

Design rules:
 - a metric family is (name, kind, label names); re-requesting an existing
   family returns the same object, and a kind/label mismatch is a loud
   ValueError — two subsystems cannot silently publish incompatible series
   under one name;
 - `reset_all()` zeroes VALUES but keeps family registrations, so modules
   that cached a handle at import time keep working across test resets;
 - rendering escapes help text and label values per the exposition format
   and `parse_exposition`/`validate_exposition` round-trip them — the
   property the obs test suite pins.
"""
from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

DEFAULT_LATENCY_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0)


def escape_label_value(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Family:
    """One metric family: shared name/help/label schema, per-labelset
    values. Thread-safe — serving handler threads read while training
    threads bump."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labels:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.label_names: Tuple[str, ...] = tuple(labels)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"family declares {sorted(self.label_names)}")
        return tuple(str(labels[ln]) for ln in self.label_names)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def items(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            return sorted(self._values.items())

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def remove(self, **labels) -> None:
        """Drop one labelset's series (e.g. a model unregistered from a
        server) so it stops rendering; the family stays registered."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    # -- rendering --------------------------------------------------------
    def _sample_lines(self, extra: Sequence[Tuple[str, str]] = ()) \
            -> List[str]:
        """Sample lines, optionally with extra (name, value) label pairs
        PREPENDED to every series — how `render_merged` stamps each
        replica's samples with its `replica` label."""
        items = self.items()
        if not items and not self.label_names:
            # an unlabeled family is born at 0 (prometheus-client
            # semantics) — a reset family renders 0, not nothing
            items = [((), 0.0)]
        names = tuple(n for n, _ in extra) + self.label_names
        vals = tuple(v for _, v in extra)
        return [self._line(self.name, names, vals + key, v)
                for key, v in items]

    @staticmethod
    def _line(name: str, label_names: Sequence[str],
              label_values: Sequence[str], v: float) -> str:
        if label_names:
            lbl = ",".join(
                f'{ln}="{escape_label_value(lv)}"'
                for ln, lv in zip(label_names, label_values))
            return f"{name}{{{lbl}}} {_fmt(v)}"
        return f"{name} {_fmt(v)}"

    def render(self) -> str:
        lines = [f"# HELP {self.name} {escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        lines += self._sample_lines()
        return "\n".join(lines) + "\n"


class Counter(_Family):
    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (n={n})")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def set_total(self, value: float, **labels) -> None:
        """Mirror an externally-accumulated monotonic total (e.g. an
        EventLog's per-kind counts) into the exposition. Not for general
        use — `inc` is the counter contract."""
        with self._lock:
            self._values[self._key(labels)] = float(value)


class Gauge(_Family):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, n: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)


def _norm_buckets(buckets: Sequence[float]) -> Tuple[float, ...]:
    b = sorted(float(x) for x in buckets)
    if not b or b[-1] != math.inf:
        b.append(math.inf)
    return tuple(b)


class Histogram(_Family):
    """Cumulative-bucket histogram (`_bucket{le=}`/`_sum`/`_count`).

    `observe(v, exemplar=trace_id)` additionally pins the LATEST exemplar
    onto the landing bucket, rendered as an OpenMetrics exemplar suffix
    (`... # {trace_id="<id>"} <value>`) so a tail bucket links back to
    the trace that caused it (docs/observability.md "Request tracing")."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labels: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS):
        super().__init__(name, help, labels)
        self.buckets: Tuple[float, ...] = _norm_buckets(buckets)
        # per-labelset: [bucket counts..., sum, count]
        self._hist: Dict[Tuple[str, ...], List[float]] = {}
        # (labelset, landing-bucket index) -> (exemplar id, observed value)
        self._exemplars: Dict[Tuple[Tuple[str, ...], int],
                              Tuple[str, float]] = {}

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            ent = self._hist.get(key)
            if ent is None:
                ent = self._hist[key] = [0.0] * (len(self.buckets) + 2)
            landing = None
            for i, le in enumerate(self.buckets):
                if v <= le:
                    if landing is None:
                        landing = i
                    ent[i] += 1
            ent[-2] += v
            ent[-1] += 1
            if exemplar is not None and landing is not None:
                self._exemplars[(key, landing)] = (str(exemplar), v)

    def exemplar(self, bucket_le: float, **labels) -> Optional[Tuple[str,
                                                                     float]]:
        """The (exemplar id, observed value) pinned on one bucket, or
        None."""
        key = self._key(labels)
        try:
            idx = self.buckets.index(float(bucket_le))
        except ValueError:
            return None
        with self._lock:
            return self._exemplars.get((key, idx))

    def count(self, **labels) -> int:
        key = self._key(labels)
        with self._lock:
            ent = self._hist.get(key)
            return int(ent[-1]) if ent else 0

    def snapshot(self, **labels) -> Tuple[float, ...]:
        """Immutable copy of one labelset's cumulative row
        ([bucket counts..., sum, count]; all-zero when never observed) —
        the baseline for a windowed `quantile(since=)` read."""
        key = self._key(labels)
        with self._lock:
            ent = self._hist.get(key)
            if ent is None:
                return (0.0,) * (len(self.buckets) + 2)
            return tuple(ent)

    def quantile(self, q: float, since: Optional[Sequence[float]] = None,
                 **labels) -> float:
        """Approximate q-quantile (q in [0, 1]) from the cumulative
        buckets, linearly interpolated inside the landing bucket — what
        the fleet autoscaler reads TTFT percentiles from without keeping
        raw samples. Observations in the +Inf bucket clamp to the last
        finite boundary (the histogram has no upper bound to interpolate
        toward). 0.0 when nothing was observed.

        `since`: a `snapshot()` baseline subtracted bucket-wise first, so
        the quantile covers only observations AFTER the snapshot — the
        buckets themselves never decay, and a control loop reading the
        lifetime quantile would treat one historic slow period as a
        permanent overload."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q={q}: want [0, 1]")
        key = self._key(labels)
        with self._lock:
            ent = self._hist.get(key)
            if ent is None or ent[-1] <= 0:
                return 0.0
            if since is not None and len(since) == len(ent):
                ent = [max(0.0, a - b) for a, b in zip(ent, since)]
                if ent[-1] <= 0:
                    return 0.0
            total = ent[-1]
            rank = q * total
            prev_le, prev_cum = 0.0, 0.0
            for i, le in enumerate(self.buckets):
                cum = ent[i]
                if cum >= rank:
                    if math.isinf(le):
                        return prev_le
                    if cum == prev_cum:
                        return le
                    frac = (rank - prev_cum) / (cum - prev_cum)
                    return prev_le + frac * (le - prev_le)
                prev_le, prev_cum = (0.0 if math.isinf(le) else le), cum
            return prev_le

    def sum(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            ent = self._hist.get(key)
            return float(ent[-2]) if ent else 0.0

    def reset(self) -> None:
        with self._lock:
            self._hist.clear()
            self._exemplars.clear()

    def remove(self, **labels) -> None:
        with self._lock:
            key = self._key(labels)
            self._hist.pop(key, None)
            for k in [k for k in self._exemplars if k[0] == key]:
                del self._exemplars[k]

    def _sample_lines(self, extra: Sequence[Tuple[str, str]] = ()) \
            -> List[str]:
        out = []
        with self._lock:
            # deep-copy the per-labelset lists INSIDE the lock: a
            # concurrent observe() mutates buckets, then sum, then count,
            # and a lock-free read could emit a torn histogram
            # (bucket{+Inf} != count) that breaks rate()/quantile math
            items = sorted((k, list(v)) for k, v in self._hist.items())
            exemplars = dict(self._exemplars)
        ex_names = tuple(n for n, _ in extra)
        ex_vals = tuple(v for _, v in extra)
        for key, ent in items:
            names = ex_names + self.label_names + ("le",)
            for i, le in enumerate(self.buckets):
                line = self._line(f"{self.name}_bucket", names,
                                  ex_vals + tuple(key) + (_fmt(le),),
                                  ent[i])
                ex = exemplars.get((key, i))
                if ex is not None:
                    line += (f' # {{trace_id="{escape_label_value(ex[0])}"}}'
                             f" {_fmt(ex[1])}")
                out.append(line)
            out.append(self._line(f"{self.name}_sum",
                                  ex_names + self.label_names,
                                  ex_vals + key, ent[-2]))
            out.append(self._line(f"{self.name}_count",
                                  ex_names + self.label_names,
                                  ex_vals + key, ent[-1]))
        return out


class MetricsRegistry:
    """A namespace of metric families with one exposition renderer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get_or_make(self, cls, name: str, help: str,
                     labels: Sequence[str], **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or \
                        fam.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind} with labels {fam.label_names}")
                if "buckets" in kw:
                    # a bucket mismatch is as incompatible as a kind
                    # mismatch: the second caller's observations would
                    # land in the first caller's boundaries
                    want = _norm_buckets(kw["buckets"])
                    if fam.buckets != want:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {fam.buckets}, requested {want}")
                return fam
            fam = cls(name, help, labels, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        """buckets=None fetches/creates with the default boundaries and
        never conflicts; explicit buckets must match an existing family."""
        kw = {} if buckets is None else {"buckets": buckets}
        return self._get_or_make(Histogram, name, help, labels, **kw)

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def counters_with_prefix(self, prefix: str,
                             suffix: str = "_total") -> Dict[str, int]:
        """{middle: value} for unlabeled counters named
        <prefix><middle><suffix> — the shim behind the pre-registry
        accessors (`checkpoint_counters()`, `watchdog_counters()`)."""
        out: Dict[str, int] = {}
        for fam in self.families():
            if (isinstance(fam, Counter) and not fam.label_names
                    and fam.name.startswith(prefix)
                    and fam.name.endswith(suffix)):
                v = fam.value()
                if v:
                    out[fam.name[len(prefix):-len(suffix)]] = int(v)
        return out

    def reset_all(self, prefix: Optional[str] = None) -> None:
        """Zero every family's values (registrations survive, so cached
        handles stay live). prefix limits the reset to one family group."""
        for fam in self.families():
            if prefix is None or fam.name.startswith(prefix):
                fam.reset()

    def render(self) -> str:
        """Prometheus exposition text for every family, sorted by name."""
        return "".join(fam.render() for fam in self.families())


def render_labeled(
        members: List[Tuple[Tuple[Tuple[str, str], ...],
                            "MetricsRegistry"]]) -> str:
    """One exposition document over MANY registries, each contributing
    its samples with an (optionally empty) tuple of extra label pairs
    prepended — the general form behind `render_merged` and the fleet
    server's /metrics. Emitting one SINGLE # HELP/# TYPE header per
    family name across all members is the point: a server whose default
    registry already carries ff_serving_*/ff_kvpool_* families (a
    non-fleet batcher in the same process) and whose fleet replicas
    carry the same families replica-labeled must render ONE exposition,
    not two concatenated documents with duplicate TYPE headers.

    Same-name families across members must agree on kind, label schema,
    and (for histograms) bucket boundaries — a mismatch is a loud
    ValueError, never a silent sum of incompatible series. A family that
    already declares one of its member's stamp labels is rejected too:
    the stamp would be ambiguous."""
    # family name -> (prototype family, [(label pairs, family), ...])
    merged: Dict[str, Tuple[_Family,
                            List[Tuple[Tuple[Tuple[str, str], ...],
                                       _Family]]]] = {}
    for pairs, reg in members:
        for ln, _ in pairs:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid merge label name {ln!r}")
        for fam in reg.families():
            for ln, _ in pairs:
                if ln in fam.label_names:
                    raise ValueError(
                        f"metric {fam.name!r} already carries a {ln!r}"
                        f" label; merging under {ln!r} would be"
                        " ambiguous")
            proto_entry = merged.get(fam.name)
            if proto_entry is None:
                merged[fam.name] = (fam, [(pairs, fam)])
                continue
            proto = proto_entry[0]
            if (proto.kind != fam.kind
                    or proto.label_names != fam.label_names
                    or getattr(proto, "buckets", None)
                    != getattr(fam, "buckets", None)):
                raise ValueError(
                    f"metric-name collision on {fam.name!r}: registered as"
                    f" {proto.kind}{proto.label_names} and"
                    f" {fam.kind}{fam.label_names} in different"
                    " registries; refusing to merge")
            proto_entry[1].append((pairs, fam))
    out = []
    for name in sorted(merged):
        proto, fams = merged[name]
        out.append(f"# HELP {name} {escape_help(proto.help)}\n")
        out.append(f"# TYPE {name} {proto.kind}\n")
        for pairs, fam in fams:
            for line in fam._sample_lines(extra=pairs):
                out.append(line + "\n")
    return "".join(out)


def render_merged(registries: Dict[str, "MetricsRegistry"],
                  label: str = "replica") -> str:
    """One exposition document over MANY registries — the fleet /metrics
    path: each serving replica owns a private MetricsRegistry (so its
    ff_serving_*/ff_kvpool_* series never clobber a sibling's), and the
    merged render stamps every sample with a `label`="<key>" pair under a
    SINGLE # HELP/# TYPE header per family. Collision semantics are
    `render_labeled`'s."""
    if not _LABEL_RE.match(label):
        raise ValueError(f"invalid merge label name {label!r}")
    return render_labeled([(((label, key),), registries[key])
                           for key in sorted(registries)])


# -- the process-wide default registry ------------------------------------
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY


# -- exposition-format checking -------------------------------------------
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{(.*)\})?"                          # optional label block
    r" ([-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))"
    r"(?: [0-9]+)?$")                         # optional timestamp
# OpenMetrics exemplar suffix, split off a sample line before _SAMPLE_RE
# runs (the greedy label-block match must never see it)
_EXEMPLAR_RE = re.compile(
    r"^\{(.*)\} ([-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))"
    r"(?: [0-9.]+)?$")
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, c + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_exposition(text: str) -> Dict[str, Dict]:
    """Strict-enough parser for the exposition subset we emit. Returns
    {family name: {"type": ..., "help": ..., "samples":
    [(name, {label: value}, float)], "exemplars":
    [(name, {label: value}, {exemplar label: value}, float)]}}. Raises
    ValueError on any line that does not parse — the checker the CI
    observability job and the obs tests run over `/metrics` output."""
    families: Dict[str, Dict] = {}

    def fam(name: str) -> Dict:
        return families.setdefault(
            name, {"type": None, "help": None, "samples": [],
                   "exemplars": []})

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3 or not _NAME_RE.match(parts[2]):
                raise ValueError(f"line {lineno}: bad HELP: {line!r}")
            fam(parts[2])["help"] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if (len(parts) != 4 or not _NAME_RE.match(parts[2])
                    or parts[3] not in ("counter", "gauge", "histogram",
                                        "summary", "untyped")):
                raise ValueError(f"line {lineno}: bad TYPE: {line!r}")
            if families.get(parts[2], {}).get("type") is not None:
                raise ValueError(
                    f"line {lineno}: duplicate TYPE for {parts[2]}")
            fam(parts[2])["type"] = parts[3]
            continue
        if line.startswith("#"):
            continue  # comment
        exemplar = None
        if " # " in line:  # OpenMetrics exemplar suffix on a sample line
            head, _, ex_part = line.rpartition(" # ")
            em = _EXEMPLAR_RE.match(ex_part)
            if em:  # else: " # " inside a label value — leave the line be
                line = head
                ex_labels = {pm.group(1): _unescape_label_value(pm.group(2))
                             for pm in _LABEL_PAIR_RE.finditer(em.group(1))}
                exemplar = (ex_labels,
                            float(em.group(2).replace("Inf", "inf")
                                  .replace("NaN", "nan")))
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: unparseable sample: {line!r}")
        name, label_block, value = m.group(1), m.group(2), m.group(3)
        labels: Dict[str, str] = {}
        if label_block:
            consumed = 0
            for pm in _LABEL_PAIR_RE.finditer(label_block):
                labels[pm.group(1)] = _unescape_label_value(pm.group(2))
                consumed = pm.end()
                if (consumed < len(label_block)
                        and label_block[consumed] == ","):
                    consumed += 1
            if consumed != len(label_block):
                raise ValueError(
                    f"line {lineno}: bad label block: {label_block!r}")
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        entry = fam(base if base in families else name)
        entry["samples"].append(
            (name, labels, float(value.replace("Inf", "inf")
                                 .replace("NaN", "nan"))))
        if exemplar is not None:
            entry["exemplars"].append(
                (name, labels, exemplar[0], exemplar[1]))
    return families


def validate_exposition(text: str) -> Dict[str, Dict]:
    """parse_exposition + structural checks: every sample belongs to a
    family with a TYPE header, and histogram families carry their
    _bucket/_sum/_count series."""
    families = parse_exposition(text)
    for name, f in families.items():
        if f["samples"] and f["type"] is None:
            raise ValueError(f"samples for {name} without a # TYPE header")
        if f["type"] == "histogram":
            kinds = {n.rsplit("_", 1)[-1] for n, _, _ in f["samples"]
                     if n != name}
            if f["samples"] and not {"sum", "count"} <= kinds:
                raise ValueError(f"histogram {name} missing _sum/_count")
    return families


def iter_samples(text: str) -> Iterable[Tuple[str, Dict[str, str], float]]:
    for f in parse_exposition(text).values():
        yield from f["samples"]
