"""Simulator calibration: predicted vs measured cost, per op and per step.

The paper's central bet is that a profiling-based cost simulator can rank
parallelization strategies; this module measures how far the simulator's
predictions drift from reality on the current backend. Two levels:

 - STEP: the searched plan's predicted step cost
   (`SearchResult.predicted_step_us`, or an analytic re-simulation of the
   chosen strategies when no search ran) against the measured mean step
   wall time from `FFModel.step_stats`.
 - OP: the cost model's per-op forward estimate under each op's CHOSEN
   strategy against an on-device micro-benchmark of the same op
   (`search/simulator.OpCostCache` — the same measurement the measured-
   cost search mode uses), so a systematic bias is attributable to a
   specific op family.

The report renders as a table, serializes to JSON (the `profile` CLI's
calibration artifact), and publishes `ff_sim_step_calibration_ratio` —
measured/predicted, 1.0 = perfectly calibrated — on the registry.
"""
from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Dict, List, Optional

from .registry import REGISTRY

def _tier_families() -> Dict[str, str]:
    """op_type value -> kernel-tier family, DERIVED from the registry's
    OPTYPE_FAMILY so the two layers cannot drift (a new tier op family
    automatically accumulates residual evidence here)."""
    from ..kernels.registry import OPTYPE_FAMILY

    return {k.value: v for k, v in OPTYPE_FAMILY.items()}


# materialized at import: rows store op_type as its string value
KERNEL_TIER_FAMILIES = _tier_families()


def op_family_residuals(rows) -> Dict[str, float]:
    """Per-kernel-family residual: the MEDIAN measured/predicted ratio
    over a family's calibrated ops (median, not mean — one bad
    micro-measurement must not nominate a kernel). Only finite ratios
    count; families with no measurable op are absent. This is the
    evidence `refit` persists into the FittedProfile and the
    KernelRegistry selects fused kernels from."""
    by_fam: Dict[str, List[float]] = {}
    for r in rows:
        fam = KERNEL_TIER_FAMILIES.get(getattr(r, "op_type", None))
        if fam is None:
            continue
        ratio = r.ratio
        if math.isfinite(ratio):
            by_fam.setdefault(fam, []).append(ratio)
    out: Dict[str, float] = {}
    for fam, ratios in by_fam.items():
        ratios.sort()
        n = len(ratios)
        out[fam] = (ratios[n // 2] if n % 2
                    else 0.5 * (ratios[n // 2 - 1] + ratios[n // 2]))
    return out


@dataclasses.dataclass
class OpCalibration:
    op: str
    op_type: str
    strategy: str
    predicted_us: float
    measured_us: float  # NaN when the op is unmeasurable in isolation
    error: Optional[str] = None
    # compute-dtype class ("bf16"/"f32") the prediction priced against —
    # the refit layer fits a separate effective flop rate per class
    dtype: str = ""

    @property
    def ratio(self) -> float:
        """measured/predicted, or NaN whenever either side is degenerate
        (non-positive or non-finite) — a zero/negative measured time
        (clock resolution on trivially small ops) must never produce a 0,
        negative, or inf ratio in a report."""
        if not (self.predicted_us > 0 and math.isfinite(self.predicted_us)
                and self.measured_us > 0
                and math.isfinite(self.measured_us)):
            return float("nan")
        return self.measured_us / self.predicted_us

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["ratio"] = self.ratio
        return d


@dataclasses.dataclass
class CollectiveCalibration:
    """One measured collective: the obs.calibrate row type the explicit
    collective lowering emits (runtime/collectives.py via the
    collective-bench sweep) and the resharding executor's transfer
    rounds produce. `refit.fit_collective_coefficients` fits the
    per-tier link constants from these — measured collectives, not the
    step-level residual attribution the per-tier fit otherwise leans on.

    op: "allreduce" (a full strategy lowering), "psum" (one tier's ring
    phase in isolation — the per-tier fit's preferred evidence),
    "transfer"/"allgather" (resharding rounds). tier: the tier the
    traffic rides ("ici"/"dcn"/... on hierarchical machines, "mesh" on
    flat ones)."""

    op: str
    strategy: str
    tier: str
    bytes: float
    participants: int
    predicted_us: float
    measured_us: float
    dtype: str = "f32"

    @property
    def ratio(self) -> float:
        """measured/predicted — NaN when either side is degenerate, the
        same contract as OpCalibration.ratio."""
        if not (self.predicted_us > 0 and math.isfinite(self.predicted_us)
                and self.measured_us > 0
                and math.isfinite(self.measured_us)):
            return float("nan")
        return self.measured_us / self.predicted_us

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["ratio"] = self.ratio
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CollectiveCalibration":
        return cls(op=str(d["op"]), strategy=str(d["strategy"]),
                   tier=str(d["tier"]), bytes=float(d["bytes"]),
                   participants=int(d["participants"]),
                   predicted_us=float(d["predicted_us"]),
                   measured_us=float(d["measured_us"]),
                   dtype=str(d.get("dtype", "f32")))


@dataclasses.dataclass
class CalibrationReport:
    backend: str
    predicted_step_us: Optional[float]
    measured_step_us: Optional[float]
    measured_steps: int
    ops: List[OpCalibration]

    @property
    def step_ratio(self) -> float:
        """measured/predicted step cost; NaN (an 'uncalibrated' record)
        when either side is missing, non-positive, or non-finite — a run
        whose steps were too fast for the clock, or a model compiled
        without any cost prediction, yields a clean n/a, never a
        div-by-zero or an inf."""
        p, m = self.predicted_step_us, self.measured_step_us
        if (p is None or m is None or not math.isfinite(p)
                or not math.isfinite(m) or p <= 0 or m <= 0):
            return float("nan")
        return m / p

    def to_dict(self) -> Dict[str, Any]:
        return {
            "backend": self.backend,
            "predicted_step_us": self.predicted_step_us,
            "measured_step_us": self.measured_step_us,
            "measured_steps": self.measured_steps,
            "step_ratio": self.step_ratio,
            "ops": [o.to_dict() for o in self.ops],
            "kernel_candidates": self.kernel_candidates(),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def kernel_candidates(self) -> List[Dict[str, Any]]:
        """Ranked fused-kernel candidates: per kernel-tier family, the
        median residual (measured/predicted) weighted by the family's
        share of predicted step time — `score = max(0, residual - 1) *
        share`. The family at the top is where a fused kernel buys the
        most wall clock; `profile --kernel-report` renders this and the
        KernelRegistry auto-selects from the same residuals once a refit
        persists them (docs/kernels.md)."""
        residuals = op_family_residuals(self.ops)
        total_pred = sum(o.predicted_us for o in self.ops
                         if o.predicted_us > 0
                         and math.isfinite(o.predicted_us))
        # every tier family present in the graph is listed — one with no
        # measurable op shows residual NaN and score 0 rather than
        # disappearing (the reader should see it was considered)
        present = {fam for o in self.ops
                   for fam in [KERNEL_TIER_FAMILIES.get(o.op_type)]
                   if fam is not None}
        out: List[Dict[str, Any]] = []
        for fam in present:
            residual = residuals.get(fam, float("nan"))
            pred = sum(o.predicted_us for o in self.ops
                       if KERNEL_TIER_FAMILIES.get(o.op_type) == fam
                       and o.predicted_us > 0
                       and math.isfinite(o.predicted_us))
            share = pred / total_pred if total_pred > 0 else 0.0
            out.append({
                "family": fam,
                "residual": residual,
                "step_share": share,
                "score": (max(0.0, residual - 1.0) * share
                          if math.isfinite(residual) else 0.0),
                "ops": sum(
                    1 for o in self.ops
                    if KERNEL_TIER_FAMILIES.get(o.op_type) == fam),
            })
        out.sort(key=lambda c: (
            -c["score"],
            -(c["residual"] if math.isfinite(c["residual"]) else 0.0)))
        return out

    def format_kernel_report(self) -> str:
        cands = self.kernel_candidates()
        lines = [
            "kernel candidates (median calibration residual weighted by "
            "share of predicted step time; score>0 = fusion headroom)",
            f"  {'family':<16} {'residual':>9} {'step share':>11} "
            f"{'score':>8} {'ops':>5}",
        ]
        if not cands:
            lines.append("  (no kernel-tier op families measurable)")
        for c in cands:
            lines.append(
                f"  {c['family']:<16} {_r(c['residual']):>9} "
                f"{c['step_share']:>10.1%} {c['score']:>8.3f} "
                f"{c['ops']:>5}")
        return "\n".join(lines)

    def format(self) -> str:
        lines = [
            f"simulator calibration ({self.backend} backend; ratio = "
            "measured/predicted, 1.0 = perfectly calibrated)",
            f"  step: predicted={_us(self.predicted_step_us)} "
            f"measured={_us(self.measured_step_us)} "
            f"over {self.measured_steps} step(s) "
            f"ratio={_r(self.step_ratio)}",
            f"  {'op':<28} {'type':<20} {'strategy':<14} "
            f"{'pred us':>10} {'meas us':>10} {'ratio':>7}",
        ]
        for o in self.ops:
            if o.error:
                lines.append(
                    f"  {o.op:<28} {o.op_type:<20} {o.strategy:<14} "
                    f"{o.predicted_us:>10.1f} {'--':>10} {'--':>7}"
                    f"  {o.error}")
            else:
                lines.append(
                    f"  {o.op:<28} {o.op_type:<20} {o.strategy:<14} "
                    f"{o.predicted_us:>10.1f} {o.measured_us:>10.1f} "
                    f"{_r(o.ratio):>7}")
        return "\n".join(lines)


def _us(v: Optional[float]) -> str:
    return f"{v:.1f}us" if v else "n/a"


def _r(v: float) -> str:
    return f"{v:.2f}" if math.isfinite(v) else "n/a"


def predicted_step_us(model) -> Optional[float]:
    """The plan's predicted step cost: the search's own number when a
    search ran, otherwise an analytic re-simulation of the chosen (or
    default) strategies — so calibration works for plain data-parallel
    compiles too."""
    sr = model.search_result
    if sr is not None and getattr(sr, "predicted_step_us", None):
        return float(sr.predicted_step_us)
    if model.graph is None:
        return None
    from ..search.machine_model import make_machine_model
    from ..search.simulator import Simulator

    n_dev = max(1, model.config.total_devices)
    sim = Simulator(make_machine_model(model.config, n_dev), model.config)
    return float(sim.simulate(model.graph, model._op_strategies or {}))


def calibrate(model, warmup: int = 1, repeats: int = 3,
              max_ops: Optional[int] = None) -> CalibrationReport:
    """Build the predicted-vs-profiled report for a compiled model.

    Per-op measurement compiles each op as a micro-function over its real
    input shapes (OpCostCache), so on CPU the measured side reflects the
    host — the report states the backend to keep cross-backend numbers
    from being compared blindly."""
    import jax

    from ..ffconst import OpType
    from ..search.machine_model import make_machine_model
    from ..search.simulator import CostModel, OpCostCache, OpStrategy

    assert model.graph is not None, "compile() the model first"
    n_dev = max(1, model.config.total_devices)
    cost = CostModel(make_machine_model(model.config, n_dev), model.config)
    cache = OpCostCache(model.config, warmup=warmup, repeats=repeats)
    strategies = model._op_strategies or {}
    default = OpStrategy(dp=1, tp=1)

    rows: List[OpCalibration] = []
    for op in model.graph.topo_order():
        if op.op_type in (OpType.INPUT, OpType.WEIGHT, OpType.NOOP):
            continue
        if max_ops is not None and len(rows) >= max_ops:
            break
        s = strategies.get(op.guid, default)
        sdesc = f"dp={s.dp},tp={s.tp}" + (f",sp={s.sp}" if s.sp > 1 else "")
        pred = cost.forward_time_us(op, s)
        dtype = "bf16" if cost.op_dtype_bytes(op) <= 2 else "f32"
        try:
            meas = cache.measure_forward_us(op, s)
            rows.append(OpCalibration(op.name, op.op_type.value, sdesc,
                                      float(pred), float(meas),
                                      dtype=dtype))
        except Exception as e:  # unmeasurable ops (multi-output glue etc.)
            rows.append(OpCalibration(
                op.name, op.op_type.value, sdesc, float(pred),
                float("nan"), error=f"{type(e).__name__}: {e}",
                dtype=dtype))

    stats = getattr(model, "step_stats", None)
    measured_step = None
    n_steps = 0
    if stats is not None and len(stats):
        # median, not mean: the first recorded step carries the jit
        # compile and would swamp short calibration runs
        measured_step = stats.summary()["p50_step_ms"] * 1e3
        n_steps = len(stats)
        if not (measured_step > 0 and math.isfinite(measured_step)):
            # steps faster than the clock's resolution (trivial models on
            # CPU CI): an uncalibrated record, not a 0 that would blow up
            # downstream ratios
            measured_step = None
    report = CalibrationReport(
        backend=jax.default_backend(),
        predicted_step_us=predicted_step_us(model),
        measured_step_us=measured_step,
        measured_steps=n_steps,
        ops=rows,
    )
    if math.isfinite(report.step_ratio):
        REGISTRY.gauge(
            "ff_sim_step_calibration_ratio",
            "Measured/predicted step cost (1.0 = calibrated)",
        ).set(report.step_ratio)
    return report
