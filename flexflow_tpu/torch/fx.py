"""Serialize a torch.fx symbolic trace to the .ff interchange format.

reference parity: python/flexflow/torch/fx.py (torch_to_flexflow) +
torch/model.py torch_to_ff node translation. Our format is JSON-lines: one
record per fx node {name, op, target, args, kwargs, module} where `module`
captures the constructor config of call_module targets.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List

import numpy as np


def _module_spec(mod) -> Dict[str, Any]:
    import torch.nn as nn

    t = type(mod).__name__
    spec: Dict[str, Any] = {"type": t}
    if isinstance(mod, nn.Linear):
        spec.update(in_features=mod.in_features, out_features=mod.out_features,
                    bias=mod.bias is not None)
    elif isinstance(mod, nn.Conv2d):
        spec.update(
            in_channels=mod.in_channels, out_channels=mod.out_channels,
            kernel_size=list(mod.kernel_size), stride=list(mod.stride),
            padding=list(mod.padding) if not isinstance(mod.padding, str) else mod.padding,
            groups=mod.groups, bias=mod.bias is not None,
        )
    elif isinstance(mod, (nn.MaxPool2d, nn.AvgPool2d)):
        def pair(v):
            return list(v) if isinstance(v, (tuple, list)) else [v, v]
        spec.update(kernel_size=pair(mod.kernel_size),
                    stride=pair(mod.stride or mod.kernel_size),
                    padding=pair(mod.padding))
    elif isinstance(mod, nn.AdaptiveAvgPool2d):
        out = mod.output_size
        spec.update(output_size=list(out) if isinstance(out, (tuple, list)) else [out, out])
    elif isinstance(mod, (nn.BatchNorm2d, nn.BatchNorm1d)):
        spec.update(num_features=mod.num_features)
    elif isinstance(mod, nn.LayerNorm):
        spec.update(normalized_shape=list(mod.normalized_shape), eps=mod.eps,
                    elementwise_affine=mod.elementwise_affine)
    elif isinstance(mod, nn.Embedding):
        spec.update(num_embeddings=mod.num_embeddings, embedding_dim=mod.embedding_dim)
    elif isinstance(mod, nn.Dropout):
        spec.update(p=mod.p)
    elif isinstance(mod, nn.Softmax):
        spec.update(dim=mod.dim)
    elif isinstance(mod, nn.Flatten):
        spec.update(start_dim=mod.start_dim, end_dim=mod.end_dim)
    elif isinstance(mod, nn.MultiheadAttention):
        spec.update(embed_dim=mod.embed_dim, num_heads=mod.num_heads,
                    dropout=mod.dropout, batch_first=mod.batch_first)
    # parameterless activations etc. carry only their type name
    return spec


def _encode_arg(a) -> Any:
    import torch.fx as tfx

    if isinstance(a, tfx.Node):
        return {"node": a.name}
    if isinstance(a, slice):
        # bounds may themselves be traced nodes (size arithmetic)
        return {"slice": [_encode_arg(a.start), _encode_arg(a.stop),
                          _encode_arg(a.step)]}
    if isinstance(a, (list, tuple)):
        return [_encode_arg(x) for x in a]
    if isinstance(a, dict):
        return {k: _encode_arg(v) for k, v in a.items()}
    if a is None or isinstance(a, (bool, int, float, str)):
        return a
    import torch

    if isinstance(a, torch.dtype):
        return {"dtype": str(a)}
    return {"repr": repr(a)}


def trace_to_records(model, tracer_cls=None,
                     input_names=None) -> List[Dict[str, Any]]:
    """Symbolically trace a torch module into .ff records.

    HuggingFace models (transformers PreTrainedModel) go through
    transformers.utils.fx.symbolic_trace, which handles their dynamic
    control flow (reference: the HF tracing path of torch/model.py:
    2427-2444); input_names selects the traced signature (e.g.
    ["input_ids"])."""
    import torch.fx as tfx

    if tracer_cls is not None:
        graph = tracer_cls().trace(model)
        traced = tfx.GraphModule(model, graph)
    elif type(model).__module__.startswith("transformers."):
        from transformers.utils import fx as hf_fx

        traced = hf_fx.symbolic_trace(
            model, input_names=list(input_names) if input_names else None)
    else:
        traced = tfx.symbolic_trace(model)
    modules = dict(traced.named_modules())
    records = []
    for node in traced.graph.nodes:
        rec: Dict[str, Any] = {
            "name": node.name,
            "op": node.op,
            "target": node.target if isinstance(node.target, str) else getattr(
                node.target, "__name__", str(node.target)
            ),
            "args": _encode_arg(list(node.args)),
            "kwargs": _encode_arg(dict(node.kwargs)),
        }
        if node.op == "call_function":
            mod_name = getattr(node.target, "__module__", "") or ""
            rec["target_module"] = mod_name
        if node.op == "call_module":
            rec["module"] = _module_spec(modules[node.target])
        if node.op == "get_attr":
            # direct parameter/buffer access (reference:
            # torch/model.py:2427+): capture the tensor value so the
            # importer can materialize it as a constant (buffers) or a
            # trainable parameter
            val, trainable = _fetch_attr(traced, node.target)
            val = val.detach().cpu()
            import torch

            if val.dtype == torch.bfloat16:  # numpy has no bf16
                val = val.float()
            arr = val.numpy()
            rec["tensor"] = {
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "trainable": trainable,
            }
            if arr.size <= 65536:
                rec["tensor"]["data"] = arr.tolist()
            else:  # large params (tied embeddings etc.): raw bytes, not
                # a 25x-bloated Python list
                import base64

                rec["tensor"]["data_b64"] = base64.b64encode(
                    np.ascontiguousarray(arr).tobytes()).decode("ascii")
        records.append(rec)
    return records


def _fetch_attr(mod, target: str):
    """Resolve a dotted get_attr target; returns (tensor, trainable)."""
    import torch

    obj = mod
    for part in target.split("."):
        obj = getattr(obj, part)
    trainable = isinstance(obj, torch.nn.Parameter) and obj.requires_grad
    return obj, trainable


def torch_to_flexflow(model, filename: str, tracer_cls=None) -> str:
    """Trace `model` and write the .ff file (one JSON record per line)."""
    records = trace_to_records(model, tracer_cls=tracer_cls)
    with open(filename, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return filename


def load_ff_file(filename: str) -> List[Dict[str, Any]]:
    with open(filename) as f:
        return [json.loads(line) for line in f if line.strip()]
