"""flexflow_tpu.torch: PyTorch (torch.fx) frontend.

reference parity: python/flexflow/torch/ (SURVEY.md §2.6) —
fx.torch_to_flexflow(model, path) serializes a symbolic trace to a .ff file;
PyTorchModel(path_or_module).apply(ffmodel, inputs) replays the graph as
flexflow_tpu layer calls. Extension over the reference: optional weight
transfer from the torch module into the compiled FFModel.
"""
from . import fx
from .model import PyTorchModel

__all__ = ["fx", "PyTorchModel"]
