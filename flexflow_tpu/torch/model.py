"""Replay a torch.fx trace (.ff records) into flexflow_tpu layer calls.

reference parity: python/flexflow/torch/model.py:2408 (PyTorchModel.apply and
the per-op Node translation classes at model.py:43+). Design differs: one
dispatch table over serialized JSON records instead of a class per op.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

from ..core.tensor import Tensor
from ..ffconst import ActiMode, AggrMode, PoolType


class _Env(dict):
    """node name -> flexflow_tpu Tensor or plain python value."""


def _is_tensor(v) -> bool:
    return isinstance(v, Tensor)


class PyTorchModel:
    def __init__(self, model_or_path, tracer_cls=None, batch_size: Optional[int] = None):
        """model_or_path: a torch.nn.Module (traced on the fly) or a path to a
        .ff file written by fx.torch_to_flexflow."""
        from . import fx

        self._torch_module = None
        if isinstance(model_or_path, str):
            self.records = fx.load_ff_file(model_or_path)
        else:
            self._torch_module = model_or_path
            self.records = fx.trace_to_records(model_or_path, tracer_cls=tracer_cls)
        self.batch_size = batch_size

    # ------------------------------------------------------------------
    def apply(self, ffmodel, input_tensors: Sequence[Tensor]) -> List[Tensor]:
        env = _Env()
        inputs = list(input_tensors)
        outputs: List[Tensor] = []
        for rec in self.records:
            op = rec["op"]
            if op == "placeholder":
                env[rec["name"]] = inputs.pop(0)
            elif op == "call_module":
                env[rec["name"]] = self._call_module(ffmodel, rec, env)
            elif op == "call_function":
                env[rec["name"]] = self._call_function(ffmodel, rec, env)
            elif op == "call_method":
                env[rec["name"]] = self._call_method(ffmodel, rec, env)
            elif op == "get_attr":
                raise NotImplementedError(
                    f"get_attr node {rec['name']} ({rec['target']}): direct "
                    "parameter access is not supported by the importer"
                )
            elif op == "output":
                out = self._decode(rec["args"], env)[0]
                outputs = list(out) if isinstance(out, (list, tuple)) else [out]
        return outputs

    # ------------------------------------------------------------------
    def _decode(self, a, env):
        if isinstance(a, dict):
            if "node" in a:
                return env[a["node"]]
            if "dtype" in a or "repr" in a:
                return a
            return {k: self._decode(v, env) for k, v in a.items()}
        if isinstance(a, list):
            return [self._decode(x, env) for x in a]
        return a

    def _args(self, rec, env):
        return self._decode(rec["args"], env), self._decode(rec["kwargs"], env)

    # -- call_module ----------------------------------------------------
    def _call_module(self, fm, rec, env):
        spec = rec["module"]
        t = spec["type"]
        args, kwargs = self._args(rec, env)
        x = args[0] if args else None
        name = rec["name"]

        if t == "Linear":
            return fm.dense(x, spec["out_features"], ActiMode.AC_MODE_NONE,
                            spec["bias"], name=name)
        if t == "Conv2d":
            pad = spec["padding"]
            if pad == "same":
                pad = [spec["kernel_size"][0] // 2, spec["kernel_size"][1] // 2]
            elif pad == "valid":
                pad = [0, 0]
            return fm.conv2d(
                x, spec["out_channels"], spec["kernel_size"][0], spec["kernel_size"][1],
                spec["stride"][0], spec["stride"][1], pad[0], pad[1],
                groups=spec["groups"], use_bias=spec["bias"], name=name,
            )
        if t in ("MaxPool2d", "AvgPool2d"):
            pt = PoolType.POOL_MAX if t == "MaxPool2d" else PoolType.POOL_AVG
            return fm.pool2d(
                x, spec["kernel_size"][0], spec["kernel_size"][1],
                spec["stride"][0], spec["stride"][1],
                spec["padding"][0], spec["padding"][1], pool_type=pt, name=name,
            )
        if t == "AdaptiveAvgPool2d":
            oh, ow = spec["output_size"]
            _, _, h, w = x.dims
            sh, sw = h // oh, w // ow
            kh, kw = h - (oh - 1) * sh, w - (ow - 1) * sw
            return fm.pool2d(x, kh, kw, sh, sw, 0, 0,
                             pool_type=PoolType.POOL_AVG, name=name)
        if t in ("BatchNorm2d",):
            return fm.batch_norm(x, relu=False, name=name)
        if t == "LayerNorm":
            axes = list(range(-len(spec["normalized_shape"]), 0))
            return fm.layer_norm(x, axes, spec["elementwise_affine"],
                                 spec["eps"], name=name)
        if t == "Embedding":
            return fm.embedding(x, spec["num_embeddings"], spec["embedding_dim"],
                                AggrMode.AGGR_MODE_NONE, name=name)
        if t == "Dropout":
            return fm.dropout(x, spec["p"], name=name)
        if t == "Softmax":
            return fm.softmax(x, spec.get("dim", -1), name=name)
        if t == "Flatten":
            if spec.get("start_dim", 1) == 1 and spec.get("end_dim", -1) == -1:
                return fm.flat(x, name=name)
            return self._flatten_range(fm, x, spec["start_dim"], spec["end_dim"], name)
        if t == "MultiheadAttention":
            q, k, v = args[0], args[1], args[2]
            if not spec.get("batch_first", False):
                # torch default layout is (L, N, E); the core op is batch-first
                q = fm.transpose(q, [1, 0, 2], name=f"{name}_qT")
                k = fm.transpose(k, [1, 0, 2], name=f"{name}_kT")
                v = fm.transpose(v, [1, 0, 2], name=f"{name}_vT")
            out = fm.multihead_attention(q, k, v, spec["embed_dim"],
                                         spec["num_heads"], name=name)
            if not spec.get("batch_first", False):
                out = fm.transpose(out, [1, 0, 2], name=f"{name}_oT")
            return [out, None]
        unary = {
            "ReLU": fm.relu, "GELU": fm.gelu, "Sigmoid": fm.sigmoid,
            "Tanh": fm.tanh, "ELU": fm.elu, "Identity": fm.identity,
        }
        if t in unary:
            return unary[t](x, name=name)
        raise NotImplementedError(f"call_module type {t} not supported")

    # -- call_function --------------------------------------------------
    def _call_function(self, fm, rec, env):
        target = rec["target"]
        name = rec["name"]
        args, kwargs = self._args(rec, env)

        def binop(tensor_fn, scalar_fn, rev_scalar_fn=None, py_fn=None):
            """rev_scalar_fn(t, c) computes c OP t for non-commutative ops
            when the scalar is on the LEFT (e.g. 1.0 - x). Two plain numbers
            (traced size() arithmetic) fold in Python via py_fn."""
            a, b = args[0], args[1]
            if not _is_tensor(a) and not _is_tensor(b):
                return py_fn(a, b)
            if _is_tensor(a) and _is_tensor(b):
                return tensor_fn(a, b, name=name)
            if _is_tensor(a):
                return scalar_fn(a, float(b), name=name)
            if rev_scalar_fn is not None:
                return rev_scalar_fn(b, float(a))
            return scalar_fn(b, float(a), name=name)

        def rev_sub(t, c):  # c - t
            return fm.scalar_add(fm.scalar_multiply(t, -1.0, name=f"{name}_neg"),
                                 c, name=name)

        def rev_div(t, c):  # c / t
            return fm.scalar_multiply(fm.pow(t, -1.0, name=f"{name}_inv"),
                                      c, name=name)

        if target in ("add", "iadd"):
            return binop(fm.add, fm.scalar_add, py_fn=lambda a, b: a + b)
        if target in ("sub", "isub"):
            return binop(fm.subtract, fm.scalar_sub, rev_sub,
                         py_fn=lambda a, b: a - b)
        if target in ("mul", "imul"):
            return binop(fm.multiply, fm.scalar_multiply,
                         py_fn=lambda a, b: a * b)
        if target in ("truediv", "div"):
            return binop(fm.divide, fm.scalar_true_divide, rev_div,
                         py_fn=lambda a, b: a / b)
        if target == "floordiv":
            return binop(None, None, py_fn=lambda a, b: a // b)
        if target == "matmul" or target == "bmm":
            return fm.batch_matmul(args[0], args[1], name=name)
        if target == "cat":
            dim = kwargs.get("dim", args[1] if len(args) > 1 else 0)
            return fm.concat(args[0], dim, name=name)
        if target == "split":
            sizes = args[1]
            dim = kwargs.get("dim", args[2] if len(args) > 2 else 0)
            if isinstance(sizes, int):
                # torch: int is the chunk SIZE; fm.split: int is the COUNT
                total = args[0].dims[dim]
                sizes = [sizes] * (total // sizes) + (
                    [total % sizes] if total % sizes else []
                )
            return fm.split(args[0], sizes, dim, name=name)
        if target == "flatten":
            start = kwargs.get("start_dim", args[1] if len(args) > 1 else 0)
            if start == 1:
                return fm.flat(args[0], name=name)
            return self._flatten_range(fm, args[0], start, -1, name)
        if target == "relu":
            return fm.relu(args[0], name=name)
        if target == "gelu":
            return fm.gelu(args[0], name=name)
        if target == "sigmoid":
            return fm.sigmoid(args[0], name=name)
        if target == "tanh":
            return fm.tanh(args[0], name=name)
        if target == "softmax":
            dim = kwargs.get("dim", args[1] if len(args) > 1 else -1)
            return fm.softmax(args[0], dim, name=name)
        if target == "dropout":
            p = kwargs.get("p", args[1] if len(args) > 1 else 0.5)
            return fm.dropout(args[0], p, name=name)
        if target == "getitem":
            return args[0][args[1]]
        if target == "getattr":
            if args[1] == "shape":
                return args[0].dims
            raise NotImplementedError(f"getattr {args[1]}")
        if target in ("mean",):
            dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
            keep = kwargs.get("keepdim", False)
            return fm.mean(args[0], self._axes(args[0], dims), keep, name=name)
        if target in ("sum",):
            dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
            keep = kwargs.get("keepdim", False)
            return fm.reduce_sum(args[0], self._axes(args[0], dims), keep,
                                 name=name)
        if target == "transpose":
            return self._transpose(fm, args[0], args[1], args[2], name)
        if target == "permute":
            perm = args[1] if isinstance(args[1], list) else list(args[1:])
            return fm.transpose(args[0], perm, name=name)
        if target == "reshape":
            return self._reshape(fm, args[0], args[1], name)
        raise NotImplementedError(f"call_function {target} not supported")

    # -- call_method ----------------------------------------------------
    def _call_method(self, fm, rec, env):
        target = rec["target"]
        name = rec["name"]
        args, kwargs = self._args(rec, env)
        x = args[0]
        if target in ("view", "reshape"):
            shape = args[1] if isinstance(args[1], list) else list(args[1:])
            return self._reshape(fm, x, shape, name)
        if target == "permute":
            perm = args[1] if isinstance(args[1], list) else list(args[1:])
            return fm.transpose(x, perm, name=name)
        if target == "transpose":
            return self._transpose(fm, x, args[1], args[2], name)
        if target == "flatten":
            start = args[1] if len(args) > 1 else 0
            if start == 1:
                return fm.flat(x, name=name)
            return self._flatten_range(fm, x, start, -1, name)
        if target == "contiguous":
            return x
        if target == "size":
            return x.dims if len(args) == 1 else x.dims[args[1]]
        if target == "mean":
            dims = args[1] if len(args) > 1 else kwargs.get("dim")
            keep = kwargs.get("keepdim", False)
            return fm.mean(x, self._axes(x, dims), keep, name=name)
        if target == "squeeze":
            dims = list(x.dims)
            if len(args) > 1:
                d = args[1]
                if dims[d] != 1:
                    return x  # torch: squeezing a non-1 dim is a no-op
                dims.pop(d)
            else:
                dims = [s for s in dims if s != 1]
            return fm.reshape(x, dims, name=name)
        if target == "unsqueeze":
            if len(args) < 2:
                raise NotImplementedError("unsqueeze requires a dim argument")
            dims = list(x.dims)
            d = args[1]
            dims.insert(d if d >= 0 else len(dims) + d + 1, 1)
            return fm.reshape(x, dims, name=name)
        if target == "softmax":
            return fm.softmax(x, args[1] if len(args) > 1 else -1, name=name)
        raise NotImplementedError(f"call_method {target} not supported")

    # -- helpers --------------------------------------------------------
    @staticmethod
    def _axes(x, dims):
        """torch dim=None means reduce over ALL axes."""
        if dims is None:
            return list(range(len(x.dims)))
        return dims if isinstance(dims, list) else [dims]

    def _reshape(self, fm, x, shape, name):
        shape = list(shape)
        total = math.prod(x.dims)
        if -1 in shape:
            known = math.prod(d for d in shape if d != -1)
            shape[shape.index(-1)] = total // known
        return fm.reshape(x, shape, name=name)

    def _transpose(self, fm, x, d0, d1, name):
        perm = list(range(len(x.dims)))
        perm[d0], perm[d1] = perm[d1], perm[d0]
        return fm.transpose(x, perm, name=name)

    def _flatten_range(self, fm, x, start, end, name):
        dims = list(x.dims)
        n = len(dims)
        start %= n
        end %= n
        merged = math.prod(dims[start:end + 1])
        return fm.reshape(x, dims[:start] + [merged] + dims[end + 1:], name=name)

    # ------------------------------------------------------------------
    def transfer_weights(self, ffmodel) -> int:
        """Copy weights from the traced torch module into the compiled
        FFModel's params (extension; the reference re-initializes). Returns
        the number of tensors copied."""
        if self._torch_module is None:
            raise ValueError("weight transfer needs a live torch module")
        import jax.numpy as jnp
        import torch.nn as nn

        modules = dict(self._torch_module.named_modules())
        # fx node target -> node name happens via records
        copied = 0
        for rec in self.records:
            if rec["op"] != "call_module":
                continue
            name = rec["name"]
            if name not in (ffmodel.params or {}):
                continue
            mod = modules[rec["target"]]
            slot = ffmodel.params[name]

            def put(key, arr):
                nonlocal copied
                slot[key] = jnp.asarray(arr.detach().cpu().numpy()).astype(
                    slot[key].dtype
                )
                copied += 1

            if isinstance(mod, nn.Linear):
                put("kernel", mod.weight.T)
                if mod.bias is not None:
                    put("bias", mod.bias)
            elif isinstance(mod, nn.Conv2d):
                put("kernel", mod.weight)  # torch OIHW == ours
                if mod.bias is not None:
                    put("bias", mod.bias)
            elif isinstance(mod, nn.Embedding):
                put("weight", mod.weight)
            elif isinstance(mod, nn.LayerNorm) and mod.elementwise_affine:
                put("gamma", mod.weight)
                put("beta", mod.bias)
            elif isinstance(mod, nn.MultiheadAttention):
                e = mod.embed_dim
                h = mod.num_heads
                hd = e // h
                if mod.in_proj_weight is not None:
                    wq, wk, wv = mod.in_proj_weight.chunk(3, dim=0)
                else:
                    wq, wk, wv = (mod.q_proj_weight, mod.k_proj_weight,
                                  mod.v_proj_weight)
                # torch proj weight is (E_out, E_in); ours is (E_in, h, hd)
                put("wq", wq.T.reshape(e, h, hd))
                put("wk", wk.T.reshape(e, h, hd))
                put("wv", wv.T.reshape(e, h, hd))
                # out_proj (E, E) -> (h, hd, E)
                put("wo", mod.out_proj.weight.T.reshape(h, hd, e))
                if mod.in_proj_bias is not None and "bq" in slot:
                    bq, bk, bv = mod.in_proj_bias.chunk(3, dim=0)
                    put("bq", bq.reshape(h, hd))
                    put("bk", bk.reshape(h, hd))
                    put("bv", bv.reshape(h, hd))
                    put("bo", mod.out_proj.bias)
        return copied
