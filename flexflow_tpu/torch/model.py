"""Replay a torch.fx trace (.ff records) into flexflow_tpu layer calls.

reference parity: python/flexflow/torch/model.py:2408 (PyTorchModel.apply and
the per-op Node translation classes at model.py:43+). Design differs: one
dispatch table over serialized JSON records instead of a class per op.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..ffconst import ActiMode, AggrMode, PoolType


class _Env(dict):
    """node name -> flexflow_tpu Tensor or plain python value."""


def _is_tensor(v) -> bool:
    return isinstance(v, Tensor)


class _Const:
    """A concrete value flowing through the import — get_attr
    parameters/buffers and trace-time mask/position arithmetic. Folded
    eagerly with numpy; materialized into the graph (create_constant) only
    where a real tensor op consumes it. The materialized tensor is stored on
    the object itself (not an id()-keyed cache — transient ids get reused),
    so a parameter read once but consumed at several sites stays ONE weight.
    source_target: the originating get_attr target, for weight transfer."""

    def __init__(self, value, trainable: bool = False,
                 source_target: Optional[str] = None):
        self.value = np.asarray(value)
        self.trainable = trainable
        self.source_target = source_target
        self._tensor = None  # set by _materialize

    def __repr__(self):
        return f"_Const{self.value.shape}"


_TORCH_NP_DTYPES = {
    "torch.float32": np.float32, "torch.float": np.float32,
    "torch.float64": np.float64, "torch.float16": np.float16,
    "torch.bfloat16": np.float32,  # folded math runs f32; cast at materialize
    "torch.int64": np.int64, "torch.long": np.int64,
    "torch.int32": np.int32, "torch.int": np.int32,
    "torch.bool": np.bool_, "torch.uint8": np.uint8,
}


def _np_dtype(d, default=np.float32):
    if d is None:
        return default
    if isinstance(d, dict):
        d = d.get("dtype")
    return _TORCH_NP_DTYPES.get(str(d), default)


def _npv(v):
    """Unwrap to a numpy-compatible value (non-Tensor args only)."""
    return v.value if isinstance(v, _Const) else v


def _foldable(v) -> bool:
    """True when v (possibly nested) contains no graph Tensor."""
    if isinstance(v, Tensor):
        return False
    if isinstance(v, (list, tuple)):
        return all(_foldable(x) for x in v)
    if isinstance(v, dict):
        return all(_foldable(x) for x in v.values())
    return True


def _fold(target: str, args, kwargs):
    """Evaluate trace-time tensor math (masks, position ids, size
    arithmetic) eagerly with numpy. Returns NotImplemented when the target
    is not a known fold. Folds run under errstate(ignore): traced models
    legitimately build masks via log(0) -> -inf and cast +-inf sentinels
    (HF attention masks), and a RuntimeWarning here is trace noise — or,
    under -W error, a spurious fold failure."""
    a = [_npv(x) for x in args]
    k = {key: _npv(v) for key, v in kwargs.items()}

    def wrap(v):
        return _Const(v) if isinstance(v, np.ndarray) else v

    def shape_args(rest):
        if len(rest) == 1 and isinstance(rest[0], (list, tuple)):
            return tuple(rest[0])
        return tuple(rest)

    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        # unknown targets and fold failures resolve to NotImplemented
        # inside the dispatch
        return _fold_dispatch(target, a, k, args, kwargs, wrap, shape_args)


def _fold_dispatch(target, a, k, args, kwargs, wrap, shape_args):
    try:
        if target in ("add", "iadd"):
            return wrap(a[0] + a[1])
        if target in ("sub", "isub"):
            return wrap(a[0] - a[1])
        if target == "rsub":  # torch.rsub(input, other) = other - input
            return wrap(a[1] - a[0])
        if target in ("mul", "imul"):
            return wrap(a[0] * a[1])
        if target in ("truediv", "div"):
            return wrap(a[0] / a[1])
        if target == "floordiv":
            return wrap(a[0] // a[1])
        if target == "neg":
            return wrap(-a[0])
        if target == "abs":
            return wrap(np.abs(a[0]))
        if target in ("pow",):
            return wrap(np.power(a[0], a[1]))
        if target == "rsqrt":
            return wrap(1.0 / np.sqrt(a[0]))
        if target == "sqrt":
            return wrap(np.sqrt(a[0]))
        if target == "log":
            return wrap(np.log(a[0]))
        if target in ("eq", "ne", "gt", "lt", "ge", "le"):
            return wrap(getattr(np, {"eq": "equal", "ne": "not_equal",
                                     "gt": "greater", "lt": "less",
                                     "ge": "greater_equal",
                                     "le": "less_equal"}[target])(a[0], a[1]))
        if target in ("min", "max"):
            if len(a) > 1 and isinstance(a[1], (int, np.integer)) \
                    and np.asarray(a[0]).ndim > 0:
                # torch dim-reduction form: returns (values, indices)
                dim = int(a[1])
                red = np.min if target == "min" else np.max
                arg = np.argmin if target == "min" else np.argmax
                return (wrap(red(a[0], axis=dim)), wrap(arg(a[0], axis=dim)))
            if len(a) > 1:
                fn = np.minimum if target == "min" else np.maximum
                return wrap(fn(a[0], a[1]))
            return wrap((np.min if target == "min" else np.max)(a[0]))
        if target == "minimum":
            return wrap(np.minimum(a[0], a[1]))
        if target == "maximum":
            return wrap(np.maximum(a[0], a[1]))
        if target == "where":
            return wrap(np.where(a[0], a[1], a[2]))
        if target == "triu":
            return wrap(np.triu(a[0], k.get("diagonal", a[1] if len(a) > 1 else 0)))
        if target == "cumsum":
            return wrap(np.cumsum(a[0], axis=k.get("dim", a[1] if len(a) > 1 else None)))
        if target == "arange":
            return wrap(np.arange(*a, dtype=_np_dtype(k.get("dtype"), np.int64)))
        if target == "ones":
            return wrap(np.ones(shape_args(a), dtype=_np_dtype(k.get("dtype"))))
        if target == "zeros":
            return wrap(np.zeros(shape_args(a), dtype=_np_dtype(k.get("dtype"))))
        if target == "full":
            # fill may be positional or the fill_value kwarg (the HF T5/mt5
            # causal-mask trace passes it by keyword, with token-dict
            # dtype/device kwargs from tensor introspection)
            fill = a[1] if len(a) > 1 else k["fill_value"]
            return wrap(np.full(shape_args([a[0]]), fill,
                                dtype=_np_dtype(k.get("dtype"))))
        if target == "full_like":
            return wrap(np.full_like(a[0], a[1] if len(a) > 1
                                     else k["fill_value"]))
        if target == "zeros_like":
            return wrap(np.zeros_like(a[0]))
        if target == "ones_like":
            return wrap(np.ones_like(a[0]))
        if target == "tensor":
            return wrap(np.asarray(a[0]))
        if target == "finfo":
            return np.finfo(_np_dtype(args[0] if args else None))
        if target == "getitem":
            idx = args[1]
            if isinstance(idx, list):
                idx = tuple(x if isinstance(x, (slice, int)) else _npv(x)
                            for x in idx)
            return wrap(a[0][idx])
        if target == "setitem":
            # trace-time mask surgery (e.g. the T5/mt5 causal-mask window
            # writes). Python never rebinds on __setitem__, so downstream
            # nodes keep referencing the ORIGINAL tensor node — mutating
            # the stored array in place (the same object in env via
            # _Const.value) serves both it and the setitem node, matching
            # eager/fx-Interpreter semantics.
            idx = args[1]
            if isinstance(idx, list):
                idx = tuple(x if isinstance(x, (slice, int)) else _npv(x)
                            for x in idx)
            arr = a[0]
            if not (isinstance(arr, np.ndarray) and arr.flags.writeable):
                # non-writeable source (e.g. a broadcast_to fold): replace
                # the value INSIDE the original holder so downstream
                # references to the source node keep aliasing the mutation
                if not isinstance(args[0], _Const):
                    raise ValueError(
                        "setitem on a non-writeable trace-time array with no "
                        "value holder — in-place aliasing cannot be preserved")
                arr = np.array(arr)
                args[0].value = arr
            arr[idx] = a[2]
            return wrap(arr)
        if target == "getattr":
            return wrap(getattr(a[0], args[1]))
        if target in ("to", "type_as"):
            dt = _np_dtype(args[1] if len(args) > 1 else k.get("dtype"),
                           default=None)
            return wrap(a[0].astype(dt) if dt is not None else a[0])
        if target in ("float",):
            return wrap(np.asarray(a[0], np.float32))
        if target in ("long", "int"):
            return wrap(np.asarray(a[0], np.int64))
        if target == "bool":
            return wrap(np.asarray(a[0], np.bool_))
        if target == "expand":
            sizes = shape_args(a[1:])
            src = np.asarray(a[0])
            tgt = [s if s != -1 else src.shape[i]
                   for i, s in enumerate(sizes)]
            return wrap(np.broadcast_to(src, tuple(tgt)).copy())
        if target == "masked_fill":
            out = np.array(a[0], dtype=np.float32 if not np.issubdtype(
                np.asarray(a[0]).dtype, np.floating) else None)
            out[np.asarray(a[1], bool)] = a[2]
            return wrap(out)
        if target in ("unsqueeze",):
            return wrap(np.expand_dims(a[0], a[1]))
        if target in ("squeeze",):
            return wrap(np.squeeze(a[0], a[1] if len(a) > 1 else None))
        if target in ("view", "reshape"):
            shape = shape_args(a[1:])
            return wrap(np.reshape(a[0], shape))
        if target in ("contiguous", "clone", "detach"):
            return wrap(np.asarray(a[0]))
        if target == "size":
            return (list(np.asarray(a[0]).shape) if len(a) == 1
                    else np.asarray(a[0]).shape[a[1]])
        if target == "dim":
            return np.asarray(a[0]).ndim
        if target == "transpose":
            arr = np.asarray(a[0])
            perm = list(range(arr.ndim))
            perm[a[1]], perm[a[2]] = perm[a[2]], perm[a[1]]
            return wrap(arr.transpose(perm))
        if target == "permute":
            perm = shape_args(a[1:])
            return wrap(np.asarray(a[0]).transpose(perm))
    except Exception:
        return NotImplemented
    return NotImplemented


class PyTorchModel:
    def __init__(self, model_or_path, tracer_cls=None,
                 batch_size: Optional[int] = None, input_names=None):
        """model_or_path: a torch.nn.Module (traced on the fly; HuggingFace
        models route through transformers' fx tracer — pass input_names) or
        a path to a .ff file written by fx.torch_to_flexflow."""
        from . import fx

        self._torch_module = None
        if isinstance(model_or_path, str):
            self.records = fx.load_ff_file(model_or_path)
        else:
            self._torch_module = model_or_path
            self.records = fx.trace_to_records(
                model_or_path, tracer_cls=tracer_cls, input_names=input_names)
        self.batch_size = batch_size

    # ------------------------------------------------------------------
    def apply(self, ffmodel, input_tensors: Sequence[Tensor]) -> List[Tensor]:
        env = _Env()
        # get_attr target -> materialized constant-op name (weight transfer)
        self._attr_op_names: Dict[str, str] = {}
        inputs = list(input_tensors)
        outputs: List[Tensor] = []
        for rec in self.records:
            op = rec["op"]
            if op == "placeholder":
                env[rec["name"]] = inputs.pop(0)
            elif op == "call_module":
                env[rec["name"]] = self._call_module(ffmodel, rec, env)
            elif op == "call_function":
                env[rec["name"]] = self._call_function(ffmodel, rec, env)
            elif op == "call_method":
                env[rec["name"]] = self._call_method(ffmodel, rec, env)
            elif op == "get_attr":
                t = rec.get("tensor")
                if t is None:
                    raise NotImplementedError(
                        f"get_attr node {rec['name']} ({rec['target']}): the "
                        ".ff file predates get_attr capture — re-trace it"
                    )
                if "data_b64" in t:
                    import base64

                    val = np.frombuffer(
                        base64.b64decode(t["data_b64"]),
                        dtype=np.dtype(t["dtype"]),
                    ).reshape(t["shape"]).copy()
                else:
                    val = np.array(t["data"], dtype=np.dtype(t["dtype"]))
                env[rec["name"]] = _Const(
                    val, trainable=t.get("trainable", False),
                    source_target=rec["target"])
            elif op == "output":
                out = self._decode(rec["args"], env)[0]
                outputs = list(out) if isinstance(out, (list, tuple)) else [out]
        return outputs

    # ------------------------------------------------------------------
    def _decode(self, a, env):
        if isinstance(a, dict):
            if "node" in a:
                return env[a["node"]]
            if "slice" in a:
                return slice(*[self._decode(x, env) for x in a["slice"]])
            # token leaves are exactly {"dtype": str} / {"repr": str}
            # (fx._encode_arg); a kwargs dict merely CONTAINING a
            # dtype/repr key must still recurse so node refs resolve
            if len(a) == 1 and ("dtype" in a or "repr" in a):
                return a
            return {k: self._decode(v, env) for k, v in a.items()}
        if isinstance(a, list):
            return [self._decode(x, env) for x in a]
        return a

    def _materialize(self, fm, v, name: str):
        """Turn a _Const into a graph tensor where an op needs one (cached
        on the _Const itself, see _Const docstring)."""
        if isinstance(v, _Const):
            if v._tensor is not None:
                return v._tensor
            val = v.value
            if val.dtype == np.int64:  # jax default x64 is off
                val = val.astype(np.int32)
            if val.dtype == np.float64:
                val = val.astype(np.float32)
            t = fm.create_constant(val, trainable=v.trainable,
                                   name=f"{name}_const")
            v._tensor = t
            if v.source_target is not None:
                self._attr_op_names[v.source_target] = t.owner_op.name
            return t
        return v

    def _args(self, rec, env):
        return self._decode(rec["args"], env), self._decode(rec["kwargs"], env)

    # -- call_module ----------------------------------------------------
    def _call_module(self, fm, rec, env):
        spec = rec["module"]
        t = spec["type"]
        args, kwargs = self._args(rec, env)
        name = rec["name"]
        # modules consume graph tensors: materialize folded constants (e.g.
        # the position-id buffer feeding an Embedding)
        args = [self._materialize(fm, a, f"{name}_in{i}")
                for i, a in enumerate(args)]
        x = args[0] if args else None

        if t == "Linear":
            return fm.dense(x, spec["out_features"], ActiMode.AC_MODE_NONE,
                            spec["bias"], name=name)
        if t == "Conv2d":
            pad = spec["padding"]
            if pad == "same":
                pad = [spec["kernel_size"][0] // 2, spec["kernel_size"][1] // 2]
            elif pad == "valid":
                pad = [0, 0]
            return fm.conv2d(
                x, spec["out_channels"], spec["kernel_size"][0], spec["kernel_size"][1],
                spec["stride"][0], spec["stride"][1], pad[0], pad[1],
                groups=spec["groups"], use_bias=spec["bias"], name=name,
            )
        if t in ("MaxPool2d", "AvgPool2d"):
            pt = PoolType.POOL_MAX if t == "MaxPool2d" else PoolType.POOL_AVG
            return fm.pool2d(
                x, spec["kernel_size"][0], spec["kernel_size"][1],
                spec["stride"][0], spec["stride"][1],
                spec["padding"][0], spec["padding"][1], pool_type=pt, name=name,
            )
        if t == "AdaptiveAvgPool2d":
            oh, ow = spec["output_size"]
            _, _, h, w = x.dims
            sh, sw = h // oh, w // ow
            kh, kw = h - (oh - 1) * sh, w - (ow - 1) * sw
            return fm.pool2d(x, kh, kw, sh, sw, 0, 0,
                             pool_type=PoolType.POOL_AVG, name=name)
        if t in ("BatchNorm2d",):
            return fm.batch_norm(x, relu=False, name=name)
        if t == "LayerNorm":
            axes = list(range(-len(spec["normalized_shape"]), 0))
            return fm.layer_norm(x, axes, spec["elementwise_affine"],
                                 spec["eps"], name=name)
        if t == "Embedding":
            return fm.embedding(x, spec["num_embeddings"], spec["embedding_dim"],
                                AggrMode.AGGR_MODE_NONE, name=name)
        if t == "Dropout":
            return fm.dropout(x, spec["p"], name=name)
        if t == "Softmax":
            return fm.softmax(x, spec.get("dim", -1), name=name)
        if t == "Flatten":
            if spec.get("start_dim", 1) == 1 and spec.get("end_dim", -1) == -1:
                return fm.flat(x, name=name)
            return self._flatten_range(fm, x, spec["start_dim"], spec["end_dim"], name)
        if t == "MultiheadAttention":
            q, k, v = args[0], args[1], args[2]
            if not spec.get("batch_first", False):
                # torch default layout is (L, N, E); the core op is batch-first
                q = fm.transpose(q, [1, 0, 2], name=f"{name}_qT")
                k = fm.transpose(k, [1, 0, 2], name=f"{name}_kT")
                v = fm.transpose(v, [1, 0, 2], name=f"{name}_vT")
            out = fm.multihead_attention(q, k, v, spec["embed_dim"],
                                         spec["num_heads"], name=name)
            if not spec.get("batch_first", False):
                out = fm.transpose(out, [1, 0, 2], name=f"{name}_oT")
            return [out, None]
        unary = {
            "ReLU": fm.relu, "GELU": fm.gelu, "Sigmoid": fm.sigmoid,
            "Tanh": fm.tanh, "ELU": fm.elu, "Identity": fm.identity,
        }
        if t in unary:
            return unary[t](x, name=name)
        raise NotImplementedError(f"call_module type {t} not supported")

    # -- call_function --------------------------------------------------
    def _call_function(self, fm, rec, env):
        target = rec["target"]
        name = rec["name"]
        args, kwargs = self._args(rec, env)

        # trace-time math on concrete values (masks, position ids, sizes)
        # folds eagerly; only ops touching graph tensors build graph nodes
        if _foldable(args) and _foldable(kwargs):
            folded = _fold(target, args, kwargs)
            if folded is not NotImplemented:
                return folded

        def binop(tensor_fn, scalar_fn, rev_scalar_fn=None, py_fn=None):
            """rev_scalar_fn(t, c) computes c OP t for non-commutative ops
            when the scalar is on the LEFT (e.g. 1.0 - x). Two plain numbers
            (traced size() arithmetic) fold in Python via py_fn. _Const
            operands scalarize when 0-d, else materialize as constants."""
            a, b = args[0], args[1]
            if isinstance(a, _Const):
                a = (float(a.value) if a.value.ndim == 0
                     else self._materialize(fm, a, name))
            if isinstance(b, _Const):
                b = (float(b.value) if b.value.ndim == 0
                     else self._materialize(fm, b, name))
            if not _is_tensor(a) and not _is_tensor(b):
                return py_fn(a, b)
            if _is_tensor(a) and _is_tensor(b):
                return tensor_fn(a, b, name=name)
            if _is_tensor(a):
                return scalar_fn(a, float(b), name=name)
            if rev_scalar_fn is not None:
                return rev_scalar_fn(b, float(a))
            return scalar_fn(b, float(a), name=name)

        def rev_sub(t, c):  # c - t
            return fm.scalar_add(fm.scalar_multiply(t, -1.0, name=f"{name}_neg"),
                                 c, name=name)

        def rev_div(t, c):  # c / t
            return fm.scalar_multiply(fm.pow(t, -1.0, name=f"{name}_inv"),
                                      c, name=name)

        if target in ("add", "iadd"):
            return binop(fm.add, fm.scalar_add, py_fn=lambda a, b: a + b)
        if target in ("sub", "isub"):
            return binop(fm.subtract, fm.scalar_sub, rev_sub,
                         py_fn=lambda a, b: a - b)
        if target in ("mul", "imul"):
            return binop(fm.multiply, fm.scalar_multiply,
                         py_fn=lambda a, b: a * b)
        if target in ("truediv", "div"):
            return binop(fm.divide, fm.scalar_true_divide, rev_div,
                         py_fn=lambda a, b: a / b)
        if target == "floordiv":
            return binop(None, None, py_fn=lambda a, b: a // b)
        if target == "matmul" or target == "bmm":
            return fm.batch_matmul(args[0], args[1], name=name)
        if target == "cat":
            dim = kwargs.get("dim", args[1] if len(args) > 1 else 0)
            return fm.concat(args[0], dim, name=name)
        if target == "split":
            sizes = args[1]
            dim = kwargs.get("dim", args[2] if len(args) > 2 else 0)
            if isinstance(sizes, int):
                # torch: int is the chunk SIZE; fm.split: int is the COUNT
                total = args[0].dims[dim]
                sizes = [sizes] * (total // sizes) + (
                    [total % sizes] if total % sizes else []
                )
            return fm.split(args[0], sizes, dim, name=name)
        if target == "flatten":
            start = kwargs.get("start_dim", args[1] if len(args) > 1 else 0)
            if start == 1:
                return fm.flat(args[0], name=name)
            return self._flatten_range(fm, args[0], start, -1, name)
        if target == "relu":
            return fm.relu(args[0], name=name)
        if target == "gelu":
            return fm.gelu(args[0], name=name)
        if target == "sigmoid":
            return fm.sigmoid(args[0], name=name)
        if target == "tanh":
            return fm.tanh(args[0], name=name)
        if target == "softmax":
            dim = kwargs.get("dim", args[1] if len(args) > 1 else -1)
            return fm.softmax(args[0], dim, name=name)
        if target == "dropout":
            p = kwargs.get("p", args[1] if len(args) > 1 else 0.5)
            return fm.dropout(args[0], p, name=name)
        if target == "getitem":
            if _is_tensor(args[0]):
                return self._tensor_getitem(fm, args[0], args[1], name)
            return args[0][args[1]]
        if target == "getattr":
            if args[1] == "shape":
                return args[0].dims
            if args[1] in ("device", "dtype"):
                # trace-time placement/dtype introspection: a token value —
                # consumed only by folded torch.* factory calls
                return {"repr": f"tensor.{args[1]}"}
            raise NotImplementedError(f"getattr {args[1]}")
        if target in ("mean",):
            dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
            keep = kwargs.get("keepdim", False)
            return fm.mean(args[0], self._axes(args[0], dims), keep, name=name)
        if target in ("sum",):
            dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
            keep = kwargs.get("keepdim", False)
            return fm.reduce_sum(args[0], self._axes(args[0], dims), keep,
                                 name=name)
        if target == "transpose":
            return self._transpose(fm, args[0], args[1], args[2], name)
        if target == "permute":
            perm = args[1] if isinstance(args[1], list) else list(args[1:])
            return fm.transpose(args[0], perm, name=name)
        if target == "reshape":
            return self._reshape(fm, args[0], args[1], name)
        if target == "scaled_dot_product_attention":
            return self._sdpa(fm, args, kwargs, name)
        if target == "rsqrt":
            return fm.rsqrt(args[0], name=name)
        if target == "pow":
            return fm.pow(args[0], float(args[1]), name=name)
        if target == "exp":
            return fm.exp(args[0], name=name)
        if target in ("to", "type_as", "float", "contiguous", "clone",
                      "detach"):
            # graph tensors carry their dtype through lowering; trace-time
            # dtype juggling is a no-op here
            return args[0]
        raise NotImplementedError(f"call_function {target} not supported")

    def _sdpa(self, fm, args, kwargs, name):
        """torch.nn.functional.scaled_dot_product_attention on [B, H, L, D]
        tensors, built from batch_matmul/softmax (the F.sdpa path HF BERT
        traces to)."""
        q, k, v = args[0], args[1], args[2]
        # positional signature: (q, k, v, attn_mask, dropout_p, is_causal)
        mask = kwargs.get("attn_mask", args[3] if len(args) > 3 else None)
        dropout_p = kwargs.get("dropout_p",
                               args[4] if len(args) > 4 else 0.0)
        is_causal = kwargs.get("is_causal",
                               args[5] if len(args) > 5 else False)
        if dropout_p:
            raise NotImplementedError("sdpa dropout_p not supported")
        d = q.dims[-1]
        lq, lk = q.dims[-2], k.dims[-2]
        add_mask = None
        if isinstance(mask, _Const):
            mv = mask.value
            if mv.dtype == np.bool_:
                mv = np.where(mv, 0.0, -1e9).astype(np.float32)
            if np.any(mv != 0.0):
                add_mask = mv.astype(np.float32)
            mask = None
        elif mask is not None:
            raise NotImplementedError("sdpa with a traced-tensor mask")
        if is_causal:
            causal = np.triu(np.full((lq, lk), -1e9, np.float32), 1)
            add_mask = causal if add_mask is None else add_mask + causal
        kt = fm.transpose(k, [0, 1, 3, 2], name=f"{name}_kT")
        s = fm.batch_matmul(q, kt, name=f"{name}_qk")
        s = fm.scalar_multiply(s, 1.0 / math.sqrt(d), name=f"{name}_scale")
        if add_mask is not None:
            # natural broadcast shape — the elementwise add broadcasts
            s = fm.add(s, self._materialize(fm, _Const(add_mask),
                                            f"{name}_mask"),
                       name=f"{name}_masked")
        p = fm.softmax(s, -1, name=f"{name}_probs")
        return fm.batch_matmul(p, v, name=f"{name}_ctx")

    # -- call_method ----------------------------------------------------
    def _call_method(self, fm, rec, env):
        target = rec["target"]
        name = rec["name"]
        args, kwargs = self._args(rec, env)
        if _foldable(args) and _foldable(kwargs):
            folded = _fold(target, args, kwargs)
            if folded is not NotImplemented:
                return folded
        x = args[0]
        if target in ("to", "type_as", "float", "clone", "detach"):
            return x
        if target == "dim":
            return len(x.dims)
        if target in ("view", "reshape"):
            shape = args[1] if isinstance(args[1], list) else list(args[1:])
            return self._reshape(fm, x, shape, name)
        if target == "permute":
            perm = args[1] if isinstance(args[1], list) else list(args[1:])
            return fm.transpose(x, perm, name=name)
        if target == "transpose":
            return self._transpose(fm, x, args[1], args[2], name)
        if target == "flatten":
            start = args[1] if len(args) > 1 else 0
            if start == 1:
                return fm.flat(x, name=name)
            return self._flatten_range(fm, x, start, -1, name)
        if target == "contiguous":
            return x
        if target == "size":
            return x.dims if len(args) == 1 else x.dims[args[1]]
        if target == "mean":
            dims = args[1] if len(args) > 1 else kwargs.get("dim")
            keep = kwargs.get("keepdim", False)
            return fm.mean(x, self._axes(x, dims), keep, name=name)
        if target == "squeeze":
            dims = list(x.dims)
            if len(args) > 1:
                d = args[1]
                if dims[d] != 1:
                    return x  # torch: squeezing a non-1 dim is a no-op
                dims.pop(d)
            else:
                dims = [s for s in dims if s != 1]
            return fm.reshape(x, dims, name=name)
        if target == "unsqueeze":
            if len(args) < 2:
                raise NotImplementedError("unsqueeze requires a dim argument")
            dims = list(x.dims)
            d = args[1]
            dims.insert(d if d >= 0 else len(dims) + d + 1, 1)
            return fm.reshape(x, dims, name=name)
        if target == "softmax":
            return fm.softmax(x, args[1] if len(args) > 1 else -1, name=name)
        if target == "pow":
            return fm.pow(x, float(args[1]), name=name)
        if target == "rsqrt":
            return fm.rsqrt(x, name=name)
        if target == "masked_fill":
            mask, value = args[1], args[2]
            if isinstance(mask, _Const):
                mv = mask.value.astype(bool)
                if not np.any(mv):
                    return x
                # exact replace semantics: x*(1-m) + value*m, constants at
                # the mask's natural shape (elementwise ops broadcast)
                keep = np.where(mv, 0.0, 1.0).astype(np.float32)
                fill = np.where(mv, float(value), 0.0).astype(np.float32)
                kept = fm.multiply(
                    x, self._materialize(fm, _Const(keep), f"{name}_keep"),
                    name=f"{name}_kept")
                return fm.add(
                    kept, self._materialize(fm, _Const(fill), f"{name}_fill"),
                    name=name)
            raise NotImplementedError("masked_fill with a traced mask")
        raise NotImplementedError(f"call_method {target} not supported")

    # -- helpers --------------------------------------------------------
    def _tensor_getitem(self, fm, x, idx, name):
        """Basic tensor indexing (x[:, 0], x[:, :L]) via split + reshape."""
        if not isinstance(idx, (list, tuple)):
            idx = [idx]
        out = x
        squeeze_axes = []
        for ax, it in enumerate(idx):
            size = out.dims[ax]
            if isinstance(it, slice):
                start, stop, step = it.indices(size)
                if step != 1:
                    raise NotImplementedError(f"strided getitem {it}")
                if (start, stop) == (0, size):
                    continue
            elif isinstance(it, int):
                start = it if it >= 0 else size + it
                stop = start + 1
                squeeze_axes.append(ax)
            else:
                raise NotImplementedError(f"getitem index {it!r}")
            pre, mid, post = start, stop - start, size - stop
            sizes = [s for s in (pre, mid, post) if s > 0]
            if len(sizes) > 1:
                out = fm.split(out, sizes, ax, name=f"{name}_ax{ax}")[
                    1 if pre > 0 else 0]
        if squeeze_axes:
            dims = [d for ax, d in enumerate(out.dims)
                    if ax not in squeeze_axes]
            out = fm.reshape(out, dims, name=f"{name}_sq")
        return out

    @staticmethod
    def _axes(x, dims):
        """torch dim=None means reduce over ALL axes."""
        if dims is None:
            return list(range(len(x.dims)))
        return dims if isinstance(dims, list) else [dims]

    def _reshape(self, fm, x, shape, name):
        shape = list(shape)
        total = math.prod(x.dims)
        if -1 in shape:
            known = math.prod(d for d in shape if d != -1)
            shape[shape.index(-1)] = total // known
        return fm.reshape(x, shape, name=name)

    def _transpose(self, fm, x, d0, d1, name):
        perm = list(range(len(x.dims)))
        perm[d0], perm[d1] = perm[d1], perm[d0]
        return fm.transpose(x, perm, name=name)

    def _flatten_range(self, fm, x, start, end, name):
        dims = list(x.dims)
        n = len(dims)
        start %= n
        end %= n
        merged = math.prod(dims[start:end + 1])
        return fm.reshape(x, dims[:start] + [merged] + dims[end + 1:], name=name)

    # ------------------------------------------------------------------
    def transfer_weights(self, ffmodel) -> int:
        """Copy weights from the traced torch module into the compiled
        FFModel's params (extension; the reference re-initializes). Returns
        the number of tensors copied."""
        if self._torch_module is None:
            raise ValueError("weight transfer needs a live torch module")
        import jax.numpy as jnp
        import torch.nn as nn

        modules = dict(self._torch_module.named_modules())
        # fx node target -> node name happens via records
        copied = 0
        for rec in self.records:
            if rec["op"] != "call_module":
                continue
            name = rec["name"]
            if name not in (ffmodel.params or {}):
                continue
            mod = modules[rec["target"]]
            slot = ffmodel.params[name]

            def put(key, arr):
                nonlocal copied
                slot[key] = jnp.asarray(arr.detach().cpu().numpy()).astype(
                    slot[key].dtype
                )
                copied += 1

            if isinstance(mod, nn.Linear):
                put("kernel", mod.weight.T)
                if mod.bias is not None:
                    put("bias", mod.bias)
            elif isinstance(mod, nn.Conv2d):
                put("kernel", mod.weight)  # torch OIHW == ours
                if mod.bias is not None:
                    put("bias", mod.bias)
            elif isinstance(mod, nn.Embedding):
                put("weight", mod.weight)
            elif isinstance(mod, nn.LayerNorm) and mod.elementwise_affine:
                put("gamma", mod.weight)
                put("beta", mod.bias)
            elif isinstance(mod, nn.MultiheadAttention):
                e = mod.embed_dim
                h = mod.num_heads
                hd = e // h
                if mod.in_proj_weight is not None:
                    wq, wk, wv = mod.in_proj_weight.chunk(3, dim=0)
                else:
                    wq, wk, wv = (mod.q_proj_weight, mod.k_proj_weight,
                                  mod.v_proj_weight)
                # torch proj weight is (E_out, E_in); ours is (E_in, h, hd)
                put("wq", wq.T.reshape(e, h, hd))
                put("wk", wk.T.reshape(e, h, hd))
                put("wv", wv.T.reshape(e, h, hd))
                # out_proj (E, E) -> (h, hd, E)
                put("wo", mod.out_proj.weight.T.reshape(h, hd, e))
                if mod.in_proj_bias is not None and "bq" in slot:
                    bq, bk, bv = mod.in_proj_bias.chunk(3, dim=0)
                    put("bq", bq.reshape(h, hd))
                    put("bk", bk.reshape(h, hd))
                    put("bv", bv.reshape(h, hd))
                    put("bo", mod.out_proj.bias)
        # get_attr-backed trainable parameters (materialized as ConstantOp
        # weights): refresh from the module's CURRENT values too
        from . import fx as _fx

        for target, op_name in getattr(self, "_attr_op_names", {}).items():
            if op_name not in (ffmodel.params or {}):
                continue
            val, _ = _fx._fetch_attr(self._torch_module, target)
            slot = ffmodel.params[op_name]
            import jax.numpy as jnp

            slot["value"] = jnp.asarray(
                val.detach().cpu().float().numpy()
            ).astype(slot["value"].dtype)
            copied += 1
        return copied
