"""Framework-wide enums.

Mirrors the capability surface of the reference's include/flexflow/ffconst.h
(OperatorType, DataType, LossType, MetricsType, ActiMode, PoolType, AggrMode,
ParameterSyncType, CompMode) re-expressed for a TPU/JAX-native framework.
"""
from __future__ import annotations

import enum


class DataType(enum.Enum):
    DT_BOOLEAN = "bool"
    DT_INT32 = "int32"
    DT_INT64 = "int64"
    DT_HALF = "float16"
    DT_BFLOAT16 = "bfloat16"
    DT_FLOAT = "float32"
    DT_DOUBLE = "float64"
    DT_NONE = "none"

    @property
    def np_dtype(self):
        import numpy as np

        return np.dtype(self.value)

    @classmethod
    def from_numpy(cls, dt) -> "DataType":
        import numpy as np

        return cls(np.dtype(dt).name)

    @property
    def jnp_dtype(self):
        import jax.numpy as jnp

        return jnp.dtype(self.value)


class ActiMode(enum.Enum):
    AC_MODE_NONE = 0
    AC_MODE_RELU = 1
    AC_MODE_SIGMOID = 2
    AC_MODE_TANH = 3
    AC_MODE_GELU = 4


class PoolType(enum.Enum):
    POOL_MAX = 0
    POOL_AVG = 1


class AggrMode(enum.Enum):
    AGGR_MODE_NONE = 0
    AGGR_MODE_SUM = 1
    AGGR_MODE_AVG = 2


class LossType(enum.Enum):
    LOSS_CATEGORICAL_CROSSENTROPY = 0
    LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = 1
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE = 2
    LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE = 3
    LOSS_IDENTITY = 4


class MetricsType(enum.Enum):
    METRICS_ACCURACY = 0
    METRICS_CATEGORICAL_CROSSENTROPY = 1
    METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = 2
    METRICS_MEAN_SQUARED_ERROR = 3
    METRICS_ROOT_MEAN_SQUARED_ERROR = 4
    METRICS_MEAN_ABSOLUTE_ERROR = 5


class CompMode(enum.Enum):
    COMP_MODE_TRAINING = 0
    COMP_MODE_INFERENCE = 1


class ParameterSyncType(enum.Enum):
    """Reference distinguishes PS vs NCCL gradient sync (config.h:55-59).

    On TPU both collapse to a psum over the data-parallel mesh axis inside the
    jitted update step; the enum is kept for API compatibility.
    """

    NONE = 0
    PS = 1
    NCCL = 2


class OpType(enum.Enum):
    """Operator types (reference: ffconst.h OperatorType)."""

    NOOP = "noop"
    INPUT = "input"
    WEIGHT = "weight"
    CONV2D = "conv2d"
    DROPOUT = "dropout"
    LINEAR = "linear"
    BATCHMATMUL = "batch_matmul"
    POOL2D = "pool2d"
    SCALAR_MULTIPLY = "scalar_multiply"
    SCALAR_ADD = "scalar_add"
    SCALAR_SUB = "scalar_sub"
    SCALAR_TRUE_DIV = "scalar_true_div"
    RELU = "relu"
    IDENTITY = "identity"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    ELU = "elu"
    GELU = "gelu"
    RSQRT = "rsqrt"
    POW = "pow"
    EXP = "exp"
    SIN = "sin"
    COS = "cos"
    FLAT = "flat"
    SOFTMAX = "softmax"
    BATCHNORM = "batchnorm"
    LAYERNORM = "layernorm"
    RMSNORM = "rmsnorm"
    CONCAT = "concat"
    SPLIT = "split"
    EMBEDDING = "embedding"
    GATHER = "gather"
    CACHE = "cache"
    AGGREGATE = "aggregate"
    AGGREGATE_SPEC = "aggregate_spec"
    RESHAPE = "reshape"
    REVERSE = "reverse"
    TRANSPOSE = "transpose"
    EW_ADD = "ew_add"
    EW_MUL = "ew_mul"
    EW_SUB = "ew_sub"
    EW_DIV = "ew_div"
    EW_MAX = "ew_max"
    EW_MIN = "ew_min"
    REDUCE_SUM = "reduce_sum"
    MEAN = "mean"
    CAST = "cast"
    MULTIHEAD_ATTENTION = "multihead_attention"
    TOPK = "topk"
    GROUP_BY = "group_by"
    EXPERTS = "experts"
    FUSED = "fused"
    LSTM = "lstm"
    # Parallel ops (reference: src/parallel_ops)
    REPARTITION = "repartition"
    COMBINE = "combine"
    REPLICATE = "replicate"
    REDUCTION = "reduction"
    ALLREDUCE = "allreduce"
    FUSED_PARALLEL = "fused_parallel"
    PIPELINE = "pipeline"
    # TPU-native new capability: sequence/context parallel attention
    RING_ATTENTION = "ring_attention"


# Parallel-dimension kinds used by the strategy layer / search.
class ParallelDimKind(enum.Enum):
    SAMPLE = "sample"  # batch dim (data parallelism)
    CHANNEL = "channel"  # feature dims (tensor/"parameter" parallelism)
    ATTRIBUTE = "attribute"  # spatial/attribute dims
    SEQUENCE = "sequence"  # sequence dim (context parallelism — new on TPU)
    REPLICA = "replica"  # replication dim
    EXPERT = "expert"  # expert dim (MoE)
