"""Logical and parallel tensors.

TPU-native re-design of the reference's TensorBase (include/flexflow/tensor.h:29)
and ParallelTensorBase (include/flexflow/parallel_tensor.h:134-198). The central
idea is kept: a *parallel tensor* is a logical tensor whose every dimension
carries a partition `degree` (plus replica dims). Where the reference realizes
degrees as Legion index-space partitions, here each partitioned dim maps to a
named mesh axis and the whole shape lowers to a `jax.sharding.NamedSharding`.

Dimension order is row-major / numpy-style (batch first) — NOT the reference's
Legion-reversed order.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import List, Optional, Sequence, Tuple, TYPE_CHECKING

import numpy as np

from ..ffconst import DataType, ParallelDimKind

if TYPE_CHECKING:
    from jax.sharding import Mesh, NamedSharding

_guid_counter = itertools.count(1000)


@dataclasses.dataclass
class ParallelDim:
    """One dimension of a parallel tensor (reference: parallel_tensor.h:36-71).

    size: global extent of this dim.
    degree: number of shards (1 = not partitioned).
    axis: mesh-axis name this dim is sharded over (None iff degree == 1).
    is_replica_dim: true for pure replication dims (size == degree; no data).
    kind: semantic kind used by the strategy search.
    """

    size: int
    degree: int = 1
    axis: Optional[str] = None
    is_replica_dim: bool = False
    kind: ParallelDimKind = ParallelDimKind.ATTRIBUTE

    def __post_init__(self):
        if self.degree > 1 and self.axis is None:
            raise ValueError("partitioned dim needs a mesh axis name")
        if self.size % self.degree != 0:
            raise ValueError(
                f"dim size {self.size} not divisible by degree {self.degree}"
            )


@dataclasses.dataclass
class ParallelTensorShape:
    """Shape of a parallel tensor (reference: parallel_tensor.h:76-111)."""

    dims: List[ParallelDim]
    dtype: DataType

    @property
    def num_replicas(self) -> int:
        n = 1
        for d in self.dims:
            if d.is_replica_dim:
                n *= d.degree
        return n

    @property
    def data_dims(self) -> List[ParallelDim]:
        return [d for d in self.dims if not d.is_replica_dim]

    @property
    def shape(self) -> Tuple[int, ...]:
        """Global (unsharded) data shape, replica dims excluded."""
        return tuple(d.size for d in self.data_dims)

    @property
    def local_shape(self) -> Tuple[int, ...]:
        return tuple(d.size // d.degree for d in self.data_dims)

    def total_degree(self) -> int:
        n = 1
        for d in self.dims:
            n *= d.degree
        return n

    def num_elements(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def piece_elements(self) -> int:
        return int(np.prod(self.local_shape)) if self.local_shape else 1

    def partition_spec(self):
        """PartitionSpec over the data dims (replica dims -> replication)."""
        from jax.sharding import PartitionSpec

        return PartitionSpec(*[d.axis if d.degree > 1 else None for d in self.data_dims])

    def sharding(self, mesh: "Mesh") -> "NamedSharding":
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.partition_spec())

    def __str__(self):
        parts = []
        for d in self.dims:
            tag = "r" if d.is_replica_dim else ""
            parts.append(f"{d.size}{tag}/{d.degree}" + (f"@{d.axis}" if d.axis else ""))
        return f"[{', '.join(parts)}]:{self.dtype.value}"


class Tensor:
    """A tensor in the computation graph.

    Covers both roles of the reference's TensorBase (frontend-visible logical
    tensor) and ParallelTensorBase (post-compile tensor with partition degrees):
    before `compile()` only `dims`/`dtype` are meaningful; compile attaches a
    `ParallelTensorShape` in `parallel_shape` once the strategy is chosen.
    """

    def __init__(
        self,
        dims: Sequence[int],
        dtype: DataType = DataType.DT_FLOAT,
        name: str = "",
        owner_op=None,
        owner_idx: int = 0,
        create_gradients: bool = True,
    ):
        self.guid: int = next(_guid_counter)
        self.dims: Tuple[int, ...] = tuple(int(d) for d in dims)
        self.dtype = dtype
        self.name = name or f"tensor_{self.guid}"
        self.owner_op = owner_op  # producing Op (None for graph inputs)
        self.owner_idx = owner_idx
        self.create_gradients = create_gradients
        self.parallel_shape: Optional[ParallelTensorShape] = None
        # host-attached initial value (reference: attach_raw_ptr / set_tensor)
        self._host_value: Optional[np.ndarray] = None
        # model backref, set by FFModel for weight get/set convenience
        self._model = None

    @property
    def num_dims(self) -> int:
        return len(self.dims)

    def num_elements(self) -> int:
        # math.prod, not np.prod: this sits on the search's hottest path
        # (cost model shape math, ~1e6 calls per big search)
        return math.prod(self.dims) if self.dims else 1

    # -- host I/O (reference: parallel_tensor.h:164-169 set_tensor/get_tensor)
    def set_tensor(self, model, value: np.ndarray) -> bool:
        value = np.asarray(value, dtype=self.dtype.np_dtype)
        if tuple(value.shape) != self.dims:
            raise ValueError(f"shape mismatch: {value.shape} vs {self.dims}")
        self._host_value = value
        if model is not None:
            model._set_tensor_value(self, value)
        return True

    def get_tensor(self, model) -> np.ndarray:
        if model is not None:
            arr = model._get_tensor_value(self)
            if arr is not None:
                return np.asarray(arr)
        if self._host_value is not None:
            return self._host_value
        raise RuntimeError(f"tensor {self.name} has no materialized value")

    def attach_numpy_array(self, value: np.ndarray) -> None:
        self._host_value = np.ascontiguousarray(value, dtype=self.dtype.np_dtype)

    def __repr__(self):
        ps = f" {self.parallel_shape}" if self.parallel_shape else ""
        return f"Tensor({self.name}, dims={self.dims}, {self.dtype.value}{ps})"


# Weight tensors are plain Tensors flagged as parameters.
class Parameter(Tensor):
    def __init__(self, *args, sync_type=None, initializer=None, **kwargs):
        super().__init__(*args, **kwargs)
        from ..ffconst import ParameterSyncType

        self.sync_type = sync_type or ParameterSyncType.NCCL
        self.initializer = initializer
