from .tensor import ParallelDim, ParallelTensorShape, Tensor
from .machine import MachineView, MachineResource, make_mesh

__all__ = [
    "ParallelDim",
    "ParallelTensorShape",
    "Tensor",
    "MachineView",
    "MachineResource",
    "make_mesh",
]
