"""Device-mesh model: MachineView / MachineResource for TPU.

In the reference, `MachineView` (include/flexflow/machine_view.h:14-35) is a
strided grid of device ids and `FFMapper::slice_task` (src/mapper/mapper.cc:364)
places each point task. On TPU the whole mapper layer collapses into GSPMD: a
MachineView here is an *ordered set of named mesh axes with sizes*; tensors
reference these axes in their ParallelDims and XLA emits the collectives.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class MachineResource:
    """Total resources available (reference: machine_view.h:51-60)."""

    num_nodes: int = 1
    devices_per_node: int = 1
    start_device_id: int = 0

    @property
    def num_devices(self) -> int:
        return self.num_nodes * self.devices_per_node


@dataclasses.dataclass(frozen=True)
class MachineView:
    """A device sub-grid: ordered (axis name, size) pairs + start offset.

    hash()/`device_ids()` mirror the reference's MachineView::hash and
    start_device_id + sum(point*stride) addressing (mapper.cc:440-447) for a
    contiguous row-major grid.
    """

    axes: Tuple[Tuple[str, int], ...] = ()
    start_device_id: int = 0

    @property
    def ndims(self) -> int:
        return len(self.axes)

    @property
    def num_devices(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= s
        return n

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return dict(self.axes)

    def device_ids(self) -> List[int]:
        return list(
            range(self.start_device_id, self.start_device_id + self.num_devices)
        )

    def hash(self) -> int:
        h = 17
        h = h * 31 + self.start_device_id
        for name, size in self.axes:
            h = h * 31 + hash(name) % (2**31)
            h = h * 31 + size
        return h & 0x7FFFFFFFFFFFFFFF

    def with_axis(self, name: str, size: int) -> "MachineView":
        return MachineView(self.axes + ((name, size),), self.start_device_id)

    def __str__(self):
        body = "x".join(f"{n}:{s}" for n, s in self.axes) or "1"
        return f"MV[{body}@{self.start_device_id}]"


def data_parallel_view(num_devices: int) -> MachineView:
    """Default fallback view (reference: config.h:96 DataParallelism_GPU)."""
    return MachineView(axes=(("data", num_devices),))


def make_mesh(axis_sizes: Dict[str, int], devices: Optional[Sequence] = None):
    """Build a jax Mesh with the given named axis sizes.

    The product of axis sizes must equal (or divide) the device count; extra
    devices are left out (reference analog: a MachineView covering a subset of
    the cluster).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    need = int(np.prod(sizes)) if sizes else 1
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    dev_array = np.array(devices[:need]).reshape(sizes if sizes else (1,))
    if not names:
        names = ("data",)
        dev_array = dev_array.reshape((1,))
    return Mesh(dev_array, names)


def mesh_for_view(view: MachineView, devices: Optional[Sequence] = None):
    return make_mesh(dict(view.axes), devices)
