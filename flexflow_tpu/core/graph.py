"""Parallel Computation Graph (PCG) structure + generic graph algorithms.

TPU-native counterpart of the reference's `PCG::Graph` (include/flexflow/
graph.h:293-377) and the header-only graph algorithm toolkit (dominators.h,
basic_graph.h): edges, topological order, roots/leaves/sinks, post-dominators
(used by the Unity search to find bottleneck split points), hashing, and dot
export (src/utils/dot)."""
from __future__ import annotations

import dataclasses
from collections import defaultdict, deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .op import Op
from .tensor import Tensor


@dataclasses.dataclass(frozen=True)
class Edge:
    """Dataflow edge (reference: graph.h:31): src op output -> dst op input."""

    src: int  # op guid
    dst: int  # op guid
    src_idx: int
    dst_idx: int


class Graph:
    """A PCG over Op nodes. Ops reference Tensors; edges derive from tensor
    producer/consumer relationships."""

    def __init__(self, ops: Sequence[Op] = ()):
        self.ops: Dict[int, Op] = {}
        # tensor guid -> replacement tensor, recorded by substitutions that
        # remove producers; resolve_tensor follows the chain so externally
        # held references (e.g. FFModel.final_tensor) stay valid
        self.tensor_aliases: Dict[int, "Tensor"] = {}
        for op in ops:
            self.add_op(op)

    def resolve_tensor(self, tensor: Tensor) -> Tensor:
        seen = set()
        while tensor.guid in self.tensor_aliases and tensor.guid not in seen:
            seen.add(tensor.guid)
            tensor = self.tensor_aliases[tensor.guid]
        return tensor

    def add_op(self, op: Op) -> None:
        self.ops[op.guid] = op
        self._topo_cache = None

    def remove_op(self, op: Op) -> None:
        del self.ops[op.guid]
        self._topo_cache = None
        # drop alias chains that now dead-end at this op's outputs: a
        # substitution that replaces the replacement records a further
        # alias BEFORE removing the producer, so anything still resolving
        # to a tensor of the removed op is dangling and resolve_tensor
        # must not hand it back
        if self.tensor_aliases:
            stale = [
                guid for guid, repl in self.tensor_aliases.items()
                if (final := self.resolve_tensor(repl)).owner_op is not None
                and final.owner_op.guid == op.guid
            ]
            for guid in stale:
                del self.tensor_aliases[guid]

    def invalidate_topo(self) -> None:
        """Call after rewiring op inputs in place (edge changes the
        add/remove hooks can't see)."""
        self._topo_cache = None

    def __len__(self):
        return len(self.ops)

    def __contains__(self, op: Op):
        return op.guid in self.ops

    # -- edges ------------------------------------------------------------
    def edges(self) -> List[Edge]:
        out: List[Edge] = []
        for op in self.ops.values():
            for dst_idx, t in enumerate(op.inputs):
                if t.owner_op is not None and t.owner_op.guid in self.ops:
                    out.append(Edge(t.owner_op.guid, op.guid, t.owner_idx, dst_idx))
        return out

    def in_edges(self, op: Op) -> List[Edge]:
        return [e for e in self.edges() if e.dst == op.guid]

    def out_edges(self, op: Op) -> List[Edge]:
        return [e for e in self.edges() if e.src == op.guid]

    def predecessors(self, op: Op) -> List[Op]:
        seen, out = set(), []
        for t in op.inputs:
            o = t.owner_op
            if o is not None and o.guid in self.ops and o.guid not in seen:
                seen.add(o.guid)
                out.append(o)
        return out

    def successors(self, op: Op) -> List[Op]:
        out = []
        for other in self.ops.values():
            if op in self.predecessors(other):
                out.append(other)
        return out

    # -- traversal --------------------------------------------------------
    def topo_order(self) -> List[Op]:
        # cached: the event-driven simulator walks the order once per
        # candidate costing (thousands of times per search); every graph
        # mutation path (add_op/remove_op/_rewire) invalidates
        cached = getattr(self, "_topo_cache", None)
        if cached is not None:
            return cached
        order = self._topo_order_uncached()
        self._topo_cache = order
        return order

    def _topo_order_uncached(self) -> List[Op]:
        indeg: Dict[int, int] = {g: 0 for g in self.ops}
        succ: Dict[int, List[int]] = defaultdict(list)
        for e in self.edges():
            indeg[e.dst] += 1
            succ[e.src].append(e.dst)
        # stable order: seed queue by op guid (construction order)
        q = deque(sorted(g for g, d in indeg.items() if d == 0))
        order: List[Op] = []
        while q:
            g = q.popleft()
            order.append(self.ops[g])
            for s in sorted(set(succ[g])):
                indeg[s] -= succ[g].count(s)
                if indeg[s] == 0:
                    q.append(s)
        if len(order) != len(self.ops):
            raise ValueError("PCG has a cycle")
        return order

    def roots(self) -> List[Op]:
        dsts = {e.dst for e in self.edges()}
        return [op for g, op in sorted(self.ops.items()) if g not in dsts]

    def leaves(self) -> List[Op]:
        srcs = {e.src for e in self.edges()}
        return [op for g, op in sorted(self.ops.items()) if g not in srcs]

    sinks = leaves
    sources = roots

    # -- dominators (reference: dominators.h; used for bottleneck splits) --
    def post_dominators(self) -> Dict[int, Set[int]]:
        """postdom[n] = set of nodes that post-dominate n (incl. n).

        Standard iterative dataflow over the reversed DAG with a virtual sink.
        """
        order = self.topo_order()
        guids = [op.guid for op in order]
        succ: Dict[int, Set[int]] = defaultdict(set)
        for e in self.edges():
            succ[e.src].add(e.dst)
        allg = set(guids)
        postdom: Dict[int, Set[int]] = {g: set(allg) for g in guids}
        changed = True
        while changed:
            changed = False
            for g in reversed(guids):
                ss = succ[g]
                if not ss:
                    new = {g}
                else:
                    new = set(allg)
                    for s in ss:
                        new &= postdom[s]
                    new |= {g}
                if new != postdom[g]:
                    postdom[g] = new
                    changed = True
        return postdom

    def bottleneck_nodes(self) -> List[Op]:
        """Nodes that every source-to-sink path passes through (excluding
        sources), in topological order — the Unity sequence-split candidates
        (reference: graph.cc find_bottleneck_node)."""
        order = self.topo_order()
        if not order:
            return []
        postdom = self.post_dominators()
        sources = self.roots()
        if not sources:
            return []
        common = set.intersection(*[postdom[s.guid] for s in sources])
        src_guids = {s.guid for s in sources}
        return [op for op in order if op.guid in common and op.guid not in src_guids]

    def segments(self) -> List[List["Op"]]:
        """Topo-ordered ops split after each bottleneck node — shared by the
        Unity sequence-split DP and the pipeline-stage planner (so both
        always agree on segment boundaries)."""
        order = self.topo_order()
        bottlenecks = {op.guid for op in self.bottleneck_nodes()}
        out: List[List[Op]] = [[]]
        for op in order:
            out[-1].append(op)
            if op.guid in bottlenecks:
                out.append([])
        return [s for s in out if s]

    # -- cloning (for search over candidate rewritten graphs) --------------
    def clone(self) -> "Graph":
        """Structural copy for substitution search: new Op shells (shared
        weights/model refs — rewrites never mutate those) with copied params
        and rewired cloned output tensors, so rule applications on the clone
        leave this graph untouched. Tensor guids are preserved, keeping the
        segment-DP memo (keyed by op guids) valid across candidates
        (reference: candidate graphs in base_optimize share the same
        simulator cache, substitution.cc:2229-2311)."""
        import copy

        new_ops: Dict[int, Op] = {}
        tensor_map: Dict[int, Tensor] = {}
        for g, op in self.ops.items():
            new_op = copy.copy(op)
            new_op.params = dict(op.params)
            new_op.outputs = []
            for t in op.outputs:
                nt = copy.copy(t)
                nt.owner_op = new_op
                tensor_map[t.guid] = nt
                new_op.outputs.append(nt)
            new_ops[g] = new_op
        for op in new_ops.values():
            op.inputs = [tensor_map.get(t.guid, t) for t in op.inputs]
        g2 = Graph.__new__(Graph)
        g2.ops = new_ops
        g2.tensor_aliases = {}
        return g2

    # -- hashing (reference: graph.h:149 dp_state_hash) --------------------
    def hash(self) -> int:
        h = 0
        for op in self.topo_order():
            oh = hash((op.op_type, tuple(t.dims for t in op.inputs)))
            mv = op.machine_view.hash() if op.machine_view else 0
            h = (h * 1000000007 + oh * 31 + mv) & 0x7FFFFFFFFFFFFFFF
        return h

    # -- subgraphs (for sequence splits) ----------------------------------
    def split_at(self, op: Op) -> Tuple["Graph", "Graph"]:
        """Split into (prefix including op, suffix) at a bottleneck node."""
        order = self.topo_order()
        idx = order.index(op)
        pre = Graph(order[: idx + 1])
        post = Graph(order[idx + 1 :])
        return pre, post

    # -- dot export (reference: --export-strategy-computation-graph-file) --
    def to_dot(self, include_costs: bool = False,
               costs: Optional[Dict[int, float]] = None,
               labels: Optional[Dict[int, str]] = None) -> str:
        lines = ["digraph PCG {", "  rankdir=TB;"]
        for g, op in sorted(self.ops.items()):
            label = f"{op.name}\\n{op.op_type.value}"
            if op.machine_view:
                label += f"\\n{op.machine_view}"
            if labels and g in labels:
                label += f"\\n{labels[g]}"
            if include_costs and costs and g in costs:
                label += f"\\ncost={costs[g]:.3g}"
            lines.append(f'  n{g} [label="{label}", shape=box];')
        for e in self.edges():
            lines.append(f"  n{e.src} -> n{e.dst};")
        lines.append("}")
        return "\n".join(lines)

    def export_dot(self, path: str, **kw) -> None:
        with open(path, "w") as f:
            f.write(self.to_dot(**kw))
