"""Operator base class and registry.

TPU-native re-design of the reference's `Op` (include/flexflow/operator.h:51-277).
The reference Op carries Legion task launchers (init/forward/backward) plus
profiling hooks; here an Op is a pure description: it computes output shapes at
construction, declares its weights, and provides a single `lower()` that emits
jax ops inside the traced train/inference step (forward only — backward comes
from jax.grad, the TPU-native replacement for hand-written backward kernels).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ffconst import DataType, OpType, ParameterSyncType
from .machine import MachineView
from .tensor import Parameter, Tensor

_op_guid = itertools.count(1)


def _freeze(v):
    """Hashable deep-freeze of op params (lists/dicts/arrays/callables)."""
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return (v.shape, v.dtype.str, v.tobytes())
    if callable(v):
        return getattr(v, "__name__", repr(v))
    return v


@dataclasses.dataclass
class WeightSpec:
    """Declaration of one weight tensor of an op."""

    name: str
    dims: Tuple[int, ...]
    dtype: DataType = DataType.DT_FLOAT
    initializer: Optional[Any] = None  # runtime.initializers.Initializer
    sync_type: ParameterSyncType = ParameterSyncType.NCCL


class LoweringContext:
    """State threaded through PCG lowering into a jax computation."""

    def __init__(self, config, mode, mesh=None, rng_key=None,
                 iter_seq_length=None):
        self.config = config
        self.mode = mode  # CompMode
        self.mesh = mesh
        self.rng_key = rng_key
        # FFIterationConfig.seq_length (reference config.h:162-167): ops with
        # a sequence dim truncate their compute to the first L positions
        self.iter_seq_length = iter_seq_length
        self._rng_count = 0
        # tensor guid -> traced jax value
        self.values: Dict[int, Any] = {}

        # non-trainable per-op state (e.g. batchnorm running stats):
        # (op_name, var_name) -> traced value; lower() may write updates here.
        self.state: Dict[Tuple[str, str], Any] = {}
        self.state_updates: Dict[Tuple[str, str], Any] = {}
        # auxiliary loss terms ops contribute (e.g. MoE load-balance loss);
        # summed into the training objective by the executor.
        self.aux_losses: List[Any] = []
        # true while lowering inside a shard_map manual-collective region
        # (ring attention, expert all_to_all) where lax collectives are legal
        self.in_shard_map: bool = False
        # mesh axes the enclosing shard_map holds MANUAL (the explicit
        # grad-sync lowering, runtime/collectives.py): sharding
        # constraints naming a manual axis are illegal inside the body,
        # so constrain() strips them (the data is already the shard)
        self.manual_axes: frozenset = frozenset()

    def next_rng(self):
        import jax

        if self.rng_key is None:
            raise RuntimeError("op needs an rng key but none was provided")
        self._rng_count += 1
        return jax.random.fold_in(self.rng_key, self._rng_count)

    def constrain(self, value, tensor: Tensor):
        """Apply the tensor's sharding as a constraint, if meshed + partitioned."""
        if self.mesh is None or tensor.parallel_shape is None:
            return value
        spec = tensor.parallel_shape.partition_spec()
        if self.manual_axes:
            from jax.sharding import PartitionSpec

            def drop_manual(p):
                if isinstance(p, (tuple, list)):
                    kept = tuple(q for q in p if q not in self.manual_axes)
                    return kept if kept else None
                return None if p in self.manual_axes else p

            spec = PartitionSpec(*[drop_manual(p) for p in spec])
        if all(p is None for p in spec):
            return value
        import jax

        from jax.sharding import NamedSharding

        return jax.lax.with_sharding_constraint(
            value, NamedSharding(self.mesh, spec)
        )


class Op:
    """Base operator. Subclasses implement shape inference + lowering."""

    op_type: OpType = OpType.NOOP

    def __init__(
        self,
        model,
        inputs: Sequence[Tensor],
        name: str = "",
        **params,
    ):
        self.guid = next(_op_guid)
        self.model = model
        self.inputs: List[Tensor] = list(inputs)
        self.params: Dict[str, Any] = params
        self.name = name or f"{self.op_type.value}_{self.guid}"
        self.machine_view: Optional[MachineView] = None
        self.profiling = bool(model is not None and model.config.profiling)

        out_dims, out_dtypes = self.output_shapes()
        self.outputs: List[Tensor] = [
            Tensor(dims, dtype, name=f"{self.name}.out{i}", owner_op=self, owner_idx=i)
            for i, (dims, dtype) in enumerate(zip(out_dims, out_dtypes))
        ]
        self.weights: List[Parameter] = []
        for ws in self.weight_specs():
            p = Parameter(
                ws.dims,
                ws.dtype,
                name=f"{self.name}.{ws.name}",
                owner_op=self,
                sync_type=ws.sync_type,
                initializer=ws.initializer,
            )
            p._weight_spec = ws
            self.weights.append(p)
        self.state_vars: List[WeightSpec] = list(self.state_specs())

    # -- subclass API -----------------------------------------------------
    def output_shapes(self) -> Tuple[List[Tuple[int, ...]], List[DataType]]:
        """Return (list of output dims, list of output dtypes)."""
        raise NotImplementedError

    def weight_specs(self) -> List[WeightSpec]:
        return []

    def state_specs(self) -> List[WeightSpec]:
        """Non-trainable per-op state (e.g. running statistics)."""
        return []

    def lower(self, ctx: LoweringContext, inputs: List[Any], weights: Dict[str, Any]):
        """Emit jax ops; return list of output values (one per output tensor)."""
        raise NotImplementedError

    # -- cost/analysis hooks (used by the simulator/search) ---------------
    def flops(self) -> float:
        """Forward FLOPs estimate; default 0 (elementwise ops dominated by BW)."""
        return 0.0

    def bytes_accessed(self) -> float:
        n = sum(t.num_elements() * t.dtype.np_dtype.itemsize for t in self.inputs)
        n += sum(t.num_elements() * t.dtype.np_dtype.itemsize for t in self.outputs)
        n += sum(w.num_elements() * w.dtype.np_dtype.itemsize for w in self.weights)
        return float(n)

    def is_parallel_op(self) -> bool:
        return False

    # -- identity/caching (reference: per-op Params structs + get_or_create_node)
    def param_key(self) -> Tuple:
        return (
            self.op_type,
            tuple(t.guid for t in self.inputs),
            _freeze(self.params),
        )

    def cost_key(self) -> Tuple:
        """Shape-based identity for cost caching: unlike param_key (whose
        input guids are unique per model), identical ops — the 12 identical
        layers of a BERT stack, or the same op in a fresh compile — share one
        key (reference: measured-cost hash cache, simulator.h:750-752)."""
        return (
            self.op_type,
            tuple((t.dims, t.dtype) for t in self.inputs),
            _freeze(self.params),
        )

    def __repr__(self):
        ins = ",".join(str(t.dims) for t in self.inputs)
        outs = ",".join(str(t.dims) for t in self.outputs)
        return f"{self.op_type.value}[{self.name}]({ins})->({outs})"


# registry: OpType -> Op subclass
OP_REGISTRY: Dict[OpType, type] = {}


def register_op(cls):
    OP_REGISTRY[cls.op_type] = cls
    return cls
