#!/usr/bin/env python3
"""Render one substitution rule of a TASO rule file as graphviz dot
(reference: tools/substitutions_to_dot/substitution_to_dot.cc — src and dst
pattern graphs as two subgraphs with tensor nodes).

Usage: python tools/substitutions_to_dot.py <json-file> <rule-name>
"""
from __future__ import annotations

import sys


def rule_to_dot(rule) -> str:
    lines = ["digraph substitution {", "  rankdir=TB;"]
    for side, ops in (("src", rule.src_ops), ("dst", rule.dst_ops)):
        lines.append(f"  subgraph cluster_{side} {{")
        lines.append(f'    label="{side}";')
        for i, op in enumerate(ops):
            para = ", ".join(f"{k}={v}" for k, v in op.params.items())
            label = op.type_name + (f"\\n{para}" if para else "")
            lines.append(f'    {side}_op{i} [label="{label}", shape=box];')
            for j, t in enumerate(op.inputs):
                if t.is_external:
                    ext = f"{side}_in{-t.op_id - 1}"
                    lines.append(
                        f'    {ext} [label="input {-t.op_id - 1}", '
                        "shape=ellipse];"
                    )
                    lines.append(f"    {ext} -> {side}_op{i} "
                                 f'[label="arg{j}"];')
                else:
                    lines.append(
                        f"    {side}_op{t.op_id} -> {side}_op{i} "
                        f'[label="out{t.ts_id}->arg{j}"];'
                    )
        lines.append("  }")
    for m in rule.mapped_outputs:
        lines.append(
            f"  src_op{m.src_op_id} -> dst_op{m.dst_op_id} "
            '[style=dashed, label="maps", constraint=false];'
        )
    lines.append("}")
    return "\n".join(lines)


def main(argv):
    if len(argv) != 3:
        print(f"Usage: {argv[0]} <json-file> <rule-name>", file=sys.stderr)
        return 1
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from flexflow_tpu.search.substitution_loader import load_substitution_file

    rules = load_substitution_file(argv[1])
    for rule in rules:
        if rule.name == argv[2]:
            print(rule_to_dot(rule))
            return 0
    print(f"Could not find rule with name {argv[2]}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
