"""PCG / strategy / sharding inspector.

Reference-parity role: gdb/pretty_print.py — the reference ships gdb
pretty-printers for its core C++ types (ParallelTensor shapes, MachineViews,
domains) because its state lives inside Legion tasks where only a debugger
can see it. Here the whole PCG is ordinary Python state, so the equivalent
debugging aid is a one-call dump: per-op type/name/shapes, the chosen
strategy, the resulting ParallelTensorShape annotations and mesh axes, and
(optionally) the pipeline plan.

Usage:
    from tools.pcg_inspect import dump_model, dump_graph
    print(dump_model(model))              # after compile()
    print(dump_graph(graph, strategies))  # inside search debugging

or from a shell:
    python tools/pcg_inspect.py <cspec.json>   # a C-API exported spec
"""
from __future__ import annotations

import sys
from typing import Dict, Optional


def _shape_str(t) -> str:
    ps = getattr(t, "parallel_shape", None)
    base = "x".join(str(d) for d in t.dims)
    if ps is None:
        return base
    ann = []
    for d in ps.dims:
        ann.append(f"{d.size}" + (f"/{d.degree}@{d.axis}" if d.degree > 1
                                  else ""))
    return "[" + ",".join(ann) + "]"


def dump_graph(graph, strategies: Optional[Dict] = None,
               costs: Optional[Dict] = None) -> str:
    """Table of the PCG in topo order: guid, op type, name, input/output
    shapes with sharding annotations (size/degree@axis), strategy."""
    strategies = strategies or {}
    lines = [f"{'guid':>5} {'type':<22} {'name':<28} "
             f"{'strategy':<22} shapes"]
    for op in graph.topo_order():
        s = strategies.get(op.guid)
        s_str = ""
        if s is not None:
            parts = [f"dp={s.dp}"]
            if s.tp > 1:
                parts.append(f"tp={s.tp}{'r' if s.tp_row else ''}")
            if s.ep > 1:
                parts.append(f"ep={s.ep}")
            if s.ap > 1:
                parts.append(f"ap={s.ap}")
            if s.sp > 1:
                parts.append(f"sp={s.sp}")
            s_str = " ".join(parts)
        ins = ",".join(_shape_str(t) for t in op.inputs)
        outs = ",".join(_shape_str(t) for t in op.outputs)
        cost = f"  {costs[op.guid]:.1f}us" if costs and op.guid in costs else ""
        lines.append(f"{op.guid:>5} {op.op_type.value:<22} "
                     f"{op.name[:28]:<28} {s_str:<22} "
                     f"{ins} -> {outs}{cost}")
    return "\n".join(lines)


def dump_model(model) -> str:
    """Full post-compile dump: mesh, per-op strategies + shardings, weight
    shardings, pipeline plan when present."""
    out = []
    axes = getattr(model, "parallel_axes", None)
    out.append(f"mesh axes: {axes or '(single device)'}")
    strategies = getattr(model, "_op_strategies", None) or {}
    out.append(dump_graph(model.graph, strategies))
    # weight shardings (only annotated ones)
    w_lines = []
    for op in model.graph.topo_order():
        for w in op.weights:
            ps = getattr(w, "parallel_shape", None)
            if ps is not None and any(d.degree > 1 for d in ps.dims):
                w_lines.append(f"  {op.name}.{w._weight_spec.name}: "
                               f"{_shape_str(w)}")
    if w_lines:
        out.append("sharded weights:")
        out.extend(w_lines)
    ex = getattr(model, "executor", None)
    plan = getattr(ex, "pipeline_plan", None) if ex else None
    if plan is not None:
        out.append(
            f"pipeline: {plan.n_stages} stages x {plan.segs_per_stage} "
            f"block(s)/stage over {len(plan.region_guids)} ops; "
            f"carry {tuple(plan.region_input.dims)}")
    return "\n".join(out)


def main(argv):
    if len(argv) != 2:
        print(__doc__)
        return 1
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from flexflow_tpu.native.c_model import model_from_spec

    model = model_from_spec(argv[1])
    from flexflow_tpu.core.graph import Graph

    print(dump_graph(Graph(model.ops)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
