#!/usr/bin/env python3
"""Convert a TASO substitution rule .pb file to the JSON rule format the
search consumes (reference: tools/protobuf_to_json/protobuf_to_json.cc +
rules.proto).

The schema (GraphSubst.RuleCollection, proto2) is tiny and fixed, so this
decodes the wire format directly — no generated bindings, no protobuf
runtime-version coupling.

Usage: python tools/protobuf_to_json.py graph_subst.pb > graph_subst.json
"""
from __future__ import annotations

import json
import sys

# enum value -> wire name (reference: protobuf_to_json.cc:14-80)
OP_NAMES = [
    "OP_INPUT", "OP_WEIGHT", "OP_ANY", "OP_CONV2D", "OP_DROPOUT",
    "OP_LINEAR", "OP_POOL2D_MAX", "OP_POOL2D_AVG", "OP_RELU", "OP_SIGMOID",
    "OP_TANH", "OP_BATCHNORM", "OP_CONCAT", "OP_SPLIT", "OP_RESHAPE",
    "OP_TRANSPOSE", "OP_EW_ADD", "OP_EW_MUL", "OP_MATMUL", "OP_MUL",
    "OP_ENLARGE", "OP_MERGE_GCONV", "OP_CONSTANT_IMM", "OP_CONSTANT_ICONV",
    "OP_CONSTANT_ONE", "OP_CONSTANT_POOL", "OP_PARTITION", "OP_COMBINE",
    "OP_REPLICATE", "OP_REDUCE", "OP_EMBEDDING",
]
# reference: protobuf_to_json.cc:82-99
PM_NAMES = [
    "PM_OP_TYPE", "PM_NUM_INPUTS", "PM_NUM_OUTPUTS", "PM_GROUP",
    "PM_KERNEL_H", "PM_KERNEL_W", "PM_STRIDE_H", "PM_STRIDE_W", "PM_PAD",
    "PM_ACTI", "PM_NUMDIM", "PM_AXIS", "PM_PERM", "PM_OUTSHUFFLE",
    "PM_MERGE_GCONV_COUNT", "PM_PARALLEL_DIM", "PM_PARALLEL_DEGREE",
]


def _decode_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _decode_message(buf: bytes):
    """-> {field_number: [values]}; values are ints or sub-message bytes."""
    fields = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _decode_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, pos = _decode_varint(buf, pos)
        elif wire == 2:  # length-delimited (sub-message here)
            length, pos = _decode_varint(buf, pos)
            val = buf[pos:pos + length]
            pos += length
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(val)
    return fields


def _int32(v: int) -> int:
    return v - (1 << 64) if v >= 1 << 63 else v


def _parameter(buf):  # Parameter {key=1, value=2}
    f = _decode_message(buf)
    key = _int32(f[1][0])
    return {
        "_t": "Parameter",
        "key": PM_NAMES[key] if 0 <= key < len(PM_NAMES) else key,
        "value": _int32(f[2][0]),
    }


def _tensor(buf):  # Tensor {opId=1, tsId=2}
    f = _decode_message(buf)
    return {"_t": "Tensor", "opId": _int32(f[1][0]), "tsId": _int32(f[2][0])}


def _operator(buf):  # Operator {type=1, input=2*, para=3*}
    f = _decode_message(buf)
    t = _int32(f[1][0])
    return {
        "_t": "Operator",
        "type": OP_NAMES[t] if 0 <= t < len(OP_NAMES) else t,
        "input": [_tensor(b) for b in f.get(2, [])],
        "para": [_parameter(b) for b in f.get(3, [])],
    }


def _map_output(buf):  # MapOutput {srcOpId=1, dstOpId=2, srcTsId=3, dstTsId=4}
    f = _decode_message(buf)
    return {
        "_t": "MapOutput",
        "srcOpId": _int32(f[1][0]), "dstOpId": _int32(f[2][0]),
        "srcTsId": _int32(f[3][0]), "dstTsId": _int32(f[4][0]),
    }


def _rule(buf, idx):  # Rule {srcOp=1*, dstOp=2*, mappedOutput=3*}
    f = _decode_message(buf)
    return {
        "_t": "Rule",
        # same naming as the reference converter's output, so rule names in
        # exported strategy files are interchangeable between the two
        "name": f"taso_rule_{idx}",
        "srcOp": [_operator(b) for b in f.get(1, [])],
        "dstOp": [_operator(b) for b in f.get(2, [])],
        "mappedOutput": [_map_output(b) for b in f.get(3, [])],
    }


def convert(pb_bytes: bytes) -> dict:
    top = _decode_message(pb_bytes)  # RuleCollection {rule=1*}
    return {
        "_t": "RuleCollection",
        "rule": [_rule(b, i) for i, b in enumerate(top.get(1, []))],
    }


def main(argv):
    if len(argv) != 2:
        print(f"Usage: {argv[0]} <rules.pb>", file=sys.stderr)
        return 1
    with open(argv[1], "rb") as f:
        doc = convert(f.read())
    json.dump(doc, sys.stdout, indent=1)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
