#!/usr/bin/env python
"""AST lint for repo-specific invariants ruff cannot express.

Four rules, each with its own allowlist of known, deliberate
exceptions (relative paths from the repo root). Run from the repo
root; exits non-zero when any un-allowlisted violation is found.
Wired into .github/workflows/lint.yml next to ruff.

Rules
-----
host-sync
    `.item()` calls and `np.asarray(...)` / `numpy.asarray(...)` in
    `flexflow_tpu/kernels/**` and `flexflow_tpu/runtime/**`. Both
    force a device->host transfer and block the async dispatch queue
    when they sneak into jitted or lowering code paths
    (docs/observability.md "host sync"). `jnp.asarray` is fine — the
    receiver name is checked, not the attribute alone.

metric-help
    `REGISTRY.counter(...)` / `.gauge(...)` / `.histogram(...)` must
    pass a help string (second positional arg or `help=`). A bare
    name registers a metric that renders without HELP text on the
    /metrics endpoint and defeats the catalogue test in
    tests/test_obs.py.

span-discipline
    A call whose attribute is `.span(...)` must be the context
    expression of a `with` statement (directly or via `as`). A span
    opened outside `with` is never closed on an exception path and
    skews every enclosing duration (obs/tracing.py).

event-docs
    Cross-file: every event-kind constant `flexflow_tpu/elastic/
    events.py` declares (uppercase module-level string assignment)
    must appear as a row of the "Event-kind catalogue" table in
    docs/observability.md, and every kind row in that table must be a
    declared constant — both directions, so the catalogue can never
    drift from the code (post-mortem consumers grep the docs for what
    a kind means; the FlightRecorder's trigger kinds live there too).

Usage:  python tools/lint_invariants.py [--list] [paths...]
"""
from __future__ import annotations

import ast
import re
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

REPO = Path(__file__).resolve().parent.parent

# Paths (relative, posix) where a rule is deliberately waived. Keep a
# short justification next to every entry — an entry without a reason
# should be treated as a bug in the allowlist, not in the code.
ALLOWLIST: Dict[str, Dict[str, str]] = {
    "host-sync": {
        # host-side checkpoint serialisation: runs outside jit by design
        "flexflow_tpu/runtime/checkpoint.py":
            "checkpoint save/restore is an explicit host boundary",
        # fetch_weights' documented device->host materialisation point
        "flexflow_tpu/runtime/executor.py":
            "_host_fetch is the one sanctioned device->host edge",
    },
    "metric-help": {},
    "event-docs": {},
    "span-discipline": {
        # the span() helper RETURNS the context manager for callers
        "flexflow_tpu/obs/tracing.py":
            "defines the span() accessor that callers `with`",
    },
}

HOST_SYNC_SCOPES = ("flexflow_tpu/kernels/", "flexflow_tpu/runtime/")
METRIC_METHODS = {"counter", "gauge", "histogram"}

EVENTS_PY = "flexflow_tpu/elastic/events.py"
EVENT_DOCS_MD = "docs/observability.md"
EVENT_DOCS_HEADING = "### Event-kind catalogue"
# a kind cell: the first backticked token of a table row
_KIND_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_.]+)`")


class Violation(Tuple[str, str, int, str]):
    """(rule, relpath, lineno, message)."""


def _with_context_calls(tree: ast.AST) -> set:
    """id()s of Call nodes used as a with-statement context expr."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    out.add(id(item.context_expr))
    return out


def _receiver_name(func: ast.Attribute) -> str:
    """Dotted receiver of an attribute call, best-effort."""
    parts: List[str] = []
    cur: ast.expr = func.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def lint_file(path: Path, rel: str) -> List[Tuple[str, str, int, str]]:
    try:
        tree = ast.parse(path.read_text(), filename=rel)
    except SyntaxError as exc:  # compileall catches these too, but be loud
        return [("parse", rel, exc.lineno or 0, f"syntax error: {exc.msg}")]

    findings: List[Tuple[str, str, int, str]] = []
    in_host_scope = any(rel.startswith(s) for s in HOST_SYNC_SCOPES)
    with_calls = _with_context_calls(tree)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        attr = func.attr

        if in_host_scope and attr == "item" and not node.args \
                and not node.keywords:
            findings.append((
                "host-sync", rel, node.lineno,
                ".item() forces a device->host sync; hoist it out of the"
                " kernels/runtime hot path"))
        if in_host_scope and attr == "asarray":
            recv = _receiver_name(func)
            if recv in ("np", "numpy"):
                findings.append((
                    "host-sync", rel, node.lineno,
                    f"{recv}.asarray() materialises on host; use"
                    f" jnp.asarray or move it behind the host boundary"))

        if attr in METRIC_METHODS and \
                _receiver_name(func).endswith("REGISTRY"):
            has_help = len(node.args) >= 2 or \
                any(k.arg == "help" for k in node.keywords)
            if not has_help:
                findings.append((
                    "metric-help", rel, node.lineno,
                    f"REGISTRY.{attr}() without a help string; metrics"
                    f" must self-describe on /metrics"))

        if attr == "span" and id(node) not in with_calls:
            findings.append((
                "span-discipline", rel, node.lineno,
                ".span() opened outside a `with` block leaks on the"
                " exception path"))

    return findings


def lint_event_docs() -> List[Tuple[str, str, int, str]]:
    """Cross-file rule: elastic/events.py kind constants <-> the
    docs/observability.md "Event-kind catalogue" table, both ways."""
    events_path = REPO / EVENTS_PY
    docs_path = REPO / EVENT_DOCS_MD
    findings: List[Tuple[str, str, int, str]] = []

    tree = ast.parse(events_path.read_text(), filename=EVENTS_PY)
    declared: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.isupper() \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            declared[node.value.value] = node.lineno

    documented: Dict[str, int] = {}
    in_section = False
    heading_line = 0
    for lineno, line in enumerate(docs_path.read_text().splitlines(), 1):
        if line.strip() == EVENT_DOCS_HEADING:
            in_section = True
            heading_line = lineno
            continue
        if in_section and line.startswith("#"):
            break  # next heading of any level ends the catalogue
        if in_section:
            m = _KIND_ROW_RE.match(line)
            if m:
                documented[m.group(1)] = lineno
    if not heading_line:
        return [("event-docs", EVENT_DOCS_MD, 1,
                 f"missing the {EVENT_DOCS_HEADING!r} section that"
                 f" catalogues {EVENTS_PY} kind constants")]

    for kind, lineno in sorted(declared.items(), key=lambda kv: kv[1]):
        if kind not in documented:
            findings.append((
                "event-docs", EVENTS_PY, lineno,
                f"event kind {kind!r} is not documented in the"
                f" {EVENT_DOCS_MD} event-kind catalogue"))
    for kind, lineno in sorted(documented.items(), key=lambda kv: kv[1]):
        if kind not in declared:
            findings.append((
                "event-docs", EVENT_DOCS_MD, lineno,
                f"catalogued kind {kind!r} matches no constant in"
                f" {EVENTS_PY} (stale doc row?)"))
    return findings


def iter_files(paths: Iterable[str]) -> Iterable[Path]:
    for p in paths:
        base = (REPO / p) if not Path(p).is_absolute() else Path(p)
        if base.is_file():
            yield base
        else:
            yield from sorted(base.rglob("*.py"))


def main(argv: List[str]) -> int:
    list_only = "--list" in argv
    argv = [a for a in argv if a != "--list"]
    roots = argv or ["flexflow_tpu"]

    violations = []
    waived = 0
    per_file = [(f, lint_file(f, f.resolve().relative_to(REPO).as_posix()))
                for f in iter_files(roots)]
    cross = lint_event_docs() \
        if (REPO / EVENTS_PY).exists() and (REPO / EVENT_DOCS_MD).exists() \
        else []
    for rule, relpath, line, msg in \
            [v for _, vs in per_file for v in vs] + cross:
        if relpath in ALLOWLIST.get(rule, {}):
            waived += 1
            continue
        violations.append((rule, relpath, line, msg))

    for rule, relpath, line, msg in violations:
        print(f"{relpath}:{line}: [{rule}] {msg}")
    if list_only:
        return 0
    if violations:
        print(f"lint_invariants: {len(violations)} violation(s)"
              f" ({waived} allowlisted)", file=sys.stderr)
        return 1
    print(f"lint_invariants: clean ({waived} allowlisted)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
