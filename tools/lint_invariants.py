#!/usr/bin/env python
"""AST lint for repo-specific invariants ruff cannot express.

Three rules, each with its own allowlist of known, deliberate
exceptions (relative paths from the repo root). Run from the repo
root; exits non-zero when any un-allowlisted violation is found.
Wired into .github/workflows/lint.yml next to ruff.

Rules
-----
host-sync
    `.item()` calls and `np.asarray(...)` / `numpy.asarray(...)` in
    `flexflow_tpu/kernels/**` and `flexflow_tpu/runtime/**`. Both
    force a device->host transfer and block the async dispatch queue
    when they sneak into jitted or lowering code paths
    (docs/observability.md "host sync"). `jnp.asarray` is fine — the
    receiver name is checked, not the attribute alone.

metric-help
    `REGISTRY.counter(...)` / `.gauge(...)` / `.histogram(...)` must
    pass a help string (second positional arg or `help=`). A bare
    name registers a metric that renders without HELP text on the
    /metrics endpoint and defeats the catalogue test in
    tests/test_obs.py.

span-discipline
    A call whose attribute is `.span(...)` must be the context
    expression of a `with` statement (directly or via `as`). A span
    opened outside `with` is never closed on an exception path and
    skews every enclosing duration (obs/tracing.py).

Usage:  python tools/lint_invariants.py [--list] [paths...]
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

REPO = Path(__file__).resolve().parent.parent

# Paths (relative, posix) where a rule is deliberately waived. Keep a
# short justification next to every entry — an entry without a reason
# should be treated as a bug in the allowlist, not in the code.
ALLOWLIST: Dict[str, Dict[str, str]] = {
    "host-sync": {
        # host-side checkpoint serialisation: runs outside jit by design
        "flexflow_tpu/runtime/checkpoint.py":
            "checkpoint save/restore is an explicit host boundary",
        # fetch_weights' documented device->host materialisation point
        "flexflow_tpu/runtime/executor.py":
            "_host_fetch is the one sanctioned device->host edge",
    },
    "metric-help": {},
    "span-discipline": {
        # the span() helper RETURNS the context manager for callers
        "flexflow_tpu/obs/tracing.py":
            "defines the span() accessor that callers `with`",
    },
}

HOST_SYNC_SCOPES = ("flexflow_tpu/kernels/", "flexflow_tpu/runtime/")
METRIC_METHODS = {"counter", "gauge", "histogram"}


class Violation(Tuple[str, str, int, str]):
    """(rule, relpath, lineno, message)."""


def _with_context_calls(tree: ast.AST) -> set:
    """id()s of Call nodes used as a with-statement context expr."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    out.add(id(item.context_expr))
    return out


def _receiver_name(func: ast.Attribute) -> str:
    """Dotted receiver of an attribute call, best-effort."""
    parts: List[str] = []
    cur: ast.expr = func.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def lint_file(path: Path, rel: str) -> List[Tuple[str, str, int, str]]:
    try:
        tree = ast.parse(path.read_text(), filename=rel)
    except SyntaxError as exc:  # compileall catches these too, but be loud
        return [("parse", rel, exc.lineno or 0, f"syntax error: {exc.msg}")]

    findings: List[Tuple[str, str, int, str]] = []
    in_host_scope = any(rel.startswith(s) for s in HOST_SYNC_SCOPES)
    with_calls = _with_context_calls(tree)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        attr = func.attr

        if in_host_scope and attr == "item" and not node.args \
                and not node.keywords:
            findings.append((
                "host-sync", rel, node.lineno,
                ".item() forces a device->host sync; hoist it out of the"
                " kernels/runtime hot path"))
        if in_host_scope and attr == "asarray":
            recv = _receiver_name(func)
            if recv in ("np", "numpy"):
                findings.append((
                    "host-sync", rel, node.lineno,
                    f"{recv}.asarray() materialises on host; use"
                    f" jnp.asarray or move it behind the host boundary"))

        if attr in METRIC_METHODS and \
                _receiver_name(func).endswith("REGISTRY"):
            has_help = len(node.args) >= 2 or \
                any(k.arg == "help" for k in node.keywords)
            if not has_help:
                findings.append((
                    "metric-help", rel, node.lineno,
                    f"REGISTRY.{attr}() without a help string; metrics"
                    f" must self-describe on /metrics"))

        if attr == "span" and id(node) not in with_calls:
            findings.append((
                "span-discipline", rel, node.lineno,
                ".span() opened outside a `with` block leaks on the"
                " exception path"))

    return findings


def iter_files(paths: Iterable[str]) -> Iterable[Path]:
    for p in paths:
        base = (REPO / p) if not Path(p).is_absolute() else Path(p)
        if base.is_file():
            yield base
        else:
            yield from sorted(base.rglob("*.py"))


def main(argv: List[str]) -> int:
    list_only = "--list" in argv
    argv = [a for a in argv if a != "--list"]
    roots = argv or ["flexflow_tpu"]

    violations = []
    waived = 0
    for f in iter_files(roots):
        rel = f.resolve().relative_to(REPO).as_posix()
        for rule, relpath, line, msg in lint_file(f, rel):
            if relpath in ALLOWLIST.get(rule, {}):
                waived += 1
                continue
            violations.append((rule, relpath, line, msg))

    for rule, relpath, line, msg in violations:
        print(f"{relpath}:{line}: [{rule}] {msg}")
    if list_only:
        return 0
    if violations:
        print(f"lint_invariants: {len(violations)} violation(s)"
              f" ({waived} allowlisted)", file=sys.stderr)
        return 1
    print(f"lint_invariants: clean ({waived} allowlisted)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
