"""Speculative draft-verify decoding through the continuous batcher
(ISSUE 14): greedy output is TOKEN-IDENTICAL to non-speculative greedy
regardless of the draft (tied, untied, at EOS, under slot reuse, on
prefix-cache hits, across a mesh resize), acceptance accounting, the
accepted-token EWMA normalization, and the repository's draft entry.
"""
import numpy as np
import pytest

from flexflow_tpu.serving.sched import ContinuousBatcher
from tests.conftest import module_xla_cache
from tests.test_generate import _build_lm

# module-scoped XLA compilation cache — see conftest.module_xla_cache
_xla_cache = pytest.fixture(scope="module", autouse=True)(module_xla_cache)

MAX_LEN = 40
SLOTS = 3


@pytest.fixture(scope="module")
def target():
    return _build_lm(SLOTS, 12)


@pytest.fixture(scope="module")
def tied_draft(target):
    """Same architecture, the TARGET's weights: acceptance ~1.0 by
    construction."""
    d = _build_lm(SLOTS, 12)
    d.params = target.params
    return d


@pytest.fixture(scope="module")
def untied_draft():
    """A genuinely different (smaller) draft: low/zero acceptance, but
    parity must hold anyway — the verify step, not the draft, decides
    every emitted token."""
    return _build_lm(SLOTS, 12, hidden=16, heads=2, layers=1)


def _prompts(lens, seed=0, vocab=50):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=(n,)).astype(np.int32) for n in lens]


def _run(model, draft, work, k=3, eos_id=None, registry=None,
         prefix_pages=0, **kw):
    b = ContinuousBatcher(model, max_len=MAX_LEN, num_slots=SLOTS,
                          page_size=4, max_queue=16,
                          prefix_cache_pages=prefix_pages,
                          draft_model=draft, spec_tokens=k,
                          registry=registry, **kw)
    with b:
        hs = [b.submit(p, n, eos_id=eos_id) for p, n in work]
        outs = [h.result(timeout=300.0).tolist() for h in hs]
    return outs, b.stats(), hs


def test_spec_parity_tied_draft_slot_reuse(target, tied_draft):
    """7 requests through 3 slots: parity under slot reuse, and a tied
    draft verifies at acceptance 1.0 (the raw verify matches, not the
    emission cap's m-1)."""
    work = [(p, 8) for p in _prompts((4, 7, 3, 9, 5, 6, 2), seed=1)]
    plain, _, _ = _run(target, None, work)
    spec, st, _ = _run(target, tied_draft, work)
    assert spec == plain
    assert st["spec"]["acceptance"] == 1.0
    assert st["spec"]["proposed"] > 0


def test_spec_parity_untied_draft(target, untied_draft):
    work = [(p, 8) for p in _prompts((4, 7, 3, 9), seed=2)]
    plain, _, _ = _run(target, None, work)
    spec, st, _ = _run(target, untied_draft, work)
    assert spec == plain
    # acceptance is whatever the draft earns — only the ACCOUNTING is
    # pinned (proposed counts k per active slot per iteration)
    assert st["spec"]["proposed"] >= st["spec"]["accepted"] >= 0


def test_spec_eos_early_stop(target, tied_draft):
    """EOS inside an accepted speculation window retires the request at
    the same token as plain greedy — the rest of the window is
    discarded."""
    work = [(p, 12) for p in _prompts((5, 3), seed=3)]
    plain, _, _ = _run(target, None, work)
    # pick an EOS that plain decode actually emits mid-stream
    eos = plain[0][2]
    plain_eos, _, _ = _run(target, None, work, eos_id=eos)
    spec_eos, _, _ = _run(target, tied_draft, work, eos_id=eos)
    assert spec_eos == plain_eos
    assert len(plain_eos[0]) < 12  # it genuinely stopped early


def test_spec_prefix_cache_hit_parity(target, tied_draft):
    """Prefix-cache hits under speculation: the TARGET installs cached
    pages (only the suffix prefills), the draft re-prefills the whole
    prompt, and the output stays token-identical to plain greedy with
    the same cache. Followers must actually hit."""
    rng = np.random.RandomState(4)
    prefix = rng.randint(1, 50, size=(8,)).astype(np.int32)
    work = [(np.concatenate([prefix,
                             rng.randint(1, 50, size=(n,)).astype(
                                 np.int32)]), 6)
            for n in (3, 2, 4)]
    pages = 24

    def run(draft):
        b = ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                              page_size=4, max_queue=16,
                              prefix_cache_pages=pages,
                              draft_model=draft, spec_tokens=3)
        with b:
            # leader first (cold, inserts the prefix pages), then the
            # followers — who must hit
            lead = b.submit(*work[0])
            first = lead.result(timeout=300.0).tolist()
            hs = [b.submit(p, n) for p, n in work[1:]]
            outs = [first] + [h.result(timeout=300.0).tolist()
                              for h in hs]
        return outs, lead, hs

    plain, _, _ = run(None)
    spec, lead, hs = run(tied_draft)
    assert spec == plain
    # the leader misses, the followers hit (page-aligned prefix = 2
    # pages of 4)
    assert not lead.cache_hit
    assert all(h.cache_hit for h in hs)


def test_spec_resize_parity_migrates_draft_caches(target, tied_draft):
    """A mid-decode shrink + grow-back under speculation: the draft's
    slot-dense caches migrate with the target's (same owned-row spans),
    and every request's greedy tokens survive the topology change."""
    work = [(p, 14) for p in _prompts((4, 6, 3), seed=5)]
    ref, _, _ = _run(target, tied_draft, work)

    b = ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                          page_size=4, max_queue=16, prefix_cache_pages=0,
                          draft_model=tied_draft, spec_tokens=3)
    import time

    with b:
        hs = [b.submit(p, n) for p, n in work]
        deadline = time.monotonic() + 300.0
        while not any(h.tokens for h in hs):
            if time.monotonic() > deadline:
                raise RuntimeError("no tokens before resize")
            time.sleep(0.005)
        shrink = b.request_resize(2).wait(timeout=300.0)
        grow = b.request_resize(SLOTS).wait(timeout=300.0)
        outs = [h.result(timeout=300.0).tolist() for h in hs]
    assert outs == ref
    assert shrink["direction"] == "shrink" and grow["direction"] == "grow"
    assert shrink["migrated_rows"] > 0


def test_spec_metrics_and_predicted_ttft_drain_horizon(target,
                                                       tied_draft):
    """The new ff_spec_decode_* families render, and predicted_ttft_s
    counts ACCEPTED TOKENS per iteration (satellite): the interleave leg
    charges full decode walls, but no more of them than the decode
    drain horizon — budgets retire at k_eff = 1 + acceptance*k tokens
    per wall, so a speculative batcher must not over-predict TTFT and
    shed servable traffic."""
    import math

    from flexflow_tpu.obs.registry import MetricsRegistry
    from flexflow_tpu.serving.sched.continuous import GenRequest

    reg = MetricsRegistry()
    work = [(p, 8) for p in _prompts((4, 5), seed=6)]
    _, st, _ = _run(target, tied_draft, work, registry=reg)
    text = reg.render()
    assert "ff_spec_decode_proposed_total" in text
    assert "ff_spec_decode_accepted_total" in text
    assert "ff_spec_decode_acceptance" in text
    assert st["spec"]["accepted"] > 0
    # the draft's prefill dispatches were MEASURED (draft-aware
    # admission samples the final synced draft chunk per request)
    assert st["draft_prefill_s_per_token"] is not None
    assert st["draft_prefill_s_per_token"] > 0

    # unit: a not-started speculative batcher with a fabricated queued
    # request and measured EWMAs. Full acceptance -> k_eff = k = 3, so
    # a 30-token budget drains in 10 walls: the interleave leg charges
    # min(ceil(total/chunk), 10) * RAW wall, where plain accounting
    # would charge every chunk a wall.
    def mk(draft, k_eff_expect):
        b = ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                              page_size=4, registry=MetricsRegistry(),
                              draft_model=draft, spec_tokens=3,
                              max_queue=8)
        b._ewma_prefill_s_per_tok = 0.01
        b._observe_decode_iter(0.3)
        assert b.stats()["decode_iter_s"] == pytest.approx(0.3)  # RAW
        b._ewma_spec_accept = 1.0
        b._queue.append(GenRequest(0, np.zeros(4, np.int32), 30,
                                   None, 0))
        assert b._decode_drain_iterations() == math.ceil(
            30 / k_eff_expect)
        return b

    b = mk(tied_draft, 3.0)
    own = 60
    total = own + 4
    chunk = b.prefill_chunk_tokens
    # draft-aware admission (PR 15 satellite): the prefill leg credits
    # the draft's doubled prefill dispatches — every prompt token (own
    # AND backlog) prefills through the draft's chunk stream too, at
    # the draft's measured per-token cost (falls back to the target's
    # until the first draft sample lands)
    want = (own * 0.01 + 4 * 0.01 + total * 0.01
            + min(math.ceil(total / chunk), 10) * 0.3)
    assert b.predicted_ttft_s(own) == pytest.approx(want)
    # a measured draft EWMA replaces the fallback in the credit term
    b._observe_draft_prefill(10, 0.05)  # 0.005 s/token
    want_measured = (own * 0.01 + 4 * 0.01 + total * 0.005
                     + min(math.ceil(total / chunk), 10) * 0.3)
    assert b.predicted_ttft_s(own) == pytest.approx(want_measured)

    # plain batcher: every chunk pays a wall (historical semantics)
    p = ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                          page_size=4, registry=MetricsRegistry(),
                          max_queue=8)
    p._ewma_prefill_s_per_tok = 0.01
    p._observe_decode_iter(0.3)
    p._queue.append(GenRequest(0, np.zeros(4, np.int32), 30, None, 0))
    want_plain = (own + 4) * 0.01 + math.ceil((own + 4) / chunk) * 0.3
    assert p.predicted_ttft_s(own) == pytest.approx(want_plain)


def test_spec_constructor_validation(target, tied_draft):
    with pytest.raises(ValueError, match="greedy-only"):
        ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                          page_size=4, temperature=0.7,
                          draft_model=tied_draft, spec_tokens=3)
    with pytest.raises(ValueError, match="chunked prefill"):
        ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                          page_size=4, prefill_chunk_tokens=0,
                          draft_model=tied_draft, spec_tokens=3)
    with pytest.raises(ValueError, match="spec_tokens"):
        ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                          page_size=4, draft_model=tied_draft,
                          spec_tokens=0)
    with pytest.raises(ValueError, match="window"):
        ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                          page_size=4, draft_model=tied_draft,
                          spec_tokens=99)
    bad_vocab = _build_lm(SLOTS, 12, vocab=17, hidden=16, heads=2,
                          layers=1)
    with pytest.raises(ValueError, match="vocab"):
        ContinuousBatcher(target, max_len=MAX_LEN, num_slots=SLOTS,
                          page_size=4, draft_model=bad_vocab,
                          spec_tokens=3)


def test_repository_speculative_entry(target, tied_draft):
    """A fleet entry with serving.speculative wires the draft into every
    replica's batcher (draft shared, per-replica draft caches)."""
    from flexflow_tpu.serving import InferenceServer
    from flexflow_tpu.serving.repository import ModelRepository

    server = InferenceServer()
    try:
        ModelRepository._register_fleet(
            server, "lm", target,
            {"mode": "fleet", "replicas": 2, "max_len": MAX_LEN,
             "num_slots": 2, "page_size": 4,
             "speculative": {"draft": "lm_draft", "tokens": 2}},
            draft=tied_draft)
        router = server._fleets["lm"]
        assert router.replica_names() == ["r0", "r1"]
        for name in router.replica_names():
            batcher = router._replicas[name].batcher
            assert batcher.draft_model is tied_draft
            assert batcher.spec_tokens == 2
        out = server.generate("lm", [[1, 2, 3]], 4)
        assert [len(t) for t in out] == [4]
    finally:
        server.shutdown()
