"""Per-op numerical alignment vs PyTorch CPU.

TPU-native analog of the reference's tests/align/ (FF-vs-PyTorch tensor
diffing, tests/align/README.md) and tests/ops/ harness (numpy/PyTorch
reference results, tests/ops/test_harness.py:20-30) — but in-process: each op
runs through the public FFModel API on the CPU mesh and its forward output
(and, for key ops, input/weight gradients) is compared against torch.
"""
import numpy as np
import pytest
import torch

import flexflow_tpu as ff
from flexflow_tpu.ffconst import CompMode

RTOL, ATOL = 2e-4, 2e-5


def run_forward(build, inputs, batch_size=None, mode=CompMode.COMP_MODE_INFERENCE,
                weights=None):
    """build(model, input_tensors) -> output tensor. Returns (np output, model)."""
    config = ff.FFConfig()
    config.batch_size = batch_size or inputs[0].shape[0]
    config.allow_mixed_precision = False  # exact f32 for alignment
    model = ff.FFModel(config)
    tins = []
    for arr in inputs:
        dt = (
            ff.DataType.DT_INT32 if arr.dtype.kind in "iu" else ff.DataType.DT_FLOAT
        )
        tins.append(model.create_tensor(arr.shape, dt))
    out = build(model, tins)
    model.final_tensor = out
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.0),
        loss_type=ff.LossType.LOSS_IDENTITY,
    )
    if weights:
        for op_name, wdict in weights.items():
            for wname, val in wdict.items():
                import jax.numpy as jnp

                model.params[op_name][wname] = jnp.asarray(val)
    feeds = {op.name: arr for op, arr in zip(model.input_ops, inputs)}
    values, _, _ = model.executor.forward_values(
        model.params, model.state, feeds, None, mode
    )
    return np.asarray(values[out.guid]), model


def assert_close(a, b, rtol=RTOL, atol=ATOL):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def test_linear_forward():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 32).astype(np.float32)
    w = rng.randn(32, 16).astype(np.float32)
    b = rng.randn(16).astype(np.float32)

    def build(m, tins):
        return m.dense(tins[0], 16, name="lin")

    out, _ = run_forward(build, [x], weights={"lin": {"kernel": w, "bias": b}})
    assert_close(out, x @ w + b)


def test_linear_relu_forward():
    rng = np.random.RandomState(1)
    x = rng.randn(8, 32).astype(np.float32)
    w = rng.randn(32, 16).astype(np.float32)
    b = rng.randn(16).astype(np.float32)

    def build(m, tins):
        return m.dense(tins[0], 16, ff.ActiMode.AC_MODE_RELU, name="lin")

    out, _ = run_forward(build, [x], weights={"lin": {"kernel": w, "bias": b}})
    assert_close(out, np.maximum(x @ w + b, 0))


def test_conv2d_forward_vs_torch():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 16, 16).astype(np.float32)
    w = rng.randn(8, 3, 3, 3).astype(np.float32)
    b = rng.randn(8).astype(np.float32)

    def build(m, tins):
        return m.conv2d(tins[0], 8, 3, 3, 2, 2, 1, 1, name="conv")

    out, _ = run_forward(build, [x], weights={"conv": {"kernel": w, "bias": b}})
    ref = torch.nn.functional.conv2d(
        torch.tensor(x), torch.tensor(w), torch.tensor(b), stride=2, padding=1
    ).numpy()
    assert_close(out, ref, rtol=1e-3, atol=1e-4)


def test_pool2d_forward_vs_torch():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, 8, 8).astype(np.float32)

    def build_max(m, tins):
        return m.pool2d(tins[0], 2, 2, 2, 2, 0, 0)

    out, _ = run_forward(build_max, [x])
    ref = torch.nn.functional.max_pool2d(torch.tensor(x), 2, 2).numpy()
    assert_close(out, ref)

    def build_avg(m, tins):
        return m.pool2d(tins[0], 2, 2, 2, 2, 0, 0, ff.PoolType.POOL_AVG)

    out, _ = run_forward(build_avg, [x])
    ref = torch.nn.functional.avg_pool2d(torch.tensor(x), 2, 2).numpy()
    assert_close(out, ref)


def test_layernorm_vs_torch():
    rng = np.random.RandomState(4)
    x = rng.randn(4, 10, 32).astype(np.float32)

    def build(m, tins):
        return m.layer_norm(tins[0], [-1], name="ln")

    out, _ = run_forward(build, [x])
    ref = torch.nn.functional.layer_norm(torch.tensor(x), (32,)).numpy()
    assert_close(out, ref, rtol=1e-3, atol=1e-4)


def test_batchnorm_inference_vs_torch():
    rng = np.random.RandomState(5)
    x = rng.randn(4, 6, 5, 5).astype(np.float32)

    def build(m, tins):
        return m.batch_norm(tins[0], relu=False, name="bn")

    # inference mode uses running stats (0 mean, 1 var) -> identity*gamma+beta
    out, _ = run_forward(build, [x])
    ref = torch.nn.functional.batch_norm(
        torch.tensor(x), torch.zeros(6), torch.ones(6), eps=1e-5
    ).numpy()
    assert_close(out, ref, rtol=1e-3, atol=1e-4)


def test_softmax_vs_torch():
    rng = np.random.RandomState(6)
    x = rng.randn(4, 10).astype(np.float32)

    def build(m, tins):
        return m.softmax(tins[0])

    out, _ = run_forward(build, [x])
    assert_close(out, torch.softmax(torch.tensor(x), -1).numpy())


def test_unary_ops_vs_torch():
    rng = np.random.RandomState(7)
    x = (rng.randn(4, 8).astype(np.float32) * 0.5)
    cases = {
        "relu": (lambda m, t: m.relu(t), torch.relu),
        "sigmoid": (lambda m, t: m.sigmoid(t), torch.sigmoid),
        "tanh": (lambda m, t: m.tanh(t), torch.tanh),
        "gelu": (lambda m, t: m.gelu(t), torch.nn.functional.gelu),
        "elu": (lambda m, t: m.elu(t), torch.nn.functional.elu),
        "exp": (lambda m, t: m.exp(t), torch.exp),
        "sin": (lambda m, t: m.sin(t), torch.sin),
        "cos": (lambda m, t: m.cos(t), torch.cos),
    }
    for name, (build_fn, torch_fn) in cases.items():
        out, _ = run_forward(lambda m, tins: build_fn(m, tins[0]), [x])
        ref = torch_fn(torch.tensor(x)).numpy()
        # jax gelu default is tanh-approx; torch default is erf — use loose tol
        tol = 2e-3 if name == "gelu" else RTOL
        assert_close(out, ref, rtol=tol, atol=tol)


def test_binary_ops():
    rng = np.random.RandomState(8)
    a = rng.randn(4, 8).astype(np.float32)
    b = rng.randn(4, 8).astype(np.float32) + 2.0
    cases = {
        "add": (lambda m, x, y: m.add(x, y), a + b),
        "sub": (lambda m, x, y: m.subtract(x, y), a - b),
        "mul": (lambda m, x, y: m.multiply(x, y), a * b),
        "div": (lambda m, x, y: m.divide(x, y), a / b),
        "max": (lambda m, x, y: m.max(x, y), np.maximum(a, b)),
        "min": (lambda m, x, y: m.min(x, y), np.minimum(a, b)),
    }
    for name, (fn, ref) in cases.items():
        out, _ = run_forward(lambda m, tins: fn(m, tins[0], tins[1]), [a, b])
        assert_close(out, ref)


def test_embedding_modes_vs_torch():
    rng = np.random.RandomState(9)
    ids = rng.randint(0, 50, (4, 6)).astype(np.int32)
    table = rng.randn(50, 8).astype(np.float32)

    def build_none(m, tins):
        return m.embedding(tins[0], 50, 8, ff.AggrMode.AGGR_MODE_NONE, name="emb")

    out, _ = run_forward(build_none, [ids], weights={"emb": {"weight": table}})
    assert_close(out, table[ids])

    def build_sum(m, tins):
        return m.embedding(tins[0], 50, 8, ff.AggrMode.AGGR_MODE_SUM, name="emb")

    out, _ = run_forward(build_sum, [ids], weights={"emb": {"weight": table}})
    assert_close(out, table[ids].sum(axis=1), rtol=1e-3, atol=1e-4)


def test_attention_vs_torch():
    rng = np.random.RandomState(10)
    B, L, E, H = 2, 6, 16, 4
    x = rng.randn(B, L, E).astype(np.float32)

    def build(m, tins):
        return m.multihead_attention(tins[0], tins[0], tins[0], E, H, bias=False,
                                     name="attn")

    out, model = run_forward(build, [x])
    # replicate with torch using our packed weights
    wq = model.get_parameter_by_id("attn", "wq")  # (E, H, D)
    wk = model.get_parameter_by_id("attn", "wk")
    wv = model.get_parameter_by_id("attn", "wv")
    wo = model.get_parameter_by_id("attn", "wo")  # (H, D, E)
    D = E // H
    tx = torch.tensor(x)
    q = torch.einsum("ble,ehd->blhd", tx, torch.tensor(wq))
    k = torch.einsum("ble,ehd->blhd", tx, torch.tensor(wk))
    v = torch.einsum("ble,ehd->blhd", tx, torch.tensor(wv))
    logits = torch.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    probs = torch.softmax(logits, -1)
    ctx = torch.einsum("bhqk,bkhd->bqhd", probs, v)
    ref = torch.einsum("bqhd,hde->bqe", ctx, torch.tensor(wo)).numpy()
    assert_close(out, ref, rtol=1e-3, atol=1e-4)


def test_shape_ops():
    rng = np.random.RandomState(11)
    x = rng.randn(4, 6, 8).astype(np.float32)

    out, _ = run_forward(lambda m, t: m.reshape(t[0], (4, 48)), [x])
    assert_close(out, x.reshape(4, 48))

    out, _ = run_forward(lambda m, t: m.transpose(t[0], (0, 2, 1)), [x])
    assert_close(out, x.transpose(0, 2, 1))

    out, _ = run_forward(lambda m, t: m.reverse(t[0], 1), [x])
    assert_close(out, x[:, ::-1, :])

    out, _ = run_forward(lambda m, t: m.flat(t[0]), [x])
    assert_close(out, x.reshape(4, 48))

    a = rng.randn(4, 3).astype(np.float32)
    b = rng.randn(4, 5).astype(np.float32)
    out, _ = run_forward(lambda m, t: m.concat([t[0], t[1]], 1), [a, b])
    assert_close(out, np.concatenate([a, b], 1))

    out, _ = run_forward(lambda m, t: m.split(t[0], [2, 6], 2)[1], [x])
    assert_close(out, x[:, :, 2:])

    out, _ = run_forward(lambda m, t: m.reduce_sum(t[0], [1]), [x])
    assert_close(out, x.sum(1), rtol=1e-3, atol=1e-4)

    out, _ = run_forward(lambda m, t: m.mean(t[0], [1, 2]), [x])
    assert_close(out, x.mean((1, 2)), rtol=1e-3, atol=1e-4)


def test_gather_vs_torch():
    rng = np.random.RandomState(12)
    x = rng.randn(4, 8).astype(np.float32)
    idx = rng.randint(0, 8, (4, 3)).astype(np.int32)
    out, _ = run_forward(lambda m, t: m.gather(t[0], t[1], 1), [x, idx])
    ref = torch.gather(torch.tensor(x), 1, torch.tensor(idx).long()).numpy()
    assert_close(out, ref)


def test_batch_matmul():
    rng = np.random.RandomState(13)
    a = rng.randn(3, 4, 5).astype(np.float32)
    b = rng.randn(3, 5, 6).astype(np.float32)
    out, _ = run_forward(lambda m, t: m.batch_matmul(t[0], t[1]), [a, b])
    assert_close(out, a @ b, rtol=1e-3, atol=1e-4)


def test_topk():
    rng = np.random.RandomState(14)
    x = rng.randn(4, 10).astype(np.float32)
    out, _ = run_forward(lambda m, t: m.top_k(t[0], 3)[0], [x])
    ref = torch.topk(torch.tensor(x), 3).values.numpy()
    assert_close(out, ref)


def test_linear_gradients_vs_torch():
    """Backward parity: d loss/d weights matches torch autograd
    (reference analog: align_test.py gradient comparison)."""
    rng = np.random.RandomState(15)
    x = rng.randn(8, 12).astype(np.float32)
    w = rng.randn(12, 6).astype(np.float32)
    b = np.zeros(6, np.float32)
    y = rng.randint(0, 6, (8, 1)).astype(np.int32)

    config = ff.FFConfig()
    config.batch_size = 8
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 12])
    t = model.dense(inp, 6, name="lin")
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.0),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    )
    import jax.numpy as jnp

    model.params["lin"]["kernel"] = jnp.asarray(w)
    model.params["lin"]["bias"] = jnp.asarray(b)
    model.set_iteration_batch([x], y)
    model.forward()
    model.backward()
    gk = np.asarray(model._manual["grads"]["lin"]["kernel"])
    gb = np.asarray(model._manual["grads"]["lin"]["bias"])

    tw = torch.tensor(w, requires_grad=True)
    tb = torch.tensor(b, requires_grad=True)
    logits = torch.tensor(x) @ tw + tb
    loss = torch.nn.functional.cross_entropy(logits, torch.tensor(y[:, 0]).long())
    loss.backward()
    assert_close(gk, tw.grad.numpy(), rtol=1e-3, atol=1e-4)
    assert_close(gb, tb.grad.numpy(), rtol=1e-3, atol=1e-4)


def test_moe_dispatch_roundtrip():
    """group_by -> identity experts -> aggregate reproduces a gate-weighted
    mixture (verifies the capacity dispatch plan is consistent)."""
    rng = np.random.RandomState(16)
    B, F, n, k = 8, 4, 4, 2
    x = rng.randn(B, F).astype(np.float32)

    config = ff.FFConfig()
    config.batch_size = B
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([B, F])
    gate = model.softmax(model.dense(inp, n, name="gate"))
    topk_v, topk_i = model.top_k(gate, k)
    grouped = model.group_by(inp, topk_i, n, alpha=float(n))  # capacity >= B*k/n
    agg = model.aggregate(topk_v, topk_i, topk_i, gate, grouped, n)
    model.final_tensor = agg
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    feeds = {model.input_ops[0].name: x}
    values, _, _ = model.executor.forward_values(
        model.params, model.state, feeds, None, CompMode.COMP_MODE_INFERENCE
    )
    out = np.asarray(values[agg.guid])
    # identity experts: aggregate(x) = sum_j gate_topk[j] * x  (full capacity)
    gates = np.asarray(values[topk_v.guid])
    ref = x * gates.sum(1, keepdims=True)
    assert_close(out, ref, rtol=1e-3, atol=1e-4)


def test_conv2d_gradients_vs_torch():
    """conv fwd + input/kernel grads vs torch (reference: conv_2d bwd kernels)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    b = rng.randn(4).astype(np.float32)

    config = ff.FFConfig()
    config.batch_size = 2
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    tin = model.create_tensor([2, 3, 8, 8])
    out = model.conv2d(tin, 4, 3, 3, 1, 1, 1, 1, name="c")
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    model.params["c"]["kernel"] = jnp.asarray(w)
    model.params["c"]["bias"] = jnp.asarray(b)

    def loss(params, xv):
        values, _, _ = model.executor.forward_values(
            params, model.state, {"input_0": xv}, None,
            CompMode.COMP_MODE_TRAINING)
        return jnp.sum(values[out.guid] ** 2)

    gw = jax.grad(loss)(model.params, jnp.asarray(x))
    gx = jax.grad(loss, argnums=1)(model.params, jnp.asarray(x))

    xt = torch.tensor(x, requires_grad=True)
    conv = torch.nn.Conv2d(3, 4, 3, padding=1)
    with torch.no_grad():
        conv.weight.copy_(torch.tensor(w))
        conv.bias.copy_(torch.tensor(b))
    lt = (conv(xt) ** 2).sum()
    lt.backward()
    assert_close(gx, xt.grad.numpy(), rtol=1e-3, atol=1e-3)
    assert_close(gw["c"]["kernel"], conv.weight.grad.numpy(), rtol=1e-3, atol=1e-3)
    assert_close(gw["c"]["bias"], conv.bias.grad.numpy(), rtol=1e-3, atol=1e-3)


def test_grouped_conv_vs_torch():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 4, 6, 6).astype(np.float32)
    w = rng.randn(8, 2, 3, 3).astype(np.float32)  # groups=2: in 4/2=2

    out, model = run_forward(
        lambda m, t: m.conv2d(t[0], 8, 3, 3, 1, 1, 1, 1, groups=2,
                              use_bias=False, name="gc"),
        [x], weights={"gc": {"kernel": w}},
    )
    ref = torch.nn.functional.conv2d(
        torch.tensor(x), torch.tensor(w), padding=1, groups=2).numpy()
    assert_close(out, ref, rtol=1e-3, atol=1e-3)


def test_layernorm_gradients_vs_torch():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(5)
    x = rng.randn(4, 10).astype(np.float32)

    config = ff.FFConfig()
    config.batch_size = 4
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    tin = model.create_tensor([4, 10])
    out = model.layer_norm(tin, [-1], name="ln")
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)

    def loss(xv):
        values, _, _ = model.executor.forward_values(
            model.params, model.state, {"input_0": xv}, None,
            CompMode.COMP_MODE_TRAINING)
        return jnp.sum(jnp.sin(values[out.guid]))

    gx = jax.grad(loss)(jnp.asarray(x))
    xt = torch.tensor(x, requires_grad=True)
    ln = torch.nn.LayerNorm(10)
    torch.sin(ln(xt)).sum().backward()
    assert_close(gx, xt.grad.numpy(), rtol=1e-3, atol=1e-3)


def test_cast_reverse_reduce_mean():
    rng = np.random.RandomState(6)
    x = rng.randn(3, 5).astype(np.float32)

    out, _ = run_forward(
        lambda m, t: m.cast(t[0], ff.DataType.DT_INT32), [x])
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, x.astype(np.int32))

    out, _ = run_forward(lambda m, t: m.reverse(t[0], axis=1), [x])
    np.testing.assert_array_equal(out, x[:, ::-1])

    out, _ = run_forward(lambda m, t: m.reduce_sum(t[0], [1]), [x])
    assert_close(out, x.sum(axis=1))

    out, _ = run_forward(lambda m, t: m.mean(t[0], [0]), [x])
    assert_close(out, x.mean(axis=0))


def test_batchnorm_training_updates_running_stats():
    rng = np.random.RandomState(7)
    x = (rng.randn(8, 3, 4, 4) * 2 + 1.5).astype(np.float32)

    config = ff.FFConfig()
    config.batch_size = 8
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    tin = model.create_tensor([8, 3, 4, 4])
    out = model.batch_norm(tin, relu=False, name="bn")
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    before = {k: np.asarray(v) for k, v in model.state.get("bn", {}).items()}
    _, new_state, _ = model.executor.forward_values(
        model.params, model.state, {"input_0": x}, None,
        CompMode.COMP_MODE_TRAINING)
    after = {k: np.asarray(v) for k, v in new_state.get("bn", {}).items()}
    assert before and after
    changed = any(not np.allclose(before[k], after[k]) for k in before)
    assert changed, "running stats did not update in training mode"


def test_dropout_train_vs_inference():
    rng = np.random.RandomState(8)
    x = np.ones((64, 64), dtype=np.float32)

    # inference: identity
    out, model = run_forward(
        lambda m, t: m.dropout(t[0], rate=0.5, name="do"), [x])
    assert_close(out, x)

    # training: ~half zeros, survivors scaled by 2
    import jax

    values, _, _ = model.executor.forward_values(
        model.params, model.state, {"input_0": x},
        jax.random.PRNGKey(0), CompMode.COMP_MODE_TRAINING)
    tr = np.asarray(values[model.final_tensor.guid])
    zero_frac = float((tr == 0).mean())
    assert 0.35 < zero_frac < 0.65, zero_frac
    nz = tr[tr != 0]
    np.testing.assert_allclose(nz, 2.0, rtol=1e-5)
