"""keras_exp frontend: tf.keras-via-ONNX replay (reference:
python/flexflow/keras_exp/models/model.py). Without tensorflow in the image,
the test feeds the ONNX form a tf.keras export would produce (authored with
the built-in wire codec, so it runs with or without the onnx package)."""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.onnx import wire


def _keras_style_onnx():
    """The graph tf2onnx emits for a Dense->ReLU->Dense keras model
    (Gemm with transB, keras-style initializer names)."""
    rng = np.random.RandomState(0)
    w1 = rng.randn(16, 20).astype(np.float32)  # (out, in), transB=1
    b1 = rng.randn(16).astype(np.float32)
    w2 = rng.randn(4, 16).astype(np.float32)
    b2 = rng.randn(4).astype(np.float32)
    nodes = [
        wire.make_node("Gemm", ["x", "dense/kernel", "dense/bias"], ["h"],
                       name="dense", transB=1),
        wire.make_node("Relu", ["h"], ["hr"], name="re_lu"),
        wire.make_node("Gemm", ["hr", "dense_1/kernel", "dense_1/bias"],
                       ["y"], name="dense_1", transB=1),
        wire.make_node("Softmax", ["y"], ["prob"], name="softmax"),
    ]
    proto = wire.make_model(
        nodes, {"x": (8, 20)}, {"prob": (8, 4)},
        {"dense/kernel": w1, "dense/bias": b1,
         "dense_1/kernel": w2, "dense_1/bias": b2},
        name="keras_mlp")
    return proto, (w1, b1, w2, b2)


def test_keras_exp_model_builds_and_trains():
    from flexflow_tpu.keras_exp import Model

    proto, _ = _keras_style_onnx()
    m = Model(proto, batch_size=8)
    ffmodel = m.build([[8, 20]])
    m.compile(optimizer=ff.AdamOptimizer(ffmodel, alpha=1e-3),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[ff.MetricsType.METRICS_ACCURACY])
    x = np.random.RandomState(1).randn(8, 20).astype(np.float32)
    y = np.zeros((8, 1), dtype=np.int32)
    hist = m.fit([x], y, batch_size=8, epochs=1)
    assert np.isfinite(hist[0]["loss"])


def test_keras_exp_weights_transfer():
    """The imported keras weights produce the same forward as numpy."""
    from flexflow_tpu.keras_exp import Model

    proto, (w1, b1, w2, b2) = _keras_style_onnx()
    m = Model(proto, batch_size=8)
    m.config.allow_mixed_precision = False
    ffmodel = m.build([[8, 20]])
    m.compile(optimizer=ff.SGDOptimizer(ffmodel, lr=0.0),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[])
    x = np.random.RandomState(1).randn(8, 20).astype(np.float32)
    ours = ffmodel.predict(x)
    h = np.maximum(x @ w1.T + b1, 0)
    logits = h @ w2.T + b2
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    ref = e / e.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(ours, ref, atol=1e-4, rtol=1e-4)


def test_keras_exp_live_tf_needs_tensorflow():
    from flexflow_tpu.keras_exp.models import _to_onnx

    class FakeKeras:
        pass

    FakeKeras.__module__ = "keras.engine.training"
    with pytest.raises(ImportError, match="tf2onnx|tensorflow"):
        _to_onnx(FakeKeras())
