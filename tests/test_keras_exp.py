"""keras_exp frontend: tf.keras-via-ONNX replay (reference:
python/flexflow/keras_exp/models/model.py). Without tensorflow in the image,
the test feeds the ONNX form a tf.keras export would produce."""
import numpy as np
import pytest

import flexflow_tpu as ff

try:
    import onnx  # noqa: F401

    HAS_ONNX = True
except ImportError:
    HAS_ONNX = False


def _keras_style_onnx():
    """The graph tf2onnx emits for a Dense->ReLU->Dense keras model
    (Gemm with transB, keras-style initializer names)."""
    import onnx.helper as oh
    import onnx.numpy_helper as nph

    rng = np.random.RandomState(0)
    w1 = rng.randn(16, 20).astype(np.float32)  # (out, in), transB=1
    b1 = rng.randn(16).astype(np.float32)
    w2 = rng.randn(4, 16).astype(np.float32)
    b2 = rng.randn(4).astype(np.float32)
    nodes = [
        oh.make_node("Gemm", ["x", "dense/kernel", "dense/bias"], ["h"],
                     name="dense", transB=1),
        oh.make_node("Relu", ["h"], ["hr"], name="re_lu"),
        oh.make_node("Gemm", ["hr", "dense_1/kernel", "dense_1/bias"], ["y"],
                     name="dense_1", transB=1),
        oh.make_node("Softmax", ["y"], ["prob"], name="softmax"),
    ]
    graph = oh.make_graph(
        nodes, "keras_mlp",
        [oh.make_tensor_value_info("x", 1, [8, 20])],
        [oh.make_tensor_value_info("prob", 1, [8, 4])],
        initializer=[
            nph.from_array(w1, "dense/kernel"),
            nph.from_array(b1, "dense/bias"),
            nph.from_array(w2, "dense_1/kernel"),
            nph.from_array(b2, "dense_1/bias"),
        ],
    )
    return oh.make_model(graph), (w1, b1, w2, b2)


@pytest.mark.skipif(not HAS_ONNX, reason="onnx not installed")
def test_keras_exp_model_builds_and_trains():
    from flexflow_tpu.keras_exp import Model

    proto, _ = _keras_style_onnx()
    m = Model(proto, batch_size=8)
    ffmodel = m.build([[8, 20]])
    m.compile(optimizer=ff.AdamOptimizer(ffmodel, alpha=1e-3),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[ff.MetricsType.METRICS_ACCURACY])
    x = np.random.RandomState(1).randn(8, 20).astype(np.float32)
    y = np.zeros((8, 1), dtype=np.int32)
    hist = m.fit([x], y, batch_size=8, epochs=1)
    assert np.isfinite(hist[0]["loss"])


def test_keras_exp_live_tf_needs_tensorflow():
    from flexflow_tpu.keras_exp.models import _to_onnx

    class FakeKeras:
        pass

    FakeKeras.__module__ = "keras.engine.training"
    with pytest.raises(ImportError, match="tf2onnx|tensorflow"):
        _to_onnx(FakeKeras())
