"""Serving fleet (ISSUE 12): prefix-affine Router, SLO admission,
drain handoff, Autoscaler, and the server/observability fan-in.

The decisive properties:
 - routing is a pure function of the PrefixCache's own page-block hash
   addresses — a follower lands on the replica that owns its prefix;
 - SLO admission sheds by PREDICTED TTFT (measured rate model), typed
   as the same 429 family the queue/pool rejections use;
 - drain re-homes queued requests with zero drops and the caller's
   handle follows transparently;
 - the autoscaler grows and shrinks replica meshes through
   `request_resize` (zero drops, deferred shrink) and can add/retire
   whole replicas;
 - per-replica registries merge into ONE exposition under a `replica`
   label, and /healthz aggregates replica health.
"""
import threading
import time

import numpy as np
import pytest

from flexflow_tpu.obs.registry import validate_exposition
from flexflow_tpu.serving.fleet import (Autoscaler, FleetUnavailable,
                                        Replica, ReplicaState, Router)
from flexflow_tpu.serving.sched import SLOExceeded
from tests.conftest import module_xla_cache
from tests.test_generate import _build_lm


# module-scoped XLA compilation cache — see conftest.module_xla_cache
_xla_cache = pytest.fixture(scope="module", autouse=True)(module_xla_cache)


@pytest.fixture(scope="module")
def lm():
    return _build_lm(2, 12)


def _mk_replica(lm, name, slots=2, max_len=48, page_size=4, max_queue=32,
                **kw):
    return Replica(name, lm, max_len=max_len, num_slots=slots,
                   page_size=page_size, max_queue=max_queue, **kw)


def _mk_fleet(lm, n=2, **kw):
    router = Router(**{k: v for k, v in kw.items()
                       if k in ("policy", "slo_ttft_s", "route_depth")})
    rep_kw = {k: v for k, v in kw.items()
              if k not in ("policy", "slo_ttft_s", "route_depth")}
    for i in range(n):
        router.add_replica(f"r{i}", _mk_replica(lm, f"r{i}", **rep_kw))
    return router


def _prompt(n, seed=0, vocab=50):
    rng = np.random.RandomState(seed)
    return rng.randint(1, vocab, size=(n,)).astype(np.int32)


# ---------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------
def test_affine_routing_lands_on_prefix_owner(lm):
    router = _mk_fleet(lm, 2)
    try:
        prefix = _prompt(8, seed=1)  # two full pages at page_size=4
        lead = router.submit(np.concatenate([prefix, _prompt(3, seed=2)]),
                             3)
        lead.result(timeout=300)
        home = lead.replica
        # follower shares the prefix: must land on the owner, affine, hit
        f = router.submit(np.concatenate([prefix, _prompt(3, seed=3)]), 3)
        f.result(timeout=300)
        assert f.replica == home
        assert f.route == "affine"
        assert f.cache_hit and f.prefix_tokens >= 8
        # a different tenant spreads to the OTHER replica (cold -> least
        # loaded with affinity-home tie-break)
        other = router.submit(
            np.concatenate([_prompt(8, seed=9), _prompt(3, seed=4)]), 3)
        other.result(timeout=300)
        assert other.replica != home
    finally:
        router.shutdown()


def test_sticky_routing_before_cache_is_warm(lm):
    router = _mk_fleet(lm, 2)
    try:
        prefix = _prompt(8, seed=5)
        suffix = _prompt(3, seed=6)
        lead = router.submit(np.concatenate([prefix, suffix]), 2)
        # submitted back-to-back: the leader is still prefilling, so no
        # cache pages exist yet — the key must still pin the follower to
        # the leader's replica instead of spraying a duplicate prefill
        follow = router.submit(np.concatenate([prefix, _prompt(3, 7)]), 2)
        assert follow.route in ("sticky", "affine")
        assert follow.replica == lead.replica
        lead.result(timeout=300)
        follow.result(timeout=300)
    finally:
        router.shutdown()


def test_cold_short_prompts_route_least_loaded(lm):
    router = _mk_fleet(lm, 2)
    try:
        # < 1 full page: no routing key at all
        a = router.submit(_prompt(3, seed=10), 2)
        b = router.submit(_prompt(3, seed=11), 2)
        assert a.route == "least_loaded" and b.route == "least_loaded"
        a.result(timeout=300)
        b.result(timeout=300)
    finally:
        router.shutdown()


def test_round_robin_policy_cycles(lm):
    router = _mk_fleet(lm, 2, policy="round_robin")
    try:
        reqs = [router.submit(_prompt(4, seed=20 + i), 2)
                for i in range(4)]
        for r in reqs:
            r.result(timeout=300)
        assert [r.route for r in reqs] == ["round_robin"] * 4
        assert {r.replica for r in reqs} == {"r0", "r1"}
    finally:
        router.shutdown()


def test_fleet_unavailable_when_all_draining(lm):
    router = _mk_fleet(lm, 1)
    try:
        router.drain("r0")
        with pytest.raises(FleetUnavailable) as ei:
            router.submit(_prompt(4), 2)
        assert ei.value.http_status == 503
    finally:
        router.shutdown()


def test_mismatched_page_size_rejected(lm):
    router = _mk_fleet(lm, 1, page_size=4)
    try:
        with pytest.raises(ValueError, match="page geometry"):
            router.add_replica("bad", _mk_replica(lm, "bad", page_size=8))
    finally:
        router.shutdown()


# ---------------------------------------------------------------------
# SLO admission
# ---------------------------------------------------------------------
def test_slo_sheds_by_predicted_ttft_only_after_measurement(lm):
    router = _mk_fleet(lm, 1, slots=1, slo_ttft_s=1e-9)
    try:
        # COLD: no rate samples -> predicted 0 -> admitted despite the
        # absurd budget (the estimate only sheds once it is backed by
        # measurements)
        first = router.submit(_prompt(6, seed=30), 2)
        first.result(timeout=300)
        rep = router.replica("r0")
        assert rep.batcher.stats()["prefill_s_per_token"] is not None
        # WARM: the measured model now predicts > 1e-9 s for any prompt
        with pytest.raises(SLOExceeded) as ei:
            router.submit(_prompt(6, seed=31), 2)
        assert ei.value.http_status == 429
        assert ei.value.reason == "slo_ttft"
        assert router.registry.counter(
            "ff_fleet_shed_total", labels=("reason",)).value(
                reason="slo_ttft") == 1
    finally:
        router.shutdown()


def test_predicted_ttft_grows_with_queue_backlog(lm):
    rep = _mk_replica(lm, "solo", slots=1, max_queue=64)
    try:
        warm = rep.submit(_prompt(6, seed=32), 2)
        warm.result(timeout=300)
        base = rep.predicted_ttft_s(8)
        assert base > 0
        # a held queue inflates the backlog term
        long_req = rep.submit(_prompt(6, seed=33), 40)
        queued = [rep.submit(_prompt(8, seed=40 + i), 2)
                  for i in range(4)]
        loaded = rep.predicted_ttft_s(8)
        assert loaded > base
        assert rep.batcher.queued_prefill_tokens() > 0
        for q in queued:
            q.result(timeout=300)
        long_req.result(timeout=300)
    finally:
        rep.stop()


# ---------------------------------------------------------------------
# drain / handoff
# ---------------------------------------------------------------------
def test_drain_hands_off_queued_requests_zero_drop(lm):
    router = _mk_fleet(lm, 2, slots=1, max_queue=16)
    try:
        # pin both replicas' single slots with long decodes, then queue
        # more work everywhere
        pin = [router.submit(_prompt(5, seed=50 + i), 40)
               for i in range(2)]
        deadline = time.monotonic() + 120
        while not all(p.tokens for p in pin):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        queued = [router.submit(_prompt(5, seed=60 + i), 3)
                  for i in range(4)]
        victim = queued[0].replica
        stats = router.drain(victim)
        assert router.replica(victim).state is ReplicaState.DRAINING
        # every queued request on the victim either re-homed or stayed
        # (sibling full) — and ALL of them finish with full token counts
        assert stats["handed_off"] + stats["kept"] >= 1
        for q in queued:
            assert q.result(timeout=300).size == 3
        for p in pin:
            assert p.result(timeout=300).size == 40
        handed = [q for q in queued if q.handoffs]
        assert len(handed) == stats["handed_off"]
        for q in handed:
            assert q.replica != victim
    finally:
        router.shutdown()


def test_second_drain_rehomes_the_callers_handle_again(lm):
    """Regression: after a handoff the router must track the CALLER's
    FleetRequest on the new home (not its internal duplicate wrapper),
    or draining the new home re-homes the wrapper while the caller's
    handle dies with RequestCancelled — a dropped request under the
    zero-drop contract."""
    router = _mk_fleet(lm, 3, slots=1, max_queue=16)
    try:
        pin = [router.submit(_prompt(5, seed=150 + i), 40)
               for i in range(3)]
        deadline = time.monotonic() + 120
        while not all(p.tokens for p in pin):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        q = router.submit(_prompt(5, seed=160), 3)
        first_home = q.replica
        s1 = router.drain(first_home)
        assert s1["handed_off"] == 1 and q.handoffs == 1
        second_home = q.replica
        assert second_home != first_home
        s2 = router.drain(second_home)
        assert s2["handed_off"] == 1 and q.handoffs == 2
        assert q.replica not in (first_home, second_home)
        assert q.result(timeout=300).size == 3
        for p in pin:
            assert p.result(timeout=300).size == 40
    finally:
        router.shutdown()


def test_affinity_lru_is_bounded_and_homes_stay_consistent(lm):
    """The affinity table must not grow with lifetime-unique tenants:
    past max_affinity_keys the coldest key evicts, and the per-replica
    homes counter the least-loaded tie-break reads stays in step."""
    router = _mk_fleet(lm, 2)
    router.max_affinity_keys = 4
    try:
        for i in range(10):
            router.submit(
                np.concatenate([_prompt(4, seed=200 + i),
                                _prompt(2, seed=300 + i)]),
                2).result(timeout=300)
        with router._lock:
            assert len(router._affinity) == 4
            homes = dict(router._homes)
        assert sum(homes.values()) == 4
        assert set(homes) <= {"r0", "r1"}
    finally:
        router.shutdown()


def test_remove_waits_for_drain_and_stops(lm):
    router = _mk_fleet(lm, 2)
    try:
        r = router.submit(_prompt(5, seed=70), 3)
        r.result(timeout=300)
        name = r.replica
        router.remove(name, timeout=120)
        assert name not in router.replica_names()
        assert len(router.replica_names()) == 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------
def test_autoscaler_grow_and_shrink_cycle(lm):
    router = _mk_fleet(lm, 1, slots=2, max_queue=64)
    asc = Autoscaler(router, min_slots=1, max_slots=4, grow_step=2,
                     shrink_step=3, queue_hi=1, util_lo=0.9,
                     idle_ticks_before_shrink=2,
                     idle_ticks_before_drain=10**9)
    try:
        rep = router.replica("r0")
        flood = [router.submit(_prompt(5, seed=80 + i), 6)
                 for i in range(8)]
        acts = asc.tick()
        assert any(a["action"] == "grow" and a["to"] == 4 for a in acts)
        for h in flood:
            assert h.result(timeout=300).size == 6
        deadline = time.monotonic() + 120
        while asc.pending_resizes():
            assert time.monotonic() < deadline
            asc.tick()
            time.sleep(0.02)
        assert rep.num_slots() == 4
        # idle now: shrink fires after the hysteresis ticks
        shrunk = []
        while rep.num_slots() != 1:
            assert time.monotonic() < deadline
            shrunk += [a for a in asc.tick() if a["action"] == "shrink"]
            time.sleep(0.02)
        assert shrunk and shrunk[0]["to"] == 1
        # nothing was dropped by the whole cycle
        assert rep.batcher.stats()["failed"] == 0
    finally:
        router.shutdown()


def test_autoscaler_adds_then_retires_replicas(lm):
    router = _mk_fleet(lm, 1, slots=1, max_queue=64)
    asc = Autoscaler(
        router, min_slots=1, max_slots=1,  # mesh pinned: overload must
        queue_hi=0, util_lo=0.9,           # add a REPLICA instead
        replica_factory=lambda: _mk_replica(lm, "auto", slots=1),
        max_replicas=2, min_replicas=1, idle_ticks_before_drain=2)
    try:
        flood = [router.submit(_prompt(5, seed=90 + i), 4)
                 for i in range(4)]
        acts = asc.tick()
        assert any(a["action"] == "add_replica" for a in acts)
        assert len(router.replica_names()) == 2
        for h in flood:
            h.result(timeout=300)
        # sustained idleness retires one replica (drain + remove runs in
        # the background; poll until the membership shrinks back)
        deadline = time.monotonic() + 120
        drained = False
        while len(router.replica_names()) > 1:
            assert time.monotonic() < deadline
            drained = drained or any(a["action"] == "drain_replica"
                                     for a in asc.tick())
            time.sleep(0.02)
        assert drained
    finally:
        router.shutdown()


def test_autoscaler_ttft_slo_is_windowed_not_lifetime(lm):
    """Regression: the TTFT SLO signal must read a sliding window, not
    the lifetime-cumulative histogram — one historic slow burst would
    otherwise read as overload forever (grow forever, shrink dead)."""
    router = _mk_fleet(lm, 1, slots=2)
    asc = Autoscaler(router, min_slots=1, max_slots=4, queue_hi=10**9,
                     util_hi=2.0, util_lo=0.9, ttft_p99_slo_ms=50.0,
                     idle_ticks_before_shrink=1,
                     idle_ticks_before_drain=10**9)
    try:
        rep = router.replica("r0")
        fam = rep.registry.get("ff_serving_ttft_ms")
        fam.observe(5000.0, cache="miss")  # historic slow burst
        assert rep.ttft_p99_ms() > 50.0   # lifetime read IS over the SLO
        acts = asc.tick() + asc.tick()
        # idle replica, burst outside the window: shrink, never grow
        assert any(a["action"] == "shrink" for a in acts)
        assert not any(a["action"] == "grow" for a in acts)
        deadline = time.monotonic() + 120
        while asc.pending_resizes():
            assert time.monotonic() < deadline
            asc.tick()
            time.sleep(0.02)
        # a FRESH breach (inside the window) still reads as overload
        fam.observe(5000.0, cache="miss")
        grown = []
        while not grown:
            assert time.monotonic() < deadline
            grown = [a for a in asc.tick() if a["action"] == "grow"]
            time.sleep(0.01)
    finally:
        router.shutdown()


# ---------------------------------------------------------------------
# observability fan-in
# ---------------------------------------------------------------------
def test_merged_exposition_has_replica_label_and_validates(lm):
    router = _mk_fleet(lm, 2)
    try:
        for i in range(3):
            router.submit(_prompt(6, seed=100 + i), 2).result(timeout=300)
        from flexflow_tpu.obs.registry import render_merged

        text = router.registry.render() + render_merged(
            router.replica_registries())
        fams = validate_exposition(text)
        ttft = fams["ff_serving_ttft_ms"]
        assert all("replica" in lbls for _, lbls, _ in ttft["samples"])
        assert {lbls["replica"] for _, lbls, _ in ttft["samples"]} \
            <= {"r0", "r1"}
        # the router's own families render exactly once
        assert text.count("# TYPE ff_fleet_requests_total counter") == 1
        assert text.count("# TYPE ff_serving_ttft_ms histogram") == 1
    finally:
        router.shutdown()


def test_server_fleet_fanin_healthz_and_load_failures(lm):
    import json
    from urllib.request import urlopen

    from flexflow_tpu.serving import InferenceServer

    server = InferenceServer()
    router = _mk_fleet(lm, 2)
    server.register_fleet("lm", router)
    # regression: a NON-fleet batcher in the same process registers the
    # serving families in the process-wide default registry; the fleet
    # /metrics must still render ONE exposition document with a single
    # TYPE header per family (naive concatenation of the default render
    # and the replica-merged render duplicated them)
    from flexflow_tpu.obs.registry import REGISTRY

    REGISTRY.gauge("ff_kvpool_pages_used", "KV pages in use",
                   labels=("pool",)).set(1, pool="solo")
    httpd = server.serve_http(port=0)
    try:
        port = httpd.server_address[1]
        out = server.generate("lm", [[1, 2, 3], [4, 5]], 3)
        assert [len(t) for t in out] == [3, 3]
        with urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            health = json.loads(r.read())
        assert health["status"] == "ok"
        assert health["fleets"]["lm"]["ready"] == 2
        # a failed replica load flows into ff_model_load_failures_total
        # and degrades /healthz
        router.add_replica("bad", lambda: (_ for _ in ()).throw(
            RuntimeError("no checkpoint")))
        router.drain("r1")
        with urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            health = json.loads(r.read())
        assert health["status"] == "degraded"
        assert health["fleets"]["lm"]["failed_loads"]
        text = server.prometheus_text()
        validate_exposition(text)
        assert 'ff_model_load_failures_total{model="lm/bad"} 1' in text
        assert 'replica="r0"' in text and "ff_fleet_requests_total" in text
        # full-fleet failure -> "down"
        router.drain("r0")
        with urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            assert json.loads(r.read())["status"] == "down"
    finally:
        httpd.shutdown()
        server.shutdown()


def test_repository_fleet_entry_registers_router(lm):
    from flexflow_tpu.serving import InferenceServer
    from flexflow_tpu.serving.repository import ModelRepository

    server = InferenceServer()
    try:
        ModelRepository._register_fleet(
            server, "lm", lm,
            {"mode": "fleet", "replicas": 2, "max_len": 48,
             "num_slots": 2, "page_size": 4, "slo_ttft_ms": 60000.0})
        router = server._fleets["lm"]
        assert router.replica_names() == ["r0", "r1"]
        assert router.slo_ttft_s == 60.0
        out = server.generate("lm", [[1, 2, 3]], 2)
        assert [len(t) for t in out] == [2]
        # one serving mode per name
        with pytest.raises(ValueError, match="serving mode"):
            server.register_fleet("lm2", router) or \
                server.register_continuous("lm", object())
    finally:
        server.shutdown()


def test_stream_through_fleet(lm):
    from flexflow_tpu.serving import InferenceServer

    server = InferenceServer()
    router = _mk_fleet(lm, 1)
    server.register_fleet("lm", router)
    try:
        gen = server.generate_stream("lm", [1, 2, 3, 4], 4)
        toks = list(gen.stream(timeout=300))
        assert len(toks) == 4
        assert toks == list(gen.tokens)
    finally:
        server.shutdown()
