"""Training watchdog + durable rollback (ISSUE 3): NaN/Inf and loss-spike
detection, skip-then-rollback recovery through the elastic coordinator,
corrupt-checkpoint fallback, and the /metrics counter export — all on the
virtual 8-device CPU mesh (conftest.py)."""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.elastic import (
    ElasticCoordinator,
    EventLog,
    FaultPlan,
    NumericBlowup,
    RecoveryFailed,
    TrainingWatchdog,
    WatchdogPolicy,
)


# -- helpers (the test_elastic.py fixtures) ------------------------------
def make_config(devices=4, batch=12, budget=4):
    cfg = ff.FFConfig()
    cfg.batch_size = batch
    cfg.search_budget = budget
    cfg.measure_op_costs = False
    cfg.device_ids = list(range(devices))
    return cfg


def builder(cfg):
    m = ff.FFModel(cfg)
    t = m.create_tensor([cfg.batch_size, 32])
    t = m.dense(t, 64, ff.ActiMode.AC_MODE_RELU)
    t = m.dense(t, 10)
    t = m.softmax(t)
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return m


def make_data(batch, n_batches=4, din=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(batch * n_batches, din).astype(np.float32)
    w = rng.randn(din, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).reshape(-1, 1).astype(np.int32)
    return x, y


# -- policy + verdict state machine --------------------------------------
def test_policy_validation():
    with pytest.raises(ValueError):
        WatchdogPolicy(spike_factor=1.0)
    with pytest.raises(ValueError):
        WatchdogPolicy(max_consecutive_bad=0)


def test_nonfinite_skips_then_rollback_then_reset():
    events = EventLog()
    wd = TrainingWatchdog(WatchdogPolicy(max_consecutive_bad=3,
                                         warmup_steps=0), events=events)
    assert wd.check(0, 1.0) == "ok"
    assert wd.check(1, float("nan")) == "skip"
    assert wd.check(2, float("inf")) == "skip"
    assert wd.check(3, float("nan")) == "rollback"
    # the consecutive counter resets after a rollback verdict...
    assert wd.check(4, float("nan")) == "skip"
    # ...and after any good step
    assert wd.check(5, 1.0) == "ok"
    assert wd.consecutive_bad == 0
    assert len(events.events("watchdog.bad_step")) == 4
    assert len(events.events("watchdog.skip")) == 3
    # a ROLLBACK verdict alone records nothing — the event belongs to the
    # site that actually restores a checkpoint (coordinator._rollback)
    assert events.events("watchdog.rollback") == []
    wd.note_rollback(2)
    assert [e.step for e in events.events("watchdog.rollback")] == [2]


def test_spike_detection_arms_after_warmup():
    wd = TrainingWatchdog(WatchdogPolicy(spike_factor=5.0, warmup_steps=3,
                                         ema_alpha=0.5))
    # wild warmup losses are tolerated (a fresh model's first steps)
    assert wd.check(0, 40.0) == "ok"
    assert wd.check(1, 2.0) == "ok"
    assert wd.check(2, 2.0) == "ok"
    assert wd.check(3, 2.0) == "ok"
    # post-warmup: a finite 100x spike is a bad step; the EMA baseline is
    # NOT polluted by it, so the next normal loss is fine again
    assert wd.check(4, 200.0) == "skip"
    assert wd.check(5, 2.0) == "ok"


def test_guard_raises_numeric_blowup():
    wd = TrainingWatchdog(WatchdogPolicy(max_consecutive_bad=1,
                                         warmup_steps=0))
    wd.guard(0, 1.0)  # fine
    with pytest.raises(NumericBlowup, match="step 3"):
        wd.guard(3, float("nan"))


# -- FFModel.fit hook (no rollback available -> typed abort) -------------
def test_model_fit_watchdog_aborts_on_nan():
    model = builder(make_config(devices=1, batch=8))
    x = np.full((32, 32), np.inf, dtype=np.float32)  # guaranteed blow-up
    y = np.zeros((32, 1), np.int32)
    wd = TrainingWatchdog(WatchdogPolicy(max_consecutive_bad=2,
                                         warmup_steps=0))
    with pytest.raises(NumericBlowup, match="consecutive bad steps"):
        model.fit(x, y, epochs=3, watchdog=wd)
    assert len(wd.events.events("watchdog.bad_step")) == 2


# -- coordinator: skip -> rollback -> replay -----------------------------
def test_coordinator_nan_steps_skip_rollback_resume(tmp_path):
    """Four consecutive blown-up steps against the default policy (3
    consecutive bad = rollback): two skips, a rollback to the step-2
    checkpoint, a clean replay, one more skip, then healthy training."""
    events = EventLog()
    plan = FaultPlan()
    for s in range(3, 7):
        plan.add_nan_step(s)
    x, y = make_data(batch=12)
    coord = ElasticCoordinator(
        builder, make_config(), fault_plan=plan, events=events,
        checkpoint_dir=str(tmp_path), checkpoint_every=2)
    assert coord.detector.rng is not None  # seeded retry jitter threaded
    history = coord.fit(x, y, steps=10)

    assert len(events.events("watchdog.rollback")) == 1
    assert len(events.events("watchdog.skip")) == 3
    assert len(events.events("fault.nan_step")) == 4
    # rollback restored the step-2 checkpoint (newest before the bad run)
    restores = events.events("recovery.restore")
    assert len(restores) == 1 and restores[0].step == 2
    # steps 3 and 4 were skipped pre-rollback but REPLAYED clean after it
    # (their faults were spent); step 6's fault hits the replay as a
    # post-rollback skip, so it alone never commits
    assert [h["step"] for h in history] == [0, 1, 2, 3, 4, 5, 7, 8, 9]
    losses = [h["loss"] for h in history]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # training still made progress


def test_coordinator_corrupt_checkpoint_falls_back(tmp_path):
    """Torn newest checkpoint + chip loss in the same dispatch: the
    recovery restore must fall back to the previous verified checkpoint
    instead of crashing on the corrupt one."""
    events = EventLog()
    plan = (FaultPlan()
            .add_corrupt_checkpoint(4)
            .add_chip_loss(4, chips=[3]))
    x, y = make_data(batch=12)
    coord = ElasticCoordinator(
        builder, make_config(devices=4, batch=12), fault_plan=plan,
        events=events, checkpoint_dir=str(tmp_path), checkpoint_every=2,
        # the scenario exists to prove the DISK path's verified fallback;
        # with live resharding on, a clean live tree would sidestep the
        # torn checkpoint entirely
        live_resharding=False)
    history = coord.fit(x, y, steps=8)

    assert len(events.events("recovery.done")) == 1
    assert coord.device_ids == [0, 1, 2]
    assert len(events.events("fault.corrupt_checkpoint")) == 1
    assert len(events.events("checkpoint.corrupt")) == 1
    # the step-4 file was torn, so restore fell back to step 2
    fallbacks = events.events("checkpoint.fallback")
    assert len(fallbacks) == 1 and fallbacks[0].step == 2
    restores = events.events("recovery.restore")
    assert restores and restores[0].step == 2
    assert [h["step"] for h in history] == list(range(8))


def test_rollback_budget_exhausts_on_deterministic_blowup(tmp_path):
    """A blow-up that recurs after every restore (faults re-arm via times)
    cannot be healed by replaying — the rollback budget must end it with a
    typed error instead of looping forever."""
    events = EventLog()
    plan = FaultPlan().add_nan_step(1, times=50)
    x, y = make_data(batch=8)
    wd = TrainingWatchdog(WatchdogPolicy(max_consecutive_bad=1,
                                         warmup_steps=0), events=events)
    coord = ElasticCoordinator(
        builder, make_config(devices=2, batch=8), fault_plan=plan,
        events=events, checkpoint_dir=str(tmp_path), watchdog=wd,
        max_rollbacks=2)
    with pytest.raises(RecoveryFailed, match="rollback budget"):
        coord.fit(x, y, steps=5)
    # only PERFORMED rollbacks are recorded; the third attempt hits the
    # budget and raises before restoring anything
    assert len(events.events("watchdog.rollback")) == 2


# -- /metrics export ------------------------------------------------------
def test_watchdog_and_checkpoint_counters_on_metrics():
    from flexflow_tpu.serving.server import InferenceServer

    # force the process-wide counters nonzero
    wd = TrainingWatchdog(WatchdogPolicy(max_consecutive_bad=2,
                                         warmup_steps=0))
    wd.check(0, float("nan"))
    srv = InferenceServer()
    text = srv.prometheus_text()
    assert "ff_watchdog_bad_steps_total" in text
    assert "ff_watchdog_skips_total" in text
    # any earlier durable save/restore in this test process shows up too
    stats = srv.stats()
    assert stats["_watchdog"]["bad_steps"] >= 1
