"""Dynamic recompilation hook (reference: RecompileState recompile.h:28-44,
MoE cache switch moe.cc:64-98)."""
import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.runtime.recompile import (
    RecompileState,
    moe_cache_alter,
    moe_cache_trigger,
)


def test_trigger_alter_fires_once():
    config = ff.FFConfig()
    config.batch_size = 8
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 16])
    model.softmax(model.dense(inp, 4))
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    fired = []
    rs = RecompileState(
        trigger=lambda m: m._step_count >= 0,
        alter=lambda m: fired.append(m._step_count),
    )
    model.recompile_on_condition(rs)
    x = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    y = np.zeros((32, 1), dtype=np.int32)
    model.fit(x, y, epochs=2)
    assert len(fired) == 1  # one-shot
    assert rs.fired == 1


def test_recompile_mid_epoch_with_steps_per_execution():
    """A recompile trigger firing between chunks of fit(steps_per_execution)
    must take effect for the REMAINING chunks of the same epoch: the
    chunked loop re-resolves the jitted multi-step after the alter
    invalidates it (regression for the stale-captured-mstep bug)."""
    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    inp = model.create_tensor([4, 16])
    model.softmax(model.dense(inp, 4))
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    seen_fns = []

    def alter(m):
        # what graph-mutating alters do at the end (e.g. moe_cache_alter,
        # recompile.py): invalidate every compiled step
        m.invalidate_compiled_steps()

    rs = RecompileState(trigger=lambda m: m._step_count >= 2, alter=alter)
    model.recompile_on_condition(rs)
    x = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    y = np.zeros((32, 1), dtype=np.int32)

    # watch which multi-step object each chunk uses
    orig_get = model._get_multi_step

    def spy():
        fn = orig_get()
        seen_fns.append(fn)
        return fn

    model._get_multi_step = spy
    model.fit(x, y, epochs=1, steps_per_execution=2)  # 4 chunks of 2
    assert rs.fired == 1
    # the alter rebuilt the step functions, so later chunks used a NEW
    # jitted multi-step object
    assert len(set(map(id, seen_fns))) == 2, (
        "chunks after the recompile kept the stale jitted multi-step")


def test_moe_cache_switch_end_to_end():
    """Cache op serves live input until scores stabilize, then the alter
    flips it to cached mode and the step recompiles."""
    batch, d = 8, 16
    config = ff.FFConfig()
    config.batch_size = batch
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, d])
    cached = model.cache(inp, name="assign_cache")
    model.softmax(model.dense(cached, 4, name="head"))
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.0),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    model.recompile_on_condition(
        RecompileState(moe_cache_trigger(threshold=1e-6, warmup_steps=2),
                       moe_cache_alter))
    # constant input -> cache divergence score goes to 0 -> trigger fires
    x = np.tile(np.random.RandomState(0).randn(1, d).astype(np.float32),
                (64, 1))
    y = np.zeros((64, 1), dtype=np.int32)
    model.fit(x, y, epochs=1)
    cache_op = next(op for op in model.graph.ops.values()
                    if op.op_type == ff.OpType.CACHE)
    assert cache_op.params.get("use_cached") is True
    # training still runs after the recompile
    hist = model.fit(x, y, epochs=1)
    assert np.isfinite(hist[-1]["loss"])
