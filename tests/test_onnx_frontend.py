"""ONNX importer tests — exercised fully only when the onnx package is
installed (reference: examples/python/onnx). Without onnx we still verify the
module is importable and fails with a clear error."""
import numpy as np
import pytest

import flexflow_tpu as ff

try:
    import onnx

    HAS_ONNX = True
except ImportError:
    HAS_ONNX = False


def test_module_imports_without_onnx():
    from flexflow_tpu.onnx import ONNXModel, ONNXModelKeras  # noqa: F401

    if not HAS_ONNX:
        with pytest.raises(ImportError, match="onnx"):
            ONNXModel("nonexistent.onnx")


@pytest.mark.skipif(not HAS_ONNX, reason="onnx not installed")
def test_onnx_mlp_roundtrip(tmp_path):
    import onnx.helper as oh
    import onnx.numpy_helper as nph

    rng = np.random.RandomState(0)
    w1 = rng.randn(20, 32).astype(np.float32)
    w2 = rng.randn(32, 4).astype(np.float32)
    nodes = [
        oh.make_node("MatMul", ["x", "w1"], ["h"], name="fc1"),
        oh.make_node("Relu", ["h"], ["hr"], name="relu1"),
        oh.make_node("MatMul", ["hr", "w2"], ["y"], name="fc2"),
    ]
    graph = oh.make_graph(
        nodes, "mlp",
        [oh.make_tensor_value_info("x", 1, [8, 20])],
        [oh.make_tensor_value_info("y", 1, [8, 4])],
        initializer=[nph.from_array(w1, "w1"), nph.from_array(w2, "w2")],
    )
    proto = oh.make_model(graph)

    from flexflow_tpu.onnx import ONNXModel

    config = ff.FFConfig()
    config.batch_size = 8
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    t = model.create_tensor([8, 20], ff.DataType.DT_FLOAT)
    om = ONNXModel(proto)
    outs = om.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    assert om.transfer_weights(model) == 2
    x = rng.randn(8, 20).astype(np.float32)
    ours = model.predict(x)
    ref = np.maximum(x @ w1, 0) @ w2
    np.testing.assert_allclose(ours, ref, atol=1e-4, rtol=1e-4)
