"""ONNX importer tests (reference: examples/python/onnx + onnx/model.py:56).

Model files are authored with the built-in wire codec
(flexflow_tpu/onnx/wire.py), so these tests run in EVERY environment; when
the onnx package is installed the same serialized bytes additionally go
through onnx's own ModelProto parser, cross-validating the codec against the
real proto schema.
"""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.onnx import wire

try:
    import onnx

    HAS_ONNX = True
except ImportError:
    HAS_ONNX = False


def _mlp_bytes():
    rng = np.random.RandomState(0)
    w1 = rng.randn(20, 32).astype(np.float32)
    w2 = rng.randn(32, 4).astype(np.float32)
    nodes = [
        wire.make_node("MatMul", ["x", "w1"], ["h"], name="fc1"),
        wire.make_node("Relu", ["h"], ["hr"], name="relu1"),
        wire.make_node("MatMul", ["hr", "w2"], ["y"], name="fc2"),
    ]
    proto = wire.make_model(nodes, {"x": (8, 20)}, {"y": (8, 4)},
                            {"w1": w1, "w2": w2}, name="mlp")
    return proto, w1, w2


def test_module_imports_without_onnx():
    from flexflow_tpu.onnx import ONNXModel, ONNXModelKeras  # noqa: F401


def test_wire_codec_roundtrip():
    proto, w1, w2 = _mlp_bytes()
    m = wire.load(proto)
    assert [n.op_type for n in m.graph.node] == ["MatMul", "Relu", "MatMul"]
    inits = {t.name: wire.to_array(t) for t in m.graph.initializer}
    np.testing.assert_array_equal(inits["w1"], w1)
    np.testing.assert_array_equal(inits["w2"], w2)
    assert [i.name for i in m.graph.input] == ["x", "w1", "w2"]
    assert m.graph.input[0].dims == [8, 20]


@pytest.mark.skipif(not HAS_ONNX, reason="onnx not installed")
def test_wire_bytes_parse_with_real_onnx():
    """The wire encoder's output is schema-valid for the onnx package."""
    proto, w1, _ = _mlp_bytes()
    m = onnx.ModelProto()
    m.ParseFromString(proto)
    assert [n.op_type for n in m.graph.node] == ["MatMul", "Relu", "MatMul"]
    import onnx.numpy_helper as nph

    got = {t.name: nph.to_array(t) for t in m.graph.initializer}
    np.testing.assert_array_equal(got["w1"], w1)
    onnx.checker.check_model(m)


def test_onnx_mlp_roundtrip():
    proto, w1, w2 = _mlp_bytes()
    from flexflow_tpu.onnx import ONNXModel

    config = ff.FFConfig()
    config.batch_size = 8
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    t = model.create_tensor([8, 20], ff.DataType.DT_FLOAT)
    om = ONNXModel(proto)
    outs = om.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    assert om.transfer_weights(model) == 2
    rng = np.random.RandomState(0)
    x = rng.randn(8, 20).astype(np.float32)
    ours = model.predict(x)
    ref = np.maximum(x @ w1, 0) @ w2
    np.testing.assert_allclose(ours, ref, atol=1e-4, rtol=1e-4)


def test_onnx_conv_attrs_and_file_load(tmp_path):
    """Conv with pads/strides + Gemm head, loaded from a FILE path."""
    rng = np.random.RandomState(1)
    k = rng.randn(4, 2, 3, 3).astype(np.float32) * 0.2
    gw = rng.randn(4 * 4 * 4, 5).astype(np.float32) * 0.2
    nodes = [
        wire.make_node("Conv", ["x", "k"], ["c"], name="conv1",
                       kernel_shape=[3, 3], strides=[2, 2],
                       pads=[1, 1, 1, 1]),
        wire.make_node("Relu", ["c"], ["cr"], name="r1"),
        wire.make_node("Flatten", ["cr"], ["f"], name="flat1"),
        wire.make_node("MatMul", ["f", "gw"], ["y"], name="fc"),
    ]
    proto = wire.make_model(nodes, {"x": (2, 2, 8, 8)}, {"y": (2, 5)},
                            {"k": k, "gw": gw})
    path = str(tmp_path / "conv.onnx")
    wire.save(proto, path)

    from flexflow_tpu.onnx import ONNXModel

    config = ff.FFConfig()
    config.batch_size = 2
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    t = model.create_tensor([2, 2, 8, 8], ff.DataType.DT_FLOAT)
    om = ONNXModel(path)
    outs = om.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    assert om.transfer_weights(model) == 2
    x = rng.randn(2, 2, 8, 8).astype(np.float32)
    ours = model.predict(x)

    import jax

    ref_c = jax.lax.conv_general_dilated(
        x, k, (2, 2), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ref = np.maximum(np.asarray(ref_c), 0).reshape(2, -1) @ gw
    np.testing.assert_allclose(ours, ref, atol=1e-3, rtol=1e-3)
