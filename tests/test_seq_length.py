"""Iteration seq_length truncation (reference: FFIterationConfig
config.h:162-167 threading into batch_matmul.cc:77-90 and attention):
forward(seq_length=L) computes the first L positions only."""
import numpy as np

import flexflow_tpu as ff


def test_attention_forward_truncates_to_seq_length():
    B, S, E, H = 2, 8, 16, 4
    L = 5
    rng = np.random.RandomState(3)
    x = rng.randn(B, S, E).astype(np.float32)

    config = ff.FFConfig()
    config.batch_size = B
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([B, S, E])
    out = model.multihead_attention(inp, inp, inp, E, H, name="attn")
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)

    model.set_iteration_batch([x], np.zeros((B, S, E), np.float32))
    full = np.asarray(model.forward())
    trunc = np.asarray(model.forward(seq_length=L))

    # reference oracle: running the full forward on the truncated input
    model2 = ff.FFModel(config)
    inp2 = model2.create_tensor([B, L, E])
    out2 = model2.multihead_attention(inp2, inp2, inp2, E, H, name="attn")
    model2.final_tensor = out2
    model2.compile(optimizer=ff.SGDOptimizer(model2, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    model2.params = model.params  # same weights
    model2.set_iteration_batch([x[:, :L]], np.zeros((B, L, E), np.float32))
    ref = np.asarray(model2.forward())

    np.testing.assert_allclose(trunc[:, :L], ref, rtol=1e-5, atol=1e-6)
    assert np.all(trunc[:, L:] == 0.0)
    # and the truncated pass differs from the full one (it really truncated)
    assert not np.allclose(trunc[:, :L], full[:, :L])


def test_batch_matmul_seq_length_dims_truncate():
    B, S, D = 2, 6, 4
    L = 3
    rng = np.random.RandomState(4)
    a = rng.randn(B, S, D).astype(np.float32)
    b = rng.randn(B, D, S).astype(np.float32)

    config = ff.FFConfig()
    config.batch_size = B
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    ta = model.create_tensor([B, S, D])
    tb = model.create_tensor([B, D, S])
    out = model.batch_matmul(ta, tb, a_seq_length_dim=1, b_seq_length_dim=2)
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)

    model.set_iteration_batch([a, b], np.zeros((B, S, S), np.float32))
    got = np.asarray(model.forward(seq_length=L))
    ref = a[:, :L] @ b[:, :, :L]
    np.testing.assert_allclose(got[:, :L, :L], ref, rtol=1e-5, atol=1e-6)
    assert np.all(got[:, L:, :] == 0.0) and np.all(got[:, :, L:] == 0.0)


def test_backward_seq_length_zeroes_truncated_grads():
    B, S, E, H = 2, 8, 16, 4
    L = 4
    config = ff.FFConfig()
    config.batch_size = B
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([B, S, E])
    out = model.multihead_attention(inp, inp, inp, E, H, name="attn")
    model.dense(out, 3, name="cls")
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.01),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    x = np.random.RandomState(0).randn(B, S, E).astype(np.float32)
    y = np.zeros((B, S, 1), dtype=np.int32)
    import jax

    model.set_iteration_batch([x], y)
    model.forward()
    model.backward()
    full_grads = jax.tree_util.tree_map(np.asarray, model._manual["grads"])
    model.forward(seq_length=L)
    model.backward(seq_length=L)
    model.update()
    grads = model._manual["grads"]
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # the truncated executable really ran: attention-weight grads differ
    # from the full-length backward
    wq_full = full_grads["attn"]["wq"]
    wq_trunc = np.asarray(grads["attn"]["wq"])
    assert not np.allclose(wq_full, wq_trunc)
