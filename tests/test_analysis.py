"""Plan sanitizer tests (ISSUE 2): a table of known-bad PCGs each
asserting its exact FFTA0xx diagnostic code, one case per pass family;
every example/zoo model's searched plan passing the analyzer clean; the
compile()-time pre-flight gate; import_strategy validation; and the
serving /metrics analyzer counters."""
import json

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.analysis import (
    PlanAnalysisError,
    Severity,
    analyze_plan,
    check_plan,
    diagnostic_counters,
    factorization_diagnostics,
    reset_counters,
)
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.ffconst import DataType
from flexflow_tpu.search.machine_model import (
    ChipSpec,
    SimpleMachineModel,
    make_machine_model,
)
from flexflow_tpu.search.simulator import CostModel, OpStrategy
from flexflow_tpu.search.unity import import_strategy, unity_optimize


def build_mlp(batch=64, din=512, hidden=2048, classes=10):
    config = ff.FFConfig()
    config.batch_size = batch
    m = ff.FFModel(config)
    inp = m.create_tensor([batch, din])
    t = m.dense(inp, hidden, ff.ActiMode.AC_MODE_RELU)
    t = m.dense(t, classes)
    m.softmax(t)
    return m, Graph(m.ops), config


def op_named(graph, fragment):
    return next(op for op in graph.ops.values() if fragment in op.name)


# ---------------------------------------------------------------------
# pass family 1: divisibility / degree
# ---------------------------------------------------------------------
def test_non_dividing_dp_degree_ffta001():
    _, g, config = build_mlp(batch=64)
    dense = op_named(g, "linear")
    report = analyze_plan(
        g, strategies={dense.guid: OpStrategy(dp=7)},
        mesh_axes={"data": 7}, batch_size=64, n_devices=8, config=config)
    assert [d.code for d in report.errors()] == ["FFTA001"]
    assert report.by_code("FFTA001")[0].op_name == dense.name


def test_non_dividing_tp_degree_ffta001():
    _, g, config = build_mlp(hidden=2048)
    dense = op_named(g, "linear")
    report = analyze_plan(
        g, strategies={dense.guid: OpStrategy(tp=3)},
        mesh_axes={"model": 3}, batch_size=64, n_devices=8, config=config)
    assert "FFTA001" in report.counts()


def test_degree_exceeds_devices_ffta003():
    _, g, config = build_mlp()
    dense = op_named(g, "linear")
    report = analyze_plan(
        g, strategies={dense.guid: OpStrategy(dp=8, tp=2)},
        mesh_axes={"data": 8, "model": 2}, batch_size=64, n_devices=8,
        config=config)
    assert "FFTA003" in report.counts()
    assert not report.ok


def test_degraded_degree_is_warning_ffta002():
    # tp on a non-tensor-parallel op degrades to replicated: suspicious
    # (the cost model over-promised) but legal
    _, g, config = build_mlp()
    sm = op_named(g, "softmax")
    report = analyze_plan(
        g, strategies={sm.guid: OpStrategy(tp=2)}, mesh_axes={"model": 2},
        batch_size=64, n_devices=8, config=config)
    assert [d.code for d in report.warnings()] == ["FFTA002"]
    assert report.ok


def test_factorization_diagnostics_ffta001_ffta004():
    _, g, config = build_mlp(batch=64)
    assert [d.code for d in
            factorization_diagnostics(g, config, 64, (3, 1, 1, 1, 1))] \
        == ["FFTA001"]
    # no EXPERTS ops: the expert axis is unusable, not a divisibility issue
    assert [d.code for d in
            factorization_diagnostics(g, config, 64, (1, 1, 2, 1, 1))] \
        == ["FFTA004"]
    # EXPERTS present but ep does not divide the expert count
    m = ff.FFModel(config)
    x = m.create_tensor([64, 32])
    gate = m.softmax(m.dense(x, 3))
    m.experts(x, gate, gate, num_exp=3, out_dim=32)
    ge = Graph(m.ops)
    assert [d.code for d in
            factorization_diagnostics(ge, config, 64, (1, 1, 2, 1, 1))] \
        == ["FFTA001"]
    # attribute/sequence parallelism unusable by this graph/config
    assert [d.code for d in
            factorization_diagnostics(g, config, 64, (1, 1, 1, 2, 1))] \
        == ["FFTA004"]
    assert [d.code for d in
            factorization_diagnostics(g, config, 64, (1, 1, 1, 1, 2))] \
        == ["FFTA004"]
    assert factorization_diagnostics(g, config, 64, (2, 2, 1, 1, 1)) == []


# ---------------------------------------------------------------------
# pass family 2: memory fit
# ---------------------------------------------------------------------
def test_hbm_overflow_ffta010():
    _, g, config = build_mlp(hidden=4096)
    machine = SimpleMachineModel(4, ChipSpec(hbm_gb=1e-4))  # 100 KB "HBM"
    report = analyze_plan(g, strategies={}, machine=machine,
                          batch_size=64, n_devices=4, config=config)
    assert [d.code for d in report.errors()] == ["FFTA010"]


def test_explicit_memory_budget_overrides_chip_spec():
    # an explicitly set --memory-budget is authoritative (the gate must
    # agree with the memory-aware search): raising it past the estimate
    # clears the overflow even on a tiny chip spec
    _, g, config = build_mlp(hidden=4096)
    config.memory_budget_mb = 64 * 1024.0
    machine = SimpleMachineModel(4, ChipSpec(hbm_gb=1e-4))
    report = analyze_plan(g, strategies={}, machine=machine,
                          batch_size=64, n_devices=4, config=config)
    assert not report.by_code("FFTA010")


def test_hbm_overflow_under_stage_sharding_warns():
    # the per-op sum cannot see GPipe 'stage' weight sharding: overflow on
    # a pipeline plan degrades to a warning instead of rejecting a plan the
    # memory-aware search chose precisely to fit
    _, g, config = build_mlp(hidden=4096)
    machine = SimpleMachineModel(4, ChipSpec(hbm_gb=1e-4))
    report = analyze_plan(g, strategies={}, machine=machine,
                          mesh_axes={"data": 2, "stage": 2},
                          batch_size=64, n_devices=4, config=config)
    diags = report.by_code("FFTA010")
    assert len(diags) == 1 and diags[0].severity is Severity.WARNING
    assert report.ok


def test_hbm_near_capacity_warns_ffta011():
    _, g, config = build_mlp()
    probe = CostModel(SimpleMachineModel(4, ChipSpec()), config)
    total = sum(probe.op_memory_bytes(op, OpStrategy())
                for op in g.ops.values())
    machine = SimpleMachineModel(4, ChipSpec(hbm_gb=total / 0.9 / 1e9))
    report = analyze_plan(g, strategies={}, machine=machine,
                          batch_size=64, n_devices=4, config=config)
    assert [d.code for d in report.warnings()] == ["FFTA011"]
    assert report.ok


# ---------------------------------------------------------------------
# pass family 3: collective legality
# ---------------------------------------------------------------------
def test_mismatched_reduction_edge_ffta020():
    # row-parallel (reduction) strategy on a non-LINEAR op: the partial-sum
    # all-reduce pairing has no meaning there
    _, g, config = build_mlp()
    sm = op_named(g, "softmax")
    report = analyze_plan(
        g, strategies={sm.guid: OpStrategy(tp=2, tp_row=True)},
        mesh_axes={"model": 2}, batch_size=64, n_devices=8, config=config)
    assert "FFTA020" in [d.code for d in report.errors()]


def test_row_parallel_non_dividing_ffta020():
    _, g, config = build_mlp(din=510)  # 510 % 4 != 0
    dense = op_named(g, "linear")
    report = analyze_plan(
        g, strategies={dense.guid: OpStrategy(tp=4, tp_row=True)},
        mesh_axes={"model": 4}, batch_size=64, n_devices=8, config=config)
    assert "FFTA020" in [d.code for d in report.errors()]


def test_mesh_axis_conflict_ffta021():
    _, g, config = build_mlp()
    d1, d2 = [op for op in g.topo_order() if "linear" in op.name]
    report = analyze_plan(
        g, strategies={d1.guid: OpStrategy(dp=2), d2.guid: OpStrategy(dp=4)},
        mesh_axes={"data": 4}, batch_size=64, n_devices=8, config=config)
    assert "FFTA021" in [d.code for d in report.errors()]


def test_mesh_needs_more_devices_ffta023():
    _, g, config = build_mlp()
    report = analyze_plan(g, strategies={}, mesh_axes={"data": 4, "model": 4},
                          batch_size=64, n_devices=8, config=config)
    assert [d.code for d in report.errors()] == ["FFTA023"]


def test_reshard_ping_pong_warns_ffta022():
    # dp dips to 1 between a finer-sharded producer and consumer: gather
    # followed by re-partition on the same chain
    _, g, config = build_mlp()
    ops = g.topo_order()
    inp, d1, d2, sm = ops
    report = analyze_plan(
        g, strategies={inp.guid: OpStrategy(dp=4), d1.guid: OpStrategy(dp=4),
                       d2.guid: OpStrategy(dp=1), sm.guid: OpStrategy(dp=4)},
        mesh_axes={"data": 4}, batch_size=64, n_devices=8, config=config)
    assert "FFTA022" in [d.code for d in report.warnings()]


# ---------------------------------------------------------------------
# pass family 4: aliasing / donation under the elastic runtime
# ---------------------------------------------------------------------
def test_donation_under_elastic_warns_ffta030():
    _, g, config = build_mlp()
    config.elastic_step_wrapper = lambda fn: fn
    report = analyze_plan(g, strategies={}, batch_size=64, n_devices=8,
                          config=config)
    diags = report.by_code("FFTA030")
    assert len(diags) == 1 and diags[0].severity is Severity.WARNING
    assert report.ok  # warning, not rejection


def test_default_strategies_mirror_attention_dropout_sp_guard():
    # _assign_strategy leaves a dropout-carrying attention op unsharded
    # under a 'seq' axis (the SP kernels have no attention-prob dropout);
    # default_strategies_for must model the same plan, or the memory pass
    # sizes that op's activations divided by sp and misses real overflow
    from flexflow_tpu.analysis import default_strategies_for

    config = ff.FFConfig()
    config.batch_size = 2
    m = ff.FFModel(config)
    t = m.create_tensor([2, 32, 64])
    attn = m.multihead_attention(t, t, t, 64, 4, dropout=0.1)
    m.softmax(m.dense(attn, 4))
    g = Graph(m.ops)
    strategies = default_strategies_for(g, {"seq": 2}, batch_size=2)
    attn_op = op_named(g, "multihead_attention")
    dense_op = op_named(g, "linear")
    assert strategies[attn_op.guid].sp == 1  # dropout: stays unsharded
    assert strategies[dense_op.guid].sp == 2  # plain position dim shards


def test_no_donation_warning_without_elastic():
    _, g, config = build_mlp()
    report = analyze_plan(g, strategies={}, batch_size=64, n_devices=8,
                          config=config)
    assert not report.by_code("FFTA030")


# ---------------------------------------------------------------------
# pass family 5: graph hygiene
# ---------------------------------------------------------------------
def test_dangling_producer_ffta040():
    _, g, config = build_mlp()
    d1 = op_named(g, "linear")
    del g.ops[d1.guid]  # bypass remove_op: simulate a buggy rewrite
    report = analyze_plan(g, strategies={}, config=config)
    assert "FFTA040" in [d.code for d in report.errors()]


def test_stale_alias_chain_ffta041():
    _, g, config = build_mlp()
    sm = op_named(g, "softmax")
    del g.ops[sm.guid]  # bypass remove_op's alias cleanup
    g.tensor_aliases[999999] = sm.outputs[0]
    report = analyze_plan(g, strategies={}, config=config)
    assert "FFTA041" in [d.code for d in report.warnings()]


def test_unreachable_op_ffta042():
    _, g, config = build_mlp()
    ops = g.topo_order()
    d2 = ops[-2]  # treat the second dense as the final output
    report = analyze_plan(g, strategies={}, config=config,
                          final_guid=d2.guid)
    assert [d.op_name for d in report.by_code("FFTA042")] == [ops[-1].name]


def test_mixed_dtype_elementwise_ffta043():
    config = ff.FFConfig()
    config.batch_size = 8
    m = ff.FFModel(config)
    x = m.create_tensor([8, 16])
    y = m.create_tensor([8, 16])
    m.add(x, y)
    g = Graph(m.ops)
    y.dtype = DataType.DT_HALF  # boundary dtype mismatch
    report = analyze_plan(g, strategies={}, config=config)
    assert "FFTA043" in [d.code for d in report.warnings()]


def test_remove_op_drops_dangling_aliases():
    # satellite: Graph.remove_op must drop alias chains that dead-end at
    # the removed op's outputs, so resolve_tensor can't hand them back
    _, g, _ = build_mlp()
    inp, d1, d2, sm = g.topo_order()
    g.tensor_aliases[d1.outputs[0].guid] = d2.outputs[0]
    g.remove_op(sm)  # d2's alias target survives: d2 still in graph
    assert d1.outputs[0].guid in g.tensor_aliases
    g.remove_op(d2)  # now the chain dead-ends: both entries must go
    assert d1.outputs[0].guid not in g.tensor_aliases
    t = g.resolve_tensor(d1.outputs[0])
    assert t is d1.outputs[0]


def test_remove_op_keeps_repaired_chains():
    _, g, _ = build_mlp()
    inp, d1, d2, sm = g.topo_order()
    # chain d1.out -> d2.out -> sm.out: removing d2 keeps both entries
    # because they resolve through to sm, which is still in the graph
    g.tensor_aliases[d1.outputs[0].guid] = d2.outputs[0]
    g.tensor_aliases[d2.outputs[0].guid] = sm.outputs[0]
    g.remove_op(d2)
    assert g.resolve_tensor(d1.outputs[0]) is sm.outputs[0]


# ---------------------------------------------------------------------
# strategy-file validation (import_strategy)
# ---------------------------------------------------------------------
def test_import_strategy_malformed_entry_ffta050(tmp_path):
    _, g, _ = build_mlp()
    path = tmp_path / "bad.json"
    path.write_text(json.dumps({
        "mesh_axes": {"data": 2},
        "ops": {op_named(g, "linear").name: {"dp": 0, "tp": "x"}},
    }))
    with pytest.raises(PlanAnalysisError) as exc:
        import_strategy(g, str(path))
    assert [d.code for d in exc.value.report.errors()] == ["FFTA050"]


def test_import_strategy_no_ops_mapping_ffta050(tmp_path):
    _, g, _ = build_mlp()
    path = tmp_path / "empty.json"
    path.write_text(json.dumps({"mesh_axes": {}}))
    with pytest.raises(PlanAnalysisError) as exc:
        import_strategy(g, str(path))
    assert "FFTA050" in [d.code for d in exc.value.report.errors()]


def test_import_strategy_unmatched_name_ffta051(tmp_path):
    reset_counters()
    _, g, _ = build_mlp()
    path = tmp_path / "unmatched.json"
    path.write_text(json.dumps({
        "mesh_axes": {"data": 2},
        "ops": {"no_such_op": {"dp": 2, "tp": 1}},
    }))
    strategies, axes = import_strategy(g, str(path))  # warns, no raise
    assert strategies == {} and axes == {"data": 2}
    assert diagnostic_counters().get("FFTA051") == 1


# ---------------------------------------------------------------------
# compile()-time pre-flight gate
# ---------------------------------------------------------------------
def _tiny_model(plan_analysis="error", **cfg_overrides):
    config = ff.FFConfig()
    config.batch_size = 8
    config.num_devices = 1
    config.plan_analysis = plan_analysis
    for k, v in cfg_overrides.items():
        setattr(config, k, v)
    m = ff.FFModel(config)
    inp = m.create_tensor([8, 16])
    t = m.dense(inp, 8)
    m.softmax(t)
    return m


def test_compile_gate_passes_clean_model():
    m = _tiny_model()
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.01))
    assert m.analyze_plan().ok


def test_compile_gate_rejects_oversized_mesh():
    m = _tiny_model()
    with pytest.raises(PlanAnalysisError) as exc:
        m.compile(optimizer=ff.SGDOptimizer(m, lr=0.01),
                  parallel_axes={"data": 128})
    assert "FFTA023" in [d.code for d in exc.value.report.errors()]


def test_compile_gate_warn_mode_does_not_raise():
    m = _tiny_model(plan_analysis="warn",
                    elastic_step_wrapper=lambda fn: fn)
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.01))
    kinds = [e.kind for e in m.analysis_events.events()]
    assert "analysis.warning" in kinds  # FFTA030 landed in the event log


def test_compile_gate_warn_mode_logs_errors(caplog):
    # warn mode must not swallow error-severity diagnostics: they skip the
    # raise but still reach the log (and the event log/counters)
    import logging

    m = _tiny_model(plan_analysis="warn")
    with caplog.at_level(logging.ERROR, logger="flexflow_tpu.model"):
        # the gate lets the plan through (warn), so compile still dies
        # later in mesh construction — but only after logging the errors
        with pytest.raises(ValueError, match="mesh needs"):
            m.compile(optimizer=ff.SGDOptimizer(m, lr=0.01),
                      parallel_axes={"data": 128})
    assert any("FFTA023" in r.getMessage() for r in caplog.records)


def test_compile_gate_off_mode_skips():
    # gate off: the illegal mesh sails past the sanitizer and dies later,
    # deep in mesh construction — exactly the late opaque error the
    # pre-flight gate exists to replace
    m = _tiny_model(plan_analysis="off")
    with pytest.raises(ValueError, match="mesh needs"):
        m.compile(optimizer=ff.SGDOptimizer(m, lr=0.01),
                  parallel_axes={"data": 128})
    assert not hasattr(m, "analysis_events")


# ---------------------------------------------------------------------
# serving /metrics analyzer counters
# ---------------------------------------------------------------------
def test_metrics_export_analyzer_counters():
    from flexflow_tpu.serving.server import InferenceServer

    reset_counters()
    _, g, config = build_mlp()
    with pytest.raises(PlanAnalysisError):
        check_plan(g, mesh_axes={"data": 4, "model": 4}, batch_size=64,
                   n_devices=8, config=config, strategies={})
    server = InferenceServer()
    text = server.prometheus_text()
    assert 'ff_plan_diagnostics_total{code="FFTA023"} 1' in text
    assert server.stats()["_analysis"]["FFTA023"] == 1
    reset_counters()
    # post-reset the registry keeps the family registered (TYPE/HELP
    # headers may render) but every per-code sample is gone
    assert "ff_plan_diagnostics_total{" not in server.prometheus_text()


# ---------------------------------------------------------------------
# the analyze CLI
# ---------------------------------------------------------------------
def test_analyze_cli_clean(capsys):
    from flexflow_tpu.analysis.cli import run_analyze

    assert run_analyze(["--model", "mnist_mlp", "--chips", "4"]) == 0
    assert "plan OK" in capsys.readouterr().out


def test_analyze_cli_json_schema_v1(capsys):
    """--json keeps stdout PURE machine-readable under the stable v1
    schema (the human verdict moves to stderr) — the contract the CI
    verify-plans job parses."""
    from flexflow_tpu.analysis.cli import run_analyze

    assert run_analyze(["--model", "mnist_mlp", "--chips", "4",
                        "--json"]) == 0
    out, err = capsys.readouterr()
    doc = json.loads(out)  # would raise if a verdict line leaked in
    assert doc["schema"] == 1
    assert doc["ok"] is True and doc["errors"] == 0
    assert set(doc) >= {"schema", "ok", "errors", "warnings", "counts",
                        "passes_run", "diagnostics"}
    assert "flow" in doc["passes_run"]
    assert "plan OK" in err


def test_analyze_cli_missing_flag_value_is_usage_error(capsys):
    from flexflow_tpu.analysis.cli import run_analyze

    assert run_analyze(["--model"]) == 2
    assert "needs a value" in capsys.readouterr().err


def test_analyze_cli_rejects_bad_strategy(tmp_path, capsys):
    from flexflow_tpu.analysis.cli import run_analyze

    path = tmp_path / "bad.json"
    path.write_text(json.dumps({
        "mesh_axes": {"data": 16},  # 16 > 4 devices
        "ops": {"linear_0": {"dp": 16, "tp": 1}},
    }))
    assert run_analyze(["--model", "mnist_mlp", "--chips", "4",
                        "--strategy", str(path)]) == 1
    assert "FFTA023" in capsys.readouterr().out


# ---------------------------------------------------------------------
# every example/zoo model's searched plan passes the analyzer clean
# ---------------------------------------------------------------------
def _zoo_graph(name, batch):
    from flexflow_tpu import models as zoo

    config = ff.FFConfig()
    config.batch_size = batch
    m = ff.FFModel(config)
    if name == "mnist_mlp":
        zoo.build_mnist_mlp(m, m.create_tensor([batch, 784]))
    elif name == "mnist_cnn":
        zoo.build_mnist_cnn(m, m.create_tensor([batch, 1, 28, 28]))
    elif name == "cifar10_cnn":
        zoo.build_cifar10_cnn(m, m.create_tensor([batch, 3, 32, 32]))
    elif name == "alexnet":
        zoo.build_alexnet(m, m.create_tensor([batch, 3, 229, 229]))
    elif name == "mlp_unify":
        zoo.build_mlp_unify(m, m.create_tensor([batch, 4096]),
                            m.create_tensor([batch, 4096]))
    elif name == "bert_small":
        cfg = zoo.TransformerConfig(hidden_size=64, embedding_size=64,
                                    num_heads=4, num_layers=2,
                                    sequence_length=32, vocab_size=128)
        zoo.build_bert_encoder(
            m, m.create_tensor([batch, 32], ff.DataType.DT_INT32), cfg)
    elif name == "moe_small":
        cfg = zoo.MoeConfig(hidden_size=32, num_attention_heads=4,
                            num_encoder_layers=1, num_exp=4, num_select=2)
        zoo.build_moe_encoder(m, m.create_tensor([batch, 16, 32]), cfg)
    else:
        raise AssertionError(name)
    return m, Graph(m.ops), config


@pytest.mark.parametrize("name", ["mnist_mlp", "mnist_cnn", "cifar10_cnn",
                                  "alexnet", "mlp_unify", "bert_small",
                                  "moe_small"])
def test_searched_plans_pass_analyzer(name):
    batch = 16
    _, g, config = _zoo_graph(name, batch)
    config.search_budget = 2
    config.use_native_search = False
    n_dev = 4
    machine = make_machine_model(config, n_dev)
    result = unity_optimize(g, config, machine, batch, n_dev)
    report = analyze_plan(
        g, strategies=result.strategies, machine=machine, config=config,
        batch_size=batch, n_devices=n_dev, mesh_axes=result.mesh_axes,
        final_guid=g.topo_order()[-1].guid)
    assert report.ok, report.format()


# ---------------------------------------------------------------------
# pass family 8: mixture-of-experts legality (FFTA08x)
# ---------------------------------------------------------------------
def _moe_graph(batch=32, n=4, k=2, alpha=None, lambda_bal=0.0,
               mixed=False):
    config = ff.FFConfig()
    config.batch_size = batch
    if mixed:
        config.allow_mixed_precision = True
    m = ff.FFModel(config)
    inp = m.create_tensor([batch, 8])
    out = m.moe(inp, n, k, 12,
                alpha=float(n) if alpha is None else alpha,
                lambda_bal=lambda_bal, fused=True, name="moe")
    m.dense(out, 3)
    return m, Graph(m.ops), config


def test_degenerate_capacity_warns_ffta080():
    # ceil(0.1 * 2 * 32 / 64) = 1 < k=2: the clamp silently raises it
    _, g, config = _moe_graph(batch=32, n=64, alpha=0.1)
    report = analyze_plan(g, batch_size=32, n_devices=1, config=config,
                          passes=("moe",))
    assert report.ok  # warning, not error
    diag = report.by_code("FFTA080")[0]
    assert "clamps" in diag.message


def test_non_dividing_ep_strategy_ffta081():
    m, g, config = _moe_graph(n=4)
    experts = op_named(g, "moe_experts")
    report = analyze_plan(
        g, strategies={experts.guid: OpStrategy(dp=2, ep=3)},
        mesh_axes={"data": 2, "expert": 3}, batch_size=32, n_devices=6,
        config=config, passes=("moe",))
    assert [d.code for d in report.errors()] == ["FFTA081"]


def test_unusable_expert_axis_warns_ffta081():
    """A mesh expert axis the op cannot divide degrades to replicated:
    legal (warning), but the axis's devices idle through the expert FFN."""
    _, g, config = _moe_graph(n=4)
    report = analyze_plan(
        g, mesh_axes={"data": 2, "expert": 3}, batch_size=32,
        n_devices=6, config=config, passes=("moe",))
    assert report.ok
    assert report.by_code("FFTA081")
    assert report.by_code("FFTA081")[0].severity == Severity.WARNING


def test_balance_loss_without_full_gate_ffta082():
    """A hand-built EXPERTS op carrying lambda_bal without the full gate
    distribution cannot lower its aux loss."""
    m, g, config = _moe_graph(lambda_bal=0.05)
    experts = op_named(g, "moe_experts")
    experts.params["lambda_bal"] = 0.05
    experts.inputs = experts.inputs[:3]  # drop the wired full_gate
    report = analyze_plan(g, batch_size=32, n_devices=1, config=config,
                          passes=("moe",))
    assert "FFTA082" in report.counts()
    assert not report.ok


def test_mixed_precision_router_warns_ffta083():
    _, g, config = _moe_graph(mixed=True)
    report = analyze_plan(g, batch_size=32, n_devices=1, config=config,
                          passes=("moe",))
    assert report.ok
    assert report.by_code("FFTA083")


def test_sub_unit_capacity_factor_warns_ffta084():
    _, g, config = _moe_graph(batch=64, n=4, alpha=0.5)
    report = analyze_plan(g, batch_size=64, n_devices=1, config=config,
                          passes=("moe",))
    assert report.ok
    assert report.by_code("FFTA084")


def test_pod_spanning_ep_factorization_ffta085():
    """factorization_diagnostics with a pod degree rejects ep tuples whose
    span (ep x nested sp/ap) crosses the pod; pod-resident tuples and
    flat machines (pod_degree=None) pass."""
    _, g, config = _moe_graph(n=16)
    assert factorization_diagnostics(
        g, config, 32, (2, 1, 8, 1, 1), pod_degree=8) == []
    diags = factorization_diagnostics(
        g, config, 32, (1, 1, 16, 1, 1), pod_degree=8)
    assert [d.code for d in diags] == ["FFTA085"]
    # nested axes count against the span: ep=8 with sp=2 inside crosses
    diags = factorization_diagnostics(
        g, config, 32, (1, 1, 8, 1, 2), pod_degree=8)
    assert any(d.code == "FFTA085" for d in diags)
    assert factorization_diagnostics(
        g, config, 32, (1, 1, 16, 1, 1), pod_degree=None) == []
