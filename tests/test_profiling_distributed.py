"""Profiling utilities and multi-host helpers (single-process CPU mesh)."""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.runtime import distributed, profiling


def small_model():
    config = ff.FFConfig()
    config.batch_size = 8
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 16])
    t = model.dense(inp, 32, ff.ActiMode.AC_MODE_RELU)
    t = model.dense(t, 4)
    model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    return model


def test_profile_ops_returns_timings():
    model = small_model()
    rows = profiling.profile_ops(model, warmup=1, repeats=2)
    types = {r["type"] for r in rows}
    assert "linear" in types and "softmax" in types
    measured = [r for r in rows if "error" not in r]
    assert measured and all(r["forward_us"] > 0 for r in measured)
    profiling.print_profile(rows, top=5)


def test_profiling_flag_prints_iteration_rate(capsys):
    config = ff.FFConfig()
    config.batch_size = 8
    config.profiling = True
    config.print_freq = 2
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 16])
    model.softmax(model.dense(inp, 4))
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    rng = np.random.RandomState(0)
    x = rng.randn(64, 16).astype(np.float32)
    yl = np.zeros((64, 1), dtype=np.int32)
    model.fit(x, yl, epochs=1)
    out = capsys.readouterr().out
    assert "samples/s" in out and "ms/iter" in out


def test_host_info_single_process():
    info = distributed.host_info()
    assert info["process_count"] == 1
    assert info["global_devices"] >= 1
    assert not distributed.is_multi_host()


def test_pod_mesh_axes():
    mesh = distributed.pod_mesh({"data": 4, "model": 2})
    assert mesh.axis_names == ("data", "model")
    assert dict(mesh.shape) == {"data": 4, "model": 2}


def test_callbacks_early_stopping_and_lr_schedule():
    from flexflow_tpu.keras.callbacks import EarlyStopping, LearningRateScheduler

    class FakeFF:
        def __init__(self):
            self.opt_state = {"lr": 0.1}
            self.set_calls = []

        def set_learning_rate(self, lr):
            self.set_calls.append(lr)
            self.opt_state["lr"] = lr

    class FakeModel:
        def __init__(self):
            self.ffmodel = FakeFF()
            self.stop_training = False

    m = FakeModel()
    sched = LearningRateScheduler(lambda epoch, lr: lr * 0.5)
    sched.set_model(m)
    sched.on_epoch_begin(0)
    sched.on_epoch_begin(1)
    assert m.ffmodel.set_calls == [0.05, 0.025]

    es = EarlyStopping(monitor="loss", patience=2)
    es.set_model(m)
    es.on_train_begin()
    for epoch, loss in enumerate([1.0, 0.5, 0.6, 0.55]):
        es.on_epoch_end(epoch, {"loss": loss})
    assert m.stop_training  # no improvement for 2 epochs after 0.5
