"""Model/tensor C API surface (reference: flexflow_c.h model-building half):
a C caller (driven here through ctypes, exactly as a C program would link)
builds the graph, runs the native search, exports the spec, and the Python
runtime trains it."""
import ctypes
import json

import numpy as np
import pytest

from flexflow_tpu import native


def _lib():
    path = native.ensure_built()
    if path is None:
        pytest.skip("native core unavailable")
    lib = ctypes.CDLL(path)
    lib.ffc_model_create.argtypes = [ctypes.c_int]
    lib.ffc_model_create.restype = ctypes.c_void_p
    lib.ffc_model_destroy.argtypes = [ctypes.c_void_p]
    lib.ffc_model_last_error.argtypes = [ctypes.c_void_p]
    lib.ffc_model_last_error.restype = ctypes.c_char_p
    lib.ffc_tensor_create.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_int64),
                                      ctypes.c_char_p]
    lib.ffc_tensor_create.restype = ctypes.c_int64
    lib.ffc_op.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                           ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p]
    lib.ffc_op.restype = ctypes.c_int64
    lib.ffc_tensor_ndims.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.c_int]
    lib.ffc_tensor_ndims.restype = ctypes.c_int
    lib.ffc_model_export_json.argtypes = [ctypes.c_void_p]
    lib.ffc_model_export_json.restype = ctypes.c_void_p
    lib.ffc_model_optimize.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_double]
    lib.ffc_model_optimize.restype = ctypes.c_void_p
    lib.ffc_free.argtypes = [ctypes.c_void_p]
    return lib


def _take_string(lib, ptr):
    s = ctypes.string_at(ptr).decode()
    lib.ffc_free(ptr)
    return s


def _dims(vals):
    return (ctypes.c_int64 * len(vals))(*vals)


def _guids(vals):
    return (ctypes.c_int64 * len(vals))(*vals)


def _build_mlp(lib, batch=8):
    h = lib.ffc_model_create(batch)
    x = lib.ffc_tensor_create(h, 2, _dims([batch, 32]), b"float32")
    assert x > 0
    t = lib.ffc_op(h, b"dense", 1, _guids([x]), b"out_dim=64;activation=relu")
    assert t > 0, lib.ffc_model_last_error(h)
    t = lib.ffc_op(h, b"dense", 1, _guids([t]), b"out_dim=16")
    t = lib.ffc_op(h, b"softmax", 1, _guids([t]), b"")
    assert t > 0
    return h, t


def test_c_api_builds_infers_shapes_and_optimizes():
    lib = _lib()
    h, out = _build_mlp(lib)
    dims = (ctypes.c_int64 * 4)()
    n = lib.ffc_tensor_ndims(h, out, dims, 4)
    assert n == 2 and list(dims[:2]) == [8, 16]

    result = _take_string(lib, lib.ffc_model_optimize(h, 8, 4, 1.2))
    assert result.startswith("cost "), result
    assert "mesh " in result and "strategy " in result
    lib.ffc_model_destroy(h)


def test_c_api_error_reporting():
    lib = _lib()
    h = lib.ffc_model_create(8)
    bad = lib.ffc_op(h, b"warp_drive", 0, _guids([]), b"")
    assert bad == -1
    assert b"warp_drive" in lib.ffc_model_last_error(h)
    lib.ffc_model_destroy(h)


def test_c_built_model_trains_in_python_runtime():
    lib = _lib()
    h, _ = _build_mlp(lib)
    spec = _take_string(lib, lib.ffc_model_export_json(h))
    lib.ffc_model_destroy(h)
    doc = json.loads(spec)
    assert doc["format"] == "flexflow_tpu_c_model"

    import flexflow_tpu as ff
    from flexflow_tpu.native.c_model import model_from_spec

    model = model_from_spec(doc)
    model.compile(optimizer=ff.AdamOptimizer(model, alpha=1e-3),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[ff.MetricsType.METRICS_ACCURACY])
    x = np.random.RandomState(0).randn(8, 32).astype(np.float32)
    y = np.zeros((8, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=8, epochs=1)
    assert np.isfinite(hist[0]["loss"])
