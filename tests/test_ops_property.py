"""Property-based per-op tests (hypothesis): algebraic invariants that must
hold for ANY shape/seed, complementing the fixed-case align-vs-torch tests
(reference analog: tests/ops/ per-op numerical harness, SURVEY §4).

All properties run the REAL op lowerings through a jitted forward on the CPU
backend with mixed precision off (exact f32).
"""
import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="property tests need hypothesis")
from hypothesis import assume, given, settings, strategies as st  # noqa: E402

import flexflow_tpu as ff  # noqa: E402

SETTINGS = dict(max_examples=20, deadline=None)


def run_ops(build, *inputs):
    """Build a model with `build(model, tensors)` and run forward on inputs."""
    config = ff.FFConfig()
    config.batch_size = inputs[0].shape[0]
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    tensors = [
        model.create_tensor(list(x.shape),
                            ff.DataType.DT_INT32 if x.dtype == np.int32
                            else ff.DataType.DT_FLOAT)
        for x in inputs
    ]
    build(model, tensors)
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    out = model.predict(list(inputs) if len(inputs) > 1 else inputs[0])
    return out, model


@st.composite
def small_tensor(draw, min_dims=2, max_dims=4):
    ndim = draw(st.integers(min_dims, max_dims))
    shape = tuple(draw(st.integers(1, 6)) for _ in range(ndim))
    data = draw(st.integers(0, 2**31 - 1))
    return np.random.RandomState(data % 2**31).randn(*shape).astype(np.float32)


@given(x=small_tensor())
@settings(**SETTINGS)
def test_transpose_involution(x):
    """transpose(transpose(x, p), argsort(p)) == x for a random permutation."""
    rng = np.random.RandomState(int(abs(x.flat[0]) * 1e6) % 2**31)
    perm = list(rng.permutation(x.ndim))
    inv = list(np.argsort(perm))

    def build(m, ts):
        t = m.transpose(ts[0], perm)
        m.transpose(t, inv)

    out, _ = run_ops(build, x)
    np.testing.assert_allclose(out, x, atol=0, rtol=0)


@given(x=small_tensor(min_dims=2, max_dims=3),
       nsplit=st.integers(2, 3))
@settings(**SETTINGS)
def test_concat_of_split_is_identity(x, nsplit):
    """concat(split(x, sizes, axis), axis) == x."""
    axis = x.ndim - 1
    total = x.shape[axis]
    assume(total >= nsplit)
    base = total // nsplit
    sizes = [base] * (nsplit - 1) + [total - base * (nsplit - 1)]

    def build(m, ts):
        parts = m.split(ts[0], sizes, axis)
        m.concat(parts, axis)

    out, _ = run_ops(build, x)
    np.testing.assert_allclose(out, x, atol=0, rtol=0)


@given(x=small_tensor(min_dims=3, max_dims=3))
@settings(**SETTINGS)
def test_layer_norm_statistics(x):
    """LayerNorm output has mean ~0 and var ~1 over the normalized axis
    (affine is identity at init)."""
    assume(x.shape[-1] >= 2)

    def build(m, ts):
        m.layer_norm(ts[0], [-1])

    out = np.asarray(run_ops(build, x)[0], np.float32)
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-4)
    # biased variance, eps=1e-5 skews tiny-variance rows: loose bound
    row_var = out.var(-1)
    assert np.all(row_var < 1.05), row_var.max()


@given(x=small_tensor(min_dims=2, max_dims=4))
@settings(**SETTINGS)
def test_softmax_rows_sum_to_one(x):
    def build(m, ts):
        m.softmax(ts[0])

    out = np.asarray(run_ops(build, x)[0], np.float32)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)
    assert np.all(out >= 0)


@given(x=small_tensor(min_dims=2, max_dims=4))
@settings(**SETTINGS)
def test_relu_exp_pointwise(x):
    """Elementwise lowerings match numpy exactly in f32."""

    def build(m, ts):
        m.exp(m.relu(ts[0]))

    out, _ = run_ops(build, x)
    np.testing.assert_allclose(out, np.exp(np.maximum(x, 0.0)), rtol=1e-6)


@given(b=st.integers(1, 4), cin=st.integers(1, 4), cout=st.integers(1, 4),
       hw=st.integers(3, 8), k=st.integers(1, 3), stride=st.integers(1, 2),
       pad=st.integers(0, 1))
@settings(**SETTINGS)
def test_conv2d_output_shape_formula(b, cin, cout, hw, k, stride, pad):
    """Output spatial size matches the reference formula
    (h + 2p - k)//s + 1 for every legal config (conv_2d.cc shape rule)."""
    assume(hw + 2 * pad >= k)
    x = np.random.RandomState(0).randn(b, cin, hw, hw).astype(np.float32)

    def build(m, ts):
        m.conv2d(ts[0], cout, k, k, stride, stride, pad, pad)

    out = np.asarray(run_ops(build, x)[0])
    expect = (hw + 2 * pad - k) // stride + 1
    assert out.shape == (b, cout, expect, expect), out.shape


@given(x=small_tensor(min_dims=2, max_dims=2), w=st.integers(1, 8))
@settings(**SETTINGS)
def test_dense_linearity(x, w):
    """dense(a*x) == a*dense(x) for bias-free linear (homogeneity)."""

    def build(m, ts):
        m.dense(ts[0], w, use_bias=False)

    y1, model = run_ops(build, x)
    y1 = np.asarray(y1, np.float32)
    y2 = np.asarray(model.predict(2.0 * x), np.float32)
    np.testing.assert_allclose(y2, 2.0 * y1, rtol=1e-5, atol=1e-5)
