"""Live resharding (ISSUE 8): the redistribution planner/executor, the
FFTA06x analysis gate, and the elastic coordinator's zero-disk recovery.

The decisive properties:
 - `redistribute` is BIT-EXACT against the checkpoint-save -> reshard-
   restore reference path (values are only moved, never transformed);
 - the executor's instrumented per-chip scratch never exceeds the
   planner's `peak_bytes` bound;
 - a chip-loss recovery with verified, covered live state reads ZERO
   checkpoint files and resumes from the FAILING step; poisoned or
   uncovered state routes to the disk fallback.
"""
import os
import tempfile

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.analysis import PlanAnalysisError, check_redistribution
from flexflow_tpu.analysis.passes import (redistribution_diagnostics,
                                          survivor_diagnostics)
from flexflow_tpu.resharding import (ArraySpec, MeshSpec, ReshardPlanError,
                                     ShardingPlan, flatten_tree, plan_move,
                                     plan_redistribution,
                                     plan_slot_migration, redistribute,
                                     schedule_cost_us, uncovered_arrays,
                                     verify_live_tree)
from flexflow_tpu.search.machine_model import ChipSpec, SimpleMachineModel


def mesh8(dp=4, mp=2):
    return MeshSpec(device_ids=tuple(range(8)),
                    axes=(("data", dp), ("model", mp)))


def machine(n=8, hbm_gb=16.0):
    return SimpleMachineModel(n, ChipSpec(hbm_gb=hbm_gb))


# ---------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------
def test_plan_noop_when_nothing_changes():
    plan = ShardingPlan(mesh=mesh8(),
                        arrays={"w": ArraySpec((4, 1), ("data", None))})
    move = plan_move("w", (16, 8), 4, "float32", plan, plan, 1 << 30)
    assert move.noop and move.rounds == 1
    assert move.total_bytes_moved() == 0


def test_plan_gather_and_slice_steps():
    old = ShardingPlan(mesh=mesh8(),
                       arrays={"w": ArraySpec((4, 1), ("data", None))})
    new = ShardingPlan(mesh=mesh8(),
                       arrays={"w": ArraySpec((1, 2), (None, "model"))})
    move = plan_move("w", (16, 8), 4, "float32", old, new, 1 << 30)
    kinds = [s.kind for s in move.steps]
    assert kinds == ["allgather", "slice"]
    assert move.steps[0].axis == "data" and move.steps[0].dim == 0
    assert move.steps[1].axis == "model" and move.steps[1].dim == 1
    # nothing is kept sharded through the move: scratch = 2x global bytes
    assert move.peak_scratch_bytes == 2 * 16 * 8 * 4


def test_plan_kept_dim_divides_scratch():
    """A dim keeping (degree, axis) stays partitioned through the move."""
    old = ShardingPlan(
        mesh=mesh8(),
        arrays={"w": ArraySpec((4, 2), ("data", "model"))})
    new = ShardingPlan(
        mesh=mesh8(),
        arrays={"w": ArraySpec((1, 2), (None, "model"))})
    move = plan_move("w", (16, 8), 4, "float32", old, new, 1 << 30)
    assert [s.kind for s in move.steps] == ["allgather"]
    assert move.peak_scratch_bytes == 2 * 16 * 8 * 4 // 2  # model kept


def test_plan_chunks_to_meet_peak_bytes():
    old = ShardingPlan(mesh=mesh8(),
                       arrays={"w": ArraySpec((4, 1), ("data", None))})
    new = ShardingPlan(mesh=mesh8(), arrays={})
    full = 2 * 64 * 16 * 4  # both-sides scratch of the unchunked move
    move = plan_move("w", (64, 16), 4, "float32", old, new, full // 4)
    assert move.rounds >= 4 and move.chunk_dim is not None
    assert move.peak_scratch_bytes <= full // 4
    assert not move.infeasible_peak
    # chunk extents stay divisible by the old degree on the chunk dim
    if move.chunk_dim == 0:
        assert (64 // move.rounds) % 4 == 0


def test_plan_infeasible_peak_flags_move():
    old = ShardingPlan(mesh=mesh8(),
                       arrays={"w": ArraySpec((4, 1), ("data", None))})
    new = ShardingPlan(mesh=mesh8(), arrays={})
    move = plan_move("w", (8, 4), 4, "float32", old, new, peak_bytes=8)
    assert move.infeasible_peak
    diags = redistribution_diagnostics(
        plan_redistribution({"w": np.zeros((8, 4), np.float32)},
                            old, new, peak_bytes=8), machine())
    assert any(d.code == "FFTA061" for d in diags)


def test_plan_rejects_indivisible_degree():
    old = ShardingPlan(mesh=mesh8(), arrays={})
    new = ShardingPlan(mesh=mesh8(),
                       arrays={"w": ArraySpec((4, 1), ("data", None))})
    with pytest.raises(ReshardPlanError, match="does not divide"):
        plan_move("w", (10, 4), 4, "float32", old, new, 1 << 30)


# ---------------------------------------------------------------------
# FFTA06x gate
# ---------------------------------------------------------------------
def test_gate_ffta060_unknown_axis_and_degree_mismatch():
    old = ShardingPlan(mesh=mesh8(), arrays={})
    # target mesh has no 'expert' axis, and 'data' has size 4, not 2
    new = ShardingPlan(
        mesh=mesh8(),
        arrays={"a": ArraySpec((8, 1), ("expert", None)),
                "b": ArraySpec((2, 1), ("data", None))})
    tree = {"a": np.zeros((8, 4), np.float32),
            "b": np.zeros((8, 4), np.float32)}
    sched = plan_redistribution(tree, old, new, peak_bytes=1 << 30)
    diags = redistribution_diagnostics(sched, machine())
    codes = sorted(d.code for d in diags)
    assert codes.count("FFTA060") == 2
    with pytest.raises(PlanAnalysisError, match="FFTA060"):
        check_redistribution(sched, machine=machine(), record=False)


def test_gate_ffta061_and_062_memory_fit():
    tiny = machine(hbm_gb=1e-6)  # 1 KB chip
    old = ShardingPlan(mesh=mesh8(), arrays={})
    new = ShardingPlan(mesh=mesh8(),
                       arrays={"w": ArraySpec((4, 1), ("data", None))})
    sched = plan_redistribution({"w": np.zeros((64, 16), np.float32)},
                                old, new, peak_bytes=1 << 30)
    assert any(d.code == "FFTA061"
               for d in redistribution_diagnostics(sched, tiny))
    # just under the cap but over 85%: warning, not error
    near = machine(hbm_gb=2 * 64 * 16 * 4 * 1.1 / 1e9)
    diags = redistribution_diagnostics(sched, near)
    assert [d.code for d in diags] == ["FFTA062"]
    check_redistribution(sched, machine=near, record=False)  # no raise


def test_gate_passes_clean_schedule():
    old = ShardingPlan(mesh=mesh8(),
                       arrays={"w": ArraySpec((4, 1), ("data", None))})
    new = ShardingPlan(mesh=mesh8(), arrays={})
    sched = plan_redistribution({"w": np.zeros((16, 8), np.float32)},
                                old, new, peak_bytes=1 << 30)
    report = check_redistribution(sched, machine=machine(), record=False)
    assert report.ok and report.passes_run == ["redistribution", "flow"]
    assert schedule_cost_us(sched, machine()) > 0


# ---------------------------------------------------------------------
# survivor coverage (FFTA063)
# ---------------------------------------------------------------------
def test_coverage_replicated_survives_any_loss():
    plan = ShardingPlan(mesh=mesh8(), arrays={})
    assert uncovered_arrays(plan, {"w": 2}, [6, 7]) == []


def test_coverage_sharded_dim_loses_unique_shards():
    # 'w' shards over data (4 groups of 2 devices); losing BOTH devices
    # of one data coordinate loses that shard
    plan = ShardingPlan(mesh=mesh8(),
                        arrays={"w": ArraySpec((4, 1), ("data", None))})
    # mesh grid is (data=4, model=2) row-major: positions 6,7 = data=3
    assert uncovered_arrays(plan, {"w": 2}, [6, 7]) == [("w", 1)]
    # losing one device of the pair keeps the shard covered
    assert uncovered_arrays(plan, {"w": 2}, [7]) == []
    diags = survivor_diagnostics(plan, {"w": 2}, [6, 7])
    assert [d.code for d in diags] == ["FFTA063"]


def test_coverage_meshless_plan():
    plan = ShardingPlan(mesh=MeshSpec(device_ids=(3,)), arrays={})
    assert uncovered_arrays(plan, {"w": 1}, [0]) == [("w", 1)]
    assert uncovered_arrays(plan, {"w": 1}, []) == []


# ---------------------------------------------------------------------
# executor: bit-exactness vs the checkpoint reference + the peak bound
# ---------------------------------------------------------------------
class _TreeModel:
    """The minimal model surface runtime/checkpoint.py needs."""

    def __init__(self, params=None, opt_state=None, state=None):
        self.params = params or {}
        self.opt_state = opt_state or {}
        self.state = state or {}
        self._step_count = 0


def _reference_reshard(tree, new_plan):
    """The path redistribute replaces: checkpoint-save the tree to disk,
    restore it (host round-trip), then device_put every leaf per the new
    plan — exactly what ElasticCoordinator's disk restore +
    reshard_params does."""
    import jax

    from flexflow_tpu.runtime.checkpoint import (restore_checkpoint,
                                                 save_checkpoint)
    from flexflow_tpu.resharding.executor import _target_sharding

    src = _TreeModel(**{k: tree.get(k, {}) for k in
                        ("params", "opt_state", "state")})
    out = _TreeModel()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ref.npz")
        save_checkpoint(path, src, step=0)
        restore_checkpoint(path, out)
    restored = {"params": out.params, "opt_state": out.opt_state,
                "state": out.state}
    placed = {}
    for path_, leaf in flatten_tree(restored).items():
        spec = new_plan.spec_for(path_, np.ndim(leaf))
        placed[path_] = jax.device_put(
            leaf, _target_sharding(new_plan.mesh, spec))
    return placed


def _bytes_view(arr):
    a = np.asarray(arr)
    if a.dtype.kind not in "iuf":
        a = a.view(np.uint16) if a.itemsize == 2 else a
    return a


def _random_case(rng, case):
    """One random (tree, old_plan, new_plan) over the 8-device mesh."""
    import jax.numpy as jnp
    import ml_dtypes

    axes_pool = [(), (("data", 4), ("model", 2)), (("data", 2),),
                 (("model", 2), ("data", 2))]
    old_axes = axes_pool[rng.randint(len(axes_pool))]
    new_axes = axes_pool[rng.randint(len(axes_pool))]
    n_old = int(np.prod([s for _, s in old_axes])) if old_axes else 1
    n_new = int(np.prod([s for _, s in new_axes])) if new_axes else 1
    old_mesh = MeshSpec(device_ids=tuple(range(8))[:max(n_old, 1)]
                        if old_axes else (0,), axes=old_axes)
    new_mesh = MeshSpec(device_ids=tuple(range(8))[:max(n_new, 1)]
                        if new_axes else (int(rng.randint(8)),),
                        axes=new_axes)

    def rand_spec(shape, axes):
        degrees, names = [], []
        free = dict(axes)
        for size in shape:
            picked = None
            for name, deg in list(free.items()):
                if rng.rand() < 0.4 and size % deg == 0:
                    picked = (deg, name)
                    del free[name]
                    break
            degrees.append(picked[0] if picked else 1)
            names.append(picked[1] if picked else None)
        return ArraySpec(tuple(degrees), tuple(names))

    shapes = {
        "params/op/w": (16, 8),
        "params/op/b": (8,),
        "opt_state/v/op/w": (16, 8),
        "state/scalar": (),
    }
    old_arrays, new_arrays = {}, {}
    tree_flat = {}
    for i, (path, shape) in enumerate(shapes.items()):
        if shape:
            old_arrays[path] = rand_spec(shape, old_axes)
            new_arrays[path] = rand_spec(shape, new_axes)
        dt = ml_dtypes.bfloat16 if (case + i) % 3 == 0 else np.float32
        val = rng.randn(*shape).astype(dt) if shape \
            else np.float32(rng.randn())
        tree_flat[path] = jnp.asarray(val)
    old_plan = ShardingPlan(mesh=old_mesh, arrays=old_arrays)
    new_plan = ShardingPlan(mesh=new_mesh, arrays=new_arrays)
    # commit the tree to the OLD layout (live state is sharded, not host)
    import jax

    from flexflow_tpu.resharding.executor import _target_sharding

    for path, leaf in tree_flat.items():
        spec = old_plan.spec_for(path, np.ndim(leaf))
        tree_flat[path] = jax.device_put(
            leaf, _target_sharding(old_mesh, spec))
    from flexflow_tpu.resharding import unflatten_tree

    return unflatten_tree(tree_flat), old_plan, new_plan


def test_redistribute_matches_checkpoint_reference_property():
    """Property test over random (old_plan, new_plan) pairs: bit-exact
    equality with the save -> reshard-restore reference, target
    shardings honored, and instrumented peak scratch within the bound."""
    rng = np.random.RandomState(0)
    peak = 4096  # small enough to force chunking on the (16, 8) arrays
    for case in range(12):
        tree, old_plan, new_plan = _random_case(rng, case)
        result = redistribute(tree, old_plan, new_plan, peak_bytes=peak,
                              machine=machine())
        assert result.observed_peak_bytes <= peak, \
            (case, result.observed_peak_bytes, result.schedule.summary())
        ref = _reference_reshard(tree, new_plan)
        got = flatten_tree(result.tree)
        assert set(got) == set(ref), case
        for path in ref:
            a, b = _bytes_view(got[path]), _bytes_view(ref[path])
            assert a.dtype == b.dtype and np.array_equal(a, b), \
                (case, path)
            assert got[path].sharding.is_equivalent_to(
                ref[path].sharding, np.ndim(got[path])), (case, path)


def test_redistribute_same_mesh_gather_uses_collective_kernel():
    """A same-mesh pure gather lowers through the explicit shard_map
    all-gather (kernels/redistribute.py) and stays bit-exact."""
    import jax
    import jax.numpy as jnp

    old_plan = ShardingPlan(
        mesh=mesh8(), arrays={"w": ArraySpec((4, 2), ("data", "model"))})
    new_plan = ShardingPlan(mesh=mesh8(), arrays={})
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8)
                    .astype(np.float32))
    from flexflow_tpu.resharding.executor import _target_sharding

    x = jax.device_put(
        x, _target_sharding(old_plan.mesh,
                            old_plan.arrays["w"]))
    result = redistribute({"w": x}, old_plan, new_plan,
                          peak_bytes=1 << 30, machine=machine())
    assert result.allgather_rounds >= 1
    assert np.array_equal(np.asarray(result.tree["w"]), np.asarray(x))


def test_verify_live_tree_catches_nonfinite():
    import jax.numpy as jnp

    clean = {"a": jnp.ones((4,)), "b": {"c": jnp.zeros((2, 2))}}
    assert verify_live_tree(clean) is None
    bad = {"a": jnp.ones((4,)),
           "b": {"c": jnp.asarray([1.0, float("nan")])}}
    reason = verify_live_tree(bad)
    assert reason is not None and "b/c" in reason
    # integer leaves are not a corruption signal
    assert verify_live_tree({"i": jnp.zeros((3,), jnp.int32)}) is None


def test_slot_migration_schedule_prices_and_gates():
    kv_shapes = {"kv/l0/k": ((4, 64, 4, 8), 4),
                 "kv/l0/v": ((4, 64, 4, 8), 4)}
    sched = plan_slot_migration(kv_shapes, 4, 2, migrated_rows=96)
    assert len(sched.moves) == 2
    assert all(s.kind == "transfer"
               for m in sched.moves for s in m.steps)
    # scratch is the WHOLE transient footprint: the resize executor
    # materializes every new array while every old one is still live
    old_bytes = 2 * (4 * 64 * 4 * 8 * 4)
    new_bytes = 2 * (2 * 64 * 4 * 8 * 4)
    assert sched.moves[0].peak_scratch_bytes == old_bytes + new_bytes
    assert sched.peak_scratch_bytes == old_bytes + new_bytes
    check_redistribution(sched, machine=machine(), record=False)
    assert schedule_cost_us(sched, machine()) > 0
    from flexflow_tpu.search.simulator import reshard_cost_us

    assert reshard_cost_us(sched, machine()) \
        == schedule_cost_us(sched, machine())


# ---------------------------------------------------------------------
# elastic coordinator: zero-disk recovery + fallbacks
# ---------------------------------------------------------------------
def _builder(cfg):
    m = ff.FFModel(cfg)
    t = m.create_tensor([cfg.batch_size, 16])
    t = m.dense(t, 32, ff.ActiMode.AC_MODE_RELU)
    t = m.dense(t, 4)
    m.softmax(t)
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[])
    return m


def _coord_config(devices=4, batch=12):
    cfg = ff.FFConfig()
    cfg.batch_size = batch
    cfg.seed = 0
    cfg.search_budget = 8
    cfg.measure_op_costs = False
    cfg.device_ids = list(range(devices))
    return cfg


def _coord_data(batch=12):
    rng = np.random.RandomState(0)
    x = rng.randn(batch * 4, 16).astype(np.float32)
    y = rng.randint(0, 4, size=(batch * 4, 1)).astype(np.int32)
    return x, y


def _restore_counts():
    from flexflow_tpu.obs.registry import REGISTRY

    c = REGISTRY.counter("ff_recovery_restore_total", "",
                         labels=("source",))
    return (int(c.value(source="live")), int(c.value(source="disk")))


def test_live_recovery_zero_disk_and_resume_at_failing_step(tmp_path):
    from flexflow_tpu.elastic import ElasticCoordinator, EventLog, FaultPlan
    from flexflow_tpu.runtime.durability import checkpoint_counters

    events = EventLog()
    plan = FaultPlan.kill_chips(at_step=3, chips=[3])
    x, y = _coord_data()
    coord = ElasticCoordinator(_builder, _coord_config(), fault_plan=plan,
                               events=events, checkpoint_dir=str(tmp_path),
                               checkpoint_every=2)
    history = coord.fit(x, y, steps=6)
    live, disk = _restore_counts()
    assert (live, disk) == (1, 0)
    # zero checkpoint-FILE reads: nothing was restored or even verified
    counts = checkpoint_counters()
    assert counts.get("restored", 0) == 0
    assert counts.get("verified", 0) == 0
    # resumed at the failing step — no replay of committed steps
    restores = events.events("recovery.restore")
    assert len(restores) == 1
    assert restores[0].step == 3
    assert restores[0].details["source"] == "live"
    assert restores[0].details["restore_ms"] > 0
    assert [h["step"] for h in history] == list(range(6))
    assert all(np.isfinite(h["loss"]) for h in history)
    assert coord.device_ids == [0, 1, 2]


def test_poisoned_live_state_falls_back_to_disk(tmp_path):
    from flexflow_tpu.elastic import ElasticCoordinator, EventLog, FaultPlan

    events = EventLog()
    # both faults fire in the SAME dispatch: poison (non-raising) first,
    # then the kill — the rot exists when recovery verifies the tree
    plan = (FaultPlan()
            .add_poison_live(4)
            .add_chip_loss(4, chips=[3]))
    x, y = _coord_data()
    coord = ElasticCoordinator(_builder, _coord_config(), fault_plan=plan,
                               events=events, checkpoint_dir=str(tmp_path),
                               checkpoint_every=2)
    history = coord.fit(x, y, steps=8)
    live, disk = _restore_counts()
    assert (live, disk) == (0, 1)
    fallbacks = events.events("recovery.live_fallback")
    assert len(fallbacks) == 1
    assert fallbacks[0].details["reason"] == "verify"
    restores = events.events("recovery.restore")
    assert restores[0].details["source"] == "disk"
    # disk path resumes from the newest checkpoint (step 4) and replays
    assert restores[0].step == 4
    assert [h["step"] for h in history] == list(range(8))
    assert all(np.isfinite(h["loss"]) for h in history)


def test_live_resharding_off_uses_disk(tmp_path):
    from flexflow_tpu.elastic import ElasticCoordinator, EventLog, FaultPlan

    events = EventLog()
    plan = FaultPlan.kill_chips(at_step=3, chips=[3])
    x, y = _coord_data()
    coord = ElasticCoordinator(_builder, _coord_config(), fault_plan=plan,
                               events=events, checkpoint_dir=str(tmp_path),
                               checkpoint_every=2, live_resharding=False)
    coord.fit(x, y, steps=6)
    live, disk = _restore_counts()
    assert (live, disk) == (0, 1)
    assert not events.events("recovery.live_fallback")
