"""PCG inspector (tools/pcg_inspect.py — the reference's gdb/pretty_print.py
role: its state needs a debugger, ours needs one call)."""
import os
import sys

import flexflow_tpu as ff

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tools.pcg_inspect import dump_graph, dump_model  # noqa: E402


def test_dump_model_tp_and_pipeline():
    from flexflow_tpu.models import TransformerConfig, build_bert_encoder

    config = ff.FFConfig()
    config.num_devices = 8
    config.batch_size = 8
    config.pipeline_microbatches = 4
    m = ff.FFModel(config)
    tok = m.create_tensor([8, 16], ff.DataType.DT_INT32)
    build_bert_encoder(m, tok, TransformerConfig(
        hidden_size=32, embedding_size=32, num_heads=4, num_layers=2,
        sequence_length=16, vocab_size=50))
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.1),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[], parallel_axes={"data": 2, "stage": 2})
    text = dump_model(m)
    assert "mesh axes: {'data': 2, 'stage': 2}" in text
    assert "pipeline: 2 stages" in text
    assert "tok_emb" in text and "layer1_attn" in text


def test_dump_graph_with_strategies():
    from flexflow_tpu.core.graph import Graph
    from flexflow_tpu.search.simulator import OpStrategy

    config = ff.FFConfig()
    config.batch_size = 4
    m = ff.FFModel(config)
    t = m.create_tensor([4, 8], ff.DataType.DT_FLOAT)
    m.softmax(m.dense(t, 6, name="lin"))
    g = Graph(m.ops)
    strategies = {op.guid: OpStrategy(dp=2, tp=2) for op in g.ops.values()}
    text = dump_graph(g, strategies)
    assert "dp=2 tp=2" in text and "lin" in text
