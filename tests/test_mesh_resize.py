"""Serving mesh resize (ISSUE 8): the pool's owned_view/resize page-table
rewrite and the ContinuousBatcher's live migration path.

The decisive properties:
 - only rows the page tables still OWN are ever copied — freed pages'
   stale contents (live in the device arrays until reallocation) can
   never ship into the new arrays;
 - a shrink defers until live sequences fit (nothing is dropped), a grow
   applies immediately;
 - in-flight requests decode token-identically across a resize.
"""
import threading
import time

import numpy as np
import pytest

from flexflow_tpu.serving.sched import (ContinuousBatcher, PagedKVPool,
                                        PoolExhausted)
from tests.conftest import module_xla_cache
from tests.test_generate import _build_lm

# module-scoped XLA compilation cache — see conftest.module_xla_cache
_xla_cache = pytest.fixture(scope="module", autouse=True)(module_xla_cache)


@pytest.fixture(scope="module")
def lm():
    return _build_lm(2, 12)


def _prompts(lens, seed=0, vocab=50):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=(n,)).astype(np.int32)
            for n in lens]


# ---------------------------------------------------------------------
# PagedKVPool.owned_view
# ---------------------------------------------------------------------
def test_owned_view_spans_follow_page_table():
    pool = PagedKVPool(num_slots=2, max_len=16, page_size=4)
    slot = pool.alloc("a", 6)  # 2 pages -> rows [0, 8)
    assert pool.owned_view("a") == [(slot, 0, 8)]
    pool.extend("a", 3)  # 9 tokens -> 3 pages -> rows [0, 12)
    assert pool.owned_view("a") == [(slot, 0, 12)]
    # freed: nothing is owned, even though the device rows still hold KV
    pool.free("a")
    assert pool.owned_view("a") == []
    assert pool.owned_view("never-allocated") == []


def test_owned_view_clamps_partial_tail_page():
    pool = PagedKVPool(num_slots=1, max_len=10, page_size=4)
    slot = pool.alloc("a", 10)  # 3 pages, last page covers rows 8..9
    assert pool.owned_view("a") == [(slot, 0, 10)]


# ---------------------------------------------------------------------
# PagedKVPool.resize
# ---------------------------------------------------------------------
def test_resize_rewrites_tables_and_freelist():
    pool = PagedKVPool(num_slots=4, max_len=16, page_size=4)
    s_a = pool.alloc("a", 5)   # slot 0
    s_b = pool.alloc("b", 3)   # slot 1
    assert (s_a, s_b) == (0, 1)
    moves = pool.resize(2)
    assert moves == [("a", 0, 0, 2), ("b", 1, 1, 1)]
    assert pool.num_slots == 2 and pool.total_pages == 2 * 4
    assert pool.free_slot_count() == 0
    with pytest.raises(PoolExhausted):
        pool.alloc("c", 1)
    # grow back: slots keep their indices, new capacity frees up
    moves = pool.resize(4)
    assert moves == [("a", 0, 0, 2), ("b", 1, 1, 1)]
    assert pool.free_slot_count() == 2
    assert pool.alloc("c", 1) in (2, 3)


def test_resize_relocates_out_of_range_slots():
    pool = PagedKVPool(num_slots=4, max_len=16, page_size=4)
    for sid in ("a", "b", "c", "d"):
        pool.alloc(sid, 5)
    pool.free("a")  # slot 0 free
    pool.free("b")  # slot 1 free
    moves = pool.resize(2)
    # c (slot 2) and d (slot 3) move into the surviving slots 0 and 1
    assert sorted(m[2] for m in moves) == [0, 1]
    for sid, old_slot, new_slot, n_pages in moves:
        assert pool.slot_of(sid) == new_slot
        assert pool.pages_of(sid) == [new_slot * pool.pages_per_slot + b
                                      for b in range(n_pages)]
        assert pool.owned_view(sid) == [(new_slot, 0, 8)]


def test_resize_refuses_when_live_exceeds_target():
    pool = PagedKVPool(num_slots=3, max_len=16, page_size=4)
    for sid in ("a", "b", "c"):
        pool.alloc(sid, 4)
    with pytest.raises(PoolExhausted, match="drain first"):
        pool.resize(2)
    # state untouched by the refusal
    assert pool.num_slots == 3 and pool.live_sequences() == 3


# ---------------------------------------------------------------------
# batcher migration
# ---------------------------------------------------------------------
def test_resize_mid_decode_token_parity_and_zero_drops(lm):
    """Shrink then grow while requests decode; every request's greedy
    tokens must match a no-resize reference run, with zero drops."""
    prompts = _prompts([6, 5, 7, 6, 5, 6])
    # staggered outputs: the two long requests are still decoding when
    # the short ones retire, so BOTH resizes migrate live sequences
    n_new = [40, 40, 16, 16, 12, 12]

    def run(resize):
        b = ContinuousBatcher(lm, max_len=48, num_slots=4, page_size=4,
                              max_queue=16)
        with b:
            handles = [b.submit(p, n) for p, n in zip(prompts, n_new)]
            if resize:
                deadline = time.monotonic() + 120
                while not any(h.tokens for h in handles):
                    assert time.monotonic() < deadline
                    time.sleep(0.005)
                shrink = b.request_resize(2).wait(timeout=300)
                grow = b.request_resize(4).wait(timeout=300)
                assert shrink["to"] == 2 and grow["to"] == 4
                assert shrink["migrated_rows"] > 0
                assert grow["migrated_rows"] > 0
            toks = [h.result(timeout=300).tolist() for h in handles]
            assert all(h.error is None for h in handles)
        return toks, b

    ref_toks, _ = run(resize=False)
    toks, b = run(resize=True)
    assert toks == ref_toks
    assert [r["direction"] for r in b.stats()["resizes"]] \
        == ["shrink", "grow"]
    assert b.num_slots == 4 and b.pool.num_slots == 4


def _nonzero_slots(batcher):
    """Slot indices holding any nonzero KV in the (drained) batcher's
    cache arrays. Only safe AFTER the scheduler thread has exited — the
    live loop donates the caches every iteration."""
    import jax.numpy as jnp

    hot = set()
    for pair in batcher._caches.values():
        for arr in pair.values():
            # row 0 excluded: every decode iteration writes a dummy row-0
            # entry into INACTIVE slots (their outputs are discarded), so
            # only rows >= 1 distinguish real sequence KV
            sums = jnp.sum(jnp.abs(arr[:, 1:].astype(jnp.float32)),
                           axis=tuple(range(1, arr.ndim)))
            hot |= {int(s) for s in np.nonzero(np.asarray(sums))[0]}
    return hot


def test_resize_never_copies_stale_pages(lm):
    """Regression for the stale-page hazard: a finished request's rows
    stay live in the device arrays, but its pages are no longer owned —
    a resize must migrate ONLY owned rows (`owned_view`), so the
    finished sequence's KV must NOT appear in the new arrays."""
    def run(resize):
        b = ContinuousBatcher(lm, max_len=48, num_slots=3, page_size=4,
                              max_queue=8)
        with b:
            # submitted together so they land in DISTINCT slots; the
            # short one finishes first, leaving its pages freed but its
            # rows live (stale) in the device arrays while the long one
            # keeps decoding
            short = b.submit(_prompts([6], seed=1)[0], 2)
            long_req = b.submit(_prompts([6], seed=2)[0], 30)
            short.result(timeout=300)
            deadline = time.monotonic() + 120
            while not long_req.tokens:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            if resize:
                res = b.request_resize(2).wait(timeout=300)
                assert res["in_flight"] == 1
                assert res["migrated_rows"] > 0
            long_req.result(timeout=300)
            assert long_req.error is None
        return b

    # without a resize the freed slot's rows are genuinely stale-but-
    # live: the finished short request's slot AND the long one are hot
    b_ref = run(resize=False)
    assert len(_nonzero_slots(b_ref)) == 2
    # across a resize only the live sequence's owned rows shipped: the
    # stale slot's KV is gone from the new arrays
    b_res = run(resize=True)
    assert b_res.num_slots == 2
    assert len(_nonzero_slots(b_res)) == 1


def test_shrink_defers_until_live_fits_and_holds_admissions(lm):
    b = ContinuousBatcher(lm, max_len=48, num_slots=3, page_size=4,
                          max_queue=8)
    with b:
        a = b.submit(_prompts([5], seed=3)[0], 40)
        c = b.submit(_prompts([5], seed=4)[0], 40)
        deadline = time.monotonic() + 120
        while not (a.tokens and c.tokens):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        ticket = b.request_resize(1)
        # two live sequences > target 1: the resize must stay deferred
        # WHILE both are live, and decoding must continue (nothing
        # dropped, no deadlock). Asserted as the invariant — deferral
        # observed only while both requests are provably unfinished —
        # not as a fixed sleep: on a warm box both 40-token budgets can
        # drain in well under any fixed sleep, and the shrink then
        # legitimately applies (the old time.sleep(0.15) form was
        # flaky for exactly that reason).
        d = None
        while not (a.done() or c.done()):
            if ticket.done() or b.num_slots != 3:
                # the apply raced the done-reads above; a retire
                # strictly precedes any apply, so re-reading done()
                # must now show it
                assert a.done() or c.done()
                break
            if d is None:
                # a request queued during the pending shrink is NOT
                # admitted to a slot (admissions are held; the ticket
                # completes strictly before any admission resumes, so
                # this read is race-free)
                d = b.submit(_prompts([5], seed=5)[0], 2)
            elif not ticket.done():
                assert not d.tokens
            assert time.monotonic() < deadline
            time.sleep(0.005)
        if d is None:
            d = b.submit(_prompts([5], seed=5)[0], 2)
        # both decoders finish -> the shrink applies -> d admits after
        a.result(timeout=300)
        c.result(timeout=300)
        res = ticket.wait(timeout=300)
        assert res["to"] == 1 and b.num_slots == 1
        assert d.result(timeout=300).size == 2


def test_concurrent_admissions_during_deferred_shrink_queue_not_429(lm):
    """Regression (ISSUE 12 satellite): while a shrink DEFERS (live >
    target), concurrent submits must be ADMITTED and held queued — the
    admission gate only meters queue count and backlog pages, so a
    pending resize must surface as waiting, never as a 429 — and every
    held request must run once capacity returns."""
    from flexflow_tpu.serving.sched import AdmissionError

    b = ContinuousBatcher(lm, max_len=96, num_slots=3, page_size=4,
                          max_queue=16)
    with b:
        # long enough that the deferred window is seconds wide — the
        # mid-shrink asserts below must run while both are still live
        long_a = b.submit(_prompts([5], seed=20)[0], 80)
        long_b = b.submit(_prompts([5], seed=21)[0], 80)
        deadline = time.monotonic() + 120
        while not (long_a.tokens and long_b.tokens):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        ticket = b.request_resize(1)  # defers: 2 live > 1
        errors = []
        held = [None] * 4

        def _submit(i):
            try:
                held[i] = b.submit(_prompts([4], seed=30 + i)[0], 2)
            except AdmissionError as e:
                errors.append(e)

        threads = [threading.Thread(target=_submit, args=(i,))
                   for i in range(len(held))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # all admitted — zero 429s — but none scheduled while the shrink
        # is pending (admissions are held, not rejected)
        assert not errors
        time.sleep(0.2)  # a buggy scheduler would run them right away
        assert not ticket.done()
        assert all(not h.tokens for h in held)
        assert b.admission.queue_depth() == len(held)
        assert b.queued_prefill_tokens() == sum(4 for _ in held)
        # the decoders finish -> shrink applies -> the held queue drains
        long_a.result(timeout=300)
        long_b.result(timeout=300)
        assert ticket.wait(timeout=300)["to"] == 1
        for h in held:
            assert h.result(timeout=300).size == 2
        assert all(h.error is None for h in held)


def test_resize_rejected_while_pending_and_after_stop(lm):
    from flexflow_tpu.serving import BatcherStopped

    b = ContinuousBatcher(lm, max_len=48, num_slots=2, page_size=4,
                          max_queue=4)
    with b:
        r = b.submit(_prompts([5], seed=6)[0], 40)
        deadline = time.monotonic() + 120
        while not r.tokens:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        # target 1 < live 1? live == 1 fits -> use a second live request
        r2 = b.submit(_prompts([5], seed=7)[0], 40)
        while not r2.tokens:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        ticket = b.request_resize(1)  # defers: 2 live > 1
        with pytest.raises(RuntimeError, match="already pending"):
            b.request_resize(2)
        r.result(timeout=300)
        r2.result(timeout=300)
        ticket.wait(timeout=300)
    with pytest.raises(BatcherStopped):
        b.request_resize(2)


def test_resize_applies_while_idle(lm):
    b = ContinuousBatcher(lm, max_len=48, num_slots=2, page_size=4,
                          max_queue=4)
    with b:
        res = b.request_resize(4).wait(timeout=300)
        assert res["to"] == 4 and res["migrated_rows"] == 0
        out = b.submit(_prompts([5], seed=8)[0], 3).result(timeout=300)
        assert out.size == 3
