"""Native prefetching batch loader (src/ffcore/dataloader.cc via
flexflow_tpu.native.BatchStream) — reference parity for the C++
SingleDataLoader (src/dataloader/dataloader.cc): batch tiling, per-epoch
shuffling, reset, and the SingleDataLoader integration."""
import numpy as np
import pytest

from flexflow_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="libffcore not built")


def make_data(n=32, f=5):
    return (np.arange(n * f, dtype=np.float32).reshape(n, f) + 1.0)


def test_sequential_batches_match_slices():
    data = make_data()
    bs = 8
    s = native.BatchStream(data, bs, shuffle=False)
    try:
        for epoch in range(3):
            for i in range(s.num_batches):
                np.testing.assert_array_equal(
                    s.next_batch(), data[i * bs:(i + 1) * bs])
    finally:
        s.close()


def test_shuffled_epoch_is_permutation_and_deterministic():
    data = make_data(n=24)
    bs = 6
    def epoch_rows(stream):
        rows = []
        for _ in range(stream.num_batches):
            rows.extend(stream.next_batch()[:, 0].tolist())
        return rows

    s1 = native.BatchStream(data, bs, shuffle=True, seed=7)
    s2 = native.BatchStream(data, bs, shuffle=True, seed=7)
    s3 = native.BatchStream(data, bs, shuffle=True, seed=8)
    try:
        e0 = epoch_rows(s1)
        assert sorted(e0) == sorted(data[:, 0].tolist())  # a permutation
        assert e0 != data[:, 0].tolist()  # actually shuffled (n=24: ~certain)
        assert epoch_rows(s2) == e0  # deterministic per seed
        assert epoch_rows(s3) != e0  # seed-sensitive
        e1 = epoch_rows(s1)
        assert e1 != e0 and sorted(e1) == sorted(e0)  # reshuffles per epoch
    finally:
        s1.close(); s2.close(); s3.close()


def test_reset_restarts_epoch_zero():
    data = make_data(n=16)
    s = native.BatchStream(data, 4, shuffle=True, seed=3)
    try:
        first = s.next_batch().copy()
        s.next_batch()
        s.reset()
        np.testing.assert_array_equal(s.next_batch(), first)
        assert s.epoch == 0
    finally:
        s.close()


def test_buffer_stable_until_next_call():
    """The handed-out buffer must not be overwritten by the prefetching
    producer before the consumer's NEXT call (the ring keeps a one-slot
    margin), even when the consumer is slow."""
    import time

    data = make_data(n=64, f=3)
    s = native.BatchStream(data, 4, shuffle=False, prefetch_depth=3)
    try:
        for i in range(s.num_batches):
            b = s.next_batch()
            expect = data[i * 4:(i + 1) * 4]
            np.testing.assert_array_equal(b, expect)
            if i < 3:
                time.sleep(0.02)  # let the producer run ahead
                np.testing.assert_array_equal(b, expect)  # still intact
    finally:
        s.close()


def test_single_dataloader_native_backend():
    import flexflow_tpu as ff

    config = ff.FFConfig()
    config.batch_size = 8
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    x = model.create_tensor([8, 5])
    t = model.dense(x, 4)
    model.softmax(t)
    data = make_data(n=32)
    loader = ff.SingleDataLoader(model, x, data)
    assert loader.backend == "native"
    np.testing.assert_array_equal(loader.next_batch(), data[:8])
    np.testing.assert_array_equal(loader.next_batch(), data[8:16])
    loader.reset()
    np.testing.assert_array_equal(loader.next_batch(), data[:8])


def test_single_dataloader_numpy_fallback_matches():
    import flexflow_tpu as ff

    config = ff.FFConfig()
    config.batch_size = 8
    model = ff.FFModel(config)
    x = model.create_tensor([8, 5])
    model.softmax(model.dense(x, 4))
    data = make_data(n=32)
    nat = ff.SingleDataLoader(model, x, data, prefetch=True)
    py = ff.SingleDataLoader(model, x, data, prefetch=False)
    assert py.backend == "numpy"
    for _ in range(2 * nat.num_batches):  # across an epoch wrap
        np.testing.assert_array_equal(nat.next_batch(), py.next_batch())


def test_fit_consumes_loaders_via_next_batch(monkeypatch):
    """fit() without x/y must pull batches through next_batch() (prefetch
    ring + shuffle honored), not read loader.data directly."""
    import flexflow_tpu as ff
    from flexflow_tpu.runtime.dataloader import SingleDataLoader

    calls = {"n": 0}
    orig = SingleDataLoader.next_batch

    def counting(self, ffmodel=None):
        calls["n"] += 1
        return orig(self, ffmodel)

    monkeypatch.setattr(SingleDataLoader, "next_batch", counting)

    config = ff.FFConfig()
    config.batch_size = 8
    config.epochs = 1
    model = ff.FFModel(config)
    x = model.create_tensor([8, 5])
    t = model.dense(x, 4)
    model.softmax(t)
    model.compile(loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    data = make_data(n=32).astype(np.float32)
    labels = np.random.RandomState(0).randint(0, 4, size=(32, 1)).astype(np.int32)
    ff.SingleDataLoader(model, x, data, shuffle=True, seed=7)
    ff.SingleDataLoader(model, model.label_tensor, labels, shuffle=True, seed=7)
    model.fit()
    # 4 batches per epoch, x and label loaders each pulled once per batch
    assert calls["n"] == 2 * (32 // 8)


def test_fit_shuffled_loaders_stay_aligned():
    """Loaders sharing a seed shuffle in lockstep: training on a learnable
    identity mapping with shuffle=True still converges (x/y not decorrelated)."""
    import flexflow_tpu as ff

    rs = np.random.RandomState(3)
    n, f = 64, 4
    data = rs.randn(n, f).astype(np.float32)
    labels = np.argmax(data, axis=1).astype(np.int32).reshape(n, 1)

    config = ff.FFConfig()
    config.batch_size = 8
    config.epochs = 30
    config.learning_rate = 0.5
    model = ff.FFModel(config)
    x = model.create_tensor([8, f])
    model.softmax(model.dense(x, f))
    model.compile(loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[ff.MetricsType.METRICS_ACCURACY])
    ff.SingleDataLoader(model, x, data, shuffle=True, seed=11)
    ff.SingleDataLoader(model, model.label_tensor, labels, shuffle=True, seed=11)
    hist = model.fit()
    assert hist[-1]["accuracy"] > 0.9, hist[-1]
