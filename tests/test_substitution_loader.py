"""Substitution rule-file loader (reference analog:
tests/unit/test_substitution_loader.cc + the --substitution-json path)."""
import json
import os

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.ffconst import OpType
from flexflow_tpu.search.substitution_loader import (
    load_substitution_file,
    summarize,
    tp_candidates_from_rules,
)

RULES_PATH = os.path.join(os.path.dirname(__file__), "..", "substitutions",
                          "tp_rules.json")
# vendored conversion of the reference's public OSDI rule data
# (tools/protobuf_to_json.py output, committed so the suite is
# self-contained); the reference's own copy is a skippable cross-check
VENDORED_RULES = os.path.join(os.path.dirname(__file__), "..",
                              "substitutions", "graph_subst_3_v2.json")
REFERENCE_RULES = "/root/reference/substitutions/graph_subst_3_v2.json"


def test_load_shipped_rules():
    rules = load_substitution_file(RULES_PATH)
    assert len(rules) == 5
    s = summarize(rules)
    assert s["supported"] == 5 and s["unsupported"] == 0
    byname = {r.name: r for r in rules}
    lin = byname["partition_linear_combine_d2"]
    assert lin.src_ops[0].op_type == OpType.LINEAR
    assert lin.dst_ops[0].op_type == OpType.REPLICATE
    assert lin.dst_ops[0].parallel_degree == 2
    assert lin.dst_ops[2].op_type == OpType.COMBINE
    assert lin.mapped_outputs[0].dst_op_id == 2


def test_tp_candidates_distillation():
    rules = load_substitution_file(RULES_PATH)
    cands = tp_candidates_from_rules(rules)
    assert cands[OpType.LINEAR] == [2, 4]
    assert cands[OpType.MULTIHEAD_ATTENTION] == [2]
    assert cands[OpType.EMBEDDING] == [2]


def test_malformed_rule_rejected(tmp_path):
    bad = {
        "_t": "RuleCollection",
        "rule": [{
            "name": "dangling",
            "srcOp": [{"type": "OP_LINEAR",
                       "input": [{"opId": 7, "tsId": 0}], "para": []}],
            "dstOp": [],
            "mappedOutput": [],
        }],
    }
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="outside the pattern"):
        load_substitution_file(str(p))


def test_load_full_osdi_rule_file():
    """The loader parses the full 640-rule OSDI artifact file (vendored)."""
    rules = load_substitution_file(VENDORED_RULES)
    assert len(rules) == 640
    s = summarize(rules)
    assert s["supported"] == len(rules)  # all op types in the file are mapped
    cands = tp_candidates_from_rules(rules)
    assert OpType.LINEAR in cands


@pytest.mark.skipif(not os.path.exists(REFERENCE_RULES),
                    reason="reference rule file not mounted")
def test_vendored_rules_match_reference_copy():
    """Cross-check: the vendored file parses to the same rules as the
    reference's own JSON conversion."""
    import json

    ours = load_substitution_file(VENDORED_RULES)
    ref = load_substitution_file(REFERENCE_RULES)
    assert len(ours) == len(ref)
    assert summarize(ours) == summarize(ref)
    v = json.load(open(VENDORED_RULES))
    r = json.load(open(REFERENCE_RULES))

    def strip(rule):
        return {k: rule[k] for k in ("srcOp", "dstOp", "mappedOutput")}

    assert all(strip(a) == strip(b)
               for a, b in zip(v["rule"], r["rule"]))


def test_search_consumes_rule_file():
    """compile() with --substitution-json restricts TP to rule-proposed op
    types and logs the rule summary."""
    config = ff.FFConfig()
    config.batch_size = 8
    config.num_devices = 8
    config.search_budget = 10
    config.substitution_json_path = RULES_PATH
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 32])
    t = model.dense(inp, 64, ff.ActiMode.AC_MODE_RELU)
    t = model.dense(t, 10)
    model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    x = np.random.RandomState(0).randn(16, 32).astype(np.int32).astype(np.float32)
    y = np.zeros((16, 1), dtype=np.int32)
    hist = model.fit(x, y, epochs=1)
    assert len(hist) == 1
    # the rule-file path must run the Python search (native core can't honor
    # the TP menu) and log the rule summary
    log = "\n".join(model.search_result.log)
    assert "substitution rules:" in log
    # chosen strategies honor the per-type degree menu (LINEAR: 2/4 only)
    from flexflow_tpu.search.substitution_loader import (
        load_substitution_file, tp_candidates_from_rules)
    menu = tp_candidates_from_rules(load_substitution_file(RULES_PATH))
    for guid, s in model.search_result.strategies.items():
        op = model.graph.ops.get(guid)
        if op is None or s.tp <= 1:
            continue
        assert op.op_type in menu and s.tp in menu[op.op_type], (
            op.op_type, s.tp)


def test_rule_file_restricts_tp_degrees():
    """An op type outside the rule file never gets TP; degrees outside the
    menu are rejected."""
    config = ff.FFConfig()
    config.batch_size = 8
    config.num_devices = 8
    config.search_budget = 5
    config.substitution_json_path = RULES_PATH
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 4, 16])
    t = model.batch_matmul(inp, model.transpose(inp, [0, 2, 1]))
    t = model.flat(t)
    t = model.dense(t, 8)
    model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    result = model.search_result
    for guid, s in result.strategies.items():
        op = model.graph.ops.get(guid)
        if op is None:
            continue
        if op.op_type == OpType.BATCHMATMUL:  # not in the rule file
            assert s.tp == 1, s
        if s.tp > 1 and op.op_type == OpType.LINEAR:
            assert s.tp in (2, 4), s
