"""Tests for the machine model, simulator, substitutions, and Unity/MCMC
search (reference analog: tests/unit/ covering machine-view math, graph
algorithms, and substitution loading — SURVEY.md §4)."""
import json

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.ffconst import ActiMode, OpType
from flexflow_tpu.search.machine_model import (
    NetworkedMachineModel,
    TpuPodModel,
)
from flexflow_tpu.search.mcmc import mcmc_optimize
from flexflow_tpu.search.simulator import OpStrategy, Simulator
from flexflow_tpu.search.substitution import apply_substitutions
from flexflow_tpu.search.unity import (
    export_strategy,
    import_strategy,
    unity_optimize,
)


def build_mlp(batch=64, din=512, hidden=2048, classes=10, relu_separate=False):
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, din])
    if relu_separate:
        t = model.dense(inp, hidden)
        t = model.relu(t)
    else:
        t = model.dense(inp, hidden, ActiMode.AC_MODE_RELU)
    t = model.dense(t, classes)
    t = model.softmax(t)
    return model


# -- machine model ------------------------------------------------------
def test_machine_model_costs_monotonic():
    m = TpuPodModel(16)
    b = 1e6
    assert m.allreduce_time_us(b, 1) == 0.0
    assert m.allreduce_time_us(2 * b, 8) > m.allreduce_time_us(b, 8)
    assert m.allgather_time_us(b, 8) > 0
    assert m.compute_time_us(1e12, 1e6, 2) > m.compute_time_us(1e9, 1e6, 2)
    # memory-bound case dominated by HBM bytes
    t_mem = m.compute_time_us(0.0, 8e9, 4)
    assert t_mem > 8e9 / (m.chip.hbm_bw_gbps * 1e9) * 1e6 * 0.99


def test_networked_machine_model_topology():
    m = NetworkedMachineModel(8)
    assert m.hop_count(0, 1) == 1
    assert m.hop_count(0, 4) == 4  # ring distance
    # ECMP on the bidirectional ring splits over both directions: a 45 GB
    # transfer at 2 x 45 GB/s streams in ~0.5 s (+ the pipelined head)
    assert m.p2p_time_us(45e9) == pytest.approx(0.5e6, rel=0.01)
    # single-path routing pays the full serial time
    m1 = NetworkedMachineModel(8, routing="single")
    assert m1.p2p_time_us(45e9) == pytest.approx(1e6, rel=0.01)
    with pytest.raises(ValueError, match="routing"):
        NetworkedMachineModel(8, routing="magic")


def test_networked_segment_pipelining():
    """Multi-hop transfers pipeline per segment (reference role:
    network.cc segment pipelining): on a ring with avg hops > 1, a large
    message costs ~bytes/bw plus ONE extra segment per extra hop, far less
    than hops x serial; shrinking the segment shrinks the overhead."""
    m_small = NetworkedMachineModel(8, segment_mb=0.125, routing="single")
    m_big = NetworkedMachineModel(8, segment_mb=8.0, routing="single")
    bytes_ = 64e6
    serial_one_hop = bytes_ / (m_small.link_gbps * 1e9) * 1e6
    t_small = m_small.p2p_time_us(bytes_)
    t_big = m_big.p2p_time_us(bytes_)
    assert serial_one_hop < t_small < t_big
    # both are far below paying every hop at line rate
    assert t_big < m_big.avg_hops() * serial_one_hop * 0.8
    # tiny message: the segment clamps to the message, cost ~ hops x msg
    t_tiny = m_small.p2p_time_us(1e3)
    assert t_tiny < 2.0  # dominated by the +1us latency term


def test_machine_model_json_loading(tmp_path):
    spec = {"num_chips": 4, "segment_mb": 0.5, "routing": "single",
            "links": [[0, 1, 45.0], [1, 2, 45.0], [2, 3, 45.0], [3, 0, 45.0]]}
    p = tmp_path / "machine.json"
    p.write_text(json.dumps(spec))
    m = NetworkedMachineModel.from_json(str(p))
    assert m.num_chips == 4
    assert m.hop_count(0, 2) == 2
    assert m.segment_bytes == 0.5e6 and m.routing == "single"
    # a 1-D ring has one shared link set: no per-axis overlap channels
    assert not m.comm_channels()
    # a 2D-torus-degree topology (4+ links/chip) has disjoint ring pairs
    conn = np.zeros((6, 6))
    for i in range(6):
        for j in range(6):
            if i != j:
                conn[i][j] = 1
    assert NetworkedMachineModel(6, connection=conn).comm_channels()


# -- simulator ----------------------------------------------------------
def test_per_axis_comm_channels_overlap():
    """Congestion analog of EnhancedMachineModel's per-link queues: on a
    torus-aware machine, dp grad allreduces (data rings) overlap tp
    boundary collectives (model rings) instead of queuing behind them; a
    flat machine serializes all comm on one timeline. Same formulas, so
    the channel-split schedule can only be <= the single-stream one."""
    model = build_mlp(batch=1024, din=2048, hidden=4096)
    graph = Graph(model.ops)
    machine = TpuPodModel(8)
    strategies = {
        op.guid: (OpStrategy(dp=4, tp=2) if op.op_type == OpType.LINEAR
                  else OpStrategy(dp=4))
        for op in model.ops
    }
    sim = Simulator(machine, model.config)
    t_channels = sim.simulate(graph, strategies)

    class FlatTpuPod(TpuPodModel):
        def comm_channels(self):
            return False

    sim_flat = Simulator(FlatTpuPod(8), model.config)
    t_flat = sim_flat.simulate(graph, strategies)
    assert t_channels < t_flat  # the dp/tp overlap must buy real time
    # with only one comm axis in use the two schedules coincide
    dp_only = {op.guid: OpStrategy(dp=8) for op in model.ops}
    assert sim.simulate(graph, dp_only) == pytest.approx(
        sim_flat.simulate(graph, dp_only), rel=1e-9)


def test_channel_schedule_never_loses_randomized():
    """Invariant over random strategy assignments: the per-axis-channel
    schedule is always <= the single-timeline schedule (same costs, strictly
    more permissive ordering), and >= the pure-compute lower bound."""
    model = build_mlp(batch=512, din=1024, hidden=2048)
    graph = Graph(model.ops)

    class FlatTpuPod(TpuPodModel):
        def comm_channels(self):
            return False

    sim_ch = Simulator(TpuPodModel(8), model.config)
    sim_flat = Simulator(FlatTpuPod(8), model.config)
    rng = np.random.RandomState(0)
    for _ in range(20):
        strategies = {}
        for op in model.ops:
            if op.op_type == OpType.LINEAR and rng.rand() < 0.5:
                strategies[op.guid] = OpStrategy(
                    dp=int(rng.choice([1, 2, 4])),
                    tp=int(rng.choice([1, 2])),
                    tp_row=bool(rng.rand() < 0.3))
            else:
                strategies[op.guid] = OpStrategy(
                    dp=int(rng.choice([1, 2, 4, 8])))
        t_ch = sim_ch.simulate(graph, strategies)
        t_flat = sim_flat.simulate(graph, strategies)
        assert t_ch <= t_flat * (1 + 1e-9), (strategies, t_ch, t_flat)
        compute_only = sum(
            sum(sim_ch.fwd_bwd_time_us(op, strategies[op.guid]))
            for op in model.ops)
        assert t_ch >= compute_only * (1 - 1e-9)


def test_simulator_dp_speedup():
    # batch large enough that per-step compute dwarfs the gradient allreduce
    model = build_mlp(batch=16384, din=1024, hidden=4096)
    graph = Graph(model.ops)
    sim = Simulator(TpuPodModel(8), model.config)
    s1 = {op.guid: OpStrategy(1, 1) for op in model.ops}
    s8 = {op.guid: OpStrategy(8, 1) for op in model.ops}
    t1 = sim.simulate(graph, s1)
    t8 = sim.simulate(graph, s8)
    assert t8 < t1  # data parallelism helps


def test_simulator_dp_not_free_for_tiny_models():
    """Gradient sync must be priced: for a tiny model/batch, DP-8 should NOT
    beat single-chip (this is exactly the tradeoff the search exists for)."""
    model = build_mlp(batch=64, din=512, hidden=2048)
    graph = Graph(model.ops)
    sim = Simulator(TpuPodModel(8), model.config)
    s1 = {op.guid: OpStrategy(1, 1) for op in model.ops}
    s8 = {op.guid: OpStrategy(8, 1) for op in model.ops}
    assert sim.simulate(graph, s8) > sim.simulate(graph, s1)


def test_simulator_tp_reduces_memory():
    model = build_mlp(hidden=4096)
    graph = Graph(model.ops)
    sim = Simulator(TpuPodModel(8), model.config)
    dp = {op.guid: OpStrategy(8, 1) for op in model.ops}
    tp = {op.guid: OpStrategy(2, 4) for op in model.ops}
    assert sim.memory_bytes(graph, tp) < sim.memory_bytes(graph, dp)


# -- substitutions ------------------------------------------------------
def test_fuse_linear_relu_substitution():
    model = build_mlp(relu_separate=True)
    graph = Graph(model.ops)
    n_before = len(graph)
    applied = apply_substitutions(graph)
    assert any("fuse_linear_activation" in a for a in applied)
    assert len(graph) == n_before - 1
    # fused op now carries the activation
    lin = [op for op in graph.ops.values() if op.op_type == OpType.LINEAR][0]
    assert lin.params["activation"] == ActiMode.AC_MODE_RELU


def test_cancel_transpose_pair():
    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    inp = model.create_tensor([4, 6, 8])
    t = model.transpose(inp, (0, 2, 1))
    t = model.transpose(t, (0, 2, 1))
    t = model.dense(t, 5)
    graph = Graph(model.ops)
    applied = apply_substitutions(graph)
    assert any("cancel_transpose_pair" in a for a in applied)
    assert all(op.op_type != OpType.TRANSPOSE for op in graph.ops.values())


def test_merge_reshape_and_scalar_chain():
    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    inp = model.create_tensor([4, 24])
    t = model.reshape(inp, (4, 6, 4))
    t = model.reshape(t, (4, 4, 6))
    t = model.scalar_multiply(t, 2.0)
    t = model.scalar_multiply(t, 3.0)
    graph = Graph(model.ops)
    apply_substitutions(graph)
    reshapes = [op for op in graph.ops.values() if op.op_type == OpType.RESHAPE]
    muls = [op for op in graph.ops.values() if op.op_type == OpType.SCALAR_MULTIPLY]
    assert len(reshapes) == 1
    assert len(muls) == 1
    assert muls[0].params["scalar"] == 6.0


# -- unity search -------------------------------------------------------
def test_unity_search_picks_dp_for_compute_heavy_model():
    batch = 16384
    model = build_mlp(batch=batch, din=1024, hidden=4096)
    model.config.search_budget = 8
    graph = Graph(model.ops)
    res = unity_optimize(graph, model.config, TpuPodModel(8), batch, 8)
    assert res.cost_us > 0
    # compute-heavy model: expect data parallelism dominant on the big GEMMs
    lin_ops = [op for op in graph.ops.values() if op.op_type == OpType.LINEAR]
    assert any(res.strategies[op.guid].dp > 1 for op in lin_ops), res.log


def test_unity_memory_search_prefers_tp():
    """With a tiny memory budget, the search must choose a TP-sharded
    factorization (reference: memory-aware lambda search fits -ll:fsize)."""
    model = build_mlp(batch=8, din=4096, hidden=8192, classes=4096)
    model.config.search_budget = 4
    model.config.memory_search = True
    # budget below replicated weights (~
    model.config.memory_budget_mb = 200.0
    graph = Graph(model.ops)
    res = unity_optimize(graph, model.config, TpuPodModel(8), 8, 8)
    assert res.mesh_axes.get("model", 1) > 1, res.log


def test_lambda_search_monotonic_in_budget():
    """The lambda binary search (reference: graph.cc:2075-2131) steers an
    OOM-under-DP model to a fitting TP strategy; chosen memory is monotone
    non-increasing as the budget shrinks, and generous budgets keep the
    unconstrained (fastest) choice."""

    def run(budget_mb):
        model = build_mlp(batch=8, din=4096, hidden=8192, classes=4096)
        model.config.search_budget = 4
        model.config.memory_search = True
        model.config.memory_budget_mb = budget_mb
        graph = Graph(model.ops)
        return unity_optimize(graph, model.config, TpuPodModel(8), 8, 8)

    generous = run(1024 * 1024.0)
    tight = run(400.0)
    tighter = run(150.0)
    assert any("lam=0 fits" in l for l in generous.log), generous.log
    assert tight.memory_bytes <= 400e6, tight.log
    assert tighter.memory_bytes <= tight.memory_bytes
    # replicated Adam state alone (~3x ~200MB weights) busts the tight
    # budgets: the fitting strategy must shard the model dim
    assert tight.mesh_axes.get("model", 1) > 1, tight.log


def test_strategy_export_import_roundtrip(tmp_path):
    model = build_mlp()
    model.config.search_budget = 4
    graph = Graph(model.ops)
    res = unity_optimize(graph, model.config, TpuPodModel(8), 64, 8)
    path = str(tmp_path / "strategy.json")
    export_strategy(res, graph, path)
    strategies, axes = import_strategy(graph, path)
    assert axes == res.mesh_axes
    assert strategies == {g: s for g, s in res.strategies.items() if g in graph.ops}


def test_mcmc_optimize_improves_or_holds():
    model = build_mlp()
    graph = Graph(model.ops)
    sim = Simulator(TpuPodModel(8), model.config)
    start = {op.guid: OpStrategy(8, 1) for op in model.ops}
    start_cost = sim.simulate(graph, start)
    best = mcmc_optimize(graph, model.config, sim, 64, 8, 1, budget=50, seed=1)
    assert sim.simulate(graph, best) <= start_cost * 1.001


def test_compile_with_search_trains():
    """e2e: search-driven compile produces a working sharded train step."""
    config = ff.FFConfig()
    config.batch_size = 64
    config.search_budget = 4
    config.epochs = 2
    rng = np.random.RandomState(0)
    x = rng.randn(256, 64).astype(np.float32)
    w = rng.randn(64, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)[:, None]
    model = ff.FFModel(config)
    inp = model.create_tensor([64, 64])
    t = model.dense(inp, 128, ActiMode.AC_MODE_RELU)
    t = model.dense(t, 10)
    t = model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.1),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    assert model.search_result is not None
    h = model.fit(x, y)
    assert h[-1]["accuracy"] > h[0]["accuracy"] - 0.05


def test_graph_bottlenecks_and_dot():
    model = build_mlp()
    graph = Graph(model.ops)
    bn = graph.bottleneck_nodes()
    assert len(bn) >= 2  # chain graph: every non-source op is a bottleneck
    dot = graph.to_dot()
    assert "digraph PCG" in dot and "->" in dot


def test_search_fusing_final_op_keeps_final_tensor_valid():
    """Regression: substitutions removing the model's last op (fused
    activation) must not orphan final_tensor."""
    config = ff.FFConfig()
    config.batch_size = 64
    config.search_budget = 2
    model = ff.FFModel(config)
    inp = model.create_tensor([64, 32])
    t = model.dense(inp, 10)
    t = model.tanh(t)  # final op gets fused away by the search
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.05),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    )
    x = np.random.RandomState(0).randn(64, 32).astype(np.float32)
    y = np.zeros((64, 1), np.int32)
    h = model.fit(x, y, epochs=1)
    assert np.isfinite(h[0]["cce"] + h[0]["samples"])
    # re-compile must not double-apply the fused activation
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.05),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    )
    lin_ops = [op for op in model.ops if op.op_type == OpType.LINEAR]
    assert len([op for op in model.ops if op.op_type == OpType.TANH]) == 0
    assert lin_ops[0].params["activation"] == ActiMode.AC_MODE_TANH


def test_repartition_axis_validation():
    config = ff.FFConfig()
    config.batch_size = 32
    model = ff.FFModel(config)
    inp = model.create_tensor([32, 16])
    t = model.repartition(inp, dim=0, degree=3)  # no axis of size 3
    t = model.dense(t, 4)
    with pytest.raises(ValueError, match="no mesh axis"):
        model.compile(loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                      parallel_axes={"data": 8})


# -- measured op costs (reference: simulator.cc:489 measures real kernels) --
def _linear_op(model):
    return next(op for op in model.ops if op.op_type == OpType.LINEAR)


def test_op_cost_cache_measures_fwd_and_bwd():
    from flexflow_tpu.search.simulator import OpCostCache

    model = build_mlp(batch=8, din=16, hidden=32, classes=4)
    cache = OpCostCache(model.config, warmup=1, repeats=2)
    op = _linear_op(model)
    fwd, bwd = cache.measure_us(op, OpStrategy(dp=1, tp=1))
    # bwd is grad-time minus fwd-time (grad re-runs the forward); on tiny
    # CPU shapes the difference can vanish in noise, so only require >= 0
    assert fwd > 0 and bwd >= 0
    assert cache.misses == 1 and cache.hits == 0
    # identical op in a *fresh* model shares the cost_key -> cache hit
    model2 = build_mlp(batch=8, din=16, hidden=32, classes=4)
    fwd2, _ = cache.measure_us(_linear_op(model2), OpStrategy(dp=1, tp=1))
    assert cache.hits == 1 and fwd2 == fwd
    # tp sharding is MEASURED at the true sharded weight shape (a fresh
    # cache entry), not divided analytically
    fwd_tp, _ = cache.measure_us(op, OpStrategy(dp=1, tp=2))
    assert cache.misses == 2
    assert fwd_tp > 0 and fwd_tp != fwd


def test_op_cost_cache_failure_is_recorded_and_fallback_counted():
    from flexflow_tpu.search.machine_model import TpuPodModel
    from flexflow_tpu.search.simulator import OpCostCache

    model = build_mlp(batch=8, din=16, hidden=32, classes=4)
    op = _linear_op(model)

    class BrokenCache(OpCostCache):
        def _measure(self, op, dp, tp=1, **kw):
            raise RuntimeError("no device")

    cache = BrokenCache(model.config)
    sim = Simulator(TpuPodModel(4), model.config, measured=cache)
    t = sim.op_step_time_us(op, OpStrategy(dp=1, tp=1))
    assert t > 0  # analytic fallback
    assert sim.analytic_fallbacks == 1
    assert len(cache.failures) == 1  # loud, not silent


def test_event_driven_sim_overlaps_collectives():
    """The two-stream schedule hides grad-sync allreduces under the
    remaining backward when overlap is on; serializing them must cost more
    (replaces the old sequential-sum + 0.8 fudge)."""

    from flexflow_tpu.search.machine_model import TpuPodModel

    model = build_mlp(batch=64, din=512, hidden=2048, classes=10)
    machine = TpuPodModel(4)
    graph = Graph(model.ops)
    strategies = {op.guid: OpStrategy(dp=4, tp=1) for op in model.ops}

    model.config.search_overlap_backward_update = True
    c_async = Simulator(machine, model.config).simulate(graph, strategies)
    model.config.search_overlap_backward_update = False
    c_sync = Simulator(machine, model.config).simulate(graph, strategies)
    assert c_async < c_sync
    # serialized cost equals the plain sum of all task durations
    sim = Simulator(machine, model.config)
    total = 0.0
    for op in model.ops:
        s = strategies[op.guid]
        fwd, bwd = sim.fwd_bwd_time_us(op, s)
        total += fwd + bwd + sim.cost.grad_sync_time_us(op, s)
    assert c_sync == pytest.approx(total)


def test_measured_costs_change_search_outcome():
    """A measured cache whose numbers disagree >2x with the analytic model
    must change the simulated cost (and can flip the chosen strategy)."""
    from flexflow_tpu.search.machine_model import TpuPodModel
    from flexflow_tpu.search.simulator import OpCostCache

    model = build_mlp(batch=64, din=256, hidden=1024, classes=10)
    machine = TpuPodModel(4)
    graph = Graph(model.ops)

    class FakeMeasured(OpCostCache):
        def _measure(self, op, dp, tp=1, **kw):
            return 5000.0 / dp, 10000.0 / dp  # much slower than analytic

    analytic = Simulator(machine, model.config)
    measured = Simulator(machine, model.config, measured=FakeMeasured(model.config))
    strategies = {op.guid: OpStrategy(dp=4, tp=1) for op in model.ops}
    c_a = analytic.simulate(graph, strategies)
    c_m = measured.simulate(graph, strategies)
    assert c_m > 2 * c_a


def test_op_cost_cache_persists(tmp_path):
    from flexflow_tpu.search.simulator import OpCostCache

    path = str(tmp_path / "costs.json")
    model = build_mlp(batch=8, din=16, hidden=32, classes=4)
    op = _linear_op(model)
    cache = OpCostCache(model.config, warmup=1, repeats=2, path=path)
    fwd, bwd = cache.measure_us(op, OpStrategy(dp=1, tp=1))
    cache.save()
    fresh = OpCostCache(model.config, path=path)
    fwd2, bwd2 = fresh.measure_us(op, OpStrategy(dp=1, tp=1))
    assert fresh.misses == 0 and fresh.hits == 1
    assert (fwd2, bwd2) == (fwd, bwd)


def test_unity_optimize_uses_measured_when_configured():
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search import simulator as sim_mod

    model = build_mlp(batch=64, din=64, hidden=128, classes=8)
    model.config.num_devices = 4
    model.config.search_budget = 4
    model.config.measure_op_costs = True
    sim_mod._GLOBAL_CACHE = None  # isolate from other tests
    machine = make_machine_model(model.config, 4)
    result = unity_optimize(Graph(model.ops), model.config, machine, 64, 4)
    assert any("measured-cost cache" in line for line in result.log)
    cache = sim_mod.get_op_cost_cache(model.config)
    assert cache.misses > 0  # real measurements happened
    sim_mod._GLOBAL_CACHE = None


def test_cancel_split_concat_rule():
    from flexflow_tpu.search.substitution import apply_substitutions

    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    x = model.create_tensor([4, 12])
    t = model.dense(x, 12, name="d1")
    parts = model.split(t, [4, 8], -1, name="sp")
    cat = model.concat(parts, -1, name="cat")
    model.softmax(model.dense(cat, 3, name="d2"))
    g = Graph(model.ops)
    n_before = len(g.ops)
    applied = apply_substitutions(g)
    assert any("cancel_split_concat" in a for a in applied), applied
    assert len(g.ops) == n_before - 2
    # d2 now consumes d1's output directly
    d2 = next(op for op in g.ops.values() if op.name == "d2")
    assert d2.inputs[0].owner_op.name == "d1"


def test_drop_zero_dropout_and_noop_cast_rules():
    from flexflow_tpu.search.substitution import apply_substitutions

    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    x = model.create_tensor([4, 8])
    t = model.dense(x, 8, name="d1")
    t = model.dropout(t, 0.0, name="dr")
    t = model.cast(t, ff.DataType.DT_FLOAT, name="c")  # same dtype
    model.softmax(model.dense(t, 3, name="d2"))
    g = Graph(model.ops)
    applied = apply_substitutions(g)
    assert any("drop_zero_dropout" in a for a in applied), applied
    assert any("drop_noop_cast" in a for a in applied), applied
    names = {op.name for op in g.ops.values()}
    assert "dr" not in names and "c" not in names


def test_split_consumed_elsewhere_not_cancelled():
    """split outputs with an extra consumer must NOT cancel (the rewrite
    would orphan that consumer's input)."""
    from flexflow_tpu.search.substitution import rule_cancel_split_concat

    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    x = model.create_tensor([4, 12])
    t = model.dense(x, 12, name="d1")
    parts = model.split(t, [6, 6], -1, name="sp")
    cat = model.concat(parts, -1, name="cat")
    extra = model.dense(parts[0], 3, name="extra")  # second consumer
    model.softmax(model.add(model.dense(cat, 3, name="d2"), extra))
    g = Graph(model.ops)
    assert rule_cancel_split_concat(g) == []


def test_strategy_roundtrip_preserves_sp(tmp_path):
    """The exported strategy file carries the sp (sequence-parallel) field
    and round-trips it (older files without it default to 1)."""
    from flexflow_tpu.search.unity import SearchResult

    model = build_mlp()
    graph = Graph(model.ops)
    strategies = {op.guid: OpStrategy(dp=2, sp=4) for op in model.ops}
    res = SearchResult(strategies, {"data": 2, "seq": 4}, 1.0, 0.0, [])
    path = str(tmp_path / "sp_strategy.json")
    export_strategy(res, graph, path)
    loaded, axes = import_strategy(graph, path)
    assert axes == {"data": 2, "seq": 4}
    assert all(s.sp == 4 and s.dp == 2 for s in loaded.values())


def test_strategy_roundtrip_preserves_all_axes(tmp_path):
    """Every per-op axis the searches emit — dp, tp(+row), ep, ap, sp —
    survives export -> import (native results flow through the same
    writer, so this also covers the native-search export path)."""
    from flexflow_tpu.search.unity import SearchResult

    model = build_mlp()
    graph = Graph(model.ops)
    strategies = {op.guid: OpStrategy(dp=2, tp=2, ep=2, ap=2, sp=1,
                                      tp_row=True) for op in model.ops}
    res = SearchResult(strategies,
                       {"data": 2, "model": 2, "expert": 2, "attr": 2},
                       1.0, 0.0, [])
    path = str(tmp_path / "full_strategy.json")
    export_strategy(res, graph, path)
    loaded, axes = import_strategy(graph, path)
    assert axes == {"data": 2, "model": 2, "expert": 2, "attr": 2}
    for s in loaded.values():
        assert (s.dp, s.tp, s.ep, s.ap, s.tp_row) == (2, 2, 2, 2, True)


# -- MCMC user path (--strategy-search mcmc) ----------------------------
def test_mcmc_flags_parse():
    cfg = ff.FFConfig()
    rest = cfg.parse_args(["--strategy-search", "mcmc",
                           "--mcmc-budget", "50", "--mcmc-propagate"])
    assert rest == []
    assert cfg.strategy_search == "mcmc"
    assert cfg.mcmc_budget == 50
    assert cfg.mcmc_propagate is True
    with pytest.raises(ValueError):
        ff.FFConfig().parse_args(["--strategy-search", "genetic"])


def test_mcmc_search_beats_pure_data_parallel():
    """mcmc_search starts each factorization from pure DP, so its winner is
    never worse than the best pure-DP strategy under the same simulator."""
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.mcmc import mcmc_search

    model = build_mlp()
    model.config.mcmc_budget = 120
    graph = Graph(model.ops)
    machine = make_machine_model(model.config, 8)
    sim = Simulator(machine, model.config)
    result = mcmc_search(graph, model.config, machine, 64, 8, simulator=sim)
    pure_dp = {op.guid: OpStrategy(dp=8, tp=1) for op in graph.ops.values()}
    assert result.cost_us <= sim.simulate(graph, pure_dp) + 1e-6
    assert result.strategies and result.mesh_axes


def test_mcmc_compile_and_export(tmp_path):
    """compile() dispatches to MCMC and exports its strategy through the
    same --export file Unity uses (reference: model.cc:3609-3617)."""
    export = tmp_path / "mcmc_strategy.json"
    config = ff.FFConfig()
    config.batch_size = 64
    config.num_devices = 8
    config.strategy_search = "mcmc"
    config.mcmc_budget = 60
    config.export_strategy_file = str(export)
    model = ff.FFModel(config)
    inp = model.create_tensor([64, 512])
    t = model.dense(inp, 2048, ActiMode.AC_MODE_RELU)
    t = model.dense(t, 10)
    model.softmax(t)
    model.compile(loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    assert model.search_result is not None
    assert export.exists()
    data = json.loads(export.read_text())
    assert data["ops"] and "mesh_axes" in data


def test_mcmc_vs_unity_comparable():
    """Unity's best-first search should match or beat annealing on a small
    graph under the same simulator/cost model."""
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.mcmc import mcmc_search

    model = build_mlp()
    model.config.search_budget = 30
    model.config.mcmc_budget = 120
    graph = Graph(model.ops)
    machine = make_machine_model(model.config, 8)
    sim = Simulator(machine, model.config)
    unity = unity_optimize(Graph(model.ops), model.config, machine, 64, 8,
                           simulator=sim)
    mcmc = mcmc_search(graph, model.config, machine, 64, 8, simulator=sim)
    assert unity.cost_us <= mcmc.cost_us * 1.05


# -- elastic-PR regressions (machine model fixes) -----------------------
def test_from_json_empty_links_uses_defaults(tmp_path):
    """A spec with no/empty 'links' must not NameError: it keeps the
    default 45 GB/s and falls back to the default ring topology (the
    elastic coordinator feeds shrunken survivor specs through here, and a
    loss can sever every link of the survivor set)."""
    m = NetworkedMachineModel.from_json({"links": []})
    assert m.num_chips == 1 and m.link_gbps == 45.0

    m4 = NetworkedMachineModel.from_json({"num_chips": 4, "links": []})
    assert m4.num_chips == 4 and m4.link_gbps == 45.0
    assert m4.hop_count(0, 2) == 2  # default-ring fallback is connected

    p = tmp_path / "empty_links.json"
    p.write_text(json.dumps({"num_chips": 3}))
    mf = NetworkedMachineModel.from_json(str(p))
    assert mf.num_chips == 3 and mf.link_gbps == 45.0

    # num_chips may be inferred from the links when omitted
    mi = NetworkedMachineModel.from_json(
        {"links": [[0, 1, 90.0], [1, 2, 90.0]]})
    assert mi.num_chips == 3 and mi.link_gbps == 90.0


def test_sp_ring_ppermute_is_single_path():
    """The ring-SP neighbor ppermute sends one direction on every chip at
    once: ECMP cannot split it over both ring directions, so its cost must
    NOT see the 2x path_diversity multiplier (while plain p2p still
    does)."""
    from flexflow_tpu.search.simulator import CostModel

    ecmp = NetworkedMachineModel(8)
    single = NetworkedMachineModel(8, routing="single")
    b = 45e9
    # plain p2p keeps the ECMP split; the single-path variant does not
    assert ecmp.p2p_time_us(b) == pytest.approx(0.5e6, rel=0.01)
    assert ecmp.p2p_single_path_time_us(b) == pytest.approx(1e6, rel=0.01)
    assert ecmp.p2p_single_path_time_us(b) \
        == pytest.approx(single.p2p_time_us(b))

    config = ff.FFConfig()
    config.batch_size = 8
    model = ff.FFModel(config)
    q = model.create_tensor([8, 128, 64])
    model.multihead_attention(q, q, q, 64, 4)
    attn = next(op for op in model.ops
                if op.op_type == OpType.MULTIHEAD_ATTENTION)
    s = OpStrategy(dp=1, tp=1, sp=4)
    ring_ecmp = CostModel(ecmp, config).sp_collective_time_us(attn, s)
    ring_single = CostModel(single, config).sp_collective_time_us(attn, s)
    assert ring_ecmp > 0
    assert ring_ecmp == pytest.approx(ring_single)


# -- plan-sanitizer pruning (ISSUE 2) -----------------------------------
def test_analysis_prune_same_strategy_fewer_candidates():
    """Pruning mesh factorizations with the cheap static passes must not
    change the chosen strategy, while the cost simulator prices strictly
    fewer candidates (the counter the serving metrics also export)."""

    def run(prune):
        # batch 50: dp=4 and dp=8 tuples genuinely fail batch divisibility
        # (FFTA001), so the dp prune path is exercised alongside the
        # unusable-axis (FFTA004) ep/ap/sp prunes
        model = build_mlp(batch=50)
        model.config.search_budget = 4
        model.config.use_native_search = False
        model.config.analysis_prune = prune
        graph = Graph(model.ops)
        return unity_optimize(graph, model.config, TpuPodModel(8), 50, 8)

    pruned = run(True)
    unpruned = run(False)
    assert pruned.mesh_axes == unpruned.mesh_axes
    # guids differ between builds; compare strategies positionally (both
    # graphs are built in the same op order)
    def by_order(res):
        return [res.strategies[g] for g in sorted(res.strategies)]

    assert by_order(pruned) == by_order(unpruned)
    assert pruned.candidates_pruned > 0
    assert unpruned.candidates_pruned == 0
    assert pruned.candidates_simulated < unpruned.candidates_simulated
    assert (pruned.candidates_simulated + pruned.candidates_pruned
            == unpruned.candidates_simulated)


def test_unpruned_baseline_cannot_realize_infeasible_sp():
    """dp/tp/ep/ap degrade safely per op inside valid_strategies, but sp's
    graph-level blockers (dropout-carrying attention here) are invisible to
    sp_shardable — the unpruned baseline must clamp such sp tuples rather
    than simulate (and possibly choose) an sp plan the pruned search
    rejects."""
    config = ff.FFConfig()
    config.batch_size = 2
    config.search_budget = 4
    config.use_native_search = False
    config.enable_sequence_parallel = True
    config.analysis_prune = False
    model = ff.FFModel(config)
    # long-context shape where sp genuinely wins the cost race (unclamped,
    # the search chooses {'data': 2, 'seq': 4} here)
    tokens = model.create_tensor([2, 4096], ff.DataType.DT_INT32)
    t = model.embedding(tokens, 100, 256, ff.AggrMode.AGGR_MODE_NONE)
    # dropout > 0: the SP kernels have no attention dropout, so every
    # sp > 1 factorization is infeasible for this graph
    attn = model.multihead_attention(t, t, t, 256, 8, dropout=0.1)
    model.softmax(model.dense(attn, 4))
    graph = Graph(model.ops)
    result = unity_optimize(graph, config, TpuPodModel(8), 2, 8)
    assert result.mesh_axes.get("seq", 1) == 1
    assert all(s.sp == 1 for s in result.strategies.values())


def test_legacy_overlap_knob_pins_blocking_pricing():
    """search_overlap_backward_update=False must force the overlap term
    to zero — blocking pricing, bit-identical to the pre-bucketing
    overlap=False path (the plain sum of task durations) — regardless
    of --grad-bucket-bytes (docs/machine.md "Overlap")."""
    from flexflow_tpu.search.machine_model import (CHIP_SPECS,
                                                   HierarchicalMachineModel,
                                                   TierSpec)

    chip = CHIP_SPECS["tpu-v5e"]
    machine = HierarchicalMachineModel(
        [TierSpec("ici", 8, chip.ici_link_gbps, 2),
         TierSpec("dcn", 2, 3.125, 1, 10.0)], chip)
    model = build_mlp(batch=64, din=512, hidden=2048, classes=10)
    graph = Graph(model.ops)
    strategies = {op.guid: OpStrategy(dp=16) for op in model.ops}
    model.config.search_overlap_backward_update = False
    costs = []
    for bb in (0, 4096, 25 * 1024 * 1024):
        model.config.grad_bucket_bytes = bb
        sim = Simulator(machine, model.config)
        costs.append(sim.simulate(graph, strategies))
        st = sim.last_sync_stats
        assert st["overlapped_sync_us"] == 0.0
        assert st["exposed_sync_us"] == st["total_sync_us"] > 0
        assert st["buckets"] == []
    assert costs[0] == costs[1] == costs[2]
    # blocking == the plain sum of all task durations (the historical
    # overlap=False contract, same as the flat-machine pin above)
    sim = Simulator(machine, model.config)
    total = 0.0
    for op in model.ops:
        s = strategies[op.guid]
        fwd, bwd = sim.fwd_bwd_time_us(op, s)
        total += fwd + bwd + sim.cost.grad_sync_time_us(op, s)
    assert costs[0] == pytest.approx(total)
    # with the knob ON, the bucketed overlap term exists and buys time
    model.config.search_overlap_backward_update = True
    sim_o = Simulator(machine, model.config)
    c_o = sim_o.simulate(graph, strategies)
    assert c_o < costs[0]
    st = sim_o.last_sync_stats
    assert st["buckets"]
    assert 0.0 <= st["exposed_sync_us"] <= st["total_sync_us"]
    assert st["overlapped_sync_us"] == pytest.approx(
        st["total_sync_us"] - st["exposed_sync_us"])


# -- expert-parallel enumeration (ISSUE 16) ---------------------------------

def _moe_search_graph(n_experts=8, batch=64, F=32, k=2, H=48):
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, F])
    out = model.moe(inp, n_experts, k, H, alpha=float(n_experts),
                    fused=True, name="moe")
    model.dense(out, 4)
    return Graph(model.ops), config


def test_feasible_ep_values_respect_divisibility():
    """ep candidates divide BOTH the device count and every expert count;
    graphs without EXPERTS ops get no ep candidates at all."""
    from flexflow_tpu.search.unity import feasible_ep_values

    graph, config = _moe_search_graph(n_experts=6)
    # divisors of 8 devices: 2, 4, 8 — only 2 divides 6 experts
    assert feasible_ep_values(graph, config, 8) == [1, 2]
    graph12, config12 = _moe_search_graph(n_experts=12)
    assert feasible_ep_values(graph12, config12, 8) == [1, 2, 4]
    dense_graph, dense_config = (lambda m: (Graph(m.ops), m.config))(
        (lambda: (m := ff.FFModel(ff.FFConfig()),
                  m.dense(m.create_tensor([8, 4]), 4), m)[-1])())
    assert feasible_ep_values(dense_graph, dense_config, 8) == [1]


def test_factorization_enumeration_includes_ep_and_prunes_non_dividing():
    """The cold sweep's factorization table carries ep>1 tuples for MoE
    graphs, and the sanitizer prunes ep values that do not divide the
    expert count before the simulator prices them."""
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.unity import GraphSearchHelper

    graph, config = _moe_search_graph(n_experts=6, batch=64)
    machine = make_machine_model(config, 8)
    helper = GraphSearchHelper(graph, config, machine)
    facts = helper._feasible_factorizations(graph, 64, 8)
    eps = {f[2] for f in facts}
    assert 2 in eps  # divides 6 experts and 8 devices
    assert 4 not in eps and 8 not in eps  # do not divide 6 experts
    assert helper.candidates_pruned > 0


def test_pod_residency_prunes_dcn_crossing_ep():
    """On a multi-tier machine the ep group's device span (ep x the axes
    nested inside it) must fit in the innermost tier: ep tuples that
    would stride the routing all_to_all across DCN are pruned (FFTA085),
    while the same tuples survive on a flat machine."""
    from flexflow_tpu.search.machine_model import (CHIP_SPECS,
                                                   HierarchicalMachineModel,
                                                   TierSpec,
                                                   make_machine_model)
    from flexflow_tpu.search.unity import GraphSearchHelper

    graph, config = _moe_search_graph(n_experts=16, batch=64)
    chip = CHIP_SPECS["tpu-v5e"]
    tiered = HierarchicalMachineModel(
        [TierSpec("ici", 8, chip.ici_link_gbps, 2),
         TierSpec("dcn", 2, 3.125, 1, 10.0)], chip)
    helper = GraphSearchHelper(graph, config, tiered)
    facts = helper._feasible_factorizations(graph, 64, 16)
    spanning = [f for f in facts if f[2] > 1 and f[2] * f[3] * f[4] > 8]
    assert not spanning, spanning
    assert any(f[2] == 8 for f in facts)  # pod-filling ep survives

    flat = make_machine_model(config, 16)
    assert not getattr(flat, "tiers", None)
    helper_flat = GraphSearchHelper(graph, config, flat)
    facts_flat = helper_flat._feasible_factorizations(graph, 64, 16)
    assert any(f[2] == 16 for f in facts_flat)  # no pod to protect
