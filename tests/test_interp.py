"""Sharding-flow verifier (ISSUE 17): abstract interpretation of
parallel plans plus deadlock/uniformity model checking of the executed
collective program (FFTA09x, docs/analysis.md "Verifier").

The decisive properties:
 - every checked-in strategy artifact, every zoo model's searched plan
   (test_analysis.py covers those through the shared pipeline), a moe
   plan searched on the multipod_2x8 hierarchy, and a live-resharding
   schedule all verify CLEAN through the new pass;
 - five seeded mutations — dropped sync, overlapping group member,
   reordered participant program, layout-incompatible edge, in-place
   overwrite of a live tensor — each produce their exact FFTA09x code;
 - the diagnostic catalogue, the analysis sources, and
   docs/analysis.md never drift apart (both directions).
"""
import copy
import glob
import json
import os
import re

import pytest

import flexflow_tpu as ff
from flexflow_tpu.analysis import (
    ALL_PASSES,
    CHEAP_PASSES,
    PlanAnalysisError,
    ShardingFlowInterpreter,
    analyze_plan,
    build_grad_sync_program,
    build_reshard_program,
    check_event_partitions,
    check_program_uniformity,
    gradient_state,
    participant_programs,
    verify_grad_sync_program,
    verify_reshard_program,
)
from flexflow_tpu.analysis.diagnostics import CODE_CATALOG, Severity
from flexflow_tpu.analysis.interp import (
    ALL_GATHER,
    PSUM,
    PSUM_SCATTER,
    AbstractLayout,
    CollectiveEvent,
)
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.search.simulator import OpStrategy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class StubMesh:
    """The mesh surface plan_grad_sync_lowering reads — no jax needed."""

    def __init__(self, n=8):
        self.axis_names = ("data",)
        self.shape = {"data": n}


def build_mlp(batch=64, din=32, hidden=128, classes=10):
    config = ff.FFConfig()
    config.batch_size = batch
    m = ff.FFModel(config)
    t = m.create_tensor([batch, din])
    t = m.dense(t, hidden, ff.ActiMode.AC_MODE_RELU)
    t = m.dense(t, classes)
    m.softmax(t)
    return m, Graph(m.ops), config


def tiered_lowering(graph, n=8, strategy="rs_ar_ag", inner=4, outer=2):
    """An explicit lowering whose every weighted op syncs over an
    inner x outer tier decomposition (built exactly the way compile()
    does, through plan_grad_sync_lowering — gate included)."""
    from flexflow_tpu.runtime.collectives import plan_grad_sync_lowering

    cfg = ff.FFConfig()
    cfg.collective_lowering = "explicit"
    plan = {op.name: {"strategy": strategy, "degree": n, "bytes": 1e6,
                      "tiers": [{"tier": "ici", "group": inner},
                                {"tier": "dcn", "group": outer}]}
            for op in graph.topo_order() if op.weights}
    lowering, reasons = plan_grad_sync_lowering(cfg, graph, StubMesh(n),
                                                plan)
    assert lowering is not None, reasons
    return lowering


# ---------------------------------------------------------------------
# the abstract domain
# ---------------------------------------------------------------------
def test_abstract_layout_of_strategy():
    _, g, _ = build_mlp()
    dense = next(op for op in g.ops.values() if "linear" in op.name)
    out = dense.outputs[0]
    lay = AbstractLayout.of_strategy(dense, OpStrategy(dp=4, tp=2), out)
    assert lay.dims[0] == ("data", 4)
    assert lay.dims[-1] == ("model", 2)
    assert lay.pending == frozenset()
    # a row-parallel matmul's raw output is a pending partial sum
    row = AbstractLayout.of_strategy(
        dense, OpStrategy(tp=2, tp_row=True), out)
    assert row.pending == frozenset({"model"})
    assert AbstractLayout.replicated(2).dims == (None, None)


def test_gradient_state_tracks_sync_degree():
    _, g, _ = build_mlp()
    weighted = [op for op in g.topo_order() if op.weights]
    synced = gradient_state(
        g, {op.guid: OpStrategy(dp=4) for op in weighted})
    assert all(synced[op.name] == frozenset({"data"}) for op in weighted)
    unsynced = gradient_state(
        g, {op.guid: OpStrategy(dp=1, tp=2) for op in weighted})
    assert all(unsynced[op.name] == frozenset() for op in weighted)
    # no strategy pinned: conservatively pending
    assert all(v == frozenset({"data"})
               for v in gradient_state(g, None).values())


def test_flow_pass_registered_in_presets():
    assert "flow" in CHEAP_PASSES and "flow" in ALL_PASSES


# ---------------------------------------------------------------------
# program construction mirrors lower_allreduce
# ---------------------------------------------------------------------
def test_program_expansion_flat_hier_rs():
    _, g, _ = build_mlp()
    flat = build_grad_sync_program(tiered_lowering(g, strategy="flat"))
    per_op = {e.tag for e in flat}
    assert len(per_op) == 2 and all(e.kind == PSUM for e in flat)
    assert all(e.groups == (tuple(range(8)),) for e in flat)

    hier = build_grad_sync_program(
        tiered_lowering(g, strategy="hier_ring"))
    kinds = [e.kind for e in hier if e.tag == sorted(per_op)[0]]
    assert kinds == [PSUM, PSUM]  # one psum per tier level

    rs = build_grad_sync_program(tiered_lowering(g, strategy="rs_ar_ag"))
    seq = [(e.kind, len(e.groups)) for e in rs
           if e.tag == sorted(per_op)[0]]
    # scatter over the 2 inner rings, psum over the 4 cross groups,
    # gather back over the inner rings — lower_allreduce's issue order
    assert seq == [(PSUM_SCATTER, 2), (PSUM, 4), (ALL_GATHER, 2)]


def test_bucketed_entries_collapse_to_one_program():
    from flexflow_tpu.runtime.collectives import GradSyncLowering

    entries = {
        "a": {"strategy": "flat", "sizes": [8], "tiers": [],
              "bucket": 0, "bytes": 1.0},
        "b": {"strategy": "flat", "sizes": [8], "tiers": [],
              "bucket": 0, "bytes": 1.0},
        "c": {"strategy": "flat", "sizes": [8], "tiers": [],
              "bucket": None, "bytes": 1.0},
    }
    low = GradSyncLowering(axis_name="data", degree=8, entries=entries,
                           mode="explicit")
    ev = build_grad_sync_program(low)
    # bucket mates fuse to ONE collective; the unbucketed op keeps its own
    assert [e.tag for e in ev] == ["bucket:0", "c"]


# ---------------------------------------------------------------------
# clean plans verify clean
# ---------------------------------------------------------------------
@pytest.mark.parametrize("strategy", ["flat", "hier_ring", "rs_ar_ag"])
def test_grad_sync_program_verifies_clean(strategy):
    _, g, _ = build_mlp()
    low = tiered_lowering(g, strategy=strategy)
    weighted = [op for op in g.topo_order() if op.weights]
    diags = verify_grad_sync_program(
        low, graph=g, strategies={op.guid: OpStrategy(dp=8)
                                  for op in weighted})
    assert diags == []


@pytest.mark.parametrize("path", sorted(
    glob.glob(os.path.join(REPO, "examples", "strategies", "*.json"))))
def test_strategy_artifacts_verify_clean(path, capsys):
    """Every checked-in strategy file passes the full pipeline (flow
    pass included) through the CLI, and the --json stdout carries no
    FFTA09x finding — the same contract the CI verify-plans job pins."""
    from flexflow_tpu.analysis.cli import run_analyze

    model = os.path.basename(path).replace("_8dev.json", "")
    assert run_analyze(["--model", model, "--chips", "8",
                        "--strategy", path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == 1 and doc["ok"]
    assert not [d for d in doc["diagnostics"]
                if d["code"].startswith("FFTA09")]
    assert "flow" in doc["passes_run"]


def test_moe_searched_plan_on_multipod_verifies():
    """A moe plan searched on the multipod_2x8 hierarchy analyzes clean
    AND its explicit grad-sync lowering model-checks clean."""
    from flexflow_tpu.runtime.collectives import plan_grad_sync_lowering
    from flexflow_tpu.search.machine_model import HierarchicalMachineModel
    from flexflow_tpu.search.unity import unity_optimize

    machine = HierarchicalMachineModel.from_json(
        os.path.join(REPO, "examples", "machines", "multipod_2x8.json"))
    config = ff.FFConfig()
    config.batch_size = 32
    config.search_budget = 2
    config.use_native_search = False
    m = ff.FFModel(config)
    inp = m.create_tensor([32, 8])
    out = m.moe(inp, 4, 2, 12, alpha=4.0, fused=True, name="moe")
    m.dense(out, 3)
    g = Graph(m.ops)
    result = unity_optimize(g, config, machine, 32, 16)
    report = analyze_plan(
        g, strategies=result.strategies, machine=machine, config=config,
        batch_size=32, n_devices=16, mesh_axes=result.mesh_axes,
        reduction_strategies=result.reduction_strategies,
        final_guid=g.topo_order()[-1].guid)
    assert report.ok, report.format()
    assert not [d for d in report.diagnostics
                if d.code.startswith("FFTA09")]
    if result.reduction_strategies:
        dp = max(e["degree"]
                 for e in result.reduction_strategies.values())
        cfg = ff.FFConfig()
        cfg.collective_lowering = "explicit"
        low, reasons = plan_grad_sync_lowering(
            cfg, g, StubMesh(dp), result.reduction_strategies)
        if low is None:
            # the documented GSPMD fallback: experts carry running
            # state, so the explicit lowering declines the whole model
            assert any("running state" in r for r in reasons), reasons
        else:
            assert verify_grad_sync_program(
                low, graph=g, strategies=result.strategies) == []


def test_live_reshard_schedule_verifies_clean():
    import numpy as np

    from flexflow_tpu.analysis import check_redistribution
    from flexflow_tpu.resharding import (ArraySpec, MeshSpec, ShardingPlan,
                                         plan_redistribution)
    from flexflow_tpu.search.machine_model import (ChipSpec,
                                                   SimpleMachineModel)

    mesh = MeshSpec(device_ids=tuple(range(8)),
                    axes=(("data", 4), ("model", 2)))
    old = ShardingPlan(mesh=mesh,
                       arrays={"w": ArraySpec((4, 1), ("data", None))})
    new = ShardingPlan(mesh=mesh, arrays={})
    sched = plan_redistribution({"w": np.zeros((16, 8), np.float32)},
                                old, new, peak_bytes=1 << 30)
    assert verify_reshard_program(sched) == []
    events, devices = build_reshard_program(sched)
    # the allgather round groups the old mesh along 'data': 2 groups of 4
    ag = [e for e in events if e.kind == ALL_GATHER]
    assert ag and all(len(e.groups) == 2 and
                      all(len(grp) == 4 for grp in e.groups)
                      for e in ag)
    report = check_redistribution(
        sched, machine=SimpleMachineModel(8, ChipSpec(hbm_gb=16.0)),
        record=False)
    assert report.ok and "flow" in report.passes_run


# ---------------------------------------------------------------------
# seeded mutations: each corruption produces its exact code
# ---------------------------------------------------------------------
def test_mutation_dropped_sync_ffta090():
    _, g, _ = build_mlp()
    low = copy.deepcopy(tiered_lowering(g))
    dropped = next(iter(low.entries))
    del low.entries[dropped]
    codes = [d.code for d in verify_grad_sync_program(low, graph=g)]
    assert codes == ["FFTA090"]
    d = verify_grad_sync_program(low, graph=g)[0]
    assert dropped in d.message and d.severity is Severity.ERROR


def test_mutation_swapped_group_member_ffta091():
    _, g, _ = build_mlp()
    events = list(build_grad_sync_program(tiered_lowering(g)))
    e0 = events[0]
    groups = [list(grp) for grp in e0.groups]
    groups[0][0] = groups[1][0]  # one member duplicated, one uncovered
    events[0] = CollectiveEvent(
        e0.kind, e0.tag, e0.phase,
        tuple(tuple(grp) for grp in groups))
    codes = {d.code for d in check_event_partitions(events, 8)}
    assert codes == {"FFTA091"}
    # the full verifier stops at the static layer for this corruption
    msgs = " ".join(d.message
                    for d in check_event_partitions(events, 8))
    assert "axis_index_group" in msgs or "cover" in msgs


def test_mutation_reordered_round_ffta092():
    _, g, _ = build_mlp()
    events = build_grad_sync_program(tiered_lowering(g, strategy="flat"))
    progs = participant_programs(events, range(8))
    # one participant issues the two fused syncs in the opposite order
    progs[3][0], progs[3][1] = progs[3][1], progs[3][0]
    codes = [d.code for d in check_program_uniformity(progs)]
    assert codes == ["FFTA092"]
    assert "cycle" in check_program_uniformity(progs)[0].message


def test_mutation_incompatible_edge_ffta093():
    _, g, _ = build_mlp()
    ops = g.topo_order()
    strategies = {op.guid: OpStrategy(dp=4) for op in ops}
    consumer = ops[1]
    t = consumer.inputs[0]
    orig = t.dims
    try:
        # a "rewrite" drifts the producer tensor's batch dim: 64 -> 66,
        # indivisible by dp=4 while the consumer's own output stays legal
        t.dims = (orig[0] + 2,) + tuple(orig[1:])
        diags = ShardingFlowInterpreter(g, strategies, batch_size=64).run()
        assert [d.code for d in diags] == ["FFTA093"]
        assert diags[0].op_name == consumer.name
    finally:
        t.dims = orig


def test_mutation_inplace_overwrite_ffta094():
    config = ff.FFConfig()
    config.batch_size = 64
    m = ff.FFModel(config)
    x = m.create_tensor([64, 32])
    h = m.dense(x, 32)
    h2 = m.dense(h, 32)
    m.add(h2, h)  # h is read again AFTER the second dense
    g = Graph(m.ops)
    clobber = next(op for op in g.topo_order()
                   if op.inputs and op.inputs[0].guid == h.guid)
    clobber.params["inplace"] = True
    diags = ShardingFlowInterpreter(g, {}).run()
    assert [d.code for d in diags] == ["FFTA094"]
    assert "add" in diags[0].message


def test_uniformity_head_disagreement_ffta091():
    # two participants reach the same sync tag with different groups
    progs = {0: [("psum", "t", 0, (0, 1))],
             1: [("psum", "t", 0, (1, 0))]}  # group order differs
    codes = [d.code for d in check_program_uniformity(progs)]
    assert codes == ["FFTA091"]
    # a participant issuing a collective excluding itself is also 091
    bad = {0: [("psum", "t", 0, (1, 2))]}
    assert [d.code for d in check_program_uniformity(bad)] == ["FFTA091"]


# ---------------------------------------------------------------------
# the runtime gate and the search prune
# ---------------------------------------------------------------------
def test_lowering_gate_raises_on_corrupt_schedule():
    from flexflow_tpu.runtime.collectives import _verify_lowered_program

    _, g, _ = build_mlp()
    low = copy.deepcopy(tiered_lowering(g))
    del low.entries[next(iter(low.entries))]
    cfg = ff.FFConfig()
    with pytest.raises(PlanAnalysisError, match="FFTA090"):
        _verify_lowered_program(cfg, g, low)
    cfg.plan_analysis = "warn"
    _verify_lowered_program(cfg, g, low)  # logs, no raise
    cfg.plan_analysis = "off"
    _verify_lowered_program(cfg, g, low)


def test_verify_candidates_flag_and_clean_search():
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.unity import unity_optimize

    config = ff.FFConfig()
    assert config.verify_candidates is False
    assert config.parse_args(["--verify-candidates"]) == []
    assert config.verify_candidates is True

    config.batch_size = 64
    config.search_budget = 2
    config.use_native_search = False
    _, g, _ = build_mlp()
    machine = make_machine_model(config, 4)
    result = unity_optimize(g, config, machine, 64, 4)
    # a clean graph loses no candidate to the verifier
    assert result.strategies
    report = analyze_plan(g, strategies=result.strategies,
                          machine=machine, config=config, batch_size=64,
                          n_devices=4, mesh_axes=result.mesh_axes)
    assert report.ok, report.format()


# ---------------------------------------------------------------------
# catalogue / docs drift guard
# ---------------------------------------------------------------------
def test_catalogue_docs_never_drift():
    """Both directions: every FFTA code referenced by the analysis
    sources or the docs exists in CODE_CATALOG, and every catalogued
    code is documented in docs/analysis.md and emitted/referenced
    somewhere in the analysis sources."""
    sources = ""
    for name in ("diagnostics.py", "passes.py", "interp.py"):
        with open(os.path.join(REPO, "flexflow_tpu", "analysis", name)) as f:
            sources += f.read()
    with open(os.path.join(REPO, "docs", "analysis.md")) as f:
        docs = f.read()
    catalog = set(CODE_CATALOG)
    in_sources = set(re.findall(r"FFTA\d{3}", sources))
    in_docs = set(re.findall(r"FFTA\d{3}", docs))
    assert in_sources <= catalog, sorted(in_sources - catalog)
    assert in_docs <= catalog, sorted(in_docs - catalog)
    assert catalog <= in_docs, sorted(catalog - in_docs)
    assert catalog <= in_sources, sorted(catalog - in_sources)
