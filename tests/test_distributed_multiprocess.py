"""REAL multi-process distributed test: two OS processes join the JAX
coordination service over localhost and train one dp-sharded step together
(reference analog: tests/multi_gpu_tests.sh with NUM_NODES>1 over mpirun —
the reference only exercises this on a real cluster in CI; here the
coordination service runs cross-process on one machine, exercising
runtime/distributed.py end to end)."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")

from flexflow_tpu.runtime import distributed

coord, pid = sys.argv[1], int(sys.argv[2])
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=pid)
info = distributed.host_info()
assert info["process_count"] == 2, info
assert info["global_devices"] == 4, info  # 2 hosts x 2 local CPU devices

# a global computation across both processes: psum over all 4 devices
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = distributed.pod_mesh({"data": 4})
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")),
    np.ones((2,), np.float32) * (pid + 1),  # host 0 holds [1,1], host 1 [2,2]
    (4,),
)
import numpy as np  # noqa: E402

@jax.jit
def total(x):
    return jnp.sum(x)

t = float(np.asarray(jax.device_get(total(arr))))
assert t == 6.0, t  # 1+1+2+2 summed across hosts
print(f"proc {pid} OK total={t}", flush=True)
distributed.shutdown()
"""


def test_two_process_coordination_service(tmp_path):
    # pick a free port for the coordinator
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    coord = f"127.0.0.1:{port}"

    script = tmp_path / "worker.py"
    script.write_text("import numpy as np\n" + WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(pid)],
            env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out[-2000:]
        assert any("proc 0 OK" in o for o in outs), outs
        assert any("proc 1 OK" in o for o in outs), outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
