"""REAL multi-process distributed tests: two OS processes join the JAX
coordination service over localhost and compute/train together (reference
analog: tests/multi_gpu_tests.sh with NUM_NODES>1 over mpirun — the
reference only exercises this on a real cluster in CI; here the
coordination service runs cross-process on one machine, exercising
runtime/distributed.py and Executor.shard_batch end to end)."""
import os
import socket
import subprocess
import sys

import pytest

from flexflow_tpu.runtime.distributed import cpu_collectives_supported

# targeted jaxlib-limitation gate: without a cross-process CPU collectives
# implementation (gloo) in the installed jaxlib, a two-process CPU run
# fails at the first jitted collective with "Multiprocess computations
# aren't implemented on the CPU backend". When gloo IS available,
# runtime/distributed.initialize() routes CPU collectives through it and
# these tests run for real.
pytestmark = pytest.mark.skipif(
    not cpu_collectives_supported(),
    reason="installed jaxlib ships no cross-process CPU collectives "
           "(gloo); multiprocess-on-CPU is a jaxlib limitation here")

WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from flexflow_tpu.runtime import distributed

coord, pid = sys.argv[1], int(sys.argv[2])
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=pid)
info = distributed.host_info()
assert info["process_count"] == 2, info
assert info["global_devices"] == 4, info  # 2 hosts x 2 local CPU devices

# a global computation across both processes: sum over all 4 devices
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = distributed.pod_mesh({"data": 4})
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")),
    np.ones((2,), np.float32) * (pid + 1),  # host 0 holds [1,1], host 1 [2,2]
    (4,),
)

@jax.jit
def total(x):
    return jnp.sum(x)

t = float(np.asarray(jax.device_get(total(arr))))
assert t == 6.0, t  # 1+1+2+2 summed across hosts
print(f"proc {pid} OK total={t}", flush=True)
distributed.shutdown()
"""

FIT_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from flexflow_tpu.runtime import distributed

coord, pid = sys.argv[1], int(sys.argv[2])
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=pid)

import flexflow_tpu as ff

config = ff.FFConfig()
config.batch_size = 8
config.allow_mixed_precision = False
model = ff.FFModel(config)
x = model.create_tensor([8, 16], ff.DataType.DT_FLOAT)
t = model.dense(x, 32, ff.ActiMode.AC_MODE_RELU)
model.softmax(model.dense(t, 4))
model.compile(optimizer=ff.AdamOptimizer(model, alpha=0.01),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[ff.MetricsType.METRICS_ACCURACY],
              parallel_axes={"data": 4})  # spans both processes

rng = np.random.RandomState(0)  # SAME global data on both hosts
X = rng.randn(64, 16).astype(np.float32)
Y = np.argmax(X @ rng.randn(16, 4), axis=1).astype(np.int32)[:, None]
losses = [model.fit(x=X, y=Y, epochs=1, verbose=False)[-1]["loss"]
          for _ in range(6)]
assert losses[-1] < losses[0], losses
print(f"proc {pid} FIT OK {losses[0]:.4f}->{losses[-1]:.4f}", flush=True)
distributed.shutdown()
"""


def _run_two_workers(tmp_path, script_text, marker, timeout=240):
    """Launch the same worker script as process 0 and 1 with a fresh
    coordinator port; assert both exit 0 and print `marker`."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    coord = f"127.0.0.1:{port}"

    script = tmp_path / "worker.py"
    script.write_text(script_text)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(pid)],
            env=env, cwd=root,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out[-2000:]
        for pid in (0, 1):
            assert any(f"proc {pid} {marker}" in o for o in outs), outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_two_process_coordination_service(tmp_path):
    _run_two_workers(tmp_path, WORKER, "OK")


def test_two_process_ffmodel_fit(tmp_path):
    """FFModel.fit trains with the data axis spanning TWO processes —
    shard_batch assembles per-host addressable shards (the MULTI-NODE.md
    launch contract, executed for real)."""
    _run_two_workers(tmp_path, FIT_WORKER, "FIT OK")
