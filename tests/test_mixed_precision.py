"""Mixed-precision (bf16 activation storage) numerics guard.

The exact-parity align tests run with allow_mixed_precision=False; this file
covers the DEFAULT path: bf16 activations at op boundaries
(ops/common.py emit_dtype, applied in runtime/executor.py) with f32
parameters, statistics, and losses. Training under the bf16 path must track
the f32 path closely — this is the regression guard for the precision
decisions in linear/conv epilogues, layernorm/batchnorm statistics, and the
attention core.
"""
import numpy as np

import flexflow_tpu as ff


def _train_losses(mixed: bool, steps: int = 8):
    config = ff.FFConfig()
    config.batch_size = 16
    config.allow_mixed_precision = mixed
    model = ff.FFModel(config)
    tokens = model.create_tensor([16, 32], ff.DataType.DT_INT32)
    t = model.embedding(tokens, 100, 64, ff.AggrMode.AGGR_MODE_NONE,
                        name="emb")
    attn = model.multihead_attention(t, t, t, 64, 4, name="attn")
    t = model.layer_norm(model.add(t, attn), [-1], name="ln1")
    h = model.dense(t, 128, ff.ActiMode.AC_MODE_GELU, name="ff1")
    h = model.dense(h, 64, name="ff2")
    t = model.layer_norm(model.add(t, h), [-1], name="ln2")
    model.softmax(model.dense(t, 4, name="cls"))
    model.compile(optimizer=ff.AdamOptimizer(model, alpha=1e-3),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[])
    rng = np.random.RandomState(0)
    x = rng.randint(0, 100, size=(16, 32)).astype(np.int32)
    y = (x[..., None] % 4).astype(np.int32)  # learnable token->class map
    losses = []
    for _ in range(steps):
        hist = model.fit([x], y, batch_size=16, epochs=1, verbose=False)
        losses.append(hist[-1]["loss"])
    return losses


def test_bf16_path_tracks_f32_losses():
    """Same seed, same data: the bf16-activation path's loss curve stays
    within a small relative band of exact f32 (both fall)."""
    f32 = _train_losses(mixed=False)
    bf16 = _train_losses(mixed=True)
    assert f32[-1] < f32[0] and bf16[-1] < bf16[0], (f32, bf16)
    for a, b in zip(f32, bf16):
        assert abs(a - b) / max(abs(a), 1e-6) < 0.05, (f32, bf16)


def test_bf16_activations_actually_bf16():
    """The executor's boundary cast is live: under mixed precision a dense
    output value traced through the PCG is bf16."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.ffconst import CompMode

    config = ff.FFConfig()
    config.batch_size = 4
    config.allow_mixed_precision = True
    model = ff.FFModel(config)
    x = model.create_tensor([4, 8], ff.DataType.DT_FLOAT)
    t = model.dense(x, 16, ff.ActiMode.AC_MODE_RELU, name="d1")
    model.softmax(model.dense(t, 2, name="d2"))
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.01),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[])

    seen = {}

    def probe(params, state, inputs):
        values, _, _ = model.executor.forward_values(
            params, state, inputs, jax.random.PRNGKey(0),
            CompMode.COMP_MODE_INFERENCE)
        for op in model.ops:
            for tt in op.outputs:
                seen[op.name] = values[tt.guid].dtype
        return 0

    inputs = {model.input_ops[0].name: jnp.zeros((4, 8), jnp.float32)}
    jax.eval_shape(probe, model.params, model.state, inputs)
    assert seen["d1"] == jnp.bfloat16, seen


def test_adam_bf16_moments_tracks_f32():
    """moments_dtype=bfloat16 (TPU bandwidth option) trains within a small
    band of the default f32-moments Adam."""
    import jax.numpy as jnp

    def losses(moments_dtype):
        config = ff.FFConfig()
        config.batch_size = 32
        model = ff.FFModel(config)
        x = model.create_tensor([32, 16], ff.DataType.DT_FLOAT)
        t = model.dense(x, 64, ff.ActiMode.AC_MODE_RELU)
        model.softmax(model.dense(t, 4))
        model.compile(
            optimizer=ff.AdamOptimizer(model, alpha=3e-3,
                                       moments_dtype=moments_dtype),
            loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
            metrics=[])
        rng = np.random.RandomState(0)
        X = rng.randn(256, 16).astype(np.float32)
        Y = np.argmax(X @ rng.randn(16, 4), axis=1).astype(np.int32)[:, None]
        out = []
        for _ in range(6):
            hist = model.fit(x=X, y=Y, epochs=1, verbose=False)
            out.append(hist[-1]["loss"])
        return out

    f32 = losses(None)
    b16 = losses(jnp.bfloat16)
    assert f32[-1] < f32[0] and b16[-1] < b16[0]
    assert abs(f32[-1] - b16[-1]) / abs(f32[-1]) < 0.1, (f32, b16)
