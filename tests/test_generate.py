"""KV-cache autoregressive generation (serving/generate.py): incremental
decoding must reproduce the naive recompute-everything loop."""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.ffconst import CompMode
from flexflow_tpu.serving.generate import GenerativeSession
from tests.conftest import module_xla_cache

# module-scoped XLA compilation cache — see conftest.module_xla_cache
_xla_cache = pytest.fixture(scope="module", autouse=True)(module_xla_cache)


def _build_lm(batch, window, vocab=50, hidden=32, heads=4, layers=2,
              use_flash=None):
    config = ff.FFConfig()
    config.batch_size = batch
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    tokens = model.create_tensor([batch, window], ff.DataType.DT_INT32)
    t = model.embedding(tokens, vocab, hidden, ff.AggrMode.AGGR_MODE_NONE,
                        name="emb")
    for i in range(layers):
        attn = model.multihead_attention(t, t, t, hidden, heads, causal=True,
                                         use_flash=use_flash,
                                         name=f"l{i}_attn")
        t = model.layer_norm(model.add(t, attn), [-1], name=f"l{i}_ln1")
        h = model.dense(t, hidden * 2, ff.ActiMode.AC_MODE_GELU,
                        name=f"l{i}_ff1")
        h = model.dense(h, hidden, name=f"l{i}_ff2")
        t = model.layer_norm(model.add(t, h), [-1], name=f"l{i}_ln2")
    model.softmax(model.dense(t, vocab, name="lm_head"))
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return model


def _naive_generate(model, prompt, n_new, window):
    """Recompute the full (causal) forward per step; greedy argmax."""
    b, plen = prompt.shape
    feeds_name = model.input_ops[0].name
    seq = list(prompt.T)  # list of (b,) columns
    out = []
    for _ in range(n_new):
        cur = len(seq)
        padded = np.zeros((b, window), np.int32)
        padded[:, :cur] = np.stack(seq, axis=1)
        values, _, _ = model.executor.forward_values(
            model.params, model.state, {feeds_name: padded}, None,
            CompMode.COMP_MODE_INFERENCE)
        probs = np.asarray(values[model.final_tensor.guid])
        tok = probs[:, cur - 1, :].argmax(-1).astype(np.int32)
        out.append(tok)
        seq.append(tok)
    return np.stack(out, axis=1)


def test_kv_cache_generate_matches_naive_loop():
    b, window, n_new = 2, 12, 5
    model = _build_lm(b, window)
    prompt = np.random.RandomState(0).randint(1, 50, size=(b, 4)).astype(np.int32)

    ref = _naive_generate(model, prompt, n_new, window)
    session = GenerativeSession(model, max_len=window)
    got = session.generate(prompt, n_new)
    np.testing.assert_array_equal(got, ref)


def test_chunked_decode_matches_per_step_loop():
    """tokens_per_dispatch > 1 (K decode steps per jitted scan dispatch)
    is token-identical to the per-step loop, including a ragged final
    chunk."""
    b, window, n_new = 2, 12, 5
    model = _build_lm(b, window)
    prompt = np.random.RandomState(2).randint(1, 50, size=(b, 4)).astype(np.int32)

    ref = GenerativeSession(model, max_len=window).generate(prompt, n_new)
    got = GenerativeSession(model, max_len=window).generate(
        prompt, n_new, tokens_per_dispatch=3)  # chunks of 3, 1 ragged
    np.testing.assert_array_equal(got, ref)


def test_chunked_decode_eos_stops_same_step():
    """With an eos_id, the chunked path stops emitting on the same step as
    the per-step loop (speculative in-flight compute is discarded).
    batch=1 so finished.all() genuinely fires, mid-chunk for K=4."""
    b, window, n_new = 1, 12, 8
    model = _build_lm(b, window)
    prompt = np.random.RandomState(3).randint(1, 50, size=(b, 4)).astype(np.int32)

    ref = GenerativeSession(model, max_len=window).generate(prompt, n_new)
    # synthetic EOS: the token the unchunked run emits at step 1, so the
    # stop lands mid-chunk for tokens_per_dispatch=4
    eos = int(ref[0, 1])
    ref_eos = GenerativeSession(model, max_len=window).generate(
        prompt, n_new, eos_id=eos)
    assert ref_eos.shape[1] < n_new, ref_eos  # the stop actually fired
    got_eos = GenerativeSession(model, max_len=window).generate(
        prompt, n_new, eos_id=eos, tokens_per_dispatch=4)
    np.testing.assert_array_equal(got_eos, ref_eos)


def test_sampled_decode_chunk_invariant():
    """temperature>0 sampling draws per-POSITION rng keys, so the same
    seed yields identical tokens at any tokens_per_dispatch — and
    different seeds yield different sequences."""
    b, window, n_new = 2, 12, 6
    model = _build_lm(b, window)
    prompt = np.random.RandomState(6).randint(1, 50, size=(b, 4)).astype(np.int32)

    kw = dict(temperature=1.0, top_k=10, seed=42)
    ref = GenerativeSession(model, max_len=window).generate(
        prompt, n_new, **kw)
    got = GenerativeSession(model, max_len=window).generate(
        prompt, n_new, tokens_per_dispatch=4, **kw)
    np.testing.assert_array_equal(got, ref)
    other = GenerativeSession(model, max_len=window).generate(
        prompt, n_new, temperature=1.0, top_k=10, seed=43)
    assert not np.array_equal(other, ref)
    # temperature=0 stays exactly the greedy path
    greedy = GenerativeSession(model, max_len=window).generate(prompt, n_new)
    greedy0 = GenerativeSession(model, max_len=window).generate(
        prompt, n_new, temperature=0.0, seed=7)
    np.testing.assert_array_equal(greedy0, greedy)


def test_partial_batch_prompts_pad_and_slice():
    """Fewer prompts than the compiled batch: the session pads by tiling
    (rows decode independently) and returns only the real rows — exact
    match with the corresponding rows of a full-batch run. Oversize and
    malformed prompts raise ValueError."""
    b, window, n_new = 2, 12, 5
    model = _build_lm(b, window)
    prompt = np.random.RandomState(8).randint(1, 50, size=(b, 4)).astype(np.int32)

    full = GenerativeSession(model, max_len=window).generate(prompt, n_new)
    one = GenerativeSession(model, max_len=window).generate(
        prompt[:1], n_new, tokens_per_dispatch=3)
    assert one.shape == (1, n_new)
    np.testing.assert_array_equal(one, full[:1])

    s = GenerativeSession(model, max_len=window)
    import pytest

    with pytest.raises(ValueError, match="exceed the session batch"):
        s.generate(np.zeros((3, 4), np.int32), n_new)
    with pytest.raises(ValueError, match="non-empty"):
        s.generate(np.zeros((4,), np.int32), n_new)
    with pytest.raises(ValueError, match="prefill window"):
        s.generate(np.zeros((2, window + 1), np.int32), 1)


def test_generate_zero_tokens_returns_empty():
    """max_new_tokens=0: both paths return an empty (b, 0) array."""
    b, window = 2, 12
    model = _build_lm(b, window)
    prompt = np.random.RandomState(5).randint(1, 50, size=(b, 4)).astype(np.int32)
    for k in (1, 4):
        got = GenerativeSession(model, max_len=window).generate(
            prompt, 0, tokens_per_dispatch=k)
        assert got.shape == (b, 0), got.shape


def test_kv_cache_generate_flash_prefill_matches_naive_loop():
    """use_flash=True prefill: the packed kernel fills the KV cache (its
    [b,l,h,d] view is a reshape of the packed projections) and decode steps
    attend against it — same tokens as the naive full-recompute loop."""
    b, window, n_new = 2, 12, 5
    model = _build_lm(b, window, use_flash=True)
    prompt = np.random.RandomState(4).randint(1, 50, size=(b, 4)).astype(np.int32)

    ref = _naive_generate(model, prompt, n_new, window)
    session = GenerativeSession(model, max_len=window)
    got = session.generate(prompt, n_new)
    np.testing.assert_array_equal(got, ref)


def test_generate_eos_early_stop():
    b, window = 1, 12  # single row: eos must genuinely stop the loop
    model = _build_lm(b, window)
    prompt = np.random.RandomState(1).randint(1, 50, size=(b, 3)).astype(np.int32)
    session = GenerativeSession(model, max_len=window)
    first = session.generate(prompt, 6)
    eos = int(first[0, 1])  # force an early stop at the 2nd generated token
    got = session.generate(prompt, 6, eos_id=eos)
    # the stop lands AT the first occurrence of the eos token — computed,
    # not assumed at index 1, because the greedy sequence may repeat a
    # token (first[0, 0] == first[0, 1] on some backends/versions)
    want = int(np.argmax(first[0] == eos)) + 1
    assert got.shape[1] == want, got
    np.testing.assert_array_equal(got[0], first[0, :want])
