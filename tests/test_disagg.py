"""Disaggregated prefill/decode serving (ISSUE 20): phase-specialized
replica roles and the priced KV-page handoff plane.

The decisive properties:
 - a request routed to a `role="prefill"` replica parks after its first
   token, ships its finished KV pages to a decode replica as a priced,
   FFTA06x-gated TRANSFER schedule, and finishes TOKEN-IDENTICAL to
   unified serving with zero recompute;
 - every failure mode (no decode pool, direct submit with no fleet
   handle, coordinator stopped) degrades to local decode — zero drops;
 - pool export/import is geometry-checked (`KVGeometryMismatch`, typed)
   and conserves fleet-wide page accounting;
 - pricing rides the hierarchical machine model: a decode pool on the
   other pod pays the DCN hop, not the innermost p2p link, and
   cross-tier shipments honor the 64 MB chunk cap;
 - `predicted_ttft_s` is role-aware: materialized-KV requests admit on
   the decode legs only, prefill replicas charge no decode leg;
 - role-scoped autoscalers size the two pools independently;
 - a repository entry with `"mode": "disagg"` builds the whole thing.
"""
import math
import os
import time
import types

import numpy as np
import pytest

from flexflow_tpu.obs.registry import MetricsRegistry, validate_exposition
from flexflow_tpu.obs.tracing import get_tracer
from flexflow_tpu.resharding.cost import schedule_cost_us
from flexflow_tpu.resharding.plan import (TRANSFER_TIER_CHUNK_BYTES,
                                          plan_slot_migration)
from flexflow_tpu.search.machine_model import (HierarchicalMachineModel,
                                               load_machine_spec)
from flexflow_tpu.serving.fleet import (Autoscaler, DisaggCoordinator,
                                        Replica, Router)
from flexflow_tpu.serving.sched.kvpool import (KVGeometryMismatch,
                                               PagedKVPool)
from tests.conftest import module_xla_cache
from tests.test_generate import _build_lm

# module-scoped XLA compilation cache — see conftest.module_xla_cache
_xla_cache = pytest.fixture(scope="module", autouse=True)(module_xla_cache)

SPEC_PATH = os.path.join(os.path.dirname(__file__), "..", "examples",
                         "machines", "multipod_2x8.json")


@pytest.fixture(scope="module")
def lm():
    return _build_lm(2, 12)


def _mk_replica(lm, name, role, slots=2, max_len=48):
    return Replica(name, lm, max_len=max_len, num_slots=slots,
                   page_size=4, role=role)


def _prompt(n, seed=0, vocab=50):
    rng = np.random.RandomState(seed)
    return rng.randint(1, vocab, size=(n,)).astype(np.int32)


def _await(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pred()


# ---------------------------------------------------------------------
# the tentpole: token-exact priced handoff under one trace
# ---------------------------------------------------------------------
def test_disagg_token_parity_priced_handoff_and_trace(lm):
    prompts = [_prompt(9, seed=i) for i in (1, 2, 3)]
    ref = Replica("u0", lm, max_len=48, num_slots=2, page_size=4)
    try:
        want = [list(ref.submit(p, 5, seed=7 + i).result(timeout=300))
                for i, p in enumerate(prompts)]
    finally:
        ref.stop()

    machine = HierarchicalMachineModel.from_json(
        load_machine_spec(SPEC_PATH))
    router = Router(policy="least_loaded")
    router.add_replica("p0", _mk_replica(lm, "p0", "prefill"))
    router.add_replica("d0", _mk_replica(lm, "d0", "decode"))
    coord = DisaggCoordinator(router, machine=machine,
                              device_ids=tuple(range(machine.num_chips)))
    coord.attach_all()
    tracer = get_tracer()
    tracer.clear()
    tracer.enable()
    try:
        frs = []
        for i, p in enumerate(prompts):
            fr = router.submit(p, 5, seed=7 + i)
            # sequential: each handoff sees an empty decode pool, so
            # every request MUST ship (no admission-shed nondeterminism)
            fr.result(timeout=300)
            frs.append(fr)
        got = [list(fr.result(timeout=300)) for fr in frs]
        assert got == want  # token-identical to unified serving
        assert all(fr.handoffs >= 1 for fr in frs)
        _await(lambda: coord.committed >= len(prompts))
        assert coord.failed == 0
        # priced on the hierarchical machine: the two pools span the
        # 16-chip multipod, so the shipment pays the DCN tier
        assert (coord.last_predicted_us or 0.0) > 0.0
        assert coord.predicted_handoff_s(64) > 0.0
        assert coord.stats()["us_per_byte"] > 0.0
        # every handoff span carries the request's ORIGINAL trace id
        stitched = {e["args"].get("trace_id")
                    for e in tracer.events("fleet.kv_handoff")}
        assert all(fr.trace_id in stitched for fr in frs)
        # the ff_disagg_* families render as one valid exposition
        fams = validate_exposition(router.registry.render())
        for f in ("ff_disagg_handoffs_total",
                  "ff_disagg_handoff_bytes_total",
                  "ff_disagg_handoff_chunks_total", "ff_disagg_handoff_ms",
                  "ff_disagg_predicted_transfer_us",
                  "ff_disagg_queue_depth"):
            assert f in fams, f
    finally:
        tracer.disable()
        coord.stop()
        router.shutdown()


def test_no_decode_pool_degrades_to_local_decode(lm):
    router = Router()
    router.add_replica("p0", _mk_replica(lm, "p0", "prefill"))
    coord = DisaggCoordinator(router)
    coord.attach_all()
    try:
        fr = router.submit(_prompt(9, seed=4), 4, seed=3)
        out = fr.result(timeout=300)
        assert len(out) == 4
        assert fr.handoffs == 0  # the handle never rebound
        assert coord.resumed >= 1 and coord.committed == 0
        assert "no READY decode replica" in (coord.last_error or "")
    finally:
        coord.stop()
        router.shutdown()


def test_direct_submit_without_fleet_handle_resumes(lm):
    """A submit that bypassed the router (warmup traffic) has no
    FleetRequest to rebind — the coordinator must decode it locally
    instead of orphaning the caller's stream."""
    router = Router()
    p0 = _mk_replica(lm, "p0", "prefill")
    router.add_replica("p0", p0)
    router.add_replica("d0", _mk_replica(lm, "d0", "decode"))
    coord = DisaggCoordinator(router)
    coord.attach_all()
    try:
        h = p0.submit(_prompt(9, seed=5), 4, seed=1)
        out = h.result(timeout=300)
        assert len(out) == 4
        assert coord.resumed >= 1 and coord.committed == 0
    finally:
        coord.stop()
        router.shutdown()


def test_coordinator_guards(lm):
    router = Router()
    router.add_replica("d0", _mk_replica(lm, "d0", "decode"))
    try:
        coord = DisaggCoordinator(router, start=False)
        # only prefill replicas park — wiring a decode replica is a bug
        with pytest.raises(ValueError, match="prefill"):
            coord.wire(router.replica("d0"))
        # a stopped coordinator rejects enqueues so the batcher's
        # on_parked falls straight back to local decode
        with pytest.raises(RuntimeError, match="stopped"):
            coord.enqueue("d0", object())
        coord.stop()  # idempotent on a never-started coordinator
    finally:
        router.shutdown()


# ---------------------------------------------------------------------
# satellite: pool export/import symmetry + typed geometry errors
# ---------------------------------------------------------------------
def test_kvpool_export_import_symmetry_and_geometry():
    src = PagedKVPool(2, 32, page_size=4)
    dst = PagedKVPool(2, 32, page_size=4)
    src.alloc("a", 10)
    desc = src.export_sequence("a")
    assert desc["n_tokens"] == 10
    assert desc["n_pages"] == len(src.pages_of("a"))
    slot = dst.import_sequence(desc)
    # symmetric accounting: the importer claims exactly the pages the
    # exporter reported, so fleet-wide pages_used is conserved once the
    # source frees
    assert dst.pages_used() == desc["n_pages"]
    assert dst.slot_of("a") == slot
    assert src.pages_used() == desc["n_pages"]  # exporter untouched
    src.free("a")
    assert src.pages_used() == 0
    # geometry mismatches are typed and non-retryable
    with pytest.raises(KVGeometryMismatch, match="page_size"):
        PagedKVPool(2, 32, page_size=8).import_sequence(desc)
    with pytest.raises(KVGeometryMismatch, match="max_len"):
        PagedKVPool(2, 8, page_size=4).import_sequence(desc)
    lying = dict(desc, n_pages=desc["n_pages"] + 1)
    pool = PagedKVPool(2, 32, page_size=4)
    with pytest.raises(KVGeometryMismatch, match="n_pages"):
        pool.import_sequence(lying)
    assert pool.pages_used() == 0  # the refused import undid its alloc
    with pytest.raises(KeyError):
        src.export_sequence("missing")


# ---------------------------------------------------------------------
# satellite: cross-pool pricing on a tiered machine
# ---------------------------------------------------------------------
def _fake_rep(num_slots, max_len):
    pool = types.SimpleNamespace(num_slots=num_slots, max_len=max_len)
    return types.SimpleNamespace(batcher=types.SimpleNamespace(pool=pool))


def test_cross_pool_pricing_over_dcn_and_chunk_cap():
    machine = HierarchicalMachineModel.from_json(
        load_machine_spec(SPEC_PATH))
    kv_shapes = {f"kv/l{i}_attn/{p}": ((4, 256, 4, 8), 4)
                 for i in range(2) for p in ("k_cache", "v_cache")}
    cross = plan_slot_migration(kv_shapes, 4, 4, 128,
                                device_ids=tuple(range(16)))
    inner = plan_slot_migration(kv_shapes, 4, 4, 128,
                                device_ids=tuple(range(8)))
    cost_cross = schedule_cost_us(cross, machine)
    cost_inner = schedule_cost_us(inner, machine)
    # a decode pool on the other pod prices over DCN (3.125 GB/s +
    # latency), not the innermost p2p links (2x45 GB/s)
    assert cost_cross > cost_inner > 0.0

    rows = {f"l{i}/k": np.zeros((2048, 64, 64), np.float32)
            for i in range(3)}  # ~100 MB total
    total = sum(r.nbytes for r in rows.values())
    assert total > TRANSFER_TIER_CHUNK_BYTES

    coord = DisaggCoordinator(
        types.SimpleNamespace(), machine=machine,
        device_ids=tuple(range(16)), registry=MetricsRegistry(),
        start=False)
    priced = coord.price_transfer(_fake_rep(4, 4096), _fake_rep(4, 4096),
                                  2048, rows)
    assert priced["cross_tier"] and priced["bytes"] == total
    assert priced["chunks"] \
        == math.ceil(total / TRANSFER_TIER_CHUNK_BYTES) == 2
    assert priced["predicted_us"] > 0.0
    # pools within one pod: no tier crossing, a single chunk, cheaper
    coord_in = DisaggCoordinator(
        types.SimpleNamespace(), machine=machine,
        device_ids=tuple(range(8)), registry=MetricsRegistry(),
        start=False)
    p2 = coord_in.price_transfer(_fake_rep(4, 4096), _fake_rep(4, 4096),
                                 2048, rows)
    assert not p2["cross_tier"] and p2["chunks"] == 1
    assert p2["predicted_us"] < priced["predicted_us"]


# ---------------------------------------------------------------------
# satellite: role-aware predicted TTFT
# ---------------------------------------------------------------------
def test_predicted_ttft_materialized_kv_and_prefill_role(lm):
    from flexflow_tpu.serving.sched import ContinuousBatcher

    # never started: predicted_ttft_s is a pure read of the rate model
    b = ContinuousBatcher(lm, max_len=48, num_slots=2, page_size=4,
                          prefill_chunk_tokens=8)
    b._ewma_prefill_s_per_tok = 0.001
    b._ewma_decode_iter_s = 0.005
    full = b.predicted_ttft_s(100)
    assert full >= 100 * 0.001
    # KV already materialized (whole-prompt prefix hit or a disagg
    # import): admitted on the decode legs only — one decode wall, no
    # prefill-EWMA charge
    assert b.predicted_ttft_s(100, shared_tokens=100) \
        == pytest.approx(0.005)
    # a queued prefill ahead still charges its backlog, never the
    # request's own (absent) prefill
    b._queue.append(types.SimpleNamespace(
        prompt=np.zeros(8, np.int32)))
    assert b.predicted_ttft_s(100, shared_tokens=100) < full

    # a prefill replica charges NO decode-interleave leg: nothing
    # decodes there (parked requests hold pages, not iterations)
    bp = ContinuousBatcher(lm, max_len=48, num_slots=2, page_size=4,
                           prefill_chunk_tokens=8, role="prefill")
    bp._ewma_prefill_s_per_tok = 0.001
    bp._ewma_decode_iter_s = 0.005
    bp._queue.append(types.SimpleNamespace(
        prompt=np.zeros(8, np.int32)))
    assert bp.predicted_ttft_s(16) == pytest.approx((16 + 8) * 0.001)


# ---------------------------------------------------------------------
# satellite: role-scoped autoscalers size the pools independently
# ---------------------------------------------------------------------
def test_autoscaler_role_scoped_pools(lm):
    router = Router()
    router.add_replica("p0", _mk_replica(lm, "p0", "prefill"))
    router.add_replica("d0", _mk_replica(lm, "d0", "decode"))
    try:
        with pytest.raises(ValueError, match="role"):
            Autoscaler(router, role="bogus")
        pre = Autoscaler(router, role="prefill", min_slots=2, max_slots=2,
                         min_replicas=1, idle_ticks_before_drain=1)
        dec = Autoscaler(router, role="decode", min_slots=2, max_slots=2,
                         min_replicas=1, idle_ticks_before_drain=1)
        # max_replicas/min_replicas bound each POOL, not the fleet
        assert pre._pool_size() == 1 and dec._pool_size() == 1
        assert Autoscaler(router)._pool_size() == 2
        # each scaler sees exactly its own pool: with min_replicas=1 and
        # the whole fleet idle, an UNSCOPED scaler would drain a surplus
        # replica — the scoped ones each see a pool already at minimum
        for _ in range(3):
            pre.tick()
            dec.tick()
        assert set(router.replica_names()) == {"p0", "d0"}
        assert not [a for a in pre.log + dec.log
                    if a.get("action") == "drain_replica"]
    finally:
        router.shutdown()


# ---------------------------------------------------------------------
# satellite: repository entry wiring
# ---------------------------------------------------------------------
def test_repository_disagg_entry(lm, tmp_path):
    from flexflow_tpu.serving import InferenceServer
    from flexflow_tpu.serving.repository import ModelRepository

    server = InferenceServer()
    try:
        ModelRepository._register_disagg(
            server, "lm", lm,
            {"mode": "disagg", "max_len": 48, "num_slots": 2,
             "page_size": 4, "prefill_replicas": 1, "decode_replicas": 1,
             "machine_spec": os.path.abspath(SPEC_PATH)},
            model_dir=str(tmp_path))
        router = server._fleets["lm"]
        assert set(router.replica_names()) == {"prefill0", "decode0"}
        assert router.replica("prefill0").role == "prefill"
        assert router.replica("decode0").role == "decode"
        assert router.disagg is not None  # shutdown() drains it first
        out = server.generate("lm", [[1, 2, 3, 4, 5, 6]], 3)
        assert [len(t) for t in out] == [3]
        _await(lambda: router.disagg.committed >= 1)
        assert (router.disagg.last_predicted_us or 0.0) > 0.0
        # speculative decoding cannot ride a prefill-only replica
        with pytest.raises(ValueError, match="speculative"):
            ModelRepository._register_disagg(
                server, "lm2", lm,
                {"mode": "disagg", "max_len": 48,
                 "speculative": {"draft": "d", "tokens": 2}},
                model_dir=str(tmp_path))
        with pytest.raises(ValueError, match="max_len"):
            ModelRepository._register_disagg(
                server, "lm3", lm, {"mode": "disagg"},
                model_dir=str(tmp_path))
    finally:
        server.shutdown()


# ---------------------------------------------------------------------
# heavier end-to-end: concurrent mixed pools (slow)
# ---------------------------------------------------------------------
@pytest.mark.slow
def test_disagg_concurrent_fleet_zero_drop_parity():
    lm4 = _build_lm(4, 12)
    prompts = [_prompt(10, seed=20 + i) for i in range(6)]
    ref = Replica("u0", lm4, max_len=48, num_slots=4, page_size=4)
    try:
        want = [list(ref.submit(p, 4, seed=i).result(timeout=300))
                for i, p in enumerate(prompts)]
    finally:
        ref.stop()

    router = Router(policy="least_loaded")
    for n in ("p0", "p1"):
        router.add_replica(
            n, _mk_replica(lm4, n, "prefill", slots=4))
    router.add_replica("d0", _mk_replica(lm4, "d0", "decode", slots=4))
    coord = DisaggCoordinator(router)
    coord.attach_all()
    try:
        frs = [router.submit(p, 4, seed=i)
               for i, p in enumerate(prompts)]
        got = [list(fr.result(timeout=300)) for fr in frs]
        # zero drop AND token parity no matter which path each request
        # took (committed handoff or resumed local decode under load)
        assert got == want
        _await(lambda: coord.committed + coord.resumed >= len(prompts))
        assert coord.failed == 0
        assert coord.committed >= 1
    finally:
        coord.stop()
        router.shutdown()
