"""Hierarchical machine model (docs/machine.md): tier-aware collective
pricing, per-tier reduction synthesis, one-tier degeneracy vs the flat
TpuPodModel, fitted-profile overlay round-trips, the FFTA07x cross-tier
legality family, and the --kernel-residual-threshold satellite."""
import dataclasses
import json

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.analysis import analyze_plan
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.obs.refit import FittedCoefficients, FittedProfile, refit
from flexflow_tpu.search.machine_model import (CHIP_SPECS,
                                               HierarchicalMachineModel,
                                               TierSpec, TpuPodModel,
                                               make_machine_model)
from flexflow_tpu.search.simulator import CostModel, OpStrategy, Simulator
from flexflow_tpu.search.unity import export_strategy, unity_optimize

CHIP = CHIP_SPECS["tpu-v5e"]


def multipod(ici=8, pods=2, dcn_gbps=3.125, dcn_latency=10.0):
    """ici-chips-per-pod x pods with a DCN tier ~14x slower than ICI."""
    return HierarchicalMachineModel(
        [TierSpec("ici", ici, CHIP.ici_link_gbps, 2),
         TierSpec("dcn", pods, dcn_gbps, 1, dcn_latency)], CHIP)


def one_tier(n=8):
    return HierarchicalMachineModel(
        [TierSpec("ici", n, CHIP.ici_link_gbps, 2)], CHIP)


def mlp_model(cfg, layers=3, width=512):
    m = ff.FFModel(cfg)
    t = m.create_tensor([cfg.batch_size, width])
    for i in range(layers):
        t = m.dense(t, width, ff.ActiMode.AC_MODE_RELU, name=f"fc{i}")
    m.softmax(m.dense(t, 10, name="head"))
    return m


# -- spec parsing -----------------------------------------------------------

def test_from_json_parses_tiers(tmp_path):
    spec = {"chip": "tpu-v5e",
            "tiers": [{"name": "ici", "degree": 4, "gbps": 45.0},
                      {"name": "dcn", "degree": 2, "gbps": 3.125,
                       "links": 1, "latency_us": 10.0}]}
    p = tmp_path / "m.json"
    p.write_text(json.dumps(spec))
    m = HierarchicalMachineModel.from_json(str(p))
    assert m.num_chips == 8
    assert [t.name for t in m.tiers] == ["ici", "dcn"]
    assert m.tiers[0].links == 2 and m.tiers[1].links == 1
    assert m.tiers[1].latency_us == 10.0


def test_from_json_rejects_bad_specs():
    with pytest.raises(ValueError, match="tiers"):
        HierarchicalMachineModel.from_json({"tiers": []})
    with pytest.raises(ValueError, match="bad tier entry"):
        HierarchicalMachineModel.from_json(
            {"tiers": [{"name": "x", "gbps": 1.0}]})  # no degree
    with pytest.raises(ValueError, match="unique"):
        HierarchicalMachineModel.from_json(
            {"tiers": [{"name": "a", "degree": 2, "gbps": 1.0},
                       {"name": "a", "degree": 2, "gbps": 1.0}]})
    with pytest.raises(ValueError, match="num_chips"):
        HierarchicalMachineModel.from_json(
            {"num_chips": 99,
             "tiers": [{"name": "a", "degree": 2, "gbps": 1.0}]})


def test_make_machine_model_dispatches_on_tiers(tmp_path):
    hier = tmp_path / "hier.json"
    hier.write_text(json.dumps(
        {"tiers": [{"name": "ici", "degree": 8, "gbps": 45.0}]}))
    cfg = ff.FFConfig()
    cfg.machine_model_file = str(hier)
    assert isinstance(make_machine_model(cfg, 8), HierarchicalMachineModel)
    net = tmp_path / "net.json"
    net.write_text(json.dumps({"num_chips": 4, "links": [[0, 1, 45.0]]}))
    cfg.machine_model_file = str(net)
    assert not hasattr(make_machine_model(cfg, 4), "tier_path")


def test_machine_spec_flag_is_an_alias():
    cfg = ff.FFConfig()
    rest = cfg.parse_args(["--machine-spec", "some/spec.json"])
    assert rest == [] and cfg.machine_model_file == "some/spec.json"


# -- tier geometry ----------------------------------------------------------

def test_tier_path_respects_inner_nesting():
    m = multipod()
    assert [(t.name, n) for t, n in m.tier_path(8)] == [("ici", 8)]
    # a degree-2 axis nested OUTSIDE the 8 in-pod devices rides the DCN
    assert [(t.name, n) for t, n in m.tier_path(2, inner=8)] == [("dcn", 2)]
    assert [(t.name, n) for t, n in m.tier_path(16)] == [("ici", 8),
                                                         ("dcn", 2)]
    assert not m.crosses_tier_boundary(8)
    assert m.crosses_tier_boundary(2, inner=8)
    # non-dividing groups round up into the next tier (conservative)
    assert [(t.name, n) for t, n in m.tier_path(12)] == [("ici", 8),
                                                         ("dcn", 2)]


# -- pricing ----------------------------------------------------------------

def test_reduction_strategy_tradeoffs():
    m = multipod()
    big = 64e6
    flat = m.allreduce_time_us(big, 16, strategy="flat")
    rs = m.allreduce_time_us(big, 16, strategy="rs_ar_ag")
    ring = m.allreduce_time_us(big, 16, strategy="hier_ring")
    # big tensors: phase overhead is noise, DCN bytes dominate
    assert rs < ring < flat
    assert m.allreduce_time_us(big, 16) == rs  # auto picks the winner
    # tiny tensors: per-phase latency dominates, the 3-phase rs_ar_ag loses
    tiny = 1e3
    assert (m.allreduce_time_us(tiny, 16, strategy="hier_ring")
            < m.allreduce_time_us(tiny, 16, strategy="rs_ar_ag"))
    # auto never picks flat across a boundary (FFTA070 legality), even
    # where flat would be cheapest
    strat, _, tiers = m.reduction_choice(tiny, 16)
    assert strat in ("rs_ar_ag", "hier_ring")
    assert [d["tier"] for d in tiers] == ["ici", "dcn"]
    # inside one pod the only (and legal) choice is flat
    strat, t, tiers = m.reduction_choice(big, 8)
    assert strat == "flat" and len(tiers) == 1
    assert t == m.allreduce_time_us(big, 8)


def test_unknown_strategy_rejected():
    with pytest.raises(ValueError, match="reduction strategy"):
        multipod().allreduce_time_us(1e6, 16, strategy="donut")


def test_collectives_price_dcn_when_crossed():
    m = multipod()
    b = 8e6
    # the same degree is far cheaper while it stays inside the pod
    assert m.allgather_time_us(b, 2, inner=8) > 5 * m.allgather_time_us(b, 2)
    assert (m.reduce_scatter_time_us(b, 2, inner=8)
            > 5 * m.reduce_scatter_time_us(b, 2))
    assert (m.all_to_all_time_us(b, 2, inner=8)
            > 5 * m.all_to_all_time_us(b, 2))
    # tiered allgather beats the flat-bottleneck ring when spanning both
    flat_ag = (16 - 1) * b / m.tier_bw(m.tiers[1]) * 1e6
    assert m.allgather_time_us(b, 16) < flat_ag
    # a ring hop advances at the slowest link the ring crosses: an
    # in-pod seq ring rotates at ICI speed, a cross-pod one at DCN speed
    assert m.ring_hop_time_us(b, 16) > 5 * m.ring_hop_time_us(b, 8)


def test_dcn_step_bytes_by_strategy():
    m = multipod()
    b = 256e3
    assert m.dcn_step_bytes(b, 8) == 0.0  # in-pod: never leaves ICI
    flat = m.dcn_step_bytes(b, 16, strategy="flat")
    rs = m.dcn_step_bytes(b, 16, strategy="rs_ar_ag")
    assert flat == pytest.approx(2 * (1 / 2) * b)
    assert rs == pytest.approx(flat / 8)  # only the 1/8 shard crosses
    # a group living entirely ON the dcn tier (dp=2, one member per
    # pod) rings its full bytes there — not zero
    assert m.dcn_step_bytes(b, 2, inner=8) == pytest.approx(b)


# -- one-tier degeneracy (satellite: bit-for-bit vs TpuPodModel) ------------

@pytest.mark.parametrize("n", [2, 3, 4, 8])
@pytest.mark.parametrize("bytes_", [1e3, 1e6, 1e9])
def test_one_tier_prices_identical_to_flat_pod(n, bytes_):
    one, pod = one_tier(8), TpuPodModel(8, CHIP)
    assert one.allreduce_time_us(bytes_, n) == pod.allreduce_time_us(bytes_, n)
    assert one.allgather_time_us(bytes_, n) == pod.allgather_time_us(bytes_, n)
    assert (one.reduce_scatter_time_us(bytes_, n)
            == pod.reduce_scatter_time_us(bytes_, n))
    assert (one.all_to_all_time_us(bytes_, n)
            == pod.all_to_all_time_us(bytes_, n))
    assert one.p2p_time_us(bytes_) == pod.p2p_time_us(bytes_)
    assert (one.ring_hop_time_us(bytes_, n)
            == pod.p2p_single_path_time_us(bytes_))
    assert one.compute_time_us(1e9, bytes_) == pod.compute_time_us(1e9, bytes_)
    assert one.memory_budget_bytes() == pod.memory_budget_bytes()


def test_one_tier_degeneracy_survives_an_overlay():
    coeffs = FittedCoefficients(
        compute_scale={"bf16": 0.5, "f32": 0.7}, hbm_scale=0.9,
        link_bw_scale=0.25, dispatch_latency_us=2.5,
        collective_latency_us=3.0, step_scale=1.2)
    one, pod = one_tier(8), TpuPodModel(8, CHIP)
    one.apply_overlay(coeffs)
    pod.apply_overlay(coeffs)
    for n in (2, 4, 8):
        assert one.allreduce_time_us(1e6, n) == pod.allreduce_time_us(1e6, n)
        assert one.allgather_time_us(1e6, n) == pod.allgather_time_us(1e6, n)
    assert one.p2p_time_us(1e6) == pod.p2p_time_us(1e6)
    assert one.compute_time_us(1e9, 1e6, 2) == pod.compute_time_us(1e9, 1e6, 2)


def test_one_tier_searched_plan_matches_flat_pod_bit_for_bit():
    def search(machine):
        cfg = ff.FFConfig()
        cfg.num_devices = 8
        cfg.batch_size = 32
        cfg.search_budget = 6
        cfg.use_native_search = False
        model = mlp_model(cfg)
        return unity_optimize(Graph(model.ops), cfg, machine, 32, 8)

    r_one = search(one_tier(8))
    r_pod = search(TpuPodModel(8, CHIP))
    assert r_one.cost_us == r_pod.cost_us
    assert r_one.memory_bytes == r_pod.memory_bytes
    assert r_one.mesh_axes == r_pod.mesh_axes
    by_name_one = {s for s in r_one.strategies.values()}
    by_name_pod = {s for s in r_pod.strategies.values()}
    assert by_name_one == by_name_pod
    # one-tier: every synthesized reduction is flat, single-tier
    assert all(v["strategy"] == "flat" and len(v["tiers"]) == 1
               for v in r_one.reduction_strategies.values())
    assert r_pod.reduction_strategies == {}


# -- overlay: per-tier fitted scales ----------------------------------------

def test_apply_overlay_per_tier_scales_with_global_fallback():
    m = multipod()
    base_ici = m.allreduce_time_us(1e6, 8)
    base_dcn = m.allreduce_time_us(1e6, 2, inner=8)
    coeffs = FittedCoefficients(link_bw_scale=0.5,
                                tier_link_scales={"dcn": 0.25})
    m.apply_overlay(coeffs)
    # dcn keyed explicitly; ici falls back to the global link scale
    assert m.tier_scales == {"ici": 0.5, "dcn": 0.25}
    lat = m.tier_latency(m.tiers[0])
    assert (m.allreduce_time_us(1e6, 8) - lat
            == pytest.approx((base_ici - lat) / 0.5))
    lat_d = m.tier_latency(m.tiers[1])
    assert (m.allreduce_time_us(1e6, 2, inner=8) - lat_d
            == pytest.approx((base_dcn - lat_d) / 0.25))


def test_fitted_profile_round_trips_tier_scales(tmp_path):
    coeffs = FittedCoefficients(tier_link_scales={"ici": 0.8, "dcn": 0.1})
    prof = FittedProfile(chip="tpu-v5e", backend="cpu", coefficients=coeffs)
    path = str(tmp_path / "prof.json")
    prof.save(path)
    loaded = FittedProfile.load(path, expect_chip="tpu-v5e",
                                expect_backend="cpu")
    assert loaded.coefficients.tier_link_scales == {"ici": 0.8, "dcn": 0.1}


def test_old_profiles_without_tier_scales_still_load(tmp_path):
    prof = FittedProfile(chip="tpu-v5e", backend="cpu",
                         coefficients=FittedCoefficients())
    d = prof.to_dict()
    del d["coefficients"]["tier_link_scales"]  # pre-PR-10 profile format
    path = tmp_path / "old.json"
    path.write_text(json.dumps(d))
    loaded = FittedProfile.load(str(path), expect_chip="tpu-v5e",
                                expect_backend="cpu")
    assert loaded.coefficients.tier_link_scales == {}
    multipod().apply_overlay(loaded.coefficients)  # applies cleanly


# -- simulator: degrees price against the tiers they cross ------------------

def _weighted_op(cfg):
    model = mlp_model(cfg, layers=1, width=1024)
    graph = Graph(model.ops)
    op = next(o for o in graph.ops.values() if o.name == "fc0")
    return graph, op


def test_grad_sync_prices_the_tiers_the_dp_axis_crosses():
    cfg = ff.FFConfig()
    cfg.num_devices = 16
    cfg.batch_size = 64
    cost = CostModel(multipod(), cfg)
    _, op = _weighted_op(cfg)
    s = OpStrategy(dp=2)
    inside = cost.grad_sync_time_us(op, s)  # 2 adjacent chips: ICI
    # the SAME op strategy under a tp=8 mesh: its dp groups stride by 8,
    # i.e. one member per pod — the sync rides the DCN and gets pricier
    # even though the bytes are identical (the stride is a property of
    # the realized MESH, not of this op's own degrees)
    cost.set_mesh_degrees(tp=8)
    outside = cost.grad_sync_time_us(op, s)
    assert outside > inside
    # and an op that itself tp-shards syncs 1/8 the bytes, still across
    # the DCN: cheaper than the replicated op's cross-pod sync
    sharded = cost.grad_sync_time_us(op, OpStrategy(dp=2, tp=8))
    assert inside < sharded < outside


def test_reduction_mode_flat_reprices_higher():
    cfg = ff.FFConfig()
    cfg.num_devices = 16
    cfg.batch_size = 64
    graph, _ = _weighted_op(cfg)
    strategies = {g: OpStrategy(dp=16) for g in graph.ops}
    auto = Simulator(multipod(), cfg)
    flat = Simulator(multipod(), cfg)
    flat.cost.reduction_mode = "flat"
    assert auto.simulate(graph, strategies) < flat.simulate(graph,
                                                            strategies)


def test_reduction_plan_records_cross_tier_choices():
    cfg = ff.FFConfig()
    cfg.num_devices = 16
    cfg.batch_size = 64
    graph, _ = _weighted_op(cfg)
    strategies = {g: OpStrategy(dp=16) for g in graph.ops}
    plan = Simulator(multipod(), cfg).cost.reduction_plan(graph, strategies)
    assert plan, "weighted dp-synced ops must appear in the plan"
    for rec in plan.values():
        assert rec["strategy"] in ("rs_ar_ag", "hier_ring")
        assert [t["tier"] for t in rec["tiers"]] == ["ici", "dcn"]
        assert rec["degree"] == 16 and rec["time_us"] > 0
    # flat machines carry no plan
    assert Simulator(TpuPodModel(16, CHIP), cfg).cost.reduction_plan(
        graph, strategies) == {}


def test_export_strategy_serializes_the_tier_decomposition(tmp_path):
    cfg = ff.FFConfig()
    cfg.num_devices = 16
    # large batch: per-chip compute outweighs the sync cost, so the
    # search picks a dp plan whose syncs the export must carry
    cfg.batch_size = 4096
    cfg.search_budget = 4
    cfg.use_native_search = False
    model = mlp_model(cfg, layers=2, width=1024)
    graph = Graph(model.ops)
    result = unity_optimize(graph, cfg, multipod(), cfg.batch_size, 16)
    path = str(tmp_path / "strategy.json")
    export_strategy(result, graph, path)
    data = json.loads(open(path).read())
    assert "reductions" in data
    assert set(data["reductions"]) <= set(data["ops"])
    assert all(r["strategy"] in ("flat", "rs_ar_ag", "hier_ring")
               and r["tiers"]
               for r in data["reductions"].values())


# -- FFTA07x ----------------------------------------------------------------

def _analyze(graph, strategies, machine, cfg, reductions, axes):
    return analyze_plan(graph, strategies=strategies, machine=machine,
                        config=cfg, batch_size=cfg.batch_size,
                        n_devices=16, mesh_axes=axes,
                        reduction_strategies=reductions, passes=("tiers",))


def test_ffta070_flat_sync_across_boundary():
    cfg = ff.FFConfig()
    cfg.num_devices = 16
    cfg.batch_size = 64
    graph, _ = _weighted_op(cfg)
    strategies = {g: OpStrategy(dp=16) for g in graph.ops}
    # a plan that pins NO decomposition (e.g. searched under a flat
    # machine model) is flat across the boundary: error
    rep = _analyze(graph, strategies, multipod(), cfg, {}, {"data": 16})
    assert rep.by_code("FFTA070") and not rep.ok
    # the machine's own synthesized decomposition passes
    plan = Simulator(multipod(), cfg).cost.reduction_plan(graph, strategies)
    rep2 = _analyze(graph, strategies, multipod(), cfg, plan, {"data": 16})
    assert not rep2.by_code("FFTA070") and not rep2.errors()
    # reductions=None means compile() will synthesize: also clean
    rep3 = _analyze(graph, strategies, multipod(), cfg, None, {"data": 16})
    assert not rep3.by_code("FFTA070") and not rep3.errors()
    # in-pod syncs never trigger the pass
    rep4 = _analyze(graph, {g: OpStrategy(dp=8) for g in graph.ops},
                    multipod(), cfg, {}, {"data": 8})
    assert not rep4.diagnostics


def test_ffta071_warns_on_heavy_dcn_traffic():
    cfg = ff.FFConfig()
    cfg.num_devices = 16
    cfg.batch_size = 64
    # 12288^2 f32 = 604 MB: even the rs_ar_ag shard (1/8) crossing the
    # DCN is ~75 MB, above the 64 MB per-step warning threshold
    model = mlp_model(cfg, layers=1, width=12288)
    graph = Graph(model.ops)
    strategies = {g: OpStrategy(dp=16) for g in graph.ops}
    rep = _analyze(graph, strategies, multipod(), cfg, None, {"data": 16})
    warns = rep.by_code("FFTA071")
    assert warns and not rep.errors()  # heavy but legal: warning only
    assert any("tier" in d.message for d in warns)
    # dp=2 one-member-per-pod (tp=8 mesh): the sync group lives ON the
    # dcn tier — flat is its only legal shape, but the full-bytes ring
    # across the DCN still draws the traffic warning (no FFTA070)
    strat2 = {g: OpStrategy(dp=2, tp=8) if graph.ops[g].name == "fc0"
              else OpStrategy(dp=2) for g in graph.ops}
    rep2 = _analyze(graph, strat2, multipod(), cfg, None,
                    {"data": 2, "model": 8})
    assert rep2.by_code("FFTA071") and not rep2.by_code("FFTA070")


def test_flat_machines_skip_the_tier_pass():
    cfg = ff.FFConfig()
    cfg.num_devices = 16
    cfg.batch_size = 64
    graph, _ = _weighted_op(cfg)
    rep = _analyze(graph, {g: OpStrategy(dp=16) for g in graph.ops},
                   TpuPodModel(16, CHIP), cfg, {}, {"data": 16})
    assert not rep.diagnostics


# -- compile wiring ---------------------------------------------------------

def test_compile_synthesizes_and_threads_the_reduction_plan(tmp_path):
    spec = tmp_path / "m.json"
    spec.write_text(json.dumps(
        {"tiers": [{"name": "ici", "degree": 4, "gbps": 45.0},
                   {"name": "dcn", "degree": 2, "gbps": 3.125, "links": 1,
                    "latency_us": 10.0}]}))
    cfg = ff.FFConfig()
    cfg.num_devices = 8
    cfg.batch_size = 32
    cfg.machine_model_file = str(spec)
    model = mlp_model(cfg, layers=2, width=64)
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.01),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[], parallel_axes={"data": 8})
    assert model._reduction_plan, "hierarchical compile must synthesize"
    assert model.executor.reduction_plan == model._reduction_plan
    for rec in model._reduction_plan.values():
        assert rec["strategy"] in ("rs_ar_ag", "hier_ring")
    # and the compile-time FFTA07x gate saw it (no errors raised) while
    # a fresh analysis run agrees
    rep = model.analyze_plan(passes=("tiers",))
    assert not rep.errors()
    # end-to-end: one training step executes on the 8-device mesh
    x = np.random.RandomState(0).randn(32, 64).astype(np.float32)
    y = np.zeros((32, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=32, epochs=1)
    assert np.isfinite(hist[0]["loss"])


# -- per-tier refit (satellite) ---------------------------------------------

def test_refit_fits_per_tier_scales(tmp_path):
    spec = tmp_path / "m.json"
    spec.write_text(json.dumps(
        {"tiers": [{"name": "ici", "degree": 4, "gbps": 45.0},
                   {"name": "dcn", "degree": 2, "gbps": 3.125, "links": 1,
                    "latency_us": 10.0}]}))
    cfg = ff.FFConfig()
    cfg.num_devices = 8
    cfg.batch_size = 32
    cfg.machine_model_file = str(spec)
    model = mlp_model(cfg, layers=2, width=1024)
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.01),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[], parallel_axes={"data": 8})
    model._op_strategies = {g: OpStrategy(dp=8) for g in model.graph.ops}
    machine = make_machine_model(cfg, 8)
    predicted = Simulator(machine, cfg).simulate(model.graph,
                                                 model._op_strategies)
    profile, history = refit(model, measured_step_us=predicted * 4.0,
                             op_rows=[], rounds=3)
    scales = profile.coefficients.tier_link_scales
    # the dp=8 sync crosses both tiers: both get a keyed scale < 1
    assert set(scales) == {"ici", "dcn"}
    assert all(0 < v < 1.0 for v in scales.values()), scales
    # the keyed profile round-trips and applies to a fresh machine
    path = str(tmp_path / "prof.json")
    profile.save(path)
    m2 = make_machine_model(
        dataclasses.replace(cfg, fitted_profile_file=path), 8)
    assert m2.tier_scales["dcn"] == pytest.approx(scales["dcn"])


def test_refit_on_flat_machine_keeps_single_scale():
    cfg = ff.FFConfig()
    cfg.num_devices = 8
    cfg.batch_size = 32
    cfg.machine_model_version = 1  # flat TpuPodModel
    model = mlp_model(cfg, layers=2, width=1024)
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.01),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[], parallel_axes={"data": 8})
    model._op_strategies = {g: OpStrategy(dp=8) for g in model.graph.ops}
    machine = make_machine_model(cfg, 8)
    predicted = Simulator(machine, cfg).simulate(model.graph,
                                                 model._op_strategies)
    profile, _ = refit(model, measured_step_us=predicted * 4.0,
                       op_rows=[], rounds=3)
    assert profile.coefficients.tier_link_scales == {}
    assert profile.coefficients.link_bw_scale != 1.0


# -- elastic shrink keeps the hierarchy when whole pods die -----------------

def test_shrink_topology_spec_preserves_tiers_on_whole_pod_loss():
    from flexflow_tpu.elastic.coordinator import shrink_topology_spec

    spec = {"chip": "tpu-v5e", "num_chips": 16,
            "tiers": [{"name": "ici", "degree": 8, "gbps": 45.0},
                      {"name": "dcn", "degree": 2, "gbps": 3.125,
                       "links": 1}]}
    # pod 1 (positions 8..15) drops off the DCN: hierarchy survives
    out = shrink_topology_spec(spec, list(range(8, 16)))
    assert out["num_chips"] == 8
    assert [t["degree"] for t in out["tiers"]] == [8, 1]
    m = HierarchicalMachineModel.from_json(out)
    assert m.num_chips == 8 and not m.crosses_tier_boundary(8)
    # a partial-pod loss cannot keep the uniform hierarchy: flat ring
    # fallback over the survivors at the innermost tier's bandwidth
    out2 = shrink_topology_spec(spec, [3])
    assert "tiers" not in out2 and out2["num_chips"] == 15
    assert all(g == 45.0 for _, _, g in out2["links"])


# -- kernel residual threshold knob (satellite) -----------------------------

def test_kernel_residual_threshold_flag_parses():
    cfg = ff.FFConfig()
    assert cfg.kernel_residual_threshold == 1.10
    cfg.parse_args(["--kernel-residual-threshold", "1.5"])
    assert cfg.kernel_residual_threshold == 1.5
    with pytest.raises(ValueError, match="must be > 0"):
        ff.FFConfig().parse_args(["--kernel-residual-threshold", "-1"])


def test_kernel_residual_threshold_gates_selection(tmp_path):
    from flexflow_tpu.kernels.registry import KERNELS

    prof = FittedProfile(chip="tpu-v5e", backend="cpu",
                         coefficients=FittedCoefficients(),
                         op_family_residuals={"layernorm": 1.3})
    path = str(tmp_path / "prof.json")
    prof.save(path)
    cfg = ff.FFConfig()
    cfg.fitted_profile_file = path
    # default threshold 1.10: the 1.3 residual nominates the fused kernel
    sel = KERNELS.select("layernorm", config=cfg, backend="tpu",
                         record=False)
    assert sel.impl == "pallas" and sel.reason == "residual"
    # a raised threshold rejects the same evidence
    cfg.kernel_residual_threshold = 1.5
    sel = KERNELS.select("layernorm", config=cfg, backend="tpu",
                         record=False)
    assert sel.impl == "reference"
    # configure() adopts the knob as the process default too
    cfg2 = ff.FFConfig()
    cfg2.kernel_residual_threshold = 1.5
    cfg2.fitted_profile_file = path
    KERNELS.configure(cfg2)
    try:
        sel = KERNELS.select("layernorm", backend="tpu", record=False)
        assert sel.impl == "reference"
    finally:
        KERNELS.configure(ff.FFConfig())


# -- tier-aware pipeline placement + overlap (docs/machine.md "Overlap") ---

def _transformer_graph(cfg, layers=8):
    from flexflow_tpu.models import TransformerConfig, build_bert_encoder

    m = ff.FFModel(cfg)
    tokens = m.create_tensor([cfg.batch_size, 64], ff.DataType.DT_INT32)
    c = TransformerConfig(hidden_size=256, embedding_size=256,
                          num_heads=4, num_layers=layers,
                          sequence_length=64, vocab_size=1000)
    build_bert_encoder(m, tokens, c)
    return Graph(m.ops)


def _pp_config(n=16, batch=64):
    cfg = ff.FFConfig()
    cfg.num_devices = n
    cfg.batch_size = batch
    cfg.search_budget = 4
    cfg.enable_pipeline_parallel = True
    cfg.pipeline_microbatches = 4
    cfg.use_native_search = False
    return cfg


def test_pipeline_candidate_places_stage_cut_on_pod_boundary():
    """On the 2-pod x 8-chip spec the best pipeline candidate must nest
    the stage axis OUTERMOST with dp covering a whole pod: the stage
    cut lands on the pod edge, DCN carries only the inter-stage
    activation hop, and each stage's dp weight syncs stay on ICI."""
    from flexflow_tpu.search.unity import GraphSearchHelper

    cfg = _pp_config()
    graph = _transformer_graph(cfg)
    machine = multipod(ici=8, pods=2)
    helper = GraphSearchHelper(graph, cfg, machine)
    cands = helper._pipeline_candidates(graph, cfg.batch_size, 16)
    assert cands
    best = min(cands, key=lambda r: r.cost_us)
    pl = best.pipeline_placement
    assert best.mesh_axes == {"stage": 2, "data": 8}, best.log
    assert list(best.mesh_axes)[0] == "stage"  # outermost: pod blocks
    assert pl["order"] == "stage_outer"
    assert pl["cut_on_tier_boundary"], pl
    assert pl["hop_tier"] == "dcn", pl
    # the same (dp, pp) under the legacy strided nesting must cost more:
    # its dp sync groups stride across the DCN
    legacy = [r for r in cands
              if r.mesh_axes.get("stage") == 2
              and r.pipeline_placement["order"] == "stage_inner"]
    assert legacy and legacy[0].cost_us > best.cost_us
    assert legacy[0].pipeline_placement["sync_us"] > pl["sync_us"]


def test_pipeline_stage_hop_priced_on_dcn_tier_not_p2p():
    """The priced stage-boundary transfer of a pod-aligned candidate
    uses the DCN tier via tier_path — not the innermost p2p term the
    flat pricing used."""
    from flexflow_tpu.search.unity import GraphSearchHelper

    cfg = _pp_config()
    graph = _transformer_graph(cfg)
    machine = multipod(ici=8, pods=2)
    helper = GraphSearchHelper(graph, cfg, machine)
    cands = helper._pipeline_candidates(graph, cfg.batch_size, 16)
    best = min(cands, key=lambda r: r.cost_us)
    m = cfg.pipeline_microbatches
    # hop bytes: per-microbatch per-dp-shard activation (seq x hidden,
    # bf16 under the default mixed precision)
    hop_bytes = (cfg.batch_size // m // 8) * 64 * 256 * 2
    want = machine.ring_hop_time_us(hop_bytes, 2, inner=8)
    assert best.pipeline_placement["hop_us"] == pytest.approx(want)
    # DCN-priced: strictly slower than the innermost-tier p2p price
    assert want > machine.p2p_time_us(hop_bytes)


def test_one_tier_pipeline_candidates_match_flat_pod_bit_for_bit():
    from flexflow_tpu.search.unity import GraphSearchHelper

    cfg = _pp_config()
    graph = _transformer_graph(cfg)
    h_one = GraphSearchHelper(graph, cfg, one_tier(16))
    h_flat = GraphSearchHelper(graph, cfg, TpuPodModel(16, CHIP))
    c_one = h_one._pipeline_candidates(graph, cfg.batch_size, 16)
    c_flat = h_flat._pipeline_candidates(graph, cfg.batch_size, 16)
    assert [r.cost_us for r in c_one] == [r.cost_us for r in c_flat]
    assert [r.mesh_axes for r in c_one] == [r.mesh_axes for r in c_flat]
    # one-tier machines keep the legacy nesting only
    assert all(r.pipeline_placement["order"] == "stage_inner"
               for r in c_one)


def test_search_result_reports_overlap_split():
    """The searched multipod plan carries the overlapped/exposed
    grad-sync split; the legacy blocking knob zeroes the overlap term
    (satellite: docs/machine.md "Overlap")."""
    cfg = ff.FFConfig()
    cfg.num_devices = 16
    cfg.batch_size = 512
    cfg.search_budget = 4
    cfg.use_native_search = False
    m = mlp_model(cfg, layers=3, width=512)
    graph = Graph(m.ops)
    res = unity_optimize(graph, cfg, multipod(ici=8, pods=2), 512, 16)
    assert res.exposed_sync_us is not None
    assert res.overlapped_sync_us is not None
    assert res.exposed_sync_us >= 0 and res.overlapped_sync_us >= 0
    cfg2 = ff.FFConfig()
    cfg2.num_devices = 16
    cfg2.batch_size = 512
    cfg2.search_budget = 4
    cfg2.use_native_search = False
    cfg2.search_overlap_backward_update = False
    m2 = mlp_model(cfg2, layers=3, width=512)
    res2 = unity_optimize(Graph(m2.ops), cfg2, multipod(ici=8, pods=2),
                          512, 16)
    assert res2.overlapped_sync_us == 0.0
    assert res2.sync_buckets == 0


def test_reduction_plan_carries_bucket_schedule():
    """Bucketed entries record the priced schedule: bucket mates share
    one strategy and bucket totals; blocking/per-tensor modes stay
    bucket-less (the pre-bucketing plan format)."""
    cfg = ff.FFConfig()
    cfg.batch_size = 64
    cfg.grad_bucket_bytes = 600 * 1024  # several buckets at 512-width
    m = mlp_model(cfg, layers=4, width=512)
    graph = Graph(m.ops)
    strategies = {op.guid: OpStrategy(dp=16) for op in m.ops}
    cm = CostModel(multipod(ici=8, pods=2), cfg)
    plan = cm.reduction_plan(graph, strategies)
    buckets = {}
    for name, e in plan.items():
        assert e["bucket"] is not None
        buckets.setdefault(e["bucket"], []).append(e)
    assert len(buckets) >= 2, plan
    for entries in buckets.values():
        assert len({e["strategy"] for e in entries}) == 1
        assert len({e["bucket_bytes"] for e in entries}) == 1
        got = sum(e["bytes"] for e in entries)
        assert got == pytest.approx(entries[0]["bucket_bytes"])
        # per-op time is the byte share of the bucket's one collective
        assert sum(e["time_us"] for e in entries) == pytest.approx(
            entries[0]["bucket_time_us"])
    cfg.search_overlap_backward_update = False
    plan_blk = CostModel(multipod(ici=8, pods=2), cfg).reduction_plan(
        graph, strategies)
    assert all("bucket" not in e for e in plan_blk.values())
    cfg.search_overlap_backward_update = True
    cfg.grad_bucket_bytes = 0
    plan_pt = CostModel(multipod(ici=8, pods=2), cfg).reduction_plan(
        graph, strategies)
    assert all("bucket" not in e for e in plan_pt.values())


def test_pipeline_placement_stage_count_differs_from_pod_count():
    """examples/machines/multipod_4x4.json (4 pods x 4 chips): stage
    counts that do NOT equal the pod count still cut on pod edges when
    dp covers whole pods — pp=2 puts two pods in each stage, pp=4 one —
    while a half-pod dp lands mid-pod."""
    import os

    from flexflow_tpu.parallel.pipeline_plan import stage_placement_options

    spec = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "machines",
        "multipod_4x4.json")
    cfg = ff.FFConfig()
    cfg.machine_model_file = spec
    machine = make_machine_model(cfg, 16)
    assert hasattr(machine, "tier_path")
    assert [t.degree for t in machine.tiers] == [4, 4]
    outer2 = stage_placement_options(machine, dp=8, pp=2)[0]
    assert outer2["cut_on_tier_boundary"] and outer2["hop_tier"] == "dcn"
    outer4 = stage_placement_options(machine, dp=4, pp=4)[0]
    assert outer4["cut_on_tier_boundary"] and outer4["hop_tier"] == "dcn"
    outer8 = stage_placement_options(machine, dp=2, pp=8)[0]
    assert not outer8["cut_on_tier_boundary"]


# -- expert-parallel all_to_all tiering (ISSUE 16) --------------------------

def _moe_experts_op(n=8, batch=64, F=16, k=2, H=24):
    cfg = ff.FFConfig()
    cfg.batch_size = batch
    m = ff.FFModel(cfg)
    inp = m.create_tensor([batch, F])
    m.moe(inp, n, k, H, alpha=float(n), fused=True, name="moe")
    graph = Graph(m.ops)
    op = next(o for o in graph.ops.values()
              if o.op_type.value == "experts")
    return graph, op, cfg


def test_expert_a2a_pod_resident_never_prices_dcn():
    """An ep group that fits the innermost tier (ep=8 on an 8-chip pod,
    inner stride 1) routes its all_to_all entirely over ICI: its price is
    a single-tier tier_path and does NOT move when the DCN tier is made
    100x slower — while the cross-pod dp grad sync of the same plan
    does."""
    _, op, cfg = _moe_experts_op(n=8)
    s = OpStrategy(dp=2, ep=8)

    def price(dcn_scale):
        machine = multipod()  # fresh: tier scales and memos reset
        machine.tier_scales["dcn"] = dcn_scale
        sim = Simulator(machine, cfg)
        sim.cost.set_mesh_degrees(tp=1, sp=1, ep=8, ap=1)
        return (sim.cost.ep_collective_time_us(op, s),
                sim.cost.grad_sync_time_us(op, s))

    a2a_fast, sync_fast = price(1.0)
    a2a_slow, sync_slow = price(0.01)
    assert a2a_fast > 0
    assert a2a_slow == pytest.approx(a2a_fast)  # ICI-only: DCN-invariant
    assert sync_slow > sync_fast  # dp=2 strided across the pods pays DCN

    machine = multipod()
    path = machine.tier_path(8, 1)
    assert [t.name for t, _ in path] == ["ici"]


def test_expert_a2a_crossing_pods_prices_the_dcn_tier():
    """The SAME ep degree with a stride that pushes the group across the
    pod boundary (an sp axis nested inside ep) spans both tiers: the
    all_to_all price jumps and now scales with the DCN link speed —
    the cost signal behind the FFTA085 pod-residency prune."""
    _, op, cfg = _moe_experts_op(n=8, batch=64)

    def price(sp_inner, dcn_scale=1.0):
        machine = multipod()
        machine.tier_scales["dcn"] = dcn_scale
        sim = Simulator(machine, cfg)
        sim.cost.set_mesh_degrees(tp=1, sp=sp_inner, ep=8, ap=1)
        s = OpStrategy(dp=16 // (8 * sp_inner) if sp_inner == 1 else 1,
                       ep=8, sp=sp_inner)
        return sim.cost.ep_collective_time_us(op, s)

    resident = price(1)
    crossing = price(2)
    assert crossing > resident
    assert price(2, dcn_scale=0.5) > crossing  # rides the DCN link

    machine = multipod()
    path = machine.tier_path(8, 2)
    assert [t.name for t, _ in path] == ["ici", "dcn"]
