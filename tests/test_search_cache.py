"""Incremental Unity search (PR 15, docs/search.md): content-addressed
plan cache, warm-started reshard-aware re-planning, background
pre-planning, determinism, and strategy provenance.

Covers the ISSUE 15 acceptance surface on the CPU test mesh:
 - same (graph, machine, config) -> bit-identical SearchResult across
   repeated runs and across export/import round-trips (the precondition
   the cache keys rely on);
 - a plan-cache hit skips enumeration entirely (candidates_simulated ==
   0) while the analysis gate still re-validates the adopted plan;
 - warm-started re-planning after a machine shrink matches the cold
   result's quality and prices a plan-distance term against a live plan;
 - the elastic coordinator pre-computes anticipated-survivor plans in
   the background and consumes them at recovery time;
 - export/import provenance (FFTA052) and the new metric families.
"""
import dataclasses
import json
import os

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.search.machine_model import (HierarchicalMachineModel,
                                               TierSpec, TpuPodModel,
                                               make_machine_model)
from flexflow_tpu.search.plan_cache import (BackgroundPlanner, PlanCache,
                                            PlanKey, get_plan_cache,
                                            graph_fingerprint,
                                            knobs_fingerprint,
                                            machine_fingerprint,
                                            plan_distance_us, plan_key,
                                            reset_plan_cache)
from flexflow_tpu.search.unity import (export_strategy, import_strategy,
                                       result_to_dict, unity_optimize)


def _config(n_devices=8, budget=4, **kw):
    cfg = ff.FFConfig()
    cfg.batch_size = 64
    cfg.search_budget = budget
    cfg.num_devices = n_devices
    cfg.use_native_search = False
    cfg.measure_op_costs = False
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def _mlp(cfg, width=128, layers=2):
    m = ff.FFModel(cfg)
    t = m.create_tensor([cfg.batch_size, 32])
    for _ in range(layers):
        t = m.dense(t, width, ff.ActiMode.AC_MODE_RELU)
    t = m.dense(t, 10)
    m.softmax(t)
    return m


def _multipod(ici=4, pods=2):
    return HierarchicalMachineModel([
        TierSpec("ici", ici, 45.0, links=2),
        TierSpec("dcn", pods, 3.125, links=1, latency_us=10.0),
    ])


def _strategies_by_name(result, graph):
    return {graph.ops[g].name: dataclasses.astuple(s)
            for g, s in result.strategies.items() if g in graph.ops}


# -- determinism (the precondition cache keys rely on) ---------------------

def test_search_is_deterministic_across_runs():
    runs = []
    for _ in range(2):
        reset_plan_cache()  # both runs COLD: determinism, not caching
        cfg = _config()
        graph = Graph(_mlp(cfg).ops)
        r = unity_optimize(graph, cfg, TpuPodModel(8), 64, 8)
        assert r.cache_mode == "cold"
        runs.append((_strategies_by_name(r, graph), r.mesh_axes,
                     r.cost_us, r.memory_bytes, r.candidates_simulated,
                     r.candidates_pruned, r.graph_hash, r.machine_hash))
    assert runs[0] == runs[1]


def test_export_import_roundtrip_bit_identical(tmp_path):
    cfg = _config()
    graph = Graph(_mlp(cfg).ops)
    r = unity_optimize(graph, cfg, TpuPodModel(8), 64, 8)
    path = str(tmp_path / "strategy.json")
    export_strategy(r, graph, path)

    cfg2 = _config()
    graph2 = Graph(_mlp(cfg2).ops)
    strategies, axes = import_strategy(graph2, path)
    assert axes == r.mesh_axes
    assert ({graph2.ops[g].name: dataclasses.astuple(s)
             for g, s in strategies.items()}
            == _strategies_by_name(r, graph))


def test_plan_key_stability_and_sensitivity():
    cfg = _config()
    g1 = Graph(_mlp(cfg).ops)
    g2 = Graph(_mlp(_config()).ops)  # fresh build, same architecture
    assert graph_fingerprint(g1) == graph_fingerprint(g2)
    # a different architecture changes the graph leg
    g3 = Graph(_mlp(_config(), width=64).ops)
    assert graph_fingerprint(g1) != graph_fingerprint(g3)
    # machine leg: size and overlaid coefficients both count
    m8, m4 = TpuPodModel(8), TpuPodModel(4)
    assert machine_fingerprint(m8) != machine_fingerprint(m4)
    m8b = TpuPodModel(8)
    m8b.step_time_scale = 1.25  # a fitted-profile overlay term
    assert machine_fingerprint(m8) != machine_fingerprint(m8b)
    # knob leg
    assert knobs_fingerprint(cfg) == knobs_fingerprint(_config())
    assert knobs_fingerprint(cfg) != knobs_fingerprint(_config(budget=9))
    # the live plan shapes candidate RANKING, not cached identity
    cfg_lp = _config()
    cfg_lp.replan_live_plan = object()
    assert knobs_fingerprint(cfg) == knobs_fingerprint(cfg_lp)
    k = plan_key(g1, cfg, m8, 64, 8)
    assert k == plan_key(g2, _config(), TpuPodModel(8), 64, 8)
    assert k != plan_key(g1, cfg, m8, 128, 8)


# -- hit path ---------------------------------------------------------------

def test_cache_hit_skips_enumeration_and_matches_cold():
    cfg = _config()
    graph = Graph(_mlp(cfg).ops)
    cold = unity_optimize(graph, cfg, TpuPodModel(8), 64, 8)
    assert cold.cache_mode == "cold" and cold.candidates_simulated > 0

    cfg2 = _config()
    graph2 = Graph(_mlp(cfg2).ops)
    hit = unity_optimize(graph2, cfg2, TpuPodModel(8), 64, 8)
    assert hit.cache_mode == "hit"
    assert hit.candidates_simulated == 0 and hit.candidates_pruned == 0
    assert hit.cost_us == cold.cost_us
    assert hit.mesh_axes == cold.mesh_axes
    assert hit.predicted_step_us == cold.predicted_step_us
    assert (_strategies_by_name(hit, graph2)
            == _strategies_by_name(cold, graph))
    from flexflow_tpu.obs.registry import REGISTRY

    assert REGISTRY.counter(
        "ff_search_cache_hits_total", "", labels=("tier",)).value(
        tier="memory") == 1


def test_cache_hit_still_runs_analysis_gate(monkeypatch):
    cfg = _config()
    graph = Graph(_mlp(cfg).ops)
    unity_optimize(graph, cfg, TpuPodModel(8), 64, 8)

    calls = {"n": 0}
    import flexflow_tpu.analysis.pipeline as pipeline

    real = pipeline.check_plan

    def spy(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(pipeline, "check_plan", spy)
    monkeypatch.setattr("flexflow_tpu.analysis.check_plan", spy)
    cfg2 = _config()
    hit = unity_optimize(Graph(_mlp(cfg2).ops), cfg2, TpuPodModel(8),
                         64, 8)
    assert hit.cache_mode == "hit"
    assert calls["n"] >= 1  # the adoption gate ran


def test_stale_entry_falls_back_to_cold():
    """An entry whose ops no longer bind (a hash collision would be the
    real-world cause; here we corrupt the stored ops) is invalidated
    and the search runs cold instead of mis-applying it."""
    cfg = _config()
    graph = Graph(_mlp(cfg).ops)
    cold = unity_optimize(graph, cfg, TpuPodModel(8), 64, 8)
    cache = get_plan_cache(cfg)
    key = plan_key(Graph(_mlp(_config()).ops), cfg, TpuPodModel(8), 64, 8)
    data = cache.get(key, count=False)
    assert data is not None
    data["ops"] = {"not_a_real_op": {"dp": 8}}
    cache.put(key, data)
    cfg2 = _config()
    r = unity_optimize(Graph(_mlp(cfg2).ops), cfg2, TpuPodModel(8), 64, 8)
    assert r.cache_mode == "cold"
    assert r.cost_us == cold.cost_us


def test_cache_lru_eviction_and_disk_persistence(tmp_path):
    cache = PlanCache(capacity=2, cache_dir=str(tmp_path))
    keys = [PlanKey(f"g{i}", "m", "k", 1, 1) for i in range(3)]
    for i, k in enumerate(keys):
        cache.put(k, {"cost_us": float(i)})
    assert len(cache) == 2  # g0 evicted from memory
    from flexflow_tpu.obs.registry import REGISTRY

    assert REGISTRY.counter(
        "ff_search_cache_evictions_total", "").value() == 1
    # ... but persists on disk and promotes back on get
    assert cache.get(keys[0])["cost_us"] == 0.0
    # a FRESH cache instance (new process) reads the same dir
    cache2 = PlanCache(capacity=4, cache_dir=str(tmp_path))
    assert cache2.get(keys[2])["cost_us"] == 2.0
    # invalidate removes the disk entry too
    cache2.invalidate(keys[2])
    assert cache2.get(keys[2]) is None


def test_plan_cache_dir_roundtrip_through_unity(tmp_path):
    cfg = _config(plan_cache_dir=str(tmp_path))
    graph = Graph(_mlp(cfg).ops)
    cold = unity_optimize(graph, cfg, TpuPodModel(8), 64, 8)
    assert any(f.startswith("plan_") for f in os.listdir(tmp_path))
    reset_plan_cache()  # "new process": in-memory tier gone
    cfg2 = _config(plan_cache_dir=str(tmp_path))
    hit = unity_optimize(Graph(_mlp(cfg2).ops), cfg2, TpuPodModel(8),
                         64, 8)
    assert hit.cache_mode == "hit"
    assert hit.cost_us == cold.cost_us


def test_no_plan_cache_flag_disables():
    cfg = _config(plan_cache=False)
    graph = Graph(_mlp(cfg).ops)
    unity_optimize(graph, cfg, TpuPodModel(8), 64, 8)
    cfg2 = _config(plan_cache=False)
    r = unity_optimize(Graph(_mlp(cfg2).ops), cfg2, TpuPodModel(8), 64, 8)
    assert r.cache_mode == "cold" and r.candidates_simulated > 0


# -- warm start -------------------------------------------------------------

def test_warm_start_after_shrink_matches_cold_quality():
    cfg = _config(n_devices=16)
    graph = Graph(_mlp(cfg).ops)
    unity_optimize(graph, cfg, _multipod(ici=8, pods=2), 64, 16)

    # one-pod shrink: near-miss key -> warm-started refinement
    cfg_w = _config(n_devices=8)
    gw = Graph(_mlp(cfg_w).ops)
    warm = unity_optimize(gw, cfg_w, _multipod(ici=8, pods=1), 64, 8)
    assert warm.cache_mode == "warm"

    reset_plan_cache()
    cfg_c = _config(n_devices=8)
    gc = Graph(_mlp(cfg_c).ops)
    cold = unity_optimize(gc, cfg_c, _multipod(ici=8, pods=1), 64, 8)
    assert cold.cache_mode == "cold"
    # ISSUE 15 acceptance: chosen-plan predicted cost within 2% of cold
    assert warm.cost_us <= 1.02 * cold.cost_us
    from flexflow_tpu.obs.registry import REGISTRY

    assert REGISTRY.counter("ff_search_warm_starts_total", "").value() == 1


def test_warm_result_is_cached_for_next_lookup():
    cfg = _config(n_devices=8)
    unity_optimize(Graph(_mlp(cfg).ops), cfg, TpuPodModel(8), 64, 8)
    cfg_w = _config(n_devices=4)
    warm = unity_optimize(Graph(_mlp(cfg_w).ops), cfg_w, TpuPodModel(4),
                          64, 4)
    assert warm.cache_mode == "warm"
    cfg_h = _config(n_devices=4)
    hit = unity_optimize(Graph(_mlp(cfg_h).ops), cfg_h, TpuPodModel(4),
                         64, 4)
    assert hit.cache_mode == "hit"
    assert hit.cost_us == warm.cost_us


def test_warm_start_disabled_by_flag():
    cfg = _config(n_devices=8)
    unity_optimize(Graph(_mlp(cfg).ops), cfg, TpuPodModel(8), 64, 8)
    cfg_w = _config(n_devices=4, search_warm_start=False)
    r = unity_optimize(Graph(_mlp(cfg_w).ops), cfg_w, TpuPodModel(4),
                       64, 4)
    assert r.cache_mode == "cold"


# -- plan distance (reshard-aware re-planning) ------------------------------

def _compiled_model(n_devices=4, **kw):
    cfg = _config(n_devices=n_devices, budget=0, **kw)
    cfg.device_ids = list(range(n_devices))
    m = _mlp(cfg)
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return m


def test_plan_distance_prices_moves_and_zeroes_noops():
    from flexflow_tpu.resharding import plan_of
    from flexflow_tpu.search.simulator import OpStrategy

    model = _compiled_model(4)
    live = plan_of(model)
    graph = model.graph
    machine = TpuPodModel(4)
    same = {g: OpStrategy(dp=4) for g in graph.ops}
    axes = {"data": 4}
    d_same = plan_distance_us(graph, live, same, axes, machine, 4)
    assert d_same == 0.0  # dp-only: weights replicated both sides
    # a TP plan shards the linear kernels: real bytes must move
    tp = {g: (OpStrategy(tp=4)
              if graph.ops[g].weights else OpStrategy())
          for g in graph.ops}
    d_tp = plan_distance_us(graph, live, tp, {"model": 4}, machine, 4)
    assert d_tp > 0.0


def test_warm_replan_prices_distance_term_in_log():
    from flexflow_tpu.resharding import plan_of

    model = _compiled_model(8)
    cfg = _config(n_devices=8)
    unity_optimize(Graph(_mlp(cfg).ops), cfg, TpuPodModel(8), 64, 8)
    cfg_w = _config(n_devices=4)
    cfg_w.replan_live_plan = plan_of(model)
    warm = unity_optimize(Graph(_mlp(cfg_w).ops), cfg_w, TpuPodModel(4),
                          64, 4)
    assert warm.cache_mode == "warm"
    assert any("reshard=" in line for line in warm.log), warm.log


# -- expert-parallel plans across a pod-loss shrink (satellite: ep
# transplant; docs/moe.md "Warm re-planning") ------------------------------

def _moe_graph_model(cfg, F=1024, n=8, k=2, H=4096, head=True):
    m = ff.FFModel(cfg)
    inp = m.create_tensor([cfg.batch_size, F])
    out = m.moe(inp, n, k, H, alpha=float(n), fused=True, name="moe")
    t = m.dense(out, 3)
    if head:
        m.softmax(t)
    return m


def test_warm_transplant_keeps_ep_legal_across_pod_loss():
    """A cached ep>1 plan warm-starts the survivor search after a pod
    loss; every transplanted EXPERTS strategy must stay legal on the
    smaller mesh (ep divides the expert count AND fits the survivor
    expert axis)."""
    from flexflow_tpu.ffconst import OpType

    cfg = _config(n_devices=8)
    cfg.batch_size = 512
    g = Graph(_moe_graph_model(cfg).ops)
    cold = unity_optimize(g, cfg, TpuPodModel(8), 512, 8)
    assert cold.cache_mode == "cold"
    assert any(s.ep > 1 for s in cold.strategies.values()), cold.log

    cfg_w = _config(n_devices=4)
    cfg_w.batch_size = 512
    cfg_w.device_ids = [0, 1, 2, 3]  # pod-loss survivors
    g_w = Graph(_moe_graph_model(cfg_w).ops)
    warm = unity_optimize(g_w, cfg_w, TpuPodModel(4), 512, 4)
    assert warm.cache_mode == "warm"
    ep_axis = warm.mesh_axes.get("expert", 1)
    for guid, s in warm.strategies.items():
        op = g_w.ops[guid]
        if op.op_type == OpType.EXPERTS:
            assert 8 % max(s.ep, 1) == 0
            assert s.ep <= ep_axis


def test_plan_distance_clamps_cached_ep_to_survivor_axis():
    """Regression: a cached strategy carrying ep=4 priced against a
    survivor mesh whose 'expert' axis shrank to 2 must claim the SAME
    degree the runtime will apply (min(s.ep, axis) — model.py
    _assign_strategy), so an effectively-unchanged layout prices as a
    noop instead of a phantom reshard."""
    from flexflow_tpu.resharding import plan_of
    from flexflow_tpu.search.simulator import OpStrategy

    cfg = _config(n_devices=4, budget=0)
    cfg.device_ids = list(range(4))
    m = _moe_graph_model(cfg, F=16, n=8, k=2, H=32)
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              parallel_axes={"data": 2, "expert": 2})
    live = plan_of(m)
    graph = m.graph
    machine = TpuPodModel(4)
    axes = {"data": 2, "expert": 2}
    # the cached (pre-loss) plan says ep=4; the survivor axis is 2
    cand = {g: (OpStrategy(dp=2, ep=4)
                if graph.ops[g].op_type.value == "experts"
                else OpStrategy(dp=2))
            for g in graph.ops}
    d = plan_distance_us(graph, live, cand, axes, machine, 4,
                         device_ids=cfg.device_ids)
    assert d == 0.0  # runtime clamps ep 4 -> 2: nothing actually moves


# -- background pre-planning ------------------------------------------------

def test_background_planner_runs_jobs_and_survives_errors():
    bp = BackgroundPlanner(idle_timeout_s=0.2)
    seen = []
    bp.submit("a", lambda: seen.append("a") or "ok")
    bp.submit("boom", lambda: 1 / 0)
    bp.submit("b", lambda: seen.append("b") or "ok")
    assert bp.join(timeout=10)
    assert seen == ["a", "b"]
    recs = {r["tag"]: r for r in bp.completed}
    assert recs["a"]["result"] == "ok"
    assert "ZeroDivisionError" in recs["boom"]["error"]
    assert all(r["wall_ms"] >= 0 for r in bp.completed)


def test_coordinator_precomputes_and_recovery_hits():
    import tempfile

    from flexflow_tpu.elastic.coordinator import ElasticCoordinator
    from flexflow_tpu.elastic.faults import FaultPlan
    from flexflow_tpu.elastic.retry import RetryPolicy

    cfg = ff.FFConfig()
    cfg.batch_size = 32
    cfg.search_budget = 4
    cfg.measure_op_costs = False
    cfg.use_native_search = False
    cfg.device_ids = list(range(4))

    def builder(c):
        m = ff.FFModel(c)
        t = m.create_tensor([c.batch_size, 64])
        t = m.dense(t, 128, ff.ActiMode.AC_MODE_RELU)
        t = m.dense(t, 10)
        m.softmax(t)
        m.compile(
            optimizer=ff.SGDOptimizer(m, lr=0.05),
            loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        return m

    rng = np.random.RandomState(0)
    x = rng.randn(64, 64).astype(np.float32)
    y = rng.randint(0, 10, size=(64, 1)).astype(np.int32)
    coord = ElasticCoordinator(
        builder, cfg,
        fault_plan=FaultPlan().add_chip_loss(3, chips=[3]),
        checkpoint_dir=tempfile.mkdtemp(prefix="ff_pc_"),
        checkpoint_every=2,
        retry_policy=RetryPolicy(max_retries=2, base_delay_s=0.01))
    assert coord.planner is not None  # auto: budget > 0 + cache on
    assert coord.preplan_join(timeout=60)
    pre = coord.events.events("plan.precompute")
    assert pre and pre[0].details["tag"] == "chip_loss"
    assert pre[0].details["wall_ms"] > 0
    coord.fit(x, y, steps=6)
    search_evs = coord.events.events("recovery.search")
    assert search_evs, "no recovery happened"
    det = search_evs[0].details
    # the recovery consumed the pre-computed plan: search off the pause
    assert det["cache"] == "hit", det
    assert det["search_ms"] is not None and det["search_ms"] >= 0


def test_coordinator_preplan_off_still_recovers():
    import tempfile

    from flexflow_tpu.elastic.coordinator import ElasticCoordinator
    from flexflow_tpu.elastic.faults import FaultPlan
    from flexflow_tpu.elastic.retry import RetryPolicy

    cfg = ff.FFConfig()
    cfg.batch_size = 32
    cfg.search_budget = 4
    cfg.measure_op_costs = False
    cfg.use_native_search = False
    cfg.device_ids = list(range(4))

    def builder(c):
        m = ff.FFModel(c)
        t = m.create_tensor([c.batch_size, 64])
        t = m.dense(t, 10)
        m.softmax(t)
        m.compile(
            optimizer=ff.SGDOptimizer(m, lr=0.05),
            loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        return m

    rng = np.random.RandomState(0)
    x = rng.randn(64, 64).astype(np.float32)
    y = rng.randint(0, 10, size=(64, 1)).astype(np.int32)
    coord = ElasticCoordinator(
        builder, cfg,
        fault_plan=FaultPlan().add_chip_loss(3, chips=[3]),
        checkpoint_dir=tempfile.mkdtemp(prefix="ff_pc_"),
        checkpoint_every=2, preplan=False,
        retry_policy=RetryPolicy(max_retries=2, base_delay_s=0.01))
    assert coord.planner is None
    assert coord.events.events("plan.precompute") == []
    coord.fit(x, y, steps=6)
    assert coord.events.events("recovery.done")


# -- autoscaler preplan hook ------------------------------------------------

def test_autoscaler_preplans_next_resize_target():
    from flexflow_tpu.serving.fleet.autoscaler import Autoscaler

    class FakeReplica:
        def __init__(self):
            from flexflow_tpu.serving.fleet.replica import ReplicaState

            self.state = ReplicaState.READY
            self._depth = 5

        def queue_depth(self):
            return self._depth

        def utilization(self):
            return 0.9

        def num_slots(self):
            return 8  # already at max: only a replica add could help

        def live_sequences(self):
            return 1

    class FakeRouter:
        def __init__(self):
            from flexflow_tpu.obs.registry import MetricsRegistry

            self.registry = MetricsRegistry()
            self._reps = {"r0": FakeReplica()}

        def replica_names(self):
            return list(self._reps)

        def replica(self, name):
            return self._reps[name]

    planned = []
    bp = BackgroundPlanner(idle_timeout_s=0.2)
    auto = Autoscaler(FakeRouter(), min_slots=1, max_slots=8,
                      preplanner=bp,
                      preplan_fn=lambda: planned.append("warm") or "ok")
    actions = auto.tick()
    assert any(a["action"] == "preplan" for a in actions), actions
    assert bp.join(timeout=10)
    assert planned == ["warm"]
    # edge-triggered: the next overloaded tick does not resubmit
    assert not any(a.get("action") == "preplan" for a in auto.tick())


# -- provenance (satellite) -------------------------------------------------

def test_export_carries_provenance_and_import_warns_on_mismatch(
        tmp_path, caplog):
    cfg = _config()
    graph = Graph(_mlp(cfg).ops)
    r = unity_optimize(graph, cfg, TpuPodModel(8), 64, 8)
    path = str(tmp_path / "s.json")
    export_strategy(r, graph, path)
    with open(path) as f:
        data = json.load(f)
    prov = data["provenance"]
    assert prov["graph_hash"] == r.graph_hash
    assert prov["machine_hash"] == r.machine_hash
    assert prov["candidates_simulated"] == r.candidates_simulated
    assert prov["cache_mode"] == "cold"

    # same graph, matching hash: no FFTA052
    g_ok = Graph(_mlp(_config()).ops)
    import logging

    with caplog.at_level(logging.WARNING):
        import_strategy(g_ok, path,
                        expect_graph_hash=graph_fingerprint(g_ok))
    assert "FFTA052" not in caplog.text
    caplog.clear()

    # a DIFFERENT graph: warns, does not raise
    g_other = Graph(_mlp(_config(), width=64, layers=1).ops)
    with caplog.at_level(logging.WARNING):
        import_strategy(g_other, path,
                        expect_graph_hash=graph_fingerprint(g_other))
    assert "FFTA052" in caplog.text
    assert "different graph" in caplog.text


def test_analyze_cli_warns_on_machine_mismatch(tmp_path, capsys):
    from flexflow_tpu.__main__ import _synthetic
    from flexflow_tpu.analysis.cli import run_analyze

    cfg = _config()
    model, _, _ = _synthetic("mnist_mlp", cfg)
    graph = Graph(model.ops)
    r = unity_optimize(graph, cfg, make_machine_model(cfg, 8),
                       cfg.batch_size, 8)
    path = str(tmp_path / "s.json")
    export_strategy(r, graph, path)
    def report_of(stdout: str) -> dict:
        # the report JSON is multi-line; anything after its closing
        # brace (the "plan OK" line) is not part of it
        text = stdout[stdout.index("{"):stdout.rindex("}") + 1]
        return json.loads(text)

    # same chips: clean
    rc = run_analyze(["--model", "mnist_mlp", "--chips", "8",
                      "--strategy", path, "--json"])
    out = report_of(capsys.readouterr().out)
    assert rc == 0
    assert not [d for d in out["diagnostics"] if d["code"] == "FFTA052"]
    # the exported plan was priced on 8 chips; dp=8 is illegal on 4, so
    # the exit is 1 — the point here is the FFTA052 provenance warning
    # landing in the SAME report
    rc = run_analyze(["--model", "mnist_mlp", "--chips", "4",
                      "--strategy", path, "--json"])
    out = report_of(capsys.readouterr().out)
    assert [d for d in out["diagnostics"] if d["code"] == "FFTA052"]


# -- metrics ----------------------------------------------------------------

def test_metric_families_render_as_valid_exposition():
    from flexflow_tpu.obs import validate_exposition
    from flexflow_tpu.obs.registry import REGISTRY

    cfg = _config()
    unity_optimize(Graph(_mlp(cfg).ops), cfg, TpuPodModel(8), 64, 8)
    cfg2 = _config()
    unity_optimize(Graph(_mlp(cfg2).ops), cfg2, TpuPodModel(8), 64, 8)
    cfg3 = _config(n_devices=4)
    unity_optimize(Graph(_mlp(cfg3).ops), cfg3, TpuPodModel(4), 64, 4)
    fams = validate_exposition(REGISTRY.render())
    for fam in ("ff_search_cache_hits_total", "ff_search_cache_misses_total",
                "ff_search_cache_evictions_total",
                "ff_search_warm_starts_total", "ff_search_wall_time_ms"):
        assert fam in fams, (fam, sorted(fams))
