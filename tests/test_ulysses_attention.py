"""Ulysses (all-to-all) sequence parallelism numerical tests on the 8-device
CPU mesh: outputs and gradients must match full (single-chip) attention —
the second sequence/context-parallel design next to ring attention."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.core.machine import make_mesh
from flexflow_tpu.kernels.ulysses_attention import ulysses_attention_sharded


def full_attention(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    B, L, H, D = 2, 32, 8, 8  # H divisible by the 8-way seq axis
    q = rng.randn(B, L, H, D).astype(np.float32)
    k = rng.randn(B, L, H, D).astype(np.float32)
    v = rng.randn(B, L, H, D).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("use_flash", [False, True])
def test_ulysses_matches_full(qkv, causal, use_flash):
    q, k, v = qkv
    mesh = make_mesh({"seq": 8})

    @jax.jit
    def uly(q, k, v):
        return ulysses_attention_sharded(q, k, v, mesh, "seq", causal=causal,
                                         use_flash=use_flash,
                                         interpret=use_flash)

    out = uly(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_gradients_match(qkv):
    q, k, v = qkv
    mesh = make_mesh({"seq": 8})

    def loss_uly(q, k, v):
        out = ulysses_attention_sharded(q, k, v, mesh, "seq", causal=True)
        return jnp.sum(out * out)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    gu = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_ulysses_head_divisibility_error(qkv):
    q, k, v = qkv
    mesh = make_mesh({"seq": 8})
    bad_q = q[:, :, :6]  # 6 heads not divisible by 8
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(bad_q, k[:, :, :6], v[:, :, :6], mesh, "seq")


def test_attention_op_ulysses_mode_trains():
    """FFModel attention with sequence_parallel_mode='ulysses' trains on a
    dp x seq mesh."""
    import flexflow_tpu as ff

    config = ff.FFConfig()
    config.batch_size = 4
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    x = model.create_tensor([4, 16, 32])
    attn = model.multihead_attention(
        x, x, x, 32, 8, sequence_parallel=True,
        sequence_parallel_mode="ulysses", name="attn")
    model.softmax(model.dense(attn, 4))
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
        parallel_axes={"data": 2, "seq": 4},
    )
    xs = np.random.RandomState(0).randn(4, 16, 32).astype(np.float32)
    ys = np.zeros((4, 16, 1), dtype=np.int32)
    hist = model.fit([xs], ys, batch_size=4, epochs=2)
    assert np.isfinite(hist[-1]["loss"])
    assert hist[-1]["loss"] <= hist[0]["loss"] + 1e-3
