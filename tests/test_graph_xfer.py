"""Loaded substitution rules as EXECUTABLE GraphXfer rewrites.

VERDICT r3 item 4: the rule-file loader must instantiate real source→target
rewrites (reference: substitution_loader.h:94-187 → GraphXfer::create_xfers,
substitution.h:119-121), not just a TP-degree menu.
"""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.ffconst import CompMode, OpType
from flexflow_tpu.runtime.executor import Executor
from flexflow_tpu.search.graph_xfer import GraphXfer, xfers_from_rules
from flexflow_tpu.search.substitution import SEARCH_RULES
from flexflow_tpu.search.substitution_loader import load_substitution_file

from tests.test_substitution_loader import VENDORED_RULES  # noqa: E402

RULES_PATH = "substitutions/tp_rules.json"


def _linear_model():
    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    t = model.create_tensor([4, 6], ff.DataType.DT_FLOAT)
    out = model.dense(t, 8, name="lin")
    return model, config


def test_loaded_rules_build_supported_xfers():
    rules = load_substitution_file(RULES_PATH)
    xfers = xfers_from_rules(rules)
    assert xfers, "no loaded rule produced an executable xfer"
    assert any("partition_linear_combine" in n for n in xfers)


def test_xfer_rewrites_graph_handwritten_rules_do_not_cover():
    """A bare LINEAR: no hand-written trade-off rule matches it, but the
    loaded replicate-linear-combine rule does — and its application inserts
    real parallel ops."""
    model, _ = _linear_model()
    g = Graph(model.ops)
    # hand-written trade-off rules: nothing to do on this graph
    for fn in SEARCH_RULES.values():
        assert fn(g) == []
    rules = load_substitution_file(RULES_PATH)
    xfers = xfers_from_rules(rules)
    name = next(n for n in xfers if "partition_linear_combine_d2" in n)
    apps = xfers[name](g)
    assert len(apps) == 1
    apps[0].apply()
    types = [op.op_type for op in g.topo_order()]
    assert OpType.REPLICATE in types and OpType.COMBINE in types
    # the linear survived (weights reused), wired through the replicate
    lin = next(op for op in g.ops.values() if op.name == "lin")
    assert lin.inputs[0].owner_op.op_type == OpType.REPLICATE
    comb = next(op for op in g.ops.values()
                if op.op_type == OpType.COMBINE)
    assert comb.params["degree"] == 2 and comb.params["dim"] == 1


def test_xfer_preserves_numerics():
    """Rewritten graph computes the identical function (parallel ops are
    identity on values; the linear keeps its weights)."""
    import jax

    m1, config = _linear_model()
    g1 = Graph(m1.ops)
    m2, config2 = _linear_model()
    g2 = Graph(m2.ops)
    rules = load_substitution_file(RULES_PATH)
    xfers = xfers_from_rules(rules)
    name = next(n for n in xfers if "partition_linear_combine_d2" in n)
    xfers[name](g2)[0].apply()

    ex1 = Executor(g1, config)
    ex2 = Executor(g2, config2)
    p1, s1 = ex1.init_params(jax.random.PRNGKey(0))
    p2, s2 = ex2.init_params(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
    inp1 = {g1.topo_order()[0].name: x}
    inp2 = {g2.topo_order()[0].name: x}
    v1, _, _ = ex1.forward_values(p1, s1, inp1, None,
                                  CompMode.COMP_MODE_INFERENCE)
    v2, _, _ = ex2.forward_values(p2, s2, inp2, None,
                                  CompMode.COMP_MODE_INFERENCE)
    out1 = v1[g1.topo_order()[-1].outputs[0].guid]
    out2 = v2[g2.resolve_tensor(g2.topo_order()[-1].outputs[0]).guid]
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-5)


def test_xfer_degree_feasibility():
    """Every application any xfer offers applies cleanly (the feasibility
    check filters degree/shape mismatches at match time)."""
    rules = load_substitution_file(RULES_PATH)
    xfers = xfers_from_rules(rules)
    for n, fn in xfers.items():
        g = Graph(_linear_model()[0].ops)
        for app in fn(g):
            app.apply()
            g.topo_order()  # still a DAG


def test_xfers_excluded_from_greedy_fixed_point():
    """Trade-off xfers must NOT diverge the greedy apply_substitutions loop
    (each application re-matches its own output); they are joint-search
    actions only."""
    from flexflow_tpu.search.substitution import apply_substitutions

    model, _ = _linear_model()
    g = Graph(model.ops)
    n_before = len(g.ops)
    rules = load_substitution_file(RULES_PATH)
    applied = apply_substitutions(g, xfers_from_rules(rules))
    assert applied == [] and len(g.ops) == n_before


def test_xfer_does_not_stack_on_own_output():
    """Applying an xfer once removes the site from its own match set."""
    model, _ = _linear_model()
    g = Graph(model.ops)
    rules = load_substitution_file(RULES_PATH)
    xfers = xfers_from_rules(rules)
    name = next(n for n in xfers if "partition_linear_combine_d2" in n)
    apps = xfers[name](g)
    assert len(apps) == 1
    apps[0].apply()
    assert xfers[name](g) == []


def test_osdi_rule_file_weight_semantics():
    """The full 640-rule OSDI file compiles into executable xfers, and
    TASO's shared-weight patterns (two linears referencing ONE weight
    external) correctly do NOT match graphs whose layers hold distinct
    weights — the binding-consistency check, not an arity accident."""
    rules = load_substitution_file(VENDORED_RULES)
    xfers = xfers_from_rules(rules)
    assert len(xfers) > 200  # most of the 640 compile to executable form
    config = ff.FFConfig()
    config.batch_size = 8
    m = ff.FFModel(config)
    t = m.create_tensor([8, 32], ff.DataType.DT_FLOAT)
    a = m.dense(t, 16, name="branch_a")
    b = m.dense(t, 16, name="branch_b")
    m.softmax(m.concat([a, b], 1, name="cat"))
    g = Graph(m.ops)
    # distinct weights: the shared-weight concat-fusion family must not fire
    assert all(fn(g) == [] for fn in xfers.values())


def test_xfer_joint_search_integration():
    """The joint search sees loaded xfers as actions and compile() runs end
    to end with a TASO rule file + search budget."""
    config = ff.FFConfig()
    config.num_devices = 2
    config.batch_size = 4
    config.search_budget = 4
    config.substitution_json_path = RULES_PATH
    model = ff.FFModel(config)
    t = model.create_tensor([4, 6], ff.DataType.DT_FLOAT)
    h = model.dense(t, 8, name="l1")
    model.softmax(model.dense(h, 4, name="l2"))
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.1),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[])
    x = np.random.RandomState(0).randn(8, 6).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, (8, 1)).astype(np.int32)
    h = model.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(h[-1]["loss"])
