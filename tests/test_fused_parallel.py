"""FusedParallelOp: descriptor-chain composition applied as one reshard
(reference: src/parallel_ops/fused_parallel_op.cc)."""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.ffconst import OpType
from flexflow_tpu.search.substitution import (
    apply_substitutions,
    rule_fuse_parallel_ops,
)


def _mlp_with(chain_builder, batch=8, feats=16):
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    x = model.create_tensor([batch, feats])
    t = chain_builder(model, x)
    model.softmax(model.dense(t, 4))
    return model, x


def _fit_briefly(model):
    rs = np.random.RandomState(0)
    data = rs.randn(16, 16).astype(np.float32)
    labels = rs.randint(0, 4, size=(16, 1)).astype(np.int32)
    model.compile(loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  parallel_axes={"data": 2})
    return model.fit(data, labels, epochs=1)


def test_fused_partition_then_combine_shape():
    """partition(dim 0) then combine(dim 0) composes to no sharding."""
    model, _ = _mlp_with(lambda m, x: m.fused_parallel(x, [
        {"type": "partition", "dim": 0, "degree": 2, "axis": "data"},
        {"type": "combine", "dim": 0},
    ]))
    _fit_briefly(model)
    fused = next(op for op in model.ops if op.op_type == OpType.FUSED_PARALLEL)
    assert all(d.degree == 1 for d in fused.outputs[0].parallel_shape.dims)


def test_fused_partition_shape_matches_standalone():
    """A single-partition fused chain shards identically to RepartitionOp."""
    fused_model, _ = _mlp_with(lambda m, x: m.fused_parallel(x, [
        {"type": "partition", "dim": 0, "degree": 2, "axis": "data"},
    ]))
    plain_model, _ = _mlp_with(lambda m, x: m.repartition(x, 0, 2, axis="data"))
    _fit_briefly(fused_model)
    _fit_briefly(plain_model)
    f = next(op for op in fused_model.ops
             if op.op_type == OpType.FUSED_PARALLEL).outputs[0].parallel_shape
    p = next(op for op in plain_model.ops
             if op.op_type == OpType.REPARTITION).outputs[0].parallel_shape
    assert [(d.size, d.degree, d.axis) for d in f.dims] == \
        [(d.size, d.degree, d.axis) for d in p.dims]


def test_replicate_descriptor_clears_sharding():
    model, _ = _mlp_with(lambda m, x: m.fused_parallel(x, [
        {"type": "partition", "dim": 0, "degree": 2, "axis": "data"},
        {"type": "replicate"},
    ]))
    _fit_briefly(model)
    fused = next(op for op in model.ops if op.op_type == OpType.FUSED_PARALLEL)
    assert all(d.degree == 1 and d.axis is None
               for d in fused.outputs[0].parallel_shape.dims)


def test_unknown_descriptor_rejected():
    model, _ = _mlp_with(lambda m, x: m.fused_parallel(x, [
        {"type": "shuffle", "dim": 0, "degree": 2},
    ]))
    with pytest.raises(ValueError, match="unknown parallel descriptor"):
        _fit_briefly(model)


def test_rule_collapses_parallel_chain():
    """repartition -> combine -> replicate collapses (to fixed point) into
    ONE FusedParallelOp carrying all three descriptors in order."""
    model, _ = _mlp_with(
        lambda m, x: m.replicate(m.combine(m.repartition(x, 0, 2), 0)))
    graph = Graph(model.ops)
    applied = apply_substitutions(
        graph, {"fuse_parallel_ops": rule_fuse_parallel_ops})
    assert len(applied) == 2, applied
    fused = [op for op in graph.ops.values()
             if op.op_type == OpType.FUSED_PARALLEL]
    assert len(fused) == 1
    assert [d["type"] for d in fused[0].params["descriptors"]] == \
        ["partition", "combine", "replicate"]
    assert not any(op.op_type in (OpType.REPARTITION, OpType.COMBINE,
                                  OpType.REPLICATE)
                   for op in graph.ops.values())


def test_fused_chain_trains_like_unfused():
    """The fused chain is a value identity: training histories match the
    unfused chain exactly (same init seed, same data)."""
    def chain(m, x):
        return m.combine(m.repartition(x, 0, 2, axis="data"), 0)

    def fused(m, x):
        return m.fused_parallel(x, [
            {"type": "partition", "dim": 0, "degree": 2, "axis": "data"},
            {"type": "combine", "dim": 0},
        ])

    h1 = _fit_briefly(_mlp_with(chain)[0])
    h2 = _fit_briefly(_mlp_with(fused)[0])
    assert h1[-1]["loss"] == pytest.approx(h2[-1]["loss"], rel=1e-5)
