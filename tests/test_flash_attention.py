"""Pallas flash-attention kernel vs naive attention (interpret mode on CPU;
align-test strategy per SURVEY.md §4 applied to kernels)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.kernels.flash_attention import (
    attention_reference,
    flash_attention,
)


def _rand(shape, seed):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "b,lq,lk,h,d,bq,bk",
    [
        (2, 64, 64, 2, 32, 32, 32),     # even blocks
        (1, 40, 56, 2, 16, 32, 32),     # ragged lengths -> padding paths
        (2, 128, 128, 4, 64, 128, 128), # single block pair
    ],
)
def test_flash_forward_matches_reference(causal, b, lq, lk, h, d, bq, bk):
    q, k, v = _rand((b, lq, h, d), 0), _rand((b, lk, h, d), 1), _rand((b, lk, h, d), 2)
    out = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk,
                          interpret=True)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    b, l, h, d = 1, 48, 2, 16  # ragged vs 32-blocks: exercises padded bwd
    q, k, v = _rand((b, l, h, d), 3), _rand((b, l, h, d), 4), _rand((b, l, h, d), 5)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                              interpret=True)
        return jnp.sum(out * jnp.cos(out))

    def loss_ref(q, k, v):
        out = attention_reference(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


def test_flash_in_jit_and_vjp_composes():
    b, l, h, d = 2, 32, 2, 16
    q, k, v = _rand((b, l, h, d), 6), _rand((b, l, h, d), 7), _rand((b, l, h, d), 8)
    fn = jax.jit(functools.partial(flash_attention, interpret=True))
    out = fn(q, k, v)
    assert out.shape == (b, l, h, d)
    assert np.isfinite(np.asarray(out)).all()


def test_bert_train_step_through_flash():
    """Full compile+fit with the attention op forced onto the Pallas kernel
    (interpret mode on CPU)."""
    import flexflow_tpu as ff

    batch, seq, hidden, heads = 2, 16, 32, 4
    config = ff.FFConfig()
    config.batch_size = batch
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, seq, hidden])
    t = model.multihead_attention(inp, inp, inp, hidden, heads, use_flash=True)
    t = model.dense(t, 2)
    model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    x = np.random.RandomState(0).randn(batch, seq, hidden).astype(np.float32)
    y = np.zeros((batch, seq, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=batch, epochs=2)
    assert np.isfinite(hist[-1]["loss"])
    assert hist[-1]["loss"] <= hist[0]["loss"] + 1e-6


def test_flash_vs_einsum_attention_op_parity():
    """The attention op produces the same output with use_flash on and off."""
    import flexflow_tpu as ff

    batch, seq, hidden, heads = 2, 24, 32, 4
    preds = []
    for use_flash in (False, True):
        config = ff.FFConfig()
        config.batch_size = batch
        config.allow_mixed_precision = False
        model = ff.FFModel(config)
        inp = model.create_tensor([batch, seq, hidden])
        model.multihead_attention(inp, inp, inp, hidden, heads,
                                  use_flash=use_flash, name="attn")
        model.compile(
            optimizer=ff.SGDOptimizer(model, lr=0.0),
            loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
            metrics=[],
        )
        x = np.random.RandomState(1).randn(batch, seq, hidden).astype(np.float32)
        preds.append(model.predict([x]))
    np.testing.assert_allclose(preds[0], preds[1], rtol=2e-5, atol=2e-5)
