"""Pallas flash-attention kernel vs naive attention (interpret mode on CPU;
align-test strategy per SURVEY.md §4 applied to kernels)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.kernels.flash_attention import (
    attention_reference,
    flash_attention,
)


def _rand(shape, seed):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "b,lq,lk,h,d,bq,bk",
    [
        (2, 64, 64, 2, 32, 32, 32),     # even blocks
        (1, 40, 56, 2, 16, 32, 32),     # ragged lengths -> padding paths
        (2, 128, 128, 4, 64, 128, 128), # single block pair
    ],
)
def test_flash_forward_matches_reference(causal, b, lq, lk, h, d, bq, bk):
    q, k, v = _rand((b, lq, h, d), 0), _rand((b, lk, h, d), 1), _rand((b, lk, h, d), 2)
    out = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk,
                          interpret=True)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    b, l, h, d = 1, 48, 2, 16  # ragged vs 32-blocks: exercises padded bwd
    q, k, v = _rand((b, l, h, d), 3), _rand((b, l, h, d), 4), _rand((b, l, h, d), 5)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                              interpret=True)
        return jnp.sum(out * jnp.cos(out))

    def loss_ref(q, k, v):
        out = attention_reference(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


def test_flash_in_jit_and_vjp_composes():
    b, l, h, d = 2, 32, 2, 16
    q, k, v = _rand((b, l, h, d), 6), _rand((b, l, h, d), 7), _rand((b, l, h, d), 8)
    fn = jax.jit(functools.partial(flash_attention, interpret=True))
    out = fn(q, k, v)
    assert out.shape == (b, l, h, d)
    assert np.isfinite(np.asarray(out)).all()


def test_bert_train_step_through_flash():
    """Full compile+fit with the attention op forced onto the Pallas kernel
    (interpret mode on CPU)."""
    import flexflow_tpu as ff

    batch, seq, hidden, heads = 2, 16, 32, 4
    config = ff.FFConfig()
    config.batch_size = batch
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, seq, hidden])
    t = model.multihead_attention(inp, inp, inp, hidden, heads, use_flash=True)
    t = model.dense(t, 2)
    model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    x = np.random.RandomState(0).randn(batch, seq, hidden).astype(np.float32)
    y = np.zeros((batch, seq, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=batch, epochs=2)
    assert np.isfinite(hist[-1]["loss"])
    assert hist[-1]["loss"] <= hist[0]["loss"] + 1e-6


@pytest.mark.parametrize("causal", [False, True])
def test_bhld_layout_matches_blhd(causal):
    """layout="bhld" (projection-fused layout, no swapaxes) is numerically
    identical to the default layout on the same logical tensors."""
    b, l, h, d = 2, 48, 2, 16
    q, k, v = _rand((b, l, h, d), 9), _rand((b, l, h, d), 10), _rand((b, l, h, d), 11)

    def loss(fn):
        def wrapped(q, k, v):
            return jnp.sum(jnp.sin(fn(q, k, v)))
        return wrapped

    f_blhd = loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32, interpret=True))
    f_bhld = loss(lambda q, k, v: jnp.swapaxes(flash_attention(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
        causal=causal, block_q=32, block_k=32, interpret=True,
        layout="bhld"), 1, 2))
    np.testing.assert_allclose(np.asarray(f_blhd(q, k, v)),
                               np.asarray(f_bhld(q, k, v)),
                               rtol=2e-5, atol=2e-5)
    g1 = jax.grad(f_blhd, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_bhld, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [32,   # multi-block: online softmax path
                                   64])  # single padded block: plain softmax
def test_packed_kernel_matches_reference(causal, block):
    """flash_attention_packed on (b, l, h*d) matches the naive oracle on the
    equivalent (b, l, h, d) tensors — values and input gradients."""
    from flexflow_tpu.kernels.flash_attention import flash_attention_packed

    b, l, h, d = 2, 48, 4, 16
    q, k, v = _rand((b, l, h, d), 12), _rand((b, l, h, d), 13), _rand((b, l, h, d), 14)

    def loss_packed(q, k, v):
        out = flash_attention_packed(
            q.reshape(b, l, h * d), k.reshape(b, l, h * d),
            v.reshape(b, l, h * d), h, causal=causal, block_q=block,
            block_k=block, interpret=True)
        return jnp.sum(jnp.sin(out.reshape(b, l, h, d)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=causal)))

    np.testing.assert_allclose(np.asarray(loss_packed(q, k, v)),
                               np.asarray(loss_ref(q, k, v)),
                               rtol=2e-5, atol=2e-5)
    gp = jax.grad(loss_packed, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(gp, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


def test_flash_vs_einsum_attention_op_grads_parity():
    """Weight gradients agree between the einsum path and the flash (bhld)
    path — guards the projection-layout restructuring in the op's lower()."""
    import flexflow_tpu as ff

    batch, seq, hidden, heads = 2, 24, 32, 4
    grads = []
    for use_flash in (False, True):
        config = ff.FFConfig()
        config.batch_size = batch
        config.allow_mixed_precision = False
        model = ff.FFModel(config)
        inp = model.create_tensor([batch, seq, hidden])
        model.multihead_attention(inp, inp, inp, hidden, heads,
                                  use_flash=use_flash, name="attn")
        model.compile(
            optimizer=ff.SGDOptimizer(model, lr=0.0),
            loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
            metrics=[],
        )
        x = np.random.RandomState(1).randn(batch, seq, hidden).astype(np.float32)
        y = np.random.RandomState(2).randn(batch, seq, hidden).astype(np.float32)
        key = jax.random.PRNGKey(0)
        inputs = {model.input_ops[0].name: model.executor.shard_batch(x)}
        grads.append(model._grad_step(model.params, model.state, inputs,
                                      jnp.asarray(y), key))
    flat0 = jax.tree_util.tree_leaves(grads[0])
    flat1 = jax.tree_util.tree_leaves(grads[1])
    assert len(flat0) == len(flat1) and len(flat0) > 0
    for a, b_ in zip(flat0, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_config_flash_block_sizes_reach_kernel():
    """FFConfig.flash_block_q/k plumb through to the packed kernel: a
    non-default block size still reproduces einsum-path numerics."""
    import flexflow_tpu as ff

    batch, seq, hidden, heads = 2, 48, 32, 4
    preds = []
    for use_flash, blocks in ((False, None), (True, 16)):
        config = ff.FFConfig()
        config.batch_size = batch
        config.allow_mixed_precision = False
        if blocks:
            config.flash_block_q = blocks
            config.flash_block_k = blocks
        model = ff.FFModel(config)
        inp = model.create_tensor([batch, seq, hidden])
        model.multihead_attention(inp, inp, inp, hidden, heads,
                                  use_flash=use_flash, name="attn")
        model.compile(
            optimizer=ff.SGDOptimizer(model, lr=0.0),
            loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
            metrics=[],
        )
        x = np.random.RandomState(5).randn(batch, seq, hidden).astype(np.float32)
        preds.append(model.predict([x]))
    np.testing.assert_allclose(preds[0], preds[1], rtol=2e-5, atol=2e-5)


def test_flash_attention_tp_heads_matches_single_device(tmp_path):
    """use_flash=True under a model=2 mesh (heads tensor-parallel) matches
    single-device numerics — regression for the packed path's TP guard:
    the packed (e, h*d) weight reshape would merge the sharded heads axis,
    so TP meshes must stay on the head-separated kernels."""
    import json

    import flexflow_tpu as ff
    from flexflow_tpu.ffconst import CompMode

    batch, seq, hidden, heads = 2, 24, 32, 4
    x = np.random.RandomState(3).randn(batch, seq, hidden).astype(np.float32)

    def build(import_file=None):
        config = ff.FFConfig()
        config.batch_size = batch
        config.allow_mixed_precision = False
        if import_file:
            config.import_strategy_file = import_file
        model = ff.FFModel(config)
        inp = model.create_tensor([batch, seq, hidden])
        t = model.multihead_attention(inp, inp, inp, hidden, heads,
                                      use_flash=True, name="attn")
        model.final_tensor = t
        model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                      loss_type=ff.LossType.LOSS_IDENTITY)
        return model, t

    single, out_s = build()
    feeds = {single.input_ops[0].name: x}
    vals, _, _ = single.executor.forward_values(
        single.params, single.state, feeds, None,
        CompMode.COMP_MODE_INFERENCE)
    ref = np.asarray(vals[out_s.guid])

    strat = {
        "mesh_axes": {"model": 2},
        "cost_us": 0.0, "memory_bytes": 0.0,
        "ops": {"attn": {"dp": 1, "tp": 2, "ep": 1, "ap": 1,
                         "tp_row": False}},
    }
    path = str(tmp_path / "strategy.json")
    with open(path, "w") as f:
        json.dump(strat, f)
    sharded, out_p = build(import_file=path)
    feeds = {sharded.input_ops[0].name: x}
    vals_p, _, _ = sharded.executor.forward_values(
        sharded.params, sharded.state, feeds, None,
        CompMode.COMP_MODE_INFERENCE)
    np.testing.assert_allclose(np.asarray(vals_p[out_p.guid]), ref,
                               rtol=2e-5, atol=2e-5)


def test_flash_vs_einsum_attention_op_parity():
    """The attention op produces the same output with use_flash on and off."""
    import flexflow_tpu as ff

    batch, seq, hidden, heads = 2, 24, 32, 4
    preds = []
    for use_flash in (False, True):
        config = ff.FFConfig()
        config.batch_size = batch
        config.allow_mixed_precision = False
        model = ff.FFModel(config)
        inp = model.create_tensor([batch, seq, hidden])
        model.multihead_attention(inp, inp, inp, hidden, heads,
                                  use_flash=use_flash, name="attn")
        model.compile(
            optimizer=ff.SGDOptimizer(model, lr=0.0),
            loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
            metrics=[],
        )
        x = np.random.RandomState(1).randn(batch, seq, hidden).astype(np.float32)
        preds.append(model.predict([x]))
    np.testing.assert_allclose(preds[0], preds[1], rtol=2e-5, atol=2e-5)
