"""torch.fx importer tests (reference test model: tests/align +
examples/python/pytorch)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn
import torch.nn.functional as F

import flexflow_tpu as ff
from flexflow_tpu.torch import PyTorchModel, fx


def make_config(batch=8):
    c = ff.FFConfig()
    c.batch_size = batch
    c.num_devices = 1
    c.allow_mixed_precision = False  # exact parity vs torch f32
    return c


class MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(20, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        x = F.relu(self.fc1(x))
        return self.fc2(x)


class CNN(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 8, 3, padding=1)
        self.pool = nn.MaxPool2d(2, 2)
        self.fc = nn.Linear(8 * 4 * 4, 5)

    def forward(self, x):
        x = self.pool(F.relu(self.conv1(x)))
        x = torch.flatten(x, 1)
        return self.fc(x)


class Residual(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 16)
        self.ln = nn.LayerNorm(16)

    def forward(self, x):
        h = self.fc1(x)
        return self.ln(x + h)


def build_and_compare(module, x_np, input_dims, dtype=ff.DataType.DT_FLOAT,
                      atol=1e-4):
    """Apply the fx import, transfer weights, compare forward vs torch."""
    module.eval()
    config = make_config(batch=x_np.shape[0])
    model = ff.FFModel(config)
    t = model.create_tensor(list(input_dims), dtype)
    pt = PyTorchModel(module)
    outs = pt.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.0),
        loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[],
    )
    n = pt.transfer_weights(model)
    assert n > 0
    ours = model.predict(x_np)
    with torch.no_grad():
        theirs = module(torch.from_numpy(x_np)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=1e-4)


def test_mlp_numerical_parity():
    x = np.random.RandomState(0).randn(8, 20).astype(np.float32)
    build_and_compare(MLP(), x, (8, 20))


def test_cnn_numerical_parity():
    x = np.random.RandomState(1).randn(8, 3, 8, 8).astype(np.float32)
    build_and_compare(CNN(), x, (8, 3, 8, 8))


def test_residual_layernorm_parity():
    x = np.random.RandomState(2).randn(8, 16).astype(np.float32)
    build_and_compare(Residual(), x, (8, 16))


def test_ff_file_roundtrip(tmp_path):
    path = str(tmp_path / "mlp.ff")
    fx.torch_to_flexflow(MLP(), path)
    config = make_config()
    model = ff.FFModel(config)
    t = model.create_tensor([8, 20], ff.DataType.DT_FLOAT)
    outs = PyTorchModel(path).apply(model, [t])
    assert outs[0].dims == (8, 4)
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.01),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[ff.MetricsType.METRICS_ACCURACY])
    x = np.random.RandomState(3).randn(16, 20).astype(np.float32)
    y = np.random.RandomState(4).randint(0, 4, size=(16, 1)).astype(np.int32)
    hist = model.fit([x], y, epochs=1)
    assert len(hist) == 1


def test_embedding_and_methods():
    class Tok(nn.Module):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(50, 8)
            self.fc = nn.Linear(8, 4)

        def forward(self, x):
            h = self.emb(x)
            h = h.mean([1])
            return self.fc(h)

    module = Tok().eval()
    config = make_config()
    model = ff.FFModel(config)
    t = model.create_tensor([8, 6], ff.DataType.DT_INT32)
    pt = PyTorchModel(module)
    outs = pt.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    pt.transfer_weights(model)
    x = np.random.RandomState(5).randint(0, 50, size=(8, 6)).astype(np.int32)
    ours = model.predict(x)
    with torch.no_grad():
        theirs = module(torch.from_numpy(x.astype(np.int64))).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)


def test_scalar_left_sub_div_parity():
    class M(nn.Module):
        def forward(self, x):
            return 1.0 - x + 2.0 / (x * x + 1.0)

    x = np.random.RandomState(6).rand(8, 10).astype(np.float32) + 0.5
    module = M().eval()
    config = make_config()
    model = ff.FFModel(config)
    t = model.create_tensor([8, 10], ff.DataType.DT_FLOAT)
    pt = PyTorchModel(module)
    outs = pt.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    ours = model.predict(x)
    with torch.no_grad():
        theirs = module(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5, rtol=1e-5)


def test_split_chunk_size_semantics():
    class M(nn.Module):
        def forward(self, x):
            a, b, c = torch.split(x, 2, dim=1)  # chunk SIZE 2 over dim of 6
            return a + b + c

    x = np.random.RandomState(7).rand(8, 6).astype(np.float32)
    module = M().eval()
    config = make_config()
    model = ff.FFModel(config)
    t = model.create_tensor([8, 6], ff.DataType.DT_FLOAT)
    outs = PyTorchModel(module).apply(model, [t])
    assert outs[0].dims == (8, 2)
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    ours = model.predict(x)
    with torch.no_grad():
        theirs = module(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)


def test_global_mean_reduction():
    class M(nn.Module):
        def forward(self, x):
            return x - x.mean()

    x = np.random.RandomState(8).rand(8, 5).astype(np.float32)
    module = M().eval()
    config = make_config()
    model = ff.FFModel(config)
    t = model.create_tensor([8, 5], ff.DataType.DT_FLOAT)
    outs = PyTorchModel(module).apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    ours = model.predict(x)
    with torch.no_grad():
        theirs = module(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)


def test_multihead_attention_parity():
    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.attn = nn.MultiheadAttention(16, 4, batch_first=True)

        def forward(self, x):
            out, _ = self.attn(x, x, x)
            return out

    x = np.random.RandomState(9).randn(4, 6, 16).astype(np.float32)
    module = M().eval()
    config = make_config(batch=4)
    model = ff.FFModel(config)
    t = model.create_tensor([4, 6, 16], ff.DataType.DT_FLOAT)
    pt = PyTorchModel(module)
    outs = pt.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    assert pt.transfer_weights(model) >= 8
    ours = model.predict(x)
    with torch.no_grad():
        theirs = module(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)


def test_size_arithmetic_view():
    class M(nn.Module):
        def forward(self, x):
            return x.view(x.size(0), x.size(1) * x.size(2))

    x = np.random.RandomState(10).rand(4, 3, 5).astype(np.float32)
    module = M().eval()
    config = make_config(batch=4)
    model = ff.FFModel(config)
    t = model.create_tensor([4, 3, 5], ff.DataType.DT_FLOAT)
    outs = PyTorchModel(module).apply(model, [t])
    assert outs[0].dims == (4, 15)


def test_squeeze_semantics():
    class M(nn.Module):
        def forward(self, x):
            return x.unsqueeze(1).squeeze() + x.squeeze(1)  # squeeze(1) no-op

    x = np.random.RandomState(11).rand(4, 6).astype(np.float32)
    module = M().eval()
    config = make_config(batch=4)
    model = ff.FFModel(config)
    t = model.create_tensor([4, 6], ff.DataType.DT_FLOAT)
    outs = PyTorchModel(module).apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  metrics=[])
    ours = model.predict(x)
    with torch.no_grad():
        theirs = module(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)


def test_setitem_aliasing_matches_torch():
    """__setitem__ never rebinds in Python, so downstream uses of the
    ORIGINAL tensor must see the mutation (fold mutates the stored array
    in place, matching eager semantics)."""

    class MaskAdd(nn.Module):
        def forward(self, x):
            m = torch.zeros(4)
            m[0] = 1.0
            return x + m  # references the original zeros node

    m = MaskAdd().eval()
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    config = make_config(2)
    model = ff.FFModel(config)
    t = model.create_tensor([2, 4])
    pt = PyTorchModel(m)
    outs = pt.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    ours = model.predict(x)
    with torch.no_grad():
        theirs = m(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-6)
