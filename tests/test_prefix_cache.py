"""Multi-tenant KV reuse (ISSUE 6): the hash-addressed prefix cache,
copy-on-write page semantics, chunked prefill, and admission's sharing
credit.

The decisive properties:
 - chunked prefill is TOKEN-IDENTICAL to the one-shot path (and to the
   lockstep GenerativeSession) for the same prompt, at any chunk size;
 - a prefix-cache HIT decodes token-identically to a cold run — shared
   pages are immutable, so no amount of divergent co-traffic can leak
   into another request's tokens;
 - refcounts block eviction while any live sequence shares an entry, and
   LRU reclaims only refcount-0 pages.
"""
import numpy as np
import pytest

from flexflow_tpu.serving.generate import GenerativeSession
from flexflow_tpu.serving.sched import (AdmissionController,
                                        ContinuousBatcher, PagedKVPool,
                                        PrefixCache, RequestTooLarge,
                                        prefix_route_chain,
                                        prefix_route_key)
from tests.conftest import module_xla_cache
from tests.test_generate import _build_lm

# module-scoped XLA compilation cache — see conftest.module_xla_cache
_xla_cache = pytest.fixture(scope="module", autouse=True)(module_xla_cache)


@pytest.fixture(scope="module")
def lm():
    """One compiled LM shared by the module (b=2, window=12)."""
    return _build_lm(2, 12)


def _prompts(lens, seed=0, vocab=50):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=(n,)).astype(np.int32) for n in lens]


# ---------------------------------------------------------------------
# prefix_route_key: the fleet routing address (ISSUE 12 satellite)
# ---------------------------------------------------------------------
def test_prefix_route_key_identical_across_replicas():
    """Routing correctness rests on every replica (and the router)
    computing the SAME key for the same prompt, with no shared state:
    the chain must equal the PrefixCache's own internal addresses, so a
    routed request really does find its pages on the target replica."""
    toks = np.arange(1, 15, dtype=np.int32)  # 3 full pages at size 4
    chain = prefix_route_chain(toks, page_size=4)
    assert len(chain) == 3
    # two independent "replicas": separate cache instances, same prompt
    c1 = PrefixCache(capacity_pages=8, page_size=4)
    c2 = PrefixCache(capacity_pages=8, page_size=4)
    for c in (c1, c2):
        assert c.insert(toks, toks.size, lambda pairs: None) == 3
    _, e1 = c1.match(toks)
    _, e2 = c2.match(toks)
    assert [e.key.hex() for e in e1] == chain
    assert [e.key.hex() for e in e2] == chain
    # pure function: recomputation and an independent caller agree
    assert prefix_route_chain(toks, page_size=4) == chain
    assert prefix_route_key(toks, page_size=4) == chain[0]
    assert prefix_route_key(toks, page_size=4, depth=2) == chain[1]
    assert prefix_route_key(toks, page_size=4, depth=99) == chain[-1]
    # prompts sharing a page-aligned prefix share exactly that chain
    other = np.concatenate([toks[:8], np.array([99, 98, 97, 96], np.int32)])
    assert prefix_route_chain(other, page_size=4)[:2] == chain[:2]
    assert prefix_route_chain(other, page_size=4)[2] != chain[2]
    # no full page -> no key (route by load instead)
    assert prefix_route_key(toks[:3], page_size=4) == ""
    # geometry is part of the address: a different page size must not
    # alias (the router enforces one fleet-wide page_size)
    assert prefix_route_key(toks, page_size=8) != chain[0]


# ---------------------------------------------------------------------
# PrefixCache units: match/insert/refcount/eviction
# ---------------------------------------------------------------------
def test_prefix_cache_insert_and_longest_match():
    c = PrefixCache(capacity_pages=8, page_size=4)
    toks = np.arange(1, 15, dtype=np.int32)  # 14 tokens = 3 full pages
    copies = []
    assert c.insert(toks, 14, copies.extend) == 3
    assert [b for b, _ in copies] == [0, 1, 2]
    assert c.pages_in_use() == 3 and c.entry_count() == 3
    # longest match walks the chain; a diverging block stops it
    assert c.match(toks)[0] == 12
    assert c.match(toks[:9])[0] == 8
    other = toks.copy()
    other[5] = 99  # diverges inside block 1
    assert c.match(other)[0] == 4
    # re-insert is idempotent (no new pages, ticks refreshed)
    assert c.insert(toks, 14, copies.extend) == 0
    assert len(copies) == 3


def test_prefix_cache_refcounts_pin_and_release():
    c = PrefixCache(capacity_pages=8, page_size=4)
    toks = np.arange(1, 14, dtype=np.int32)
    c.insert(toks, 13, lambda pairs: None)
    n, entries = c.acquire("s1", toks)
    assert n == 12 and [e.refcount for e in entries] == [1, 1, 1]
    # max_pages caps the match (the scheduler leaves >= 1 suffix token)
    n2, _ = c.acquire("s2", toks, max_pages=2)
    assert n2 == 8 and c.refcount_of(toks) == [2, 2, 1]
    with pytest.raises(ValueError, match="already holds pins"):
        c.acquire("s1", toks)
    c.release("s1")
    c.release("s1")  # idempotent
    assert c.refcount_of(toks) == [1, 1, 0]
    c.release("s2")
    assert c.refcount_of(toks) == [0, 0, 0]
    assert c.stats()["hits"] == 2 and c.stats()["pages_saved"] == 5


def test_prefix_cache_lru_evicts_only_refcount_zero():
    c = PrefixCache(capacity_pages=2, page_size=4)
    a = np.arange(1, 5, dtype=np.int32)
    b = np.arange(11, 15, dtype=np.int32)
    c.insert(a, 4, lambda *_: None)
    c.insert(b, 4, lambda *_: None)
    assert c.pages_in_use() == 2
    # 'a' is pinned by a live sequence; 'b' is LRU but unpinned
    c.acquire("s", a)
    d = np.arange(21, 25, dtype=np.int32)
    assert c.insert(d, 4, lambda *_: None) == 1  # evicted 'b'
    assert c.match(b)[0] == 0 and c.match(a)[0] == 4
    assert c.stats()["evictions"] == 1
    # everything pinned -> nothing evictable -> insert degrades to no-op
    c.acquire("s2", d)
    e = np.arange(31, 35, dtype=np.int32)
    assert c.insert(e, 4, lambda *_: None) == 0
    assert c.match(a)[0] == 4 and c.match(d)[0] == 4


def test_prefix_cache_cow_break_unshares_without_mutating():
    """A writer diverging inside shared pages severs ITS share from the
    containing block onward; the cached pages (and other readers) are
    untouched — the copy-on-write contract."""
    c = PrefixCache(capacity_pages=8, page_size=4)
    toks = np.arange(1, 14, dtype=np.int32)
    c.insert(toks, 13, lambda pairs: None)
    c.acquire("w", toks)   # the writer
    c.acquire("r", toks)   # an innocent reader
    assert c.shared_tokens("w") == 12
    assert c.cow_break("w", 6) == 2  # writes at pos 6 -> blocks 1,2 unshared
    assert c.shared_tokens("w") == 4
    assert c.refcount_of(toks) == [2, 1, 1]
    # the reader still matches the full chain: content never mutated
    assert c.match(toks)[0] == 12
    c.release("w")
    c.release("r")
    assert c.refcount_of(toks) == [0, 0, 0]


def test_pool_band_geometry_uses_full_pages_only():
    """Band pages must hold page_size REAL rows: a slot's partial tail
    page is unusable (packing it would clamp the device copy and corrupt
    the neighboring page — the bug this test pins)."""
    pool = PagedKVPool(2, 30, page_size=8, prefix_cache_pages=7)
    assert pool.pages_per_slot == 4       # sequences: ceil(30/8)
    assert pool.full_pages_per_slot == 3  # band packing: floor(30/8)
    assert pool.band_slots == 3           # ceil(7/3)
    seen = set()
    for p in range(7):
        slot, row = pool.band_coords(p)
        assert row + pool.page_size <= pool.max_len, (p, slot, row)
        seen.add((slot, row))
    assert len(seen) == 7  # no two pages alias
    # a pool whose slots can't hold one full page disables the cache
    assert PagedKVPool(1, 6, page_size=8, prefix_cache_pages=4).prefix is None


def test_pool_free_releases_prefix_pins():
    pool = PagedKVPool(2, 32, page_size=8, prefix_cache_pages=4)
    toks = np.arange(1, 20, dtype=np.int32)
    pool.prefix.insert(toks, 19, lambda pairs: None)
    pool.alloc("s", 19)
    pool.prefix.acquire("s", toks)
    assert pool.prefix.refcount_of(toks) == [1, 1]
    pool.free("s")
    assert pool.prefix.refcount_of(toks) == [0, 0]
    assert "prefix" in pool.stats()


# ---------------------------------------------------------------------
# Admission: sharing credit + windowless (chunked) mode
# ---------------------------------------------------------------------
def test_admission_credits_expected_sharing():
    pool = PagedKVPool(num_slots=1, max_len=32, page_size=4)
    adm = AdmissionController(pool, window=None, max_queue=8,
                              queue_pages_budget=6)
    # 24 worst-case tokens = 6 pages: fills the budget exactly when cold
    adm.admit("cold", 16, 8)
    with pytest.raises(Exception):
        adm.admit("cold2", 16, 8)
    adm.release("cold")
    # the same request with 4 expected shared pages costs only 2
    adm.admit("warm", 16, 8, shared_pages=4)
    assert adm.backlog_pages() == 2
    adm.admit("warm2", 16, 8, shared_pages=4)
    adm.release("warm")
    adm.release("warm2")
    # the credit never touches the static per-slot capacity check
    with pytest.raises(RequestTooLarge, match="cache capacity"):
        adm.admit("huge", 30, 8, shared_pages=100)


def test_admission_windowless_admits_long_prompts():
    pool = PagedKVPool(num_slots=1, max_len=64, page_size=4)
    adm = AdmissionController(pool, window=None, max_queue=4)
    adm.admit("long", 40, 8)  # longer than any typical model window
    adm.release("long")
    capped = AdmissionController(pool, window=12, max_queue=4)
    with pytest.raises(RequestTooLarge, match="prefill window"):
        capped.admit("long", 40, 8)


# ---------------------------------------------------------------------
# Chunked prefill: token parity + window-free prompts
# ---------------------------------------------------------------------
def test_chunked_prefill_token_parity_with_one_shot_and_lockstep(lm):
    """The same prompts through lockstep, one-shot continuous, and
    chunked continuous (awkward chunk size on purpose): identical greedy
    tokens everywhere."""
    prompts = _prompts([4, 7, 3], seed=0)
    session = GenerativeSession(lm, max_len=12)
    refs = [session.generate(p[None, :], 5)[0] for p in prompts]
    kw = dict(max_len=12, num_slots=2, page_size=4, max_queue=8,
              prefix_cache_pages=0)
    with ContinuousBatcher(lm, prefill_chunk_tokens=0, **kw) as cb:
        oneshot = [cb.submit(p, 5).result(timeout=300) for p in prompts]
    with ContinuousBatcher(lm, prefill_chunk_tokens=3, **kw) as cb:
        chunked = [cb.submit(p, 5).result(timeout=300) for p in prompts]
    for ref, a, b in zip(refs, oneshot, chunked):
        np.testing.assert_array_equal(a, np.asarray(ref))
        np.testing.assert_array_equal(b, np.asarray(ref))


def test_chunked_prefill_last_chunk_never_clamps(lm):
    """The final chunk always dispatches at FULL chunk width, so with a
    prompt ending near max_len its cache write would run past the array
    edge — and dynamic_update_slice silently CLAMPS the start index,
    shifting real prompt K/V rows (the bug this pins: chunk=7 on
    15-token prompts in a max_len=20 cache diverged from chunk=5 on 1 of
    4 prompts before the slack-row fix in _zero_small)."""
    prompts = _prompts([15, 15, 15, 15], seed=13)
    outs = {}
    for chunk in (5, 7):
        with ContinuousBatcher(lm, max_len=20, num_slots=2, page_size=4,
                               prefill_chunk_tokens=chunk,
                               prefix_cache_pages=0, max_queue=8) as cb:
            outs[chunk] = [cb.submit(p, 4).result(timeout=300)
                           for p in prompts]
    for a, b in zip(outs[5], outs[7]):
        np.testing.assert_array_equal(a, b)


def test_chunked_prefill_admits_prompt_longer_than_window(lm):
    """The model window is 12; a 15-token prompt one-shot would be a 400.
    Chunked mode admits it and chunk size does not change the tokens
    (chunking invariance is the only available reference: no other path
    can run this prompt)."""
    [p] = _prompts([15], seed=2)
    outs = {}
    for chunk in (4, 7):
        with ContinuousBatcher(lm, max_len=20, num_slots=2, page_size=4,
                               prefill_chunk_tokens=chunk,
                               prefix_cache_pages=0, max_queue=4) as cb:
            outs[chunk] = cb.submit(p, 4).result(timeout=300)
    np.testing.assert_array_equal(outs[4], outs[7])
    assert len(outs[4]) == 4
    with ContinuousBatcher(lm, max_len=20, num_slots=2, page_size=4,
                           prefill_chunk_tokens=0, max_queue=4) as cb:
        with pytest.raises(RequestTooLarge, match="prefill window"):
            cb.submit(p, 4)


# ---------------------------------------------------------------------
# Prefix-cache hits: parity, CoW isolation, accounting
# ---------------------------------------------------------------------
def test_prefix_hit_token_parity_and_divergence_isolation(lm):
    """Shared prefix, divergent suffixes, interleaved: every request's
    greedy tokens are identical to a cold lockstep run of its own prompt,
    and a request that diverges after the shared prefix cannot perturb a
    later request that reuses it (the shared pages are immutable)."""
    rng = np.random.RandomState(7)
    pre = rng.randint(1, 50, size=(8,)).astype(np.int32)  # 2 full pages
    mk = lambda n: np.concatenate(  # noqa: E731
        [pre, rng.randint(1, 50, size=(n,)).astype(np.int32)])
    a, b, c = mk(3), mk(2), mk(4)
    session = GenerativeSession(lm, max_len=20)
    refs = [session.generate(x[None, :], 5)[0] for x in (a, b, c)]
    with ContinuousBatcher(lm, max_len=20, num_slots=2, page_size=4,
                           max_queue=8) as cb:
        ra = cb.submit(a, 5)
        np.testing.assert_array_equal(ra.result(timeout=300),
                                      np.asarray(refs[0]))
        assert not ra.cache_hit  # cold leader
        # b and c share the prefix, diverge after it, run interleaved
        rb, rc = cb.submit(b, 5), cb.submit(c, 5)
        np.testing.assert_array_equal(rb.result(timeout=300),
                                      np.asarray(refs[1]))
        np.testing.assert_array_equal(rc.result(timeout=300),
                                      np.asarray(refs[2]))
        assert rb.cache_hit and rc.cache_hit
        assert rb.prefix_tokens == 8 and rc.prefix_tokens == 8
        # a fresh reuse AFTER the divergent traffic finished still decodes
        # identically: nothing leaked into the shared pages
        rd = cb.submit(a, 5)
        np.testing.assert_array_equal(rd.result(timeout=300),
                                      np.asarray(refs[0]))
        assert rd.cache_hit
        st = cb.stats()["pool"]["prefix"]
        assert st["hits"] == 3 and st["pages_saved"] == 6
        assert st["pages_in_use"] > 0
    # all pins released at retire
    assert cb.pool.prefix.refcount_of(a) == [0, 0]


def test_prefix_cache_ttft_histogram_split_by_outcome(lm):
    from flexflow_tpu.obs import REGISTRY

    [p] = _prompts([9], seed=9)
    with ContinuousBatcher(lm, max_len=16, num_slots=2, page_size=4,
                           max_queue=8) as cb:
        cb.submit(p, 3).result(timeout=300)
        cb.submit(p[:9], 3).result(timeout=300)
    h = REGISTRY.histogram("ff_serving_ttft_ms", labels=("cache",))
    assert h.count(cache="miss") == 1
    assert h.count(cache="hit") == 1
    g = REGISTRY.gauge("ff_kvpool_pages_saved", labels=("pool",))
    assert g.value(pool=cb.pool.label) == 2


def test_prefix_cache_survives_slot_churn(lm):
    """One slot, many sequenced requests sharing a prefix: every request
    reuses the slot the previous one released, and hits stay exact (the
    band is independent of slot reuse)."""
    rng = np.random.RandomState(3)
    pre = rng.randint(1, 50, size=(4,)).astype(np.int32)
    prompts = [np.concatenate(
        [pre, rng.randint(1, 50, size=(3,)).astype(np.int32)])
        for _ in range(3)]
    session = GenerativeSession(lm, max_len=16)
    refs = [session.generate(p[None, :], 4)[0] for p in prompts]
    with ContinuousBatcher(lm, max_len=16, num_slots=1, page_size=4,
                           max_queue=8, queue_pages_budget=64) as cb:
        for p, ref in zip(prompts, refs):
            np.testing.assert_array_equal(
                cb.submit(p, 4).result(timeout=300), np.asarray(ref))
    st = cb.stats()["pool"]["prefix"]
    assert st["hits"] == 2 and st["misses"] == 1
