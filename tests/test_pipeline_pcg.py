"""Pipeline parallelism through the PCG: compile(parallel_axes={'stage': S})
routes the repeated-block region through the GPipe kernel, and the Unity
search can choose a 'stage' axis under --enable-pipeline-parallel.

Beyond-reference capability (upstream's OP_PIPELINE enum ffconst.h:159 is
unused there); closes VERDICT r3 item 3 — round 3's pipeline was a demo silo
outside FFModel/compile/search.
"""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.models import TransformerConfig, build_bert_encoder
from flexflow_tpu.parallel.pipeline_plan import (
    find_isomorphic_run,
    find_pipeline_plan,
)

BATCH, SEQ, HID, LAYERS = 8, 16, 32, 4


def _build(axes=None, ndev=1, microbatches=4):
    config = ff.FFConfig()
    config.num_devices = ndev
    config.batch_size = BATCH
    config.pipeline_microbatches = microbatches
    model = ff.FFModel(config)
    tokens = model.create_tensor([BATCH, SEQ], ff.DataType.DT_INT32)
    cfg = TransformerConfig(hidden_size=HID, embedding_size=HID,
                            num_heads=4, num_layers=LAYERS,
                            sequence_length=SEQ, vocab_size=50)
    build_bert_encoder(model, tokens, cfg)
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.1),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[], parallel_axes=axes)
    return model


def _data():
    x = np.random.RandomState(0).randint(0, 50, (BATCH, SEQ)).astype(np.int32)
    y = np.random.RandomState(1).randint(0, 2, (BATCH, SEQ, 1)).astype(np.int32)
    return x, y


def test_plan_finds_transformer_body():
    """The run finder recovers one group per encoder layer (period > 1:
    each layer spans two bottleneck segments)."""
    config = ff.FFConfig()
    config.batch_size = BATCH
    model = ff.FFModel(config)
    tokens = model.create_tensor([BATCH, SEQ], ff.DataType.DT_INT32)
    cfg = TransformerConfig(hidden_size=HID, embedding_size=HID,
                            num_heads=4, num_layers=LAYERS,
                            sequence_length=SEQ, vocab_size=50)
    build_bert_encoder(model, tokens, cfg)
    g = Graph(model.ops)
    run_len, run, entries = find_isomorphic_run(g)
    assert run_len == LAYERS
    assert len({len(grp) for grp in run}) == 1  # isomorphic groups
    assert all(tuple(e.dims) == (BATCH, SEQ, HID) for e in entries)
    plan = find_pipeline_plan(g, n_stages=LAYERS)
    assert plan.segs_per_stage == 1
    plan2 = find_pipeline_plan(g, n_stages=LAYERS // 2)
    assert plan2.segs_per_stage == 2


def test_pipeline_fit_steps_per_execution():
    """Chunked fit on a stage mesh: the K-step scan wraps the GPipe scan
    (scan-inside-scan) and trains — loss stays finite and falls."""
    model = _build(axes={"stage": 2}, ndev=2)
    x, y = _data()
    xs = np.tile(x, (4, 1))
    ys = np.tile(y, (4, 1, 1))
    hist = model.fit([xs], ys, epochs=2, steps_per_execution=2)
    assert np.isfinite(hist[-1]["loss"])
    assert hist[-1]["loss"] <= hist[0]["loss"] + 1e-6


def test_plan_loud_on_unpipelineable_graph():
    """No repeated structure -> a loud error naming the constraint."""
    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    t = model.create_tensor([4, 8], ff.DataType.DT_FLOAT)
    t = model.dense(t, 13, name="a")
    t = model.dense(t, 7, name="b")
    model.softmax(t)
    with pytest.raises(ValueError, match="isomorphic"):
        find_pipeline_plan(Graph(model.ops), n_stages=2)


def test_adopt_params_plain_to_plain():
    """adopt_params_from between two sequential compilations: predictions
    become identical; a different-graph source raises loudly."""
    m_a = _build(None, ndev=1)
    m_b = _build(None, ndev=1)
    # different init seeds would be the realistic case; force a difference
    import jax.numpy as jnp

    first = next(n for n in m_b.params if m_b.params[n])
    k0 = next(iter(m_b.params[first]))
    m_b.params[first][k0] = m_b.params[first][k0] + 1.0
    m_b.adopt_params_from(m_a)
    x, y = _data()
    name = m_a.input_ops[0].name
    np.testing.assert_allclose(
        np.asarray(m_a.predict(x)), np.asarray(m_b.predict(x)),
        rtol=1e-6, atol=1e-7)

    config = ff.FFConfig()
    config.batch_size = 4
    other = ff.FFModel(config)
    t = other.create_tensor([4, 8])
    other.softmax(other.dense(t, 3, name="different_head"))
    other.compile(optimizer=ff.SGDOptimizer(other, lr=0.1),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[])
    with pytest.raises(KeyError, match="no counterpart"):
        m_b.adopt_params_from(other)


def test_pp_matches_sequential_numerics():
    """One fit epoch through a dp=2 x stage=4 mesh matches the sequential
    model when both start from identical weights: GPipe is the same math."""
    m_seq = _build(None, ndev=1)
    m_pp = _build({"data": 2, "stage": 4}, ndev=8)
    plan = m_pp.executor.pipeline_plan
    assert plan is not None and plan.n_stages == 4

    # overwrite the pp model's weights with the sequential model's
    m_pp.adopt_params_from(m_seq)
    # the reverse direction is explicitly unsupported
    with pytest.raises(ValueError, match="sequential source"):
        m_seq.adopt_params_from(m_pp)

    x, y = _data()
    h_seq = m_seq.fit(x, y, epochs=1, verbose=False)
    h_pp = m_pp.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(h_pp[-1]["loss"])
    np.testing.assert_allclose(h_pp[-1]["loss"], h_seq[-1]["loss"],
                               rtol=2e-2)

    # post-update suffix weights agree (they sit outside the pipeline)
    w_seq = np.asarray(m_seq.params["cls"]["kernel"])
    w_pp = np.asarray(m_pp.params["cls"]["kernel"])
    np.testing.assert_allclose(w_pp, w_seq, atol=2e-2)


def test_pp_pure_stage_mesh():
    """stage-only mesh (no data axis) trains to a finite loss."""
    m = _build({"stage": 4}, ndev=8, microbatches=2)
    x, y = _data()
    h = m.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(h[-1]["loss"])


def test_pp_truncates_indivisible_run():
    """3 stages on a 4-block body: pipeline 3 blocks, run 1 sequentially."""
    m = _build({"stage": 3}, ndev=8, microbatches=2)
    plan = m.executor.pipeline_plan
    assert plan.n_stages == 3 and len(plan.segments) == 3
    x, y = _data()
    h = m.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(h[-1]["loss"])


def test_pp_too_many_stages_raises():
    with pytest.raises(ValueError, match="repeats only"):
        _build({"stage": 5}, ndev=8)


def test_pp_weight_accessors():
    """get/set_tensor and get_parameter_by_id reach INTO the stacked
    pipeline tree (reference: ParallelTensor set_tensor/get_tensor work on
    any op's weights regardless of placement)."""
    m = _build({"stage": 4}, ndev=8, microbatches=2)
    # a weight belonging to a stage-2 block
    op = next(o for o in m.graph.topo_order() if o.name == "layer2_ff1")
    w = op.weights[0]
    val = np.asarray(m._get_tensor_value(w))
    got = m.get_parameter_by_id("layer2_ff1", w._weight_spec.name)
    np.testing.assert_array_equal(val, got)
    new = np.full_like(val, 0.25)
    m._set_tensor_value(w, new)
    np.testing.assert_array_equal(
        m.get_parameter_by_id("layer2_ff1", w._weight_spec.name), new)
    # a DIFFERENT stage's copy is untouched
    other = m.get_parameter_by_id("layer1_ff1", w._weight_spec.name)
    assert not np.allclose(other, new)
    x, y = _data()
    h = m.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(h[-1]["loss"])


def test_pp_checkpoint_roundtrip(tmp_path):
    """Stacked '__pipeline__' params survive save/restore (generic pytree
    flattening) and the restored model trains on."""
    import os

    from flexflow_tpu.runtime.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    m = _build({"data": 2, "stage": 4}, ndev=8)
    x, y = _data()
    m.fit(x, y, epochs=1, verbose=False)
    p = os.path.join(str(tmp_path), "ckpt.npz")
    save_checkpoint(p, m, step=1)
    m2 = _build({"data": 2, "stage": 4}, ndev=8)
    restore_checkpoint(p, m2)
    k0 = next(iter(m.params["__pipeline__"]))
    for wname, val in m.params["__pipeline__"][k0].items():
        np.testing.assert_array_equal(
            np.asarray(val), np.asarray(m2.params["__pipeline__"][k0][wname]))
    h = m2.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(h[-1]["loss"])


def test_search_picks_pp_under_memory_pressure():
    """Deep-narrow graph, batch caps dp at 2, TP-indivisible dims: with a
    memory budget that dp-replication busts, the lambda search must buy the
    pipeline's S-way weight sharding (cost model: region memory / pp)."""
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.unity import unity_optimize

    config = ff.FFConfig()
    config.num_devices = 8
    config.batch_size = 4
    config.search_budget = 8
    config.enable_pipeline_parallel = True
    config.pipeline_microbatches = 2
    config.memory_search = True
    model = ff.FFModel(config)
    t = model.create_tensor([4, 97], ff.DataType.DT_FLOAT)
    for i in range(8):  # 97 is prime: no TP divides; batch 4: dp <= 4
        t = model.dense(t, 97, name=f"deep{i}")
    model.softmax(t)
    graph = Graph(model.ops)
    machine = make_machine_model(config, 8)

    # budget below the replicated-weights footprint: only 'stage' sharding
    # of the repeated region can fit
    from flexflow_tpu.search.unity import GraphSearchHelper

    helper = GraphSearchHelper(graph, config, machine)
    full = helper._parallelize(graph, 4, 8)
    pp_cands = helper._pipeline_candidates(graph, 4, 8)
    assert pp_cands, "search produced no pipeline candidates"
    assert any(r.mesh_axes.get("stage", 1) > 1 for r in pp_cands)
    # every pp candidate must report less region memory than replication
    rep_mem = full.memory_bytes
    assert min(r.memory_bytes for r in pp_cands) < rep_mem

    budget = min(r.memory_bytes for r in pp_cands) * 1.5
    best = helper.graph_optimize(4, 8, memory_budget_bytes=budget)
    assert best.mesh_axes.get("stage", 1) > 1, (
        f"memory-aware search did not choose PP: {best.mesh_axes}")


def test_search_pp_compiles_end_to_end():
    """unity_optimize result with a stage axis flows through compile()."""
    config = ff.FFConfig()
    config.num_devices = 8
    config.batch_size = BATCH
    config.search_budget = 4
    config.enable_pipeline_parallel = True
    config.pipeline_microbatches = 4
    model = ff.FFModel(config)
    tokens = model.create_tensor([BATCH, SEQ], ff.DataType.DT_INT32)
    cfg = TransformerConfig(hidden_size=HID, embedding_size=HID,
                            num_heads=4, num_layers=LAYERS,
                            sequence_length=SEQ, vocab_size=50)
    build_bert_encoder(model, tokens, cfg)
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.1),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[])
    x, y = _data()
    h = model.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(h[-1]["loss"])


def test_stage_placement_options_tier_nesting():
    """stage_placement_options (docs/machine.md "Overlap"): multi-tier
    machines offer the stage-OUTER nesting (contiguous per-stage device
    blocks, cut on the pod edge when dp covers whole inner groups);
    flat and one-tier machines keep only the legacy strided nesting so
    their pricing is unchanged bit-for-bit."""
    from flexflow_tpu.parallel.pipeline_plan import stage_placement_options
    from flexflow_tpu.search.machine_model import (CHIP_SPECS,
                                                   HierarchicalMachineModel,
                                                   TierSpec, TpuPodModel)

    chip = CHIP_SPECS["tpu-v5e"]
    hier = HierarchicalMachineModel(
        [TierSpec("ici", 8, 45.0, 2),
         TierSpec("dcn", 2, 3.125, 1, 10.0)], chip)
    opts = stage_placement_options(hier, dp=8, pp=2)
    assert [o["order"] for o in opts] == ["stage_outer", "stage_inner"]
    outer, inner = opts
    assert outer["axes"] == (("stage", 2), ("data", 8))
    assert outer["hop_inner"] == 8 and outer["dp_inner"] == 1
    assert outer["hop_tier"] == "dcn" and outer["cut_on_tier_boundary"]
    assert inner["axes"] == (("data", 8), ("stage", 2))
    assert inner["hop_inner"] == 1 and inner["dp_inner"] == 2
    assert inner["hop_tier"] == "ici" and not inner["cut_on_tier_boundary"]
    # dp=4 covers only half a pod: the cut lands mid-pod
    assert not stage_placement_options(hier, 4, 4)[0]["cut_on_tier_boundary"]
    # flat and one-tier machines: legacy nesting only
    assert [o["order"] for o in stage_placement_options(
        TpuPodModel(16, chip), 8, 2)] == ["stage_inner"]
    one = HierarchicalMachineModel([TierSpec("ici", 16, 45.0, 2)], chip)
    assert [o["order"] for o in stage_placement_options(one, 8, 2)] \
        == ["stage_inner"]


def test_pp_compiles_with_stage_outer_mesh():
    """A stage-OUTERMOST mesh (the tier-aware placement's nesting)
    compiles and trains: make_mesh preserves the axes order, so each
    stage owns a contiguous device block."""
    config = ff.FFConfig()
    config.num_devices = 8
    config.batch_size = BATCH
    # per-microbatch batch must divide over the data axis (BATCH=8,
    # m=2 -> 4 per microbatch over dp=4)
    config.pipeline_microbatches = 2
    model = ff.FFModel(config)
    tokens = model.create_tensor([BATCH, SEQ], ff.DataType.DT_INT32)
    cfg = TransformerConfig(hidden_size=HID, embedding_size=HID,
                            num_heads=4, num_layers=LAYERS,
                            sequence_length=SEQ, vocab_size=50)
    build_bert_encoder(model, tokens, cfg)
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.1),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[], parallel_axes={"stage": 2, "data": 4})
    assert tuple(model.mesh.axis_names) == ("stage", "data")
    x, y = _data()
    h = model.fit(x, y, epochs=1, verbose=False)
    assert np.isfinite(h[-1]["loss"])
