"""Unified observability layer (flexflow_tpu/obs): metrics registry,
span tracer, step stats, and simulator calibration."""
import json
import time

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu import obs
from flexflow_tpu.obs import (MetricsRegistry, StepStats, Tracer,
                              parse_exposition, validate_exposition)


# ---------------------------------------------------------------------------
# MetricsRegistry + exposition format
# ---------------------------------------------------------------------------
def test_registry_counter_gauge_histogram_render():
    reg = MetricsRegistry()
    c = reg.counter("ff_x_total", "things", labels=("kind",))
    c.inc(kind="a")
    c.inc(2, kind="b")
    g = reg.gauge("ff_g", "a gauge")
    g.set(2.5)
    h = reg.histogram("ff_h_ms", "latencies", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    h.observe(50.0)
    text = reg.render()
    fams = validate_exposition(text)
    assert fams["ff_x_total"]["type"] == "counter"
    assert fams["ff_g"]["type"] == "gauge"
    assert fams["ff_h_ms"]["type"] == "histogram"
    samples = {(n, tuple(sorted(lbl.items()))): v
               for n, lbl, v in fams["ff_x_total"]["samples"]}
    assert samples[("ff_x_total", (("kind", "a"),))] == 1
    assert samples[("ff_x_total", (("kind", "b"),))] == 2
    # histogram: cumulative buckets + sum + count
    hs = {(n, lbl.get("le")): v for n, lbl, v in fams["ff_h_ms"]["samples"]}
    assert hs[("ff_h_ms_bucket", "1")] == 1
    assert hs[("ff_h_ms_bucket", "10")] == 2
    assert hs[("ff_h_ms_bucket", "+Inf")] == 3
    assert hs[("ff_h_ms_count", None)] == 3
    assert hs[("ff_h_ms_sum", None)] == pytest.approx(55.5)


def test_registry_label_escaping_round_trips():
    reg = MetricsRegistry()
    nasty = 'he said "hi"\\path\nnewline'
    reg.counter("ff_esc_total", "escapes", labels=("v",)).inc(v=nasty)
    fams = parse_exposition(reg.render())
    (_, labels, value), = fams["ff_esc_total"]["samples"]
    assert labels["v"] == nasty
    assert value == 1


def test_registry_kind_mismatch_rejected_and_reset_keeps_handles():
    reg = MetricsRegistry()
    c = reg.counter("ff_one_total", "one")
    with pytest.raises(ValueError):
        reg.gauge("ff_one_total", "one")
    c.inc(3)
    reg.reset_all()
    assert c.value() == 0
    c.inc()  # the cached handle still feeds the same (reset) family
    assert reg.counter("ff_one_total", "one").value() == 1


def test_histogram_bucket_mismatch_rejected():
    reg = MetricsRegistry()
    h = reg.histogram("ff_hb_ms", "h", buckets=(1.0, 10.0))
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("ff_hb_ms", "h", buckets=(100.0, 1000.0))
    # fetching without explicit buckets never conflicts
    assert reg.histogram("ff_hb_ms", "h") is h


def test_histogram_quantile_interpolates_and_clamps():
    reg = MetricsRegistry()
    h = reg.histogram("ff_q_ms", "q", buckets=(10.0, 100.0, 1000.0))
    assert h.quantile(0.99) == 0.0  # nothing observed
    for v in (5.0, 5.0, 50.0, 50.0):
        h.observe(v)
    # p50 lands on the 10ms bucket boundary (2 of 4 samples <= 10)
    assert h.quantile(0.5) == pytest.approx(10.0)
    # p75 interpolates inside (10, 100]
    assert 10.0 < h.quantile(0.75) <= 100.0
    # +Inf bucket clamps to the last finite boundary
    h.observe(10_000.0)
    assert h.quantile(1.0) == pytest.approx(1000.0)
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        h.quantile(1.5)
    # labeled histograms quantile per labelset
    lab = reg.histogram("ff_ql_ms", "q", labels=("cache",),
                        buckets=(10.0, 100.0))
    lab.observe(5.0, cache="hit")
    assert lab.quantile(0.9, cache="hit") <= 10.0
    assert lab.quantile(0.9, cache="miss") == 0.0


def test_histogram_windowed_quantile_since_snapshot():
    """quantile(since=snapshot) covers only observations AFTER the
    snapshot — the windowed read the fleet autoscaler's TTFT SLO signal
    uses (the buckets themselves never decay)."""
    reg = MetricsRegistry()
    h = reg.histogram("ff_w_ms", "w", buckets=(10.0, 100.0, 1000.0))
    # an unseen labelset snapshots as all-zero
    base0 = h.snapshot()
    assert set(base0) == {0.0}
    h.observe(900.0)          # historic slow burst
    snap = h.snapshot()
    assert h.quantile(0.99) > 100.0              # lifetime sees it
    assert h.quantile(0.99, since=snap) == 0.0   # window is empty
    h.observe(5.0)
    h.observe(5.0)
    assert h.quantile(0.99, since=snap) <= 10.0  # window: fast only
    assert h.quantile(0.99, since=base0) > 100.0  # pre-burst baseline
    assert h.quantile(0.99) > 100.0              # lifetime unchanged


def test_render_merged_stamps_replica_label_and_rejects_collisions():
    from flexflow_tpu.obs import render_merged

    a, b = MetricsRegistry(), MetricsRegistry()
    for reg, n in ((a, 3), (b, 5)):
        reg.counter("ff_m_total", "c", labels=("outcome",)).inc(
            n, outcome="ok")
        reg.histogram("ff_m_ms", "h", buckets=(1.0, 10.0)).observe(n)
        reg.gauge("ff_only_a" if reg is a else "ff_only_b", "g").set(n)
    text = render_merged({"r0": a, "r1": b})
    fams = validate_exposition(text)
    # ONE TYPE header per family, every sample stamped
    assert text.count("# TYPE ff_m_total counter") == 1
    samples = fams["ff_m_total"]["samples"]
    assert {(s[1]["replica"], s[1]["outcome"], s[2]) for s in samples} \
        == {("r0", "ok", 3.0), ("r1", "ok", 5.0)}
    assert all("replica" in s[1] for s in fams["ff_m_ms"]["samples"])
    # families present in only one registry still render, stamped
    assert 'ff_only_a{replica="r0"} 3' in text
    # kind collision -> loud error, never a silent sum
    c = MetricsRegistry()
    c.gauge("ff_m_total", "now a gauge", labels=("outcome",))
    with pytest.raises(ValueError, match="collision"):
        render_merged({"r0": a, "r2": c})
    # histogram bucket mismatch is a collision too
    d = MetricsRegistry()
    d.histogram("ff_m_ms", "h", buckets=(500.0,)).observe(1)
    with pytest.raises(ValueError, match="collision"):
        render_merged({"r0": a, "r3": d})
    # a family already carrying the merge label is ambiguous
    e = MetricsRegistry()
    e.counter("ff_r_total", "c", labels=("replica",)).inc(replica="x")
    with pytest.raises(ValueError, match="ambiguous"):
        render_merged({"r0": e})


def test_render_labeled_mixes_bare_and_stamped_members():
    # the fleet /metrics fan-in shape: an UNSTAMPED member (the server /
    # default registry) sharing a family name with replica-stamped
    # members must render under ONE TYPE header, bare samples first-class
    # alongside the labeled ones.
    from flexflow_tpu.obs import render_labeled

    base, r0, r1 = (MetricsRegistry() for _ in range(3))
    for reg, v in ((base, 1), (r0, 2), (r1, 3)):
        reg.gauge("ff_pages", "g", labels=("pool",)).set(v, pool="p")
    text = render_labeled([((), base),
                           ((("replica", "r0"),), r0),
                           ((("replica", "r1"), ("fleet", "f")), r1)])
    fams = validate_exposition(text)
    assert text.count("# TYPE ff_pages gauge") == 1
    got = {(s[1].get("replica"), s[1].get("fleet"), s[2])
           for s in fams["ff_pages"]["samples"]}
    assert got == {(None, None, 1.0), ("r0", None, 2.0), ("r1", "f", 3.0)}
    with pytest.raises(ValueError, match="invalid merge label"):
        render_labeled([((("bad-name!", "x"),), base)])


def test_validate_exposition_rejects_garbage():
    with pytest.raises(ValueError):
        validate_exposition("ff_bad{unterminated 1\n")
    with pytest.raises(ValueError):
        validate_exposition("# TYPE ff_x sometype\n")
    with pytest.raises(ValueError):
        validate_exposition("not a metric line at all!\n")


def test_preexisting_counter_shims_are_registry_backed():
    from flexflow_tpu.elastic.watchdog import (reset_watchdog_counters,
                                               watchdog_counters)
    from flexflow_tpu.runtime.durability import (checkpoint_counters,
                                                 reset_checkpoint_counters)

    obs.REGISTRY.counter("ff_checkpoint_saved_total", "").inc(2)
    obs.REGISTRY.counter("ff_watchdog_skips_total", "").inc()
    assert checkpoint_counters() == {"saved": 2}
    assert watchdog_counters() == {"skips": 1}
    reset_checkpoint_counters()
    assert checkpoint_counters() == {}
    assert watchdog_counters() == {"skips": 1}  # untouched by the other reset
    reset_watchdog_counters()
    assert watchdog_counters() == {}


# ---------------------------------------------------------------------------
# Tracer + Chrome trace export
# ---------------------------------------------------------------------------
def test_chrome_trace_events_are_spec_compliant(tmp_path):
    t = Tracer(enabled=True)
    with t.span("outer", phase="demo"):
        time.sleep(0.002)
        with t.span("inner"):
            time.sleep(0.001)
        with t.span("inner"):
            pass
    t.instant("marker", note=1)
    path = t.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        data = json.load(f)  # valid JSON
    events = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    assert len(events) == 3
    for e in events:
        for field in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert field in e, (field, e)
        assert e["dur"] >= 0
    # nested spans properly contained in their parent
    outer = next(e for e in events if e["name"] == "outer")
    for inner in (e for e in events if e["name"] == "inner"):
        assert outer["ts"] <= inner["ts"] + 1e-3
        assert (inner["ts"] + inner["dur"]
                <= outer["ts"] + outer["dur"] + 1e-3)
    # the profile CLI's validator agrees
    from flexflow_tpu.obs.cli import validate_trace

    assert validate_trace(path) == ["inner", "marker", "outer"]


def test_span_records_exception_and_args():
    t = Tracer(enabled=True)
    with pytest.raises(RuntimeError):
        with t.span("boom", step=3):
            raise RuntimeError("x")
    (ev,) = t.events("boom")
    assert ev["args"]["error"] == "RuntimeError"
    assert ev["args"]["step"] == 3


def test_disabled_tracing_is_effectively_free():
    """ISSUE acceptance: spans compile to no-ops when disabled; the
    enabled path is bounded. Min-of-repeats de-noises a loaded CI host;
    the bounds are deliberately loose — the property pinned is the ORDER
    of the overhead, not the constant."""
    t = Tracer(enabled=False)

    def per_span_us(n=5_000, repeats=5):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(n):
                with t.span("hot"):
                    pass
            best = min(best, (time.perf_counter() - t0) / n * 1e6)
        return best

    disabled_us = per_span_us()
    assert disabled_us < 20.0, f"disabled span cost {disabled_us:.2f}us"
    assert t.events() == []  # and truly recorded nothing
    t.enable()
    enabled_us = per_span_us()
    assert enabled_us < 250.0, f"enabled span cost {enabled_us:.2f}us"


def test_tracer_ring_bounds_memory():
    t = Tracer(enabled=True, max_events=10)
    for i in range(50):
        with t.span(f"s{i}"):
            pass
    evs = t.events()
    assert len(evs) == 10
    assert evs[0]["name"] == "s40"  # oldest dropped


# ---------------------------------------------------------------------------
# Request-scoped tracing: handoff/resume stitching, ring drops, exemplars
# ---------------------------------------------------------------------------
def test_handoff_resume_stitches_spans_across_threads(tmp_path):
    """The serving submit path in miniature: a client thread opens a span
    and captures a Handoff; a scheduler thread resumes it. Both spans
    must share one trace_id, the resumed span must parent under the
    submitting span, and the flow-arrow pair must bind the two tracks."""
    import threading

    from flexflow_tpu.obs.tracing import root_context, use_context

    t = Tracer(enabled=True)
    t.set_thread_name("client")
    ctx = root_context()
    with use_context(ctx):
        with t.span("submit"):
            token = t.handoff("crossing")

    def worker():
        t.set_thread_name("sched")
        with t.resume(token), t.span("prefill", request=1):
            pass

    th = threading.Thread(target=worker)
    th.start()
    th.join(10.0)
    assert not th.is_alive()

    spans = {e["name"]: e for e in t.events() if e["ph"] == "X"}
    assert spans["prefill"]["args"]["trace_id"] == ctx.trace_id
    assert spans["submit"]["args"]["trace_id"] == ctx.trace_id
    assert spans["prefill"]["args"]["parent_id"] \
        == spans["submit"]["args"]["span_id"]
    assert spans["submit"]["tid"] != spans["prefill"]["tid"]
    # flow arrow: start on the client track, finish on the scheduler's,
    # sharing one id under the "handoff" category
    s, f = [e for e in t.events() if e["ph"] in ("s", "f")]
    assert (s["ph"], f["ph"]) == ("s", "f")
    assert s["id"] == f["id"] and s["cat"] == f["cat"] == "handoff"
    assert f["bp"] == "e"
    assert s["tid"] == spans["submit"]["tid"]
    assert f["tid"] == spans["prefill"]["tid"]
    # a second resume re-enters the context but must not re-emit the
    # flow finish (the arrow is one edge, not one per resume)
    with t.resume(token):
        pass
    assert len([e for e in t.events() if e["ph"] == "f"]) == 1

    # the export names both tracks and still validates
    path = t.export_chrome_trace(str(tmp_path / "t.json"))
    from flexflow_tpu.obs.cli import validate_trace

    assert validate_trace(path) == ["prefill", "submit"]
    with open(path) as fh:
        data = json.load(fh)
    names = {e["args"]["name"] for e in data["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert {"client", "sched"} <= names


def test_handoff_is_noop_when_disabled_or_contextless():
    t = Tracer(enabled=False)
    assert t.handoff() is None
    with t.resume(None):  # a None token must be a no-op scope
        pass
    t.enable()
    assert t.handoff() is None  # no current context -> nothing to carry
    assert t.events() == []


def test_instant_args_are_jsonable(tmp_path):
    """Regression: numpy scalars/arrays passed to instant() must not
    break json.dump at export time."""
    t = Tracer(enabled=True)
    t.instant("marker", arr=np.arange(3), val=np.float64(1.5),
              n=np.int64(7))
    path = t.export_chrome_trace(str(tmp_path / "t.json"))
    with open(path) as fh:
        data = json.load(fh)  # would raise before the fix
    (ev,) = [e for e in data["traceEvents"] if e.get("ph") == "i"]
    assert ev["args"]["val"] == 1.5


def test_ring_overflow_counts_drops_and_stamps_export():
    t = Tracer(enabled=True, max_events=10)
    for i in range(50):
        with t.span(f"s{i}"):
            pass
    assert t.dropped_events == 40
    data = t.to_chrome_trace()
    meta = next(e for e in data["traceEvents"]
                if e.get("ph") == "M" and e["name"] == "trace_metadata")
    assert meta["args"]["dropped_events"] == 40
    assert meta["args"]["epoch_wall_s"] > 0
    # mirrored onto the registry so dashboards see the truncation
    from flexflow_tpu.obs import get_registry

    assert get_registry().counter(
        "ff_trace_events_dropped_total", "").value() == 40
    t.clear()
    assert t.dropped_events == 0


def test_histogram_exemplar_round_trip():
    reg = MetricsRegistry()
    h = reg.histogram("ff_e_ms", "latencies", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0, exemplar="abc123")
    text = reg.render()
    assert '# {trace_id="abc123"} 5' in text
    validate_exposition(text)  # exemplars must not break validation
    fams = parse_exposition(text)
    # samples stay plain 3-tuples; exemplars ride in their own list
    assert all(len(s) == 3 for s in fams["ff_e_ms"]["samples"])
    (name, labels, exlabels, value), = fams["ff_e_ms"]["exemplars"]
    assert name == "ff_e_ms_bucket" and labels["le"] == "10"
    assert exlabels == {"trace_id": "abc123"}
    assert value == pytest.approx(5.0)


def test_traceparent_request_scope_parsing():
    """The HTTP door: a well-formed inbound traceparent CONTINUES the
    caller's trace; garbage or absence mints local state only when
    tracing is on; the request id is always present."""
    from flexflow_tpu.obs.tracing import get_tracer
    from flexflow_tpu.serving.server import (_format_traceparent,
                                             _request_scope)

    caller_trace, caller_span = "ab" * 16, "cd" * 8
    ctx, rid = _request_scope({
        "traceparent": f"00-{caller_trace}-{caller_span}-01",
        "X-Request-Id": "req-42"})
    assert rid == "req-42"
    assert ctx.trace_id == caller_trace and ctx.parent_id == caller_span
    assert _format_traceparent(ctx) \
        == f"00-{caller_trace}-{ctx.span_id}-01"
    # malformed header + tracing disabled -> no context, minted id
    assert not get_tracer().enabled  # conftest reset guarantees this
    ctx2, rid2 = _request_scope({"traceparent": "00-nope-bad-ff"})
    assert ctx2 is None and len(rid2) == 16
    # tracing enabled -> a fresh local root even without a header
    get_tracer().enable()
    try:
        ctx3, _ = _request_scope({})
        assert ctx3 is not None and ctx3.parent_id is None
    finally:
        get_tracer().disable()


def test_flight_recorder_rings_triggers_and_debounces(tmp_path):
    from flexflow_tpu.elastic.events import EventLog
    from flexflow_tpu.obs.flightrecorder import FlightRecorder

    reg = MetricsRegistry()
    reg.counter("ff_fr_total", "recorded things").inc()
    tracer = Tracer(enabled=True)
    with tracer.span("before_death"):
        pass
    elog = EventLog()
    rec = FlightRecorder(dump_dir=str(tmp_path / "fr"), capacity=8,
                         tracer=tracer, registries={"unit": reg},
                         max_dumps=2, debounce_s=3600.0).attach(elog)
    try:
        elog.record("fleet.suspect", replica="r0")   # health stream
        elog.record("retry", attempt=1)              # plain event
        rec.snapshot_metrics()                       # metrics stream
        assert not rec.dumps  # nothing triggered yet
        elog.record("fleet.dead", replica="r0")      # TRIGGER
        elog.record("fleet.failover", replica="r0")  # debounced away
        assert len(rec.dumps) == 1
        bundle = rec.dumps[0]
        with open(bundle + "/recorder.json") as fh:
            dump = json.load(fh)
        assert dump["meta"]["trigger"] == "fleet.dead"
        assert {"health", "events", "metrics"} \
            <= set(dump["meta"]["streams"])
        kinds = [e.get("kind") for e in dump["entries"]]
        assert "fleet.suspect" in kinds and "retry" in kinds
        # the bundle carries the trace and a fresh exposition render
        with open(bundle + "/trace.json") as fh:
            trace = json.load(fh)
        assert any(e.get("name") == "before_death"
                   for e in trace["traceEvents"])
        with open(bundle + "/metrics_unit.txt") as fh:
            assert "ff_fr_total" in fh.read()
        # manual dumps bypass the debounce, max_dumps caps the disk
        assert rec.dump(trigger="manual") is not None
        assert rec.dump(trigger="manual") is None  # cap reached
        # ring stays bounded
        for i in range(20):
            elog.record("retry", attempt=i)
        assert len(rec.entries()) == 8
    finally:
        rec.detach()


# ---------------------------------------------------------------------------
# StepStats
# ---------------------------------------------------------------------------
def test_stepstats_rates_and_summary():
    reg = MetricsRegistry()
    s = StepStats(flops_per_step=1e9, peak_tflops=10.0, registry=reg)
    s.start()
    time.sleep(0.005)
    rec = s.record_step(32, loss=1.5)
    assert rec["wall_ms"] >= 5.0 * 0.5  # timer resolution slack
    assert rec["samples_per_s"] > 0
    assert rec["tflops"] == pytest.approx(
        1e9 / (rec["step_ms"] / 1e3) / 1e12)
    assert rec["mfu"] == pytest.approx(rec["tflops"] / 10.0)
    time.sleep(0.001)
    s.record_step(32, loss=1.0, steps=4)  # a K-step chunk
    summ = s.summary()
    assert summ["steps"] == 5 and summ["recorded"] == 2
    assert summ["last_loss"] == 1.0
    assert summ["p95_step_ms"] >= summ["p50_step_ms"]
    assert reg.counter("ff_train_steps_total", "").value() == 5
    assert reg.histogram("ff_step_wall_ms", "").count() == 2


def test_stepstats_zero_dt_guard():
    s = StepStats(flops_per_step=1e9, peak_tflops=1.0,
                  registry=MetricsRegistry())
    s._mark = time.perf_counter() + 60.0  # force a non-positive interval
    rec = s.record_step(8, loss=0.1)
    assert rec["wall_ms"] == 0.0
    assert rec["samples_per_s"] == 0.0 and rec["mfu"] == 0.0


def test_stepstats_ring_capacity():
    s = StepStats(capacity=4, registry=MetricsRegistry())
    s.start()
    for _ in range(10):
        s.record_step(1)
    assert len(s) == 4 and s.total_steps == 10


# ---------------------------------------------------------------------------
# fit() integration: step stats recorded, history schema unchanged
# ---------------------------------------------------------------------------
def _small_model(batch=8, **cfg_kw):
    config = ff.FFConfig()
    config.batch_size = batch
    for k, v in cfg_kw.items():
        setattr(config, k, v)
    m = ff.FFModel(config)
    t = m.create_tensor([batch, 16])
    t = m.dense(t, 32, ff.ActiMode.AC_MODE_RELU)
    t = m.dense(t, 4)
    m.softmax(t)
    m.compile(
        optimizer=ff.SGDOptimizer(m, lr=0.05),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    return m


def _data(n=32):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 16).astype(np.float32)
    y = rng.randint(0, 4, size=(n, 1)).astype(np.int32)
    return x, y


def test_fit_records_step_stats_and_keeps_history_schema():
    m = _small_model()
    x, y = _data()
    hist = m.fit(x, y, epochs=2)
    assert m.step_stats is not None
    assert m.step_stats.total_steps == 8  # 4 steps/epoch x 2
    assert len(m.step_stats) == 8
    for r in m.step_stats.records():
        assert r["samples"] == 8 and "loss" in r
    # history schema unchanged by the obs layer
    assert set(hist[-1]) == {"samples", "accuracy", "loss", "cce",
                             "sparse_cce", "mse", "rmse", "mae", "epoch",
                             "throughput"}
    assert obs.REGISTRY.counter("ff_train_steps_total", "").value() == 8


def test_fit_chunked_path_records_per_dispatch():
    m = _small_model()
    x, y = _data(32)
    m.fit(x, y, epochs=1, steps_per_execution=2)
    # 2 chunks of K=2: two records carrying 2 steps each
    assert m.step_stats.total_steps == 4
    assert [r["steps"] for r in m.step_stats.records()] == [2.0, 2.0]


def test_fit_with_tracing_emits_dispatch_spans():
    tr = obs.enable_tracing()
    tr.clear()
    try:
        m = _small_model()
        x, y = _data()
        m.fit(x, y, epochs=1)
        names = tr.span_names()
        assert "compile" in names
        assert "executor.train_step" in names
        assert len(tr.events("executor.train_step")) == 4
    finally:
        obs.disable_tracing()


# ---------------------------------------------------------------------------
# search: predicted step cost recorded for calibration
# ---------------------------------------------------------------------------
def test_search_result_carries_predicted_step_us():
    m = _small_model(batch=8, search_budget=4, num_devices=8,
                     measure_op_costs=False)
    sr = m.search_result
    assert sr is not None
    assert sr.predicted_step_us == pytest.approx(sr.cost_us)
    assert sr.predicted_step_us > 0


def test_calibration_report_shape_and_json():
    m = _small_model()
    x, y = _data()
    m.fit(x, y, epochs=1)
    rep = obs.calibrate(m, warmup=0, repeats=1)
    assert rep.predicted_step_us and rep.predicted_step_us > 0
    assert rep.measured_step_us and rep.measured_step_us > 0
    ops = {o.op: o for o in rep.ops}
    assert {"linear_0", "linear_1", "softmax_0"} <= set(ops)
    good = [o for o in rep.ops if o.error is None]
    assert good and all(o.predicted_us > 0 for o in good)
    data = json.loads(rep.to_json())
    assert data["measured_steps"] == 4
    assert data["step_ratio"] == pytest.approx(rep.step_ratio)
    assert "calibration" in rep.format()


# ---------------------------------------------------------------------------
# serving: /metrics via the shared renderer, /healthz
# ---------------------------------------------------------------------------
def test_server_metrics_render_validates_and_keeps_names():
    from flexflow_tpu.analysis import record_report
    from flexflow_tpu.analysis.diagnostics import (DiagnosticReport,
                                                   make_diag)
    from flexflow_tpu.elastic.events import EventLog
    from flexflow_tpu.runtime.durability import _bump
    from flexflow_tpu.serving.server import InferenceServer

    server = InferenceServer()
    try:
        server.record_load_failure("broken", RuntimeError("nope"))
        _bump("saved")
        obs.REGISTRY.counter("ff_watchdog_skips_total", "").inc()
        record_report(DiagnosticReport(
            [make_diag("FFTA050", "synthetic")], passes_run=("t",)))
        ev = EventLog()
        ev.record("retry", step=1)
        server.attach_elastic_events(ev)
        text = server.prometheus_text()
        fams = validate_exposition(text)  # every line parses
        # all pre-existing metric names survive the registry migration
        for name in ("ff_inference_requests_total",
                     "ff_inference_failures_total",
                     "ff_inference_avg_latency_ms",
                     "ff_model_load_failures_total",
                     "ff_plan_diagnostics_total",
                     "ff_checkpoint_saved_total",
                     "ff_watchdog_skips_total",
                     "ff_elastic_events_total"):
            assert name in fams, name
        assert 'ff_model_load_failures_total{model="broken"} 1' in text
        assert "ff_checkpoint_saved_total 1" in text.replace("\r", "")
        (_, diag_lbl, _), = fams["ff_plan_diagnostics_total"]["samples"]
        assert diag_lbl["code"] == "FFTA050"
        (_, ev_lbl, ev_n), = fams["ff_elastic_events_total"]["samples"]
        assert ev_lbl == {"kind": "retry"} and ev_n == 1
    finally:
        server.shutdown()


def test_reregistered_model_metrics_start_from_zero():
    from flexflow_tpu.serving.server import InferenceServer, ModelMetrics

    server = InferenceServer()
    try:
        m1 = ModelMetrics(server.registry, "m")
        server._metrics["m"] = m1
        m1.record(50.0, ok=True)
        m1.record(10.0, ok=True)
        assert m1.stats()["requests"] == 2
        server.unregister("m")
        # the old incarnation's series no longer render
        assert 'model="m"' not in server.prometheus_text()
        # a fresh registration under the same name starts from zero —
        # no mixing of the old histogram sums with a reset max_ms
        m2 = ModelMetrics(server.registry, "m")
        s = m2.stats()
        assert s == {"requests": 0, "failures": 0, "avg_latency_ms": 0.0,
                     "max_latency_ms": 0.0}
        # and the idle model renders zero-valued series immediately
        # (dashboards join on series existence)
        assert 'ff_inference_requests_total{model="m"} 0' \
            in server.prometheus_text()
    finally:
        server.shutdown()


def test_generate_metrics_survive_repeat_requests():
    """_metrics_for must not rebuild (and thereby zero) live series on a
    repeat request — the eager-setdefault trap."""
    from flexflow_tpu.serving.server import InferenceServer

    server = InferenceServer()
    try:
        m = server._metrics_for("g")
        m.record(1.0, ok=True)
        assert server._metrics_for("g") is m
        server._metrics_for("g").record(2.0, ok=True)
        assert server.stats("g")["requests"] == 2
    finally:
        server.shutdown()


def test_two_servers_do_not_share_per_model_series():
    from flexflow_tpu.serving.server import InferenceServer

    a, b = InferenceServer(), InferenceServer()
    try:
        a.record_load_failure("m", RuntimeError("x"))
        assert 'ff_model_load_failures_total{model="m"} 1' \
            in a.prometheus_text()
        assert 'ff_model_load_failures_total{model="m"}' \
            not in b.prometheus_text()
    finally:
        a.shutdown()
        b.shutdown()


def test_healthz_endpoint():
    import urllib.request

    from flexflow_tpu.serving.server import InferenceServer

    server = InferenceServer()
    httpd = server.serve_http(port=0)
    try:
        port = httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz") as r:
            body = json.loads(r.read())
        assert r.status == 200
        assert body["status"] == "ok"
        assert body["models"] == []
        assert body["uptime_s"] >= 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as r:
            validate_exposition(r.read().decode())
    finally:
        httpd.shutdown()
        server.shutdown()


# ---------------------------------------------------------------------------
# satellites: print_event_log tail=0, IterationTimer shim
# ---------------------------------------------------------------------------
def test_print_event_log_tail_zero_shows_counts_only():
    from flexflow_tpu.elastic.events import EventLog
    from flexflow_tpu.runtime.profiling import print_event_log

    ev = EventLog()
    ev.record("retry", step=1)
    ev.record("retry", step=2)
    out = []
    print_event_log(ev, sink=out.append, tail=0)
    assert out == [ev.summary()]
    out2 = []
    print_event_log(ev, sink=out2.append, tail=1)
    assert len(out2) == 2  # one event line + the summary
    out3 = []
    print_event_log(EventLog(), sink=out3.append, tail=0)
    assert out3 == ["elastic: no events"]


def test_iteration_timer_zero_dt_and_prints():
    from flexflow_tpu.runtime.profiling import IterationTimer

    lines = []
    t = IterationTimer(4, print_freq=2, sink=lines.append)
    for _ in range(5):  # consecutive ticks can land in one clock quantum
        t.tick()
    assert t._count == 4
    assert len(lines) == 2 and all("samples/s" in ln for ln in lines)


# ---------------------------------------------------------------------------
# elastic: recovery spans appear in the trace
# ---------------------------------------------------------------------------
def test_recovery_spans_in_trace(tmp_path):
    from flexflow_tpu.elastic import (ElasticCoordinator, EventLog,
                                      FaultPlan, RetryPolicy)
    from flexflow_tpu.obs.cli import validate_trace

    tr = obs.enable_tracing()
    tr.clear()
    try:
        def builder(cfg):
            m = ff.FFModel(cfg)
            t = m.create_tensor([cfg.batch_size, 16])
            t = m.dense(t, 32, ff.ActiMode.AC_MODE_RELU)
            t = m.dense(t, 4)
            m.softmax(t)
            m.compile(
                optimizer=ff.SGDOptimizer(m, lr=0.05),
                loss_type=(
                    ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY),
                metrics=[])
            return m

        config = ff.FFConfig()
        config.batch_size = 8
        config.device_ids = [0, 1, 2, 3]
        plan = FaultPlan().add_chip_loss(at_step=3, chips=[3])
        coord = ElasticCoordinator(
            builder, config, fault_plan=plan,
            checkpoint_dir=str(tmp_path), checkpoint_every=2,
            events=EventLog(),
            retry_policy=RetryPolicy(max_retries=2, base_delay_s=0.01),
            # pin the disk restore path: this test asserts the
            # checkpoint.restore span; live-recovery spans are covered
            # by test_resharding.py
            live_resharding=False)
        x, y = _data(32)
        coord.fit(x, y, steps=6)
        names = tr.span_names()
        for required in ("elastic.recover", "elastic.replan",
                         "elastic.restore", "checkpoint.save",
                         "checkpoint.restore", "elastic.detect",
                         "elastic.resume", "compile",
                         "executor.train_step"):
            assert required in names, (required, names)
        # recover contains replan + restore
        rec = tr.events("elastic.recover")[0]
        for child in ("elastic.replan", "elastic.restore"):
            ev = tr.events(child)[0]
            assert rec["ts"] <= ev["ts"]
            assert ev["ts"] + ev["dur"] <= rec["ts"] + rec["dur"] + 1e-3
        path = tr.export_chrome_trace(str(tmp_path / "trace.json"))
        validate_trace(path)
    finally:
        obs.disable_tracing()


def test_conftest_fixture_resets_counters():
    """Paired with the autouse fixture: state bumped in OTHER tests must
    not be visible here (each test starts from zero)."""
    from flexflow_tpu.analysis import diagnostic_counters
    from flexflow_tpu.runtime.durability import checkpoint_counters

    assert checkpoint_counters() == {}
    assert diagnostic_counters() == {}
    assert obs.get_tracer().events() == []
