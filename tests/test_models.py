"""Model-zoo coverage: every reference example family builds, compiles, and
runs one training step (reference analog: examples/cpp/* drivers +
tests/cpp_gpu_tests.sh running each example at small scale)."""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu import models as zoo


def _fit_one(model, inputs, label, batch):
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    hist = model.fit(inputs, label, batch_size=batch, epochs=1)
    assert len(hist) == 1
    assert np.isfinite(hist[0]["loss"]) if "loss" in hist[0] else True
    return hist


def _image_model(builder, chans=3, size=32, batch=4, **kw):
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, chans, size, size])
    out = builder(model, inp, **kw)
    x = np.random.RandomState(0).randn(batch, chans, size, size).astype(np.float32)
    y = np.zeros((batch, 1), dtype=np.int32)
    _fit_one(model, [x], y, batch)
    return out


def test_alexnet_builds_and_trains():
    # AlexNet needs ≥ 65x65 input for its stride stack
    _image_model(zoo.build_alexnet, size=128)


def test_mnist_cnn():
    _image_model(zoo.build_mnist_cnn, chans=1, size=28)


def test_cifar10_cnn():
    _image_model(zoo.build_cifar10_cnn, size=32)


def test_resnet_small():
    _image_model(zoo.build_resnet, size=64, stages=(1, 1))


def test_resnext_small():
    config = ff.FFConfig()
    config.batch_size = 2
    model = ff.FFModel(config)
    inp = model.create_tensor([2, 3, 64, 64])
    out = zoo.build_resnext50(model, inp, num_classes=10, groups=4)
    assert out.dims[-1] == 10


def test_inception_v3_builds():
    config = ff.FFConfig()
    config.batch_size = 2
    model = ff.FFModel(config)
    inp = model.create_tensor([2, 3, 299, 299])
    out = zoo.build_inception_v3(model, inp)
    # channel count after the E blocks is 2048, spatial collapsed
    assert out.dims == (2, 10)


def test_dlrm_trains():
    batch = 8
    cfg = zoo.DLRMConfig(
        sparse_feature_size=8,
        embedding_size=[100, 100],
        mlp_bot=[4, 16, 8],
        mlp_top=[8, 16, 2],
    )
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    dense = model.create_tensor([batch, 4])
    sparse = [
        model.create_tensor([batch, cfg.embedding_bag_size], ff.DataType.DT_INT32)
        for _ in cfg.embedding_size
    ]
    zoo.build_dlrm(model, dense, sparse, cfg)
    rng = np.random.RandomState(0)
    xs = [rng.randn(batch, 4).astype(np.float32)] + [
        rng.randint(0, 100, size=(batch, 1)).astype(np.int32)
        for _ in cfg.embedding_size
    ]
    y = np.zeros((batch, 1), dtype=np.int32)
    _fit_one(model, xs, y, batch)


def test_dlrm_dot_interaction():
    batch = 4
    cfg = zoo.DLRMConfig(
        sparse_feature_size=8,
        embedding_size=[50],
        mlp_bot=[4, 8],
        mlp_top=[8, 2],
        arch_interaction_op="dot",
    )
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    dense = model.create_tensor([batch, 4])
    sparse = [model.create_tensor([batch, 1], ff.DataType.DT_INT32)]
    out = zoo.build_dlrm(model, dense, sparse, cfg)
    assert out.dims[-1] == 2


def test_xdl_builds():
    batch = 8
    cfg = zoo.XDLConfig(sparse_feature_size=8, embedding_size=[100, 100, 100])
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    sparse = [
        model.create_tensor([batch, 1], ff.DataType.DT_INT32)
        for _ in cfg.embedding_size
    ]
    out = zoo.build_xdl(model, sparse, cfg)
    assert out.dims == (batch, 2)


def test_candle_uno_builds():
    batch = 4
    cfg = zoo.CandleUnoConfig(
        dense_layers=[32, 32], dense_feature_layers=[32, 32],
    )
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    feats = {
        "dose1": model.create_tensor([batch, 1]),
        "cell.rnaseq": model.create_tensor([batch, 942]),
        "drug1.descriptors": model.create_tensor([batch, 5270]),
    }
    out = zoo.build_candle_uno(model, feats, cfg)
    assert out.dims == (batch, 1)


def test_mlp_unify_trains():
    batch = 8
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    in1 = model.create_tensor([batch, 16])
    in2 = model.create_tensor([batch, 16])
    zoo.build_mlp_unify(model, in1, in2, hidden_dims=(32, 32))
    rng = np.random.RandomState(0)
    xs = [rng.randn(batch, 16).astype(np.float32) for _ in range(2)]
    y = np.zeros((batch, 1), dtype=np.int32)
    _fit_one(model, xs, y, batch)


def test_transformer_builds():
    cfg = zoo.TransformerConfig(hidden_size=32, embedding_size=32,
                                num_heads=4, num_layers=2, sequence_length=8)
    config = ff.FFConfig()
    config.batch_size = 2
    model = ff.FFModel(config)
    inp = model.create_tensor([2, 8, 32])
    out = zoo.build_transformer(model, inp, cfg)
    assert out.dims == (2, 8, 2)


def test_bert_encoder_trains():
    batch, seq = 2, 8
    cfg = zoo.TransformerConfig(hidden_size=32, num_heads=4, num_layers=1,
                                vocab_size=100)
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    tokens = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    zoo.build_bert_encoder(model, tokens, cfg)
    x = np.random.RandomState(0).randint(0, 100, size=(batch, seq)).astype(np.int32)
    y = np.zeros((batch, seq, 1), dtype=np.int32)
    _fit_one(model, [x], y, batch)


def test_moe_encoder_trains():
    cfg = zoo.MoeConfig(hidden_size=16, num_attention_heads=4,
                        num_encoder_layers=1, num_exp=4, num_select=2)
    batch, seq = 4, 8
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, seq, 16])
    out = zoo.build_moe_encoder(model, inp, cfg)
    assert out.dims == (batch, seq, 16)
    pooled = model.mean(out, [1])
    model.softmax(model.dense(pooled, 10))
    x = np.random.RandomState(0).randn(batch, seq, 16).astype(np.float32)
    y = np.zeros((batch, 1), dtype=np.int32)
    _fit_one(model, [x], y, batch)


def test_lstm_nmt_trains():
    batch, seq = 2, 6
    config = ff.FFConfig()
    config.batch_size = batch
    model = ff.FFModel(config)
    src = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    tgt = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    out = zoo.build_lstm_nmt(model, src, tgt, src_vocab=50, tgt_vocab=50,
                             embed_dim=8, hidden_size=8, num_layers=1)
    assert out.dims == (batch, seq, 50)
    rng = np.random.RandomState(0)
    xs = [rng.randint(0, 50, size=(batch, seq)).astype(np.int32) for _ in range(2)]
    y = np.zeros((batch, seq, 1), dtype=np.int32)
    _fit_one(model, xs, y, batch)


def test_lstm_numerics_vs_reference():
    """Scan LSTM matches a straightforward numpy step-by-step LSTM."""
    import jax.numpy as jnp

    batch, seq, dim, hidden = 2, 5, 3, 4
    config = ff.FFConfig()
    config.batch_size = batch
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, seq, dim])
    out = model.lstm(inp, hidden)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.0),
        loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[],
    )
    x = np.random.RandomState(1).randn(batch, seq, dim).astype(np.float32)
    pred = model.predict([x])

    # extract weights and replay in numpy
    lstm_op = next(op for op in model.graph.ops.values()
                   if op.op_type == ff.OpType.LSTM)
    w = model.params[lstm_op.name]
    wx, wh, b = (np.asarray(w["kernel"]), np.asarray(w["recurrent_kernel"]),
                 np.asarray(w["bias"]))

    def sigmoid(v):
        return 1.0 / (1.0 + np.exp(-v))

    h = np.zeros((batch, hidden), np.float32)
    c = np.zeros((batch, hidden), np.float32)
    outs = []
    for t in range(seq):
        gates = x[:, t] @ wx + h @ wh + b
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sigmoid(f) * c + sigmoid(i) * np.tanh(g)
        h = sigmoid(o) * np.tanh(c)
        outs.append(h)
    ref = np.stack(outs, axis=1)
    np.testing.assert_allclose(pred, ref, rtol=2e-4, atol=2e-4)


def test_model_summary():
    import flexflow_tpu as ff

    config = ff.FFConfig()
    config.batch_size = 8
    model = ff.FFModel(config)
    x = model.create_tensor([8, 16])
    t = model.dense(x, 32, ff.ActiMode.AC_MODE_RELU, name="fc1")
    model.softmax(model.dense(t, 4, name="fc2"))
    out = model.summary(print_fn=None)
    assert "fc1 (linear)" in out and "(8, 32)" in out
    assert "Total params: 676" in out  # 16*32+32 + 32*4+4


def test_moe_transformer_builds_with_rank3_experts():
    """build_moe_transformer keeps the fused EXPERTS ops on the native
    (batch, seq, hidden) states and alternates dense/MoE FFNs under
    moe_every."""
    from flexflow_tpu.ffconst import OpType

    cfg = zoo.MoeTransformerConfig(hidden_size=16, num_heads=4,
                                   num_layers=4, num_experts=4, top_k=2,
                                   moe_every=2, vocab_size=50)
    config = ff.FFConfig()
    config.batch_size = 2
    model = ff.FFModel(config)
    tokens = model.create_tensor([2, 8], ff.DataType.DT_INT32)
    out = zoo.build_moe_transformer(model, tokens, cfg, num_classes=3)
    assert out.dims == (2, 8, 3)
    experts = zoo.moe_expert_ops(model)
    # moe_every=2 on 4 layers -> MoE FFN in layers 1 and 3 only
    assert [op.name for op in experts] == ["l1_moe_experts",
                                           "l3_moe_experts"]
    assert all(op.inputs[0].dims == (2, 8, 16) for op in experts)
    assert all(op.op_type == OpType.EXPERTS for op in experts)


def test_moe_lm_builds_causal_vocab_head():
    cfg = zoo.MoeTransformerConfig(hidden_size=16, num_heads=2,
                                   num_layers=1, num_experts=4, top_k=2,
                                   vocab_size=37)
    config = ff.FFConfig()
    config.batch_size = 2
    model = ff.FFModel(config)
    tokens = model.create_tensor([2, 6], ff.DataType.DT_INT32)
    out = zoo.build_moe_lm(model, tokens, cfg)
    assert out.dims == (2, 6, 37)
    attn = [op for op in model.ops if op.name.endswith("_attn")]
    assert attn and all(op.params.get("causal") for op in attn)


@pytest.mark.slow
def test_moe_transformer_trains_with_balance_loss():
    """End-to-end fit(): the router's load-balance aux loss rides into
    the reported loss (lambda_bal > 0 strictly raises it at identical
    init/data), and one epoch of training leaves finite loss + live
    router state."""
    def run(lambda_bal):
        cfg = zoo.MoeTransformerConfig(hidden_size=16, num_heads=2,
                                       num_layers=1, num_experts=4,
                                       top_k=2, lambda_bal=lambda_bal,
                                       vocab_size=50)
        config = ff.FFConfig()
        config.batch_size = 4
        config.seed = 7
        model = ff.FFModel(config)
        tokens = model.create_tensor([4, 8], ff.DataType.DT_INT32)
        zoo.build_moe_transformer(model, tokens, cfg)
        rng = np.random.RandomState(5)
        x = rng.randint(0, 50, size=(4, 8)).astype(np.int32)
        y = np.zeros((4, 8, 1), dtype=np.int32)
        model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                      loss_type=ff.LossType
                      .LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        hist = model.fit([x], y, batch_size=4, epochs=1)
        return model, hist[0]["loss"]

    model0, loss0 = run(0.0)
    model1, loss1 = run(0.5)
    assert np.isfinite(loss0) and np.isfinite(loss1)
    assert loss1 > loss0  # the aux loss is folded into fit()'s loss
    # router state was threaded through the step
    load = np.asarray(model1.state["l0_moe_experts"]["load"])
    assert load.shape == (4,) and np.isclose(load.sum(), 1.0, atol=1e-4)
