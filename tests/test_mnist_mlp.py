"""End-to-end MNIST-style MLP training (reference analog:
examples/python/native/mnist_mlp.py with the ≥90% accuracy gate from
examples/python/native/accuracy.py:19-24 — here a learnable synthetic task)."""
import numpy as np
import pytest

import flexflow_tpu as ff


def make_synthetic(n=2048, dim=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype(np.float32)
    w = rng.randn(dim, classes).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)[:, None]
    return x, y


def test_mlp_trains_to_accuracy():
    config = ff.FFConfig()
    config.batch_size = 64
    config.epochs = 12
    x, y = make_synthetic()

    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 64])
    t = model.dense(inp, 128, ff.ActiMode.AC_MODE_RELU)
    t = model.dense(t, 10)
    t = model.softmax(t)
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=2e-3),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[
            ff.MetricsType.METRICS_ACCURACY,
            ff.MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
        ],
    )
    history = model.fit(x, y)
    assert history[-1]["accuracy"] > 0.9, history[-1]
    # loss must decrease
    assert history[-1]["sparse_cce"] < history[0]["sparse_cce"]

    ev = model.eval(x[:512], y[:512])
    assert ev["accuracy"] > 0.9


def test_manual_training_loop():
    """reference parity: forward/zero_gradients/backward/update manual loop."""
    config = ff.FFConfig()
    config.batch_size = 32
    x, y = make_synthetic(n=256, dim=32)

    model = ff.FFModel(config)
    inp = model.create_tensor([32, 32])
    t = model.dense(inp, 64, ff.ActiMode.AC_MODE_RELU)
    t = model.dense(t, 10)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.05),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    losses = []
    for it in range(8):
        model.set_iteration_batch([x[:32]], y[:32])
        model.forward()
        model.zero_gradients()
        model.backward()
        model.update()
        import jax.numpy as jnp

        pred = model._manual["pred"]
        from flexflow_tpu.runtime.losses import sparse_categorical_crossentropy

        losses.append(float(sparse_categorical_crossentropy(pred, jnp.asarray(y[:32]))))
    assert losses[-1] < losses[0]


def test_dataloader_fit():
    config = ff.FFConfig()
    config.batch_size = 32
    config.epochs = 2
    x, y = make_synthetic(n=256, dim=32)
    model = ff.FFModel(config)
    inp = model.create_tensor([32, 32])
    t = model.dense(inp, 10)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.05),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    ff.SingleDataLoader(model, inp, x, 256)
    ff.SingleDataLoader(model, model.label_tensor, y, 256)
    history = model.fit()
    assert len(history) == 2


def test_steps_per_execution_on_data_parallel_mesh():
    """Chunked fit on a data=8 mesh: the stacked (K, B, ...) batches shard
    the SECOND axis over 'data' (batch_axis=1), and the K-step scan carries
    sharded params — numerics match the plain dp fit exactly."""
    import jax

    def build():
        config = ff.FFConfig()
        config.batch_size = 16
        config.allow_mixed_precision = False
        config.seed = 13
        model = ff.FFModel(config)
        x = model.create_tensor([16, 12])
        t = model.dense(x, 8, ff.ActiMode.AC_MODE_RELU)
        model.softmax(model.dense(t, 3))
        model.compile(
            optimizer=ff.AdamOptimizer(model, alpha=0.01),
            loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
            metrics=[],
            parallel_axes={"data": 8},
        )
        return model

    rng = np.random.RandomState(2)
    X = rng.randn(64, 12).astype(np.float32)
    Y = rng.randint(0, 3, size=(64, 1)).astype(np.int32)

    plain = build()
    chunked = build()
    plain.fit(x=X, y=Y, epochs=1)
    chunked.fit(x=X, y=Y, epochs=1, steps_per_execution=4)
    for a, b in zip(jax.tree_util.tree_leaves(plain.params),
                    jax.tree_util.tree_leaves(chunked.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


def test_dataloader_fit_steps_per_execution():
    """Attached dataloaders drive the chunked path: load_host pulls K
    sequential batches per dispatch, so the prefetch ring and shuffle
    stream stay aligned with the x/y pairing."""
    config = ff.FFConfig()
    config.batch_size = 16
    config.epochs = 2
    x, y = make_synthetic(n=96, dim=32)
    model = ff.FFModel(config)
    inp = model.create_tensor([16, 32])
    model.softmax(model.dense(inp, 10))
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.05),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    ff.SingleDataLoader(model, inp, x, 96)
    ff.SingleDataLoader(model, model.label_tensor, y, 96)
    history = model.fit(steps_per_execution=3)
    assert len(history) == 2
    assert np.isfinite(history[-1]["loss"])
    assert history[-1]["loss"] < history[0]["loss"] + 1e-6


def test_steps_per_execution_matches_single_step():
    """fit(steps_per_execution=4) — K optimizer steps per jitted dispatch —
    produces the same final params and losses as plain fit, to float
    tolerance (the scan body IS the single train step; the model has no
    dropout, so the documented rng-stream difference between the two paths
    cannot affect numerics). n=20 with bs*K=16 exercises the trailing-
    samples path: the last update of each epoch runs single-step, keeping
    updates-per-epoch equal to plain fit's n//bs."""
    import flexflow_tpu as ff

    def build():
        config = ff.FFConfig()
        config.batch_size = 4
        config.allow_mixed_precision = False
        config.seed = 11
        model = ff.FFModel(config)
        x = model.create_tensor([4, 6])
        t = model.dense(x, 8, ff.ActiMode.AC_MODE_RELU)
        model.softmax(model.dense(t, 3))
        model.compile(optimizer=ff.AdamOptimizer(model, alpha=0.01),
                      loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                      metrics=[ff.MetricsType.METRICS_ACCURACY])
        return model

    rng = np.random.RandomState(1)
    X = rng.randn(20, 6).astype(np.float32)
    Y = rng.randint(0, 3, size=(20, 1)).astype(np.int32)

    plain = build()
    chunked = build()
    h1 = plain.fit(x=X, y=Y, epochs=2)
    h2 = chunked.fit(x=X, y=Y, epochs=2, steps_per_execution=4)

    import jax

    for a, b in zip(jax.tree_util.tree_leaves(plain.params),
                    jax.tree_util.tree_leaves(chunked.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    # epoch summaries agree (same updates, same metric accounting weights)
    for k in ("loss", "accuracy"):
        np.testing.assert_allclose(h1[-1][k], h2[-1][k], atol=1e-5, rtol=1e-5)
    # _step_count counts OPTIMIZER steps under chunking, not dispatches
    # (advisor r4: recompile warmup and checkpointed step_count must not
    # silently mean K x more steps when fit is chunked)
    assert plain._step_count == chunked._step_count
    # mutual exclusion with accumulation
    import pytest

    with pytest.raises(ValueError, match="mutually exclusive"):
        plain.fit(x=X, y=Y, epochs=1, accum_steps=2, steps_per_execution=2)


def test_steps_per_execution_with_dropout_trains():
    """Dropout under the chunked path: the rng stream legitimately differs
    from single-step fit (documented in the fit docstring — keys split per
    chunk), so this asserts training behavior, not bit equality: the model
    learns, and the per-epoch losses are not all identical (a constant
    dropout mask — e.g. one key reused for all K scan steps — would make
    successive same-data epochs nearly deterministic replicas)."""
    import flexflow_tpu as ff

    config = ff.FFConfig()
    config.batch_size = 8
    config.seed = 3
    model = ff.FFModel(config)
    x = model.create_tensor([8, 16])
    t = model.dense(x, 32, ff.ActiMode.AC_MODE_RELU)
    t = model.dropout(t, 0.5)
    model.softmax(model.dense(t, 3))
    model.compile(optimizer=ff.AdamOptimizer(model, alpha=0.01),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[])
    rng = np.random.RandomState(7)
    X = rng.randn(64, 16).astype(np.float32)
    Y = (X[:, :1].sum(-1, keepdims=True) > 0).astype(np.int32)
    hist = model.fit(x=X, y=Y, epochs=6, steps_per_execution=4)
    assert np.isfinite(hist[-1]["loss"])
    assert hist[-1]["loss"] < hist[0]["loss"]
    # per-step rng actually varies: epoch losses must not be constant
    losses = [h["loss"] for h in hist]
    assert len({round(l, 8) for l in losses}) > 1, losses


def test_gradient_accumulation_matches_large_batch():
    """SGD with fit(accum_steps=2) at microbatch 4 must match one batch-8
    step exactly (per-batch mean losses: the accumulated average IS the
    full-batch gradient)."""
    import flexflow_tpu as ff

    def build(bs):
        config = ff.FFConfig()
        config.batch_size = bs
        config.allow_mixed_precision = False
        config.seed = 7
        model = ff.FFModel(config)
        x = model.create_tensor([bs, 6])
        t = model.dense(x, 8, ff.ActiMode.AC_MODE_RELU)
        model.softmax(model.dense(t, 3))
        model.compile(optimizer=ff.SGDOptimizer(model, lr=0.1),
                      loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                      metrics=[])
        return model

    rng = np.random.RandomState(0)
    X = rng.randn(8, 6).astype(np.float32)
    Y = rng.randint(0, 3, size=(8, 1)).astype(np.int32)

    big = build(8)
    small = build(4)
    # same seed => identical init
    big.fit(x=X, y=Y, epochs=1)
    small.fit(x=X, y=Y, epochs=1, accum_steps=2)

    import jax

    assert (jax.tree_util.tree_structure(big.params)
            == jax.tree_util.tree_structure(small.params))
    for a, b in zip(jax.tree_util.tree_leaves(big.params),
                    jax.tree_util.tree_leaves(small.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    # microbatches are SUB-steps: one optimizer update advances the step
    # counter once, same as the equivalent large-batch step
    assert small._step_count == big._step_count == 1
