"""Native C++ core (libffcore) tests: graph algorithms and the Unity search
must agree with the pure-Python implementations (reference test model:
tests/unit/test_dominators.cc, test_machine_view.cc)."""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu import native
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.search.machine_model import TpuPodModel
from flexflow_tpu.search.unity import GraphSearchHelper, unity_optimize

pytestmark = pytest.mark.skipif(
    not native.available(), reason="libffcore not buildable"
)


def build_mlp_model(n_dev=4, batch=32, tp_friendly=True):
    config = ff.FFConfig()
    config.batch_size = batch
    config.num_devices = n_dev
    config.search_budget = 8
    model = ff.FFModel(config)
    t = model.create_tensor([batch, 64], ff.DataType.DT_FLOAT)
    h = model.dense(t, 128 if tp_friendly else 126, ff.ActiMode.AC_MODE_RELU)
    h = model.dense(h, 128, ff.ActiMode.AC_MODE_RELU)
    out = model.dense(h, 10)
    out = model.softmax(out)
    return config, model


def branching_model():
    config = ff.FFConfig()
    config.batch_size = 16
    model = ff.FFModel(config)
    t = model.create_tensor([16, 32], ff.DataType.DT_FLOAT)
    a = model.dense(t, 32, name="branch_a")
    b = model.dense(t, 32, name="branch_b")
    m = model.add(a, b)
    out = model.dense(m, 8, name="join")
    return config, model


def test_version():
    assert native.version().startswith("ffcore")


def test_topo_matches_python():
    config, model = branching_model()
    g = Graph(model.ops)
    ours = native.topo_order(g)
    theirs = [op.guid for op in g.topo_order()]
    assert ours == theirs


def test_bottlenecks_match_python():
    config, model = branching_model()
    g = Graph(model.ops)
    ours = native.bottlenecks(g)
    theirs = [op.guid for op in g.bottleneck_nodes()]
    assert ours == theirs
    # the join dense and the add must be bottlenecks; the branches must not
    names = {g.ops[guid].name for guid in ours}
    assert "join" in names
    assert "branch_a" not in names


def test_search_agrees_with_python():
    config, model = build_mlp_model()
    g = Graph(model.ops)
    machine = TpuPodModel(4)

    native_res = native.optimize_strategy(g, config, machine, 32, 4)

    config.use_native_search = False
    helper = GraphSearchHelper(g, config, machine)
    py_res = helper.graph_optimize(32, 4)

    # identical cost model -> near-identical optimal cost
    assert native_res.cost_us == pytest.approx(py_res.cost_us, rel=1e-6)
    assert native_res.mesh_axes == py_res.mesh_axes
    # strategies agree per-op (same menu order, same tie-breaking)
    for guid, s in py_res.strategies.items():
        ns = native_res.strategies[guid]
        assert (ns.dp, ns.tp) == (s.dp, s.tp), g.ops[guid].name


def test_unity_optimize_dispatches_to_native():
    config, model = build_mlp_model()
    g = Graph(model.ops)
    machine = TpuPodModel(4)
    res = unity_optimize(g, config, machine, 32, 4)
    assert any("native" in line for line in res.log)
    assert res.cost_us > 0


def test_native_memory_search_penalizes_overflow():
    config, model = build_mlp_model()
    g = Graph(model.ops)
    machine = TpuPodModel(4)
    base = native.optimize_strategy(g, config, machine, 32, 4)
    config.memory_search = True
    config.memory_budget_mb = 1e-3  # impossible budget -> penalty applies
    res = native.optimize_strategy(g, config, machine, 32, 4)
    assert res.cost_us > base.cost_us


def test_native_mcmc_never_worse():
    config, model = build_mlp_model()
    g = Graph(model.ops)
    machine = TpuPodModel(4)
    base = native.optimize_strategy(g, config, machine, 32, 4)
    refined = native.optimize_strategy(g, config, machine, 32, 4,
                                       mcmc_iters=200)
    assert refined.cost_us <= base.cost_us * (1 + 1e-9)


def test_compile_uses_native_search_end_to_end():
    config = ff.FFConfig()
    config.batch_size = 32
    config.num_devices = 1  # single real device; search still runs
    config.search_budget = 4
    model = ff.FFModel(config)
    t = model.create_tensor([32, 64], ff.DataType.DT_FLOAT)
    h = model.dense(t, 128, ff.ActiMode.AC_MODE_RELU)
    out = model.softmax(model.dense(h, 10))
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.05),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[ff.MetricsType.METRICS_ACCURACY])
    x = np.random.RandomState(0).randn(32, 64).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, (32, 1)).astype(np.int32)
    hist = model.fit([x], y, epochs=1)
    assert len(hist) == 1


def test_native_infeasible_raises():
    config, model = build_mlp_model(n_dev=4, batch=30)  # 30 % 4 != 0
    config.only_data_parallel = True
    g = Graph(model.ops)
    machine = TpuPodModel(4)
    with pytest.raises(ValueError, match="no feasible"):
        native.optimize_strategy(g, config, machine, 30, 4)


def transformer_model(n_dev=8, batch=16, seq=32, dropout=0.0):
    config = ff.FFConfig()
    config.batch_size = batch
    config.num_devices = n_dev
    config.search_budget = 8
    config.enable_sequence_parallel = True
    config.refine_top_k = 99  # refine every factorization: exact parity
    model = ff.FFModel(config)
    from flexflow_tpu.models import TransformerConfig, build_bert_encoder

    cfg = TransformerConfig(hidden_size=32, embedding_size=32, num_heads=4,
                            num_layers=2, sequence_length=seq, vocab_size=50)
    tokens = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    build_bert_encoder(model, tokens, cfg)
    return config, model


def test_native_sp_search_agrees_with_python():
    """The native core enumerates the 'seq' axis (round 4): same cost and
    per-op (dp, tp, sp) as the Python search under
    --enable-sequence-parallel."""
    config, model = transformer_model()
    g = Graph(model.ops)
    machine = TpuPodModel(8)

    native_res = native.optimize_strategy(g, config, machine, 16, 8)

    config.use_native_search = False
    helper = GraphSearchHelper(g, config, machine)
    py_res = helper.graph_optimize(16, 8)

    assert native_res.cost_us == pytest.approx(py_res.cost_us, rel=1e-6)
    assert native_res.mesh_axes == py_res.mesh_axes
    for guid, s in py_res.strategies.items():
        ns = native_res.strategies[guid]
        assert (ns.dp, ns.tp, ns.sp) == (s.dp, s.tp, s.sp), g.ops[guid].name


def test_native_sp_gated_by_dropout():
    """Attention-prob dropout has no SP kernel: both paths refuse sp > 1."""
    config, model = transformer_model()
    for op in model.ops:
        if op.op_type.value == "multihead_attention":
            op.params["dropout"] = 0.1
    g = Graph(model.ops)
    machine = TpuPodModel(8)
    res = native.optimize_strategy(g, config, machine, 16, 8)
    assert "seq" not in res.mesh_axes


def test_native_dispatch_covers_sp():
    """unity_optimize routes --enable-sequence-parallel graphs through the
    native core now (it forced the Python path before round 4)."""
    config, model = transformer_model()
    g = Graph(model.ops)
    machine = TpuPodModel(8)
    res = unity_optimize(g, config, machine, 16, 8)
    assert any("native" in line for line in res.log)


def moe_model(n_dev=8, batch=512):
    """Expert-FFN-dominated graph: the winning strategy should shard the
    EXPERTS op over the expert axis (mirrors test_experts.py's search
    test, here for native/Python parity)."""
    B, F, n, k, H = batch, 1024, 8, 2, 4096
    config = ff.FFConfig()
    config.batch_size = B
    config.num_devices = n_dev
    config.search_budget = 8
    config.refine_top_k = 99  # refine every factorization: exact parity
    model = ff.FFModel(config)
    inp = model.create_tensor([B, F])
    out = model.moe(inp, n, k, H, alpha=float(n), fused=True, name="moe")
    model.dense(out, 3)
    return config, model


def test_native_ep_search_agrees_with_python():
    """The native core enumerates the 'expert' axis (round 4, session 3):
    same cost and per-op (dp, tp, ep) as the Python search on an
    expert-dominated MoE graph — and BOTH pick ep > 1."""
    config, model = moe_model()
    g = Graph(model.ops)
    machine = TpuPodModel(8)

    native_res = native.optimize_strategy(g, config, machine, 512, 8)

    config.use_native_search = False
    helper = GraphSearchHelper(g, config, machine)
    py_res = helper.graph_optimize(512, 8)

    assert native_res.cost_us == pytest.approx(py_res.cost_us, rel=1e-6)
    assert native_res.mesh_axes == py_res.mesh_axes
    assert py_res.mesh_axes.get("expert", 1) > 1, py_res.log
    for guid, s in py_res.strategies.items():
        ns = native_res.strategies[guid]
        assert (ns.dp, ns.tp, ns.ep) == (s.dp, s.tp, s.ep), g.ops[guid].name


def test_native_dispatch_covers_experts():
    """unity_optimize routes EXPERTS graphs through the native core now
    (has_experts forced the Python path before round 4 session 3)."""
    config, model = moe_model()
    g = Graph(model.ops)
    machine = TpuPodModel(8)
    res = unity_optimize(g, config, machine, 512, 8)
    assert any("native" in line for line in res.log), res.log
    assert res.mesh_axes.get("expert", 1) > 1, res.log


def conv_model(n_dev=8, batch=4):
    """Spatially-dominated conv graph under --enable-attribute-parallel:
    batch (4) < devices (8), so data parallelism alone cannot use the
    mesh and the winning factorization must shard H over 'attr'."""
    config = ff.FFConfig()
    config.batch_size = batch
    config.num_devices = n_dev
    config.search_budget = 8
    config.enable_attribute_parallel = True
    config.refine_top_k = 99  # refine every factorization: exact parity
    model = ff.FFModel(config)
    # big spatial extent: per-op compute must dominate the cost model's
    # per-op floors or spatial sharding can never win
    inp = model.create_tensor([batch, 32, 256, 256])
    t = model.conv2d(inp, 64, 3, 3, 1, 1, 1, 1, name="c1")
    t = model.conv2d(t, 64, 3, 3, 1, 1, 1, 1, name="c2")
    t = model.flat(t, name="flat")
    # 3 classes: indivisible head, so tp cannot absorb the leftover mesh
    # and the spatial 'attr' axis is the only way to use all 8 devices
    model.softmax(model.dense(t, 3, name="cls"))
    return config, model


def test_native_ap_search_agrees_with_python():
    """The native core enumerates the 'attr' axis (round 4, session 3):
    same cost and per-op (dp, tp, ap) as the Python search under
    --enable-attribute-parallel — and BOTH pick ap > 1 (the exact-parity
    claim is only meaningful when the axis under test actually engages;
    the first version of this test was won by pure dp and asserted
    nothing about ap)."""
    config, model = conv_model()
    g = Graph(model.ops)
    machine = TpuPodModel(8)

    native_res = native.optimize_strategy(g, config, machine, 4, 8)

    config.use_native_search = False
    helper = GraphSearchHelper(g, config, machine)
    py_res = helper.graph_optimize(4, 8)

    assert native_res.cost_us == pytest.approx(py_res.cost_us, rel=1e-6)
    assert native_res.mesh_axes == py_res.mesh_axes
    assert py_res.mesh_axes.get("attr", 1) > 1, py_res.log
    for guid, s in py_res.strategies.items():
        ns = native_res.strategies[guid]
        assert (ns.dp, ns.tp, ns.ap) == (s.dp, s.tp, s.ap), g.ops[guid].name


def test_native_dispatch_covers_attr():
    """unity_optimize routes --enable-attribute-parallel graphs through the
    native core now (wants_attr forced the Python path before r4s3)."""
    config, model = conv_model()
    g = Graph(model.ops)
    machine = TpuPodModel(8)
    res = unity_optimize(g, config, machine, 4, 8)
    assert any("native" in line for line in res.log), res.log
    assert res.mesh_axes.get("attr", 1) > 1, res.log


def megatron_model(n_dev=8, batch=8):
    """Big paired linears under --enable-parameter-parallel: the winning
    layout is the Megatron column->row pair."""
    config = ff.FFConfig()
    config.batch_size = batch
    config.num_devices = n_dev
    config.search_budget = 8
    config.enable_parameter_parallel = True
    config.refine_top_k = 99
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, 4096])
    t = model.dense(inp, 8192, ff.ActiMode.AC_MODE_RELU, name="up")
    t = model.dense(t, 4096, name="down")
    model.softmax(model.dense(t, 4, name="cls"))
    return config, model


def test_native_row_tp_search_agrees_with_python():
    """The native core emits row-parallel strategies (round 4, session 3):
    same cost and per-op (dp, tp, tp_row) as the Python search under
    --enable-parameter-parallel, and BOTH pick the column->row pairing."""
    config, model = megatron_model()
    g = Graph(model.ops)
    machine = TpuPodModel(8)

    native_res = native.optimize_strategy(g, config, machine, 8, 8)

    config.use_native_search = False
    helper = GraphSearchHelper(g, config, machine)
    py_res = helper.graph_optimize(8, 8)

    assert native_res.cost_us == pytest.approx(py_res.cost_us, rel=1e-6)
    assert native_res.mesh_axes == py_res.mesh_axes
    assert any(s.tp_row for s in py_res.strategies.values()), py_res.log
    for guid, s in py_res.strategies.items():
        ns = native_res.strategies[guid]
        assert (ns.dp, ns.tp, ns.tp_row) == (s.dp, s.tp, s.tp_row), \
            g.ops[guid].name
