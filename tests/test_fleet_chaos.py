"""Fleet failure domain (ISSUE 18): chaos injection, health detection,
and token-exact in-flight failover.

The decisive properties:
 - `FleetFaultPlan.randomized(seed)` is deterministic: same seed, same
   schedule — the reproducibility contract of every chaos run;
 - a crashed scheduler (dead thread) is declared DEAD by one synchronous
   `HealthMonitor.poll()` and `Router.fail_over` replays its in-flight
   requests on survivors with IDENTICAL tokens — mid-decode, queued
   (between submit and slot bind), and mid-drain alike;
 - failure surfaces as a typed `ReplicaLost`, never a hang: a fleet
   with no survivors terminates the handle instead of blocking it, and
   `Router.remove` exits its drain-wait when the replica dies under it;
 - hangs flag via heartbeat age, stragglers flag SUSPECT (never DEAD)
   via the fleet-median step-latency score;
 - the Autoscaler respawns the dead replica under the same name and
   `health()` walks degraded -> ok.

Monitors in these tests use huge heartbeat windows unless the test IS
about heartbeats: a cold dispatch compile stalls the scheduler loop for
seconds and is indistinguishable from a hang, so heartbeat tests warm
the replica first and every other test relies on the dead-thread probe
(which needs no window at all).
"""
import threading
import time

import numpy as np
import pytest

from flexflow_tpu.serving.fleet import (Autoscaler, ChaosEngine,
                                        FleetFault, FleetFaultPlan,
                                        HealthMonitor, HealthState,
                                        InjectedCrash, Replica,
                                        ReplicaLost, ReplicaState, Router)
from tests.conftest import module_xla_cache
from tests.test_generate import _build_lm

# module-scoped XLA compilation cache — see conftest.module_xla_cache
_xla_cache = pytest.fixture(scope="module", autouse=True)(module_xla_cache)


@pytest.fixture(scope="module")
def lm():
    return _build_lm(2, 12)


def _mk_replica(lm, name, slots=2, max_len=48, page_size=4, max_queue=32,
                **kw):
    return Replica(name, lm, max_len=max_len, num_slots=slots,
                   page_size=page_size, max_queue=max_queue, **kw)


def _mk_fleet(lm, n=2, **kw):
    router = Router(**{k: v for k, v in kw.items()
                       if k in ("policy", "slo_ttft_s", "route_depth")})
    rep_kw = {k: v for k, v in kw.items()
              if k not in ("policy", "slo_ttft_s", "route_depth")}
    for i in range(n):
        router.add_replica(f"r{i}", _mk_replica(lm, f"r{i}", **rep_kw))
    return router


def _prompt(n, seed=0, vocab=50):
    rng = np.random.RandomState(seed)
    return rng.randint(1, vocab, size=(n,)).astype(np.int32)


def _monitor(router, **kw):
    """A monitor that only ever fires on the dead-thread probe: the
    heartbeat windows are far beyond any test's runtime, so compile
    stalls can never produce a verdict."""
    kw.setdefault("suspect_after_s", 300.0)
    kw.setdefault("dead_after_s", 600.0)
    return HealthMonitor(router, **kw)


def _poll_until_dead(mon, name, timeout=30.0):
    """Synchronous sweeps (the injected fault needs a scheduler
    iteration or two to fire) until the DEAD verdict lands — and with
    it, the default on_dead already ran fail_over."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        mon.poll()
        if mon.state(name) is HealthState.DEAD:
            return
        time.sleep(0.02)
    raise AssertionError(f"{name} never went DEAD: {mon.states()}")


# ---------------------------------------------------------------------
# fault plans: determinism + validation
# ---------------------------------------------------------------------
def test_fault_plan_same_seed_identical_schedule():
    names = ["r0", "r1", "r2"]
    a = FleetFaultPlan.randomized(7, names).describe()
    b = FleetFaultPlan.randomized(7, names).describe()
    assert a == b
    assert FleetFaultPlan.randomized(8, names).describe() != a
    # the schedule is pure config — no runtime state leaks into it
    assert all(set(f) == {"kind", "replica", "at_token", "stall_s",
                          "iterations", "submits"} for f in a)


def test_fault_plan_rejects_unknown_kind():
    with pytest.raises(ValueError):
        FleetFault("meteor", "r0")
    with pytest.raises(ValueError):
        FleetFaultPlan.randomized(0, ["r0"], kinds=("crash", "meteor"))


def test_fault_plan_builders_and_for_replica():
    plan = FleetFaultPlan().crash("r0", at_token=5) \
        .hang("r1", stall_s=0.5).flaky_submit("r0", submits=2)
    assert [f.kind for f in plan.for_replica("r0")] == ["crash",
                                                        "flaky_submit"]
    assert plan.describe()[1]["stall_s"] == 0.5


# ---------------------------------------------------------------------
# crash -> DEAD -> token-exact failover
# ---------------------------------------------------------------------
def test_crash_mid_decode_failover_token_parity(lm):
    router = _mk_fleet(lm, 2)
    mon = _monitor(router)
    try:
        prompts = [_prompt(6, seed=s) for s in (1, 2, 3, 4)]
        # fault-free reference: greedy tokens are a pure function of the
        # prompt, so any healthy run of the same prompts is THE oracle
        ref = [list(router.submit(p, 10).result(timeout=300))
               for p in prompts]
        handles = [router.submit(p, 10) for p in prompts]
        # crash wherever the first request landed, on that replica's
        # next scheduler iteration — guaranteed mid-flight work
        victim = handles[0].replica
        at = router.replica(victim).batcher.tokens_emitted
        engine = ChaosEngine(FleetFaultPlan().crash(victim, at_token=at))
        engine.arm(router)
        _poll_until_dead(mon, victim)
        got = [list(h.result(timeout=300)) for h in handles]
        assert got == [list(map(int, r)) for r in ref]
        # the victim really was loaded: something failed over mid-flight
        assert any(h.failovers > 0 for h in handles)
        assert all(h.error is None and h.done() for h in handles)
        assert [f["kind"] for f in engine.fired] == ["crash"]
        assert victim not in router.replica_names()
        assert router.lost_replicas() == {victim: "scheduler_crashed"}
        assert router.health()["status"] == "degraded"
    finally:
        router.shutdown()


def test_crash_with_queued_request_replays_from_prompt(lm):
    """A request caught between submit() and its slot bind has emitted
    nothing — failover must replay it from the bare prompt."""
    router = _mk_fleet(lm, 2)
    mon = _monitor(router)
    try:
        # home three same-prefix requests on one replica: 2 slots fill,
        # the third queues behind them
        prefix = _prompt(8, seed=11)
        mk = lambda s: np.concatenate([prefix, _prompt(3, seed=s)])
        lead = router.submit(mk(1), 12)
        victim = lead.replica
        # the prefix pages land in the victim's cache as the lead's
        # prefill completes — wait for its first token so the followers
        # route affine (to the victim) instead of racing the install
        deadline = time.monotonic() + 30.0
        while not lead.tokens and time.monotonic() < deadline:
            time.sleep(0.01)
        rest = [router.submit(mk(s), 12) for s in (2, 3)]
        assert rest[-1].replica == victim  # affine kept the tenant home
        at = router.replica(victim).batcher.tokens_emitted + 2
        engine = ChaosEngine(FleetFaultPlan().crash(victim, at_token=at))
        engine.arm(router)
        _poll_until_dead(mon, victim)
        got = [list(h.result(timeout=300)) for h in [lead] + rest]
        # oracle after the fact: the survivor decodes the same prompts
        ref = [list(router.submit(mk(s), 12).result(timeout=300))
               for s in (1, 2, 3)]
        assert got == ref
        assert all(h.error is None for h in [lead] + rest)
    finally:
        router.shutdown()


def test_crash_during_drain_handoff(lm):
    """Drain hands the queued work off, then the drained replica dies
    with sequences still decoding: fail_over replays them and
    `remove()`'s drain-wait exits instead of spinning to timeout."""
    router = _mk_fleet(lm, 2)
    mon = _monitor(router)
    try:
        prefix = _prompt(8, seed=21)
        mk = lambda s: np.concatenate([prefix, _prompt(3, seed=s)])
        lead = router.submit(mk(1), 16)
        victim = lead.replica
        more = [router.submit(mk(s), 16) for s in (2, 3, 4)]
        # straggle the victim so its actives provably outlive the drain
        # below — on a hot compile cache 16 greedy tokens take tens of
        # milliseconds, and the crash must land while they decode
        engine = ChaosEngine(FleetFaultPlan().straggle(
            victim, at_token=0, stall_s=0.08, iterations=1000))
        engine.arm(router)
        deadline = time.monotonic() + 30.0
        while router.replica(victim).live_sequences() < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.01)  # both slots bound -> exactly 2 queued
        stats = router.drain(victim)  # queued work re-homes now
        assert router.replica(victim).state is ReplicaState.DRAINING
        removed = threading.Event()

        def _remove():
            router.remove(victim, timeout=120.0)
            removed.set()

        t = threading.Thread(target=_remove, daemon=True)
        t.start()
        # kill it mid-drain, actives still decoding (the straggle
        # guarantees ~1.3 s of runway); re-arm to pick up the new fault
        at = router.replica(victim).batcher.tokens_emitted + 1
        engine.plan.crash(victim, at_token=at)
        engine.arm(router)
        # either the monitor's sweep or remove()'s own dead-scheduler
        # check wins the race to fail_over — both must replay the work
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            mon.poll()
            if victim in router.lost_replicas():
                break
            time.sleep(0.02)
        assert router.lost_replicas() == {victim: "scheduler_crashed"}
        assert removed.wait(timeout=30.0), \
            "remove() kept waiting on a DEAD replica's drain"
        got = [list(h.result(timeout=300)) for h in [lead] + more]
        ref = [list(router.submit(mk(s), 16).result(timeout=300))
               for s in (1, 2, 3, 4)]
        assert got == ref
        # both slots were bound when drain ran: the two queued requests
        # re-homed, the two actives stayed to finish (and then crashed)
        assert stats == {"handed_off": 2, "kept": 2}
    finally:
        router.shutdown()


def test_no_survivor_surfaces_typed_replica_lost(lm):
    """A fleet of one: the crash leaves nobody to replay on — the
    caller gets a typed ReplicaLost promptly, never a hang."""
    router = _mk_fleet(lm, 1)
    mon = _monitor(router)
    try:
        h = router.submit(_prompt(6, seed=31), 12)
        at = router.replica("r0").batcher.tokens_emitted + 2
        engine = ChaosEngine(FleetFaultPlan().crash("r0", at_token=at))
        engine.arm(router)
        _poll_until_dead(mon, "r0")
        with pytest.raises(ReplicaLost):
            h.result(timeout=30.0)
        assert isinstance(h.error, ReplicaLost)
        assert h.done()
        assert router.health()["status"] == "down"
    finally:
        router.shutdown()


def test_flaky_submit_is_invisible_to_callers(lm):
    router = _mk_fleet(lm, 2)
    try:
        engine = ChaosEngine(FleetFaultPlan().flaky_submit("r0",
                                                           submits=2))
        engine.arm(router)
        handles = [router.submit(_prompt(6, seed=s), 6)
                   for s in range(5, 11)]
        for h in handles:
            h.result(timeout=300.0)
        assert all(h.error is None for h in handles)
        fired = [f["kind"] for f in engine.fired]
        assert fired.count("flaky_submit") == 2
        engine.disarm()
        # submit is restored: no more injections
        router.submit(_prompt(6, seed=12), 4).result(timeout=300.0)
        assert len(engine.fired) == 2
    finally:
        router.shutdown()


# ---------------------------------------------------------------------
# respawn: degraded -> ok
# ---------------------------------------------------------------------
def test_autoscaler_respawns_dead_replica_to_ok(lm):
    router = _mk_fleet(lm, 2)
    mon = _monitor(router)
    asc = Autoscaler(router, min_slots=2, max_slots=2,
                     replica_factory=lambda: _mk_replica(lm, "respawn"),
                     max_replicas=2, min_replicas=2,
                     idle_ticks_before_drain=10**9, monitor=mon)
    try:
        h = router.submit(_prompt(6, seed=41), 8)
        # at_token = the CURRENT count: the crash fires on r0's very next
        # scheduler iteration (idle iterations run the hook too), so the
        # test never depends on where `h` was routed
        at = router.replica("r0").batcher.tokens_emitted
        engine = ChaosEngine(FleetFaultPlan().crash("r0", at_token=at))
        engine.arm(router)
        _poll_until_dead(mon, "r0")
        assert router.health()["status"] == "degraded"
        actions = asc.tick()
        assert [a["action"] for a in actions] == ["respawn"]
        assert sorted(router.replica_names()) == ["r0", "r1"]
        assert router.lost_replicas() == {}
        assert router.health()["status"] == "ok"
        # the verdict was reset: the respawned name is READY again
        assert mon.state("r0") is HealthState.READY
        h.result(timeout=300.0)
        # the replacement takes traffic under the old name
        router.submit(_prompt(6, seed=42), 4).result(timeout=300.0)
    finally:
        router.shutdown()


# ---------------------------------------------------------------------
# heartbeat + straggler probes (real stalls: slow lane)
# ---------------------------------------------------------------------
@pytest.mark.slow
def test_hang_detection_and_failover_via_heartbeat(lm):
    router = _mk_fleet(lm, 2)
    try:
        # warm the victim OUTSIDE the monitored window: a cold-dispatch
        # compile stalls the loop exactly like a hang would
        for name in router.replica_names():
            router.replica(name).submit(
                np.zeros(5, np.int32), 2).result(timeout=600.0)
        mon = HealthMonitor(router, suspect_after_s=0.2,
                            dead_after_s=0.6)
        # fault-free oracle first; affine then routes the real request
        # back to the same home (the prefix page is cached there)
        rh = router.submit(_prompt(6, seed=51), 10)
        ref = list(rh.result(timeout=300.0))
        victim = rh.replica
        at = router.replica(victim).batcher.tokens_emitted + 2
        engine = ChaosEngine(
            FleetFaultPlan().hang(victim, at_token=at, stall_s=30.0))
        engine.arm(router)
        h = router.submit(_prompt(6, seed=51), 10)
        assert h.replica == victim
        _poll_until_dead(mon, victim, timeout=30.0)
        # the hang was detected by heartbeat age, not thread death
        assert list(h.result(timeout=300.0)) == ref
        assert h.failovers == 1
        assert victim in router.lost_replicas()
        # the condemned thread bails out of its stall once aborted —
        # well before the scripted 30 s
        t0 = time.monotonic()
        deadline = t0 + 20.0
        batcher = engine._hooked[victim]
        thread = batcher._thread
        while thread is not None and thread.is_alive() \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert thread is None or not thread.is_alive()
    finally:
        router.shutdown()


@pytest.mark.slow
def test_straggler_goes_suspect_never_dead(lm):
    # THREE replicas: with two, the median of {slow, fast} is their
    # mean, and `slow > 2 * mean` can never hold — the relative score
    # needs a majority of healthy siblings, exactly like production
    router = _mk_fleet(lm, 3)
    try:
        for name in router.replica_names():
            router.replica(name).submit(
                np.zeros(5, np.int32), 2).result(timeout=600.0)
        mon = _monitor(router, slow_factor=2.0, straggle_probes=2)
        engine = ChaosEngine(FleetFaultPlan().straggle(
            "r0", at_token=0, stall_s=0.25, iterations=500))
        engine.arm(router)
        # keep EVERY replica busy so each has step-latency samples (the
        # relative score needs a fleet median of busy siblings)
        handles = [router.replica(n).submit(_prompt(6, seed=60 + i), 24)
                   for i, n in enumerate(router.replica_names())]
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            mon.poll()
            if mon.state("r0") is HealthState.SUSPECT:
                break
            time.sleep(0.1)
        assert mon.state("r0") is HealthState.SUSPECT, mon.states()
        # straggling alone never kills: the replica still finishes
        engine.disarm()
        for h in handles:
            h.result(timeout=600.0)
        assert mon.state("r0") is not HealthState.DEAD
        assert "r0" in router.replica_names()
    finally:
        router.shutdown()


def test_degraded_fleet_tightens_slo_budget(lm):
    """While a replica's capacity is missing, the SLO shed budget is
    multiplied by degraded_slo_factor — the fleet sheds EARLIER."""
    router = Router(slo_ttft_s=10.0, degraded_slo_factor=0.25)
    mon = _monitor(router)
    try:
        for i in range(2):
            router.add_replica(f"r{i}", _mk_replica(lm, f"r{i}"))
        h = router.submit(_prompt(6, seed=71), 8)
        # immediate trigger: fires on r0's next (possibly idle) iteration
        at = router.replica("r0").batcher.tokens_emitted
        engine = ChaosEngine(
            FleetFaultPlan().crash("r0", at_token=at))
        engine.arm(router)
        _poll_until_dead(mon, "r0")
        assert router.lost_replicas()
        # white-box: the effective budget is slo * factor while degraded
        assert router.degraded_slo_factor == 0.25
        assert router.health()["lost_replicas"] == {
            "r0": "scheduler_crashed"}
        router.clear_lost("r0")
        assert router.health()["lost_replicas"] == {}
        h.result(timeout=300.0)
    finally:
        router.shutdown()


# ---------------------------------------------------------------------
# observability (ISSUE 19): a failover CONTINUES the request's trace and
# the death auto-dumps a flight-recorder post-mortem
# ---------------------------------------------------------------------
def test_failover_continues_trace_and_dumps_postmortem(lm, tmp_path):
    import json
    import os

    from flexflow_tpu.elastic.events import EventLog
    from flexflow_tpu.obs.flightrecorder import FlightRecorder
    from flexflow_tpu.obs.tracing import get_tracer

    tracer = get_tracer()
    tracer.clear()
    tracer.enable()
    router = _mk_fleet(lm, 2)
    elog = EventLog()
    router.events = elog
    rec = FlightRecorder(dump_dir=str(tmp_path / "fr"), tracer=tracer,
                         registries={"router": router.registry}
                         ).attach(elog)
    mon = _monitor(router, event_log=elog)
    try:
        prompts = [_prompt(6, seed=s) for s in (1, 2, 3, 4)]
        handles = [router.submit(p, 10) for p in prompts]
        victim = handles[0].replica
        at = router.replica(victim).batcher.tokens_emitted
        engine = ChaosEngine(FleetFaultPlan().crash(victim, at_token=at),
                             event_log=elog)
        engine.arm(router)
        _poll_until_dead(mon, victim)
        for h in handles:
            h.result(timeout=300.0)
        failed = [h for h in handles if h.failovers > 0]
        assert failed, "the crash caught no in-flight work"

        # each failed-over request's spans stitch under its ORIGINAL
        # trace_id: the survivor's scheduler track carries it, and a
        # mid-decode victim leaves its spans on the dead track too
        trace = tracer.to_chrome_trace()
        names = {e["tid"]: e["args"]["name"]
                 for e in trace["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
        tracks = {}
        for e in trace["traceEvents"]:
            a = e.get("args")
            if e.get("ph") == "X" and isinstance(a, dict) \
                    and "trace_id" in a and e["tid"] in names:
                tracks.setdefault(a["trace_id"], set()).add(
                    names[e["tid"]])
        for h in failed:
            assert h.trace_id is not None
            got = tracks.get(h.trace_id, set())
            assert got - {victim}, (h.trace_id, got)
            if h.replayed_tokens:
                assert victim in got, (h.trace_id, got)
        # the replay leg itself is a span of the original trace
        fo = tracer.events("fleet.failover")
        assert fo
        assert all(e["args"]["trace_id"] in tracks for e in fo)

        # the DEAD verdict auto-dumped ONE bundle (the failover burst
        # right behind it is debounced) with the trace alongside
        assert len(rec.dumps) == 1, rec.dumps
        bundle = rec.dumps[0]
        with open(os.path.join(bundle, "recorder.json")) as f:
            dump = json.load(f)
        assert dump["meta"]["trigger"] == "fleet.dead"
        assert os.path.exists(os.path.join(bundle, "trace.json"))
    finally:
        rec.detach()
        tracer.disable()
        router.shutdown()
