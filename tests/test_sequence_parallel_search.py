"""Sequence parallelism as a Unity SEARCH axis (--enable-sequence-parallel,
NEW vs the reference which has no SP at all): the search may shard the
position dim over a 'seq' mesh axis, priced by the ring-attention K/V
rotation cost, and the chosen strategy executes on the mesh."""
import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.search.machine_model import make_machine_model
from flexflow_tpu.search.unity import unity_optimize


def build_transformer(batch=2, seq=32, hidden=32, heads=4, sp_flag=True):
    config = ff.FFConfig()
    config.batch_size = batch
    config.search_budget = 8
    config.enable_sequence_parallel = sp_flag
    config.use_native_search = False
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    tokens = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    t = model.embedding(tokens, 100, hidden, ff.AggrMode.AGGR_MODE_NONE,
                        name="emb")
    attn = model.multihead_attention(t, t, t, hidden, heads, name="attn")
    t = model.layer_norm(model.add(t, attn), [-1], name="ln1")
    h = model.dense(t, hidden * 4, ff.ActiMode.AC_MODE_GELU, name="ff1")
    h = model.dense(h, hidden, name="ff2")
    t = model.layer_norm(model.add(t, h), [-1], name="ln2")
    model.softmax(model.dense(t, 4, name="cls"))
    return model, config


def test_search_considers_sp_factorizations():
    """With batch 2 on 8 devices, dp tops out at 2 — the sp factorizations
    are enumerated and costed alongside dp/tp."""
    model, config = build_transformer()
    machine = make_machine_model(config, 8)
    res = unity_optimize(Graph(model.ops), config, machine, 2, 8)
    assert any("sp=4" in l or "sp=2" in l or "sp=8" in l for l in res.log), \
        res.log


def test_sp_wins_at_long_sequence():
    """At long sequence the attention core dominates and sequence sharding
    divides it across chips: the simulator must prefer dp x sp over the
    dp-only strategy that leaves the seq axis idle."""
    from flexflow_tpu.search.machine_model import TpuPodModel
    from flexflow_tpu.search.simulator import OpStrategy, Simulator

    model, config = build_transformer(batch=2, seq=8192, hidden=64, heads=4)
    graph = Graph(model.ops)
    sim = Simulator(TpuPodModel(8), config)
    dp_only = {op.guid: OpStrategy(dp=2) for op in model.ops}
    dp_sp = {op.guid: OpStrategy(dp=2, sp=4) for op in model.ops}
    assert sim.simulate(graph, dp_sp) < sim.simulate(graph, dp_only)


def test_sp_disabled_without_flag():
    model, config = build_transformer(sp_flag=False)
    machine = make_machine_model(config, 8)
    res = unity_optimize(Graph(model.ops), config, machine, 2, 8)
    assert "seq" not in res.mesh_axes, res.mesh_axes
    assert not any("sp=2" in l or "sp=4" in l for l in res.log
                   if "sp=1" not in l), res.log


def test_searched_sp_strategy_trains():
    """compile() with the SP search enabled executes the chosen strategy
    (seq-sharded activations + ring attention) on the 8-device mesh."""
    model, config = build_transformer()
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    x = np.random.RandomState(0).randint(0, 100, size=(2, 32)).astype(np.int32)
    y = np.zeros((2, 32, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=2, epochs=2)
    assert np.isfinite(hist[-1]["loss"])
    assert hist[-1]["loss"] <= hist[0]["loss"] + 1e-3


def test_sp_memory_shards_activations():
    """The memory model sees sequence sharding: per-chip activation bytes
    fall with sp, steering the lambda memory search toward SP for long
    sequences."""
    from flexflow_tpu.search.machine_model import TpuPodModel
    from flexflow_tpu.search.simulator import OpStrategy, Simulator

    model, config = build_transformer()
    graph = Graph(model.ops)
    sim = Simulator(TpuPodModel(8), config)
    s1 = {op.guid: OpStrategy(dp=2, sp=1) for op in model.ops}
    s4 = {op.guid: OpStrategy(dp=2, sp=4) for op in model.ops}
    assert sim.memory_bytes(graph, s4) < sim.memory_bytes(graph, s1)
