"""Sequence parallelism as a Unity SEARCH axis (--enable-sequence-parallel,
NEW vs the reference which has no SP at all): the search may shard the
position dim over a 'seq' mesh axis, priced by the ring-attention K/V
rotation cost, and the chosen strategy executes on the mesh."""
import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.search.machine_model import make_machine_model
from flexflow_tpu.search.unity import unity_optimize


def build_transformer(batch=2, seq=32, hidden=32, heads=4, sp_flag=True):
    config = ff.FFConfig()
    config.batch_size = batch
    config.search_budget = 8
    config.enable_sequence_parallel = sp_flag
    config.use_native_search = False
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    tokens = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    t = model.embedding(tokens, 100, hidden, ff.AggrMode.AGGR_MODE_NONE,
                        name="emb")
    attn = model.multihead_attention(t, t, t, hidden, heads, name="attn")
    t = model.layer_norm(model.add(t, attn), [-1], name="ln1")
    h = model.dense(t, hidden * 4, ff.ActiMode.AC_MODE_GELU, name="ff1")
    h = model.dense(h, hidden, name="ff2")
    t = model.layer_norm(model.add(t, h), [-1], name="ln2")
    model.softmax(model.dense(t, 4, name="cls"))
    return model, config


def test_sp_mode_cost_crossover():
    """The cost model is SP-MODE-AWARE and prices the real ring/Ulysses
    crossover: per chip the ring moves 4T(sp-1)/sp bytes (T = one q/k/v
    tensor) in 2(sp-1) latency-bearing rotations, Ulysses 8T(sp-1)/sp^2
    bytes in 8 all_to_alls — so Ulysses wins where bytes dominate (traffic
    ratio 2/sp, times another 1/2 because the all_to_all rides both ring
    directions while the neighbor ppermute uses one link: net cost ratio
    1/sp) and the ring wins the latency-dominated regime (tiny blocks,
    small sp, fewer collectives)."""
    from flexflow_tpu.search.machine_model import TpuPodModel
    from flexflow_tpu.search.simulator import CostModel, OpStrategy

    def costs(seq, sp):
        model, config = build_transformer(seq=seq, hidden=256, heads=8)
        attn = next(op for op in model.ops
                    if op.op_type.value == "multihead_attention")
        cost = CostModel(TpuPodModel(8), config)
        s = OpStrategy(dp=1, tp=1, sp=sp)
        ring = cost.sp_collective_time_us(attn, s)
        attn.params["sequence_parallel_mode"] = "ulysses"
        uly = cost.sp_collective_time_us(attn, s)
        return ring, uly

    # bytes-dominated: the 1/sp cost ratio shows through
    for sp in (4, 8):
        ring, uly = costs(seq=8192, sp=sp)
        assert 0.0 < uly < ring, (sp, uly, ring)
        assert uly / ring == pytest.approx(1.0 / sp, rel=0.25), (sp, uly,
                                                                 ring)
    # latency-dominated: 8 all_to_alls cost more than 2 tiny rotations
    ring, uly = costs(seq=32, sp=2)
    assert ring < uly, (ring, uly)

    # cross-attention: the q/out blocks carry L_q, not L_kv — a long-query
    # short-memory op must cost more than its short-query twin (regression:
    # all four blocks were priced at K/V size)
    def cross_uly(lq, lkv):
        config = ff.FFConfig()
        config.batch_size = 2
        m = ff.FFModel(config)
        q = m.create_tensor([2, lq, 256])
        kv = m.create_tensor([2, lkv, 256])
        m.multihead_attention(q, kv, kv, 256, 8,
                              sequence_parallel=True,
                              sequence_parallel_mode="ulysses", name="x")
        attn = next(op for op in m.ops
                    if op.op_type.value == "multihead_attention")
        cost = CostModel(TpuPodModel(8), config)
        return cost.sp_collective_time_us(attn, OpStrategy(dp=1, sp=8))

    # 64x the q length must show up as a multiple of the cost (the +1us
    # per-collective latency floor dilutes the exact ratio)
    assert cross_uly(4096, 64) > 3 * cross_uly(64, 64)


def test_native_ulysses_cost_parity():
    """The native core prices the Ulysses mode identically (the sp_ulysses
    node flag flows over the protocol)."""
    from flexflow_tpu import native
    from flexflow_tpu.search.machine_model import TpuPodModel
    from flexflow_tpu.search.unity import GraphSearchHelper

    if not native.available():
        pytest.skip("native core unavailable")
    model, config = build_transformer()
    for op in model.ops:
        if op.op_type.value == "multihead_attention":
            op.params["sequence_parallel_mode"] = "ulysses"
    g = Graph(model.ops)
    machine = TpuPodModel(8)
    native_res = native.optimize_strategy(g, config, machine, 2, 8)
    helper = GraphSearchHelper(g, config, machine)
    py_res = helper.graph_optimize(2, 8)
    assert native_res.cost_us == pytest.approx(py_res.cost_us, rel=1e-6)
    assert native_res.mesh_axes == py_res.mesh_axes


def test_search_considers_sp_factorizations():
    """With batch 2 on 8 devices, dp tops out at 2 — the sp factorizations
    are enumerated and costed alongside dp/tp."""
    model, config = build_transformer()
    machine = make_machine_model(config, 8)
    res = unity_optimize(Graph(model.ops), config, machine, 2, 8)
    assert any("sp=4" in l or "sp=2" in l or "sp=8" in l for l in res.log), \
        res.log


def test_sp_wins_at_long_sequence():
    """At long sequence the attention core dominates and sequence sharding
    divides it across chips: the simulator must prefer dp x sp over the
    dp-only strategy that leaves the seq axis idle."""
    from flexflow_tpu.search.machine_model import TpuPodModel
    from flexflow_tpu.search.simulator import OpStrategy, Simulator

    model, config = build_transformer(batch=2, seq=8192, hidden=64, heads=4)
    graph = Graph(model.ops)
    sim = Simulator(TpuPodModel(8), config)
    dp_only = {op.guid: OpStrategy(dp=2) for op in model.ops}
    dp_sp = {op.guid: OpStrategy(dp=2, sp=4) for op in model.ops}
    assert sim.simulate(graph, dp_sp) < sim.simulate(graph, dp_only)


def test_sp_disabled_without_flag():
    model, config = build_transformer(sp_flag=False)
    machine = make_machine_model(config, 8)
    res = unity_optimize(Graph(model.ops), config, machine, 2, 8)
    assert "seq" not in res.mesh_axes, res.mesh_axes
    assert not any("sp=2" in l or "sp=4" in l for l in res.log
                   if "sp=1" not in l), res.log


def test_searched_sp_strategy_trains():
    """compile() with the SP search enabled executes the chosen strategy
    (seq-sharded activations + ring attention) on the 8-device mesh."""
    model, config = build_transformer()
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    x = np.random.RandomState(0).randint(0, 100, size=(2, 32)).astype(np.int32)
    y = np.zeros((2, 32, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=2, epochs=2)
    assert np.isfinite(hist[-1]["loss"])
    assert hist[-1]["loss"] <= hist[0]["loss"] + 1e-3


def test_sp_memory_shards_activations():
    """The memory model sees sequence sharding: per-chip activation bytes
    fall with sp, steering the lambda memory search toward SP for long
    sequences."""
    from flexflow_tpu.search.machine_model import TpuPodModel
    from flexflow_tpu.search.simulator import OpStrategy, Simulator

    model, config = build_transformer()
    graph = Graph(model.ops)
    sim = Simulator(TpuPodModel(8), config)
    s1 = {op.guid: OpStrategy(dp=2, sp=1) for op in model.ops}
    s4 = {op.guid: OpStrategy(dp=2, sp=4) for op in model.ops}
    assert sim.memory_bytes(graph, s4) < sim.memory_bytes(graph, s1)
