"""Calibration-driven refit loop (obs/refit.py): fitted-profile
round-trip + typed mismatch errors, the robust coefficient fit, drift
detection, the hardened calibration ratios, and the coordinator's
drift-triggered budgeted re-plan."""
import dataclasses
import json
import math
import os
import tempfile

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu import obs
from flexflow_tpu.obs.calibration import CalibrationReport, OpCalibration
from flexflow_tpu.obs.refit import (DriftDetector, FittedCoefficients,
                                    FittedProfile, FittedProfileError,
                                    FittedProfileMismatch,
                                    fit_compute_coefficients, refit,
                                    usable_rows)
from flexflow_tpu.search.machine_model import (CHIP_SPECS,
                                               SimpleMachineModel,
                                               make_machine_model)

COEFFS = FittedCoefficients(
    compute_scale={"bf16": 0.125, "f32": 0.5}, hbm_scale=0.75,
    link_bw_scale=2.0, dispatch_latency_us=42.5,
    collective_latency_us=3.25, step_scale=11.0)


def _profile(tmp_path, chip="tpu-v5e", backend="cpu", name="p.json",
             coeffs=COEFFS, **kw):
    prof = FittedProfile(chip=chip, backend=backend, coefficients=coeffs,
                         **kw)
    return prof, prof.save(os.path.join(str(tmp_path), name))


# -- fitted-profile persistence -------------------------------------------

def test_profile_round_trip_is_exact(tmp_path):
    prof, path = _profile(tmp_path, fitted_steps=7, fitted_ops=4, rounds=2,
                          step_ratio=1.01, num_chips=8)
    loaded = FittedProfile.load(path, expect_chip="tpu-v5e",
                                expect_backend="cpu")
    assert loaded.coefficients == prof.coefficients  # exact, no rounding
    assert loaded.spec_hash == prof.spec_hash
    assert (loaded.fitted_ops, loaded.rounds, loaded.num_chips) == (4, 2, 8)


def test_profile_overlay_reproduces_coefficients_exactly(tmp_path):
    _, path = _profile(tmp_path)
    m = SimpleMachineModel(8, CHIP_SPECS["tpu-v5e"])
    base = m.chip
    FittedProfile.load(path, expect_chip="tpu-v5e",
                       expect_backend="cpu").apply_to(m)
    assert m.chip.peak_bf16_tflops == base.peak_bf16_tflops * 0.125
    assert m.chip.peak_f32_tflops == base.peak_f32_tflops * 0.5
    assert m.chip.hbm_bw_gbps == base.hbm_bw_gbps * 0.75
    assert m.chip.ici_link_gbps == base.ici_link_gbps * 2.0
    assert m.dispatch_overhead_us == 42.5
    assert m.collective_latency_us == 3.25
    assert m.step_time_scale == 11.0
    assert CHIP_SPECS["tpu-v5e"].peak_bf16_tflops == base.peak_bf16_tflops


def test_make_machine_model_applies_profile(tmp_path):
    _, path = _profile(tmp_path)
    cfg = ff.FFConfig()
    cfg.fitted_profile_file = path
    m = make_machine_model(cfg, 8)
    assert m.chip.peak_bf16_tflops == pytest.approx(
        CHIP_SPECS["tpu-v5e"].peak_bf16_tflops * 0.125)
    assert m.step_time_scale == 11.0


def test_profile_chip_mismatch_is_typed(tmp_path):
    _, path = _profile(tmp_path, chip="tpu-v4")
    with pytest.raises(FittedProfileMismatch, match="tpu-v4"):
        FittedProfile.load(path, expect_chip="tpu-v5e")


def test_profile_backend_mismatch_is_typed(tmp_path):
    _, path = _profile(tmp_path, backend="tpu")
    with pytest.raises(FittedProfileMismatch, match="backend"):
        FittedProfile.load(path, expect_chip="tpu-v5e",
                           expect_backend="cpu")


def test_profile_stale_hash_refuses_to_load(tmp_path):
    _, path = _profile(tmp_path)
    with open(path) as f:
        d = json.load(f)
    d["chip"] = "tpu-v4"  # spec edited without re-fitting: hash now stale
    with open(path, "w") as f:
        json.dump(d, f)
    with pytest.raises(FittedProfileMismatch, match="stale or tampered"):
        FittedProfile.load(path)


def test_profile_future_format_version_refused(tmp_path):
    _, path = _profile(tmp_path)
    with open(path) as f:
        d = json.load(f)
    d["version"] = 99
    with open(path, "w") as f:
        json.dump(d, f)
    with pytest.raises(FittedProfileError, match="format v99"):
        FittedProfile.load(path)


def test_profile_unreadable_and_malformed_are_typed(tmp_path):
    with pytest.raises(FittedProfileError, match="unreadable"):
        FittedProfile.load(os.path.join(str(tmp_path), "missing.json"))
    bad = os.path.join(str(tmp_path), "bad.json")
    with open(bad, "w") as f:
        f.write('{"version": 1}')
    with pytest.raises(FittedProfileError, match="malformed"):
        FittedProfile.load(bad)


# -- the coefficient fit ---------------------------------------------------

def _rows(pred_meas, dtype="f32"):
    return [OpCalibration(f"op{i}", "linear", "dp=1", p, m, dtype=dtype)
            for i, (p, m) in enumerate(pred_meas)]


def test_fit_recovers_known_slope_and_latency():
    # measured = 3 * roofline + 5: the fit must divide the effective flop
    # rate by ~3 and land the dispatch latency near 5us
    machine = SimpleMachineModel(1, CHIP_SPECS["tpu-v5e"])
    rows = _rows([(p + 1.0, 3.0 * p + 5.0)
                  for p in (10.0, 40.0, 160.0, 640.0, 2560.0)])
    out = fit_compute_coefficients(rows, FittedCoefficients(), machine)
    assert out.compute_scale["f32"] == pytest.approx(1 / 3.0, rel=0.05)
    assert out.dispatch_latency_us == pytest.approx(5.0, rel=0.2)
    assert out.compute_scale["bf16"] == 1.0  # no bf16 rows: untouched


def test_fit_is_robust_to_one_outlier():
    machine = SimpleMachineModel(1, CHIP_SPECS["tpu-v5e"])
    pts = [(p + 1.0, 2.0 * p) for p in (10.0, 20.0, 40.0, 80.0, 160.0,
                                        320.0, 640.0, 1280.0, 2560.0)]
    pts.append((5121.0, 2.0 * 5120.0 * 50))  # one 50x-poisoned point
    out = fit_compute_coefficients(_rows(pts), FittedCoefficients(),
                                   machine)
    assert out.compute_scale["f32"] == pytest.approx(0.5, rel=0.1)


def test_usable_rows_drops_degenerate_measurements():
    rows = _rows([(10.0, 20.0), (10.0, 0.0), (10.0, -5.0),
                  (10.0, float("nan")), (0.0, 20.0),
                  (10.0, float("inf"))])
    assert [r.op for r in usable_rows(rows)] == ["op0"]


# -- hardened calibration ratios ------------------------------------------

@pytest.mark.parametrize("pred,meas", [
    (10.0, 0.0), (10.0, -3.0), (0.0, 10.0), (-1.0, 10.0),
    (10.0, float("nan")), (float("inf"), 10.0)])
def test_op_ratio_degenerate_inputs_are_nan(pred, meas):
    r = OpCalibration("o", "linear", "dp=1", pred, meas)
    assert math.isnan(r.ratio)


@pytest.mark.parametrize("pred,meas", [
    (None, 100.0), (100.0, None), (0.0, 100.0), (100.0, 0.0),
    (-5.0, 100.0), (100.0, -5.0), (float("nan"), 100.0)])
def test_step_ratio_degenerate_inputs_are_uncalibrated(pred, meas):
    rep = CalibrationReport(backend="cpu", predicted_step_us=pred,
                            measured_step_us=meas, measured_steps=3,
                            ops=[])
    assert math.isnan(rep.step_ratio)
    assert "n/a" in rep.format()  # renders cleanly, no div-by-zero
    json.loads(rep.to_json())  # and serializes


def test_refit_refuses_unmeasured_step():
    with pytest.raises(FittedProfileError, match="measured_step_us"):
        refit(object.__new__(type("M", (), {"graph": object()})),
              0.0, [])


# -- drift detection -------------------------------------------------------

def test_drift_detector_warmup_budget_and_rearm():
    det = DriftDetector(predicted_step_us=100.0, threshold=0.5,
                        warmup_steps=2, patience=2, max_replans=1)
    assert det.observe(1e6) is False  # warmup 1 (jit step)
    assert det.observe(1e6) is False  # warmup 2
    assert det.observe(1e6) is False  # breach 1 of patience 2
    assert det.observe(1e6) is True   # sustained: fire (budget available)
    # observing never consumes the budget — a caller that cannot re-plan
    # (plain fit) leaves it intact, so the verdict re-fires every
    # patience window
    assert det.replans == 0
    assert det.observe(1e6) is False  # fresh patience window
    assert det.observe(1e6) is True
    det.note_replan()                 # the re-planner consumed the budget
    assert det.replans == 1
    assert det.observe(1e6) is False  # budget spent: never fires again
    assert det.observe(1e6) is False
    assert det.drift > 0.5
    det.rearm(1e6)  # re-anchored to the re-planned prediction
    assert det.measured_step_us is None and det.drift == 0.0
    for _ in range(10):
        assert det.observe(1.05e6) is False  # 5% off: calibrated now


def test_drift_detector_ignores_degenerate_and_calibrated_steps():
    det = DriftDetector(predicted_step_us=100.0, threshold=0.5,
                        warmup_steps=0, patience=1, max_replans=5)
    assert det.observe(0.0) is False          # clock-resolution zero
    assert det.observe(float("nan")) is False
    for _ in range(5):
        assert det.observe(110.0) is False    # within threshold
    assert det.drift == pytest.approx(0.1, abs=0.01)
    assert obs.REGISTRY.gauge(
        "ff_calibration_drift", "").value() == pytest.approx(det.drift)


def test_drift_detector_requires_positive_prediction():
    with pytest.raises(ValueError):
        DriftDetector(predicted_step_us=0.0)


# -- end-to-end: refit converges, drift fires one budgeted re-plan ---------

def _tiny_builder(cfg):
    m = ff.FFModel(cfg)
    t = m.create_tensor([cfg.batch_size, 32])
    t = m.dense(t, 64, ff.ActiMode.AC_MODE_RELU)
    t = m.dense(t, 10)
    m.softmax(t)
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[ff.MetricsType.METRICS_ACCURACY])
    return m


def _tiny_data(bs, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(bs * 4, 32).astype(np.float32)
    y = rng.randint(0, 10, size=(bs * 4, 1)).astype(np.int32)
    return x, y


def test_refit_converges_from_miscalibrated_spec(tmp_path):
    """The acceptance drill's core, in-process: 2x overstated flop rate +
    0.5x understated ICI bandwidth must converge predicted-vs-measured to
    within +-15% in <= 3 rounds."""
    prior = FittedCoefficients(compute_scale={"bf16": 2.0, "f32": 2.0},
                               link_bw_scale=0.5)
    cfg = ff.FFConfig()
    cfg.batch_size = 16
    cfg.fitted_profile_file = FittedProfile(
        chip="tpu-v5e", backend="cpu", coefficients=prior,
    ).save(os.path.join(str(tmp_path), "miscal.json"))
    model = _tiny_builder(cfg)
    x, y = _tiny_data(cfg.batch_size)
    model.fit(x, y, epochs=2)
    rep = obs.calibrate(model, max_ops=2)
    assert rep.measured_step_us and rep.measured_step_us > 0
    profile, history = refit(model, rep.measured_step_us, rep.ops,
                             prior=prior, rounds=3, tol=0.15)
    assert len(history) <= 4  # <= 3 fitting rounds + the final verdict
    assert abs(history[-1].ratio - 1.0) <= 0.15
    # the persisted profile reproduces the converged prediction when
    # loaded as a make_machine_model overlay
    path = profile.save(os.path.join(str(tmp_path), "fitted.json"))
    cfg2 = dataclasses.replace(cfg, fitted_profile_file=path)
    from flexflow_tpu.search.simulator import Simulator

    sim = Simulator(make_machine_model(cfg2, cfg2.total_devices), cfg2)
    repriced = sim.simulate(model.graph, model._op_strategies or {})
    assert repriced == pytest.approx(history[-1].predicted_step_us,
                                     rel=1e-6)


def test_coordinator_drift_fires_exactly_one_budgeted_replan(tmp_path):
    from flexflow_tpu.elastic.coordinator import ElasticCoordinator

    obs.enable_tracing().clear()
    try:
        cfg = ff.FFConfig()
        cfg.batch_size = 16
        cfg.device_ids = list(range(4))
        x, y = _tiny_data(cfg.batch_size)
        refits = []

        def refit_hook(model, measured_us):
            rep = obs.calibrate(model, max_ops=1)
            prof, hist = refit(model, measured_us, rep.ops, rounds=3,
                               tol=0.15)
            refits.append(hist)
            return prof.save(os.path.join(str(tmp_path), "fitted.json"))

        coord = ElasticCoordinator(
            _tiny_builder, cfg,
            checkpoint_dir=tempfile.mkdtemp(prefix="ff_refit_t_"),
            checkpoint_every=2)
        # armed against an absurdly fast prediction: drift is immediate
        det = DriftDetector(predicted_step_us=1.0, threshold=0.5,
                            warmup_steps=1, patience=1, max_replans=1)
        coord.drift_detector = det
        coord.drift_refit = refit_hook
        history = coord.fit(x, y, steps=8)
        assert len(history) == 8  # training completed through the re-plan
        assert det.replans == 1
        assert len(refits) == 1
        assert abs(refits[0][-1].ratio - 1.0) <= 0.15
        assert obs.REGISTRY.counter("ff_replan_total", "").value() == 1
        counts = coord.events.counts()
        assert counts.get("drift.replan") == 1
        assert counts.get("drift.refit") == 1
        spans = obs.get_tracer().span_names()
        assert "refit.replan" in spans and "refit.fit" in spans
        # the re-built model priced with the fitted profile
        assert coord.model.config.fitted_profile_file == os.path.join(
            str(tmp_path), "fitted.json")
        # budget spent: the detector never fires again even if drift stays
        assert det.observe(1e9) is False
    finally:
        obs.disable_tracing()


def test_chip_loss_recovery_rearms_drift_detector(tmp_path):
    """A chip-loss recovery re-prices the plan for the shrunken mesh; the
    drift detector must be re-anchored to the NEW prediction (with fresh
    warmup), or the replayed steps would read as calibration drift and
    burn the re-plan budget on a healthy plan."""
    from flexflow_tpu.elastic.coordinator import ElasticCoordinator
    from flexflow_tpu.elastic.faults import FaultPlan

    cfg = ff.FFConfig()
    cfg.batch_size = 12  # divisible by 4 pre-loss and 3 post-loss
    cfg.device_ids = list(range(4))
    x, y = _tiny_data(cfg.batch_size)
    coord = ElasticCoordinator(
        _tiny_builder, cfg,
        fault_plan=FaultPlan().add_chip_loss(at_step=4, chips=[3]),
        checkpoint_dir=tempfile.mkdtemp(prefix="ff_refit_rc_"),
        checkpoint_every=2)
    from flexflow_tpu.obs.calibration import predicted_step_us

    # a sentinel prediction no re-price would reproduce, so the rearm is
    # unambiguous; warmup never ends, isolating the rearm path from
    # actual drift detection
    sentinel = 123456.0
    det = DriftDetector(predicted_step_us=sentinel, threshold=10.0,
                        warmup_steps=10 ** 6, max_replans=1)
    coord.drift_detector = det
    history = coord.fit(x, y, steps=8)
    assert len(history) == 8
    assert len(coord.device_ids) == 3  # the recovery actually happened
    # rearmed: anchored to the survivors' re-planned prediction, budget
    # untouched
    assert det.predicted_step_us != sentinel
    assert det.predicted_step_us == pytest.approx(
        predicted_step_us(coord.model))
    assert det.replans == 0 and det.measured_step_us is None
