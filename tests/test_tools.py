"""Substitution tools (reference: tools/protobuf_to_json,
tools/substitutions_to_dot).

The vendored `substitutions/graph_subst_3_v2.json` (the converter's own
output over the reference's public OSDI rule data) makes these tests — and
the graph-xfer/joint-search suites — self-contained; the tests against the
reference's original .pb/.json files remain as skippable cross-checks.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VENDORED = os.path.join(REPO, "substitutions", "graph_subst_3_v2.json")
PB = "/root/reference/substitutions/graph_subst_3_v2.pb"
JSON_REF = "/root/reference/substitutions/graph_subst_3_v2.json"


def test_vendored_rules_load():
    """The committed rule file parses, has all 640 rules, and loads in the
    search's rule loader (no reference checkout needed)."""
    conv = json.load(open(VENDORED))
    assert len(conv["rule"]) == 640
    from flexflow_tpu.search.substitution_loader import (
        rules_from_spec,
        summarize,
    )

    assert summarize(rules_from_spec(conv))["supported"] == 640


@pytest.mark.skipif(not os.path.exists(PB), reason="reference pb not present")
def test_protobuf_to_json_roundtrips_reference_file(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "protobuf_to_json.py"), PB],
        capture_output=True, text=True, check=True,
    ).stdout
    conv = json.loads(out)
    # the vendored file IS this conversion, bit-for-bit
    assert conv == json.load(open(VENDORED))
    ref = json.load(open(JSON_REF))
    assert len(conv["rule"]) == len(ref["rule"]) == 640

    def strip(r):
        return {k: r[k] for k in ("srcOp", "dstOp", "mappedOutput")}

    assert all(strip(a) == strip(b)
               for a, b in zip(conv["rule"], ref["rule"]))


def test_substitutions_to_dot_renders_rule():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "substitutions_to_dot.py"),
         VENDORED, "taso_rule_448"],
        capture_output=True, text=True, check=True,
    ).stdout
    assert out.startswith("digraph substitution")
    assert "cluster_src" in out and "cluster_dst" in out
    assert "OP_LINEAR" in out


# -- tools/lint_invariants.py ----------------------------------------------

def test_lint_invariants_repo_is_clean():
    """The invariant lint (host-sync, metric-help, span-discipline) runs
    clean over the tree — the same invocation lint CI makes."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_invariants.py"),
         "flexflow_tpu"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_lint_invariants_rules_fire(tmp_path):
    """Each of the three rules flags its seeded violation; the scoped
    host-sync rule stays silent outside kernels/runtime."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_invariants", os.path.join(REPO, "tools", "lint_invariants.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    bad = tmp_path / "probe.py"
    bad.write_text(
        "import numpy as np\n"
        "def f(x, REGISTRY, tracer):\n"
        "    v = x.item()\n"
        "    a = np.asarray(x)\n"
        "    REGISTRY.counter('ff_x_total').inc()\n"
        "    s = tracer.span('oops')\n"
        "    with tracer.span('fine'):\n"
        "        pass\n"
        "    return v, a, s\n")
    in_scope = {r for r, *_ in lint.lint_file(
        bad, "flexflow_tpu/runtime/probe.py")}
    assert in_scope == {"host-sync", "metric-help", "span-discipline"}
    out_of_scope = {r for r, *_ in lint.lint_file(
        bad, "flexflow_tpu/search/probe.py")}
    assert out_of_scope == {"metric-help", "span-discipline"}
