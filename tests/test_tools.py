"""Substitution tools (reference: tools/protobuf_to_json,
tools/substitutions_to_dot)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PB = "/root/reference/substitutions/graph_subst_3_v2.pb"
JSON_REF = "/root/reference/substitutions/graph_subst_3_v2.json"


@pytest.mark.skipif(not os.path.exists(PB), reason="reference pb not present")
def test_protobuf_to_json_roundtrips_reference_file(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "protobuf_to_json.py"), PB],
        capture_output=True, text=True, check=True,
    ).stdout
    conv = json.loads(out)
    ref = json.load(open(JSON_REF))
    assert len(conv["rule"]) == len(ref["rule"]) == 640

    def strip(r):
        return {k: r[k] for k in ("srcOp", "dstOp", "mappedOutput")}

    assert all(strip(a) == strip(b)
               for a, b in zip(conv["rule"], ref["rule"]))
    # and the converted file loads in the search's rule loader
    from flexflow_tpu.search.substitution_loader import (
        rules_from_spec,
        summarize,
    )

    assert summarize(rules_from_spec(conv))["supported"] == 640


@pytest.mark.skipif(not os.path.exists(JSON_REF),
                    reason="reference json not present")
def test_substitutions_to_dot_renders_rule():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "substitutions_to_dot.py"),
         JSON_REF, "taso_rule_448"],
        capture_output=True, text=True, check=True,
    ).stdout
    assert out.startswith("digraph substitution")
    assert "cluster_src" in out and "cluster_dst" in out
    assert "OP_LINEAR" in out
