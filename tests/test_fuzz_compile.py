"""Randomized end-to-end soak: seeded random layer graphs through
compile (with search) + one training epoch on the 8-device CPU mesh.
Catches integration crashes no targeted test covers (shape plumbing,
search edge cases, mixed-precision boundaries, sharding constraints)."""
import numpy as np
import pytest

import flexflow_tpu as ff


def random_model(rng, n_devices=8):
    config = ff.FFConfig()
    batch = int(rng.choice([4, 8]))
    config.batch_size = batch
    config.search_budget = int(rng.choice([0, 4]))
    config.use_native_search = bool(rng.randint(2))
    config.allow_mixed_precision = bool(rng.randint(2))
    # v1 engages the torus-aware machine model + per-axis comm channels in
    # whichever search (Python or native) prices the strategies
    config.machine_model_version = int(rng.randint(2))
    model = ff.FFModel(config)

    kind = rng.choice(["mlp", "conv", "attn"])
    if kind == "mlp":
        width = int(rng.choice([8, 16, 32]))
        x = model.create_tensor([batch, width])
        t = x
        for _ in range(rng.randint(1, 4)):
            t = model.dense(t, int(rng.choice([8, 16, 32])),
                            rng.choice([ff.ActiMode.AC_MODE_RELU,
                                        ff.ActiMode.AC_MODE_GELU,
                                        ff.ActiMode.AC_MODE_NONE]))
            if rng.randint(2):
                t = model.dropout(t, float(rng.choice([0.0, 0.1])))
        feat_x = np.random.RandomState(0).randn(
            4 * batch, width).astype(np.float32)
    elif kind == "conv":
        c = int(rng.choice([1, 3]))
        hw = int(rng.choice([8, 12]))
        x = model.create_tensor([batch, c, hw, hw])
        t = model.conv2d(x, int(rng.choice([4, 8])), 3, 3, 1, 1, 1, 1,
                         ff.ActiMode.AC_MODE_RELU)
        if rng.randint(2):
            t = model.batch_norm(t, relu=bool(rng.randint(2)))
        if rng.randint(2):
            t = model.pool2d(t, 2, 2, 2, 2, 0, 0)
        t = model.flat(t)
        t = model.dense(t, 16, ff.ActiMode.AC_MODE_RELU)
        feat_x = np.random.RandomState(0).randn(
            4 * batch, c, hw, hw).astype(np.float32)
    else:
        seq = int(rng.choice([8, 16]))
        hidden = int(rng.choice([16, 32]))
        heads = int(rng.choice([2, 4]))
        x = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
        t = model.embedding(x, 50, hidden, ff.AggrMode.AGGR_MODE_NONE)
        attn = model.multihead_attention(
            t, t, t, hidden, heads, causal=bool(rng.randint(2)))
        t = model.layer_norm(model.add(t, attn), [-1])
        t = model.dense(t, hidden, ff.ActiMode.AC_MODE_GELU)
        feat_x = np.random.RandomState(0).randint(
            0, 50, size=(4 * batch, seq)).astype(np.int32)

    classes = 3
    model.softmax(model.dense(t, classes))
    out_positions = () if kind != "attn" else (feat_x.shape[1],)
    y = np.random.RandomState(1).randint(
        0, classes, size=(4 * batch,) + out_positions + (1,)).astype(np.int32)
    return model, feat_x, y


@pytest.mark.parametrize("seed", range(12))
def test_random_graph_compiles_and_trains(seed):
    rng = np.random.RandomState(1000 + seed)
    model, X, Y = random_model(rng)
    model.compile(
        optimizer=(ff.AdamOptimizer(model, alpha=1e-3)
                   if rng.randint(2) else ff.SGDOptimizer(model, lr=0.01)),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    # sometimes chunk K optimizer steps per dispatch (the dataset is
    # 4*batch samples, so K=4 is exactly one full-chunk epoch and K=2/3
    # exercise the trailing single-step path)
    k = int(rng.choice([1, 1, 2, 3, 4]))
    hist = model.fit(x=X, y=Y, epochs=1, verbose=False,
                     steps_per_execution=k)
    assert np.isfinite(hist[-1]["loss"]), hist
    pred = model.predict(X[: model.config.batch_size])
    assert np.all(np.isfinite(np.asarray(pred, np.float32)))


@pytest.mark.parametrize("axes,kind", [
    ({"data": 8}, "mlp"),
    ({"data": 2, "model": 4}, "mlp"),
    ({"model": 8}, "mlp"),
    ({"data": 2, "seq": 4}, "attn_ring"),
    ({"data": 2, "seq": 4}, "attn_ulysses"),
    ({"data": 4, "attr": 2}, "conv"),
    ({"data": 2, "model": 2, "seq": 2}, "attn_ring"),
    ({"stage": 4}, "stack"),
    ({"data": 2, "stage": 4}, "stack"),
])
def test_explicit_axes_compile_and_train(axes, kind):
    """Every advertised mesh-axis combination compiles and trains with
    compatible shapes (dp x tp, dp x sp, dp x attr, and dp x tp x sp)."""
    config = ff.FFConfig()
    batch = 8
    config.batch_size = batch
    config.allow_mixed_precision = False
    if "attr" in axes:
        config.enable_attribute_parallel = True
    model = ff.FFModel(config)

    if kind == "mlp":
        x = model.create_tensor([batch, 32])
        t = model.dense(x, 64, ff.ActiMode.AC_MODE_RELU)
        t = model.dense(t, 32)
        X = np.random.RandomState(0).randn(2 * batch, 32).astype(np.float32)
        Y = np.random.RandomState(1).randint(
            0, 4, size=(2 * batch, 1)).astype(np.int32)
    elif kind.startswith("attn"):
        seq, hidden, heads = 16, 32, 4
        x = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
        t = model.embedding(x, 50, hidden, ff.AggrMode.AGGR_MODE_NONE)
        attn = model.multihead_attention(
            t, t, t, hidden, heads, sequence_parallel=True,
            sequence_parallel_mode=("ulysses" if kind.endswith("ulysses")
                                    else "ring"))
        t = model.layer_norm(model.add(t, attn), [-1])
        t = model.dense(t, hidden, ff.ActiMode.AC_MODE_GELU)
        X = np.random.RandomState(0).randint(
            0, 50, size=(2 * batch, seq)).astype(np.int32)
        Y = np.random.RandomState(1).randint(
            0, 4, size=(2 * batch, seq, 1)).astype(np.int32)
    elif kind == "stack":  # isomorphic blocks -> pipeline stages
        config.pipeline_microbatches = 4
        x = model.create_tensor([batch, 32])
        t = model.dense(x, 32, ff.ActiMode.AC_MODE_RELU, name="stem")
        for i in range(4):
            t = model.dense(t, 32, ff.ActiMode.AC_MODE_RELU,
                            name=f"block{i}")
        X = np.random.RandomState(0).randn(2 * batch, 32).astype(np.float32)
        Y = np.random.RandomState(1).randint(
            0, 4, size=(2 * batch, 1)).astype(np.int32)
    else:  # conv
        x = model.create_tensor([batch, 3, 8, 8])
        t = model.conv2d(x, 8, 3, 3, 1, 1, 1, 1, ff.ActiMode.AC_MODE_RELU)
        t = model.flat(t)
        t = model.dense(t, 16, ff.ActiMode.AC_MODE_RELU)
        X = np.random.RandomState(0).randn(
            2 * batch, 3, 8, 8).astype(np.float32)
        Y = np.random.RandomState(1).randint(
            0, 4, size=(2 * batch, 1)).astype(np.int32)

    model.softmax(model.dense(t, 4))
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
        parallel_axes=axes,
    )
    hist = model.fit(x=X, y=Y, epochs=1, verbose=False)
    assert np.isfinite(hist[-1]["loss"]), (axes, kind, hist)
