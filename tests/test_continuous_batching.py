"""Continuous-batching serving (ISSUE 5): the paged KV pool, the
iteration-level scheduler, admission control, streaming, and the three
batcher/generate satellite fixes.

The decisive property throughout: continuous decode is TOKEN-IDENTICAL to
the lockstep GenerativeSession path for the same prompt — per-row
attention over the slot-dense cache is independent of what else shares
the iteration."""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from flexflow_tpu.serving import (BatcherStopped, DynamicBatcher,
                                  InferenceServer)
from flexflow_tpu.serving.generate import GenerativeSession
from flexflow_tpu.serving.sched import (AdmissionController,
                                        ContinuousBatcher, PagedKVPool,
                                        PoolExhausted, PoolSaturated,
                                        QueueFull, RequestState,
                                        RequestTooLarge, derive_num_slots,
                                        kv_bytes_per_token)
from tests.conftest import module_xla_cache
from tests.test_generate import _build_lm

# module-scoped XLA compilation cache — see conftest.module_xla_cache
_xla_cache = pytest.fixture(scope="module", autouse=True)(module_xla_cache)


@pytest.fixture(scope="module")
def lm():
    """One compiled LM shared by the module (b=2, window=12)."""
    return _build_lm(2, 12)


def _prompts(lens, seed=0, vocab=50):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=(n,)).astype(np.int32) for n in lens]


# ---------------------------------------------------------------------
# PagedKVPool
# ---------------------------------------------------------------------
def test_pool_alloc_extend_free_accounting():
    pool = PagedKVPool(num_slots=2, max_len=16, page_size=4)
    assert pool.pages_per_slot == 4 and pool.total_pages == 8
    s0 = pool.alloc("a", 5)  # 5 tokens -> 2 pages
    assert s0 in (0, 1)
    assert pool.pages_used() == 2 and pool.live_sequences() == 1
    # growing within the page: no new page; crossing: one more
    pool.extend("a", 3)  # 8 tokens -> still 2 pages
    assert pool.pages_used() == 2
    pool.extend("a", 1)  # 9 tokens -> 3 pages
    assert pool.pages_used() == 3
    assert pool.pages_of("a") == [s0 * 4, s0 * 4 + 1, s0 * 4 + 2]
    s1 = pool.alloc("b", 1)
    assert s1 != s0
    assert pool.free_slot_count() == 0
    pool.free("a")
    assert pool.pages_used() == 1 and pool.free_slot_count() == 1
    pool.free("a")  # idempotent
    pool.free("b")
    assert pool.pages_used() == 0 and pool.utilization() == 0.0


def test_pool_exhaustion_and_limits():
    pool = PagedKVPool(num_slots=1, max_len=8, page_size=4)
    pool.alloc("a", 4)
    with pytest.raises(PoolExhausted, match="slots in use"):
        pool.alloc("b", 1)
    with pytest.raises(ValueError, match="already allocated"):
        pool.alloc("a", 1)
    with pytest.raises(PoolExhausted, match="per-slot capacity"):
        pool.extend("a", 5)  # 4 + 5 > max_len=8
    with pytest.raises(KeyError):
        pool.extend("zzz", 1)
    pool.free("a")
    with pytest.raises(PoolExhausted, match="per-slot capacity"):
        pool.alloc("c", 9)


def test_pool_gauges_track_usage_per_pool():
    """Gauge series are labeled per pool, so two pools in one process (a
    multi-model server) never clobber each other's values."""
    from flexflow_tpu.obs import REGISTRY

    pool = PagedKVPool(num_slots=2, max_len=8, page_size=4)
    other = PagedKVPool(num_slots=1, max_len=8, page_size=4)
    used = REGISTRY.gauge("ff_kvpool_pages_used", labels=("pool",))
    total = REGISTRY.gauge("ff_kvpool_pages_total", labels=("pool",))
    assert total.value(pool=pool.label) == 4
    assert total.value(pool=other.label) == 2
    pool.alloc("a", 8)
    other.alloc("x", 1)
    assert used.value(pool=pool.label) == 2
    assert used.value(pool=other.label) == 1
    pool.free("a")
    assert used.value(pool=pool.label) == 0
    assert used.value(pool=other.label) == 1


def test_derive_num_slots_from_machine_spec(lm):
    from flexflow_tpu.search.machine_model import ChipSpec, SimpleMachineModel

    # v5e-class HBM vs a toy model: the ceiling clamps
    big = SimpleMachineModel(1, ChipSpec())
    assert derive_num_slots(lm, 64, machine=big, max_slots=16) == 16
    # a chip whose HBM the model itself exhausts: the floor keeps serving
    tiny = SimpleMachineModel(1, ChipSpec(hbm_gb=1e-9))
    assert derive_num_slots(lm, 64, machine=tiny) == 1
    # in between: capacity scales with (HBM - model) / (kv/token * max_len)
    per_tok = kv_bytes_per_token(lm)
    from flexflow_tpu.analysis import plan_memory_bytes

    model_bytes, _, _ = plan_memory_bytes(
        lm.graph, big, lm.config, optimizer_state_factor=1.0)
    want = int((big.memory_budget_bytes() - model_bytes) // (per_tok * 64))
    got = derive_num_slots(lm, 64, machine=big, max_slots=10**9)
    assert got == want and got > 16


# ---------------------------------------------------------------------
# AdmissionController
# ---------------------------------------------------------------------
def test_admission_static_rejections():
    pool = PagedKVPool(num_slots=2, max_len=16, page_size=4)
    adm = AdmissionController(pool, window=8, max_queue=4)
    with pytest.raises(RequestTooLarge, match="empty"):
        adm.admit("r0", 0, 4)
    with pytest.raises(RequestTooLarge, match="prefill window"):
        adm.admit("r1", 9, 4)
    with pytest.raises(RequestTooLarge, match="cache capacity"):
        adm.admit("r2", 8, 9)  # 17 > max_len 16
    assert adm.queue_depth() == 0  # nothing was reserved


def test_admission_queue_and_page_backpressure():
    pool = PagedKVPool(num_slots=1, max_len=16, page_size=4)  # 4 pages
    adm = AdmissionController(pool, window=8, max_queue=2,
                              queue_pages_budget=6)
    adm.admit("a", 8, 8)  # 4 pages of backlog
    with pytest.raises(PoolSaturated):
        adm.admit("b", 8, 8)  # 4 more > budget 6
    adm.admit("c", 4, 2)  # 2 pages -> exactly at budget
    with pytest.raises(QueueFull):
        adm.admit("d", 1, 1)  # depth bound (2) hit first
    # scheduling moves pages out of the backlog and frees the queue
    wait = adm.on_scheduled("a")
    assert wait >= 0.0
    adm.admit("d", 1, 1)
    assert adm.queue_depth() == 2 and adm.backlog_pages() == 3
    adm.release("c")
    adm.release("d")
    assert adm.queue_depth() == 0 and adm.backlog_pages() == 0


# ---------------------------------------------------------------------
# ContinuousBatcher: parity, state machine, slot reuse, streaming
# ---------------------------------------------------------------------
def test_continuous_token_parity_with_lockstep(lm):
    """Mixed prompt lengths through 2 slots (3 requests, so one reuses a
    freed slot): every request's greedy tokens are IDENTICAL to a lockstep
    GenerativeSession run of that prompt alone."""
    prompts = _prompts([4, 7, 3], seed=0)
    session = GenerativeSession(lm, max_len=12)
    refs = [session.generate(p[None, :], 5)[0] for p in prompts]
    with ContinuousBatcher(lm, max_len=12, num_slots=2, page_size=4,
                           max_queue=8) as cb:
        reqs = [cb.submit(p, 5) for p in prompts]
        outs = [r.result(timeout=300) for r in reqs]
    for out, ref, req in zip(outs, refs, reqs):
        np.testing.assert_array_equal(out, np.asarray(ref))
        assert req.state is RequestState.FINISHED
        assert req.t_first_token is not None and req.t_done is not None
        assert req.ttft_s >= 0 and req.queue_wait_s >= 0
    st = cb.stats()
    assert st["completed"] == 3 and st["failed"] == 0
    assert st["pool"]["pages_used"] == 0 and st["slots_active"] == 0


def test_continuous_slot_reuse_mid_decode(lm):
    """num_slots=1 forces full serialization through ONE slot: each next
    request prefills into the slot the previous one released, and the
    cache rows left behind never leak into the next request's tokens."""
    prompts = _prompts([5, 5, 5], seed=3)
    session = GenerativeSession(lm, max_len=12)
    refs = [session.generate(p[None, :], 6)[0] for p in prompts]
    with ContinuousBatcher(lm, max_len=12, num_slots=1, page_size=4,
                           max_queue=8, queue_pages_budget=64) as cb:
        reqs = [cb.submit(p, 6) for p in prompts]
        for req, ref in zip(reqs, refs):
            np.testing.assert_array_equal(req.result(timeout=300),
                                          np.asarray(ref))
    assert cb.pool.free_slot_count() == 1


def test_continuous_eos_frees_slot_early(lm):
    """A request that hits EOS stops emitting THAT iteration and returns
    fewer tokens; its pages are released immediately."""
    [p] = _prompts([4], seed=1)
    ref = GenerativeSession(lm, max_len=12).generate(p[None, :], 6)[0]
    eos = int(ref[2])
    with ContinuousBatcher(lm, max_len=12, num_slots=2,
                           page_size=4) as cb:
        out = cb.submit(p, 6, eos_id=eos).result(timeout=300)
    np.testing.assert_array_equal(out, ref[:3])  # stops AT the eos token


def test_continuous_streaming_order_and_result_agree(lm):
    [p] = _prompts([4], seed=2)
    with ContinuousBatcher(lm, max_len=12, num_slots=2,
                           page_size=4) as cb:
        req = cb.submit(p, 5)
        streamed = list(req.stream(timeout=300))
        np.testing.assert_array_equal(req.result(timeout=10), streamed)
    assert len(streamed) == 5


def test_continuous_sampling_deterministic_and_traffic_independent(lm):
    """temperature>0: a request's tokens are a function of its own
    (seed, prompt) — the same request alone or sharing iterations with
    other traffic samples the SAME sequence; a different seed differs."""
    prompts = _prompts([4, 6, 5], seed=4)
    kw = dict(max_len=12, num_slots=2, page_size=4, temperature=1.0,
              top_k=10)
    with ContinuousBatcher(lm, **kw) as cb:
        alone = cb.submit(prompts[0], 5, seed=42).result(timeout=300)
    with ContinuousBatcher(lm, **kw) as cb:
        reqs = [cb.submit(prompts[0], 5, seed=42),
                cb.submit(prompts[1], 5, seed=7),
                cb.submit(prompts[2], 5, seed=9)]
        crowded = reqs[0].result(timeout=300)
        other = cb.submit(prompts[0], 5, seed=43).result(timeout=300)
    np.testing.assert_array_equal(alone, crowded)
    assert not np.array_equal(alone, other)


def test_continuous_admission_rejections(lm):
    with ContinuousBatcher(lm, max_len=12, num_slots=1, page_size=4,
                           max_queue=2) as cb:
        # chunked prefill (the default) removed the prompt <= window cap:
        # a 13-token prompt against the 12-token window is only rejected
        # because prompt + max_new exceeds the per-slot cache span
        with pytest.raises(RequestTooLarge, match="cache capacity"):
            cb.submit(np.ones(13, np.int32), 2)
        with pytest.raises(RequestTooLarge, match="cache capacity"):
            cb.submit(np.ones(8, np.int32), 8)
        with pytest.raises(ValueError, match="max_new_tokens"):
            cb.submit(np.ones(4, np.int32), 0)
        with pytest.raises(ValueError, match="ONE prompt"):
            cb.submit(np.ones((2, 4), np.int32), 2)
        from flexflow_tpu.obs import REGISTRY

        rej = REGISTRY.counter("ff_serving_rejections_total",
                               labels=("reason",))
        assert rej.value(reason="too_large") == 2
    # the one-shot path keeps the window cap (it pads the prompt to the
    # model's declared input length)
    with ContinuousBatcher(lm, max_len=16, num_slots=1, page_size=4,
                           max_queue=2, prefill_chunk_tokens=0) as cb:
        with pytest.raises(RequestTooLarge, match="prefill window"):
            cb.submit(np.ones(13, np.int32), 2)


def test_continuous_stop_fails_queued_typed(lm):
    """stop(): active requests finish; requests still queued fail with
    BatcherStopped; submits after stop are rejected."""
    cb = ContinuousBatcher(lm, max_len=12, num_slots=1, page_size=4,
                           max_queue=8, queue_pages_budget=64)
    cb._running = True  # accept submits; the scheduler loop never runs
    reqs = [cb.submit(p, 4) for p in _prompts([4, 4], seed=5)]
    cb.stop()
    for r in reqs:
        with pytest.raises(BatcherStopped):
            r.result(timeout=10)
        assert r.state is RequestState.FAILED
    with pytest.raises(BatcherStopped):
        cb.submit(_prompts([4])[0], 2)


def test_continuous_cancel_queued_request(lm):
    """cancel() removes a still-queued request (reservation released,
    typed RequestCancelled), and refuses once it reached a slot."""
    from flexflow_tpu.serving.sched import RequestCancelled

    cb = ContinuousBatcher(lm, max_len=12, num_slots=1, page_size=4,
                           max_queue=8, queue_pages_budget=64)
    cb._running = True  # accept submits; scheduler loop never runs
    a = cb.submit(_prompts([4], seed=8)[0], 4)
    assert cb.cancel(a) is True
    with pytest.raises(RequestCancelled):
        a.result(timeout=5)
    assert cb.admission.queue_depth() == 0
    cb._running = False
    # a FINISHED/scheduled request cannot be cancelled
    with ContinuousBatcher(lm, max_len=12, num_slots=1,
                           page_size=4) as cb2:
        b = cb2.submit(_prompts([4], seed=9)[0], 3)
        b.result(timeout=300)
        assert cb2.cancel(b) is False


def test_batcher_submit_after_stop_fails_fast():
    """submit() on a stopped batcher must fail the future with
    BatcherStopped, not enqueue into a dead queue and hang the waiter."""
    fake = _FakeModel()
    b = DynamicBatcher(fake, max_batch_size=4)
    b.start()
    b.stop()
    with pytest.raises(BatcherStopped):
        b.submit({"x": np.zeros((1, 3), np.float32)}).result(timeout=5)


# ---------------------------------------------------------------------
# server wiring: /generate, streaming, 429 backpressure
# ---------------------------------------------------------------------
def test_server_continuous_generate_and_stream(lm):
    prompts = _prompts([4, 6], seed=6)
    session = GenerativeSession(lm, max_len=12)
    refs = [session.generate(p[None, :], 5)[0] for p in prompts]
    server = InferenceServer()
    server.register_continuous(
        "clm", ContinuousBatcher(lm, max_len=12, num_slots=2, page_size=4))
    httpd = server.serve_http(port=0)
    try:
        port = httpd.server_address[1]

        def post(payload, path="/v2/models/clm/generate"):
            return urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"}))

        # ragged multi-prompt: each row matches its lockstep reference
        with post({"prompt": [p.tolist() for p in prompts],
                   "max_new_tokens": 5}) as r:
            toks = json.load(r)["tokens"]
        for row, ref in zip(toks, refs):
            np.testing.assert_array_equal(row, np.asarray(ref))
        # streaming: one NDJSON line per token, then the done trailer
        with post({"prompt": prompts[0].tolist(), "max_new_tokens": 5,
                   "stream": True}) as r:
            assert r.headers["Content-Type"] == "application/x-ndjson"
            lines = [json.loads(ln) for ln in r.read().splitlines()]
        assert [ln["token"] for ln in lines[:-1]] == list(refs[0])
        assert lines[-1]["done"] is True
        assert lines[-1]["tokens"] == list(refs[0])
        # health inventory + stats carry the scheduler state
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz") as r:
            assert json.load(r)["continuous"] == ["clm"]
        assert server.stats()["_continuous"]["clm"]["completed"] >= 3
        # request that can never fit -> 400 with the typed reason
        with pytest.raises(urllib.error.HTTPError) as e400:
            post({"prompt": list(range(1, 14)), "max_new_tokens": 2})
        assert e400.value.code == 400
        assert json.load(e400.value)["reason"] == "too_large"
        # /metrics carries the serving families and stays exposition-valid
        from flexflow_tpu.obs import validate_exposition

        text = server.prometheus_text()
        validate_exposition(text)
        for fam in ("ff_kvpool_pages_used", "ff_serving_slots_active",
                    "ff_serving_ttft_ms", "ff_serving_queue_depth"):
            assert fam in text, fam
    finally:
        httpd.shutdown()
        server.shutdown()


def test_server_continuous_backpressure_429(lm):
    """Typed saturation surfaces as HTTP 429: a batcher whose queue budget
    is exhausted by a held (unscheduled) request rejects the next one."""
    server = InferenceServer()
    cb = ContinuousBatcher(lm, max_len=12, num_slots=1, page_size=4,
                           max_queue=1)
    server.register_continuous("clm", cb, start=False)
    cb._running = True  # accept submits without running the scheduler
    blocker = cb.submit(_prompts([4], seed=7)[0], 4)  # fills max_queue=1
    httpd = server.serve_http(port=0)
    try:
        port = httpd.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as e429:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v2/models/clm/generate",
                data=json.dumps({"prompt": [1, 2, 3],
                                 "max_new_tokens": 2}).encode()))
        assert e429.value.code == 429
        assert json.load(e429.value)["reason"] == "queue_full"
    finally:
        httpd.shutdown()
        cb._running = False
        server.shutdown()
        with pytest.raises(BatcherStopped):
            blocker.result(timeout=10)


def test_register_continuous_mode_exclusive(lm):
    server = InferenceServer()
    try:
        server.register_generative("lm", GenerativeSession(lm, max_len=12))
        with pytest.raises(ValueError, match="one serving mode"):
            server.register_continuous(
                "lm", ContinuousBatcher(lm, max_len=12, num_slots=1),
                start=False)
    finally:
        server.shutdown()


# ---------------------------------------------------------------------
# satellite regressions: DynamicBatcher + GenerativeSession padding
# ---------------------------------------------------------------------
class _FakeModel:
    """Recording stand-in for InferenceModel: no jax, just shapes."""

    def __init__(self, dim=3):
        self.input_names = ["x"]
        self.input_specs = {"x": (dim,)}
        self.batches = []

    def predict(self, inputs):
        x = inputs["x"]
        self.batches.append(x.shape[0])
        return x * 2.0


def test_batcher_caps_coalescing_at_max_batch_size():
    """The merged batch NEVER exceeds max_batch_size: the overflow request
    leads the next batch instead (pre-fix, 3x2 rows coalesced into one
    6-row batch against max_batch_size=4)."""
    fake = _FakeModel()
    reqs = [np.full((2, 3), i, np.float32) for i in range(3)]
    with DynamicBatcher(fake, max_batch_size=4, max_delay_ms=200.0) as b:
        futs = [b.submit({"x": r}) for r in reqs]
        outs = [f.result(timeout=30) for f in futs]
    for r, o in zip(reqs, outs):
        np.testing.assert_array_equal(o, r * 2.0)
    assert fake.batches and max(fake.batches) <= 4, fake.batches
    assert sum(fake.batches) == 6


def test_batcher_validates_at_submit_failing_only_offender():
    """One malformed request must not poison the batch it would have
    joined: bad names/shapes fail at submit(), good requests still run."""
    fake = _FakeModel()
    with DynamicBatcher(fake, max_batch_size=8, max_delay_ms=50.0) as b:
        bad_name = b.submit({"y": np.zeros((1, 3), np.float32)})
        bad_shape = b.submit({"x": np.zeros((1, 4), np.float32)})
        bad_empty = b.submit({"x": np.zeros((0, 3), np.float32)})
        good = b.submit({"x": np.ones((1, 3), np.float32)})
        np.testing.assert_array_equal(good.result(timeout=30),
                                      np.full((1, 3), 2.0))
        with pytest.raises(KeyError):
            bad_name.result(timeout=5)
        with pytest.raises(ValueError, match="trailing shape"):
            bad_shape.result(timeout=5)
        with pytest.raises(ValueError, match="leading batch dim"):
            bad_empty.result(timeout=5)


def test_batcher_stop_drains_pending_with_typed_error():
    """stop() fails still-queued futures with BatcherStopped instead of
    leaving their waiters hanging."""
    fake = _FakeModel()
    b = DynamicBatcher(fake, max_batch_size=4)
    futs = [b.submit({"x": np.zeros((1, 3), np.float32)})
            for _ in range(3)]  # never started: everything stays queued
    b.stop()
    for f in futs:
        with pytest.raises(BatcherStopped):
            f.result(timeout=5)


def test_generate_padded_rows_never_delay_eos(lm):
    """Partial-batch padding rows are finished from step 0: under sampling
    the tiled pad row draws its own tokens, and pre-fix its (non-)eos kept
    the whole batch decoding past the real row's stop (width 6, not 2)."""
    p = np.random.RandomState(11).randint(1, 50, size=(1, 4)).astype(np.int32)
    kw = dict(temperature=1.0, top_k=10, seed=5)
    free = GenerativeSession(lm, max_len=12).generate(p, 6, **kw)
    eos = int(free[0, 1])
    got = GenerativeSession(lm, max_len=12).generate(p, 6, eos_id=eos, **kw)
    assert got.shape == (1, 2), got
    np.testing.assert_array_equal(got[0], free[0, :2])
    # the chunked path honors the same early stop
    chunked = GenerativeSession(lm, max_len=12).generate(
        p, 6, eos_id=eos, tokens_per_dispatch=3, **kw)
    np.testing.assert_array_equal(chunked, got)


# ---------------------------------------------------------------------
# expert-affine admission (ISSUE 16)
# ---------------------------------------------------------------------
class _FakeReq:
    def __init__(self, sig, skips=0):
        self.expert_sig = frozenset(sig)
        self.affinity_skips = skips


def test_pick_affine_prefers_overlap_within_window():
    from flexflow_tpu.serving.sched.affinity import (overlap_fraction,
                                                     pick_affine)

    active = [frozenset({0, 1})]
    queue = [_FakeReq({2, 3}), _FakeReq({0, 1}), _FakeReq({1, 4}),
             _FakeReq({0, 1})]
    idx, outcome, frac = pick_affine(queue, active, window=4)
    assert (idx, outcome, frac) == (1, "affine", 1.0)  # ties -> lowest idx
    # outside the window the perfect match is invisible
    idx, outcome, _ = pick_affine(queue[:1] + queue[2:], active, window=1)
    assert (idx, outcome) == (0, "fifo")
    assert overlap_fraction(frozenset(), active) == 0.0


def test_pick_affine_forces_starved_head():
    from flexflow_tpu.serving.sched.affinity import pick_affine

    queue = [_FakeReq({2, 3}, skips=4), _FakeReq({0, 1})]
    idx, outcome, _ = pick_affine(queue, [frozenset({0, 1})], window=4)
    assert (idx, outcome) == (0, "forced")  # no starvation past `window`


def test_expert_affinity_batcher_parity_and_stats():
    """Affinity ON re-orders admissions only: every request's tokens
    match the lockstep GenerativeSession reference, and the scheduler
    reports its pick outcomes + overlap EWMA."""
    from flexflow_tpu.serving.sched.affinity import ExpertAffinityProbe
    from flexflow_tpu.serving.sched.bench import build_tiny_moe_lm

    lm = build_tiny_moe_lm(2, 16, vocab=32, hidden=16, heads=2, layers=1,
                           experts=4, moe_top_k=2)
    probe = ExpertAffinityProbe(lm)
    assert probe.num_experts == 4 and probe.top_k == 2
    prompts = _prompts([4, 6, 5, 3, 7, 4], seed=9, vocab=32)
    sigs = [probe.signature(p) for p in prompts]
    assert all(len(s) == 2 for s in sigs)
    assert sigs[0] == probe.signature(prompts[0])  # deterministic

    session = GenerativeSession(lm, max_len=16)
    refs = [session.generate(p[None, :], 4)[0] for p in prompts]
    with ContinuousBatcher(lm, max_len=16, num_slots=2, page_size=4,
                           expert_affinity=True,
                           affinity_window=3) as cb:
        reqs = [cb.submit(p, 4) for p in prompts]
        outs = [r.result(timeout=300) for r in reqs]
        stats = cb.stats()
    for got, ref in zip(outs, refs):
        np.testing.assert_array_equal(got, ref)
    aff = stats["affinity"]
    assert aff["window"] == 3
    assert sum(aff["picks"].values()) > 0
    if aff["overlap_ewma"] is not None:
        assert 0.0 <= aff["overlap_ewma"] <= 1.0


def test_expert_affinity_rejects_dense_models(lm):
    """expert_affinity=True on a model with no EXPERTS op fails fast at
    construction, not mid-serve."""
    with pytest.raises(ValueError, match="EXPERTS"):
        ContinuousBatcher(lm, max_len=12, num_slots=2, page_size=4,
                          expert_affinity=True)
