"""HuggingFace symbolic_trace importer path + get_attr support (reference:
python/flexflow/torch/model.py:2427-2444 HF tracing; tests/align scale)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn

import flexflow_tpu as ff
from flexflow_tpu.torch import PyTorchModel


def make_config(batch):
    c = ff.FFConfig()
    c.batch_size = batch
    c.num_devices = 1
    c.allow_mixed_precision = False
    return c


class T5StyleBlock(nn.Module):
    """T5/mt5-style block with a custom RMS layernorm whose weight is read
    via get_attr (self.ln_weight) — the pattern plain torch.fx traces to
    get_attr nodes."""

    def __init__(self, d=32, heads=4):
        super().__init__()
        self.d = d
        self.heads = heads
        self.ln_weight = nn.Parameter(torch.ones(d))
        self.q = nn.Linear(d, d, bias=False)
        self.k = nn.Linear(d, d, bias=False)
        self.v = nn.Linear(d, d, bias=False)
        self.o = nn.Linear(d, d, bias=False)
        self.wi = nn.Linear(d, 4 * d, bias=False)
        self.wo = nn.Linear(4 * d, d, bias=False)

    def rms_norm(self, x):
        var = x.pow(2).mean(-1, keepdim=True)
        return self.ln_weight * (x * torch.rsqrt(var + 1e-6))

    def forward(self, x):
        b, l, d = 2, 8, self.d
        h = self.rms_norm(x)
        hd = d // self.heads
        q = self.q(h).view(b, l, self.heads, hd).transpose(1, 2)
        k = self.k(h).view(b, l, self.heads, hd).transpose(1, 2)
        v = self.v(h).view(b, l, self.heads, hd).transpose(1, 2)
        s = torch.matmul(q, k.transpose(2, 3)) / (hd ** 0.5)
        p = torch.softmax(s, dim=-1)
        ctx = torch.matmul(p, v).transpose(1, 2).reshape(b, l, d)
        x = x + self.o(ctx)
        h = self.rms_norm(x)
        return x + self.wo(torch.relu(self.wi(h)))


def test_get_attr_t5_style_block_parity():
    m = T5StyleBlock().eval()
    x = np.random.RandomState(0).randn(2, 8, 32).astype(np.float32)

    config = make_config(2)
    model = ff.FFModel(config)
    t = model.create_tensor([2, 8, 32])
    pt = PyTorchModel(m)
    outs = pt.apply(model, [t])
    model.final_tensor = outs[0]
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    n = pt.transfer_weights(model)
    assert n >= 6
    ours = model.predict(x)
    with torch.no_grad():
        theirs = m(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)


def test_hf_bert_encoder_align():
    """mt5-encoder-scale align: a real HuggingFace encoder traced through
    transformers.utils.fx, imported, weights transferred, outputs matching
    torch (reference: tests/align + the HF symbolic_trace path)."""
    transformers = pytest.importorskip("transformers")
    from transformers import BertConfig, BertModel

    cfg = BertConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    m = BertModel(cfg).eval()
    B, L = 2, 16
    ids = np.random.RandomState(0).randint(0, 128, size=(B, L)).astype(np.int32)

    config = make_config(B)
    model = ff.FFModel(config)
    t = model.create_tensor([B, L], ff.DataType.DT_INT32)
    pt = PyTorchModel(m, input_names=["input_ids"])
    outs = pt.apply(model, [t])
    out = outs[0]
    if isinstance(out, dict):
        out = out["last_hidden_state"]
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    n = pt.transfer_weights(model)
    assert n > 20  # embeddings + 2 layers of qkv/out/ffn/ln + pooler
    ours = model.predict(ids)
    with torch.no_grad():
        theirs = m(torch.from_numpy(ids.astype(np.int64))
                   ).last_hidden_state.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=1e-3)


def test_hf_mt5_align():
    """The reference's align target (tests/align mt5_encoder) and beyond:
    the FULL mt5 encoder-decoder (relative position bias, causal masks via
    trace-time setitem/full folding, cross-attention) traced through
    transformers.utils.fx, imported, weights transferred, outputs matching
    torch."""
    transformers = pytest.importorskip("transformers")
    from transformers import MT5Config, MT5Model

    cfg = MT5Config(vocab_size=128, d_model=64, d_kv=16, d_ff=128,
                    num_layers=2, num_decoder_layers=2, num_heads=4,
                    dropout_rate=0.0)
    m = MT5Model(cfg).eval()
    B, L = 2, 16
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, size=(B, L)).astype(np.int32)
    dids = rng.randint(0, 128, size=(B, L)).astype(np.int32)

    config = make_config(B)
    model = ff.FFModel(config)
    t = model.create_tensor([B, L], ff.DataType.DT_INT32)
    td = model.create_tensor([B, L], ff.DataType.DT_INT32)
    pt = PyTorchModel(m, input_names=["input_ids", "decoder_input_ids"])
    outs = pt.apply(model, [t, td])
    out = outs[0]
    if isinstance(out, dict):
        out = out.get("last_hidden_state", out)
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    n = pt.transfer_weights(model)
    assert n >= 50, n  # embeddings + 2 enc + 2 dec blocks incl. cross-attn
    ours = model.predict([ids, dids])
    with torch.no_grad():
        theirs = m(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            decoder_input_ids=torch.from_numpy(dids.astype(np.int64)),
        ).last_hidden_state.numpy()
    np.testing.assert_allclose(ours, theirs, atol=5e-4, rtol=1e-3)
