"""Search scalability soak (VERDICT r3 item 6): a reference-scale graph —
BERT-24, 170+ ops — searched at 256 devices with every axis enabled must
finish in bounded wall-clock. The reference's memoized DP exists precisely
for this regime (graph.cc:1586); here the budget pyramid is: memoized
segment DP for every mesh factorization, full-graph event simulation once
per factorization, and the expensive cross-segment refinement only for the
top-K seeded candidates (config.refine_top_k).

Local timing ~40s; the bound leaves headroom for slower CI machines.
Scaling datapoint (not asserted): BERT-48, 340 ops, at 512 devices with
every axis + the memory-aware lambda search finishes in ~194s.
"""
import time

import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.models import TransformerConfig, build_bert_encoder
from flexflow_tpu.search.machine_model import make_machine_model
from flexflow_tpu.search.unity import unity_optimize

WALL_CLOCK_BOUND_S = 240.0


def _bert24_graph():
    config = ff.FFConfig()
    config.num_devices = 256
    config.batch_size = 1024
    config.search_budget = 50
    config.measure_op_costs = False
    config.enable_sequence_parallel = True
    config.enable_pipeline_parallel = True
    config.memory_search = True
    config.memory_budget_mb = 8 * 1024.0
    model = ff.FFModel(config)
    tokens = model.create_tensor([1024, 128], ff.DataType.DT_INT32)
    cfg = TransformerConfig(hidden_size=1024, embedding_size=1024,
                            num_heads=16, num_layers=24,
                            sequence_length=128, vocab_size=30522)
    build_bert_encoder(model, tokens, cfg)
    return Graph(model.ops), config


def test_bert24_search_at_256_devices_bounded():
    graph, config = _bert24_graph()
    assert len(graph.ops) >= 128, "soak graph must be reference-scale"
    machine = make_machine_model(config, 256)
    t0 = time.perf_counter()
    res = unity_optimize(graph, config, machine, 1024, 256)
    dt = time.perf_counter() - t0
    assert dt < WALL_CLOCK_BOUND_S, (
        f"search took {dt:.0f}s (> {WALL_CLOCK_BOUND_S:.0f}s) on a "
        f"{len(graph.ops)}-op graph at 256 devices")
    # the result must be a real full coverage strategy set
    assert set(res.strategies) == set(graph.ops)
    assert res.mesh_axes and np.prod(list(res.mesh_axes.values())) <= 256
    assert np.isfinite(res.cost_us) and res.cost_us > 0
    # memory-aware: the chosen strategy respects the budget when feasible
    assert res.memory_bytes <= config.memory_budget_mb * 1e6 * 1.05


def test_simulate_memoization_consistent():
    """The memoized cost path returns the same numbers as a fresh
    simulator (guards the caches added for the soak)."""
    from flexflow_tpu.search.simulator import OpStrategy, Simulator

    config = ff.FFConfig()
    config.batch_size = 64
    config.measure_op_costs = False
    model = ff.FFModel(config)
    t = model.create_tensor([64, 32], ff.DataType.DT_FLOAT)
    h = model.dense(t, 64, ff.ActiMode.AC_MODE_RELU)
    model.softmax(model.dense(h, 8))
    g = Graph(model.ops)
    machine = make_machine_model(config, 8)
    strategies = {guid: OpStrategy(dp=4, tp=2) for guid in g.ops}

    sim = Simulator(machine, config)
    first = sim.simulate(g, strategies)
    again = sim.simulate(g, strategies)       # memoized path
    fresh = Simulator(machine, config).simulate(g, strategies)
    assert first == again == fresh
