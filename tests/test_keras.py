"""Keras frontend tests (reference test model: tests/python_interface_test.sh,
examples/python/keras/*)."""
import numpy as np
import pytest

import flexflow_tpu.keras as keras
from flexflow_tpu.keras.layers import (
    Activation,
    Add,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    Input,
    MaxPooling2D,
)
from flexflow_tpu.keras.models import Model, Sequential
from flexflow_tpu.config import FFConfig


def small_config(batch=16):
    c = FFConfig()
    c.batch_size = batch
    c.num_devices = 1
    return c


def separable_data(n=128, dim=20, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim) * 3.0
    y = rng.randint(0, classes, size=n).astype(np.int32)
    x = (centers[y] + rng.randn(n, dim) * 0.5).astype(np.float32)
    return x, y.reshape(-1, 1)


def test_sequential_mlp_learns():
    x, y = separable_data()
    model = Sequential()
    model.add(Dense(32, activation="relu", input_shape=(20,)))
    model.add(Dense(4))
    model.add(Activation("softmax"))
    model.compile(
        optimizer=keras.optimizers.SGD(learning_rate=0.1),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
        ffconfig=small_config(),
    )
    hist = model.fit(x, y, epochs=8)
    assert hist.history["accuracy"][-1] > 0.8
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_sequential_steps_per_execution_learns():
    """compile(steps_per_execution=K) — tf.keras semantics: K optimizer
    steps per jitted dispatch — trains to the same accuracy bar as the
    per-step path."""
    x, y = separable_data()
    model = Sequential()
    model.add(Dense(32, activation="relu", input_shape=(20,)))
    model.add(Dense(4))
    model.add(Activation("softmax"))
    model.compile(
        optimizer=keras.optimizers.SGD(learning_rate=0.1),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
        ffconfig=small_config(),
        steps_per_execution=4,
    )
    hist = model.fit(x, y, epochs=8)
    assert hist.history["accuracy"][-1] > 0.8
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_sequential_cnn_compiles_and_trains():
    rng = np.random.RandomState(0)
    x = rng.rand(16, 3, 8, 8).astype(np.float32)
    y = rng.randint(0, 2, size=(16, 1)).astype(np.int32)
    model = Sequential()
    model.add(Conv2D(filters=4, input_shape=(3, 8, 8), kernel_size=(3, 3),
                     strides=(1, 1), padding=(1, 1), activation="relu"))
    model.add(MaxPooling2D(pool_size=(2, 2), strides=(2, 2), padding="valid"))
    model.add(Flatten())
    model.add(Dense(2))
    model.add(Activation("softmax"))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], ffconfig=small_config())
    hist = model.fit(x, y, epochs=1)
    assert "loss" in hist.history


def test_functional_model_merge():
    a = Input(shape=(10,))
    b = Input(shape=(10,))
    ha = Dense(8, activation="relu")(a)
    hb = Dense(8, activation="relu")(b)
    merged = Concatenate(axis=1)([ha, hb])
    out = Dense(3, activation="softmax")(merged)
    model = Model(inputs=[a, b], outputs=out)
    model.compile(optimizer=keras.optimizers.Adam(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], ffconfig=small_config())
    rng = np.random.RandomState(1)
    xa = rng.rand(32, 10).astype(np.float32)
    xb = rng.rand(32, 10).astype(np.float32)
    y = rng.randint(0, 3, size=(32, 1)).astype(np.int32)
    hist = model.fit([xa, xb], y, epochs=2)
    assert len(hist.history["loss"]) == 2
    s = model.summary()
    assert "Total params" in s


def test_residual_add():
    a = Input(shape=(16,))
    h = Dense(16, activation="relu")(a)
    res = Add()([a, h])
    out = Dense(2, activation="softmax")(res)
    model = Model(inputs=a, outputs=out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], ffconfig=small_config())
    x = np.random.RandomState(2).rand(16, 16).astype(np.float32)
    y = np.zeros((16, 1), dtype=np.int32)
    model.fit(x, y, epochs=1)


def test_embedding_sequential():
    model = Sequential()
    model.add(Embedding(100, 8, input_shape=(12,)))
    model.add(Flatten())
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], ffconfig=small_config())
    x = np.random.RandomState(3).randint(0, 100, size=(16, 12)).astype(np.int32)
    y = np.random.RandomState(4).randint(0, 4, size=(16, 1)).astype(np.int32)
    model.fit(x, y, epochs=1)


def test_lr_scheduler_and_early_stop():
    x, y = separable_data(n=64)
    lrs = []

    def schedule(epoch):
        lr = 0.1 * (0.5 ** epoch)
        lrs.append(lr)
        return lr

    model = Sequential()
    model.add(Dense(16, activation="relu", input_shape=(20,)))
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], ffconfig=small_config())
    cb = keras.callbacks.LearningRateScheduler(schedule)
    stop = keras.callbacks.EpochVerifyMetrics(10.0)  # stop once acc >= 10%
    hist = model.fit(x, y, epochs=6, callbacks=[cb, stop])
    assert len(lrs) >= 1
    assert len(hist.epoch) < 6  # early stop triggered


def test_predict_and_weights_roundtrip():
    x, y = separable_data(n=32)
    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(20,)))
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], ffconfig=small_config())
    pred = model.predict(x)
    assert pred.shape == (32, 4)
    w = model.get_weights()
    w2 = [np.zeros_like(a) for a in w]
    model.set_weights(w2)
    assert float(np.abs(model.get_weights()[0]).sum()) == 0.0
    model.set_weights(w)


def test_regularizer_increases_loss():
    x, y = separable_data(n=32)
    def build(reg):
        model = Sequential()
        model.add(Dense(8, activation="relu", input_shape=(20,),
                        kernel_regularizer=reg))
        model.add(Dense(4, activation="softmax"))
        model.compile(optimizer=keras.optimizers.SGD(learning_rate=0.0),
                      loss="sparse_categorical_crossentropy",
                      metrics=[], ffconfig=small_config())
        return model.fit(x, y, epochs=1).history["loss"][0]

    base = build(None)
    reg = build(keras.regularizers.L2(10.0))
    assert reg > base


def test_datasets_and_utils():
    (xt, yt), (xv, yv) = keras.datasets.mnist.load_data()
    assert xt.shape[1:] == (28, 28) and xt.dtype == np.uint8
    (xc, yc), _ = keras.datasets.cifar10.load_data(num_samples=100)
    assert xc.shape == (100, 3, 32, 32) and yc.shape == (100, 1)
    (xr, yr), _ = keras.datasets.reuters.load_data()
    assert len(xr) > 0

    oh = keras.utils.to_categorical([0, 2, 1], 3)
    assert oh.shape == (3, 3) and oh[1, 2] == 1

    padded = keras.preprocessing.sequence.pad_sequences([[1, 2], [3]], maxlen=4)
    assert padded.shape == (2, 4) and padded[0, -1] == 2

    tok = keras.preprocessing.text.Tokenizer()
    tok.fit_on_texts(["hello world", "hello there"])
    seqs = tok.texts_to_sequences(["hello world"])
    assert len(seqs[0]) == 2


def test_model_checkpoint_callback(tmp_path):
    x, y = separable_data(n=32)
    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(20,)))
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], ffconfig=small_config())
    path = str(tmp_path / "ckpt_{epoch}")
    cb = keras.callbacks.ModelCheckpoint(path)
    model.fit(x, y, epochs=2, callbacks=[cb])
    import os

    assert os.path.exists(str(tmp_path / "ckpt_1.npz"))

    from flexflow_tpu.runtime.checkpoint import restore_checkpoint

    step = restore_checkpoint(str(tmp_path / "ckpt_1"), model.ffmodel)
    assert step == 1


def test_stable_layer_names_across_models():
    def build():
        m = Sequential()
        m.add(Dense(8, activation="relu", input_shape=(20,)))
        m.add(Dense(4, activation="softmax"))
        m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=[], ffconfig=small_config())
        return sorted(m.ffmodel.params.keys())

    names1 = build()
    names2 = build()  # second model in same process must get identical keys
    assert names1 == names2


def test_bfloat16_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp
    import ml_dtypes
    from flexflow_tpu.runtime.checkpoint import restore_checkpoint, save_checkpoint

    x, y = separable_data(n=32)
    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(20,)))
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=[], ffconfig=small_config())
    fm = model.ffmodel
    # force a bf16 param
    op = sorted(fm.params)[0]
    w = sorted(fm.params[op])[0]
    orig = fm.params[op][w]
    fm.params[op][w] = orig.astype(jnp.bfloat16)
    save_checkpoint(str(tmp_path / "bf16"), fm, step=3)
    fm.params[op][w] = orig
    step = restore_checkpoint(str(tmp_path / "bf16"), fm)
    assert step == 3
    assert fm.params[op][w].dtype == jnp.bfloat16


def test_same_padding_stride_aware():
    from flexflow_tpu.keras.layers.convolutional import _padding

    # stride==kernel -> no padding (reference formula max(k-s,0)//2)
    assert _padding("same", (2, 2), (2, 2)) == (0, 0)
    assert _padding("same", (3, 3), (1, 1)) == (1, 1)
    pool = MaxPooling2D(pool_size=(2, 2), strides=(2, 2), padding="same")
    assert pool.compute_output_shape([(None, 4, 8, 8)]) == (None, 4, 4, 4)
