"""Test harness config: run on a virtual 8-device CPU mesh.

Mirrors SURVEY.md §4's implication: the reference can only test multi-device
logic on a real cluster; we test multi-chip sharding without hardware via
XLA's host-platform device-count override.

Note: the TPU platform plugin may already be registered at interpreter start
(site hook), so JAX_PLATFORMS in os.environ alone is not enough — we force the
platform through jax.config, which takes effect before any backend client is
created."""
from flexflow_tpu.runtime.platform import force_platform

force_platform("cpu", n_host_devices=8)
