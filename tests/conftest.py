"""Test harness config: run on a virtual 8-device CPU mesh.

Mirrors SURVEY.md §4's implication: the reference can only test multi-device
logic on a real cluster; we test multi-chip sharding without hardware via
XLA's host-platform device-count override.

Note: the TPU platform plugin may already be registered at interpreter start
(site hook), so JAX_PLATFORMS in os.environ alone is not enough — we force the
platform through jax.config, which takes effect before any backend client is
created."""
from flexflow_tpu.runtime.platform import force_platform

force_platform("cpu", n_host_devices=8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_obs_state():
    """Process-wide observability state must not leak between tests: one
    obs.reset_all() zeroes every registry counter family (plan
    diagnostics, checkpoint, watchdog, step stats) and drops buffered
    trace spans — replacing the three separate reset_*_counters calls
    tests previously had to remember."""
    import flexflow_tpu.obs as obs

    obs.reset_all()
    yield
    obs.reset_all()
