"""Test harness config: run on a virtual 8-device CPU mesh.

Mirrors SURVEY.md §4's implication: the reference can only test multi-device
logic on a real cluster; we test multi-chip sharding without hardware via
XLA's host-platform device-count override.

Note: the TPU platform plugin may already be registered at interpreter start
(site hook), so JAX_PLATFORMS in os.environ alone is not enough — we force the
platform through jax.config, which takes effect before any backend client is
created."""
from flexflow_tpu.runtime.platform import force_platform

force_platform("cpu", n_host_devices=8)

import pytest  # noqa: E402


def pytest_configure(config):
    """Register the `slow` marker (no pytest.ini/pyproject marker table in
    this repo): heavy multi-step training tests — MoE transformers
    training to parity, large searched-plan fits — opt out of the tier-1
    sweep, which runs `-m 'not slow'`. A full `pytest tests/` still runs
    them."""
    config.addinivalue_line(
        "markers",
        "slow: heavy training/search tests excluded from the tier-1 "
        "`-m 'not slow'` sweep")


def module_xla_cache():
    """Generator behind the serving modules' module-scoped XLA
    compilation-cache fixture (each module wires it up as
    `_xla_cache = pytest.fixture(scope="module", autouse=True)(
    module_xla_cache)`). Those modules build fresh batchers/replicas per
    test whose per-instance jax.jit dispatches trace to identical HLO
    (same tiny model, same pool geometry), so a per-module disk cache
    turns each repeat compile into a ~5x-cheaper deserialization and
    roughly halves the module's wall clock. Deliberately NOT suite-wide:
    the cache segfaults on the multi-device TRAINING executables other
    test modules compile (donated shard_map buffers on the CPU mesh),
    and single-device serving inference is the only surface it has been
    proven safe on."""
    import jax

    prev_entry = jax.config.jax_persistent_cache_min_entry_size_bytes
    prev_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_compilation_cache_dir", _serving_xla_cache_dir())
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    yield
    jax.config.update("jax_compilation_cache_dir", None)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                      prev_entry)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      prev_secs)


_SERVING_XLA_CACHE_DIR = None


def _serving_xla_cache_dir() -> str:
    """ONE cache dir per pytest session, shared by every serving module:
    jax latches the persistent-cache instance at first initialization,
    so a per-module mkdtemp would only redirect the CONFIG while writes
    keep landing in the first module's (possibly deleted) directory —
    and sharing the dir lets later modules hit entries the earlier ones
    compiled. Removed at session end by _serving_xla_cache_cleanup."""
    global _SERVING_XLA_CACHE_DIR
    if _SERVING_XLA_CACHE_DIR is None:
        import tempfile

        _SERVING_XLA_CACHE_DIR = tempfile.mkdtemp(
            prefix="ff_serving_xla_cache_")
    return _SERVING_XLA_CACHE_DIR


@pytest.fixture(scope="session", autouse=True)
def _serving_xla_cache_cleanup():
    yield
    if _SERVING_XLA_CACHE_DIR is not None:
        import shutil

        shutil.rmtree(_SERVING_XLA_CACHE_DIR, ignore_errors=True)


@pytest.fixture(autouse=True)
def _reset_plan_cache():
    """The process-wide plan cache (search/plan_cache.py) must not leak
    between tests: a test searching the same (graph, machine, knobs) an
    earlier test searched would HIT and skip enumeration, breaking
    asserts on the search's internals (candidates_simulated, logs)."""
    from flexflow_tpu.search.plan_cache import reset_plan_cache

    reset_plan_cache()
    yield
    reset_plan_cache()


@pytest.fixture(autouse=True)
def _reset_obs_state():
    """Process-wide observability state must not leak between tests: one
    obs.reset_all() zeroes every registry counter family (plan
    diagnostics, checkpoint, watchdog, step stats) and drops buffered
    trace spans — replacing the three separate reset_*_counters calls
    tests previously had to remember."""
    import flexflow_tpu.obs as obs

    obs.reset_all()
    yield
    obs.reset_all()
