"""Attribute/spatial parallelism (reference: --enable-attribute-parallel,
config.h:136; create_mapping_xfers<Conv2D/Pool2D>, substitution.cc:1795-1797):
conv/pool H sharded over an 'attr' mesh axis (GSPMD emits the halo
exchanges), embedding attribute dims over the channel axis."""
import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.ffconst import CompMode


def _convnet(parallel_axes=None, batch=8):
    config = ff.FFConfig()
    config.batch_size = batch
    config.allow_mixed_precision = False
    config.enable_attribute_parallel = True
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, 3, 16, 16])
    t = model.conv2d(inp, 8, 3, 3, 1, 1, 1, 1,
                     activation=ff.ActiMode.AC_MODE_RELU, name="c1")
    t = model.pool2d(t, 2, 2, 2, 2, 0, 0, name="p1")
    t = model.conv2d(t, 8, 3, 3, 1, 1, 1, 1, name="c2")
    t = model.flat(t, name="flat")
    out = model.softmax(model.dense(t, 4, name="cls"))
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  parallel_axes=parallel_axes)
    return model, out


def _forward(model, out, x):
    feeds = {model.input_ops[0].name: x}
    values, _, _ = model.executor.forward_values(
        model.params, model.state, feeds, None, CompMode.COMP_MODE_INFERENCE
    )
    return np.asarray(values[out.guid])


def test_conv_spatial_split_matches_single_device():
    """H-sharded convs (halo exchange) produce single-device numerics."""
    rng = np.random.RandomState(5)
    x = rng.randn(8, 3, 16, 16).astype(np.float32)

    single, out_s = _convnet()
    ref = _forward(single, out_s, x)

    import jax

    sharded, out_p = _convnet(parallel_axes={"data": 2, "attr": 4})
    sharded.params = jax.device_put(
        {k: {kk: np.asarray(vv) for kk, vv in v.items()}
         for k, v in single.params.items()}
    )
    got = _forward(sharded, out_p, x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    # conv outputs are actually annotated with the attr axis
    conv = next(op for op in sharded.graph.ops.values() if op.name == "c1")
    assert conv.outputs[0].parallel_shape.partition_spec()[2] == "attr"


def test_conv_spatial_split_trains():
    model, _ = _convnet(parallel_axes={"data": 2, "attr": 4})
    x = np.random.RandomState(0).randn(8, 3, 16, 16).astype(np.float32)
    y = np.zeros((8, 1), dtype=np.int32)
    model.optimizer = ff.SGDOptimizer(model, lr=0.01)
    model._build_step_functions()
    model.opt_state = model.optimizer.init_state(model.params)
    hist = model.fit([x], y, batch_size=8, epochs=1)
    assert np.isfinite(hist[0]["loss"])


def test_search_selects_spatial_parallelism():
    """batch 4 on 8 devices: pure dp tops out at 4 chips; with
    --enable-attribute-parallel the search uses the other 4 on the H dim."""
    config = ff.FFConfig()
    config.batch_size = 4
    config.search_budget = 4
    config.enable_attribute_parallel = True
    model = ff.FFModel(config)
    inp = model.create_tensor([4, 64, 64, 64])
    t = model.conv2d(inp, 128, 3, 3, 1, 1, 1, 1, name="c1")
    t = model.conv2d(t, 128, 3, 3, 1, 1, 1, 1, name="c2")
    model.softmax(model.dense(model.flat(t), 10, name="cls"))

    from flexflow_tpu.core.graph import Graph
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.unity import unity_optimize

    machine = make_machine_model(config, 8)
    result = unity_optimize(Graph(model.ops), config, machine, 4, 8)
    assert result.mesh_axes.get("attr", 1) > 1, result.log
    assert any(s.ap > 1 for s in result.strategies.values())


def test_search_shards_dlrm_embeddings():
    """DLRM-style graph: huge embedding tables push the search to shard the
    embedding attribute (feature) dim (BASELINE.md config 5)."""
    config = ff.FFConfig()
    config.batch_size = 64
    config.search_budget = 4
    config.enable_attribute_parallel = True
    model = ff.FFModel(config)
    dense_in = model.create_tensor([64, 16])
    sparse_in = model.create_tensor([64, 8], ff.DataType.DT_INT32)
    emb = model.embedding(sparse_in, 500000, 64, ff.AggrMode.AGGR_MODE_SUM,
                          name="emb")
    t = model.concat([dense_in, emb], axis=-1, name="cat")
    t = model.dense(t, 64, ff.ActiMode.AC_MODE_RELU, name="mlp1")
    model.softmax(model.dense(t, 2, name="cls"))

    from flexflow_tpu.core.graph import Graph
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.unity import unity_optimize

    machine = make_machine_model(config, 8)
    result = unity_optimize(Graph(model.ops), config, machine, 64, 8)
    emb_op = next(op for op in model.ops if op.name == "emb")
    s = result.strategies[emb_op.guid]
    assert s.tp > 1, (s, result.log)
