"""Elastic runtime: fault injection, failure detection, retry, and the
kill-a-chip -> re-search -> restore -> resume recovery path, all on the
virtual 8-device CPU mesh (conftest.py)."""
import json

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.elastic import (
    ElasticCoordinator,
    EventLog,
    FaultPlan,
    RetriesExhausted,
    RetryPolicy,
    TopologyLoss,
    TransientFault,
    call_with_retry,
    classify_error,
    ring_topology_spec,
    shrink_topology_spec,
)


# -- helpers -------------------------------------------------------------
def make_config(devices=4, batch=12, budget=4):
    cfg = ff.FFConfig()
    cfg.batch_size = batch
    cfg.search_budget = budget  # > 0: recovery re-runs the Unity search
    cfg.measure_op_costs = False
    cfg.device_ids = list(range(devices))
    return cfg


def builder(cfg):
    m = ff.FFModel(cfg)
    t = m.create_tensor([cfg.batch_size, 32])
    t = m.dense(t, 64, ff.ActiMode.AC_MODE_RELU)
    t = m.dense(t, 10)
    t = m.softmax(t)
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return m


def make_data(batch, n_batches=4, din=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(batch * n_batches, din).astype(np.float32)
    w = rng.randn(din, 10).astype(np.float32)  # learnable labels
    y = np.argmax(x @ w, axis=1).reshape(-1, 1).astype(np.int32)
    return x, y


# -- retry policy --------------------------------------------------------
def test_retry_policy_backoff_bounded():
    p = RetryPolicy(max_retries=10, base_delay_s=0.1, backoff=2.0,
                    max_delay_s=0.5)
    delays = [p.delay_s(k) for k in range(8)]
    assert delays[0] == pytest.approx(0.1)
    assert delays[1] == pytest.approx(0.2)
    assert all(d <= 0.5 for d in delays)  # capped
    assert delays[-1] == pytest.approx(0.5)


def test_retry_jitter_seeded_deterministic():
    """With a seeded random.Random the jitter — and so a drill's whole
    retry timeline — replays exactly; the global-random fallback stays for
    callers that don't care."""
    import random

    p = RetryPolicy(max_retries=5, base_delay_s=0.1, jitter_frac=0.5,
                    max_delay_s=10.0)
    d1 = [p.delay_s(k, random.Random(7)) for k in range(5)]
    d2 = [p.delay_s(k, random.Random(7)) for k in range(5)]
    assert d1 == d2
    # jitter lands inside [base, base * (1 + jitter_frac)]
    for k, d in enumerate(d1):
        base = min(0.1 * 2.0 ** k, 10.0)
        assert base <= d <= base * 1.5


def test_call_with_retry_threads_rng_into_delays():
    import random

    events = EventLog()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise TransientFault("x")
        return 1

    policy = RetryPolicy(max_retries=5, base_delay_s=0.125, jitter_frac=1.0)
    call_with_retry(flaky, policy, events=events, step=1,
                    sleep=lambda s: None, rng=random.Random(3))
    got = [e.details["delay_s"] for e in events.events("retry")]
    replay = random.Random(3)
    assert got == [policy.delay_s(k, replay) for k in range(3)]


def test_call_with_retry_transient_then_success():
    events = EventLog()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientFault("flaky")
        return "done"

    out = call_with_retry(flaky, RetryPolicy(max_retries=3,
                                             base_delay_s=0.0),
                          events=events, step=7, sleep=lambda s: None)
    assert out == "done"
    assert calls["n"] == 3
    retries = events.events("retry")
    assert len(retries) == 2
    assert all(e.step == 7 for e in retries)


def test_call_with_retry_exhaustion_and_topology():
    def always_transient():
        raise TransientFault("never heals")

    with pytest.raises(RetriesExhausted) as ei:
        call_with_retry(always_transient,
                        RetryPolicy(max_retries=2, base_delay_s=0.0),
                        sleep=lambda s: None)
    assert isinstance(ei.value.__cause__, TransientFault)

    calls = {"n": 0}

    def topo():
        calls["n"] += 1
        raise TopologyLoss([3])

    # topology loss must escalate on the FIRST occurrence, never retry
    with pytest.raises(TopologyLoss):
        call_with_retry(topo, RetryPolicy(max_retries=5, base_delay_s=0.0),
                        sleep=lambda s: None)
    assert calls["n"] == 1


def test_classify_error_patterns():
    assert classify_error(TransientFault("x")) == "transient"
    assert classify_error(TopologyLoss([0])) == "topology"
    assert classify_error(RuntimeError("DEADLINE_EXCEEDED: tunnel")) \
        == "transient"
    assert classify_error(RuntimeError("DATA_LOSS: chip went away")) \
        == "topology"
    assert classify_error(RuntimeError("slice has been preempted")) \
        == "topology"
    assert classify_error(ValueError("plain bug")) == "unknown"


# -- fault plan ----------------------------------------------------------
def test_fault_plan_times_and_spending():
    plan = FaultPlan().add_transient(at_step=3, times=2)
    assert plan.take(2) == []
    assert len(plan.take(3)) == 1  # first firing
    assert len(plan.take(3)) == 1  # the retry's re-dispatch
    assert plan.take(3) == []      # spent
    assert plan.pending() == []


def test_same_step_faults_fire_one_at_a_time():
    """A raising fault must not consume later same-step faults: the
    transient fires first, and the chip loss survives for the retry's
    re-dispatch instead of being silently spent."""
    plan = (FaultPlan()
            .add_transient(at_step=5)
            .add_chip_loss(at_step=5, chips=[3]))
    first = plan.take(5)
    assert len(first) == 1 and first[0].kind == "transient"
    assert [f.kind for f in plan.pending()] == ["chip_loss"]
    second = plan.take(5)
    assert len(second) == 1 and second[0].kind == "chip_loss"
    assert plan.take(5) == []


def test_slow_link_stall_flagged_by_ewma():
    from flexflow_tpu.elastic import FailureDetector
    from flexflow_tpu.elastic.faults import FaultInjector

    t = {"now": 0.0}
    events = EventLog()
    plan = FaultPlan().add_slow_link(at_step=5, stall_s=1.0)
    inj = FaultInjector(plan, events=events,
                        sleep=lambda s: t.__setitem__("now", t["now"] + s))
    det = FailureDetector(events=events, injector=inj, warmup_steps=0,
                          clock=lambda: t["now"])

    def thunk():
        t["now"] += 0.01  # steady-state dispatch time
        return 0

    for step in range(8):
        det.current_step = step
        det.dispatch(thunk)
    slow = events.events("detect.slow_step")
    assert len(slow) == 1 and slow[0].step == 5
    assert len(events.events("fault.slow_link")) == 1


def test_fault_plan_rejects_bad_faults():
    with pytest.raises(ValueError):
        FaultPlan().add_chip_loss(at_step=1, chips=[])
    from flexflow_tpu.elastic import Fault

    with pytest.raises(ValueError):
        Fault("meteor", at_step=0)


# -- topology shrink -----------------------------------------------------
def test_shrink_topology_spec_renumbers():
    spec = ring_topology_spec(8)
    out = shrink_topology_spec(spec, [6, 7])
    assert out["num_chips"] == 6
    chips = {i for link in out["links"] for i in link[:2]}
    assert chips <= set(range(6))  # densely renumbered
    # the ring lost the 5-6, 6-7, 7-0 arcs: 5 surviving links
    assert len(out["links"]) == 5

    # losing both neighbors of a chip can empty the link list — the
    # machine model falls back to its default ring (the from_json fix)
    tiny = shrink_topology_spec(ring_topology_spec(3), [1, 2])
    assert tiny == {"num_chips": 1, "links": []}
    from flexflow_tpu.search.machine_model import NetworkedMachineModel

    m = NetworkedMachineModel.from_json(tiny)
    assert m.num_chips == 1 and m.link_gbps == 45.0


# -- integration: retry in place ----------------------------------------
def test_retry_on_transient_resumes_in_place():
    events = EventLog()
    plan = FaultPlan().add_transient(at_step=1, times=2)
    x, y = make_data(batch=12)
    coord = ElasticCoordinator(
        builder, make_config(), fault_plan=plan, events=events,
        retry_policy=RetryPolicy(max_retries=3, base_delay_s=0.0),
        checkpoint_every=100)
    history = coord.fit(x, y, steps=3)
    assert [h["step"] for h in history] == [0, 1, 2]
    assert len(events.events("fault.transient")) == 2
    assert len(events.events("retry")) == 2
    assert events.events("recovery.start") == []  # no re-plan needed


def test_retries_exhausted_escalates():
    plan = FaultPlan().add_transient(at_step=1, times=10)
    x, y = make_data(batch=8)
    coord = ElasticCoordinator(
        builder, make_config(devices=1, batch=8), fault_plan=plan,
        retry_policy=RetryPolicy(max_retries=2, base_delay_s=0.0))
    with pytest.raises(RetriesExhausted):
        coord.fit(x, y, steps=3)


# -- integration: chip loss -> re-search -> restore -> resume ------------
def test_kill_chip_research_restore_resume(tmp_path):
    events = EventLog()
    plan = FaultPlan.kill_chips(at_step=3, chips=[3])
    x, y = make_data(batch=12)
    coord = ElasticCoordinator(
        builder, make_config(devices=4, batch=12), fault_plan=plan,
        events=events, checkpoint_dir=str(tmp_path), checkpoint_every=2,
        # this test pins the DISK restore path (checkpoint + replay);
        # the zero-disk live path has its own tests in test_resharding.py
        live_resharding=False)
    history = coord.fit(x, y, steps=8)

    # recovered exactly once onto the 3 survivors
    assert len(events.events("recovery.done")) == 1
    assert coord.device_ids == [0, 1, 2]
    assert coord.model.config.total_devices == 3
    if coord.model.mesh is not None:
        assert coord.model.mesh.devices.size == 3
    # the re-plan ran the strategy selection for the shrunken machine
    search_evs = events.events("recovery.search")
    assert search_evs and search_evs[0].details["n_devices"] == 3
    # restore came from the step-2 checkpoint (latest before the fault)
    restore_evs = events.events("recovery.restore")
    assert restore_evs and restore_evs[0].step == 2

    # every step committed exactly once, in order
    assert [h["step"] for h in history] == list(range(8))
    # loss keeps decreasing from the checkpoint through the recovery:
    # batches cycle with period 4, so compare like against like
    for phase in range(4):
        losses = [h["loss"] for h in history if h["step"] % 4 == phase]
        assert losses[-1] < losses[0], (phase, losses)


def test_recover_to_single_survivor(tmp_path):
    """2 -> 1 devices: the rebuilt model is mesh-less, and params must be
    committed to the SURVIVOR, not jax.devices()[0] (the lost chip)."""
    import jax

    events = EventLog()
    plan = FaultPlan.kill_chips(at_step=2, chips=[0])
    x, y = make_data(batch=8)
    coord = ElasticCoordinator(
        builder, make_config(devices=2, batch=8), fault_plan=plan,
        events=events, checkpoint_dir=str(tmp_path), checkpoint_every=2)
    history = coord.fit(x, y, steps=4)
    assert coord.device_ids == [1]
    assert coord.model.mesh is None
    survivor = jax.devices()[1]
    for entry in coord.model.params.values():
        for arr in entry.values():
            assert survivor in arr.devices(), arr.devices()
    # the whole restored training state follows, not just params
    for leaf in jax.tree.leaves(coord.model.opt_state):
        assert survivor in leaf.devices(), leaf.devices()
    assert [h["step"] for h in history] == [0, 1, 2, 3]
    assert len(events.events("recovery.done")) == 1


def test_unidentified_topology_loss_fails_fast(tmp_path):
    """Real topology-classified errors carry no chip ids; the coordinator
    must fail with a clear message instead of 'recovering' onto the same
    device set (which would re-hit the dead chip until the budget runs
    out)."""
    from flexflow_tpu.elastic import RecoveryFailed

    x, y = make_data(batch=8)
    coord = ElasticCoordinator(
        builder, make_config(devices=2, batch=8),
        checkpoint_dir=str(tmp_path))
    coord._save(0)
    with pytest.raises(RecoveryFailed, match="did not identify"):
        coord._recover(TopologyLoss([]))


# -- event log -----------------------------------------------------------
def test_event_log_roundtrip_and_counts():
    log = EventLog()
    log.record("fault.chip_loss", step=5, chips=[6, 7])
    log.record("recovery.done", step=4, n_devices=6)
    log.record("recovery.done", step=9, n_devices=4)
    assert log.counts() == {"fault.chip_loss": 1, "recovery.done": 2}
    clone = EventLog.from_json(log.to_json())
    assert [e.to_dict() for e in clone.events()] \
        == [e.to_dict() for e in log.events()]
    text = log.prometheus_text()
    assert 'ff_elastic_events_total{kind="recovery.done"} 2' in text
    assert "recovery.done=2" in log.summary()


def test_event_log_on_serving_metrics_endpoint():
    from flexflow_tpu.serving.server import InferenceServer

    log = EventLog()
    log.record("fault.transient", step=1)
    srv = InferenceServer()
    srv.attach_elastic_events(log)
    text = srv.prometheus_text()
    assert 'ff_elastic_events_total{kind="fault.transient"} 1' in text
    assert srv.stats()["_elastic"] == {"fault.transient": 1}


def test_print_event_log(capsys):
    from flexflow_tpu.runtime.profiling import print_event_log

    log = EventLog()
    print_event_log(log)
    assert "no events" in capsys.readouterr().out
    log.record("retry", step=2, attempt=1)
    print_event_log(log)
    out = capsys.readouterr().out
    assert "retry" in out and "attempt=1" in out
