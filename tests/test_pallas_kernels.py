"""Pallas fused-kernel tier (ISSUE 9): interpret-mode fwd+bwd parity of
every fused kernel against its jnp reference, the KernelRegistry's
selection semantics, calibration-driven candidacy, simulator pricing,
and token-identical greedy decode through the continuous batcher with
the fused decode kernel forced.

Tolerances: f32 kernels must match the reference to float-roundoff
(1e-5); bf16 I/O kernels accumulate in f32 and are compared at bf16
resolution (2e-2 on normalized outputs).
"""
import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.kernels.pallas import (fused_cumsum,
                                         fused_decode_attention,
                                         fused_layernorm, fused_reduce,
                                         fused_rmsnorm, fused_softmax)
from flexflow_tpu.kernels.registry import (KERNELS, PALLAS_COST_GAIN,
                                           KernelRegistry)

F32_TOL = dict(rtol=1e-5, atol=1e-5)
BF16_TOL = dict(rtol=2e-2, atol=2e-2)


def _rand(rng, shape, dtype=np.float32):
    return jnp.asarray(rng.randn(*shape).astype(np.float32)).astype(dtype)


@contextlib.contextmanager
def force_pallas(*families):
    with contextlib.ExitStack() as st:
        for fam in families:
            st.enter_context(KERNELS.override(fam, "pallas"))
        yield


# ---------------------------------------------------------------------
# norm kernels: fwd + bwd parity
# ---------------------------------------------------------------------
def _ref_layernorm(x, g=None, b=None, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if g is not None:
        y = y * g.astype(jnp.float32) + b.astype(jnp.float32)
    return y.astype(x.dtype)


def _ref_rmsnorm(x, g=None, eps=1e-6):
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    if g is not None:
        y = y * g.astype(jnp.float32)
    return y.astype(x.dtype)


@pytest.mark.parametrize("dtype,tol", [(np.float32, F32_TOL),
                                       (jnp.bfloat16, BF16_TOL)])
def test_layernorm_fwd_bwd_parity(dtype, tol):
    rng = np.random.RandomState(0)
    x = _rand(rng, (3, 9, 48), dtype)  # 9 rows: exercises row padding
    g = _rand(rng, (48,), dtype)
    b = _rand(rng, (48,), dtype)
    y = fused_layernorm(x, g, b, interpret=True, block_rows=4)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(_ref_layernorm(x, g, b),
                                          np.float32), **tol)

    def loss(fn):
        return lambda x, g, b: jnp.sum(jnp.sin(
            fn(x, g, b).astype(jnp.float32)))

    gf = jax.grad(loss(lambda x, g, b: fused_layernorm(
        x, g, b, interpret=True, block_rows=4)), argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(loss(_ref_layernorm), argnums=(0, 1, 2))(x, g, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(r, np.float32), **tol)


def test_layernorm_no_affine_parity():
    rng = np.random.RandomState(1)
    x = _rand(rng, (4, 5, 32))
    y = fused_layernorm(x, interpret=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(_ref_layernorm(x)),
                               **F32_TOL)
    gf = jax.grad(lambda x: jnp.sum(jnp.sin(
        fused_layernorm(x, interpret=True))))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(_ref_layernorm(x))))(x)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), **F32_TOL)


@pytest.mark.parametrize("dtype,tol", [(np.float32, F32_TOL),
                                       (jnp.bfloat16, BF16_TOL)])
def test_rmsnorm_fwd_bwd_parity(dtype, tol):
    rng = np.random.RandomState(2)
    x = _rand(rng, (2, 7, 64), dtype)
    g = _rand(rng, (64,), dtype)
    y = fused_rmsnorm(x, g, interpret=True, block_rows=4)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(_ref_rmsnorm(x, g), np.float32),
                               **tol)
    gf = jax.grad(lambda x, g: jnp.sum(jnp.sin(fused_rmsnorm(
        x, g, interpret=True, block_rows=4).astype(jnp.float32))),
        argnums=(0, 1))(x, g)
    gr = jax.grad(lambda x, g: jnp.sum(jnp.sin(
        _ref_rmsnorm(x, g).astype(jnp.float32))), argnums=(0, 1))(x, g)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(r, np.float32), **tol)


@pytest.mark.parametrize("dtype,tol", [(np.float32, F32_TOL),
                                       (jnp.bfloat16, BF16_TOL)])
def test_softmax_fwd_bwd_parity(dtype, tol):
    rng = np.random.RandomState(3)
    x = _rand(rng, (5, 11, 40), dtype)
    ref = jax.nn.softmax(x.astype(jnp.float32), -1).astype(x.dtype)
    y = fused_softmax(x, interpret=True, block_rows=4)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(ref, np.float32), **tol)
    gf = jax.grad(lambda x: jnp.sum(jnp.sin(fused_softmax(
        x, interpret=True, block_rows=4).astype(jnp.float32))))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(jax.nn.softmax(
        x.astype(jnp.float32), -1))))(x)
    np.testing.assert_allclose(np.asarray(gf, np.float32),
                               np.asarray(gr, np.float32), **tol)


# ---------------------------------------------------------------------
# reduction / scan
# ---------------------------------------------------------------------
def test_fused_reduce_parity_and_grads():
    rng = np.random.RandomState(4)
    x = _rand(rng, (7, 33))  # 231 elements: lane + row padding
    np.testing.assert_allclose(float(fused_reduce(x, "sum", interpret=True)),
                               float(jnp.sum(x)), rtol=1e-5)
    np.testing.assert_allclose(float(fused_reduce(x, "mean", interpret=True)),
                               float(jnp.mean(x)), rtol=1e-5)
    assert float(fused_reduce(x, "max", interpret=True)) == float(jnp.max(x))
    for kind, ref in (("sum", jnp.sum), ("mean", jnp.mean)):
        gf = jax.grad(lambda x: fused_reduce(x, kind, interpret=True))(x)  # noqa: B023
        gr = jax.grad(lambda x: ref(x))(x)  # noqa: B023
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), **F32_TOL)
    with pytest.raises(TypeError, match="forward-only"):
        jax.grad(lambda x: fused_reduce(x, "max", interpret=True))(x)


def test_fused_reduce_tiny_and_empty():
    assert float(fused_reduce(jnp.asarray([3.0]), "sum",
                              interpret=True)) == 3.0
    assert float(fused_reduce(jnp.zeros((0,)), "sum", interpret=True)) == 0.0


def test_fused_cumsum_parity():
    rng = np.random.RandomState(5)
    x = _rand(rng, (3, 5, 17))
    y = fused_cumsum(x, interpret=True, block_rows=4)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(jnp.cumsum(x, -1)), **F32_TOL)
    gf = jax.grad(lambda x: jnp.sum(jnp.sin(
        fused_cumsum(x, interpret=True, block_rows=4))))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(jnp.cumsum(x, -1))))(x)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), **F32_TOL)


# ---------------------------------------------------------------------
# fused decode step
# ---------------------------------------------------------------------
def _ref_decode(q, kc, vc, pos, scale):
    m = kc.shape[1]
    mask = (jnp.arange(m)[None, :] <= pos[:, None])[:, None, None, :]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kc.astype(q.dtype),
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype),
                      vc.astype(q.dtype))


@pytest.mark.parametrize("block_k", [64, 8])  # single- and multi-block
def test_fused_decode_ragged_positions(block_k):
    rng = np.random.RandomState(6)
    B, M, h, d = 5, 24, 3, 8
    q = _rand(rng, (B, 1, h, d))
    kc = _rand(rng, (B, M, h, d))
    vc = _rand(rng, (B, M, h, d))
    # ragged: includes pos 0 (one live row) and pos M-1 (the whole cache)
    pos = jnp.asarray([0, 3, 11, 23, 7], dtype=jnp.int32)
    scale = 1.0 / np.sqrt(d)
    out = fused_decode_attention(q, kc, vc, pos, scale=scale,
                                 block_k=block_k, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref_decode(q, kc, vc, pos, scale)),
        rtol=1e-5, atol=1e-6)


def test_fused_decode_bf16_cache():
    rng = np.random.RandomState(7)
    B, M, h, d = 2, 16, 2, 16
    q = _rand(rng, (B, 1, h, d))
    kc = _rand(rng, (B, M, h, d), jnp.bfloat16)
    vc = _rand(rng, (B, M, h, d), jnp.bfloat16)
    pos = jnp.asarray([5, 15], dtype=jnp.int32)
    scale = 1.0 / np.sqrt(d)
    out = fused_decode_attention(q, kc, vc, pos, scale=scale, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32),
        np.asarray(_ref_decode(q, kc, vc, pos, scale), np.float32),
        **BF16_TOL)


def test_fused_decode_rejects_multi_query():
    q = jnp.zeros((1, 2, 2, 4))
    kc = vc = jnp.zeros((1, 8, 2, 4))
    with pytest.raises(ValueError, match="one query token"):
        fused_decode_attention(q, kc, vc, jnp.zeros((1,), jnp.int32),
                               scale=1.0, interpret=True)


# ---------------------------------------------------------------------
# KernelRegistry semantics
# ---------------------------------------------------------------------
def test_registry_selection_order():
    # CPU backend: auto is always reference
    assert KERNELS.select("layernorm", record=False).reason == "backend"
    assert not KERNELS.select("layernorm", record=False)
    # param beats everything, both ways
    with KERNELS.override("attention", "reference"):
        assert KERNELS.select("attention", param=True, record=False)
        assert KERNELS.select("attention", param=True,
                              record=False).reason == "param"
    # override beats config; restores the previous override on exit
    with KERNELS.override("softmax", "pallas"):
        c = KERNELS.select("softmax", record=False)
        assert c and c.reason == "override"
        with KERNELS.override("softmax", "reference"):
            assert not KERNELS.select("softmax", record=False)
        assert KERNELS.select("softmax", record=False)
    assert KERNELS.select("softmax", record=False).reason == "backend"
    with pytest.raises(KeyError):
        KERNELS.select("not_a_family")


def test_registry_config_knob_and_parse_spec():
    assert KernelRegistry.parse_spec("auto") == {}
    assert KernelRegistry.parse_spec("pallas")["layernorm"] == "pallas"
    assert KernelRegistry.parse_spec(
        "attention=pallas,softmax=reference") == {
            "attention": "pallas", "softmax": "reference"}
    for bad in ("nope", "attention=fused", "zzz=pallas"):
        with pytest.raises(ValueError, match="kernel-impl"):
            KernelRegistry.parse_spec(bad)
    import flexflow_tpu as ff

    cfg = ff.FFConfig()
    cfg.parse_args(["--kernel-impl", "layernorm=pallas"])
    assert cfg.kernel_impl == "layernorm=pallas"
    reg = KernelRegistry()
    reg.configure(cfg)
    c = reg.select("layernorm", record=False)
    assert c and c.reason == "config"
    # reconfiguring back to auto clears it
    cfg.kernel_impl = "auto"
    reg.configure(cfg)
    assert not reg.select("layernorm", record=False)
    with pytest.raises(ValueError, match="kernel-impl"):
        ff.FFConfig().parse_args(["--kernel-impl", "bogus"])


def test_registry_residual_driven_selection(tmp_path):
    """A fitted profile whose residuals mark layernorm as underpriced
    makes auto select pallas on a TPU backend — the calibration-driven
    loop — while a calibrated family stays on reference."""
    import flexflow_tpu as ff
    from flexflow_tpu.obs.refit import FittedCoefficients, FittedProfile

    path = str(tmp_path / "prof.json")
    FittedProfile(
        chip="cpu-host", backend="cpu",
        coefficients=FittedCoefficients(),
        op_family_residuals={"layernorm": 1.8, "softmax": 1.01},
    ).save(path)
    cfg = ff.FFConfig()
    cfg.fitted_profile_file = path
    reg = KernelRegistry()
    reg.configure(cfg)
    assert reg.residual("layernorm") == 1.8
    c = reg.select("layernorm", backend="tpu", record=False)
    assert c and c.reason == "residual"
    # residual below threshold: falls through to the family default
    assert not reg.select("softmax", backend="tpu", record=False)
    # and on CPU the backend gate still wins
    assert not reg.select("layernorm", backend="cpu", record=False)


def test_registry_decode_inherits_attention_residual_and_defaults(tmp_path):
    """attention_decode never appears as a calibratable graph op: its
    auto selection on TPU rides the attention family's residual.
    reduction (same situation, but with no related family and no SPMD
    partitioning rule for its pallas_call) stays knob-opt-in: reference
    on every backend under auto."""
    import flexflow_tpu as ff
    from flexflow_tpu.obs.refit import FittedCoefficients, FittedProfile

    reg = KernelRegistry()
    assert not reg.select("attention_decode", backend="tpu", record=False)
    assert not reg.select("reduction", backend="tpu", record=False)
    assert not reg.select("reduction", backend="cpu", record=False)
    path = str(tmp_path / "prof.json")
    FittedProfile(chip="x", backend="cpu",
                  coefficients=FittedCoefficients(),
                  op_family_residuals={"attention": 2.0}).save(path)
    cfg = ff.FFConfig()
    cfg.fitted_profile_file = path
    reg.configure(cfg)
    d = reg.select("attention_decode", backend="tpu", record=False)
    assert d and d.reason == "residual"


def test_registry_residual_respects_size_heuristic():
    """Under attention residual evidence, the measured score-bytes
    crossover still gates per instance: a small-context op stays on the
    einsum path even when the profiled model's residual nominated the
    family."""
    from flexflow_tpu.kernels.registry import flash_crossover

    reg = KernelRegistry()
    reg._residuals = {"attention": 2.0}
    big = reg.select("attention", backend="tpu",
                     heuristic=lambda: True, record=False)
    assert big and big.reason == "residual"
    small = reg.select("attention", backend="tpu",
                       heuristic=lambda: False, record=False)
    assert not small and small.reason == "heuristic"
    # the shared helper itself: bert-bench scale crosses, tiny does not
    assert flash_crossover(64, 16, 512, 512, dp=1)
    assert not flash_crossover(2, 4, 64, 64, dp=1)


def test_registry_per_call_config_isolation(tmp_path):
    """Two models with different --kernel-impl knobs in one process:
    select(config=...) resolves each model's own knob regardless of
    which one configure()d the process default last (the retrace-after-
    another-compile hazard)."""
    import flexflow_tpu as ff

    cfg_a = ff.FFConfig()
    cfg_a.kernel_impl = "layernorm=pallas"
    cfg_b = ff.FFConfig()  # auto
    reg = KernelRegistry()
    reg.configure(cfg_b)  # B compiled LAST — the process default
    a = reg.select("layernorm", config=cfg_a, record=False)
    assert a and a.reason == "config"
    assert not reg.select("layernorm", config=cfg_b, record=False)
    # and a config-carrying call ignores the global default entirely
    reg.configure(cfg_a)
    assert not reg.select("layernorm", config=cfg_b, record=False)


def test_cost_model_gates_match_lowering():
    """The simulator never discounts an op the lowering would not fuse:
    non-trailing-axis norms and non-last-axis softmax price at 1.0 even
    with pallas forced."""
    import flexflow_tpu as ff
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.simulator import CostModel, OpStrategy

    cfg = ff.FFConfig()
    cfg.num_devices = 1
    m = ff.FFModel(cfg)
    inp = m.create_tensor([4, 16, 32])
    m.layer_norm(inp, [1], name="ln_axis1")       # NOT trailing
    m.softmax(inp, axis=0, name="sm_axis0")       # NOT last
    ops = {op.name: op for op in m.ops}
    cost = CostModel(make_machine_model(cfg, 1), cfg)
    s = OpStrategy()
    with force_pallas("layernorm", "softmax"):
        assert cost.kernel_time_factor(ops["ln_axis1"], s) == 1.0
        assert cost.kernel_time_factor(ops["sm_axis0"], s) == 1.0


def test_registry_profile_roundtrip_residuals(tmp_path):
    from flexflow_tpu.obs.refit import FittedCoefficients, FittedProfile

    path = str(tmp_path / "p.json")
    FittedProfile(chip="x", backend="cpu",
                  coefficients=FittedCoefficients(),
                  op_family_residuals={"attention": 2.5}).save(path)
    loaded = FittedProfile.load(path, expect_chip="x",
                                expect_backend="cpu")
    assert loaded.op_family_residuals == {"attention": 2.5}


def test_registry_selection_counter():
    from flexflow_tpu.obs import REGISTRY

    fam = REGISTRY.counter("ff_kernel_selected_total",
                           "Kernel-tier selections by op family and "
                           "implementation", labels=("op", "impl"))
    before = fam.value(op="rmsnorm", impl="pallas")
    with KERNELS.override("rmsnorm", "pallas"):
        KERNELS.select("rmsnorm")
        KERNELS.select("rmsnorm", record=False)  # peeks never count
    assert fam.value(op="rmsnorm", impl="pallas") == before + 1


# ---------------------------------------------------------------------
# simulator pricing: the search sees the kernel tier
# ---------------------------------------------------------------------
def test_cost_model_prices_pallas_selection():
    import flexflow_tpu as ff
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.simulator import CostModel, OpStrategy

    cfg = ff.FFConfig()
    cfg.num_devices = 1
    m = ff.FFModel(cfg)
    inp = m.create_tensor([4, 16, 32])
    m.layer_norm(inp, [-1], name="ln")
    ln_op = [op for op in m.ops if op.op_type.value == "layernorm"][0]
    s = OpStrategy(dp=1, tp=1)
    # fresh CostModel per selection regime: the factor memo assumes the
    # policy is stable for one model's lifetime
    t_ref = CostModel(make_machine_model(cfg, 1), cfg).forward_time_us(
        ln_op, s)
    with KERNELS.override("layernorm", "pallas"):
        t_pallas = CostModel(make_machine_model(cfg, 1),
                             cfg).forward_time_us(ln_op, s)
    assert t_pallas == pytest.approx(
        t_ref * PALLAS_COST_GAIN["layernorm"], rel=1e-6)
    assert t_pallas < t_ref


# ---------------------------------------------------------------------
# op lowerings: forced-pallas model matches the reference model
# ---------------------------------------------------------------------
def _tiny_model(seed=0):
    import flexflow_tpu as ff

    cfg = ff.FFConfig()
    cfg.batch_size = 4
    cfg.seed = seed
    m = ff.FFModel(cfg)
    inp = m.create_tensor([4, 6, 32])
    t = m.layer_norm(inp, [-1], name="ln")
    t = m.rms_norm(t, [-1], name="rms")
    t = m.dense(t, 10, name="cls")
    m.softmax(t)
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[ff.MetricsType.METRICS_ACCURACY])
    return m


def test_training_parity_reference_vs_forced_pallas():
    """Same data, same seed: a full fit() through the fused layernorm/
    rmsnorm/softmax/reduction kernels lands on the reference run's loss
    to float tolerance — fwd AND bwd exercised end-to-end."""
    rng = np.random.RandomState(8)
    x = rng.randn(8, 6, 32).astype(np.float32)
    y = rng.randint(0, 10, size=(8, 6, 1)).astype(np.int32)
    h_ref = _tiny_model().fit([x], y, batch_size=4, epochs=2)
    with force_pallas("layernorm", "rmsnorm", "softmax", "reduction"):
        h_fused = _tiny_model().fit([x], y, batch_size=4, epochs=2)
    assert h_fused[-1]["loss"] == pytest.approx(h_ref[-1]["loss"],
                                               rel=1e-4, abs=1e-5)
    assert h_fused[-1]["accuracy"] == h_ref[-1]["accuracy"]


def test_rms_norm_op_reference_lowering_correct():
    """The RMSNorm op's reference lowering (and its multi-axis fallback
    route) against a direct jnp computation."""
    import flexflow_tpu as ff

    cfg = ff.FFConfig()
    cfg.batch_size = 2
    cfg.allow_mixed_precision = False  # f32 oracle comparison
    m = ff.FFModel(cfg)
    inp = m.create_tensor([2, 3, 16])
    m.rms_norm(inp, [-1], name="rms")
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.0),
              loss_type=ff.LossType.LOSS_IDENTITY)
    x = np.random.RandomState(9).randn(2, 3, 16).astype(np.float32)
    out = m.predict(x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref_rmsnorm(jnp.asarray(x))),
        **F32_TOL)


# ---------------------------------------------------------------------
# continuous batcher: fused decode, token-identical, slot reuse
# ---------------------------------------------------------------------
def test_continuous_batcher_fused_decode_token_parity():
    """Greedy decode through the continuous batcher with the fused
    vector-decode kernel FORCED (registry override; interpret mode on
    CPU) is token-identical to the lockstep reference — ragged prompt
    lengths AND slot reuse (3 requests through 2 slots)."""
    from flexflow_tpu.serving.generate import GenerativeSession
    from flexflow_tpu.serving.sched import ContinuousBatcher
    from tests.test_generate import _build_lm

    lm = _build_lm(2, 12)
    rng = np.random.RandomState(10)
    prompts = [rng.randint(1, 50, size=(n,)).astype(np.int32)
               for n in (4, 7, 3)]
    session = GenerativeSession(lm, max_len=12)
    refs = [session.generate(p[None, :], 5)[0] for p in prompts]
    with force_pallas("attention_decode"):
        with ContinuousBatcher(lm, max_len=12, num_slots=2, page_size=4,
                               max_queue=8) as cb:
            outs = [r.result(timeout=300)
                    for r in [cb.submit(p, 5) for p in prompts]]
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, np.asarray(ref))


def test_calibration_kernel_candidates_ranking():
    """Synthetic calibration rows: the candidates section ranks by
    residual weighted by predicted-step share, and op_family_residuals
    takes the per-family MEDIAN."""
    from flexflow_tpu.obs.calibration import (CalibrationReport,
                                              OpCalibration,
                                              op_family_residuals)

    rows = [
        # layernorm: big residual (x3) but small share
        OpCalibration("ln1", "layernorm", "dp=1", 10.0, 30.0),
        OpCalibration("ln2", "layernorm", "dp=1", 10.0, 50.0),
        OpCalibration("ln3", "layernorm", "dp=1", 10.0, 30.0),
        # attention: modest residual (x1.5) on most of the step
        OpCalibration("attn", "multihead_attention", "dp=1", 400.0, 600.0),
        # linear: not a kernel-tier family — never a candidate
        OpCalibration("fc", "linear", "dp=1", 100.0, 500.0),
        # failed measurement: excluded from residuals
        OpCalibration("sm", "softmax", "dp=1", 5.0, float("nan"),
                      error="x"),
    ]
    fams = op_family_residuals(rows)
    assert fams["layernorm"] == 3.0  # median of [3, 5, 3]
    assert fams["attention"] == 1.5
    assert "softmax" not in fams and "linear" not in fams

    rep = CalibrationReport(backend="cpu", predicted_step_us=1000.0,
                            measured_step_us=1500.0, measured_steps=3,
                            ops=rows)
    cands = rep.kernel_candidates()
    by_fam = {c["family"]: c for c in cands}
    assert set(by_fam) == {"layernorm", "attention", "softmax"}
    # attention: 0.5 residual excess * (400/535) share beats layernorm's
    # 2.0 excess * (30/535)
    assert cands[0]["family"] == "attention"
    assert by_fam["softmax"]["score"] == 0.0  # unmeasurable -> no score
    assert by_fam["layernorm"]["score"] == pytest.approx(
        2.0 * 30.0 / 535.0)
    # the report renders and serializes with the section included
    assert "kernel candidates" in rep.format_kernel_report()
    assert rep.to_dict()["kernel_candidates"][0]["family"] == "attention"


def test_refit_persists_family_residuals(tmp_path):
    """A real refit run records the per-family residuals into the saved
    profile, and a fresh registry configured with that profile sees
    them."""
    import flexflow_tpu as ff
    from flexflow_tpu.obs import calibrate
    from flexflow_tpu.obs.refit import FittedProfile, refit

    m = _tiny_model()
    x = np.random.RandomState(11).randn(8, 6, 32).astype(np.float32)
    y = np.random.RandomState(11).randint(
        0, 10, size=(8, 6, 1)).astype(np.int32)
    m.fit([x], y, batch_size=4, epochs=2)
    rep = calibrate(m)
    measured = rep.measured_step_us or 5000.0
    profile, _ = refit(m, measured, rep.ops, rounds=1, tol=0.15)
    # the tiny model has layernorm+rmsnorm+softmax rows; at least one
    # family must have produced evidence
    assert profile.op_family_residuals
    path = str(tmp_path / "fitted.json")
    profile.save(path)
    assert (FittedProfile.load(path).op_family_residuals
            == profile.op_family_residuals)
    cfg = ff.FFConfig()
    cfg.fitted_profile_file = path
    reg = KernelRegistry()
    reg.configure(cfg)
    assert reg.residual_source == path
    for fam, r in profile.op_family_residuals.items():
        assert reg.residual(fam) == r

# ---------------------------------------------------------------------
# multi-query decode kernel (ISSUE 14)
# ---------------------------------------------------------------------
def _ref_mq_decode(q, kc, vc, pos, scale):
    b, c = q.shape[0], q.shape[1]
    m = kc.shape[1]
    qpos = pos[:, None] + jnp.arange(c)[None, :]
    mask = (jnp.arange(m)[None, None, :]
            <= qpos[:, :, None])[:, None, :, :]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kc.astype(q.dtype),
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype),
                      vc.astype(q.dtype))


@pytest.mark.parametrize("block_k", [64, 8])  # single- and multi-block
def test_fused_multiquery_decode_parity(block_k):
    from flexflow_tpu.kernels.pallas import (
        fused_multiquery_decode_attention)

    rng = np.random.RandomState(12)
    B, C, M, h, d = 5, 3, 24, 3, 8
    q = _rand(rng, (B, C, h, d))
    kc = _rand(rng, (B, M, h, d))
    vc = _rand(rng, (B, M, h, d))
    # ragged: pos 0 (the query window IS the live prefix) through M-C
    # (the window ends at the last cache row)
    pos = jnp.asarray([0, 3, 11, 21, 7], dtype=jnp.int32)
    scale = 1.0 / np.sqrt(d)
    out = fused_multiquery_decode_attention(
        q, kc, vc, pos, scale=scale, block_k=block_k, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref_mq_decode(q, kc, vc, pos, scale)),
        rtol=1e-5, atol=1e-6)


def test_fused_multiquery_decode_bf16_cache():
    from flexflow_tpu.kernels.pallas import (
        fused_multiquery_decode_attention)

    rng = np.random.RandomState(13)
    B, C, M, h, d = 2, 4, 16, 2, 16
    q = _rand(rng, (B, C, h, d))
    kc = _rand(rng, (B, M, h, d), jnp.bfloat16)
    vc = _rand(rng, (B, M, h, d), jnp.bfloat16)
    pos = jnp.asarray([5, 12], dtype=jnp.int32)
    scale = 1.0 / np.sqrt(d)
    for block_k in (64, 8):
        out = fused_multiquery_decode_attention(
            q, kc, vc, pos, scale=scale, block_k=block_k, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32),
            np.asarray(_ref_mq_decode(q, kc, vc, pos, scale), np.float32),
            **BF16_TOL)


def test_fused_multiquery_c1_matches_single_query():
    """C = 1 through the multi-query entry is the single-query kernel's
    exact math (shared body), in both block regimes."""
    from flexflow_tpu.kernels.pallas import (
        fused_multiquery_decode_attention)

    rng = np.random.RandomState(14)
    B, M, h, d = 3, 24, 2, 8
    q = _rand(rng, (B, 1, h, d))
    kc = _rand(rng, (B, M, h, d))
    vc = _rand(rng, (B, M, h, d))
    pos = jnp.asarray([0, 9, 23], dtype=jnp.int32)
    for block_k in (64, 8):
        a = fused_multiquery_decode_attention(
            q, kc, vc, pos, scale=0.3, block_k=block_k, interpret=True)
        b = fused_decode_attention(
            q, kc, vc, pos, scale=0.3, block_k=block_k, interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_continuous_batcher_fused_decode_multiblock_token_parity():
    """Satellite 3 (lifts the PR 9 docs caveat): greedy decode through
    the continuous batcher with BOTH fused decode kernels forced and
    flash_block_k SMALLER than the cache span — every decode streams
    multiple KV blocks through the online softmax — stays
    token-identical to the pure-reference run. Ragged prompts, slot
    reuse (4 requests through 2 slots), chunked prefill through the
    multi-query kernel."""
    from flexflow_tpu.serving.sched import ContinuousBatcher
    from tests.test_generate import _build_lm

    rng = np.random.RandomState(15)
    prompts = [rng.randint(1, 50, size=(n,)).astype(np.int32)
               for n in (4, 9, 3, 7)]

    def run(forced):
        lm = _build_lm(2, 12)
        lm.config.flash_block_k = 8  # cache span 24 -> 3 KV blocks
        import contextlib
        with contextlib.ExitStack() as st:
            for fam in forced:
                st.enter_context(KERNELS.override(fam, "pallas"))
            with ContinuousBatcher(lm, max_len=24, num_slots=2,
                                   page_size=4, max_queue=8) as cb:
                return [r.result(timeout=300).tolist()
                        for r in [cb.submit(p, 10) for p in prompts]]

    ref = run(())
    fused = run(("attention_decode", "attention_decode_mq"))
    assert fused == ref


def test_chunk_offset_prefill_lowers_through_mq_kernel():
    """The chunk-offset (scalar-pos) prefill entry lowers through the
    multi-query kernel when selected: a chunked prefill with the kernel
    forced produces the same first token and downstream stream as the
    reference chunk path."""
    from flexflow_tpu.serving.sched import ContinuousBatcher
    from tests.test_generate import _build_lm

    lm = _build_lm(2, 12)
    prompt = np.random.RandomState(16).randint(
        1, 50, size=(9,)).astype(np.int32)

    def run(force):
        import contextlib
        with contextlib.ExitStack() as st:
            if force:
                st.enter_context(KERNELS.override("attention_decode_mq",
                                                  "pallas"))
            with ContinuousBatcher(lm, max_len=16, num_slots=2,
                                   page_size=4, prefill_chunk_tokens=4,
                                   max_queue=4) as cb:
                return cb.submit(prompt, 5).result(timeout=300).tolist()

    assert run(True) == run(False)


# ---------------------------------------------------------------------
# registry: mq family, fitted thresholds, decode pricing
# ---------------------------------------------------------------------
def test_registry_mq_family_aliases_attention_residual(tmp_path):
    import json

    from flexflow_tpu.obs.refit import FittedCoefficients, FittedProfile

    prof = FittedProfile(chip="c", backend="cpu",
                         coefficients=FittedCoefficients(),
                         op_family_residuals={"attention": 1.5})
    path = str(tmp_path / "p.json")
    prof.save(path)
    assert "attention" in json.load(open(path))["op_family_residuals"]
    reg = KernelRegistry()

    class Cfg:
        kernel_impl = "auto"
        fitted_profile_file = path
        kernel_residual_threshold = 1.10

    d = reg.select("attention_decode_mq", backend="tpu", config=Cfg(),
                   record=False)
    assert d and d.reason == "residual"
    # no evidence -> reference
    assert not reg.select("attention_decode_mq", backend="tpu",
                          record=False)


def test_registry_fitted_threshold_overrides_knob(tmp_path):
    """A profile carrying kernel_residual_thresholds wins over the
    hand-set --kernel-residual-threshold default: evidence below the
    knob but above the FITTED threshold selects pallas, and a fitted
    threshold ABOVE the knob demands the stronger evidence."""
    from flexflow_tpu.obs.refit import FittedCoefficients, FittedProfile

    def mk(residual, fitted):
        prof = FittedProfile(
            chip="c", backend="cpu", coefficients=FittedCoefficients(),
            op_family_residuals={"attention": residual},
            kernel_residual_thresholds=(
                {"attention": fitted} if fitted else {}))
        path = str(tmp_path / f"p_{residual}_{fitted}.json")
        prof.save(path)

        class Cfg:
            kernel_impl = "auto"
            fitted_profile_file = path
            kernel_residual_threshold = 1.10

        return Cfg()

    reg = KernelRegistry()
    # residual 1.05 < knob 1.10: reference without a fitted threshold...
    assert not reg.select("attention_decode", backend="tpu",
                          config=mk(1.05, None), record=False)
    # ...but pallas when the PALLAS impl measured at 1.02
    assert reg.select("attention_decode", backend="tpu",
                      config=mk(1.05, 1.03), record=False)
    # a fitted threshold above the knob demands more evidence
    assert not reg.select("attention_decode", backend="tpu",
                          config=mk(1.15, 1.30), record=False)
    assert reg.select("attention_decode", backend="tpu",
                      config=mk(1.35, 1.30), record=False)


def test_fit_kernel_thresholds_from_pallas_rows():
    """The fitted threshold is the fused impl's own median residual x
    margin, floored at 1.0 — derived from before/after measurement rows,
    replacing the hand-set 1.10 constant."""
    from flexflow_tpu.obs.calibration import OpCalibration
    from flexflow_tpu.obs.refit import fit_kernel_thresholds

    rows = [
        OpCalibration("a1", "multihead_attention", "dp=1", 10.0, 10.4),
        OpCalibration("a2", "multihead_attention", "dp=1", 10.0, 10.6),
        OpCalibration("a3", "multihead_attention", "dp=1", 10.0, 10.4),
        # a fused impl BEATING the roofline still floors at 1.0
        OpCalibration("ln", "layernorm", "dp=1", 10.0, 7.0),
        # degenerate rows are excluded
        OpCalibration("sm", "softmax", "dp=1", 5.0, float("nan"),
                      error="x"),
    ]
    th = fit_kernel_thresholds(rows, margin=1.02)
    assert th["attention"] == pytest.approx(1.04 * 1.02)
    assert th["layernorm"] == pytest.approx(1.02)
    assert "softmax" not in th


def test_fitted_thresholds_profile_roundtrip(tmp_path):
    from flexflow_tpu.obs.refit import (FittedCoefficients, FittedProfile)

    prof = FittedProfile(
        chip="c", backend="cpu", coefficients=FittedCoefficients(),
        kernel_residual_thresholds={"attention": 1.07, "layernorm": 1.0})
    path = str(tmp_path / "p.json")
    prof.save(path)
    assert (FittedProfile.load(path, expect_backend="cpu")
            .kernel_residual_thresholds
            == {"attention": 1.07, "layernorm": 1.0})


def test_cost_model_prices_decode_dispatches():
    """decode_step_time_us prices the serving hot dispatches through the
    kernel tier: fused/reference ratio is exactly the family's
    PALLAS_COST_GAIN, the multi-query dispatch costs more than the
    single-query one, and C rides through the mq family."""
    from flexflow_tpu.ffconst import OpType
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.simulator import CostModel
    from tests.test_generate import _build_lm

    lm = _build_lm(2, 12)
    attn = next(op for op in lm.graph.ops.values()
                if op.op_type == OpType.MULTIHEAD_ATTENTION)
    machine = make_machine_model(lm.config, 1)
    cost = CostModel(machine, lm.config)
    ref1 = cost.decode_step_time_us(attn, 4, 64, 1)
    ref4 = cost.decode_step_time_us(attn, 4, 64, 4)
    # the mq dispatch streams the SAME cache once for all C queries —
    # at decode sizes the roofline is bytes-bound, so C is (near) free:
    # that amortization is the whole speculative-decoding win
    assert ref4 >= ref1 > 0
    with force_pallas("attention_decode", "attention_decode_mq"):
        cost2 = CostModel(machine, lm.config)
        assert cost2.decode_step_time_us(attn, 4, 64, 1) / ref1 == \
            pytest.approx(PALLAS_COST_GAIN["attention_decode"])
        assert cost2.decode_step_time_us(attn, 4, 64, 4) / ref4 == \
            pytest.approx(PALLAS_COST_GAIN["attention_decode_mq"])
