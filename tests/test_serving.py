"""Serving subsystem tests (reference analog: triton/src/test gtests +
triton/qa/L0_e2e — the only mocked-infra tests in the reference; here the
real executor runs on the CPU mesh)."""
import json
import urllib.request

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.serving import DynamicBatcher, InferenceModel, InferenceServer


def make_model(dim=8, classes=4):
    return make_sharded_model(None, dim=dim, classes=classes)


def make_sharded_model(axes, dim=8, classes=4):
    """The serving test model; axes=None compiles single-device, a dict
    compiles over that mesh (reference role: multi-node Triton serving,
    triton/src/strategy.cc)."""
    config = ff.FFConfig()
    config.batch_size = 16
    config.allow_mixed_precision = False
    config.seed = 9
    config.num_devices = int(np.prod(list(axes.values()))) if axes else 1
    model = ff.FFModel(config)
    inp = model.create_tensor([16, dim])
    t = model.dense(inp, 16, ff.ActiMode.AC_MODE_RELU)
    t = model.dense(t, classes)
    model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.0),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
        parallel_axes=axes,
    )
    return model


@pytest.mark.parametrize("axes", [{"data": 2}, {"model": 2},
                                  {"data": 2, "model": 2}])
def test_sharded_batched_inference_matches_single_device(axes):
    """Batched serving over a dp/tp/dp x tp mesh: bucket padding, partial
    batches, and the batcher all produce the single-device numbers."""
    ref = InferenceModel(make_sharded_model(None), batch_buckets=(2, 8))
    im = InferenceModel(make_sharded_model(axes), batch_buckets=(2, 8))
    name = im.input_names[0]
    x = np.random.RandomState(3).randn(5, 8).astype(np.float32)
    out = im.predict({name: x})
    np.testing.assert_allclose(out, ref.predict({name: x}),
                               rtol=1e-5, atol=1e-6)
    with DynamicBatcher(im, max_batch_size=8, max_delay_ms=5.0) as b:
        futs = [b.submit({name: x[i:i + 1]}) for i in range(5)]
        outs = np.concatenate([f.result(timeout=30) for f in futs])
    np.testing.assert_allclose(outs, out, rtol=1e-5, atol=1e-6)


def test_inference_model_pads_to_buckets():
    model = make_model()
    im = InferenceModel(model, batch_buckets=(2, 8))
    name = im.input_names[0]
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    out = im.predict({name: x})
    assert out.shape == (5, 4)
    # padding must not change the un-padded rows: compare against bucket=8 direct
    out8 = im.predict({name: np.concatenate([x, np.zeros((3, 8), np.float32)])})
    np.testing.assert_allclose(out, out8[:5], rtol=1e-5, atol=1e-6)
    # batches over the largest bucket are chunked
    x16 = np.random.RandomState(1).randn(16, 8).astype(np.float32)
    assert im.predict({name: x16}).shape == (16, 4)


def test_dynamic_batcher_matches_direct_and_coalesces():
    model = make_model()
    im = InferenceModel(model, batch_buckets=(1, 4, 16))
    name = im.input_names[0]
    rng = np.random.RandomState(0)
    reqs = [rng.randn(1, 8).astype(np.float32) for _ in range(12)]
    with DynamicBatcher(im, max_batch_size=16, max_delay_ms=20.0) as b:
        futs = [b.submit({name: r}) for r in reqs]
        outs = [f.result(timeout=30) for f in futs]
    direct = im.predict({name: np.concatenate(reqs)})
    got = np.concatenate(outs)
    np.testing.assert_allclose(got, direct, rtol=1e-5, atol=1e-6)


def test_server_generate_endpoint():
    """POST /v2/models/<name>/generate: the server-side incremental
    decoding role of the reference's Triton prototype — tokens match a
    direct GenerativeSession run, stats are recorded."""
    from tests.test_generate import _build_lm
    from flexflow_tpu.serving.generate import GenerativeSession

    b, window, n_new = 2, 12, 5
    model = _build_lm(b, window)
    prompt = np.random.RandomState(1).randint(1, 50, size=(b, 4)).astype(np.int32)
    ref = GenerativeSession(model, max_len=window).generate(prompt, n_new)

    server = InferenceServer()
    # chunk size is server policy (client-chosen sizes would be a
    # compile-DoS surface); 3 exercises ragged chunking against n_new=5
    server.register_generative("lm", GenerativeSession(model, max_len=window),
                               tokens_per_dispatch=3)
    httpd = server.serve_http(port=0)
    try:
        port = httpd.server_address[1]
        req = json.dumps({"prompt": prompt.tolist(),
                          "max_new_tokens": n_new}).encode()
        with urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/v2/models/lm/generate", data=req,
                headers={"Content-Type": "application/json"}),
        ) as r:
            toks = np.asarray(json.load(r)["tokens"], np.int32)
        np.testing.assert_array_equal(toks, ref)
        assert server.stats("lm")["requests"] == 1
        # single-prompt request against the batch-2 session: rows decode
        # independently, so the padded run's first row is exact
        req1 = json.dumps({"prompt": prompt[:1].tolist(),
                           "max_new_tokens": n_new}).encode()
        with urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/v2/models/lm/generate", data=req1,
                headers={"Content-Type": "application/json"}),
        ) as r:
            toks1 = np.asarray(json.load(r)["tokens"], np.int32)
        np.testing.assert_array_equal(toks1, ref[:1])
        # unknown session -> 404; malformed body -> 400
        with pytest.raises(urllib.error.HTTPError) as e404:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/v2/models/nope/generate",
                    data=b"{}"))
        assert e404.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e400:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/v2/models/lm/generate",
                    data=b"{}"))
        assert e400.value.code == 400
        # flat token list (not (n, L)) and oversize batches -> 400 too
        for bad in ([1, 2, 3], [[1, 2]] * 5):
            with pytest.raises(urllib.error.HTTPError) as ebad:
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{port}/v2/models/lm/generate",
                        data=json.dumps({"prompt": bad}).encode()))
            assert ebad.value.code == 400, bad
    finally:
        httpd.shutdown()
        server.shutdown()


def test_register_generative_validates_policy():
    """Bad decode policy fails at REGISTRATION (a per-request failure would
    be misreported as a client error)."""
    from tests.test_generate import _build_lm
    from flexflow_tpu.serving.generate import GenerativeSession

    model = _build_lm(2, 12)
    server = InferenceServer()
    session = GenerativeSession(model, max_len=12)
    with pytest.raises(ValueError, match="top_k"):
        server.register_generative("lm", session, top_k=0)
    with pytest.raises(ValueError, match="temperature"):
        server.register_generative("lm", session, temperature=-1.0)


def test_batcher_propagates_errors():
    model = make_model()
    im = InferenceModel(model, batch_buckets=(4,))
    with DynamicBatcher(im, max_delay_ms=1.0) as b:
        fut = b.submit({"not_an_input": np.zeros((1, 8), np.float32)})
        with pytest.raises(KeyError):
            fut.result(timeout=30)


def test_server_http_roundtrip():
    model = make_model()
    server = InferenceServer()
    server.register("mlp", model, batch_buckets=(1, 4))
    name = InferenceModel(model).input_names[0]
    httpd = server.serve_http(port=0)  # ephemeral port
    try:
        port = httpd.server_address[1]
        # model listing
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/v2/models") as r:
            assert json.load(r)["models"] == ["mlp"]
        # inference
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        req = json.dumps({"inputs": {name: x.tolist()}}).encode()
        with urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/v2/models/mlp/infer", data=req,
                headers={"Content-Type": "application/json"}),
        ) as r:
            out = np.asarray(json.load(r)["outputs"], np.float32)
        direct = InferenceModel(model).predict({name: x})
        np.testing.assert_allclose(out, direct, rtol=1e-4, atol=1e-5)
        # unknown model -> 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/v2/models/nope/infer",
                    data=b"{}"),
            )
        # the route segment must literally be "models": /v2/<junk>/... is a
        # 404, not an alias (advisor r4: the path matcher skipped parts[1])
        for path in ("/v2/anything/mlp/infer", "/v2/anything/mlp/generate"):
            with pytest.raises(urllib.error.HTTPError) as estrict:
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{port}{path}", data=req))
            assert estrict.value.code == 404, path
    finally:
        httpd.shutdown()
        server.shutdown()


def test_server_metrics_and_stats():
    server = InferenceServer()
    server.register("m", make_model(), max_batch_size=8, max_delay_ms=0.5)
    try:
        x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        inp = {server._models["m"].model.input_names[0]: x}
        server.infer("m", inp, timeout=30.0)
        server.infer("m", inp, timeout=30.0)
        s = server.stats("m")
        assert s["requests"] == 2 and s["failures"] == 0
        assert s["avg_latency_ms"] > 0
        text = server.prometheus_text()
        assert 'ff_inference_requests_total{model="m"} 2' in text
    finally:
        server.shutdown()


def test_model_repository_loads_and_serves(tmp_path):
    """Triton's primary UX: a directory per model (config + artifact) that
    the server scans and loads (reference: triton/src/model.cc per-dir
    loading)."""
    from flexflow_tpu.onnx import wire
    from flexflow_tpu.serving import ModelRepository

    rng = np.random.RandomState(0)
    w1 = rng.randn(6, 12).astype(np.float32)
    w2 = rng.randn(12, 3).astype(np.float32)
    nodes = [
        wire.make_node("MatMul", ["x", "w1"], ["h"], name="fc1"),
        wire.make_node("Relu", ["h"], ["hr"], name="relu1"),
        wire.make_node("MatMul", ["hr", "w2"], ["y"], name="fc2"),
    ]
    proto = wire.make_model(nodes, {"x": (8, 6)}, {"y": (8, 3)},
                            {"w1": w1, "w2": w2}, name="mlp")

    mdir = tmp_path / "mlp"
    mdir.mkdir()
    wire.save(proto, str(mdir / "model.onnx"))
    (mdir / "config.json").write_text(json.dumps({
        "format": "onnx",
        "file": "model.onnx",
        "inputs": [{"dims": [8, 6], "dtype": "float32"}],
        "max_batch_size": 8,
        "batch_buckets": [1, 4, 8],
        "mixed_precision": False,  # exact f32 so the allclose stays strict
    }))

    repo = ModelRepository(str(tmp_path))
    assert repo.model_names() == ["mlp"]
    server = InferenceServer()
    try:
        assert repo.load(server) == ["mlp"]
        x = rng.randn(2, 6).astype(np.float32)
        out = np.asarray(server.infer(
            "mlp", {server._models["mlp"].model.input_names[0]: x},
            timeout=30.0))
        ref = np.maximum(x @ w1, 0.0) @ w2
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
        repo.unload(server, "mlp")
        assert server.models() == []
    finally:
        server.shutdown()


def test_model_repository_cspec_format(tmp_path):
    """ff_cspec repository entry: a model spec exported by the C API
    (ffc_model_export_json) served by name."""
    from flexflow_tpu.serving import ModelRepository

    spec = {
        "format": "flexflow_tpu_c_model",
        "config": {"batch_size": 8},
        "ops": [
            {"type": "input", "name": "x", "dims": [8, 6],
             "dtype": "float32", "inputs": [], "outputs": [1]},
            {"type": "dense", "name": "fc1", "inputs": [1], "outputs": [2],
             "params": {"out_dim": 12, "activation": "relu"}},
            {"type": "dense", "name": "fc2", "inputs": [2], "outputs": [3],
             "params": {"out_dim": 3}},
            {"type": "softmax", "name": "sm", "inputs": [3], "outputs": [4],
             "params": {}},
        ],
    }
    mdir = tmp_path / "cmodel"
    mdir.mkdir()
    (mdir / "model_spec.json").write_text(json.dumps(spec))
    (mdir / "config.json").write_text(json.dumps({
        "format": "ff_cspec", "file": "model_spec.json",
        "max_batch_size": 8, "batch_buckets": [1, 8],
    }))

    repo = ModelRepository(str(tmp_path))
    server = InferenceServer()
    try:
        assert repo.load(server) == ["cmodel"]
        x = np.random.RandomState(0).randn(2, 6).astype(np.float32)
        out = np.asarray(server.infer(
            "cmodel", {server._models["cmodel"].model.input_names[0]: x},
            timeout=30.0))
        assert out.shape == (2, 3)
        # repository models serve with mixed precision on (bf16 rounding)
        np.testing.assert_allclose(out.sum(axis=-1), 1.0, atol=1e-2)
        assert server.stats("cmodel")["requests"] == 1
    finally:
        server.shutdown()


def test_model_repository_checkpoint_restore(tmp_path):
    """The repository's `checkpoint` field: a trained model's weights are
    restored into the repo-built model, and serving returns the TRAINED
    predictions (the full train -> checkpoint -> serve user flow)."""
    from flexflow_tpu.runtime.checkpoint import save_checkpoint
    from flexflow_tpu.serving import ModelRepository

    spec = {
        "format": "flexflow_tpu_c_model",
        "config": {"batch_size": 8},
        "ops": [
            {"type": "input", "name": "x", "dims": [8, 6],
             "dtype": "float32", "inputs": [], "outputs": [1]},
            {"type": "dense", "name": "fc1", "inputs": [1], "outputs": [2],
             "params": {"out_dim": 12, "activation": "relu"}},
            {"type": "dense", "name": "fc2", "inputs": [2], "outputs": [3],
             "params": {"out_dim": 3}},
            {"type": "softmax", "name": "sm", "inputs": [3], "outputs": [4],
             "params": {}},
        ],
    }

    # train a model built from the SAME spec (same op names -> checkpoint
    # keys line up)
    from flexflow_tpu.native.c_model import model_from_spec

    trained = model_from_spec(json.dumps(spec))
    trained.config.allow_mixed_precision = False
    trained.compile(
        optimizer=ff.SGDOptimizer(trained, lr=0.1),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[])
    rng = np.random.RandomState(0)
    X = rng.randn(64, 6).astype(np.float32)
    Y = rng.randint(0, 3, size=(64, 1)).astype(np.int32)
    trained.fit(x=X, y=Y, epochs=3, verbose=False)
    expected = np.asarray(trained.predict(X[:8]), np.float32)

    mdir = tmp_path / "cmodel"
    mdir.mkdir()
    (mdir / "model_spec.json").write_text(json.dumps(spec))
    save_checkpoint(str(mdir / "weights"), trained)
    (mdir / "config.json").write_text(json.dumps({
        "format": "ff_cspec", "file": "model_spec.json",
        "checkpoint": "weights.npz", "max_batch_size": 8,
    }))

    repo = ModelRepository(str(tmp_path))
    server = InferenceServer()
    try:
        repo.load(server)
        out = np.asarray(server.infer(
            "cmodel", {"x": X[:8]}, timeout=30.0), np.float32)
        np.testing.assert_allclose(out, expected, atol=2e-2, rtol=2e-2)
    finally:
        server.shutdown()


def test_model_repository_isolates_failed_models(tmp_path):
    """One model's missing artifact / corrupt checkpoint must not abort
    loading every OTHER model (ISSUE 3 satellite): the bad entries are
    recorded on the server and the good one serves."""
    from flexflow_tpu.serving import ModelRepository

    spec = {
        "format": "flexflow_tpu_c_model",
        "config": {"batch_size": 8},
        "ops": [
            {"type": "input", "name": "x", "dims": [8, 6],
             "dtype": "float32", "inputs": [], "outputs": [1]},
            {"type": "dense", "name": "fc", "inputs": [1], "outputs": [2],
             "params": {"out_dim": 3}},
            {"type": "softmax", "name": "sm", "inputs": [2],
             "outputs": [3], "params": {}},
        ],
    }
    good = tmp_path / "good"
    good.mkdir()
    (good / "model_spec.json").write_text(json.dumps(spec))
    (good / "config.json").write_text(json.dumps(
        {"format": "ff_cspec", "file": "model_spec.json"}))
    # artifact file missing entirely
    missing = tmp_path / "missing_artifact"
    missing.mkdir()
    (missing / "config.json").write_text(json.dumps(
        {"format": "ff_cspec", "file": "nope.json"}))
    # checkpoint points at a plain npz that is NOT a checkpoint
    badckpt = tmp_path / "bad_ckpt"
    badckpt.mkdir()
    (badckpt / "model_spec.json").write_text(json.dumps(spec))
    np.savez(str(badckpt / "weights.npz"), w=np.ones(3, np.float32))
    (badckpt / "config.json").write_text(json.dumps(
        {"format": "ff_cspec", "file": "model_spec.json",
         "checkpoint": "weights.npz"}))

    repo = ModelRepository(str(tmp_path))
    server = InferenceServer()
    try:
        loaded = repo.load(server)
        assert loaded == ["good"]
        assert server.models() == ["good"]
        out = server.infer("good", {"x": np.ones((8, 6), np.float32)},
                           timeout=30.0)
        assert np.asarray(out).shape == (8, 3)
        failures = server.stats()["_load_failures"]
        assert set(failures) == {"bad_ckpt", "missing_artifact"}
        assert "CheckpointError" in failures["bad_ckpt"]
        text = server.prometheus_text()
        assert 'ff_model_load_failures_total{model="bad_ckpt"} 1' in text
        # strict mode restores all-or-nothing for callers that want it
        with pytest.raises(Exception):
            repo.load(InferenceServer(), strict=True)
    finally:
        server.shutdown()


def test_fold_batchnorm_preserves_inference():
    """Serving-time conv+BN folding: after a few training steps (non-trivial
    running stats), the folded graph's eval-mode predictions match the
    unfolded model."""
    from flexflow_tpu.serving.optimize import fold_batchnorm

    config = ff.FFConfig()
    config.batch_size = 8
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    x = model.create_tensor([8, 3, 8, 8])
    t = model.conv2d(x, 6, 3, 3, 1, 1, 1, 1, name="conv")
    t = model.batch_norm(t, relu=True, name="bn")
    t = model.flat(t)
    model.softmax(model.dense(t, 4, name="cls"))
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.05),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    rng = np.random.RandomState(0)
    X = rng.randn(32, 3, 8, 8).astype(np.float32)
    Y = rng.randint(0, 4, size=(32, 1)).astype(np.int32)
    model.fit(x=X, y=Y, epochs=2, verbose=False)

    before = np.asarray(model.predict(X[:8]), np.float32)
    folded = fold_batchnorm(model)
    assert folded == ["bn"], folded
    assert all(op.name != "bn" for op in model.ops)
    after = np.asarray(model.predict(X[:8]), np.float32)
    np.testing.assert_allclose(after, before, atol=1e-5, rtol=1e-4)
    # eval works post-fold; training refuses with a clear error
    m = model.eval(x=X[:8], y=Y[:8])
    assert np.isfinite(m["loss"])
    with pytest.raises(RuntimeError, match="optimized for inference"):
        model.fit(x=X, y=Y, epochs=1)

    # the folded model serves
    server = InferenceServer()
    try:
        server.register("folded", model, max_batch_size=8,
                        batch_buckets=[8])
        out = np.asarray(server.infer("folded", {
            model.input_ops[0].name: X[:8]}, timeout=30.0), np.float32)
        np.testing.assert_allclose(out, before, atol=1e-5, rtol=1e-4)
    finally:
        server.shutdown()
