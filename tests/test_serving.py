"""Serving subsystem tests (reference analog: triton/src/test gtests +
triton/qa/L0_e2e — the only mocked-infra tests in the reference; here the
real executor runs on the CPU mesh)."""
import json
import threading
import urllib.request

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.serving import DynamicBatcher, InferenceModel, InferenceServer


def make_model(dim=8, classes=4):
    config = ff.FFConfig()
    config.batch_size = 16
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([16, dim])
    t = model.dense(inp, 16, ff.ActiMode.AC_MODE_RELU)
    t = model.dense(t, classes)
    model.softmax(t)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.0),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    return model


def test_inference_model_pads_to_buckets():
    model = make_model()
    im = InferenceModel(model, batch_buckets=(2, 8))
    name = im.input_names[0]
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    out = im.predict({name: x})
    assert out.shape == (5, 4)
    # padding must not change the un-padded rows: compare against bucket=8 direct
    out8 = im.predict({name: np.concatenate([x, np.zeros((3, 8), np.float32)])})
    np.testing.assert_allclose(out, out8[:5], rtol=1e-5, atol=1e-6)
    # batches over the largest bucket are chunked
    x16 = np.random.RandomState(1).randn(16, 8).astype(np.float32)
    assert im.predict({name: x16}).shape == (16, 4)


def test_dynamic_batcher_matches_direct_and_coalesces():
    model = make_model()
    im = InferenceModel(model, batch_buckets=(1, 4, 16))
    name = im.input_names[0]
    rng = np.random.RandomState(0)
    reqs = [rng.randn(1, 8).astype(np.float32) for _ in range(12)]
    with DynamicBatcher(im, max_batch_size=16, max_delay_ms=20.0) as b:
        futs = [b.submit({name: r}) for r in reqs]
        outs = [f.result(timeout=30) for f in futs]
    direct = im.predict({name: np.concatenate(reqs)})
    got = np.concatenate(outs)
    np.testing.assert_allclose(got, direct, rtol=1e-5, atol=1e-6)


def test_batcher_propagates_errors():
    model = make_model()
    im = InferenceModel(model, batch_buckets=(4,))
    with DynamicBatcher(im, max_delay_ms=1.0) as b:
        fut = b.submit({"not_an_input": np.zeros((1, 8), np.float32)})
        with pytest.raises(KeyError):
            fut.result(timeout=30)


def test_server_http_roundtrip():
    model = make_model()
    server = InferenceServer()
    server.register("mlp", model, batch_buckets=(1, 4))
    name = InferenceModel(model).input_names[0]
    httpd = server.serve_http(port=0)  # ephemeral port
    try:
        port = httpd.server_address[1]
        # model listing
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/v2/models") as r:
            assert json.load(r)["models"] == ["mlp"]
        # inference
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        req = json.dumps({"inputs": {name: x.tolist()}}).encode()
        with urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/v2/models/mlp/infer", data=req,
                headers={"Content-Type": "application/json"}),
        ) as r:
            out = np.asarray(json.load(r)["outputs"], np.float32)
        direct = InferenceModel(model).predict({name: x})
        np.testing.assert_allclose(out, direct, rtol=1e-4, atol=1e-5)
        # unknown model -> 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/v2/models/nope/infer",
                    data=b"{}"),
            )
    finally:
        httpd.shutdown()
        server.shutdown()
