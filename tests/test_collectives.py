"""Explicit collective lowering (ISSUE 11, runtime/collectives.py).

Pins the pricing->execution contract: the per-tier reduction schedule
the Unity search synthesizes (docs/machine.md) is LOWERED into real
grouped collectives — numerically parity with the GSPMD path it
replaces, visibly decomposed in the compiled HLO, counted/spanned for
traces, checked by FFTA072 against the priced plan, and measurable by
collective-bench into rows the per-tier refit consumes.
"""
import math

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.runtime.collectives import (CollectiveLoweringError,
                                              lower_allreduce,
                                              tier_axis_groups)

# two "pods" of four devices on the 8-dev test mesh, DCN-class outer tier
SPEC_4x2 = {"chip": "tpu-v5e", "tiers": [
    {"name": "ici", "degree": 4, "gbps": 45.0, "links": 2},
    {"name": "dcn", "degree": 2, "gbps": 3.125, "links": 1,
     "latency_us": 10.0}]}


def _make_machine(n=8, spec=SPEC_4x2):
    from flexflow_tpu.search.machine_model import HierarchicalMachineModel

    return HierarchicalMachineModel.from_json(spec)


# -- tier group math -------------------------------------------------------

def test_tier_axis_groups_mixed_radix():
    groups = tier_axis_groups(8, [4, 2])
    assert groups[0] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert groups[1] == [[0, 4], [1, 5], [2, 6], [3, 7]]
    g3 = tier_axis_groups(8, [2, 2, 2])
    assert g3[0] == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert g3[1] == [[0, 2], [1, 3], [4, 6], [5, 7]]
    assert g3[2] == [[0, 4], [1, 5], [2, 6], [3, 7]]


def test_tier_axis_groups_bad_product():
    with pytest.raises(CollectiveLoweringError):
        tier_axis_groups(8, [4, 3])


# -- leaf-level lowering vs plain psum -------------------------------------

def _apply_strategy(x_global, strategy, sizes, dtype=np.float32):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from flexflow_tpu.kernels import get_shard_map

    n = x_global.shape[0]
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    groups = tier_axis_groups(n, sizes)

    def body(x):
        return lower_allreduce(x[0], "data", strategy, sizes, groups)[None]

    sm = get_shard_map(check_vma=False)
    fn = jax.jit(sm(body, mesh=mesh, in_specs=P("data"),
                    out_specs=P("data")))
    return np.asarray(fn(x_global.astype(dtype)))


@pytest.mark.parametrize("strategy", ["flat", "rs_ar_ag", "hier_ring"])
@pytest.mark.parametrize("length", [16, 13, 3])
def test_lower_allreduce_sums_exactly(strategy, length):
    # length 13/3: not divisible by the inner tier degree — the
    # rs_ar_ag pad/unpad path
    x = np.arange(8 * length, dtype=np.float32).reshape(8, length)
    out = _apply_strategy(x, strategy, [4, 2])
    expected = np.tile(x.sum(axis=0), (8, 1))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_lower_allreduce_bf16():
    import jax.numpy as jnp

    x = np.random.RandomState(0).randn(8, 24).astype(np.float32)
    ref = _apply_strategy(x, "flat", [4, 2], dtype=jnp.bfloat16)
    out = _apply_strategy(x, "rs_ar_ag", [4, 2], dtype=jnp.bfloat16)
    np.testing.assert_allclose(out.astype(np.float32),
                               ref.astype(np.float32), rtol=2e-2,
                               atol=1e-2)


def test_lower_allreduce_three_tiers():
    x = np.random.RandomState(1).randn(8, 10).astype(np.float32)
    out = _apply_strategy(x, "rs_ar_ag", [2, 2, 2])
    np.testing.assert_allclose(out, np.tile(x.sum(axis=0), (8, 1)),
                               rtol=1e-5)


# -- end-to-end parity: explicit vs GSPMD vs 1-dev -------------------------

def _train(lowering, n_dev, mixed=False, spec=SPEC_4x2, epochs=2,
           bucket_bytes=None, overlap=True):
    cfg = ff.FFConfig()
    cfg.num_devices = n_dev
    cfg.batch_size = 16
    cfg.allow_mixed_precision = mixed
    cfg.seed = 7
    cfg.collective_lowering = lowering
    if bucket_bytes is not None:
        cfg.grad_bucket_bytes = bucket_bytes
    cfg.search_overlap_backward_update = overlap
    if n_dev > 1 and spec is not None:
        cfg.machine_model_file = spec
    m = ff.FFModel(cfg)
    x_t = m.create_tensor([16, 64])
    t = m.dense(x_t, 256, ff.ActiMode.AC_MODE_RELU, name="fc_big")
    t = m.dense(t, 64, name="fc_small")
    m.softmax(m.dense(t, 4, name="cls"))
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[ff.MetricsType.METRICS_ACCURACY],
              parallel_axes={"data": n_dev} if n_dev > 1 else None)
    x = np.random.RandomState(0).randn(32, 64).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, size=(32, 1)).astype(np.int32)
    hist = m.fit([x], y, batch_size=16, epochs=epochs)
    return [h["loss"] for h in hist], m


def test_explicit_parity_f32():
    losses_g, _ = _train("gspmd", 8)
    losses_e, m = _train("explicit", 8)
    losses_1, _ = _train("gspmd", 1)
    lowering = m.executor.grad_sync_lowering
    assert lowering is not None
    # the synthesized plan covers cross-tier strategies, and the
    # executed schedule matches the priced plan (the FFTA072 contract)
    executed = lowering.executed_plan()
    planned = {k: v["strategy"] for k, v in m._reduction_plan.items()}
    for name, strat in planned.items():
        assert executed[name] == strat
    assert any(len(e["sizes"]) > 1 for e in lowering.entries.values())
    for le, lg in zip(losses_e, losses_g):
        assert abs(le - lg) / max(abs(lg), 1e-8) < 1e-5, (losses_e,
                                                          losses_g)
    assert abs(losses_e[-1] - losses_1[-1]) \
        / max(abs(losses_1[-1]), 1e-8) < 2e-3


def test_explicit_parity_bf16():
    losses_g, _ = _train("gspmd", 8, mixed=True)
    losses_e, _ = _train("explicit", 8, mixed=True)
    assert abs(losses_e[-1] - losses_g[-1]) \
        / max(abs(losses_g[-1]), 1e-8) < 5e-3, (losses_e, losses_g)


def test_explicit_on_flat_machine_is_flat_psum():
    # no machine spec: no tiers, the lowering still runs — every sync a
    # flat psum — and parity holds
    losses_g, _ = _train("gspmd", 8, spec=None)
    losses_e, m = _train("explicit", 8, spec=None)
    lowering = m.executor.grad_sync_lowering
    assert lowering is not None
    assert set(lowering.executed_plan().values()) == {"flat"}
    for le, lg in zip(losses_e, losses_g):
        assert abs(le - lg) / max(abs(lg), 1e-8) < 1e-5


def test_auto_lowers_cross_tier_and_skips_flat():
    _, m_tiered = _train("auto", 8)
    assert m_tiered.executor.grad_sync_lowering is not None
    _, m_flat = _train("auto", 8, spec=None)
    # nothing cross-tier on a flat machine: auto keeps GSPMD
    assert m_flat.executor.grad_sync_lowering is None


def test_partial_final_batch_falls_back_to_gspmd():
    # 40 samples at batch 16 -> final batch of 8, which 8 devices still
    # divide; use batch 12 -> 12 % 8 != 0 exercises the trace-time
    # fallback inside the wrapped step
    cfg = ff.FFConfig()
    cfg.num_devices = 8
    cfg.batch_size = 12
    cfg.allow_mixed_precision = False
    cfg.collective_lowering = "explicit"
    cfg.machine_model_file = SPEC_4x2
    m = ff.FFModel(cfg)
    x_t = m.create_tensor([12, 16])
    m.softmax(m.dense(x_t, 4, name="cls"))
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[], parallel_axes={"data": 8})
    x = np.random.RandomState(0).randn(12, 16).astype(np.float32)
    y = np.zeros((12, 1), dtype=np.int32)
    hist = m.fit([x], y, batch_size=12, epochs=1)
    assert np.isfinite(hist[0]["loss"])


# -- compiled HLO contains the decomposition -------------------------------

def test_explicit_hlo_contains_reduce_scatter_all_gather():
    import jax

    _, m = _train("explicit", 8, epochs=1)
    assert any(e["strategy"] == "rs_ar_ag"
               for e in m.executor.grad_sync_lowering.entries.values())
    ex = m.executor
    x = np.random.RandomState(0).randn(16, 64).astype(np.float32)
    y = np.zeros((16, 1), dtype=np.int32)
    inputs = {m.input_ops[0].name: ex.shard_batch(x)}
    label = ex.shard_batch(y)
    hlo = m._train_step.__wrapped__.lower(
        m.params, m.opt_state, m.state, inputs, label,
        jax.random.PRNGKey(0)).as_text()
    assert "reduce_scatter" in hlo
    assert "all_gather" in hlo
    # and the GSPMD baseline of the same model does NOT carry the
    # manual grouped decomposition marker
    _, m_g = _train("gspmd", 8, epochs=1)
    hlo_g = m_g._train_step.__wrapped__.lower(
        m_g.params, m_g.opt_state, m_g.state,
        {m_g.input_ops[0].name: m_g.executor.shard_batch(x)},
        m_g.executor.shard_batch(y), jax.random.PRNGKey(0)).as_text()
    assert "reduce_scatter" not in hlo_g


# -- gating ----------------------------------------------------------------

def test_explicit_raises_on_model_axis():
    cfg = ff.FFConfig()
    cfg.num_devices = 8
    cfg.batch_size = 16
    cfg.collective_lowering = "explicit"
    cfg.machine_model_file = SPEC_4x2
    m = ff.FFModel(cfg)
    x_t = m.create_tensor([16, 32])
    m.softmax(m.dense(x_t, 8, name="cls"))
    with pytest.raises(CollectiveLoweringError):
        m.compile(
            optimizer=ff.SGDOptimizer(m, lr=0.05),
            loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
            metrics=[], parallel_axes={"data": 4, "model": 2})


def test_auto_falls_back_on_model_axis_and_stateful_ops():
    cfg = ff.FFConfig()
    cfg.num_devices = 8
    cfg.batch_size = 16
    cfg.collective_lowering = "auto"
    cfg.machine_model_file = SPEC_4x2
    m = ff.FFModel(cfg)
    x_t = m.create_tensor([16, 32])
    m.softmax(m.dense(x_t, 8, name="cls"))
    m.compile(optimizer=ff.SGDOptimizer(m, lr=0.05),
              loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[], parallel_axes={"data": 4, "model": 2})
    assert m.executor.grad_sync_lowering is None
    assert any("model" in r for r in m.executor._grad_sync_reasons)
    # batch-norm running stats need GSPMD's global batch statistics
    cfg2 = ff.FFConfig()
    cfg2.num_devices = 8
    cfg2.batch_size = 16
    cfg2.collective_lowering = "auto"
    cfg2.machine_model_file = SPEC_4x2
    m2 = ff.FFModel(cfg2)
    inp = m2.create_tensor([16, 3, 8, 8])
    t = m2.conv2d(inp, 4, 3, 3, 1, 1, 1, 1, name="c1")
    t = m2.batch_norm(t, name="bn")
    t = m2.flat(t)
    m2.softmax(m2.dense(t, 4, name="cls2"))
    m2.compile(optimizer=ff.SGDOptimizer(m2, lr=0.05),
               loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[], parallel_axes={"data": 8})
    assert m2.executor.grad_sync_lowering is None
    assert any("state" in r for r in m2.executor._grad_sync_reasons)


def test_bad_knob_value_rejected():
    cfg = ff.FFConfig()
    with pytest.raises(ValueError):
        cfg.parse_args(["--collective-lowering", "magic"])
    rest = cfg.parse_args(["--collective-lowering", "auto"])
    assert rest == [] and cfg.collective_lowering == "auto"


# -- observability: counter + spans ----------------------------------------

def test_lowered_counter_and_grad_sync_span():
    from flexflow_tpu.obs import enable_tracing, get_tracer
    from flexflow_tpu.obs.registry import REGISTRY

    enable_tracing()
    _, m = _train("explicit", 8, epochs=1)
    c = REGISTRY.counter(
        "ff_collective_lowered_total",
        "Collectives lowered explicitly, by reduction strategy and tier",
        labels=("strategy", "tier"))
    entries = m.executor.grad_sync_lowering.entries
    for e in entries.values():
        for tier in e["tiers"]:
            assert c.value(strategy=e["strategy"], tier=tier) >= 1
    spans = get_tracer().events("exec.grad_sync")
    assert spans, get_tracer().span_names()
    args = spans[0]["args"]
    assert args["mode"] == "explicit" and args["tensors"] == len(entries)


def test_resharding_transfer_rows_and_span():
    import jax
    from jax.sharding import Mesh

    from flexflow_tpu.obs import enable_tracing, get_tracer
    from flexflow_tpu.resharding.executor import redistribute
    from flexflow_tpu.resharding.plan import (ArraySpec, MeshSpec,
                                              ShardingPlan)

    enable_tracing()
    machine = _make_machine()
    tree = {"w": np.arange(8 * 16, dtype=np.float32).reshape(8, 16)}
    old = ShardingPlan(
        mesh=MeshSpec(device_ids=tuple(range(8)), axes=(("data", 8),)),
        arrays={"w": ArraySpec(degrees=(8, 1), axes=("data", None))})
    new = ShardingPlan(
        mesh=MeshSpec(device_ids=tuple(range(8)), axes=(("data", 8),)),
        arrays={})  # replicate: a pure all-gather move
    res = redistribute(tree, old, new, peak_bytes=1 << 20,
                       machine=machine, collect_timings=True)
    assert res.calibration_rows
    row = res.calibration_rows[0]
    assert row.op == "allgather" and row.measured_us > 0
    # the gather group is the full 8-wide data axis, spanning both
    # tiers of the 4x2 spec
    assert row.tier == "dcn" and row.participants == 8
    assert math.isfinite(row.predicted_us) and row.predicted_us > 0
    assert get_tracer().events("exec.transfer")
    np.testing.assert_array_equal(
        np.asarray(res.tree["w"]), tree["w"])
    # timings are opt-in: the default path keeps rounds async and
    # collects nothing
    res2 = redistribute(tree, old, new, peak_bytes=1 << 20,
                        machine=machine)
    assert res2.calibration_rows == []


def test_intra_pod_allgather_labeled_with_its_groups_tier():
    # on a DCN-spanning mesh, a gather whose group stays inside one
    # ICI pod must label its rows AND its counter series 'ici', not
    # the whole mesh's outermost tier
    from flexflow_tpu.obs.registry import REGISTRY
    from flexflow_tpu.resharding.executor import redistribute
    from flexflow_tpu.resharding.plan import (ArraySpec, MeshSpec,
                                              ShardingPlan)
    from flexflow_tpu.runtime.collectives import lowered_counter

    machine = _make_machine()
    mesh = MeshSpec(device_ids=tuple(range(8)),
                    axes=(("data", 2), ("model", 4)))
    tree = {"w": np.arange(8 * 16, dtype=np.float32).reshape(8, 16)}
    old = ShardingPlan(
        mesh=mesh,
        arrays={"w": ArraySpec(degrees=(1, 4), axes=(None, "model"))})
    new = ShardingPlan(mesh=mesh, arrays={})
    res = redistribute(tree, old, new, peak_bytes=1 << 20,
                       machine=machine, collect_timings=True)
    assert res.calibration_rows
    # the 4-wide 'model' group is innermost (stride 1): one ICI pod
    assert all(r.tier == "ici" and r.participants == 4
               for r in res.calibration_rows)
    assert lowered_counter().value(strategy="allgather", tier="ici") >= 1
    assert REGISTRY.counter(
        "ff_collective_lowered_total", "x",
        labels=("strategy", "tier")).value(
            strategy="allgather", tier="dcn") == 0
    np.testing.assert_array_equal(np.asarray(res.tree["w"]), tree["w"])


# -- per-tier transfer pricing + chunk cap ---------------------------------

def test_transfer_priced_on_tier_path():
    from flexflow_tpu.resharding.cost import step_cost_us
    from flexflow_tpu.resharding.plan import ReshardStep, TRANSFER

    machine = _make_machine()
    step = ReshardStep(kind=TRANSFER, participants=8,
                       bytes_per_chip=1_000_000)
    tiered = step_cost_us(step, machine)
    # the flat-link price is the innermost tier's p2p — crossing the
    # DCN must cost (much) more
    flat_price = machine.p2p_time_us(step.bytes_per_chip)
    assert tiered > 5 * flat_price
    inner_only = step_cost_us(
        ReshardStep(kind=TRANSFER, participants=1,
                    bytes_per_chip=1_000_000), machine)
    assert inner_only == pytest.approx(flat_price)
    # a REPLICATED landing records participants=1 on the step — the
    # device span (n_devices, threaded by schedule_cost_us) must still
    # price the cross-pod hop
    replicated = step_cost_us(
        ReshardStep(kind=TRANSFER, participants=1,
                    bytes_per_chip=1_000_000), machine, n_devices=8)
    assert replicated == pytest.approx(tiered)


def test_schedule_cost_prices_replicated_transfer_on_device_span():
    from flexflow_tpu.resharding.cost import schedule_cost_us
    from flexflow_tpu.resharding.plan import (ArraySpec, MeshSpec,
                                              ShardingPlan,
                                              plan_redistribution)

    machine = _make_machine()
    tree = {"w": np.zeros((8, 1024), dtype=np.float32)}
    old = ShardingPlan(
        mesh=MeshSpec(device_ids=(0, 1, 2, 3), axes=(("data", 4),)),
        arrays={"w": ArraySpec(degrees=(4, 1), axes=("data", None))})
    # cross-mesh move onto all 8 devices, landing REPLICATED: the
    # TRANSFER step's participants is the array degree (1), but the
    # target group spans both pods
    new = ShardingPlan(
        mesh=MeshSpec(device_ids=tuple(range(8)), axes=(("data", 8),)),
        arrays={})
    sched = plan_redistribution(tree, old, new, peak_bytes=1 << 22,
                                machine=machine)
    cost_tiered = schedule_cost_us(sched, machine)
    transfer_bytes = max(
        s.bytes_per_chip for m in sched.moves for s in m.steps
        if s.kind == "transfer")
    # must be at least the DCN hop price of the transfer leg, far above
    # the innermost p2p
    assert cost_tiered > machine.ring_hop_time_us(transfer_bytes, 8) / 2
    assert cost_tiered > machine.p2p_time_us(transfer_bytes)


def test_cross_tier_transfer_chunk_cap():
    from flexflow_tpu.resharding.plan import (TRANSFER_TIER_CHUNK_BYTES,
                                              transfer_chunk_bound)

    machine = _make_machine()
    # 8 devices span the dcn tier -> the cap engages
    cap = transfer_chunk_bound(machine, 8, kept_degree=1, new_total=1)
    assert cap == int(2 * TRANSFER_TIER_CHUNK_BYTES)
    # 4 devices stay inside one pod -> no cap
    assert transfer_chunk_bound(machine, 4, 1, 1) is None
    assert transfer_chunk_bound(None, 8, 1, 1) is None


# -- FFTA072 ----------------------------------------------------------------

def test_ffta072_tolerates_non_factoring_flat_fallback():
    # tier_path's conservative round-up on a non-factoring mesh (e.g.
    # dp=12 on an 8x2 spec) prices rs_ar_ag over groups that do NOT
    # multiply to the sync degree; the lowering's documented fallback
    # syncs flat — legal, and FFTA072 must not reject the compile
    from flexflow_tpu.analysis.passes import (AnalysisContext,
                                              check_executed_reductions)
    from flexflow_tpu.core.graph import Graph

    cfg = ff.FFConfig()
    cfg.num_devices = 1
    m = ff.FFModel(cfg)
    x_t = m.create_tensor([12, 8])
    m.dense(x_t, 4, name="fc")
    graph = Graph(m.ops)
    plan = {"fc": {"strategy": "rs_ar_ag", "degree": 12,
                   "tiers": [{"tier": "ici", "group": 8},
                             {"tier": "dcn", "group": 2}]}}
    ctx = AnalysisContext(graph=graph, reduction_strategies=plan,
                          executed_reductions={"fc": "flat"})
    assert check_executed_reductions(ctx) == []
    # but a flat substitution where the decomposition WAS expressible
    # still fails
    plan_ok = {"fc": {"strategy": "rs_ar_ag", "degree": 16,
                      "tiers": [{"tier": "ici", "group": 8},
                                {"tier": "dcn", "group": 2}]}}
    ctx2 = AnalysisContext(graph=graph, reduction_strategies=plan_ok,
                           executed_reductions={"fc": "flat"})
    assert len(check_executed_reductions(ctx2)) == 1


def test_lowering_falls_back_flat_on_non_factoring_tiers():
    from flexflow_tpu.runtime.collectives import plan_grad_sync_lowering

    _, m = _train("explicit", 8, epochs=1)
    plan = {name: dict(e) for name, e in m._reduction_plan.items()}
    # corrupt one entry's decomposition so it cannot factor dp=8
    name = next(iter(plan))
    plan[name] = dict(plan[name])
    plan[name]["tiers"] = [{"tier": "ici", "group": 3},
                           {"tier": "dcn", "group": 2}]
    lowering, reasons = plan_grad_sync_lowering(
        m.config, m.graph, m.mesh, plan, pipeline_plan=None)
    assert lowering is not None, reasons
    assert lowering.entries[name]["strategy"] == "flat"
    assert lowering.entries[name]["sizes"] == [8]


def test_ffta072_clean_and_divergent():
    from flexflow_tpu.analysis import analyze_plan
    from flexflow_tpu.analysis.passes import (AnalysisContext,
                                              check_executed_reductions)

    _, m = _train("explicit", 8, epochs=1)
    rep = m.analyze_plan()
    assert not rep.by_code("FFTA072"), rep.format()
    # the full pipeline flags a dropped and a renamed entry
    executed = m.executor.grad_sync_lowering.executed_plan()
    bad = dict(executed)
    renamed = next(iter(bad))
    del bad[renamed]
    rep2 = analyze_plan(
        m.graph, strategies=m._op_strategies,
        machine=None, config=m.config,
        mesh_axes=m.parallel_axes,
        reduction_strategies=m._reduction_plan,
        executed_reductions=bad, passes=("tiers",))
    assert rep2.by_code("FFTA072"), rep2.format()
    # direct check: strategy substitution on an expressible (factoring)
    # decomposition also fires — only the documented non-factoring flat
    # fallback is tolerated
    ctx = AnalysisContext(
        graph=m.graph,
        reduction_strategies={"fc_big": {
            "strategy": "rs_ar_ag", "degree": 8,
            "tiers": [{"tier": "ici", "group": 4},
                      {"tier": "dcn", "group": 2}]}},
        executed_reductions={"fc_big": "hier_ring"})
    assert len(check_executed_reductions(ctx)) == 1


def test_compile_gate_rejects_divergent_lowering(monkeypatch):
    from flexflow_tpu.analysis import PlanAnalysisError
    from flexflow_tpu.runtime.collectives import GradSyncLowering

    orig = GradSyncLowering.executed_plan

    def dropped(self):
        out = orig(self)
        out.pop(next(iter(out)))
        return out

    monkeypatch.setattr(GradSyncLowering, "executed_plan", dropped)
    with pytest.raises(PlanAnalysisError) as ei:
        _train("explicit", 8, epochs=1)
    assert ei.value.report.by_code("FFTA072")


# -- collective-bench + per-tier refit -------------------------------------

def test_sweep_collectives_rows():
    from flexflow_tpu.obs.collective_bench import sweep_collectives

    cfg = ff.FFConfig()
    cfg.num_devices = 8
    cfg.machine_model_file = SPEC_4x2
    result = sweep_collectives(cfg, [65536, 262144],
                               ["flat", "rs_ar_ag"], warmup=0, repeats=1)
    rows = result["rows"]
    assert result["tiers"] == ["ici", "dcn"]
    kinds = {(r.op, r.strategy, r.tier) for r in rows}
    assert ("allreduce", "flat", "dcn") in kinds
    assert ("allreduce", "rs_ar_ag", "dcn") in kinds
    assert ("psum", "tier_ring", "ici") in kinds
    assert ("psum", "tier_ring", "dcn") in kinds
    assert all(r.measured_us > 0 and r.predicted_us > 0 for r in rows)


def test_fit_collective_coefficients_round_trip():
    from flexflow_tpu.obs.calibration import CollectiveCalibration
    from flexflow_tpu.obs.refit import fit_collective_coefficients

    machine = _make_machine()
    true_scales = {"ici": 0.5, "dcn": 2.0}
    rows = []
    path = machine.tier_path(8)
    for tier, nj in path:
        for b in (1e5, 1e6, 4e6):
            slope = 2.0 * (nj - 1) / nj / machine.tier_bw(tier) * 1e6
            lat = machine.tier_latency(tier)
            rows.append(CollectiveCalibration(
                op="psum", strategy="tier_ring", tier=tier.name,
                bytes=b, participants=nj,
                predicted_us=slope * b + lat,
                measured_us=slope / true_scales[tier.name] * b + lat))
    coeffs = fit_collective_coefficients(rows, machine)
    for name, want in true_scales.items():
        assert coeffs.tier_link_scales[name] == pytest.approx(want,
                                                              rel=0.1)
    # the fitted scales round-trip through the overlay into the machine
    machine2 = _make_machine()
    machine2.apply_overlay(coeffs)
    assert machine2.tier_scales["ici"] == pytest.approx(0.5, rel=0.1)
    assert machine2.tier_scales["dcn"] == pytest.approx(2.0, rel=0.1)


# -- bucketed/async grad-sync lowering (docs/machine.md "Overlap") ---------

def test_bucketed_lowering_parity_and_executed_schedule():
    """A tiny bucket target forces SEVERAL fused buckets; the bucketed
    schedule must be loss-parity with the per-tensor explicit path and
    GSPMD, and the executed bucket assignment must equal the priced
    plan's (the extended FFTA072 contract)."""
    losses_b, m_b = _train("explicit", 8, bucket_bytes=4096)
    losses_p, _ = _train("explicit", 8, bucket_bytes=0)
    losses_g, _ = _train("gspmd", 8)
    lowering = m_b.executor.grad_sync_lowering
    assert lowering is not None
    buckets = lowering.bucket_map()
    assert len(buckets) >= 2, buckets
    planned = {name: e.get("bucket")
               for name, e in m_b._reduction_plan.items()}
    assert lowering.executed_buckets() == {**lowering.executed_buckets(),
                                           **planned}
    for lb, lp, lg in zip(losses_b, losses_p, losses_g):
        assert abs(lb - lp) / max(abs(lp), 1e-8) < 1e-5, (losses_b,
                                                          losses_p)
        assert abs(lb - lg) / max(abs(lg), 1e-8) < 1e-5, (losses_b,
                                                          losses_g)


def test_bucket_zero_and_blocking_disable_bucketing():
    # per-tensor mode and the legacy blocking knob must both produce an
    # un-bucketed plan (every entry bucket-less, the pre-bucketing
    # schedule)
    _, m_p = _train("explicit", 8, bucket_bytes=0, epochs=1)
    assert m_p.executor.grad_sync_lowering.bucket_map() == {}
    assert all(e.get("bucket") is None
               for e in m_p._reduction_plan.values())
    _, m_k = _train("explicit", 8, overlap=False, epochs=1)
    assert m_k.executor.grad_sync_lowering.bucket_map() == {}
    assert m_k._sync_overlap is None


def test_bucket_counter_and_span():
    from flexflow_tpu.obs import enable_tracing, get_tracer
    from flexflow_tpu.obs.registry import REGISTRY
    from flexflow_tpu.runtime.collectives import overlap_bucket_counter

    enable_tracing()
    _, m = _train("explicit", 8, epochs=1, bucket_bytes=4096)
    lowering = m.executor.grad_sync_lowering
    buckets = lowering.bucket_map()
    assert buckets
    c = overlap_bucket_counter()
    total = sum(v for _, v in c.items())
    assert total >= len(buckets)
    spans = get_tracer().events("exec.grad_sync")
    assert spans and spans[0]["args"]["buckets"] == len(buckets)
    bspans = get_tracer().events("exec.grad_sync.bucket")
    assert len(bspans) >= len(buckets)
    assert {s["args"]["bucket"] for s in bspans} >= set(buckets)
    # the predicted overlap split landed on the gauge
    g = REGISTRY.get("ff_grad_sync_overlap_us")
    assert g is not None
    assert g.value(kind="exposed") >= 0.0


def test_ffta072_bucket_schedule_divergence():
    from flexflow_tpu.analysis.passes import (AnalysisContext,
                                              check_executed_reductions)

    _, m = _train("explicit", 8, epochs=1, bucket_bytes=4096)
    rep = m.analyze_plan()
    assert not rep.by_code("FFTA072"), rep.format()
    lowering = m.executor.grad_sync_lowering
    # regroup one tensor into a different bucket: the extended FFTA072
    # check must reject the divergent bucket schedule
    bad = dict(lowering.executed_buckets())
    name = next(n for n, b in bad.items() if b is not None)
    bad[name] = (bad[name] or 0) + 97
    ctx = AnalysisContext(
        graph=m.graph,
        reduction_strategies=m._reduction_plan,
        executed_reductions=lowering.executed_plan(),
        executed_buckets=bad)
    diags = check_executed_reductions(ctx)
    assert diags and all(d.code == "FFTA072" for d in diags), diags
    # matching buckets stay clean
    ctx_ok = AnalysisContext(
        graph=m.graph,
        reduction_strategies=m._reduction_plan,
        executed_reductions=lowering.executed_plan(),
        executed_buckets=lowering.executed_buckets())
    assert not check_executed_reductions(ctx_ok)


def test_compile_gate_rejects_bucket_divergence(monkeypatch):
    from flexflow_tpu.analysis import PlanAnalysisError
    from flexflow_tpu.runtime.collectives import GradSyncLowering

    orig = GradSyncLowering.executed_buckets

    def regrouped(self):
        out = orig(self)
        for k, v in out.items():
            if v is not None:
                out[k] = v + 1
                break
        return out

    monkeypatch.setattr(GradSyncLowering, "executed_buckets", regrouped)
    with pytest.raises(PlanAnalysisError) as ei:
        _train("explicit", 8, epochs=1, bucket_bytes=4096)
    assert ei.value.report.by_code("FFTA072")
