"""Checkpoint/resume (new capability — the reference has no model
checkpointing, SURVEY.md §5) and the durability layer on top of it:
atomic checksummed files, torn-write detection, manifest retention, and
fallback to the newest VERIFIED checkpoint (ISSUE 3)."""
import json
import os
import types

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.runtime.checkpoint import (CheckpointError,
                                             restore_checkpoint,
                                             save_checkpoint,
                                             verify_checkpoint)
from flexflow_tpu.runtime.durability import DurableCheckpointer


def build(seed_data):
    config = ff.FFConfig()
    config.batch_size = 8
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 16])
    t = model.dense(inp, 32, ff.ActiMode.AC_MODE_RELU)
    model.softmax(model.dense(t, 4))
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=1e-2),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    return model


def test_checkpoint_roundtrip_and_resume(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(64, 16).astype(np.float32)
    y = rng.randint(0, 4, size=(64, 1)).astype(np.int32)

    m1 = build(0)
    m1.fit(x, y, epochs=2)
    pred1 = m1.predict(x)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, m1, step=7)

    m2 = build(1)
    # fresh model differs before restore
    assert not np.allclose(m2.predict(x), pred1)
    step = restore_checkpoint(path, m2)
    assert step == 7
    np.testing.assert_allclose(m2.predict(x), pred1, rtol=1e-5, atol=1e-6)

    # resume training from the restored optimizer state: loss keeps falling
    h1 = m1.fit(x, y, epochs=1)
    h2 = m2.fit(x, y, epochs=1)
    np.testing.assert_allclose(h1[-1]["sparse_cce"], h2[-1]["sparse_cce"],
                               rtol=1e-4, atol=1e-5)


# -- typed errors on non-checkpoints (ISSUE 3 satellite) -----------------
def test_restore_non_checkpoint_npz_raises_named_error(tmp_path):
    """A plain npz (e.g. a repository weights.npz) used to die with a bare
    KeyError: '__meta__'; now it's a CheckpointError naming the path."""
    path = str(tmp_path / "weights.npz")
    np.savez(path, w=np.ones((3, 3), np.float32))
    with pytest.raises(CheckpointError, match="weights.npz"):
        restore_checkpoint(path, build(0))
    with pytest.raises(CheckpointError, match="not a flexflow_tpu"):
        verify_checkpoint(path)


def test_restore_missing_and_garbage_files(tmp_path):
    with pytest.raises(CheckpointError, match="does not exist"):
        restore_checkpoint(str(tmp_path / "nope"), build(0))
    garbage = tmp_path / "bad.npz"
    garbage.write_bytes(b"this is not a zip archive")
    with pytest.raises(CheckpointError, match="bad.npz"):
        restore_checkpoint(str(garbage), build(0))


# -- checksums + bfloat16 ------------------------------------------------
def _fake_model(params):
    return types.SimpleNamespace(params=params, opt_state={}, state={},
                                 _step_count=3)


def test_bfloat16_roundtrip_with_checksums(tmp_path):
    """bfloat16 arrays survive save/restore with CRC verification on: the
    checksums cover the widened-to-f32 bytes as stored, and restore gets
    the true dtype back."""
    import ml_dtypes

    rng = np.random.RandomState(0)
    src = rng.randn(16, 8).astype(ml_dtypes.bfloat16)
    path = save_checkpoint(str(tmp_path / "bf16"), _fake_model(
        {"fc": {"kernel": src, "bias": np.zeros(8, np.float32)}}), step=5)
    meta = verify_checkpoint(path)  # every array passes its CRC
    assert meta["dtypes"] == {"params/fc/kernel": "bfloat16"}
    assert set(meta["crc32"]) == {"params/fc/kernel", "params/fc/bias"}

    dst = _fake_model({})
    assert restore_checkpoint(path, dst) == 5
    got = np.asarray(dst.params["fc"]["kernel"])
    assert got.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got.astype(np.float32),
                                  np.asarray(src).astype(np.float32))


def test_crc_mismatch_detected(tmp_path):
    """Bit rot (not just truncation): hand-edit the stored CRC table so an
    intact array no longer matches — verification must fail."""
    path = save_checkpoint(str(tmp_path / "c"), _fake_model(
        {"fc": {"w": np.ones((4, 4), np.float32)}}), step=1)
    data = dict(np.load(path, allow_pickle=False))
    meta = json.loads(str(data.pop("__meta__")))
    meta["crc32"]["params/fc/w"] ^= 0xFF
    np.savez(path, __meta__=json.dumps(meta), **data)
    with pytest.raises(CheckpointError, match="CRC32"):
        verify_checkpoint(path)


def test_save_is_atomic_no_tmp_left_behind(tmp_path):
    path = save_checkpoint(str(tmp_path / "a"), _fake_model(
        {"fc": {"w": np.ones(3, np.float32)}}), step=0)
    assert os.path.basename(path) == "a.npz"
    assert sorted(os.listdir(tmp_path)) == ["a.npz"]  # no .tmp.* residue


# -- durable checkpointer: manifest, GC, verified fallback ---------------
def _saver(tmp_path, **kw):
    ckpt = DurableCheckpointer(str(tmp_path), **kw)
    model = build(0)
    return ckpt, model


def test_manifest_retention_gc(tmp_path):
    ckpt, model = _saver(tmp_path, keep_last=2)
    for step in (0, 2, 4, 6):
        ckpt.save(model, step=step)
    files = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    assert files == ["ckpt_000004.npz", "ckpt_000006.npz"]
    assert [e["step"] for e in ckpt.entries()] == [4, 6]
    with open(ckpt.manifest_path) as f:
        manifest = json.load(f)
    assert manifest["keep_last"] == 2
    step, path = ckpt.latest_verified()
    assert step == 6 and path.endswith("ckpt_000006.npz")


def test_torn_write_falls_back_to_previous_verified(tmp_path):
    """Truncate the newest checkpoint mid-file (the crash-mid-save relic):
    restore must fall back to the previous good one, not die."""
    from flexflow_tpu.elastic import EventLog

    events = EventLog()
    ckpt = DurableCheckpointer(str(tmp_path), keep_last=3, events=events)
    model = build(0)
    ckpt.save(model, step=0)
    model.fit(np.random.RandomState(0).randn(16, 16).astype(np.float32),
              np.zeros((16, 1), np.int32), epochs=1)
    ckpt.save(model, step=2)
    newest = os.path.join(str(tmp_path), "ckpt_000002.npz")
    size = os.path.getsize(newest)
    with open(newest, "r+b") as f:
        f.truncate(size // 2)

    target = build(1)
    step, path = ckpt.restore_latest(target)
    assert step == 0 and path.endswith("ckpt_000000.npz")
    assert len(events.events("checkpoint.corrupt")) == 1
    fb = events.events("checkpoint.fallback")
    assert len(fb) == 1 and fb[0].details["skipped"] == 1


def test_no_verified_checkpoint_raises(tmp_path):
    ckpt, model = _saver(tmp_path)
    path = ckpt.save(model, step=0)
    with open(path, "r+b") as f:
        f.truncate(10)
    with pytest.raises(CheckpointError, match="no verified checkpoint"):
        ckpt.restore_latest(build(1))


def test_entries_survive_missing_manifest(tmp_path):
    """A pre-durability dir (files, no MANIFEST.json) still restores: the
    directory scan is the fallback source of truth."""
    model = build(0)
    save_checkpoint(str(tmp_path / "ckpt_000004"), model, step=4)
    ckpt = DurableCheckpointer(str(tmp_path))
    step, path = ckpt.latest_verified()
    assert step == 4 and path.endswith("ckpt_000004.npz")
