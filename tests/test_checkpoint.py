"""Checkpoint/resume (new capability — the reference has no model
checkpointing, SURVEY.md §5)."""
import os

import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.runtime.checkpoint import restore_checkpoint, save_checkpoint


def build(seed_data):
    config = ff.FFConfig()
    config.batch_size = 8
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 16])
    t = model.dense(inp, 32, ff.ActiMode.AC_MODE_RELU)
    model.softmax(model.dense(t, 4))
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=1e-2),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    return model


def test_checkpoint_roundtrip_and_resume(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(64, 16).astype(np.float32)
    y = rng.randint(0, 4, size=(64, 1)).astype(np.int32)

    m1 = build(0)
    m1.fit(x, y, epochs=2)
    pred1 = m1.predict(x)
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, m1, step=7)

    m2 = build(1)
    # fresh model differs before restore
    assert not np.allclose(m2.predict(x), pred1)
    step = restore_checkpoint(path, m2)
    assert step == 7
    np.testing.assert_allclose(m2.predict(x), pred1, rtol=1e-5, atol=1e-6)

    # resume training from the restored optimizer state: loss keeps falling
    h1 = m1.fit(x, y, epochs=1)
    h2 = m2.fit(x, y, epochs=1)
    np.testing.assert_allclose(h1[-1]["sparse_cce"], h2[-1]["sparse_cce"],
                               rtol=1e-4, atol=1e-5)
