"""Real-dataset accuracy gates (reference:
examples/python/native/accuracy.py:19-24 — ModelAccuracy >= 90% per model).

The reference downloads MNIST/CIFAR; this environment has no egress, so the
gates run on scikit-learn's bundled REAL handwritten-digit data (1797 8x8
images) — genuine data, same >= 90% bar, both MLP and CNN families."""
import numpy as np
import pytest

sklearn_datasets = pytest.importorskip("sklearn.datasets")

import flexflow_tpu as ff

ACCURACY_GATE = 0.90  # reference: ModelAccuracy.MNIST_MLP etc. = 90


def _digits():
    d = sklearn_datasets.load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)[:, None]
    rng = np.random.RandomState(0)
    idx = rng.permutation(len(x))
    x, y = x[idx], y[idx]
    n_train = 1536
    return (x[:n_train], y[:n_train]), (x[n_train:1792], y[n_train:1792])


def _evaluate(model, x, y, batch):
    pm = model.eval([x], y, batch_size=batch)
    return pm["accuracy"]


def test_digits_mlp_accuracy_gate():
    (xtr, ytr), (xte, yte) = _digits()
    batch = 64
    config = ff.FFConfig()
    config.batch_size = batch
    config.epochs = 30
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, 64])
    t = model.dense(inp, 128, ff.ActiMode.AC_MODE_RELU, name="fc1")
    t = model.dense(t, 64, ff.ActiMode.AC_MODE_RELU, name="fc2")
    model.softmax(model.dense(t, 10, name="cls"))
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=2e-3),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    model.fit([xtr], ytr, batch_size=batch, epochs=config.epochs)
    acc = _evaluate(model, xte, yte, batch)
    assert acc >= ACCURACY_GATE, f"digits MLP accuracy {acc:.3f} < 90%"


def test_digits_cnn_accuracy_gate():
    (xtr, ytr), (xte, yte) = _digits()
    xtr = xtr.reshape(-1, 1, 8, 8)
    xte = xte.reshape(-1, 1, 8, 8)
    batch = 64
    config = ff.FFConfig()
    config.batch_size = batch
    config.epochs = 30
    model = ff.FFModel(config)
    inp = model.create_tensor([batch, 1, 8, 8])
    t = model.conv2d(inp, 16, 3, 3, 1, 1, 1, 1,
                     activation=ff.ActiMode.AC_MODE_RELU, name="c1")
    t = model.conv2d(t, 32, 3, 3, 1, 1, 1, 1,
                     activation=ff.ActiMode.AC_MODE_RELU, name="c2")
    t = model.pool2d(t, 2, 2, 2, 2, 0, 0, name="p1")
    t = model.flat(t, name="flat")
    model.softmax(model.dense(t, 10, name="cls"))
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=2e-3),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    model.fit([xtr], ytr, batch_size=batch, epochs=config.epochs)
    acc = _evaluate(model, xte, yte, batch)
    assert acc >= ACCURACY_GATE, f"digits CNN accuracy {acc:.3f} < 90%"
