"""Joint substitution x parallelization search (reference:
GraphSearchHelper::base_optimize, substitution.cc:2229-2311): rewrites are
best-first search actions costed by their optimal parallelization, which can
beat greedily applying every rewrite first."""
import os

import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.search.machine_model import make_machine_model
from flexflow_tpu.search.unity import unity_optimize


def _three_linears(joint: bool):
    """Three wide linears sharing one input: C(511) first, then A(512),
    B(512). Greedy merge (first match) folds C+A -> 1023, then +B -> 1535 —
    a width no tp divides, killing tensor parallelism. The joint search can
    instead merge only A+B (1024, tp-shardable) or skip merging."""
    config = ff.FFConfig()
    config.batch_size = 8
    config.search_budget = 8
    config.joint_search = joint
    config.use_native_search = False
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 4096])
    c = model.dense(inp, 511, name="lin_c")
    a = model.dense(inp, 512, name="lin_a")
    b = model.dense(inp, 512, name="lin_b")
    out = model.concat([c, a, b], axis=-1, name="cat")
    model.softmax(model.dense(out, 4, name="cls"))
    return model, config


def test_joint_search_beats_greedy_rewrites():
    greedy_model, greedy_cfg = _three_linears(joint=False)
    joint_model, joint_cfg = _three_linears(joint=True)
    machine = make_machine_model(greedy_cfg, 8)

    greedy = unity_optimize(Graph(greedy_model.ops), greedy_cfg, machine, 8, 8)
    joint = unity_optimize(Graph(joint_model.ops), joint_cfg, machine, 8, 8)

    assert any("greedy substitutions" in l for l in greedy.log), greedy.log
    assert any(l.startswith("joint:") for l in joint.log), joint.log
    assert joint.cost_us < greedy.cost_us, (
        f"joint {joint.cost_us} !< greedy {greedy.cost_us}\n"
        + "\n".join(joint.log + ["---"] + greedy.log)
    )


def test_joint_search_trains_after_rewrite():
    """compile() with the joint search enabled executes the rewritten graph
    (merged linear + split) end to end."""
    model, config = _three_linears(joint=True)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    x = np.random.RandomState(0).randn(8, 4096).astype(np.float32)
    y = np.zeros((8, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=8, epochs=1)
    assert np.isfinite(hist[0]["loss"])


def test_taso_file_activates_merge_template():
    """The 640-rule OSDI file drives actual rewrites: its matmul-fusion rule
    family activates merge_parallel_linears as a joint-search action."""
    from flexflow_tpu.search.substitution import search_rules_from_spec
    from flexflow_tpu.search.substitution_loader import (
        rules_from_spec,
        xfer_templates_from_rules,
    )
    import json

    with open(os.path.join(os.path.dirname(__file__), "..", "substitutions",
                           "graph_subst_3_v2.json")) as f:
        spec = json.load(f)
    rules = rules_from_spec(spec)
    templates = xfer_templates_from_rules(rules)
    assert "merge_parallel_linears" in templates
    active = search_rules_from_spec(spec, True)
    assert "merge_parallel_linears" in active


def _branch_convs(joint: bool):
    """Inception-style branch: three same-window 1x1 convs on one input,
    channel-concatenated — the merge_parallel_convs pattern."""
    config = ff.FFConfig()
    config.batch_size = 8
    config.search_budget = 8
    config.joint_search = joint
    config.use_native_search = False
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 16, 8, 8])
    b1 = model.conv2d(inp, 8, 1, 1, 1, 1, 0, 0, name="br1")
    b2 = model.conv2d(inp, 12, 1, 1, 1, 1, 0, 0, name="br2")
    b3 = model.conv2d(inp, 4, 1, 1, 1, 1, 0, 0, name="br3")
    cat = model.concat([b1, b2, b3], axis=1, name="cat")
    t = model.flat(cat)
    model.softmax(model.dense(t, 4, name="cls"))
    return model, config


def test_joint_search_explores_conv_merge():
    model, config = _branch_convs(joint=True)
    machine = make_machine_model(config, 8)
    res = unity_optimize(Graph(model.ops), config, machine, 8, 8)
    assert any("merge_parallel_convs" in l for l in res.log), res.log


def test_conv_merge_trains_after_rewrite():
    """The rewritten graph (merged conv + channel split) executes end to
    end when the joint search picks it."""
    from flexflow_tpu.search.substitution import rule_merge_parallel_convs

    model, config = _branch_convs(joint=True)
    g = Graph(model.ops)
    apps = rule_merge_parallel_convs(g)
    assert len(apps) == 3, [a.description for a in apps]  # 3 pairs
    apps[0].apply()
    # merged conv + split present, shapes consistent
    merged = [o for o in g.ops.values() if o.name == "br1+br2"]
    assert merged and merged[0].params["out_channels"] == 20
    model.ops = list(g.topo_order())  # compile rebuilds its graph from ops
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    x = np.random.RandomState(0).randn(8, 16, 8, 8).astype(np.float32)
    y = np.zeros((8, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=8, epochs=1)
    assert np.isfinite(hist[-1]["loss"])
