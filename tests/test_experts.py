"""Fused ExpertsOp: numerics vs the unfused group_by/dense/aggregate path,
and device-level expert parallelism on the 8-device CPU mesh (reference:
search-placed expert ops, src/ops/group_by.cc + aggregate.cc +
examples/cpp/mixture_of_experts/moe.cc)."""
import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.ffconst import CompMode


def _build_moe(fused, B, F, n, k, H, parallel_axes=None):
    config = ff.FFConfig()
    config.batch_size = B
    config.allow_mixed_precision = False
    model = ff.FFModel(config)
    inp = model.create_tensor([B, F])
    out = model.moe(inp, n, k, H, alpha=float(n), fused=fused, name="moe")
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY,
                  parallel_axes=parallel_axes)
    return model, out


def _forward(model, out, x):
    feeds = {model.input_ops[0].name: x}
    values, _, _ = model.executor.forward_values(
        model.params, model.state, feeds, None, CompMode.COMP_MODE_INFERENCE
    )
    return np.asarray(values[out.guid])


def _transplant(src_model, dst_model, n):
    """Copy gate weights and pack the unfused per-expert dense weights into
    the fused (n, F, H) / (n, H) stacks."""
    params = {k: dict(v) for k, v in dst_model.params.items()}
    src = src_model.params
    params["moe_gate"] = dict(src["moe_gate"])
    kernel = np.stack([np.asarray(src[f"moe_exp{i}"]["kernel"]) for i in range(n)])
    bias = np.stack([np.asarray(src[f"moe_exp{i}"]["bias"]) for i in range(n)])
    import jax.numpy as jnp

    params["moe_experts"] = {"kernel": jnp.asarray(kernel),
                             "bias": jnp.asarray(bias)}
    dst_model.params = params
    return dst_model


def test_fused_experts_match_unfused():
    B, F, n, k, H = 8, 6, 4, 2, 5
    rng = np.random.RandomState(7)
    x = rng.randn(B, F).astype(np.float32)

    unfused, out_u = _build_moe(False, B, F, n, k, H)
    fused, out_f = _build_moe(True, B, F, n, k, H)
    _transplant(unfused, fused, n)

    ref = _forward(unfused, out_u, x)
    got = _forward(fused, out_f, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_expert_parallel_matches_replicated():
    """Experts sharded over an 'expert' mesh axis produce the same numerics
    as the single-device fused path."""
    B, F, n, k, H = 8, 6, 4, 2, 5
    rng = np.random.RandomState(8)
    x = rng.randn(B, F).astype(np.float32)

    single, out_s = _build_moe(True, B, F, n, k, H)
    ref = _forward(single, out_s, x)

    ep_model, out_e = _build_moe(True, B, F, n, k, H,
                                 parallel_axes={"data": 2, "expert": 4})
    # same weights as the single-device model
    import jax

    ep_model.params = jax.device_put(
        {k: {kk: np.asarray(vv) for kk, vv in v.items()}
         for k, v in single.params.items()}
    )
    got = _forward(ep_model, out_e, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    # expert kernel is actually sharded over the mesh
    w = ep_model.graph.ops[
        next(op.guid for op in ep_model.graph.ops.values()
             if op.op_type.value == "experts")
    ].weights[0]
    spec = w.parallel_shape.partition_spec()
    assert spec[0] == "expert"


def test_search_proposes_expert_parallelism():
    """The Unity search enumerates the expert mesh axis for EXPERTS graphs
    and — with expert FFN FLOPs dominating — selects an ep>1 strategy."""
    B, F, n, k, H = 512, 1024, 8, 2, 4096
    config = ff.FFConfig()
    config.batch_size = B
    config.search_budget = 4
    model = ff.FFModel(config)
    inp = model.create_tensor([B, F])
    out = model.moe(inp, n, k, H, alpha=float(n), fused=True, name="moe")
    model.dense(out, 3)

    from flexflow_tpu.core.graph import Graph
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.unity import unity_optimize

    machine = make_machine_model(config, 8)
    result = unity_optimize(Graph(model.ops), config, machine, B, 8)
    # the candidate list must include ep>1 factorizations
    assert any("ep=2" in line or "ep=4" in line or "ep=8" in line
               for line in result.log), result.log
    # expert compute dominates this graph: the winning strategy shards it
    assert result.mesh_axes.get("expert", 1) > 1, result.log
    assert any(s.ep > 1 for s in result.strategies.values())


def test_expert_parallel_trains():
    """One training step with dp x ep sharding runs and yields finite loss."""
    B, F, n, k, H = 8, 6, 4, 2, 6
    config = ff.FFConfig()
    config.batch_size = B
    model = ff.FFModel(config)
    inp = model.create_tensor([B, F])
    out = model.moe(inp, n, k, H, alpha=float(n), lambda_bal=0.1,
                    fused=True, name="moe")
    model.dense(out, 3)
    model.compile(optimizer=ff.AdamOptimizer(model, alpha=1e-3),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  parallel_axes={"data": 2, "expert": 4})
    x = np.random.RandomState(0).randn(B, F).astype(np.float32)
    y = np.zeros((B, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=B, epochs=1)
    assert np.isfinite(hist[0]["loss"])


def test_moe_capacity_clamps_to_top_k():
    """ceil(alpha*k*B/n) can round below k for tiny batches; the clamp
    floors at k (a capacity under k cannot hold one token's k assignments
    when the router concentrates) and the degenerate predicate flags
    exactly the clamped configurations for the FFTA080 warning."""
    from flexflow_tpu.ops.moe import moe_capacity, moe_capacity_degenerate

    # raw = ceil(1.0 * 2 * 4 / 64) = 1 < k=2 -> clamped to 2
    assert moe_capacity(4, 2, 64, 1.0) == 2
    assert moe_capacity_degenerate(4, 2, 64, 1.0)
    # ample batch: the requested capacity is the one that runs
    assert moe_capacity(64, 2, 4, 1.0) == 32
    assert not moe_capacity_degenerate(64, 2, 4, 1.0)
    # the clamp never lowers a legal capacity
    assert moe_capacity(64, 2, 4, 2.0) == 64


def test_rank3_experts_match_flattened_rank2():
    """(batch, seq, F) inputs dispatch per token over the flattened
    leading dims — numerics match the same tokens fed as a rank-2 batch,
    and the output restores the (batch, seq, out) shape. This is the
    contract the serving decode path (seq=1) relies on."""
    B, S, F, n, k, H = 4, 3, 6, 4, 2, 5
    rng = np.random.RandomState(11)
    x3 = rng.randn(B, S, F).astype(np.float32)

    cfg = ff.FFConfig()
    cfg.batch_size = B
    cfg.allow_mixed_precision = False
    m3 = ff.FFModel(cfg)
    inp3 = m3.create_tensor([B, S, F])
    out3 = m3.moe(inp3, n, k, H, alpha=float(n), fused=True, name="moe")
    m3.final_tensor = out3
    m3.compile(optimizer=ff.SGDOptimizer(m3, lr=0.0),
               loss_type=ff.LossType.LOSS_IDENTITY)
    got3 = _forward(m3, out3, x3)
    assert got3.shape == (B, S, H)

    flat, out_f = _build_moe(True, B * S, F, n, k, H)
    flat.params = {kk: dict(vv) for kk, vv in flat.params.items()}
    flat.params["moe_gate"] = dict(m3.params["moe_gate"])
    flat.params["moe_experts"] = dict(m3.params["moe_experts"])
    ref = _forward(flat, out_f, x3.reshape(B * S, F))
    np.testing.assert_allclose(got3, ref.reshape(B, S, H),
                               rtol=1e-4, atol=1e-5)


def test_router_state_tracks_drops_and_load():
    """The fused op threads router health through functional op state:
    `load` holds the last step's per-expert assignment fractions (sums
    to 1), `dropped` grows monotonically when a sub-1.0 capacity factor
    forces overflow, and publish_moe_metrics mirrors both into the
    ff_moe_* families."""
    from flexflow_tpu.ffconst import CompMode
    from flexflow_tpu.obs import publish_moe_metrics
    from flexflow_tpu.obs.registry import MetricsRegistry

    B, F, n, k, H = 32, 6, 4, 2, 5
    cfg = ff.FFConfig()
    cfg.batch_size = B
    model = ff.FFModel(cfg)
    inp = model.create_tensor([B, F])
    # alpha=0.25: capacity = max(k, ceil(0.25*2*32/4)) = 4 slots per
    # expert for 64 assignments -> overflow is guaranteed
    out = model.moe(inp, n, k, H, alpha=0.25, fused=True, name="moe")
    model.final_tensor = out
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_IDENTITY)
    x = np.random.RandomState(3).randn(B, F).astype(np.float32)

    feeds = {model.input_ops[0].name: x}
    _, state1, _ = model.executor.forward_values(
        model.params, model.state, feeds, None,
        CompMode.COMP_MODE_INFERENCE)
    model.state = state1
    load = np.asarray(state1["moe_experts"]["load"])
    assert load.shape == (n,)
    assert np.isclose(load.sum(), 1.0, atol=1e-5)
    d1 = float(state1["moe_experts"]["dropped"])
    assert d1 > 0

    _, state2, _ = model.executor.forward_values(
        model.params, model.state, feeds, None,
        CompMode.COMP_MODE_INFERENCE)
    assert float(state2["moe_experts"]["dropped"]) == 2 * d1  # monotone across steps

    reg = MetricsRegistry()
    model.state = state2
    raw = publish_moe_metrics(model, registry=reg)
    assert raw["moe_experts"]["dropped"] == 2 * d1
    text = reg.render()
    assert "ff_moe_router_dropped_tokens_total" in text
    assert "ff_moe_expert_load_imbalance" in text
