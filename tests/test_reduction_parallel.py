"""Reduction/"parameter" parallelism (reference: --enable-parameter-parallel
+ src/parallel_ops/reduction.cc): row-parallel linears whose kernel shards
the input-feature dim, paired with column-parallel producers."""
import json

import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.ffconst import CompMode


def test_col_row_pair_matches_single_device(tmp_path):
    """fc1 column-parallel + fc2 row-parallel on a model=2 mesh reproduces
    single-device numerics (GSPMD inserts the one allreduce)."""
    B, F, H = 8, 16, 12
    rng = np.random.RandomState(9)
    x = rng.randn(B, F).astype(np.float32)

    def build(config, import_file=None):
        config.batch_size = B
        config.allow_mixed_precision = False
        if import_file:
            config.import_strategy_file = import_file
        model = ff.FFModel(config)
        inp = model.create_tensor([B, F])
        t = model.dense(inp, H, ff.ActiMode.AC_MODE_RELU, name="fc1")
        t = model.dense(t, F, name="fc2")
        model.final_tensor = t
        model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                      loss_type=ff.LossType.LOSS_IDENTITY)
        return model, t

    single, out_s = build(ff.FFConfig())
    feeds = {single.input_ops[0].name: x}
    vals, _, _ = single.executor.forward_values(
        single.params, single.state, feeds, None, CompMode.COMP_MODE_INFERENCE)
    ref = np.asarray(vals[out_s.guid])

    # strategy file: fc1 column-parallel, fc2 row-parallel at tp=2
    strat = {
        "mesh_axes": {"model": 2},
        "cost_us": 0.0, "memory_bytes": 0.0,
        "ops": {
            "fc1": {"dp": 1, "tp": 2, "ep": 1, "ap": 1, "tp_row": False},
            "fc2": {"dp": 1, "tp": 2, "ep": 1, "ap": 1, "tp_row": True},
        },
    }
    path = str(tmp_path / "strategy.json")
    with open(path, "w") as f:
        json.dump(strat, f)

    sharded, out_p = build(ff.FFConfig(), import_file=path)
    # verify the shardings really are Megatron col->row
    fc1 = next(op for op in sharded.graph.ops.values() if op.name == "fc1")
    fc2 = next(op for op in sharded.graph.ops.values() if op.name == "fc2")
    assert fc1.weights[0].parallel_shape.partition_spec()[-1] == "model"
    assert fc2.weights[0].parallel_shape.partition_spec()[0] == "model"
    assert fc2.inputs[0].parallel_shape.partition_spec()[-1] == "model"

    import jax

    sharded.params = jax.device_put(
        {k: {kk: np.asarray(vv) for kk, vv in v.items()}
         for k, v in single.params.items()})
    feeds = {sharded.input_ops[0].name: x}
    vals, _, _ = sharded.executor.forward_values(
        sharded.params, sharded.state, feeds, None,
        CompMode.COMP_MODE_INFERENCE)
    got = np.asarray(vals[out_p.guid])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_search_emits_row_parallel_pairs():
    """With --enable-parameter-parallel, big paired linears search to a
    column->row layout (one allreduce instead of gather+scatter chains)."""
    config = ff.FFConfig()
    config.batch_size = 8
    config.search_budget = 6
    config.enable_parameter_parallel = True
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 4096])
    t = model.dense(inp, 8192, ff.ActiMode.AC_MODE_RELU, name="up")
    t = model.dense(t, 4096, name="down")
    model.softmax(model.dense(t, 4, name="cls"))

    from flexflow_tpu.core.graph import Graph
    from flexflow_tpu.search.machine_model import make_machine_model
    from flexflow_tpu.search.unity import unity_optimize

    machine = make_machine_model(config, 8)
    result = unity_optimize(Graph(model.ops), config, machine, 8, 8)
    by_name = {op.name: result.strategies[op.guid] for op in model.ops
               if op.guid in result.strategies}
    assert any(s.tp_row for s in result.strategies.values()), result.log
    # the row op follows a same-degree column op (the pairing)
    assert by_name["down"].tp_row and by_name["down"].tp > 1, result.log
    assert by_name["up"].tp == by_name["down"].tp and not by_name["up"].tp_row


def test_row_parallel_trains():
    config = ff.FFConfig()
    config.batch_size = 8
    config.search_budget = 6
    config.enable_parameter_parallel = True
    config.num_devices = 8
    model = ff.FFModel(config)
    inp = model.create_tensor([8, 256])
    t = model.dense(inp, 512, ff.ActiMode.AC_MODE_RELU, name="up")
    t = model.dense(t, 256, name="down")
    model.softmax(model.dense(t, 4, name="cls"))
    model.compile(optimizer=ff.AdamOptimizer(model, alpha=1e-3),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    x = np.random.RandomState(0).randn(8, 256).astype(np.float32)
    y = np.zeros((8, 1), dtype=np.int32)
    hist = model.fit([x], y, batch_size=8, epochs=1)
    assert np.isfinite(hist[0]["loss"])
