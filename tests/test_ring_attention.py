"""Ring attention (sequence parallelism) numerical tests on the 8-device
CPU mesh: outputs and gradients must match full (single-block) attention."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.core.machine import make_mesh
from flexflow_tpu.kernels.ring_attention import ring_attention_sharded


def full_attention(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((lq, lk), bool), lk - lq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    B, L, H, D = 2, 32, 4, 8
    q = rng.randn(B, L, H, D).astype(np.float32)
    k = rng.randn(B, L, H, D).astype(np.float32)
    v = rng.randn(B, L, H, D).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(qkv, causal):
    q, k, v = qkv
    mesh = make_mesh({"seq": 8})

    @jax.jit
    def ring(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, "seq", causal=causal)

    out = ring(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_match(qkv):
    q, k, v = qkv
    mesh = make_mesh({"seq": 8})

    def loss_ring(q, k, v):
        out = ring_attention_sharded(q, k, v, mesh, "seq", causal=True)
        return jnp.sum(out * out)

    def loss_full(q, k, v):
        out = full_attention(q, k, v, causal=True)
        return jnp.sum(out * out)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.jit(jax.grad(loss_full, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_attention_op_sequence_parallel_end_to_end():
    """FFModel attention with sequence_parallel=True trains on a seq-sharded
    mesh."""
    import flexflow_tpu as ff

    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    B, L, E, H = 4, 16, 32, 4
    x = model.create_tensor([B, L, E])
    t = model.multihead_attention(x, x, x, E, H, causal=True,
                                  sequence_parallel=True)
    t = model.dense(t, 8)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
        parallel_axes={"seq": 8},
    )
    rng = np.random.RandomState(0)
    xd = rng.randn(64, L, E).astype(np.float32)
    yd = rng.randint(0, 8, (64, L, 1)).astype(np.int32)
    h = model.fit([xd], yd, epochs=2)
    assert len(h) == 2
    assert np.isfinite(h[-1]["accuracy"])


def test_ring_attention_dp_sp_combo():
    """DP x SP: batch sharded over 'data', sequence over 'seq' — outputs must
    still match full attention (regression: batch was force-replicated)."""
    rng = np.random.RandomState(1)
    B, L, H, D = 4, 16, 2, 8
    q = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    mesh = make_mesh({"data": 2, "seq": 4})

    @jax.jit
    def ring(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, "seq", causal=True)

    out = ring(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_sequence_parallel_dropout_rejected():
    import flexflow_tpu as ff

    config = ff.FFConfig()
    config.batch_size = 4
    model = ff.FFModel(config)
    x = model.create_tensor([4, 16, 32])
    with pytest.raises(ValueError, match="dropout"):
        model.multihead_attention(x, x, x, 32, 4, dropout=0.1,
                                  sequence_parallel=True)


def test_ring_attention_long_context():
    """Long-context leg: L=2048 over 8 seq shards matches full attention
    (the claim the SP kernels exist for; small head dims keep CI fast)."""
    rng = np.random.RandomState(7)
    B, L, H, D = 1, 2048, 2, 4
    q = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    mesh = make_mesh({"seq": 8})

    @jax.jit
    def ring(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, "seq", causal=True)

    out = ring(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
