"""GPipe pipeline parallelism over a 'stage' mesh axis (new capability —
reference OP_PIPELINE is an unused enum, ffconst.h:159)."""
import numpy as np

import jax
import jax.numpy as jnp

from flexflow_tpu.models.pipeline_transformer import (
    init_pipeline_params,
    make_train_step,
    pipeline_forward,
    sequential_forward,
)


def _mesh(stages):
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:stages])
    return Mesh(devs, ("stage",))


def test_gpipe_forward_matches_sequential():
    stages, layers, hidden, heads = 4, 4, 16, 4
    B, L = 8, 6
    params = init_pipeline_params(jax.random.PRNGKey(0), layers, hidden,
                                  heads, stages=stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, L, hidden))

    ref = np.asarray(sequential_forward(params, x))
    mesh = _mesh(stages)
    got = np.asarray(pipeline_forward(params, x, mesh, microbatches=4))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_gpipe_backward_matches_sequential():
    """jax.grad through the scan/ppermute pipeline == sequential grads."""
    stages, layers, hidden, heads = 2, 2, 8, 2
    B, L = 4, 5
    params = init_pipeline_params(jax.random.PRNGKey(2), layers, hidden,
                                  heads, stages=stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (B, L, hidden))
    mesh = _mesh(stages)

    g_ref = jax.grad(lambda p: jnp.sum(sequential_forward(p, x) ** 2))(params)
    g_pipe = jax.grad(
        lambda p: jnp.sum(pipeline_forward(p, x, mesh, microbatches=2) ** 2)
    )(params)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_ref[k]),
                                   rtol=5e-4, atol=5e-5)


def test_gpipe_train_step_loss_falls():
    stages, layers, hidden, heads, vocab = 4, 4, 16, 4, 30
    B, L = 8, 6
    mesh = _mesh(stages)
    params = init_pipeline_params(jax.random.PRNGKey(4), layers, hidden,
                                  heads, stages=stages)
    emb = jax.random.normal(jax.random.PRNGKey(5), (vocab, hidden)) * 0.02
    head = jax.random.normal(jax.random.PRNGKey(6), (hidden, vocab)) * 0.02
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, (B, L)))
    labels = jnp.asarray(rng.randint(0, vocab, (B, L)))

    step = make_train_step(mesh, microbatches=4, lr=0.1)
    losses = []
    for _ in range(8):
        params, emb, head, loss = step(params, emb, head, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(l) for l in losses)
