/* C-embedded model building (reference: flexflow_c.h users, e.g. the
 * Legion-side C bindings): build an MLP, run the native Unity search, and
 * export the spec the Python runtime trains.
 *
 * Build:  gcc mlp.c -o mlp -L../../src/ffcore -lffcore \
 *             -Wl,-rpath,'$ORIGIN/../../src/ffcore'
 * Train:  python -c "from flexflow_tpu.native.c_model import model_from_spec;
 *                    m = model_from_spec('mlp.json'); ..."
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

extern void* ffc_model_create(int batch_size);
extern void ffc_model_destroy(void* h);
extern const char* ffc_model_last_error(void* h);
extern int64_t ffc_tensor_create(void* h, int ndims, const int64_t* dims,
                                 const char* dtype);
extern int64_t ffc_op(void* h, const char* type, int n_inputs,
                      const int64_t* inputs, const char* params);
extern char* ffc_model_export_json(void* h);
extern char* ffc_model_optimize(void* h, int n_devices, int budget,
                                double alpha);
extern void ffc_free(char* p);

int main(void) {
  void* m = ffc_model_create(64);
  int64_t dims[2] = {64, 784};
  int64_t x = ffc_tensor_create(m, 2, dims, "float32");
  int64_t t = ffc_op(m, "dense", 1, &x, "out_dim=512;activation=relu");
  t = ffc_op(m, "dense", 1, &t, "out_dim=512;activation=relu");
  t = ffc_op(m, "dense", 1, &t, "out_dim=10");
  t = ffc_op(m, "softmax", 1, &t, "");
  if (t < 0) {
    fprintf(stderr, "build failed: %s\n", ffc_model_last_error(m));
    return 1;
  }

  char* result = ffc_model_optimize(m, 8, 8, 1.2);
  printf("native search over 8 chips:\n%s", result);
  ffc_free(result);

  char* spec = ffc_model_export_json(m);
  FILE* f = fopen("mlp.json", "w");
  if (!f) {
    fprintf(stderr, "cannot write mlp.json\n");
    ffc_free(spec);
    ffc_model_destroy(m);
    return 1;
  }
  fputs(spec, f);
  fclose(f);
  ffc_free(spec);
  printf("wrote mlp.json (train with flexflow_tpu.native.c_model)\n");

  ffc_model_destroy(m);
  return 0;
}
