"""Reuters topic-classification MLP (reference:
examples/python/keras/seq_reuters_mlp.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu.keras as keras
from flexflow_tpu.keras.datasets import reuters
from flexflow_tpu.keras.layers import Activation, Dense
from flexflow_tpu.keras.models import Sequential
from flexflow_tpu.keras.preprocessing.text import Tokenizer


def main():
    max_words = 1000
    (x_train, y_train), _ = reuters.load_data(num_words=max_words)
    tokenizer = Tokenizer(num_words=max_words)
    x_train = tokenizer.sequences_to_matrix(x_train, mode="binary").astype(np.float32)
    num_classes = int(np.max(y_train)) + 1
    y_train = y_train.astype(np.int32).reshape(-1, 1)

    model = Sequential()
    model.add(Dense(512, activation="relu", input_shape=(max_words,)))
    model.add(Dense(num_classes))
    model.add(Activation("softmax"))
    model.compile(
        optimizer=keras.optimizers.Adam(learning_rate=1e-3),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    hist = model.fit(x_train, y_train, epochs=4, batch_size=64)
    print(f"[seq_reuters_mlp] final accuracy "
          f"{hist.history['accuracy'][-1] * 100:.2f}%")


if __name__ == "__main__":
    main()
