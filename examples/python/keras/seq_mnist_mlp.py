"""Sequential MNIST MLP through the keras frontend (reference:
examples/python/keras/seq_mnist_mlp.py — the python_interface_test.sh smoke
model)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu.keras as keras
from flexflow_tpu.keras.datasets import mnist
from flexflow_tpu.keras.layers import Activation, Dense
from flexflow_tpu.keras.models import Sequential


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)

    model = Sequential()
    model.add(Dense(512, activation="relu", input_shape=(784,)))
    model.add(Dense(512, activation="relu"))
    model.add(Dense(10))
    model.add(Activation("softmax"))
    model.compile(
        optimizer=keras.optimizers.Adam(learning_rate=1e-3),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    hist = model.fit(x_train, y_train, epochs=4, batch_size=64)
    acc = hist.history["accuracy"][-1] * 100
    print(f"[seq_mnist_mlp] final accuracy {acc:.2f}%")
    if acc < 90.0:
        raise SystemExit("accuracy gate (90%) failed")


if __name__ == "__main__":
    main()
