"""Functional-API CIFAR-10 CNN (reference:
examples/python/keras/func_cifar10_cnn.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu.keras as keras
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.keras.layers import (
    Activation, Conv2D, Dense, Flatten, Input, MaxPooling2D,
)
from flexflow_tpu.keras.models import Model


def main():
    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0
    if x_train.shape[-1] == 3:
        x_train = np.transpose(x_train, (0, 3, 1, 2))
    y_train = y_train.astype(np.int32).reshape(-1, 1)

    inp = Input(shape=(3, 32, 32))
    t = Conv2D(32, (3, 3), padding="same", activation="relu")(inp)
    t = Conv2D(32, (3, 3), padding="same", activation="relu")(t)
    t = MaxPooling2D((2, 2))(t)
    t = Conv2D(64, (3, 3), padding="same", activation="relu")(t)
    t = Conv2D(64, (3, 3), padding="same", activation="relu")(t)
    t = MaxPooling2D((2, 2))(t)
    t = Flatten()(t)
    t = Dense(512, activation="relu")(t)
    t = Dense(10)(t)
    out = Activation("softmax")(t)

    model = Model(inputs=inp, outputs=out)
    model.compile(
        optimizer=keras.optimizers.Adam(learning_rate=1e-3),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    hist = model.fit(x_train, y_train, epochs=2, batch_size=64)
    print(f"[func_cifar10_cnn] final accuracy "
          f"{hist.history['accuracy'][-1] * 100:.2f}%")


if __name__ == "__main__":
    main()
