"""Callback usage (reference: examples/python/keras/callback.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu.keras as keras
from flexflow_tpu.keras.callbacks import EarlyStopping, LearningRateScheduler
from flexflow_tpu.keras.layers import Activation, Dense
from flexflow_tpu.keras.models import Sequential


def main():
    rng = np.random.RandomState(0)
    x = rng.randn(512, 20).astype(np.float32)
    y = rng.randint(0, 4, size=(512, 1)).astype(np.int32)

    model = Sequential()
    model.add(Dense(64, activation="relu", input_shape=(20,)))
    model.add(Dense(4))
    model.add(Activation("softmax"))
    model.compile(
        optimizer=keras.optimizers.SGD(learning_rate=0.05),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    hist = model.fit(
        x, y, epochs=10, batch_size=64,
        callbacks=[
            LearningRateScheduler(lambda epoch, lr: lr * 0.9),
            EarlyStopping(monitor="loss", patience=3),
        ],
    )
    print(f"[callback] epochs ran: {len(hist.history['loss'])}")


if __name__ == "__main__":
    main()
