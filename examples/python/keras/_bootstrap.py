"""Make the in-tree flexflow_tpu importable when not installed."""
import os
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# Honor an explicit JAX_PLATFORMS=cpu (the TPU site hook otherwise
# overrides the env var), with the tests' 8-device virtual CPU mesh.
from flexflow_tpu.runtime.platform import honor_env_platform

honor_env_platform()
