"""Make the in-tree flexflow_tpu importable when not installed."""
import os
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
