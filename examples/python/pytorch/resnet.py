"""torchvision ResNet-18 via fx import (reference:
examples/python/pytorch/resnet.py, torch_vision.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.torch import PyTorchModel


def main():
    try:
        from torchvision.models import resnet18
        torch_model = resnet18(weights=None)
    except ImportError:
        print("[pytorch resnet] torchvision not available; skipping")
        return

    config = ff.FFConfig()
    config.batch_size = 16
    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 3, 224, 224])
    pt = PyTorchModel(torch_model)
    (out,) = pt.apply(model, [inp])
    model.softmax(out)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    rng = np.random.RandomState(0)
    x = rng.randn(config.batch_size * 2, 3, 224, 224).astype(np.float32)
    y = rng.randint(0, 1000, size=(config.batch_size * 2, 1)).astype(np.int32)
    hist = model.fit([x], y, batch_size=config.batch_size, epochs=1)
    print(f"[pytorch resnet18] 1 epoch done, loss finite: "
          f"{np.isfinite(hist[-1].get('loss', np.nan))}")


if __name__ == "__main__":
    main()
