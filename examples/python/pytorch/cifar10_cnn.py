"""Torch CNN via fx import (reference: examples/python/pytorch/cifar10_cnn.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.torch import PyTorchModel


def build_torch_cnn():
    import torch
    import torch.nn as nn

    class CNN(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 32, 3, padding=1)
            self.conv2 = nn.Conv2d(32, 32, 3, padding=1)
            self.conv3 = nn.Conv2d(32, 64, 3, padding=1)
            self.conv4 = nn.Conv2d(64, 64, 3, padding=1)
            self.pool = nn.MaxPool2d(2, 2)
            self.fc1 = nn.Linear(64 * 8 * 8, 512)
            self.fc2 = nn.Linear(512, 10)
            self.relu = nn.ReLU()

        def forward(self, x):
            x = self.pool(self.relu(self.conv2(self.relu(self.conv1(x)))))
            x = self.pool(self.relu(self.conv4(self.relu(self.conv3(x)))))
            x = torch.flatten(x, 1)
            return self.fc2(self.relu(self.fc1(x)))

    return CNN()


def main():
    config = ff.FFConfig()
    config.batch_size = 64
    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 3, 32, 32])
    pt = PyTorchModel(build_torch_cnn())
    (out,) = pt.apply(model, [inp])
    model.softmax(out)
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=1e-3),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    pt.transfer_weights(model)  # start from the torch init

    from flexflow_tpu.keras.datasets import cifar10

    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0
    if x_train.shape[-1] == 3:
        x_train = np.transpose(x_train, (0, 3, 1, 2))
    y_train = y_train.astype(np.int32).reshape(-1, 1)
    hist = model.fit([x_train], y_train, batch_size=config.batch_size, epochs=2)
    print(f"[pytorch cifar10_cnn] final accuracy {hist[-1]['accuracy']*100:.2f}%")


if __name__ == "__main__":
    main()
