"""Train a torch.nn MLP through the torch.fx importer (reference:
examples/python/pytorch/mnist_mlp.py + mnist_mlp_torch.py: the torch module
is serialized with torch_to_flexflow and replayed into FFModel)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.torch import PyTorchModel, fx


def build_torch_mlp():
    import torch.nn as nn

    return nn.Sequential(
        nn.Linear(784, 512), nn.ReLU(),
        nn.Linear(512, 512), nn.ReLU(),
        nn.Linear(512, 10), nn.Softmax(dim=-1),
    )


def main():
    torch_model = build_torch_mlp()
    fx.torch_to_flexflow(torch_model, "/tmp/mnist_mlp.ff")

    config = ff.FFConfig()
    config.batch_size = 64
    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 784])
    pt = PyTorchModel("/tmp/mnist_mlp.ff")
    (out,) = pt.apply(model, [inp])
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=1e-3),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )

    from flexflow_tpu.keras.datasets import mnist

    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)
    hist = model.fit([x_train], y_train, batch_size=config.batch_size, epochs=4)
    acc = hist[-1]["accuracy"] * 100
    print(f"[pytorch mnist_mlp] final accuracy {acc:.2f}%")
    if acc < 90.0:
        raise SystemExit("accuracy gate (90%) failed")


if __name__ == "__main__":
    main()
