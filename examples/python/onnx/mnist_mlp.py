"""MNIST MLP imported from an ONNX file (reference:
examples/python/onnx/mnist_mlp.py / mnist_mlp_pt.py — the .onnx is exported
from torch, then replayed into FFModel)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.onnx.model import ONNXModel


def export_onnx(path):
    import torch
    import torch.nn as nn

    torch_model = nn.Sequential(
        nn.Linear(784, 512), nn.ReLU(),
        nn.Linear(512, 512), nn.ReLU(),
        nn.Linear(512, 10),
    )
    torch.onnx.export(
        torch_model, torch.randn(64, 784), path,
        input_names=["input"], output_names=["output"], dynamo=False,
    )
    return path


def main():
    try:
        import onnx  # noqa: F401
        import torch  # noqa: F401
    except ImportError as e:
        print(f"[onnx mnist_mlp] {e.name} not available; skipping")
        return
    path = export_onnx("/tmp/mnist_mlp.onnx")

    config = ff.FFConfig()
    config.batch_size = 64
    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 784])
    om = ONNXModel(path)
    (out,) = om.apply(model, [inp])
    model.softmax(out)
    model.compile(
        optimizer=ff.AdamOptimizer(model, alpha=1e-3),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    om.transfer_weights(model)

    from flexflow_tpu.keras.datasets import mnist

    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)
    hist = model.fit([x_train], y_train, batch_size=config.batch_size, epochs=4)
    acc = hist[-1]["accuracy"] * 100
    print(f"[onnx mnist_mlp] final accuracy {acc:.2f}%")


if __name__ == "__main__":
    main()
