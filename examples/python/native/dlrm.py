"""DLRM (reference: examples/python/native/dlrm.py, examples/cpp/DLRM) —
attribute-parallel embedding sharding benchmark config."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import DLRMConfig, build_dlrm

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=64, epochs=1)
    cfg = DLRMConfig(
        sparse_feature_size=64,
        embedding_size=[100000] * 4,
        mlp_bot=[4, 64, 64],
        mlp_top=[64 * 5, 64, 2],  # 4 embeddings + bottom output, concat
    )
    batch = config.batch_size
    n = batch * 8
    rng = np.random.RandomState(0)
    dense_np = rng.randn(n, cfg.mlp_bot[0]).astype(np.float32)
    sparse_np = [rng.randint(0, v, size=(n, cfg.embedding_bag_size)).astype(np.int32)
                 for v in cfg.embedding_size]
    y = rng.randint(0, 2, size=(n, 1)).astype(np.int32)

    model = ff.FFModel(config)
    dense = model.create_tensor([batch, cfg.mlp_bot[0]])
    sparse = [model.create_tensor([batch, cfg.embedding_bag_size],
                                  ff.DataType.DT_INT32)
              for _ in cfg.embedding_size]
    build_dlrm(model, dense, sparse, cfg)
    train_and_report(model, [dense_np] + sparse_np, y, config, "dlrm")


if __name__ == "__main__":
    main()
