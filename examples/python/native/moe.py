"""Mixture-of-experts encoder (reference: examples/cpp/mixture_of_experts/
moe.cc) — expert parallelism via topk/group_by/aggregate."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import MoeConfig, build_moe_encoder

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=32, epochs=1)
    cfg = MoeConfig()
    batch, seq, d = config.batch_size, 16, cfg.hidden_size
    n = batch * 4
    rng = np.random.RandomState(0)
    x = rng.randn(n, seq, d).astype(np.float32)
    y = rng.randint(0, 10, size=(n, 1)).astype(np.int32)

    model = ff.FFModel(config)
    inp = model.create_tensor([batch, seq, d])
    out = build_moe_encoder(model, inp, cfg)
    pooled = model.mean(out, [1])
    model.softmax(model.dense(pooled, 10, name="head"))
    train_and_report(model, [x], y, config, "moe")


if __name__ == "__main__":
    main()
