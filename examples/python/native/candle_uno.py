"""CANDLE Uno (reference: examples/cpp/candle_uno) — multi-input regression;
demonstrates multi-tensor inputs through the native API."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import CandleUnoConfig, build_candle_uno

from _util import get_config


def main():
    config = get_config(batch_size=32, epochs=1)
    cfg = CandleUnoConfig(dense_layers=[512] * 2, dense_feature_layers=[512] * 2)
    batch = config.batch_size
    feature_dims = {"dose1": 1, "dose2": 1, "cell.rnaseq": 942,
                    "drug1.descriptors": 5270, "drug1.fingerprints": 2048}
    n = batch * 4
    rng = np.random.RandomState(0)

    model = ff.FFModel(config)
    feats = {name: model.create_tensor([batch, d])
             for name, d in feature_dims.items()}
    build_candle_uno(model, feats, cfg)
    model.compile(
        optimizer=ff.SGDOptimizer(model, lr=0.001),
        loss_type=ff.LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[ff.MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    xs = [rng.randn(n, d).astype(np.float32) for d in feature_dims.values()]
    y = rng.randn(n, 1).astype(np.float32)
    hist = model.fit(xs, y, batch_size=batch, epochs=config.epochs)
    print(f"[candle_uno] final mse {hist[-1].get('mse', float('nan')):.4f}")


if __name__ == "__main__":
    main()
