"""InceptionV3 (reference: examples/python/native/inception.py)."""
import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import build_inception_v3

from _util import get_config, synthetic_images, train_and_report


def main():
    config = get_config(batch_size=8, epochs=1)
    x, y = synthetic_images(config.batch_size * 2, 3, 299)

    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 3, 299, 299])
    build_inception_v3(model, inp)
    train_and_report(model, [x], y, config, "inception_v3")


if __name__ == "__main__":
    main()
