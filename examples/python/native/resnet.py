"""ResNet-50 (reference: examples/python/native/resnet.py,
examples/cpp/ResNet)."""
import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import build_resnet50

from _util import get_config, synthetic_images, train_and_report


def main():
    config = get_config(batch_size=16, epochs=1)
    size = 224
    x, y = synthetic_images(config.batch_size * 2, 3, size)

    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 3, size, size])
    build_resnet50(model, inp)
    train_and_report(model, [x], y, config, "resnet50")


if __name__ == "__main__":
    main()
