"""BERT-style transformer (reference: examples/cpp/Transformer — the
OSDI'22 bert.sh benchmark config: 12 layers, hidden 1024, 16 heads,
seq 512)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import TransformerConfig, build_bert_encoder

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=8, epochs=1)
    cfg = TransformerConfig(num_layers=2, hidden_size=256, num_heads=8,
                            sequence_length=128)  # laptop-scale default
    batch, seq = config.batch_size, cfg.sequence_length
    n = batch * 4
    rng = np.random.RandomState(0)
    x = rng.randint(0, cfg.vocab_size, size=(n, seq)).astype(np.int32)
    y = rng.randint(0, 2, size=(n, seq, 1)).astype(np.int32)

    model = ff.FFModel(config)
    tokens = model.create_tensor([batch, seq], ff.DataType.DT_INT32)
    build_bert_encoder(model, tokens, cfg)
    train_and_report(model, [x], y, config, "bert",
                     optimizer=ff.AdamOptimizer(model, alpha=1e-4))


if __name__ == "__main__":
    main()
