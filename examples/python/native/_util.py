"""Shared helpers for the native examples: CLI config, synthetic datasets,
throughput printing (role of each reference example's parse_input_args +
bespoke DataLoader)."""
from __future__ import annotations

import sys
import time

import numpy as np

import flexflow_tpu as ff


def get_config(batch_size: int = 64, epochs: int = 1) -> ff.FFConfig:
    """Example defaults first, then CLI flags override them."""
    config = ff.FFConfig()
    config.batch_size = batch_size
    config.epochs = epochs
    config.parse_args(sys.argv[1:])
    return config


def synthetic_images(n, chans, size, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, chans, size, size).astype(np.float32)
    y = rng.randint(0, classes, size=(n, 1)).astype(np.int32)
    return x, y


def train_and_report(model, inputs, labels, config, name,
                     optimizer=None, target_accuracy=None):
    model.compile(
        optimizer=optimizer or ff.SGDOptimizer(model, lr=0.01),
        loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[ff.MetricsType.METRICS_ACCURACY],
    )
    n = labels.shape[0]
    start = time.time()
    hist = model.fit(inputs, labels, batch_size=config.batch_size,
                     epochs=config.epochs)
    elapsed = time.time() - start
    thru = n * config.epochs / max(elapsed, 1e-9)
    acc = hist[-1].get("accuracy", float("nan")) * 100.0
    print(f"[{name}] time {elapsed:.2f}s, throughput {thru:.1f} samples/s, "
          f"final accuracy {acc:.2f}%")
    if target_accuracy is not None and acc < target_accuracy:
        raise SystemExit(
            f"{name}: accuracy {acc:.2f}% below gate {target_accuracy}%")
    return hist
