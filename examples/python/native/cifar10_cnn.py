"""CIFAR-10 CNN (reference: examples/python/native/cifar10_cnn.py)."""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.models import build_cifar10_cnn

from _util import get_config, train_and_report


def main():
    config = get_config(batch_size=64, epochs=3)
    from flexflow_tpu.keras.datasets import cifar10

    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0
    if x_train.shape[-1] == 3:  # NHWC → NCHW
        x_train = np.transpose(x_train, (0, 3, 1, 2))
    y_train = y_train.astype(np.int32).reshape(-1, 1)

    model = ff.FFModel(config)
    inp = model.create_tensor([config.batch_size, 3, 32, 32])
    build_cifar10_cnn(model, inp)
    train_and_report(model, [x_train], y_train, config, "cifar10_cnn",
                     optimizer=ff.AdamOptimizer(model, alpha=1e-3))


if __name__ == "__main__":
    main()
