"""Sharded serving: the SAME generate()/infer path compiled over a device
mesh (reference role: the multi-node Triton prototype, triton/README.md —
there per-GPU model instances coordinate over NCCL; here one SPMD program
spans the mesh and decoding is token-identical to a single-device session).

Run on the 8-virtual-device CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python sharded_serving.py
"""
import numpy as np

import _bootstrap  # noqa: F401

import flexflow_tpu as ff
from flexflow_tpu.serving import InferenceModel
from flexflow_tpu.serving.generate import GenerativeSession

from _util import get_config


def build_lm(axes, batch=4, vocab=100, hidden=64, heads=4, window=24):
    config = get_config(batch_size=batch, epochs=1)
    config.allow_mixed_precision = False
    config.seed = 7
    config.num_devices = int(np.prod(list(axes.values()))) if axes else 1
    model = ff.FFModel(config)
    tokens = model.create_tensor([batch, window], ff.DataType.DT_INT32)
    t = model.embedding(tokens, vocab, hidden, ff.AggrMode.AGGR_MODE_NONE,
                        name="emb")
    attn = model.multihead_attention(t, t, t, hidden, heads, causal=True,
                                     name="attn")
    t = model.layer_norm(model.add(t, attn), [-1], name="ln")
    model.softmax(model.dense(t, vocab, name="lm_head"))
    model.compile(optimizer=ff.SGDOptimizer(model, lr=0.0),
                  loss_type=ff.LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  parallel_axes=axes)
    return model


def main():
    import jax

    n = jax.device_count()
    axes = {"data": 2, "model": n // 2} if n >= 4 else {"data": min(2, n)}
    prompt = np.random.RandomState(0).randint(1, 100, size=(4, 6)).astype(
        np.int32)

    ref = GenerativeSession(build_lm(None), max_len=24).generate(
        prompt, max_new_tokens=10)
    sharded_model = build_lm(axes)
    sharded = GenerativeSession(sharded_model, max_len=24).generate(
        prompt, max_new_tokens=10)
    assert np.array_equal(np.asarray(ref), np.asarray(sharded))
    print(f"generate over {axes}: token-identical to single-device")
    print("tokens:", np.asarray(sharded).tolist())

    # batched inference shards the same way (one SPMD program per bucket)
    im = InferenceModel(sharded_model, batch_buckets=(2, 4))
    name = im.input_names[0]
    x = np.random.RandomState(1).randint(1, 100, size=(3, 24)).astype(
        np.int32)
    out = im.predict({name: x})
    print(f"sharded batched infer: {np.asarray(out).shape} "
          f"(partial batch padded to a bucket)")


if __name__ == "__main__":
    main()
